"""AsyncJaxEngine: asyncio facade over the engine step loop.

The step loop runs on a dedicated thread (JAX dispatch blocks); results cross
back via loop.call_soon_threadsafe into per-request asyncio queues. This is the
native analogue of the reference's engine subprocess + ZMQ output loop
(reference: lib/llm/src/engines/vllm/worker.rs _output_loop) with the process
boundary removed.
"""

from __future__ import annotations

import asyncio
import queue as thread_queue
import threading
import time

import numpy as np
from dataclasses import dataclass
from typing import AsyncIterator, Callable, Optional

from dynamo_tpu.engine.config import EngineConfig
from dynamo_tpu.engine.page_table import PageAllocator
from dynamo_tpu.engine.scheduler import EngineRequest, Scheduler, StepOutput
from dynamo_tpu.llm.kv_events import KvCacheEvent
from dynamo_tpu.runtime.context import current_context
from dynamo_tpu.utils import events, get_logger, tracing
from dynamo_tpu.utils.goodput import GoodputTracker
from dynamo_tpu.utils.health import HealthMonitor
from dynamo_tpu.utils.prometheus import Histogram
from dynamo_tpu.utils.slo import SloTracker, targets_from_env

log = get_logger("engine")

# engine-loop watchdog cadence: cheap checks, no need to run per step
_WATCHDOG_INTERVAL_S = 1.0

# migration pause (freeze -> first continuation token): localhost handoffs
# are tens of ms; a cross-host pull of a deep sequence reaches seconds
_MIGRATION_PAUSE_BUCKETS = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
                            1.0, 2.5, 5.0, 10.0, 30.0)


def _resolve(fut: asyncio.Future, result, exc) -> None:
    if fut.done():
        return
    if exc is not None:
        fut.set_exception(exc)
    else:
        fut.set_result(result)


@dataclass
class ForwardPassMetrics:
    """Worker load metrics for the KV router
    (reference: lib/llm/src/kv_router/protocols.rs:19-33)."""

    request_active_slots: int = 0
    request_total_slots: int = 0
    kv_active_blocks: int = 0
    kv_total_blocks: int = 0
    num_requests_waiting: int = 0
    gpu_cache_usage_perc: float = 0.0  # name kept for wire compat; TPU HBM here
    gpu_prefix_cache_hit_rate: float = 0.0

    def to_wire(self) -> dict:
        return self.__dict__.copy()


class AsyncJaxEngine:
    """Tokens-in/tokens-out streaming engine (the ExecutionContext contract)."""

    def __init__(self, config: EngineConfig, kv_event_sink: Optional[Callable[[KvCacheEvent], None]] = None):
        self.config = config
        self._extra_kv_sink = kv_event_sink
        self._kv_events: list[KvCacheEvent] = []
        self._inbox: thread_queue.Queue = thread_queue.Queue()
        self._cancel_box: thread_queue.Queue = thread_queue.Queue()
        self._cmd_box: thread_queue.Queue = thread_queue.Queue()
        self._outputs: dict[str, asyncio.Queue] = {}
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._thread: Optional[threading.Thread] = None
        self._stopping = threading.Event()
        self._started = False
        self.scheduler: Optional[Scheduler] = None
        self.allocator: Optional[PageAllocator] = None
        self.runner = None
        self.model = None
        self.step_count = 0
        # fleet health plane: lifecycle state + engine-loop heartbeats +
        # stuck-request watchdog (utils/health.py); rolling SLO percentiles
        # for queue-wait/TTFT (utils/slo.py, attached to the scheduler)
        self.health = HealthMonitor("engine")
        self.slo = SloTracker(
            targets_from_env({"ttft": config.slo_ttft_ms, "itl": config.slo_itl_ms})
        )
        # goodput plane (utils/goodput.py): every naturally-finished request
        # emits one RequestOutcome from the scheduler; budgets default to the
        # engine's SLO targets (untargeted engines still count errors)
        self.goodput = GoodputTracker(
            ttft_budget_s=self.slo.targets.get("ttft"),
            itl_budget_s=self.slo.targets.get("itl"),
        )
        # cost-attribution plane (utils/metering.py): ONE ledger per engine —
        # the scheduler's dispatch bills, every KV tier's residency edges,
        # and the queued/admitted/consumed token charges all post here.
        # None when config.metering is off: every hook degrades to a
        # `meter is None` check (the zero-cost path the tests pin).
        if config.metering:
            from dynamo_tpu.utils.metering import MeterLedger

            self.meter = MeterLedger()
        else:
            self.meter = None
        # multi-tenant QoS (utils/qos.py): measured queue-drain rate — every
        # finished request feeds it via the outcome sink, and both retriable
        # status paths (draining 503, backpressure 429) price Retry-After
        # from it instead of a constant
        from dynamo_tpu.utils.qos import DrainRateEstimator

        self.drain_estimator = DrainRateEstimator()
        self._next_watchdog = 0.0
        # fleet-wide prefix cache (disagg/prefix_fetch.py): the pull client
        # the scheduler fetches remote prefixes with, and the export server
        # peers pull OUR prefixes from — both attached by the hosting worker
        self.prefix_fetcher = None
        self.kv_pull_server = None
        # live migration (disagg/migrate.py): pause = freeze -> the
        # destination's first continuation token reaches the client stream
        self.migration_pause_hist = Histogram(
            "dynamo_migration_pause_seconds",
            "client-visible stream pause of one live migration, sequence "
            "freeze to the destination's first relayed token",
            _MIGRATION_PAUSE_BUCKETS,
        )

    # ---------------- lifecycle ----------------

    async def start(self) -> None:
        if self._started:
            return
        self._loop = asyncio.get_running_loop()
        await self._loop.run_in_executor(None, self._initialize)
        self._thread = threading.Thread(target=self._run_loop, name="engine-loop", daemon=True)
        self._thread.start()
        self._started = True
        if self.config.warmup == "background":
            self._warmup_task = asyncio.create_task(self._background_warmup())

    async def _background_warmup(self) -> None:
        """Compile the feature trace variants on the engine thread, one per
        idle gap: each thunk runs via run_on_engine (the thread that owns the
        donated state), and we yield to live traffic between thunks so a
        request arriving mid-warmup waits for at most one compile."""
        for thunk in self.runner.warmup_extra_thunks():
            while self.scheduler is not None and self.scheduler.has_work():
                await asyncio.sleep(0.05)
            if self._stopping.is_set():
                return
            try:
                await self.run_on_engine(thunk)
            except asyncio.CancelledError:
                raise
            except Exception:
                # one failed variant compile must not kill serving OR abandon
                # the remaining variants; this one will lazily compile (with
                # a stall) if traffic ever needs it
                log.exception("background warmup variant failed; continuing")
        log.info("background warmup: trace variants compiled")

    def _initialize(self) -> None:
        from dynamo_tpu.engine.model_runner import ModelRunner
        from dynamo_tpu.models.registry import load_model

        t0 = time.monotonic()
        self.model, params = load_model(
            self.config.model_id, quantize=self.config.quantize,
            kv_cache_dtype=self.config.kv_cache_dtype,
        )
        self.runner = ModelRunner(self.config, self.model, params)
        offload = None
        if self.config.host_cache_blocks > 0 or self.config.host_cache_bytes > 0:
            from dynamo_tpu.engine.offload import (
                HostKvPool,
                resolve_host_capacity_blocks,
            )

            # byte budgets resolve at the model's ACTUAL per-page wire cost
            # (int8 host blocks are ~half the bf16 bytes -> ~2x blocks for
            # the same DRAM budget); the drain watermarks then operate on a
            # truthful block capacity
            page_bytes = (
                self.model.kv_page_bytes(self.config.page_size)
                if hasattr(self.model, "kv_page_bytes")
                else 0
            )
            blocks = resolve_host_capacity_blocks(
                self.config.host_cache_blocks,
                # a model without page-cost accounting can't honor a byte
                # budget — fall back to the explicit block knob only
                self.config.host_cache_bytes if page_bytes else 0,
                page_bytes,
            )
            if blocks > 0:
                offload = HostKvPool(self.runner, blocks, block_bytes=page_bytes)
        if offload is not None and self.config.disk_cache_bytes > 0:
            # third tier: host-pool LRU victims demote to disk (int8 wire,
            # xxh3-checksummed files) instead of dropping; restores ride the
            # FETCHING_KV deferred-admission path (engine/kv_store.py)
            from dynamo_tpu.engine.kv_store import DiskKvStore, disk_block_bytes

            mcfg = getattr(self.model, "config", None)
            block_bytes = (
                disk_block_bytes(
                    self.config.page_size, mcfg.num_kv_heads, mcfg.head_dim,
                    mcfg.num_layers,
                )
                if mcfg is not None
                and all(
                    hasattr(mcfg, a)
                    for a in ("num_kv_heads", "head_dim", "num_layers")
                )
                else 0
            )
            offload.disk = DiskKvStore(
                directory=self.config.disk_cache_dir or None,
                budget_bytes=self.config.disk_cache_bytes,
                page_axis=getattr(self.model, "wire_n_axis", 2),
                block_bytes=block_bytes,
            )
        self.offload = offload
        self.allocator = PageAllocator(
            self.config.num_pages,
            self.config.page_size,
            event_sink=self._on_kv_event,
            offload=offload,
        )
        self.scheduler = Scheduler(self.config, self.runner, self.allocator)
        self.scheduler.slo = self.slo
        self.scheduler.outcome_sink = self._observe_outcome
        self.scheduler.prefix_fetcher = self.prefix_fetcher
        if self.meter is not None:
            # wire the cost ledger into every plane that generates charges:
            # anatomy phases split across dispatch bill rows, HBM pages price
            # at the model's actual per-page wire cost, and the host/disk
            # tiers meter their own residency edges
            self.scheduler.meter = self.meter
            self.scheduler.anatomy.meter = self.meter
            self.allocator.meter = self.meter
            self.allocator.meter_page_bytes = (
                self.model.kv_page_bytes(self.config.page_size)
                if hasattr(self.model, "kv_page_bytes")
                else 0
            )
            if offload is not None:
                offload.meter = self.meter
                if offload.disk is not None:
                    offload.disk.meter = self.meter
        if self.config.warmup == "background":
            # readiness waits only for the traces first requests need; the
            # feature variants (logprobs/penalties, extras prefill) compile
            # between serving steps via run_on_engine — see start()
            self.runner.warmup_core()
        elif self.config.warmup:
            self.runner.warmup()
        log.info(
            "engine ready: model=%s quantize=%s kv_dtype=%s tp=%d pp=%d sp=%d pages=%d (%.1fs)",
            self.config.model_id,
            self.config.quantize or "none",
            self.config.kv_cache_dtype or "bf16",
            self.config.tp,
            self.config.pp,
            self.config.sp,
            self.config.num_pages,
            time.monotonic() - t0,
        )
        self.health.set_state("ready", "engine initialized")

    async def shutdown(self, join_timeout: float = 120.0) -> None:
        self.health.set_state("draining", "shutdown requested")
        self._stopping.set()
        task = getattr(self, "_warmup_task", None)
        if task is not None and not task.done():
            task.cancel()
            try:
                await task
            except (asyncio.CancelledError, Exception):
                pass
        if self._thread is not None:
            await asyncio.get_running_loop().run_in_executor(
                None, lambda: self._thread.join(join_timeout)
            )
            if self._thread.is_alive():
                # the loop thread is wedged (a hung device op / dead PJRT
                # relay): it's a daemon thread, so give up on it rather than
                # hanging the caller's teardown forever
                log.error("engine loop did not exit within %.0fs; abandoning thread", join_timeout)
        disk = getattr(getattr(self, "offload", None), "disk", None)
        if disk is not None:
            # drain the disk tier's write queue and stop its worker (a store
            # that owns its tempdir also cleans it up)
            await asyncio.get_running_loop().run_in_executor(None, disk.close)
        self.health.set_state("dead", "shutdown complete")

    # ---------------- request API ----------------

    async def generate(self, request: EngineRequest) -> AsyncIterator[StepOutput]:
        """Submit a request; yields StepOutputs until finished."""
        async for batch in self.generate_batched(request):
            for item in batch:
                yield item

    async def generate_batched(self, request: EngineRequest) -> AsyncIterator[list[StepOutput]]:
        """Submit a request; yields LISTS of StepOutputs (one list per decode
        window arrival). The engine loop reconciles decode_steps tokens per
        window, so batching here collapses the per-token thread crossings,
        detokenizer calls, and SSE writes that dominated the serving-stack
        overhead (reference's HTTP frontend is an explicitly thin layer:
        lib/llm/src/http/service/openai.rs:132-214)."""
        self._stamp_submission(request)
        self._register_stream(request.request_id)
        self._inbox.put(request)
        async for batch in self._drain_stream_batched(request.request_id):
            yield batch

    @staticmethod
    def _stamp_submission(request: EngineRequest) -> None:
        """Observability stamps at the engine boundary: submission time (the
        queue-wait/TTFT zero point) and the edge trace id the engine thread's
        spans stitch to (the engine loop runs outside the request context)."""
        if not request.enqueue_ts:
            request.enqueue_ts = time.monotonic()
        if request.trace_id is None:
            ctx = current_context()
            if ctx is not None:
                request.trace_id = ctx.trace_id
        events.emit(
            "request.enqueued",
            request_id=request.request_id, trace_id=request.trace_id,
            tenant=request.tenant, priority=request.priority or "",
            prompt_tokens=len(request.token_ids),
        )

    def _register_stream(self, request_id: str) -> None:
        """Open the output channel for a request without scheduling it (the
        disagg decode path schedules via adoption instead)."""
        if not self._started:
            raise RuntimeError("engine not started")
        out_q: asyncio.Queue = asyncio.Queue()
        # Capture the caller's loop per request: generate() may be called from a
        # different event loop than start() (each call_soon_threadsafe must
        # target the loop that owns the queue).
        self._outputs[request_id] = (asyncio.get_running_loop(), out_q)

    async def _drain_stream(self, request_id: str) -> AsyncIterator[StepOutput]:
        async for batch in self._drain_stream_batched(request_id):
            for item in batch:
                yield item

    async def _drain_stream_batched(self, request_id: str) -> AsyncIterator[list[StepOutput]]:
        """Queue items are single StepOutputs or lists of them (one decode
        window's tokens for this request, posted in one thread crossing)."""
        _, out_q = self._outputs[request_id]
        try:
            while True:
                item = await out_q.get()
                if isinstance(item, Exception):
                    raise item
                batch = item if isinstance(item, list) else [item]
                done = False
                for i, o in enumerate(batch):
                    if o.finished:  # belt: nothing rides past a finish
                        batch, done = batch[: i + 1], True
                        break
                yield batch
                if done:
                    return
        finally:
            self._outputs.pop(request_id, None)
            self._cancel_box.put(request_id)

    async def run_on_engine(self, fn):
        """Run fn() on the engine thread (it owns the KV cache/allocator/
        scheduler). fn may return (value, [StepOutput...]) to also emit stream
        items; returns the value."""
        loop = asyncio.get_running_loop()
        fut: asyncio.Future = loop.create_future()
        self._cmd_box.put((fn, loop, fut))
        return await fut

    # ---------------- disaggregation (run via run_on_engine) ----------------
    # The decode side allocates pages and adopts; the prefill side computes KV
    # in its own cache and extracts blocks. See dynamo_tpu/disagg/.

    def sync_lookup_prefix(self, token_ids: list[int], salt: int = 0) -> int:
        return self.allocator.lookup_prefix(token_ids, salt=salt)

    def attach_prefix_fetch(self, fetcher) -> None:
        """Wire the fleet prefix-cache pull client into the scheduler (safe
        before or after start — _initialize copies it through)."""
        self.prefix_fetcher = fetcher
        if self.scheduler is not None:
            self.scheduler.prefix_fetcher = fetcher

    def sync_export_prefix(self, hashes: list[int]):
        """Engine thread: serve a peer's prefix pull (disagg/prefix_fetch.py
        KvPullServer). Walks the contiguous leading run of the requested
        chained block hashes down the tier ladder — HBM pages (the device
        gather is dispatched HERE, atomically with the lookup, so a later
        scatter can't reuse a page before the gather captured it; XLA orders
        the buffers), then host-pool blocks. Returns ``(n_dev_blocks,
        dev_host_future_or_None, host_blocks, cat_axis)``; None = leading
        block in no tier (the server answers with a clean "gone")."""
        alloc, runner = self.allocator, self.runner
        if alloc is None or runner is None:
            return None
        pages: list[int] = []
        for h in hashes:
            page = alloc.cached_page(h)
            if page is None:
                break
            pages.append(page)
        host_blocks: list = []
        offload = getattr(self, "offload", None)
        if offload is not None:
            for h in hashes[len(pages):]:
                data = offload.peek(h)
                if data is None:
                    break
                host_blocks.append(data)
        if not pages and not host_blocks:
            return None
        fut = (
            runner.extract_pages_async(np.asarray(pages, np.int32))
            if pages
            else None
        )
        axis = getattr(runner.model, "wire_n_axis", 2)
        return len(pages), fut, host_blocks, axis

    # ---------------- live migration (disagg/migrate.py) ----------------
    # MIGRATING_OUT: the source freezes a sequence, ships its manifest, and
    # relays the destination's continuation tokens into the original output
    # stream. ADOPTING: the destination re-enters the sequence through
    # normal admission, pulling committed KV via the seq_handoff fetch kind
    # (FETCHING_KV) with chunked recompute from history as the fallback.

    def sync_export_sequence(self, seq_id: str, hashes: list[int]):
        """Engine thread: serve a ``seq_handoff`` pull — the named LIVE
        sequence's own page run for the requested chained hashes. Unlike
        ``sync_export_prefix`` this walks the sequence's pages directly, so
        decode-written blocks whose cache registration deduped onto another
        sequence's page still export OUR copy; a sequence already released
        (source raced ahead) falls back to the shared prefix cache, which
        usually still holds the committed blocks."""
        alloc, runner = self.allocator, self.runner
        if alloc is None or runner is None or not hashes:
            return None
        state = alloc._seqs.get(seq_id)
        if state is None or state.token_seq is None:
            return self.sync_export_prefix(hashes)
        chain = [b.sequence_hash for b in state.token_seq.blocks]
        try:
            start = chain.index(hashes[0])
        except ValueError:
            # the requested run is not in this sequence's chain (destination
            # cached a different leading run): the prefix cache may still
            # resolve it
            return self.sync_export_prefix(hashes)
        pages: list[int] = []
        for i, h in enumerate(hashes):
            j = start + i
            if j >= len(chain) or chain[j] != h or j >= len(state.pages):
                break
            pages.append(state.pages[j])
        if not pages:
            return None
        fut = runner.extract_pages_async(np.asarray(pages, np.int32))
        axis = getattr(runner.model, "wire_n_axis", 2)
        return len(pages), fut, [], axis

    def sync_snapshot_for_migration(self, request_id: str):
        """Engine thread: freeze one in-flight decode sequence
        (MIGRATING_OUT) and build its authoritative manifest. Returns
        ``(manifest_or_None, drained_outputs)``; None = not migratable right
        now (unknown/finished/already migrating/still prefilling/fetching/
        multimodal) — including the double-migration race, where the second
        caller simply gets None."""
        sched = self.scheduler
        seq = next(
            (s for s in sched.slots
             if s is not None and s.req.request_id == request_id),
            None,
        )
        if (
            seq is None or seq.finished or seq.migrating
            or seq.prefill_pos is not None or seq.fetch is not None
            or not seq.generated
        ):
            return None, []
        if seq.req.images:
            # multimodal sequences don't migrate: mm_embeds (device-resident
            # vision encodings) don't ride the ~1KB manifest, and a silent
            # handoff would rebuild the prompt WITHOUT them on any KV-pull
            # miss — wrong tokens, not a slow path. Reject structurally so
            # the caller (and the planner's rebalancer) can pick another
            # victim instead of reading "not migratable right now".
            return "multimodal", []
        # drain the dispatch-ahead pipeline: seq.generated must be the
        # complete materialized history before it becomes the manifest
        outputs = sched._reconcile(block=True, drain=True)
        if seq.finished:
            return None, outputs  # EOS/length landed during the drain
        seq.migrating = True
        events.emit(
            "migration.freeze",
            request_id=seq.req.request_id, trace_id=seq.req.trace_id,
            tenant=seq.req.tenant, priority=seq.req.priority or "",
            generated=len(seq.generated),
        )
        return self._build_manifest(seq), outputs

    def _build_manifest(self, seq):
        import dataclasses

        from dynamo_tpu.disagg.migrate import SequenceManifest

        req = seq.req
        ps = self.config.page_size
        hist_len = seq.prompt_len + len(seq.generated)
        state = self.allocator._seqs.get(req.request_id)
        kv_blocks = 0
        if state is not None and state.token_seq is not None:
            # exportable = full blocks whose KV is complete (the newest
            # token's KV is not written — it is the next decode input)
            kv_blocks = min(
                (hist_len - 1) // ps,
                len(state.token_seq.blocks),
                len(state.pages),
            )
        addr = self.kv_pull_server.address if self.kv_pull_server is not None else ""
        age = (
            max(0.0, time.monotonic() - req.enqueue_ts) if req.enqueue_ts else 0.0
        )
        return SequenceManifest(
            request_id=req.request_id,
            prompt_tokens=list(req.token_ids),
            generated=list(seq.generated),
            sampling=dataclasses.asdict(req.sampling),
            eos_token_ids=list(req.eos_token_ids),
            lora_name=req.lora_name,
            logprobs=req.logprobs,
            penalty_output_from=(
                req.penalty_output_from
                if req.penalty_output_from is not None
                else seq.prompt_len
            ),
            trace_id=req.trace_id,
            tenant=req.tenant,
            scenario=req.scenario,
            priority=req.priority,
            source_addr=addr if kv_blocks > 0 else "",
            kv_blocks=kv_blocks,
            age_s=age,
        )

    def sync_commit_migration(self, request_id: str):
        """Engine thread: the destination's continuation is live — release
        the frozen local sequence WITHOUT a finish output or a goodput
        outcome (the destination records the request's one outcome).
        Returns False when the sequence already ended locally (cancel/EOS
        raced the handoff) — the caller must drop the destination stream."""
        sched = self.scheduler
        seq = next(
            (s for s in sched.slots
             if s is not None and s.req.request_id == request_id),
            None,
        )
        if seq is None or seq.finished or not seq.migrating:
            return False, []
        sched._release(seq, count_finished=False)
        return True, []

    def sync_abort_migration(self, request_id: str):
        """Engine thread: the handoff failed before any continuation token —
        un-freeze the sequence so local decode resumes (never worse than
        preempt+recompute; here not even that)."""
        sched = self.scheduler
        seq = next(
            (s for s in sched.slots
             if s is not None and s.req.request_id == request_id),
            None,
        )
        if seq is None or seq.finished or not seq.migrating:
            return False, []
        seq.migrating = False
        return True, []

    def sync_resume_migration(self, manifest, relayed: list):
        """Engine thread: the destination died AFTER continuation tokens
        were already relayed to the client — requeue a preempt-style resume
        request over history + relayed tokens, so the stream continues
        locally, token-identically (the prefix cache usually still holds
        the committed blocks)."""
        req = manifest.to_resume_request(list(relayed), time.monotonic())
        self.scheduler.waiting.appendleft(req)
        return True, []

    async def migrate_out(self, request_id: str, adopter, timeout_s=None) -> dict:
        """Hand one in-flight sequence to a peer mid-decode and re-pin its
        output stream to the peer's continuation.

        ``adopter(manifest)`` is an async iterator of StepOutputs — the
        in-process form is another engine's ``adopt_migrated``; the worker
        wraps its peer's ``migrate`` endpoint in the same shape. The failure
        ladder: a handoff that dies before the first continuation token
        un-freezes the sequence (local decode resumes); one that dies after
        relaying tokens requeues a preempt-style resume over history +
        relayed tokens. Returns a status dict; "ok" means the stream now
        lives on the destination."""
        timeout = timeout_s or self.config.migration_timeout_s
        sched = self.scheduler
        if not self.config.migration:
            return {"status": "skipped", "reason": "migration disabled"}
        manifest = await self.run_on_engine(
            lambda: self.sync_snapshot_for_migration(request_id)
        )
        if manifest == "multimodal":
            # structured VL rejection (PR 14 follow-up): distinct from the
            # transient "not migratable right now" — this sequence will
            # NEVER migrate; callers must not retry it
            events.emit(
                "migration.fallback", request_id=request_id,
                arm="multimodal_rejected",
            )
            return {
                "status": "rejected",
                "reason": "multimodal_sequence",
                "detail": "mm_embeds do not ride the manifest; "
                          "migrating would silently drop vision context",
            }
        if manifest is None:
            return {"status": "skipped", "reason": "not migratable"}
        t0 = time.monotonic()
        gen = None
        first = None
        try:
            gen = adopter(manifest).__aiter__()
            first = await asyncio.wait_for(gen.__anext__(), timeout)
            if first.finished and first.finish_reason == "error":
                raise RuntimeError("destination rejected the adoption")
        except asyncio.CancelledError:
            raise
        except Exception as e:
            await self._aclose(gen)
            await self.run_on_engine(
                lambda: self.sync_abort_migration(request_id)
            )
            sched.migration_out_failed += 1
            log.warning("migration of %s failed before handoff: %s", request_id, e)
            events.emit(
                "migration.fallback",
                request_id=request_id, trace_id=manifest.trace_id,
                tenant=manifest.tenant, priority=manifest.priority or "",
                arm="abort_unfreeze", error=type(e).__name__,
            )
            return {"status": "failed", "error": f"{type(e).__name__}: {e}"}
        pause = time.monotonic() - t0
        committed = await self.run_on_engine(
            lambda: self.sync_commit_migration(request_id)
        )
        if not committed:
            # cancel/EOS raced the handoff: the local stream already ended;
            # the destination's adopted copy is orphaned — drop it
            await self._aclose(gen)
            return {"status": "skipped", "reason": "sequence ended locally"}
        self.migration_pause_hist.observe(pause)
        tracing.record_span(
            "engine.migrate_out", t0, duration=pause,
            request_id=request_id, trace_id=manifest.trace_id,
            attrs={"kv_blocks": manifest.kv_blocks,
                   "generated": len(manifest.generated)},
        )
        events.emit(
            "migration.handoff",
            request_id=request_id, trace_id=manifest.trace_id,
            tenant=manifest.tenant, priority=manifest.priority or "",
            pause_ms=round(pause * 1e3, 3), kv_blocks=manifest.kv_blocks,
        )
        relayed: list[int] = []
        item = first
        try:
            while True:
                if item.finished and item.finish_reason == "error":
                    raise RuntimeError("destination errored mid-continuation")
                if item.token is not None:
                    relayed.append(item.token)
                self._post(request_id, item)
                if item.finished:
                    sched.migration_out += 1
                    return {
                        "status": "ok", "pause_s": pause,
                        "tokens_relayed": len(relayed),
                        "kv_blocks": manifest.kv_blocks,
                    }
                item = await gen.__anext__()
        except asyncio.CancelledError:
            raise
        except Exception as e:  # incl. StopAsyncIteration without a finish
            await self._aclose(gen)
            sched.migration_out_failed += 1
            if request_id in self._outputs:
                # destination died mid-stream: continue locally from
                # history + everything already relayed (never worse than
                # preempt+recompute)
                log.warning(
                    "migration of %s lost the destination after %d relayed "
                    "tokens (%s); resuming locally",
                    request_id, len(relayed), e,
                )
                await self.run_on_engine(
                    lambda: self.sync_resume_migration(manifest, relayed)
                )
                events.emit(
                    "migration.fallback",
                    request_id=request_id, trace_id=manifest.trace_id,
                    tenant=manifest.tenant, priority=manifest.priority or "",
                    arm="resume_relayed", tokens_relayed=len(relayed),
                    error=type(e).__name__,
                )
                return {"status": "resumed", "tokens_relayed": len(relayed)}
            # the client is gone too: nothing to resume for
            events.emit(
                "migration.fallback",
                request_id=request_id, trace_id=manifest.trace_id,
                tenant=manifest.tenant, priority=manifest.priority or "",
                arm="client_gone", error=type(e).__name__,
            )
            return {"status": "failed", "error": f"{type(e).__name__}: {e}"}

    @staticmethod
    async def _aclose(gen) -> None:
        if gen is not None:
            try:
                await gen.aclose()
            except Exception:
                pass

    async def adopt_migrated(self, manifest) -> AsyncIterator[StepOutput]:
        """ADOPTING side: re-enter a migrated sequence through the normal
        admission path. The manifest's history is the prompt; committed KV
        pulls from the source via seq_handoff (FETCHING_KV) with chunked
        recompute as the fallback; sampling continues positionally, so the
        continuation is token-identical for greedy and seeded lanes."""
        if not self.config.migration:
            raise RuntimeError("migration is disabled on this engine")
        req = manifest.to_engine_request(now=time.monotonic())
        events.emit(
            "migration.adopted",
            request_id=req.request_id, trace_id=req.trace_id,
            tenant=req.tenant, priority=req.priority or "",
            kv_blocks=manifest.kv_blocks, generated=len(manifest.generated),
            age_ms=round(manifest.age_s * 1e3, 3),
        )
        self._stamp_submission(req)
        self._register_stream(req.request_id)
        self._inbox.put(req)
        async for batch in self._drain_stream_batched(req.request_id):
            for item in batch:
                yield item

    def sync_allocate_remote(
        self, request_id: str, token_ids: list[int]
    ) -> tuple[int, int, list[int]]:
        """Decode side: allocate pages for a remote-prefill sequence.
        Returns (cached_len, shared_prefix_pages, page_ids) — the page ids in
        logical order, so the caller can scatter streamed KV parts into them
        as the parts land, before adoption."""
        cached_len, state = self.allocator.allocate_sequence(request_id, token_ids)
        return cached_len, state.shared_prefix_pages, list(state.pages)

    def sync_abort_remote(self, request_id: str) -> None:
        """Abort a remote-prefill request at ANY stage: adoption may already
        have completed on this thread even though the caller saw a
        cancellation, in which case the sequence sits in a decode slot and
        only scheduler.cancel releases both the slot and its pages (freeing
        pages while the slot keeps decoding would corrupt their next owner)."""
        if not self.scheduler.cancel(request_id):
            if request_id in self.allocator._seqs:
                self.allocator.free_sequence(request_id)

    def sync_remote_prefill(
        self, rp, device: bool = False, mode: str | None = None, on_part=None
    ):
        """Prefill side: full chunked prefill in our own cache (prefix cache
        applies), then extract the requested block range.

        Returns ``(PrefillResult, host_data_or_None)``. mode:
          - "inline" — KV staged to host and serialized into the result
            (legacy / tiny transfers)
          - "ici" — same-process handoff: KV gathered into a device array
            parked in the ici hub; result carries kv_transfer_id
          - "socket" — KV staged to host and RETURNED alongside the result;
            the caller ships it over the dedicated data plane
            (disagg/dataplane.py) while the result message becomes the
            completion notification

        ``on_part`` (socket mode only) switches to the CHUNK-STREAMED export:
        instead of one monolithic post-prefill pull, pages finalized by each
        prefill chunk are gathered immediately (D2H resolved off this thread,
        see ModelRunner.extract_pages_async) and handed to
        ``on_part(part_seq, part_total, page_from, page_to, host_future)``
        while the next chunk computes. The result then carries
        ``kv_parts == part_total`` and no host_data."""
        from dynamo_tpu.disagg import ici
        from dynamo_tpu.disagg.dataplane import stream_part_plan
        from dynamo_tpu.engine.sampling import SamplingParams
        from dynamo_tpu.llm.remote_prefill import PrefillResult

        if mode is None:
            mode = "ici" if device else "inline"
        rid = f"rp-{rp.request_id}"
        prompt_len = len(rp.token_ids)
        cached_len, state = self.allocator.allocate_sequence(rid, list(rp.token_ids))
        # fleet prefix pull BEFORE recomputing (ROADMAP item 3 follow-up):
        # when the router attached a holder whose cached prefix beats ours,
        # pull the missing leading blocks over the dataplane — the same
        # timeout -> recompute fallback the decode-side FETCHING_KV path
        # uses, synchronous here because the prefill worker's engine thread
        # has nothing to interleave with this request anyway
        if getattr(rp, "kv_holder_addr", ""):
            cached_len = self._pull_remote_prefix(
                rp.kv_holder_addr, int(getattr(rp, "kv_holder_blocks", 0) or 0),
                state, cached_len, prompt_len, trace_id=rp.trace_id or None,
            )
        ps = self.config.page_size
        start_page = rp.skip_leading_tokens // ps
        n_pages = -(-prompt_len // ps)
        plan = (
            stream_part_plan(
                start_page, cached_len, prompt_len, ps, self.config.max_prefill_chunk
            )
            if (mode == "socket" and on_part is not None)
            else []
        )
        try:
            page_table = self._page_table_for(state)
            req = EngineRequest(
                request_id=rid,
                token_ids=list(rp.token_ids),
                sampling=SamplingParams(
                    temperature=rp.temperature, top_k=rp.top_k, top_p=rp.top_p, max_tokens=1
                ),
                trace_id=rp.trace_id or None,
            )
            data = None
            if plan:
                total = len(plan)
                next_part = [0]

                def flush(tokens_final: int, last: bool) -> None:
                    limit = n_pages if last else tokens_final // ps
                    while next_part[0] < total and plan[next_part[0]][1] <= limit:
                        pf, pt = plan[next_part[0]]
                        ids = np.asarray(state.pages[pf:pt], np.int32)
                        with tracing.span(
                            "disagg.kv_extract", request_id=rp.request_id,
                            trace_id=req.trace_id, pages=len(ids), mode="socket",
                            part=next_part[0],
                        ):
                            fut = self.runner.extract_pages_async(ids)
                        on_part(next_part[0], total, pf, pt, fut)
                        next_part[0] += 1

                # prefix-cached pages below cached_len are final already;
                # everything else ships as its finalizing chunk completes
                flush(cached_len, False)
                first_token = self.scheduler.run_prefill_chunks(
                    req, page_table, cached_len, prompt_len,
                    on_chunk=lambda s, e: flush(e, e == prompt_len),
                )
                self.allocator.commit_prefilled(rid, prompt_len)
            else:
                first_token = self.scheduler.run_prefill_chunks(
                    req, page_table, cached_len, prompt_len
                )
                self.allocator.commit_prefilled(rid, prompt_len)
                ids = state.pages[start_page:n_pages]
                if ids:
                    with tracing.span(
                        "disagg.kv_extract", request_id=rp.request_id,
                        trace_id=req.trace_id, pages=len(ids), mode=mode,
                    ):
                        if mode == "ici":
                            data = self.runner.extract_pages_device(np.asarray(ids, np.int32))
                        else:
                            data = self.runner.extract_pages(np.asarray(ids, np.int32))
        finally:
            self.allocator.free_sequence(rid)  # full blocks stay cached for reuse

        transfer_id = ""
        if mode == "ici" and data is not None:
            transfer_id = ici.transfer_key(rp.decode_worker_id, rp.request_id)
            if not ici.put_transfer(transfer_id, data):
                transfer_id = ""  # consumer abandoned the request already
        # int8 caches export the {"q","s"} wire dict: shape/dtype describe
        # the int8 payload; the scale plane rides its own result fields on
        # the inline path (sockets carry it in part headers instead)
        from dynamo_tpu.quant.kv import is_quantized_wire

        payload = data["q"] if is_quantized_wire(data) else data
        inline = data is not None and mode == "inline"
        scales = data["s"] if (inline and is_quantized_wire(data)) else None
        result = PrefillResult(
            request_id=rp.request_id,
            first_token=int(first_token),
            prompt_len=prompt_len,
            skip_leading_tokens=start_page * ps,
            kv_shape=tuple(payload.shape) if data is not None else (),
            kv_dtype=str(payload.dtype) if data is not None else "",
            kv_bytes=payload.tobytes() if inline else b"",
            kv_transfer_id=transfer_id,
            kv_mode="socket" if plan else (mode if data is not None else "inline"),
            kv_parts=len(plan),
            kv_scales_bytes=scales.tobytes() if scales is not None else b"",
            kv_scales_shape=tuple(scales.shape) if scales is not None else (),
            kv_scales_dtype=str(scales.dtype) if scales is not None else "",
        )
        return result, (data if mode == "socket" else None)

    def _pull_remote_prefix(
        self, holder_addr: str, holder_blocks: int, state, cached_len: int,
        prompt_len: int, trace_id=None,
    ) -> int:
        """Prefill-side fleet prefix pull: fetch the contiguous leading
        blocks past our local cache from ``holder_addr`` and scatter them
        into the sequence's pre-allocated pages. Returns the new cached_len;
        ANY failure (no fetcher, timeout, gone, partial scatter) returns the
        original — the caller recomputes, never errors."""
        sched, cfg = self.scheduler, self.config
        fetcher = self.prefix_fetcher
        if fetcher is None or not cfg.prefix_fetch or holder_blocks <= 0:
            return cached_len
        ps = cfg.page_size
        base = cached_len // ps
        # the final prompt token must prefill so the model emits logits
        want_to = min(holder_blocks, (prompt_len - 1) // ps)
        if want_to - base < max(1, cfg.prefix_fetch_min_blocks):
            return cached_len
        hashes = [b.sequence_hash for b in state.token_seq.blocks[base:want_to]]
        if not hashes:
            return cached_len
        t0 = time.monotonic()
        try:
            fut = fetcher.fetch(holder_addr, hashes, timeout_s=cfg.prefix_fetch_timeout_s)
            res = fut.result(timeout=cfg.prefix_fetch_timeout_s + 2.0)
        except Exception:
            log.exception("prefill-side prefix pull from %s failed", holder_addr)
            sched.prefix_fetch_fallbacks += 1
            return cached_len
        dt = time.monotonic() - t0
        sched.stage_hist["prefix_fetch"].observe(dt)
        applied = 0
        if getattr(res, "status", "") == "hit" and res.blocks:
            try:
                for part in res.parts:
                    if part.block_from != applied:
                        break  # hole: only the contiguous leading run counts
                    ids = np.asarray(
                        state.pages[base + part.block_from : base + part.block_to],
                        np.int32,
                    )
                    if len(ids) != part.block_to - part.block_from:
                        break
                    self.runner.inject_pages_bucketed(ids, part.data, axis=part.cat_axis)
                    applied = part.block_to
            except Exception:
                log.exception("scatter of pulled prefix failed; recomputing")
                applied = 0
        if not applied:
            sched.prefix_fetch_fallbacks += 1
            return cached_len
        new_cached = (base + applied) * ps
        sched.prefix_fetch_hits += 1
        sched.prefix_fetch_blocks += applied
        sched.prefix_fetch_bytes += res.bytes
        sched.prefix_fetch_tokens += max(0, new_cached - cached_len)
        tracing.record_span(
            "engine.prefix_fetch", t0, duration=dt, trace_id=trace_id,
            attrs={"blocks": applied, "bytes": res.bytes, "holder": holder_addr,
                   "side": "prefill"},
        )
        return max(cached_len, new_cached)

    def sync_adopt_prefilled(
        self, req: EngineRequest, result, cached_len: int, kv_data=None,
        injected_pages: int = 0,
    ):
        """Decode side: inject received KV blocks into the pre-allocated pages
        and enter the sequence into decode. KV arrives as wire bytes (inline),
        as a device array via the ici hub (same-pod path), as a host array
        the caller already pulled off the dedicated data-plane socket
        (``kv_data``), or — the streamed path — scattered incrementally as
        parts landed, in which case ``injected_pages`` says how many pages
        the caller already wrote and this adopt only validates the count."""
        from dynamo_tpu.disagg import ici

        state = self.allocator._seqs[req.request_id]
        ps = self.config.page_size
        data = kv_data
        if data is None and result.kv_transfer_id:
            data = ici.pop_transfer(result.kv_transfer_id)
            if data is None:
                raise RuntimeError(
                    f"ici transfer {result.kv_transfer_id} missing for {req.request_id}"
                )
        elif data is None and result.kv_bytes:
            data = result.kv_array()
        start_page = result.skip_leading_tokens // ps
        n_pages = -(-result.prompt_len // ps)
        ids = state.pages[start_page:n_pages]
        if data is not None:
            with tracing.span(
                "disagg.kv_inject", request_id=req.request_id,
                trace_id=req.trace_id, pages=len(ids), mode=result.kv_mode,
            ):
                self.runner.inject_pages(np.asarray(ids, np.int32), data)
        elif injected_pages:
            # streamed adoption: every part was scattered on arrival; a count
            # mismatch means a part was lost — decoding from the hole's
            # uninitialized pages would be silent corruption
            if injected_pages != len(ids):
                raise RuntimeError(
                    f"streamed KV for {req.request_id} injected "
                    f"{injected_pages} pages, expected {len(ids)}"
                )
        elif ids:
            # pages were expected to be filled remotely but the result carried
            # no KV (e.g. a swallowed transfer): adopting would decode from
            # uninitialized pages — fail the request loudly instead
            raise RuntimeError(
                f"prefill result for {req.request_id} carried no KV for "
                f"{len(ids)} pending pages"
            )
        self.allocator.commit_prefilled(req.request_id, result.prompt_len)
        outputs = self.scheduler.adopt_prefilled(req, result.first_token, cached_len)
        return None, outputs  # (value, stream outputs) convention

    def _page_table_for(self, state) -> "np.ndarray":
        # sized to the sequence's ladder rung, not the dense width — the
        # remote-prefill path dispatches the same bucketed traces the local
        # scheduler does
        width = self.config.table_bucket_for(max(1, len(state.pages)))
        page_table = np.zeros(width, np.int32)
        page_table[: len(state.pages)] = state.pages
        return page_table

    # ---------------- metrics / events ----------------

    def metrics(self) -> ForwardPassMetrics:
        alloc, sched = self.allocator, self.scheduler
        if alloc is None or sched is None:
            return ForwardPassMetrics()
        hit_rate = (
            alloc.cache_hit_blocks / alloc.cache_query_blocks
            if alloc.cache_query_blocks
            else 0.0
        )
        return ForwardPassMetrics(
            request_active_slots=sched.num_running,
            request_total_slots=self.config.max_seqs,
            kv_active_blocks=alloc.active_pages,
            kv_total_blocks=self.config.num_pages - 1,
            num_requests_waiting=len(sched.waiting),
            gpu_cache_usage_perc=alloc.used_pages / max(1, self.config.num_pages - 1),
            gpu_prefix_cache_hit_rate=hit_rate,
        )

    def resource_snapshot(self) -> dict:
        """Engine resource gauges for stats broadcasts + Prometheus: KV
        page-pool occupancy/high-watermark, prefix-cache hit/miss,
        preemption/offload counters, device HBM live/peak bytes, and the
        monitored-jit compile churn (count + cumulative seconds)."""
        alloc, sched, runner = self.allocator, self.scheduler, self.runner
        if alloc is None or sched is None:
            return {}
        # actual-dtype KV byte accounting: the page-size arithmetic everyone
        # downstream (dynotop, capacity planning) used to do assuming bf16
        page_bytes = 0
        if runner is not None and hasattr(runner.model, "kv_page_bytes"):
            page_bytes = runner.model.kv_page_bytes(self.config.page_size)
        snap = {
            "kv_cache_dtype": self.config.kv_cache_dtype or "bf16",
            "kv_page_bytes": page_bytes,
            "kv_pool_bytes_total": page_bytes * (self.config.num_pages - 1),
            "kv_pool_bytes_used": page_bytes * alloc.used_pages,
            "kv_pages_total": self.config.num_pages - 1,
            "kv_pages_used": alloc.used_pages,
            "kv_pages_active": alloc.active_pages,
            "kv_pages_free": alloc.free_pages,
            "kv_pages_peak": alloc.peak_used_pages,
            "prefix_cache_hit_blocks": alloc.cache_hit_blocks,
            "prefix_cache_miss_blocks": max(
                0, alloc.cache_query_blocks - alloc.cache_hit_blocks
            ),
            "prefix_cache_query_blocks": alloc.cache_query_blocks,
            # fleet prefix cache: remote pulls this engine issued (requester
            # side; the pull SERVER's counters ride the worker's kv_pull stats)
            "prefix_fetch_hits": sched.prefix_fetch_hits,
            "prefix_fetch_fallbacks": sched.prefix_fetch_fallbacks,
            "prefix_fetch_blocks": sched.prefix_fetch_blocks,
            "prefix_fetch_bytes": sched.prefix_fetch_bytes,
            "prefix_fetch_tokens": sched.prefix_fetch_tokens,
            # live migration (disagg/migrate.py): both roles' counters ride
            # worker stats -> /cluster/status -> dynotop's MIG column
            "migration_out": sched.migration_out,
            "migration_out_failed": sched.migration_out_failed,
            "migration_in": sched.migration_in,
            "migration_in_pulled": sched.migration_in_pulled,
            "migration_in_recomputed": sched.migration_in_recomputed,
            "migration_tokens_salvaged": sched.migration_tokens_salvaged,
            "preemptions": sched.preempt_count,
            "pressure_drains": sched.pressure_drain_count,
            # multi-tenant QoS: running lanes per priority class, per-class
            # preemption victims, and critical-triggered sheds (dynotop QOS
            # column + the enforcement audit trail)
            "qos": {
                "enabled": self.config.qos,
                "running": self._qos_running_classes(sched),
                "preempted": dict(sched.qos_preempted),
                "sheds": sched.qos_sheds,
                "shed_migrations": sched.qos_shed_migrations,
            },
            # long-context: table-width ladder + depth-aware chunking +
            # watermark-driven cold-KV drain (str keys: JSON-safe on the wire)
            "context_table_promotions": sched.table_promotions,
            "context_table_dispatches": {
                str(w): n for w, n in sorted(sched.table_dispatches.items())
            },
            "context_chunk_dispatches": {
                str(b): n for b, n in sorted(sched.chunk_dispatches.items())
            },
            "offload_pressure_blocks": sched.offload_pressure_blocks,
            "requests_waiting": len(sched.waiting),
            "oldest_waiting_age_s": round(sched.oldest_waiting_age(), 3),
            "engine_steps": self.step_count,
            # step-anatomy plane (utils/step_anatomy.py): per-kind phase
            # seconds, host/roofline fractions, decode dispatch cadence —
            # nested dict rides /cluster/status + dynotop STEP/ROOF columns
            "step_anatomy": sched.anatomy.snapshot(),
            # cost-attribution plane (utils/metering.py): per-tenant device-
            # seconds / KV byte-seconds / token charges — rides worker stats
            # -> /cluster/costs, dynotop's COST column, and the planner's
            # per-tenant demand signal. None-safe: {} when metering is off.
            "costs": self.meter.snapshot() if self.meter is not None else {},
            # graceful zeros when no runner reports (CPU, or pre-init)
            "hbm_bytes_in_use": 0,
            "hbm_peak_bytes_in_use": 0,
            "hbm_bytes_limit": 0,
            "hbm_reporting_devices": 0,
        }
        offload = getattr(self, "offload", None)
        if offload is not None:
            snap.update(
                offload_saves=offload.saves,
                offload_loads=offload.loads,
                offload_drops=offload.drops,
                offload_blocks_resident=len(offload),
                offload_capacity_blocks=offload.capacity_blocks,
                # at the ACTUAL wire dtype (int8 host blocks ~half of bf16)
                offload_block_bytes=offload.block_bytes,
                offload_bytes_resident=offload.bytes_resident,
            )
            disk = getattr(offload, "disk", None)
            if disk is not None:
                snap.update(
                    disk_spills=disk.spills,
                    disk_restores=disk.restores,
                    disk_drops=disk.drops,
                    disk_io_errors=disk.io_errors,
                    disk_blocks_resident=len(disk),
                    disk_bytes_resident=disk.bytes_resident,
                    disk_budget_bytes=disk.budget_bytes,
                    disk_restore_s=round(disk.restore_s, 4),
                    disk_restore_hits=sched.disk_restore_hits,
                    disk_restore_fallbacks=sched.disk_restore_fallbacks,
                    disk_restore_blocks=sched.disk_restore_blocks,
                    disk_restore_tokens=sched.disk_restore_tokens,
                )
        spec = self.config.spec
        if spec is not None:
            st = sched.stage
            snap["spec_proposer"] = spec.kind
            snap["spec_acceptance_rate"] = round(
                st.spec_accepted / max(1, st.spec_proposed), 4
            )
            draft = getattr(runner, "draft", None) if runner is not None else None
            if draft is not None:
                # the draft model's OWN paged pool (acceptance criterion:
                # draft KV pages visible in resource_snapshot)
                snap["spec_draft_pages_total"] = draft.pages_total
                snap["spec_draft_pages_used"] = draft.pages_used
                snap["spec_draft_model"] = spec.model
        store = getattr(runner, "lora_store", None) if runner is not None else None
        if store is not None:
            # multi-LoRA: device slot occupancy, eviction/load churn, and
            # per-adapter demand (dynotop's LORA column + dynamo_lora_*)
            ls = store.metrics_snapshot()
            snap["lora_resident"] = ls["resident"]
            snap["lora_capacity"] = ls["capacity"]
            snap["lora_evictions"] = ls["evictions"]
            snap["lora_loads"] = ls["loads"]
            snap["lora_load_seconds"] = ls["load_seconds"]
            snap["lora_requests"] = ls["requests"]
            snap["lora_hot"] = ls["hot"]
        if runner is not None:
            snap.update(runner.hbm_stats())
            cm = getattr(runner, "compile_monitor", None)
            if cm is not None:
                c = cm.snapshot()
                snap["xla_compiles"] = c["compiles"]
                snap["xla_compile_s"] = c["compile_s"]
        return snap

    @staticmethod
    def _qos_running_classes(sched) -> dict:
        out: dict = {}
        for s in sched.slots:
            if s is not None and not s.finished:
                cls = s.req.priority or "standard"
                out[cls] = out.get(cls, 0) + 1
        return out

    def slo_snapshot(self) -> dict:
        return self.slo.snapshot()

    def events_snapshot(self, limit: int = 32) -> dict:
        """Flight-recorder summary for worker stats broadcasts (the fleet
        /cluster/events merge + dynotop's EVT column read this)."""
        return events.JOURNAL.snapshot(limit=limit)

    def debug_steps(self, limit: int = 128, kind: Optional[str] = None) -> dict:
        """The ``/debug/steps`` payload: recent per-dispatch StepRecords
        (newest last) + the summary fractions — where the milliseconds of a
        live engine's steps went, inspectable without tracing enabled."""
        if self.scheduler is None:
            return {"records": [], "summary": {}}
        anatomy = self.scheduler.anatomy
        return {
            "records": anatomy.records(limit=limit, kind=kind),
            "summary": anatomy.snapshot(),
        }

    def goodput_snapshot(self) -> dict:
        """Windowed goodput per scenario/tenant (worker stats broadcasts +
        dynotop's GOODPUT column)."""
        return self.goodput.snapshot()

    def cost_snapshot(self) -> dict:
        """Cost-attribution rollup (utils/metering.py MeterLedger.snapshot):
        per-tenant device-seconds by dispatch kind, per-tier KV byte-seconds
        and residency, queued-seconds, and the admitted-vs-consumed token
        counters. {} when metering is off."""
        return self.meter.snapshot() if self.meter is not None else {}

    def request_cost(self, request_id: str) -> Optional[dict]:
        """Per-request cost footer for /debug/requests/{id}: device-ms by
        dispatch kind + peak resident KV bytes per tier. None when metering
        is off or the footer LRU already evicted the request."""
        if self.meter is None:
            return None
        return self.meter.request_cost(request_id)

    def _observe_outcome(self, outcome) -> None:
        """Scheduler outcome sink: goodput accounting + the drain-rate
        sample every Retry-After estimate prices from."""
        self.drain_estimator.note_finish()
        self.goodput.observe(outcome)

    def backpressure_snapshot(self) -> dict:
        """The frontend's engine-backpressure view (utils/qos.py): queue
        depth, measured drain rate, and the estimated wait a NEW request
        faces — the shed check compares est_wait_s against the TTFT budget
        and sheds batch-class load first. est_wait_s is None until anything
        has finished (a cold engine must not shed on a fake rate)."""
        sched = self.scheduler
        depth = len(sched.waiting) if sched is not None else 0
        rate = self.drain_estimator.rate_rps()
        return {
            "queue_depth": depth,
            "drain_rps": round(rate, 4) if rate is not None else None,
            "est_wait_s": (
                round(depth / rate, 4) if rate and rate > 0 else None
            ),
            "retry_after_s": self.drain_estimator.retry_after_s(depth),
        }

    def stage_snapshot(self) -> dict:
        """Per-stage latency attribution totals (scheduler StageStats plus the
        host-KV-offload transfer leg) — the bench artifact's breakdown source."""
        if self.scheduler is None:
            return {}
        snap = self.scheduler.stage.snapshot()
        offload = getattr(self, "offload", None)
        if offload is not None:
            snap["kv_offload_s"] = round(offload.transfer_s, 4)
            snap["kv_offload_blocks"] = offload.saves + offload.loads
        return snap

    def render_stage_metrics(self) -> str:
        """Prometheus text for the engine-stage histograms (queue wait, TTFT,
        prefill, decode-window dispatch, reconcile wait) + stage-seconds
        counters; mounted under the serving /metrics endpoint."""
        if self.scheduler is None:
            return ""
        from dynamo_tpu.utils.prometheus import render_family

        parts = [h.render() for h in self.scheduler.stage_hist.values()]
        stage_seconds = {
            "queue_wait": self.scheduler.stage.queue_wait_s,
            "prefill": self.scheduler.stage.prefill_s,
            "decode_dispatch": self.scheduler.stage.decode_dispatch_s,
            "reconcile_wait": self.scheduler.stage.reconcile_wait_s,
        }
        offload = getattr(self, "offload", None)
        if offload is not None:
            stage_seconds["kv_offload"] = offload.transfer_s
        st = self.scheduler.stage
        if st.spec_rounds:
            stage_seconds["spec_verify"] = st.spec_dispatch_s
        parts.append(render_family(
            "dynamo_engine_stage_seconds_total", "counter",
            "cumulative engine-thread seconds attributed to each stage",
            [({"stage": k}, v) for k, v in sorted(stage_seconds.items())],
        ))
        if self.config.speculative is not None:
            parts.append(render_family(
                "dynamo_spec_proposed_total", "counter",
                "draft tokens proposed by the speculative proposer",
                [({}, st.spec_proposed)],
            ))
            parts.append(render_family(
                "dynamo_spec_accepted_total", "counter",
                "proposed draft tokens accepted by batched verification",
                [({}, st.spec_accepted)],
            ))
            spec = self.config.spec
            # acceptance labeled by proposer kind: dashboards comparing an
            # ngram fleet against a draft-model fleet read ONE family
            parts.append(render_family(
                "dynamo_spec_acceptance_ratio", "gauge",
                "accepted/proposed draft tokens, labeled by proposer kind",
                [({"proposer": spec.kind},
                  round(st.spec_accepted / max(1, st.spec_proposed), 4))],
            ))
            if spec.kind == "draft":
                parts.append(render_family(
                    "dynamo_spec_draft_seconds_total", "counter",
                    "engine-thread seconds in the draft model, by phase "
                    "(dispatch = the batched per-round drafting call; "
                    "prefill = draft-cache builds at admission/resume)",
                    [({"phase": "dispatch"}, round(st.spec_draft_s, 4)),
                     ({"phase": "prefill"}, round(st.spec_draft_prefill_s, 4))],
                ))
                parts.append(render_family(
                    "dynamo_spec_draft_dispatch_total", "counter",
                    "batched draft-model drafting dispatches (one per spec "
                    "round with >= 1 live draft lane)",
                    [({}, st.spec_draft_calls)],
                ))
                parts.append(render_family(
                    "dynamo_spec_draft_prefill_total", "counter",
                    "draft-cache prefills (admission, preemption resume, "
                    "offload restore, and catch-up rebuilds)",
                    [({}, st.spec_draft_prefills)],
                ))
        # step-anatomy families: dynamo_step_seconds_total{phase,kind} +
        # dynamo_step_dispatch_total{kind} + dynamo_engine_roofline_fraction
        parts.append(self.scheduler.anatomy.render_metrics())
        # cost-attribution families: the five dynamo_cost_* (utils/metering.py)
        if self.meter is not None:
            parts.append(self.meter.render_metrics())
        parts.append(self._render_resource_metrics())
        # fleet prefix cache: wire-side client/server families join the
        # engine surface when the hosting worker attached them
        if self.prefix_fetcher is not None:
            parts.append(self.prefix_fetcher.render_metrics())
        if self.kv_pull_server is not None:
            parts.append(self.kv_pull_server.render_metrics())
        parts.append(self.health.render_metrics())
        # engine-scoped prefix: a colocated HTTP frontend renders its own
        # tracker under dynamo_slo_*; sharing that name here would emit
        # duplicate families in the combined exposition
        parts.append(self.slo.render_metrics(prefix="dynamo_engine_slo"))
        # goodput plane, same prefix logic (the frontend owns dynamo_goodput_*)
        parts.append(self.goodput.render_metrics(prefix="dynamo_engine_goodput"))
        return "".join(parts)

    def _render_resource_metrics(self) -> str:
        """Resource gauge families from resource_snapshot(): page pool,
        prefix cache, preemptions, offload, HBM, compile churn."""
        from dynamo_tpu.utils.prometheus import render_family

        r = self.resource_snapshot()
        if not r:
            return ""
        parts = [
            render_family(
                "dynamo_engine_kv_pages", "gauge",
                "KV page-pool occupancy by state (total excludes the null page)",
                [({"state": s}, r[f"kv_pages_{s}"])
                 for s in ("total", "used", "active", "free", "peak")],
            ),
            render_family(
                "dynamo_engine_prefix_cache_blocks_total", "counter",
                "prefix-cache lookups by result (block granularity)",
                [({"result": "hit"}, r["prefix_cache_hit_blocks"]),
                 ({"result": "miss"}, r["prefix_cache_miss_blocks"])],
            ),
            render_family(
                "dynamo_prefix_fetch_requests_total", "counter",
                "remote prefix pulls resolved by this engine, by outcome "
                "(hit = blocks scattered and recompute skipped; fallback = "
                "timeout/gone/error degraded to recompute)",
                [({"result": "hit"}, r["prefix_fetch_hits"]),
                 ({"result": "fallback"}, r["prefix_fetch_fallbacks"])],
            ),
            render_family(
                "dynamo_prefix_fetch_blocks_total", "counter",
                "KV blocks pulled from peers and scattered into local pages",
                [({}, r["prefix_fetch_blocks"])],
            ),
            render_family(
                "dynamo_prefix_fetch_bytes_total", "counter",
                "KV payload bytes pulled from peers (at the wire KV dtype)",
                [({}, r["prefix_fetch_bytes"])],
            ),
            render_family(
                "dynamo_prefix_fetch_tokens_total", "counter",
                "prompt tokens whose prefill recompute a remote pull skipped",
                [({}, r["prefix_fetch_tokens"])],
            ),
            # live migration: handoffs out (ok = stream re-pinned to the
            # destination; failed = resumed locally) and adoptions in
            # (pulled = committed KV arrived over seq_handoff; recomputed =
            # timeout/gone/corrupt degraded to chunked recompute)
            render_family(
                "dynamo_migration_requests_total", "counter",
                "live sequence migrations by role and terminal result",
                [({"role": "out", "result": "ok"}, r["migration_out"]),
                 ({"role": "out", "result": "failed"}, r["migration_out_failed"]),
                 ({"role": "in", "result": "pulled"}, r["migration_in_pulled"]),
                 ({"role": "in", "result": "recomputed"}, r["migration_in_recomputed"])],
            ),
            render_family(
                "dynamo_migration_tokens_salvaged_total", "counter",
                "history tokens whose prefill recompute a seq_handoff KV "
                "pull skipped at adoption",
                [({}, r["migration_tokens_salvaged"])],
            ),
            self.migration_pause_hist.render(),
            render_family(
                "dynamo_engine_preemptions_total", "counter",
                "sequences bounced back to the waiting queue by page pressure",
                [({}, r["preemptions"])],
            ),
            # multi-tenant QoS: victims by priority class (page pressure AND
            # critical-triggered sheds; result=migrated = the victim went via
            # live migration instead of preempt+recompute)
            render_family(
                "dynamo_qos_preemptions_total", "counter",
                "preemption/shed victims by priority class (multi-tenant "
                "QoS: batch lanes pay before standard, standard before "
                "critical; migrated = victim handed to a peer instead of "
                "recomputed)",
                [({"class": c, "result": "preempted"}, n)
                 for c, n in sorted(r["qos"]["preempted"].items())]
                + [({"class": "any", "result": "migrated"},
                    r["qos"]["shed_migrations"])],
            ),
            render_family(
                "dynamo_engine_pressure_drains_total", "counter",
                "pipeline drains forced by ensure_capacity misses",
                [({}, r["pressure_drains"])],
            ),
            # long-context families: the page-table width ladder (dispatches
            # by width + mid-flight rung promotions), depth-aware prefill
            # chunk buckets, and the watermark-driven cold-KV host drain
            render_family(
                "dynamo_engine_context_table_dispatch_total", "counter",
                "engine dispatches by page-table width (the pow2 ladder "
                "rung the call's widest sequence needed)",
                [({"width": w}, n)
                 for w, n in sorted(r["context_table_dispatches"].items(),
                                    key=lambda kv: int(kv[0]))]
                or [({"width": str(self.config.table_buckets[0])}, 0)],
            ),
            render_family(
                "dynamo_engine_context_table_promotions_total", "counter",
                "sequences promoted to a wider page-table ladder rung "
                "mid-flight (decode growth past their current width)",
                [({}, r["context_table_promotions"])],
            ),
            render_family(
                "dynamo_engine_context_chunk_total", "counter",
                "prefill chunks by padded bucket length (the depth-aware "
                "planner shrinks chunks as context deepens)",
                [({"len": b}, n)
                 for b, n in sorted(r["context_chunk_dispatches"].items(),
                                    key=lambda kv: int(kv[0]))]
                or [({"len": str(min(self.config.prefill_buckets))}, 0)],
            ),
            render_family(
                "dynamo_engine_offload_pressure_blocks_total", "counter",
                "cold refcount-0 KV blocks drained to the host tier by the "
                "occupancy-watermark pressure path (batched gathers)",
                [({}, r["offload_pressure_blocks"])],
            ),
            render_family(
                "dynamo_engine_hbm_bytes", "gauge",
                "device memory summed over local devices (zeros on CPU)",
                [({"kind": "live"}, r["hbm_bytes_in_use"]),
                 ({"kind": "peak"}, r["hbm_peak_bytes_in_use"]),
                 ({"kind": "limit"}, r["hbm_bytes_limit"])],
            ),
            # KV cache bytes at the ACTUAL storage dtype (int8 pages cost
            # half + scale planes; pre-r6 consumers assumed bf16)
            render_family(
                "dynamo_engine_kv_cache_bytes", "gauge",
                "KV page-pool bytes at the configured kv_cache_dtype",
                [({"kind": "total"}, r["kv_pool_bytes_total"]),
                 ({"kind": "used"}, r["kv_pool_bytes_used"])],
            ),
            render_family(
                "dynamo_engine_kv_cache_page_bytes", "gauge",
                "bytes one KV page costs across all layers (K+V, incl. int8 "
                "scale planes), labeled with the cache storage dtype",
                [({"dtype": r["kv_cache_dtype"]}, r["kv_page_bytes"])],
            ),
        ]
        if "xla_compiles" in r:
            parts.append(render_family(
                "dynamo_engine_xla_compiles_total", "counter",
                "XLA compilations observed by the monitored-jit wrappers "
                "(a climbing value mid-serving is a recompile storm)",
                [({}, r["xla_compiles"])],
            ))
            parts.append(render_family(
                "dynamo_engine_xla_compile_seconds_total", "counter",
                "cumulative seconds engine calls spent tracing + compiling",
                [({}, round(r["xla_compile_s"], 4))],
            ))
        if "offload_saves" in r:
            parts.append(render_family(
                "dynamo_engine_offload_blocks_total", "counter",
                "host-DRAM KV tier block movement by operation",
                [({"op": "save"}, r["offload_saves"]),
                 ({"op": "load"}, r["offload_loads"]),
                 ({"op": "drop"}, r["offload_drops"])],
            ))
            parts.append(render_family(
                "dynamo_engine_offload_bytes_resident", "gauge",
                "host-DRAM KV tier bytes resident at the ACTUAL wire dtype "
                "(int8 blocks cost ~half of bf16)",
                [({}, r["offload_bytes_resident"])],
            ))
        if "disk_blocks_resident" in r:
            # disk KV tier (engine/kv_store.py): the third rung of the
            # ladder — resident blocks/bytes against the byte budget plus
            # spill/restore churn and cumulative restore wall time
            parts.append(render_family(
                "dynamo_engine_disk_blocks", "gauge",
                "disk KV tier blocks resident (int8-compressed block files "
                "keyed by chained sequence hash)",
                [({}, r["disk_blocks_resident"])],
            ))
            parts.append(render_family(
                "dynamo_engine_disk_bytes", "gauge",
                "disk KV tier bytes: resident payload vs the configured "
                "byte budget (disk_cache_bytes)",
                [({"kind": "resident"}, r["disk_bytes_resident"]),
                 ({"kind": "budget"}, r["disk_budget_bytes"])],
            ))
            parts.append(render_family(
                "dynamo_engine_disk_spills_total", "counter",
                "disk KV tier block writes by outcome (spill = host-pool "
                "victim demoted; drop = budget eviction — the block left "
                "its last tier)",
                [({"op": "spill"}, r["disk_spills"]),
                 ({"op": "drop"}, r["disk_drops"])],
            ))
            parts.append(render_family(
                "dynamo_engine_disk_restores_total", "counter",
                "disk KV tier blocks restored (ok = verified + promoted to "
                "device; error = read/checksum failures that fell back to "
                "recompute)",
                [({"outcome": "ok"}, r["disk_restores"]),
                 ({"outcome": "error"}, r["disk_io_errors"])],
            ))
            parts.append(render_family(
                "dynamo_engine_disk_restore_seconds", "counter",
                "cumulative wall seconds the disk worker spent reading, "
                "verifying, and dequantizing restore runs (off the engine "
                "loop — restores park in FETCHING_KV)",
                [({}, r["disk_restore_s"])],
            ))
        if "lora_resident" in r:
            # multi-LoRA adapter pool: slot occupancy, LRU eviction and
            # host-load churn, and per-adapter request demand
            parts.append(render_family(
                "dynamo_lora_slots", "gauge",
                "LoRA adapter device slots (resident = adapters currently "
                "holding a slot; capacity excludes the reserved zero slot)",
                [({"state": "resident"}, r["lora_resident"]),
                 ({"state": "capacity"}, r["lora_capacity"])],
            ))
            parts.append(render_family(
                "dynamo_lora_evictions_total", "counter",
                "adapters LRU-evicted from device slots (host copy kept; a "
                "hot-swap back costs one scatter, not a reload)",
                [({}, r["lora_evictions"])],
            ))
            parts.append(render_family(
                "dynamo_lora_loads_total", "counter",
                "adapter host-weight loads (async; requests wait without "
                "blocking other traffic)",
                [({}, r["lora_loads"])],
            ))
            parts.append(render_family(
                "dynamo_lora_load_seconds_total", "counter",
                "cumulative seconds spent loading adapter host weights",
                [({}, round(r["lora_load_seconds"], 4))],
            ))
            parts.append(render_family(
                "dynamo_lora_requests_total", "counter",
                "sequences admitted per adapter (slot acquisitions)",
                [({"adapter": name}, n)
                 for name, n in sorted(r["lora_requests"].items())]
                or [({"adapter": ""}, 0)],
            ))
        if "spec_draft_pages_total" in r:
            # the draft model's OWN paged pool — separate from the target's
            # dynamo_engine_kv_pages (acceptance criterion: draft KV pages
            # visible alongside the target pool's occupancy)
            parts.append(render_family(
                "dynamo_spec_draft_pages", "gauge",
                "draft-model KV page-pool occupancy (its own pool, separate "
                "from the target cache; total excludes the trash page)",
                [({"state": "total"}, r["spec_draft_pages_total"]),
                 ({"state": "used"}, r["spec_draft_pages_used"])],
            ))
        return "".join(parts)

    def _on_kv_event(self, event: KvCacheEvent) -> None:
        if self._extra_kv_sink is not None:
            self._extra_kv_sink(event)

    # ---------------- engine thread ----------------

    def _run_loop(self) -> None:
        while not self._stopping.is_set():
            self.health.beat()
            now = time.monotonic()
            if now >= self._next_watchdog:
                # stuck-request watchdog: degrade (and auto-recover) on a
                # too-old waiting queue or a frozen progress marker while
                # work exists — the signals a wedged device op produces
                self._next_watchdog = now + _WATCHDOG_INTERVAL_S
                self.health.check(
                    oldest_waiting_age=self.scheduler.oldest_waiting_age(now),
                    has_work=self.scheduler.has_work(),
                    progress_marker=self.scheduler.progress_marker(),
                )
            did_work = self._drain_inboxes()
            if self.scheduler.has_work():
                try:
                    outputs = self.scheduler.step()
                    self.step_count += 1
                except Exception as e:  # engine-step failure: fail all running
                    log.exception("engine step failed")
                    # the black box: record the crash, then dump the journal
                    # ring to a JSONL post-mortem BEFORE failing requests, so
                    # the dump holds the events that led here
                    try:
                        events.emit(
                            "engine.crash", request_id="",
                            error=type(e).__name__, step=self.step_count,
                        )
                        path = events.JOURNAL.dump_post_mortem(
                            f"engine step failed: {type(e).__name__}: {e}"
                        )
                        if path:
                            log.error("flight-recorder post-mortem: %s", path)
                    except Exception:
                        log.exception("post-mortem dump failed")
                    self._fail_all(e)
                    continue
                self._post_grouped(outputs)
            elif not did_work:
                try:
                    req = self._inbox.get(timeout=0.02)
                    self.scheduler.add_request(req)
                except thread_queue.Empty:
                    pass

    def _drain_inboxes(self) -> bool:
        got = False
        while True:
            try:
                req = self._inbox.get_nowait()
                self.scheduler.add_request(req)
                got = True
            except thread_queue.Empty:
                break
        while True:
            try:
                fn, loop, fut = self._cmd_box.get_nowait()
                got = True
                try:
                    result = fn()
                    outputs = []
                    if isinstance(result, tuple) and len(result) == 2 and isinstance(result[1], list):
                        result, outputs = result
                    self._post_grouped(outputs)
                    loop.call_soon_threadsafe(_resolve, fut, result, None)
                except Exception as e:
                    log.exception("engine command failed")
                    loop.call_soon_threadsafe(_resolve, fut, None, e)
            except thread_queue.Empty:
                break
        while True:
            try:
                rid = self._cancel_box.get_nowait()
                self.scheduler.cancel(rid)
            except thread_queue.Empty:
                break
        return got

    def _post_grouped(self, outputs: list) -> None:
        """Post a step's outputs grouped per request: one call_soon_threadsafe
        (and one queue wakeup) per request per decode window instead of per
        token. Order within a request is preserved (dict insertion order)."""
        if not outputs:
            return
        by_rid: dict[str, list] = {}
        for out in outputs:
            by_rid.setdefault(out.request_id, []).append(out)
        for rid, group in by_rid.items():
            self._post(rid, group if len(group) > 1 else group[0])

    def _post(self, request_id: str, item) -> None:
        entry = self._outputs.get(request_id)
        if entry is None:
            return
        loop, q = entry
        try:
            loop.call_soon_threadsafe(q.put_nowait, item)
        except RuntimeError:
            # caller's loop is gone; treat as cancelled
            self._outputs.pop(request_id, None)
            self._cancel_box.put(request_id)

    def _fail_all(self, exc: Exception) -> None:
        """Fail every request the scheduler knows about. Includes the waiting
        queue: a step can die while admitting (e.g. a trace error on the very
        first prefill), before the request ever reaches a slot — those callers
        must not be left waiting forever."""
        sched = self.scheduler
        rids = {s.req.request_id for s in sched.slots if s is not None}
        rids.update(s.req.request_id for s in sched.adopted_waiting)
        rids.update(r.request_id for r in sched.waiting)
        for rid in rids:
            sched.cancel(rid)
            self._post(rid, exc)
