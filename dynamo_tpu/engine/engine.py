"""AsyncJaxEngine: asyncio facade over the engine step loop.

The step loop runs on a dedicated thread (JAX dispatch blocks); results cross
back via loop.call_soon_threadsafe into per-request asyncio queues. This is the
native analogue of the reference's engine subprocess + ZMQ output loop
(reference: lib/llm/src/engines/vllm/worker.rs _output_loop) with the process
boundary removed.
"""

from __future__ import annotations

import asyncio
import queue as thread_queue
import threading
import time
from dataclasses import dataclass, field
from typing import AsyncIterator, Callable, Optional

from dynamo_tpu.engine.config import EngineConfig
from dynamo_tpu.engine.page_table import PageAllocator
from dynamo_tpu.engine.scheduler import EngineRequest, Scheduler, StepOutput
from dynamo_tpu.llm.kv_events import KvCacheEvent
from dynamo_tpu.utils import get_logger

log = get_logger("engine")


@dataclass
class ForwardPassMetrics:
    """Worker load metrics for the KV router
    (reference: lib/llm/src/kv_router/protocols.rs:19-33)."""

    request_active_slots: int = 0
    request_total_slots: int = 0
    kv_active_blocks: int = 0
    kv_total_blocks: int = 0
    num_requests_waiting: int = 0
    gpu_cache_usage_perc: float = 0.0  # name kept for wire compat; TPU HBM here
    gpu_prefix_cache_hit_rate: float = 0.0

    def to_wire(self) -> dict:
        return self.__dict__.copy()


class AsyncJaxEngine:
    """Tokens-in/tokens-out streaming engine (the ExecutionContext contract)."""

    def __init__(self, config: EngineConfig, kv_event_sink: Optional[Callable[[KvCacheEvent], None]] = None):
        self.config = config
        self._extra_kv_sink = kv_event_sink
        self._kv_events: list[KvCacheEvent] = []
        self._inbox: thread_queue.Queue = thread_queue.Queue()
        self._cancel_box: thread_queue.Queue = thread_queue.Queue()
        self._outputs: dict[str, asyncio.Queue] = {}
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._thread: Optional[threading.Thread] = None
        self._stopping = threading.Event()
        self._started = False
        self.scheduler: Optional[Scheduler] = None
        self.allocator: Optional[PageAllocator] = None
        self.runner = None
        self.model = None
        self.step_count = 0

    # ---------------- lifecycle ----------------

    async def start(self) -> None:
        if self._started:
            return
        self._loop = asyncio.get_running_loop()
        await self._loop.run_in_executor(None, self._initialize)
        self._thread = threading.Thread(target=self._run_loop, name="engine-loop", daemon=True)
        self._thread.start()
        self._started = True

    def _initialize(self) -> None:
        from dynamo_tpu.engine.model_runner import ModelRunner
        from dynamo_tpu.models.registry import load_model

        t0 = time.monotonic()
        self.model, params = load_model(self.config.model_id)
        self.runner = ModelRunner(self.config, self.model, params)
        self.allocator = PageAllocator(
            self.config.num_pages, self.config.page_size, event_sink=self._on_kv_event
        )
        self.scheduler = Scheduler(self.config, self.runner, self.allocator)
        log.info(
            "engine ready: model=%s tp=%d pages=%d (%.1fs)",
            self.config.model_id,
            self.config.tp,
            self.config.num_pages,
            time.monotonic() - t0,
        )

    async def shutdown(self) -> None:
        self._stopping.set()
        if self._thread is not None:
            await asyncio.get_running_loop().run_in_executor(None, self._thread.join)

    # ---------------- request API ----------------

    async def generate(self, request: EngineRequest) -> AsyncIterator[StepOutput]:
        """Submit a request; yields StepOutputs until finished."""
        if not self._started:
            raise RuntimeError("engine not started")
        out_q: asyncio.Queue = asyncio.Queue()
        # Capture the caller's loop per request: generate() may be called from a
        # different event loop than start() (each call_soon_threadsafe must
        # target the loop that owns the queue).
        self._outputs[request.request_id] = (asyncio.get_running_loop(), out_q)
        self._inbox.put(request)
        try:
            while True:
                item = await out_q.get()
                if isinstance(item, Exception):
                    raise item
                yield item
                if item.finished:
                    return
        finally:
            self._outputs.pop(request.request_id, None)
            self._cancel_box.put(request.request_id)

    # ---------------- metrics / events ----------------

    def metrics(self) -> ForwardPassMetrics:
        alloc, sched = self.allocator, self.scheduler
        if alloc is None or sched is None:
            return ForwardPassMetrics()
        hit_rate = (
            alloc.cache_hit_blocks / alloc.cache_query_blocks
            if alloc.cache_query_blocks
            else 0.0
        )
        return ForwardPassMetrics(
            request_active_slots=sched.num_running,
            request_total_slots=self.config.max_seqs,
            kv_active_blocks=alloc.active_pages,
            kv_total_blocks=self.config.num_pages - 1,
            num_requests_waiting=len(sched.waiting),
            gpu_cache_usage_perc=alloc.used_pages / max(1, self.config.num_pages - 1),
            gpu_prefix_cache_hit_rate=hit_rate,
        )

    def _on_kv_event(self, event: KvCacheEvent) -> None:
        if self._extra_kv_sink is not None:
            self._extra_kv_sink(event)

    # ---------------- engine thread ----------------

    def _run_loop(self) -> None:
        while not self._stopping.is_set():
            did_work = self._drain_inboxes()
            if self.scheduler.has_work():
                try:
                    outputs = self.scheduler.step()
                    self.step_count += 1
                except Exception as e:  # engine-step failure: fail all running
                    log.exception("engine step failed")
                    self._fail_all(e)
                    continue
                for out in outputs:
                    self._post(out.request_id, out)
            elif not did_work:
                try:
                    req = self._inbox.get(timeout=0.02)
                    self.scheduler.add_request(req)
                except thread_queue.Empty:
                    pass

    def _drain_inboxes(self) -> bool:
        got = False
        while True:
            try:
                req = self._inbox.get_nowait()
                self.scheduler.add_request(req)
                got = True
            except thread_queue.Empty:
                break
        while True:
            try:
                rid = self._cancel_box.get_nowait()
                self.scheduler.cancel(rid)
            except thread_queue.Empty:
                break
        return got

    def _post(self, request_id: str, item) -> None:
        entry = self._outputs.get(request_id)
        if entry is None:
            return
        loop, q = entry
        try:
            loop.call_soon_threadsafe(q.put_nowait, item)
        except RuntimeError:
            # caller's loop is gone; treat as cancelled
            self._outputs.pop(request_id, None)
            self._cancel_box.put(request_id)

    def _fail_all(self, exc: Exception) -> None:
        for seq in [s for s in self.scheduler.slots if s is not None]:
            self.scheduler.cancel(seq.req.request_id)
            self._post(seq.req.request_id, exc)
