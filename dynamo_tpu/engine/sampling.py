"""Sampling under jit: greedy / temperature / top-k / top-p, fully batched.

Per-slot sampling parameters are arrays so one compiled function serves any mix
of requests (no recompiles on parameter changes, XLA-friendly static shapes).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Sequence

import jax
import jax.numpy as jnp

_NEG_INF = -1e30

MAX_EOS_IDS = 8  # per-slot EOS ids carried on device for min_tokens masking


def fold_seed(seed) -> int:
    """Any user seed (64-bit, negative, ...) -> nonzero int31 device seed;
    only ``None`` maps to 0 (= unseeded). One folding used by prefill AND
    decode so a request's stream is consistent across both. ``seed=0`` is a
    real seed (it folds to 1): a user asking for seed 0 gets the same
    deterministic stream every run, not the engine's shared key stream."""
    if seed is None:
        return 0
    return (int(seed) % 0x7FFFFFFE) + 1


@dataclass
class SamplingParams:
    """Per-request sampling options (reference: lib/llm/src/protocols/common.rs
    SamplingOptions/StopConditions)."""

    temperature: float = 0.0  # 0 => greedy
    top_k: int = 0  # 0 => disabled
    top_p: float = 1.0  # 1.0 => disabled
    min_p: float = 0.0  # 0 => disabled; keep tokens with p >= min_p * p_max
    max_tokens: int = 512
    min_tokens: int = 0  # EOS suppressed until this many tokens generated
    stop: Sequence[str] = ()
    seed: Optional[int] = None  # per-request deterministic sampling stream
    ignore_eos: bool = False
    # vLLM-semantics penalties (the reference's engine behavior):
    # presence/frequency over OUTPUT tokens, repetition over prompt + output
    presence_penalty: float = 0.0
    frequency_penalty: float = 0.0
    repetition_penalty: float = 1.0

    @property
    def needs_penalties(self) -> bool:
        return (
            self.presence_penalty != 0.0
            or self.frequency_penalty != 0.0
            or self.repetition_penalty != 1.0
        )


def apply_penalties(
    logits: jnp.ndarray,  # [B, V] float32
    counts: jnp.ndarray,  # [B, V] int32 output-token counts
    seen: jnp.ndarray,  # [B, V] bool, token in prompt or output
    presence: jnp.ndarray,  # [B]
    frequency: jnp.ndarray,  # [B]
    repetition: jnp.ndarray,  # [B] (1.0 = off)
) -> jnp.ndarray:
    """vLLM-semantics sampling penalties (what the reference's engines do),
    in vLLM's order: repetition divides positive / multiplies negative RAW
    logits of any seen token FIRST, then presence/frequency subtract over
    output-token occurrences."""
    rep = repetition[:, None]
    penalized = jnp.where(logits > 0, logits / rep, logits * rep)
    logits = jnp.where(seen, penalized, logits)
    cf = counts.astype(jnp.float32)
    logits = logits - frequency[:, None] * cf
    logits = logits - presence[:, None] * (cf > 0)
    return logits


def filter_keep_mask(
    logits: jnp.ndarray,  # [B, V] float32
    temperature: jnp.ndarray,  # [B]
    top_k: jnp.ndarray,  # [B] int32 (0 = off)
    top_p: jnp.ndarray,  # [B] (1.0 = off)
    min_p: jnp.ndarray | None = None,  # [B] (0 = off)
) -> jnp.ndarray:
    """[B, V] bool mask of tokens surviving top-k/top-p/min-p, shared by
    sample_tokens and speculative acceptance so both paths draw from the
    identical filtered distribution."""
    B, V = logits.shape
    temp = jnp.where(temperature > 0, temperature, 1.0)[:, None]
    # Sort once (descending); top-k and top-p become rank/cdf thresholds.
    sorted_logits = -jnp.sort(-logits, axis=-1)  # [B, V] descending

    # top-k: keep entries with logit >= k-th largest value
    k = jnp.where(top_k > 0, jnp.clip(top_k, 1, V), V)
    kth_value = jnp.take_along_axis(sorted_logits, (k - 1)[:, None], axis=-1)
    keep_k = logits >= kth_value

    # top-p: over the sorted distribution (temperature-scaled), keep the
    # prefix whose cumulative probability is < p (always keeping the first)
    sorted_probs = jax.nn.softmax(sorted_logits / temp, axis=-1)
    cum = jnp.cumsum(sorted_probs, axis=-1)
    sorted_keep = (cum - sorted_probs) < top_p[:, None]  # prefix incl. first
    num_keep = jnp.maximum(jnp.sum(sorted_keep, axis=-1), 1)
    p_value = jnp.take_along_axis(sorted_logits, (num_keep - 1)[:, None], axis=-1)
    keep_p = logits >= p_value

    keep = keep_k & keep_p
    if min_p is not None:
        # keep tokens whose (tempered) prob >= min_p * max prob: in logit
        # space, logit/temp >= max/temp + log(min_p)
        max_l = jnp.max(logits, axis=-1, keepdims=True)
        thresh = max_l / temp + jnp.log(jnp.maximum(min_p, 1e-10))[:, None]
        keep_m = (logits / temp) >= thresh
        keep = keep & jnp.where(min_p[:, None] > 0, keep_m, True)
    return keep


def sample_tokens(
    logits: jnp.ndarray,  # [B, V] float32
    key: jax.Array,
    temperature: jnp.ndarray,  # [B]
    top_k: jnp.ndarray,  # [B] int32 (0 = off)
    top_p: jnp.ndarray,  # [B] (1.0 = off)
    min_p: jnp.ndarray | None = None,  # [B] (0 = off)
    seeds: jnp.ndarray | None = None,  # [B] int32, 0 = unseeded
    positions: jnp.ndarray | None = None,  # [B] sampling-step index per slot
) -> jnp.ndarray:
    """Sample one token per slot. Greedy where temperature <= 0.

    Seeded slots (seeds != 0) draw from a per-request stream keyed by
    (seed, position) — deterministic across retries, preemption, and batch
    composition. Unseeded slots share the engine's key stream."""
    B, V = logits.shape
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    temp = jnp.where(temperature > 0, temperature, 1.0)[:, None]

    def draw(masked):
        if seeds is None:
            return jax.random.categorical(key, masked / temp).astype(jnp.int32)
        # per-slot keys: seeded slots fold (seed, position) off a fixed base
        # so their stream ignores batch placement; unseeded fold the slot
        # index off the engine's window key
        base = jax.random.key(0x5EED)
        pos = positions if positions is not None else jnp.zeros(B, jnp.int32)

        def slot_key(i, seed, p):
            seeded = jax.random.fold_in(jax.random.fold_in(base, seed), p)
            unseeded = jax.random.fold_in(key, i)
            return jax.lax.cond(seed != 0, lambda: seeded, lambda: unseeded)

        keys = jax.vmap(slot_key)(jnp.arange(B, dtype=jnp.int32), seeds, pos)
        return jax.vmap(
            lambda k_, row: jax.random.categorical(k_, row)
        )(keys, masked / temp).astype(jnp.int32)

    def filtered():
        keep = filter_keep_mask(logits, temperature, top_k, top_p, min_p=min_p)
        return draw(jnp.where(keep, logits, _NEG_INF))

    # Runtime-gated fast paths (lax.cond executes one branch on TPU): the
    # full-vocab sort/cumsum machinery only runs when some slot has an active
    # filter (with none, the keep-mask is all-true, so `draw(logits)` is
    # bit-identical), and RNG runs only when some slot actually samples.
    need_filter = jnp.any((top_k > 0) | (top_p < 1.0))
    if min_p is not None:
        need_filter |= jnp.any(min_p > 0)
    any_sampling = jnp.any(temperature > 0)

    sampled = jax.lax.cond(
        any_sampling,
        lambda: jax.lax.cond(need_filter, filtered, lambda: draw(logits)),
        lambda: greedy,
    )
    return jnp.where(temperature > 0, sampled, greedy)


LOGPROBS_K = 20  # top alternatives computed on device (= the OpenAI API max)


def sample_tokens_with_logprobs(
    logits: jnp.ndarray,  # [B, V] float32, possibly penalized/masked
    key: jax.Array,
    temperature: jnp.ndarray,
    top_k: jnp.ndarray,
    top_p: jnp.ndarray,
    raw_logits: jnp.ndarray | None = None,  # pre-penalty/mask model logits
    **kwargs,  # min_p / seeds / positions, forwarded to sample_tokens
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """sample_tokens + OpenAI-style logprobs of the model distribution.

    Returns (tokens [B], chosen_logprob [B], topk_ids [B, K], topk_logprobs
    [B, K]). Logprobs are log-softmax of the RAW model logits (pass
    ``raw_logits`` when sampling from penalized/EOS-masked ones) — the
    model's distribution, matching the OpenAI API semantic; sampling itself
    applies temperature/top-k/top-p (and any forwarded filters).
    """
    tokens = sample_tokens(logits, key, temperature, top_k, top_p, **kwargs)
    logprobs = jax.nn.log_softmax(
        logits if raw_logits is None else raw_logits, axis=-1
    )
    chosen = jnp.take_along_axis(logprobs, tokens[:, None].astype(jnp.int32), -1)[:, 0]
    top_vals, top_ids = jax.lax.top_k(logprobs, LOGPROBS_K)
    return tokens, chosen, top_ids.astype(jnp.int32), top_vals


def accept_speculative(
    logits: jnp.ndarray,  # [B, K+1, V] float32; row i predicts position p+i+1
    drafts: jnp.ndarray,  # [B, K] int32 proposed tokens (pad rows arbitrary)
    n_drafts: jnp.ndarray,  # [B] int32 real drafts per slot (<= K)
    key: jax.Array,
    temperature: jnp.ndarray,  # [B]
    top_k: jnp.ndarray,  # [B] int32 (0 = off)
    top_p: jnp.ndarray,  # [B] (1.0 = off)
    min_p: jnp.ndarray | None = None,  # [B] (0 = off)
    seeds: jnp.ndarray | None = None,  # [B] int32, 0 = unseeded
    positions: jnp.ndarray | None = None,  # [B] anchor fed position per slot
    draft_probs: jnp.ndarray | None = None,  # [B, K, V] real draft dists q
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Speculative acceptance over one verify pass: (tokens [B, K+1], n_emit [B]).

    A slot's verify pass fed [t_p, d_1..d_K] at positions p..p+K, so
    ``logits[:, i]`` is the target distribution for the token at position
    p+i+1 conditioned on a correct prefix through d_i. Per slot the caller
    emits ``tokens[:n_emit]``; drafts beyond the first rejection are dead
    (their KV is overwritten by the next pass at the new anchor).

    Greedy slots (temperature <= 0): a draft is accepted iff it equals the
    raw-logits argmax, so the emitted chain is token-identical to the
    non-speculative engine; ``tokens`` are the argmax rows themselves
    (accepted drafts == their argmax; the row after the last acceptance is
    the correction/bonus token).

    Sampling slots: distribution-exact rejection sampling (Leviathan et al. /
    Chen et al.). Without ``draft_probs`` the proposal is treated as
    degenerate (one-hot q, the n-gram case): accept d_i with probability
    min(1, p(d_i)); on rejection resample from p with d_i removed. With
    ``draft_probs`` (a draft model's real distributions, q[:, i] being the
    filtered distribution d_{i+1} was sampled from) the full rule runs:
    accept d_i with probability min(1, p(d_i)/q(d_i)), and on rejection
    resample from the residual max(0, p - q) renormalized; when every draft
    is accepted, the bonus token samples from the last row unmodified. p is
    the FULL filtered distribution (temperature/top-k/top-p/min-p) via
    filter_keep_mask, so the emitted marginal matches sample_tokens exactly.
    Seeded slots draw from a (seed, position, row) stream — deterministic
    across retries and batch composition, but a distinct stream from the
    non-speculative sampler's (only the distribution is guaranteed equal).
    """
    B, K1, V = logits.shape
    K = K1 - 1
    flat = logits.reshape(B * K1, V)
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)  # [B, K1] raw argmax
    draft_valid = jnp.arange(K, dtype=jnp.int32)[None, :] < n_drafts[:, None]

    # greedy acceptance: count of leading argmax matches among real drafts
    g_match = (greedy[:, :K] == drafts) & draft_valid
    g_acc = jnp.cumprod(g_match.astype(jnp.int32), axis=1).sum(axis=1)  # [B]

    # target distribution: identical filtering to sample_tokens, per row
    def per_row(a):  # [B] -> [B*K1] slot params broadcast over rows
        return jnp.repeat(a, K1)

    temps_r = per_row(temperature)
    keep = filter_keep_mask(
        flat, temps_r, per_row(top_k), per_row(top_p),
        min_p=None if min_p is None else per_row(min_p),
    )
    temp_r = jnp.where(temps_r > 0, temps_r, 1.0)[:, None]
    probs = jax.nn.softmax(
        jnp.where(keep, flat, _NEG_INF) / temp_r, axis=-1
    ).reshape(B, K1, V)
    p_draft = jnp.take_along_axis(
        probs[:, :K], drafts[..., None].astype(jnp.int32), axis=-1
    )[..., 0]  # [B, K]

    # per-(slot, row) keys: seeded slots fold (seed, anchor position, row) off
    # a fixed base so their stream ignores batch placement; unseeded fold the
    # slot index off this round's engine key (same scheme as sample_tokens)
    base = jax.random.key(0x5EC5)
    pos = positions if positions is not None else jnp.zeros(B, jnp.int32)
    sd = seeds if seeds is not None else jnp.zeros(B, jnp.int32)

    def slot_key(i, seed, p):
        seeded = jax.random.fold_in(jax.random.fold_in(base, seed), p)
        unseeded = jax.random.fold_in(key, i)
        return jax.lax.cond(seed != 0, lambda: seeded, lambda: unseeded)

    slot_keys = jax.vmap(slot_key)(jnp.arange(B, dtype=jnp.int32), sd, pos)
    rows = jnp.arange(K1, dtype=jnp.int32)
    row_keys = jax.vmap(
        lambda k_: jax.vmap(lambda t: jax.random.fold_in(k_, t))(rows)
    )(slot_keys)  # [B, K1] keys

    # rejection test per draft row (computed in parallel; the cumprod makes
    # acceptance stop at the first rejection, matching the sequential rule)
    u = jax.vmap(jax.vmap(lambda k_: jax.random.uniform(jax.random.fold_in(k_, 0))))(
        row_keys[:, :K]
    )
    if draft_probs is None:
        s_match = (u < p_draft) & draft_valid
    else:
        # real proposal: accept with probability min(1, p(d)/q(d)); q > 0
        # wherever the draft actually sampled, the floor only guards pads
        q_draft = jnp.take_along_axis(
            draft_probs, drafts[..., None].astype(jnp.int32), axis=-1
        )[..., 0]  # [B, K]
        s_match = (u * jnp.maximum(q_draft, 1e-20) < p_draft) & draft_valid
    s_acc = jnp.cumprod(s_match.astype(jnp.int32), axis=1).sum(axis=1)  # [B]

    a = jnp.where(temperature > 0, s_acc, g_acc)  # [B] accepted drafts

    b_idx = jnp.arange(B)
    rejected = a < n_drafts
    final_keys = jax.vmap(lambda k_: jax.random.fold_in(k_, 1))(row_keys[b_idx, a])
    if draft_probs is None:
        # final token: row a's filtered logits; on a rejection the rejected
        # draft is removed — the residual max(0, p - q) for a one-hot q
        row_logits = jnp.where(keep, flat, _NEG_INF).reshape(B, K1, V)[b_idx, a]
        d_rej = jnp.take_along_axis(
            drafts, jnp.clip(a, 0, max(K - 1, 0))[:, None], axis=1
        )[:, 0]
        row_logits = row_logits.at[b_idx, d_rej].add(
            jnp.where(rejected, _NEG_INF, 0.0)
        )
        final = jax.vmap(
            lambda k_, row, t: jax.random.categorical(k_, row / jnp.where(t > 0, t, 1.0))
        )(final_keys, row_logits, temperature).astype(jnp.int32)
    else:
        # final token in probability space (temperature already applied by
        # the softmax above): rejection -> the renormalized residual
        # max(0, p - q) at row a; all-accepted -> the bonus row's p itself.
        # categorical(log p) == categorical(logits/temp) bit for bit (the
        # gumbel draw is shift-invariant), so the q -> one-hot limit matches
        # the branch above exactly.
        p_rows = probs[b_idx, a]  # [B, V]
        q_rows = draft_probs[b_idx, jnp.clip(a, 0, max(K - 1, 0))]
        res = jnp.maximum(p_rows - q_rows, 0.0)
        # a residual can only be empty through float cancellation (p == q
        # rejects with probability 0); fall back to p rather than NaN
        has_res = jnp.sum(res, axis=-1, keepdims=True) > 0
        use_res = rejected[:, None] & has_res
        dist = jnp.where(use_res, res, p_rows)
        final = jax.vmap(
            lambda k_, row: jax.random.categorical(
                k_, jnp.where(row > 0, jnp.log(jnp.maximum(row, 1e-38)), _NEG_INF)
            )
        )(final_keys, dist).astype(jnp.int32)

    drafts_pad = jnp.concatenate(
        [drafts.astype(jnp.int32), jnp.zeros((B, 1), jnp.int32)], axis=1
    )
    out_sampled = jnp.where(
        jnp.arange(K1, dtype=jnp.int32)[None, :] < a[:, None], drafts_pad,
        final[:, None],
    )
    out = jnp.where(temperature[:, None] > 0, out_sampled, greedy)
    return out, a + 1
