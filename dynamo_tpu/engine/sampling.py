"""Sampling under jit: greedy / temperature / top-k / top-p, fully batched.

Per-slot sampling parameters are arrays so one compiled function serves any mix
of requests (no recompiles on parameter changes, XLA-friendly static shapes).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional, Sequence

import jax
import jax.numpy as jnp

_NEG_INF = -1e30

MAX_EOS_IDS = 8  # per-slot EOS ids carried on device for min_tokens masking


def fold_seed(seed) -> int:
    """Any user seed (64-bit, negative, ...) -> nonzero int31 device seed;
    0 stays 0 (= unseeded). One folding used by prefill AND decode so a
    request's stream is consistent across both."""
    if not seed:
        return 0
    return (int(seed) % 0x7FFFFFFE) + 1


@dataclass
class SamplingParams:
    """Per-request sampling options (reference: lib/llm/src/protocols/common.rs
    SamplingOptions/StopConditions)."""

    temperature: float = 0.0  # 0 => greedy
    top_k: int = 0  # 0 => disabled
    top_p: float = 1.0  # 1.0 => disabled
    min_p: float = 0.0  # 0 => disabled; keep tokens with p >= min_p * p_max
    max_tokens: int = 512
    min_tokens: int = 0  # EOS suppressed until this many tokens generated
    stop: Sequence[str] = ()
    seed: Optional[int] = None  # per-request deterministic sampling stream
    ignore_eos: bool = False
    # vLLM-semantics penalties (the reference's engine behavior):
    # presence/frequency over OUTPUT tokens, repetition over prompt + output
    presence_penalty: float = 0.0
    frequency_penalty: float = 0.0
    repetition_penalty: float = 1.0

    @property
    def needs_penalties(self) -> bool:
        return (
            self.presence_penalty != 0.0
            or self.frequency_penalty != 0.0
            or self.repetition_penalty != 1.0
        )


def apply_penalties(
    logits: jnp.ndarray,  # [B, V] float32
    counts: jnp.ndarray,  # [B, V] int32 output-token counts
    seen: jnp.ndarray,  # [B, V] bool, token in prompt or output
    presence: jnp.ndarray,  # [B]
    frequency: jnp.ndarray,  # [B]
    repetition: jnp.ndarray,  # [B] (1.0 = off)
) -> jnp.ndarray:
    """vLLM-semantics sampling penalties (what the reference's engines do),
    in vLLM's order: repetition divides positive / multiplies negative RAW
    logits of any seen token FIRST, then presence/frequency subtract over
    output-token occurrences."""
    rep = repetition[:, None]
    penalized = jnp.where(logits > 0, logits / rep, logits * rep)
    logits = jnp.where(seen, penalized, logits)
    cf = counts.astype(jnp.float32)
    logits = logits - frequency[:, None] * cf
    logits = logits - presence[:, None] * (cf > 0)
    return logits


def sample_tokens(
    logits: jnp.ndarray,  # [B, V] float32
    key: jax.Array,
    temperature: jnp.ndarray,  # [B]
    top_k: jnp.ndarray,  # [B] int32 (0 = off)
    top_p: jnp.ndarray,  # [B] (1.0 = off)
    min_p: jnp.ndarray | None = None,  # [B] (0 = off)
    seeds: jnp.ndarray | None = None,  # [B] int32, 0 = unseeded
    positions: jnp.ndarray | None = None,  # [B] sampling-step index per slot
) -> jnp.ndarray:
    """Sample one token per slot. Greedy where temperature <= 0.

    Seeded slots (seeds != 0) draw from a per-request stream keyed by
    (seed, position) — deterministic across retries, preemption, and batch
    composition. Unseeded slots share the engine's key stream."""
    B, V = logits.shape
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    temp = jnp.where(temperature > 0, temperature, 1.0)[:, None]

    def draw(masked):
        if seeds is None:
            return jax.random.categorical(key, masked / temp).astype(jnp.int32)
        # per-slot keys: seeded slots fold (seed, position) off a fixed base
        # so their stream ignores batch placement; unseeded fold the slot
        # index off the engine's window key
        base = jax.random.key(0x5EED)
        pos = positions if positions is not None else jnp.zeros(B, jnp.int32)

        def slot_key(i, seed, p):
            seeded = jax.random.fold_in(jax.random.fold_in(base, seed), p)
            unseeded = jax.random.fold_in(key, i)
            return jax.lax.cond(seed != 0, lambda: seeded, lambda: unseeded)

        keys = jax.vmap(slot_key)(jnp.arange(B, dtype=jnp.int32), seeds, pos)
        return jax.vmap(
            lambda k_, row: jax.random.categorical(k_, row)
        )(keys, masked / temp).astype(jnp.int32)

    def filtered():
        # Sort once (descending); top-k and top-p become rank/cdf thresholds.
        sorted_logits = -jnp.sort(-logits, axis=-1)  # [B, V] descending

        # top-k: keep entries with logit >= k-th largest value
        k = jnp.where(top_k > 0, jnp.clip(top_k, 1, V), V)
        kth_value = jnp.take_along_axis(sorted_logits, (k - 1)[:, None], axis=-1)
        keep_k = logits >= kth_value

        # top-p: over the sorted distribution (temperature-scaled), keep the
        # prefix whose cumulative probability is < p (always keeping the first)
        sorted_probs = jax.nn.softmax(sorted_logits / temp, axis=-1)
        cum = jnp.cumsum(sorted_probs, axis=-1)
        sorted_keep = (cum - sorted_probs) < top_p[:, None]  # prefix incl. first
        num_keep = jnp.maximum(jnp.sum(sorted_keep, axis=-1), 1)
        p_value = jnp.take_along_axis(sorted_logits, (num_keep - 1)[:, None], axis=-1)
        keep_p = logits >= p_value

        keep = keep_k & keep_p
        if min_p is not None:
            # keep tokens whose (tempered) prob >= min_p * max prob: in logit
            # space, logit/temp >= max/temp + log(min_p)
            max_l = jnp.max(logits, axis=-1, keepdims=True)
            thresh = max_l / temp + jnp.log(jnp.maximum(min_p, 1e-10))[:, None]
            keep_m = (logits / temp) >= thresh
            keep = keep & jnp.where(min_p[:, None] > 0, keep_m, True)
        return draw(jnp.where(keep, logits, _NEG_INF))

    # Runtime-gated fast paths (lax.cond executes one branch on TPU): the
    # full-vocab sort/cumsum machinery only runs when some slot has an active
    # filter (with none, the keep-mask is all-true, so `draw(logits)` is
    # bit-identical), and RNG runs only when some slot actually samples.
    need_filter = jnp.any((top_k > 0) | (top_p < 1.0))
    if min_p is not None:
        need_filter |= jnp.any(min_p > 0)
    any_sampling = jnp.any(temperature > 0)

    sampled = jax.lax.cond(
        any_sampling,
        lambda: jax.lax.cond(need_filter, filtered, lambda: draw(logits)),
        lambda: greedy,
    )
    return jnp.where(temperature > 0, sampled, greedy)


LOGPROBS_K = 20  # top alternatives computed on device (= the OpenAI API max)


def sample_tokens_with_logprobs(
    logits: jnp.ndarray,  # [B, V] float32, possibly penalized/masked
    key: jax.Array,
    temperature: jnp.ndarray,
    top_k: jnp.ndarray,
    top_p: jnp.ndarray,
    raw_logits: jnp.ndarray | None = None,  # pre-penalty/mask model logits
    **kwargs,  # min_p / seeds / positions, forwarded to sample_tokens
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """sample_tokens + OpenAI-style logprobs of the model distribution.

    Returns (tokens [B], chosen_logprob [B], topk_ids [B, K], topk_logprobs
    [B, K]). Logprobs are log-softmax of the RAW model logits (pass
    ``raw_logits`` when sampling from penalized/EOS-masked ones) — the
    model's distribution, matching the OpenAI API semantic; sampling itself
    applies temperature/top-k/top-p (and any forwarded filters).
    """
    tokens = sample_tokens(logits, key, temperature, top_k, top_p, **kwargs)
    logprobs = jax.nn.log_softmax(
        logits if raw_logits is None else raw_logits, axis=-1
    )
    chosen = jnp.take_along_axis(logprobs, tokens[:, None].astype(jnp.int32), -1)[:, 0]
    top_vals, top_ids = jax.lax.top_k(logprobs, LOGPROBS_K)
    return tokens, chosen, top_ids.astype(jnp.int32), top_vals
