"""Sampling under jit: greedy / temperature / top-k / top-p, fully batched.

Per-slot sampling parameters are arrays so one compiled function serves any mix
of requests (no recompiles on parameter changes, XLA-friendly static shapes).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional, Sequence

import jax
import jax.numpy as jnp

_NEG_INF = -1e30


@dataclass
class SamplingParams:
    """Per-request sampling options (reference: lib/llm/src/protocols/common.rs
    SamplingOptions/StopConditions)."""

    temperature: float = 0.0  # 0 => greedy
    top_k: int = 0  # 0 => disabled
    top_p: float = 1.0  # 1.0 => disabled
    max_tokens: int = 512
    stop: Sequence[str] = ()
    seed: Optional[int] = None
    ignore_eos: bool = False


def sample_tokens(
    logits: jnp.ndarray,  # [B, V] float32
    key: jax.Array,
    temperature: jnp.ndarray,  # [B]
    top_k: jnp.ndarray,  # [B] int32 (0 = off)
    top_p: jnp.ndarray,  # [B] (1.0 = off)
) -> jnp.ndarray:
    """Sample one token per slot. Greedy where temperature <= 0."""
    B, V = logits.shape
    greedy = jnp.argmax(logits, axis=-1)

    # Sort once (descending); both top-k and top-p become rank/cdf thresholds.
    sorted_logits = -jnp.sort(-logits, axis=-1)  # [B, V] descending
    ranks = jnp.arange(V, dtype=jnp.int32)

    # top-k: keep entries with logit >= k-th largest value
    k = jnp.where(top_k > 0, jnp.clip(top_k, 1, V), V)
    kth_value = jnp.take_along_axis(sorted_logits, (k - 1)[:, None], axis=-1)  # [B,1]
    keep_k = logits >= kth_value

    # top-p: over the sorted distribution (temperature-scaled), keep the prefix
    # whose cumulative probability is < p (always keeping the first)
    temp = jnp.where(temperature > 0, temperature, 1.0)[:, None]
    sorted_probs = jax.nn.softmax(sorted_logits / temp, axis=-1)
    cum = jnp.cumsum(sorted_probs, axis=-1)
    sorted_keep = (cum - sorted_probs) < top_p[:, None]  # prefix incl. first
    # threshold value = smallest kept logit in sorted order
    num_keep = jnp.maximum(jnp.sum(sorted_keep, axis=-1), 1)
    p_value = jnp.take_along_axis(sorted_logits, (num_keep - 1)[:, None], axis=-1)
    keep_p = logits >= p_value

    masked = jnp.where(keep_k & keep_p, logits, _NEG_INF)
    sampled = jax.random.categorical(key, masked / temp)
    return jnp.where(temperature > 0, sampled, greedy).astype(jnp.int32)


LOGPROBS_K = 20  # top alternatives computed on device (= the OpenAI API max)


def sample_tokens_with_logprobs(
    logits: jnp.ndarray,  # [B, V] float32
    key: jax.Array,
    temperature: jnp.ndarray,
    top_k: jnp.ndarray,
    top_p: jnp.ndarray,
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """sample_tokens + OpenAI-style logprobs of the model distribution.

    Returns (tokens [B], chosen_logprob [B], topk_ids [B, K], topk_logprobs
    [B, K]). Logprobs are log-softmax of the raw (untempered) logits — the
    model's distribution, matching the OpenAI API semantic; sampling itself
    still applies temperature/top-k/top-p.
    """
    tokens = sample_tokens(logits, key, temperature, top_k, top_p)
    logprobs = jax.nn.log_softmax(logits, axis=-1)
    chosen = jnp.take_along_axis(logprobs, tokens[:, None].astype(jnp.int32), -1)[:, 0]
    top_vals, top_ids = jax.lax.top_k(logprobs, LOGPROBS_K)
    return tokens, chosen, top_ids.astype(jnp.int32), top_vals
