"""ModelRunner: owns the device mesh, sharded params, the donated paged KV
cache, the device-resident token-feedback buffer, and the jitted
prefill/decode+sample step functions.

TPU execution notes:
  - prefill chunks are padded to config.prefill_buckets so jit caches one
    executable per bucket (static shapes, no recompiles per request)
  - the KV cache is donated on every step — XLA aliases it in place
  - sampling is fused into the step so only the sampled token ids (a few bytes)
    cross back to host per step
  - the last sampled token per slot lives in a donated device state bundle
    (``slot_state``, with the penalty counters): a sampling prefill writes
    its slot's first token there,
    and decode windows read/update it on device. The host therefore never has
    to sync on a window's results before dispatching the next one — the
    scheduler runs windows dispatch-ahead and reconciles token results as they
    arrive (hides dispatch/transfer latency entirely; on tunneled PJRT
    platforms that latency is ~100 ms per round trip)
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dynamo_tpu.engine.config import EngineConfig
from dynamo_tpu.engine.sampling import MAX_EOS_IDS, SamplingParams, accept_speculative, apply_penalties, fold_seed, sample_tokens, sample_tokens_with_logprobs
from dynamo_tpu.utils import get_logger

log = get_logger("engine.runner")


class ModelRunner:
    def __init__(
        self,
        config: EngineConfig,
        model,
        params,
        mesh: Optional[Mesh] = None,
    ):
        self.config = config
        self.model = model
        if config.sp > 1 and config.pp > 1:
            # composed pp x sp (long-context: depth over pp, length over sp)
            # runs ring prefill inside the pipeline shard_map; the layer must
            # support the sp row all-gather before its pool scatter
            import inspect

            if "sp_axis" not in inspect.signature(model._layer).parameters:
                raise ValueError(
                    f"model {type(model).__name__} does not support the "
                    "composed pp x sp mesh (no _layer sp_axis)"
                )
        if config.sp > 1 and config.tp > 1:
            h = getattr(model.config, "num_heads", None)
            hkv = getattr(model.config, "num_kv_heads", None)
            if h is None or hkv is None:
                # a model without per-head attention geometry (e.g. a latent-
                # attention variant) must fail HERE, not inside a traced
                # shard_map later — 0 % tp == 0 would slip through the gate
                raise ValueError(
                    f"model {type(model).__name__} config lacks num_heads/"
                    "num_kv_heads; composed sp x tp needs per-head geometry"
                )
            if h % config.tp or hkv % config.tp:
                raise ValueError(
                    f"tp={config.tp} must divide num_heads={h} and "
                    f"num_kv_heads={hkv} for the composed sp x tp mesh"
                )
        if getattr(model.config, "kv_quantized", False):
            if not getattr(model, "SUPPORTS_KV_INT8", False):
                raise ValueError(
                    f"model {type(model).__name__} does not support the int8 KV cache"
                )
            if config.pp > 1:
                # the stage-sharded pool split has no QuantizedPages wiring
                # yet (EngineConfig also gates this; a tiny:{...} override
                # JSON could otherwise sneak the combination past it)
                raise ValueError("int8 KV cache does not compose with pp > 1 yet")
        if config.pp > 1:
            if model.config.num_layers % config.pp:
                raise ValueError(
                    f"num_layers={model.config.num_layers} not divisible by pp={config.pp}"
                )
            if len(jax.devices()) < config.pp * config.tp:
                raise ValueError(
                    f"pp={config.pp} x tp={config.tp} but only "
                    f"{len(jax.devices())} devices available"
                )
            if any(b % config.pp for b in config.prefill_buckets):
                raise ValueError(
                    f"every prefill bucket must divide into pp={config.pp} microbatches"
                )
            if config.max_seqs % config.pp:
                raise ValueError(f"max_seqs must be divisible by pp={config.pp}")
            if config.tp > 1:
                h = getattr(model.config, "num_heads", None)
                hkv = getattr(model.config, "num_kv_heads", None)
                if h is None or hkv is None:
                    raise ValueError(
                        f"model {type(model).__name__} config lacks num_heads/"
                        "num_kv_heads; composed pp x tp needs per-head geometry"
                    )
                if h % config.tp or hkv % config.tp:
                    raise ValueError(
                        f"tp={config.tp} must divide num_heads={h} and "
                        f"num_kv_heads={hkv} for the composed pp x tp mesh"
                    )
                import inspect

                if "tp_axis" not in inspect.signature(model._layer).parameters:
                    # fail at init, not at first traced prefill: the layer
                    # must run on a head shard with in-layer psums
                    raise ValueError(
                        f"model {type(model).__name__} does not support tp "
                        "inside the pipeline shard_map (no _layer tp_axis)"
                    )
        if config.sp > 1:
            if not hasattr(model, "prefill_sp"):
                raise ValueError(
                    f"model {type(model).__name__} has no sequence-parallel prefill"
                )
            if len(jax.devices()) < config.pp * config.sp * config.tp:
                raise ValueError(
                    f"pp={config.pp} x sp={config.sp} x tp={config.tp} but only "
                    f"{len(jax.devices())} devices available"
                )
            if not any(b % config.sp == 0 for b in config.prefill_buckets):
                raise ValueError(
                    f"sp={config.sp} divides none of prefill_buckets="
                    f"{config.prefill_buckets}; SP prefill would never engage"
                )
        if mesh is None:
            if config.pp > 1 and config.sp > 1:
                # composed stage x sequence (x head) mesh: sp between pp and
                # tp so a ring's peers stay ICI-adjacent within their stage
                n = config.pp * config.sp * config.tp
                devices = jax.devices()[:n]
                if config.tp > 1:
                    mesh = Mesh(
                        np.array(devices).reshape(config.pp, config.sp, config.tp),
                        ("pp", "sp", "tp"),
                    )
                else:
                    mesh = Mesh(
                        np.array(devices).reshape(config.pp, config.sp), ("pp", "sp")
                    )
            elif config.pp > 1 and config.tp > 1:
                # composed stage x head mesh: tp is the minor (fastest-
                # varying) axis so a head shard's peers are ICI neighbors
                devices = jax.devices()[: config.pp * config.tp]
                mesh = Mesh(
                    np.array(devices).reshape(config.pp, config.tp), ("pp", "tp")
                )
            elif config.sp > 1 and config.tp > 1:
                # composed sequence x head mesh: each tp head shard runs its
                # own independent sp ring (attention is head-local)
                devices = jax.devices()[: config.sp * config.tp]
                mesh = Mesh(
                    np.array(devices).reshape(config.sp, config.tp), ("sp", "tp")
                )
            elif config.pp > 1:
                devices = jax.devices()[: config.pp]
                mesh = Mesh(np.array(devices).reshape(len(devices)), ("pp",))
            elif config.sp > 1:
                devices = jax.devices()[: config.sp]
                mesh = Mesh(np.array(devices).reshape(len(devices)), ("sp",))
            else:
                devices = jax.devices()[: config.tp]
                mesh = Mesh(np.array(devices).reshape(len(devices)), ("tp",))
        self.mesh = mesh
        if config.tp > 1 and config.pp == 1:
            # the Pallas decode kernel runs under shard_map on this mesh
            # (attention is head-parallel; no collectives inside). With pp > 1
            # attention runs INSIDE the pipeline's own (pp, tp) shard_map on
            # local pool shards, so the dispatcher must not re-wrap it.
            model.attn_mesh = mesh
        if config.pp > 1:
            # stage sharding: layer stack + layer-major KV pool split over pp
            from dynamo_tpu.parallel.pipeline import (
                stage_kv_sharding,
                stage_param_shardings,
            )

            shardings = stage_param_shardings(model, mesh)
            kv_sharding = stage_kv_sharding(
                mesh, folded=getattr(model.config, "kv_folded", False)
            )
            probe = jax.eval_shape(
                lambda: model.init_kv_cache(config.num_pages, config.page_size)
            )
            if set(probe) != {"k", "v"}:
                raise ValueError(
                    "pp currently supports the k/v page-pool model families"
                )
        else:
            shardings = model.param_shardings(mesh)
            kv_sharding = model.kv_cache_sharding(mesh)
        self.params = jax.device_put(params, shardings)
        self.kv_cache = jax.device_put(
            model.init_kv_cache(config.num_pages, config.page_size), kv_sharding
        )
        self._replicated = NamedSharding(mesh, P())
        self._key = jax.random.key(0)
        # device-resident per-slot state, donated through every step:
        #   tokens — last sampled token (the decode feedback loop)
        #   counts — output-token occurrence counts (frequency/presence)
        #   seen   — token appeared in prompt or output (repetition)
        # counts/seen ([max_seqs, V] — up to tens of MB for large vocabs) are
        # allocated lazily on the first penalty-enabled request; until then the
        # bundle is just the token feedback buffer and penalty-free traffic
        # never pays the HBM or donation traffic.
        self.slot_state = {"tokens": jnp.zeros(config.max_seqs, jnp.int32)}
        # multi-LoRA multiplexing (dynamo_tpu/lora/): device-resident stacked
        # adapter pools + the LRU slot store. The pool rides every forward as
        # a read-only (never donated) pytree; per-slot adapter ids live in
        # slot_state["lora"] next to the token-feedback buffer so decode
        # windows read them on device with no extra H2D. None = disabled and
        # every trace is byte-identical to the pre-LoRA engine.
        self.lora = None
        self.lora_store = None
        if config.lora_adapters:
            from dynamo_tpu.lora import LoraStore, init_lora_pool

            if not getattr(model, "SUPPORTS_LORA", False):
                raise ValueError(
                    f"model {type(model).__name__} does not support LoRA adapters"
                )
            if config.pp > 1:
                # config gates this too; a tiny:{...} override JSON must not
                # sneak the combination past it
                raise ValueError("lora_adapters do not compose with pp > 1 yet")
            pool = init_lora_pool(model, config.max_loras, config.lora_rank)
            self.lora = jax.device_put(pool, NamedSharding(mesh, P()))
            self.slot_state["lora"] = jnp.zeros(config.max_seqs, jnp.int32)

            def _lora_write_impl(pool, slot, tree, scale):
                mods = {
                    m: {
                        "a": pool["mods"][m]["a"].at[:, slot].set(tree[m]["a"]),
                        "b": pool["mods"][m]["b"].at[:, slot].set(tree[m]["b"]),
                    }
                    for m in pool["mods"]
                }
                return {"scales": pool["scales"].at[slot].set(scale), "mods": mods}

            self._lora_write = jax.jit(_lora_write_impl, donate_argnums=(0,))

            def _set_lora_impl(st, slot, val):
                return dict(st, lora=st["lora"].at[slot].set(val, mode="drop"))

            self._set_lora = jax.jit(_set_lora_impl, donate_argnums=(0,))
            self.lora_store = LoraStore(config, model, self.load_lora_slot)

        # compile-churn telemetry: every serving-path jit is wrapped so a
        # recompile storm (the top TPU serving hazard — a stray dynamic shape
        # mid-traffic) shows up as a climbing compile counter + seconds in the
        # engine resource gauges, not as unexplained latency
        from dynamo_tpu.utils.compile_monitor import CompileMonitor, monitored_jit

        self.compile_monitor = CompileMonitor()

        def _mjit(label, fn):
            return monitored_jit(fn, label, self.compile_monitor)

        self._prefill = _mjit("prefill", jax.jit(
            self._prefill_impl, donate_argnums=(1, 2),
            static_argnames=("want_lp", "want_pen", "want_seed", "want_eos_mask", "mp"),
        ))
        # cross-request packed prefill (one weight pass for N lanes); one
        # executable per (N, bucket, table width) actually used
        self._prefill_packed = _mjit("prefill_packed", jax.jit(
            self._prefill_packed_impl, donate_argnums=(1, 2),
            static_argnames=("want_lp", "want_pen", "want_seed", "want_eos_mask", "mp"),
        ))
        # multimodal vision encode (compiled lazily; text-only models never
        # pay for it — the mm prefill variant is _prefill traced with embeds)
        self._encode_images = _mjit("encode_images", jax.jit(
            lambda params, patches, rows, cols, valid, segments: self.model.encode_images(
                params, patches, rows, cols, valid, segments=segments
            )
        ))
        if config.sp > 1:
            # sequence-parallel whole-prompt prefill (ring attention over sp)
            self._prefill_sp = _mjit("prefill_sp", jax.jit(
                self._prefill_sp_impl, donate_argnums=(1, 2),
                static_argnames=("want_lp", "want_pen", "want_seed", "want_eos_mask", "mp"),
            ))
        self._decode_window = _mjit("decode_window", jax.jit(
            self._decode_window_impl, donate_argnums=(1, 2),
            static_argnames=("num_steps", "want_lp", "want_pen", "want_seed", "want_eos_mask"),
        ))
        # speculative verify step (spec subsystem): ONE trace regardless of
        # sampling features — seeds/filters are neutral-input no-ops, and
        # penalties/logprobs requests never ride this path (the scheduler
        # routes them through classic windows)
        self._verify = _mjit("verify", jax.jit(self._verify_impl, donate_argnums=(1,)))
        # draft-model speculation: a second model with its own paged KV pool
        # and a batched k-token drafting dispatch (spec/draft.py). Loaded
        # through the registry with THIS engine's quantize/kv_cache_dtype so
        # the draft composes with int8 weights and the int8 KV cache.
        self.draft = None
        spec = config.spec
        if spec is not None and spec.kind == "draft":
            from dynamo_tpu.spec.draft import DraftModelRunner

            self.draft = DraftModelRunner(
                config, spec, compile_monitor=self.compile_monitor
            )
        def _write_tokens_impl(st, idx, vals):
            return dict(st, tokens=st["tokens"].at[idx].set(vals, mode="drop"))

        self._write_tokens = jax.jit(_write_tokens_impl, donate_argnums=(0,))

        def _seed_pen_impl2(st, slot, prompt_ids, output_ids):
            # reset the slot's penalty state, mark prompt+output tokens seen,
            # and restore output occurrence counts (preemption resume); both
            # id arrays are bucket-padded with V (dropped by the OOB scatter)
            counts = st["counts"].at[slot].set(0)
            counts = counts.at[slot, output_ids].add(1, mode="drop")
            seen = st["seen"].at[slot].set(False)
            seen = seen.at[slot, prompt_ids].set(True, mode="drop")
            return dict(st, counts=counts, seen=seen)

        self._seed_pen = jax.jit(_seed_pen_impl2, donate_argnums=(0,))
        # block-granularity KV IO for disaggregation / offload
        # (the NIXL-slot replacement, reference: patch nixl.py register_kv_caches).
        # The model defines its canonical wire layout (llama: [L,2,n,ps,Hkv,D];
        # MLA: [L,n,ps,latent_padded]); on device the pools are flat [L*P, ...].
        L = model.config.num_layers
        Pn = config.num_pages

        def _flat_ids(ids):  # [n] logical -> [L, n] flat
            return ids[None, :] + (jnp.arange(L, dtype=jnp.int32) * Pn)[:, None]

        self._gather_pages = _mjit("gather_pages", jax.jit(
            lambda kv, ids: model.gather_pages_wire(kv, _flat_ids(ids))
        ))
        self._scatter_pages = _mjit("scatter_pages", jax.jit(
            lambda kv, ids, data: model.scatter_pages_wire(kv, _flat_ids(ids), data),
            donate_argnums=(0,),
        ))

    # ---------------- jitted bodies ----------------

    def _model_prefill(self, params, kv, tokens, positions, page_table, valid, last, embeds=None, emask=None, rope_pos=None, lora=None, lora_id=None):
        """model.prefill, or its GPipe-pipelined form when pp > 1 (which has
        no LoRA threading — the lora+pp combination is gated at init)."""
        if self.config.pp > 1:
            from dynamo_tpu.parallel.pipeline import prefill_pipelined

            return prefill_pipelined(
                self.model, params, kv, tokens, positions, page_table, valid, last,
                self.mesh, input_embeds=embeds, embeds_mask=emask,
                rope_positions=rope_pos,
            )
        lkw = {} if lora is None else dict(lora=lora, lora_id=lora_id)
        return self.model.prefill(
            params, kv, tokens, positions, page_table, valid, last,
            input_embeds=embeds, embeds_mask=emask, rope_positions=rope_pos, **lkw,
        )

    def _model_decode(self, params, kv, tokens, positions, page_tables, active, rope_deltas=None, lora=None, lora_ids=None):
        if self.config.pp > 1:
            from dynamo_tpu.parallel.pipeline import decode_pipelined

            return decode_pipelined(
                self.model, params, kv, tokens, positions, page_tables, active,
                self.mesh, rope_deltas=rope_deltas,
            )
        lkw = {} if lora is None else dict(lora=lora, lora_ids=lora_ids)
        return self.model.decode(
            params, kv, tokens, positions, page_tables, active,
            rope_deltas=rope_deltas, **lkw,
        )

    def _prefill_impl(self, params, kv, slot_state, ints, flts, key, embeds=None, emask=None, rope_pos=None, lora=None, want_lp=False, want_pen=False, want_seed=False, want_eos_mask=False, mp=None):
        """ints [bucket + mp + 6 + MAX_EOS_IDS] = token buf, page
        table, (start_pos, n_real, top_k, slot, seed, lora_slot), then the
        request's EOS ids (V-padded); flts [6] = (temperature, top_p, min_p,
        presence, frequency, repetition). Positions and the valid mask derive
        on device — one packed H2D per chunk. The sampled token is written into
        ``slot_state["tokens"][slot]`` (slot >= max_seqs drops the write) so a
        following decode window can consume it without any host round trip.

        ``mp`` is the page-table width this trace is compiled for — a rung
        of the config's table-width ladder, not the dense max_pages_per_seq.
        Multimodal chunks pass ``embeds`` [bucket, D] + ``emask`` [bucket];
        ``lora`` (the adapter pool; chunk's slot id rides the ints) applies
        one adapter's delta to the whole chunk — slot 0 is the zero adapter;
        want_lp/want_pen/want_seed/want_eos_mask gate logprobs, penalties,
        seeded streams, and min_tokens EOS suppression out of the default
        trace."""
        if mp is None:
            mp = self.config.max_pages_per_seq
        bucket = ints.shape[0] - mp - 6 - MAX_EOS_IDS
        tokens = ints[:bucket]
        page_table = ints[bucket : bucket + mp]
        start_pos = ints[bucket + mp]
        n = ints[bucket + mp + 1]
        top_k = ints[bucket + mp + 2]
        slot = ints[bucket + mp + 3]
        seed = ints[bucket + mp + 4]
        lora_id = ints[bucket + mp + 5]
        eos_ids = ints[bucket + mp + 6 :]
        positions = start_pos + jnp.arange(bucket, dtype=jnp.int32)
        valid = jnp.arange(bucket) < n
        logits, kv = self._model_prefill(
            params, kv, tokens, positions, page_table, valid, n - 1,
            embeds=embeds, emask=emask, rope_pos=rope_pos,
            lora=lora, lora_id=lora_id,
        )
        tok, lp, slot_state = self._sample_one(
            logits, key, flts, top_k, slot, seed, start_pos + n - 1, slot_state,
            want_lp, want_pen, want_seed,
            eos_ids=eos_ids if want_eos_mask else None,
        )
        return tok, lp, kv, slot_state

    def _sample_one(self, logits, key, flts, top_k, slot, seed, sample_pos,
                    slot_state, want_lp, want_pen, want_seed, eos_ids=None):
        """Shared prefill-side sampling tail: penalties (against the slot's
        state), logprobs, seeded streams, token feedback write. ``eos_ids``
        (min_tokens requests): the first sampled token is generation #1, so
        EOS logits are suppressed outright here."""
        raw_b = logits[None, :]
        if eos_ids is not None:
            logits = logits.at[eos_ids].add(jnp.float32(-1e30), mode="drop")
        logits_b = logits[None, :]
        if want_pen:
            counts = slot_state["counts"][slot][None]
            seen = slot_state["seen"][slot][None]
            logits_b = apply_penalties(
                logits_b, counts, seen, flts[3:4], flts[4:5], flts[5:6]
            )
        kwargs = {}
        if want_seed:
            kwargs = dict(seeds=seed[None], positions=sample_pos[None])
        if want_lp:
            toks, chosen, tids, tvals = sample_tokens_with_logprobs(
                logits_b, key, flts[:1], top_k[None], flts[1:2],
                raw_logits=raw_b, min_p=flts[2:3], **kwargs
            )
            lp = (chosen[0], tids[0], tvals[0])
        else:
            toks = sample_tokens(
                logits_b, key, flts[:1], top_k[None], flts[1:2], min_p=flts[2:3], **kwargs
            )
            lp = None
        tok = toks[0]
        tokens = slot_state["tokens"].at[slot].set(tok, mode="drop")
        slot_state = dict(slot_state, tokens=tokens)
        if want_pen:
            counts = slot_state["counts"].at[slot, tok].add(1, mode="drop")
            seen = slot_state["seen"].at[slot, tok].set(True, mode="drop")
            slot_state = dict(slot_state, counts=counts, seen=seen)
        return tok, lp, slot_state

    def _prefill_packed_impl(self, params, kv, slot_state, ints, flts, key, lora=None, want_lp=False, want_pen=False, want_seed=False, want_eos_mask=False, mp=None):
        """Cross-request packed prefill: ints [N, bucket + mp + 6 +
        MAX_EOS_IDS] — N lanes of the SAME per-lane row layout as
        _prefill_impl (``mp`` = the call's ladder table width); flts [6, N].
        Every lane's last-row logits are sampled
        ([N] tokens); the host ignores tokens of lanes that weren't a final
        chunk (their slot is out-of-range so the feedback write drops too).
        A mixed-adapter pack stays ONE dispatch: each lane's lora slot id
        gathers its adapter planes inside the shared weight pass."""
        if mp is None:
            mp = self.config.max_pages_per_seq
        N = ints.shape[0]
        bucket = ints.shape[1] - mp - 6 - MAX_EOS_IDS
        tokens = ints[:, :bucket]
        page_tables = ints[:, bucket : bucket + mp]
        start_pos = ints[:, bucket + mp]
        n = ints[:, bucket + mp + 1]
        top_ks = ints[:, bucket + mp + 2]
        slots = ints[:, bucket + mp + 3]
        seeds = ints[:, bucket + mp + 4]
        lora_ids = ints[:, bucket + mp + 5]
        eos_ids = ints[:, bucket + mp + 6 :]  # [N, MAX_EOS_IDS] V-padded
        positions = start_pos[:, None] + jnp.arange(bucket, dtype=jnp.int32)[None, :]
        valid = jnp.arange(bucket)[None, :] < n[:, None]
        lkw = {} if lora is None else dict(lora=lora, lora_ids=lora_ids)
        logits, kv = self.model.prefill_packed(
            params, kv, tokens, positions, page_tables, valid, n - 1, **lkw
        )
        raw_b = logits  # [N, V]
        if want_eos_mask:
            rows = jnp.arange(N)[:, None]
            logits = logits.at[rows, eos_ids].add(jnp.float32(-1e30), mode="drop")
        if want_pen:
            # out-of-range slots (non-final lanes) clip to an arbitrary row;
            # their sampled token is discarded, so the penalty values applied
            # don't matter — only the UPDATE below must drop, and it does.
            counts = jnp.take(slot_state["counts"], slots, axis=0, mode="clip")
            seen = jnp.take(slot_state["seen"], slots, axis=0, mode="clip")
            logits = apply_penalties(
                logits, counts, seen, flts[3], flts[4], flts[5]
            )
        kwargs = dict(min_p=flts[2])
        if want_seed:
            kwargs.update(seeds=seeds, positions=start_pos + n - 1)
        if want_lp:
            toks, chosen, tids, tvals = sample_tokens_with_logprobs(
                logits, key, flts[0], top_ks, flts[1], raw_logits=raw_b, **kwargs
            )
            lp = (chosen, tids, tvals)
        else:
            toks = sample_tokens(logits, key, flts[0], top_ks, flts[1], **kwargs)
            lp = None
        slot_state = dict(
            slot_state, tokens=slot_state["tokens"].at[slots].set(toks, mode="drop")
        )
        if want_pen:
            counts = slot_state["counts"].at[slots, toks].add(1, mode="drop")
            seen = slot_state["seen"].at[slots, toks].set(True, mode="drop")
            slot_state = dict(slot_state, counts=counts, seen=seen)
        return toks, lp, kv, slot_state

    def pack_prefill_lanes(
        self,
        lanes: list,  # [(tokens np[int32], start_pos, page_table, slot_or_-1, sampling, eos_ids, is_final[, lora_slot])]
        N: int,  # lane count the executable is compiled for (>= len(lanes))
    ):
        """Host-prep half of :meth:`prefill_chunk_batch`: build the packed
        int/float control arrays on the host (no device work). Split out so
        ``tools/profile_prefill.py`` can time host prep, H2D staging, and
        dispatch against the SAME arrays production dispatches — returns
        (ints, flts, want_extras, mp)."""
        V = self.model.config.vocab_size
        bucket = self.config.bucket_for(max(len(l[0]) for l in lanes))
        # table width for THIS call: the widest lane's ladder bucket (narrow
        # lanes zero-pad into the trash page) — short packs keep their
        # narrow executable; only packs containing a deep sequence go wide
        mp = self.config.table_bucket_for(max(len(l[2]) for l in lanes))
        ints = np.full((N, bucket + mp + 6 + MAX_EOS_IDS), V, np.int32)
        ints[:, :bucket] = 0
        ints[:, bucket : bucket + mp] = 0
        flts = np.zeros((6, N), np.float32)
        flts[1] = 1.0  # top_p neutral
        flts[5] = 1.0  # repetition neutral
        want_extras = False
        for j, lane in enumerate(lanes):
            tokens, start_pos, page_table, slot, sampling, eos_ids, is_final = lane[:7]
            lora_slot = lane[7] if len(lane) > 7 else 0
            n = len(tokens)
            ints[j, :n] = tokens
            ints[j, bucket : bucket + len(page_table[:mp])] = page_table[:mp]
            ints[j, bucket + mp] = start_pos
            ints[j, bucket + mp + 1] = n
            ints[j, bucket + mp + 2] = sampling.top_k
            ints[j, bucket + mp + 3] = slot if (is_final and slot >= 0) else self.config.max_seqs
            ints[j, bucket + mp + 4] = fold_seed(sampling.seed)
            ints[j, bucket + mp + 5] = lora_slot
            want_eos = bool(
                is_final and eos_ids and sampling.min_tokens >= 1
                and not sampling.ignore_eos
            )
            if want_eos:
                if len(eos_ids) > MAX_EOS_IDS:
                    log.warning(
                        "min_tokens: %d EOS ids exceed the device limit %d; ids "
                        "beyond the limit are not suppressed",
                        len(eos_ids), MAX_EOS_IDS,
                    )
                ids = np.asarray(eos_ids, np.int32)[:MAX_EOS_IDS]
                ints[j, bucket + mp + 6 : bucket + mp + 6 + len(ids)] = ids
            flts[0, j] = sampling.temperature
            flts[1, j] = sampling.top_p
            flts[2, j] = sampling.min_p
            flts[3, j] = sampling.presence_penalty
            flts[4, j] = sampling.frequency_penalty
            flts[5, j] = sampling.repetition_penalty
            want_extras = want_extras or want_eos or (
                is_final and (sampling.needs_penalties or sampling.seed is not None)
            )
        # pad lanes: n=0 (valid all-False), start 0, page table 0 (every read
        # lands in the in-bounds trash page — the V fill would DMA out of the
        # pool), slot out-of-range so the feedback write drops, lora slot 0
        # (the zero adapter)
        for j in range(len(lanes), N):
            ints[j, bucket : bucket + mp + 6] = 0
            ints[j, bucket + mp + 3] = self.config.max_seqs
        return ints, flts, want_extras, mp

    def prefill_chunk_batch(
        self,
        lanes: list,
        N: int,
        want_logprobs: bool = False,
    ):
        """Dispatch ONE packed prefill covering chunks of up to N distinct
        sequences (pad lanes are all-invalid; see :meth:`pack_prefill_lanes`
        for the lane tuple contract). Returns the [N] device token array
        (async copy started) — callers read only final-chunk lanes — plus
        the logprob arrays when requested."""
        ints, flts, want_extras, mp = self.pack_prefill_lanes(lanes, N)
        if want_extras:
            self._ensure_penalty_state()
        toks, lp, self.kv_cache, self.slot_state = self._prefill_packed(
            self.params,
            self.kv_cache,
            self.slot_state,
            jnp.asarray(ints),
            jnp.asarray(flts),
            self._next_key(),
            lora=self.lora,
            want_lp=want_logprobs,
            want_pen=want_extras,
            want_seed=want_extras,
            want_eos_mask=want_extras,
            mp=mp,
        )
        try:
            toks.copy_to_host_async()
            if lp is not None:
                for a in lp:
                    a.copy_to_host_async()
        except Exception:
            pass
        return (toks, lp) if want_logprobs else toks

    def _prefill_sp_impl(self, params, kv, slot_state, ints, flts, key, lora=None, want_lp=False, want_pen=False, want_seed=False, want_eos_mask=False, mp=None):
        """Same packed-ints contract as _prefill_impl, but the whole-prompt
        chunk runs sequence-parallel (model.prefill_sp: ring attention over
        the sp mesh axis). Only called with start_pos == 0."""
        if mp is None:
            mp = self.config.max_pages_per_seq
        bucket = ints.shape[0] - mp - 6 - MAX_EOS_IDS
        tokens = ints[:bucket]
        page_table = ints[bucket : bucket + mp]
        n = ints[bucket + mp + 1]
        top_k = ints[bucket + mp + 2]
        slot = ints[bucket + mp + 3]
        seed = ints[bucket + mp + 4]
        lora_id = ints[bucket + mp + 5]
        eos_ids = ints[bucket + mp + 6 :]
        positions = jnp.arange(bucket, dtype=jnp.int32)
        valid = positions < n
        if self.config.pp > 1:
            # composed pp x sp: ring attention inside the GPipe shard_map
            from dynamo_tpu.parallel.pipeline import prefill_pipelined_ring

            logits, kv = prefill_pipelined_ring(
                self.model, params, kv, tokens, positions, page_table, valid,
                n - 1, self.mesh,
            )
        else:
            lkw = {} if lora is None else dict(lora=lora, lora_id=lora_id)
            logits, kv = self.model.prefill_sp(
                params, kv, tokens, positions, page_table, valid, n - 1,
                mesh=self.mesh, **lkw,
            )
        tok, lp, slot_state = self._sample_one(
            logits, key, flts, top_k, slot, seed, n - 1, slot_state,
            want_lp, want_pen, want_seed,
            eos_ids=eos_ids if want_eos_mask else None,
        )
        return tok, lp, kv, slot_state

    def _decode_window_impl(self, params, kv, slot_state, ints, flts, key, lora=None, num_steps=1, want_lp=False, want_pen=False, want_seed=False, want_eos_mask=False):
        """num_steps fused decode steps; the sampled-token feedback loop starts
        from the device-resident ``slot_state["tokens"]`` buffer, so the host can
        dispatch windows back-to-back without reading any results in between.

        All small per-slot inputs ride in two packed arrays (one H2D transfer
        each — per-call transfer latency dominates on tunneled platforms):
        ``ints`` [7 + MAX_EOS_IDS + max_pages, B] = positions, limits, active,
        top_ks, rope_deltas, seeds, eos_allowed_from, the per-slot EOS id rows
        (V-padded), then the transposed page tables; ``flts`` [6, B] = temps,
        top_ps, min_ps, presence, frequency, repetition. Page
        tables are static across the window — the host pre-allocates pages to
        cover positions + num_steps - 1 before calling, and a sequence freezes
        once its fed position would pass ``limits`` (no writes past its
        capacity)."""
        positions, limits = ints[0], ints[1]
        active = ints[2].astype(bool)
        top_ks = ints[3]
        rope_deltas = ints[4]  # M-RoPE per-slot offsets (zeros for text models)
        seeds = ints[5]  # per-request sampling seeds (0 = unseeded)
        eos_allowed_from = ints[6]  # fed position where EOS unblocks (min_tokens)
        eos_ids = ints[7 : 7 + MAX_EOS_IDS].T  # [B, MAX_EOS_IDS], V-padded
        page_tables = ints[7 + MAX_EOS_IDS :].T  # [B, max_pages]
        temps, top_ps, min_ps = flts[0], flts[1], flts[2]
        pres, freq, reps = flts[3], flts[4], flts[5]
        keys = jax.random.split(key, num_steps)

        def body(carry, k):
            kv, st, positions, act = carry
            logits, kv = self._model_decode(
                params, kv, st["tokens"], positions, page_tables, act,
                rope_deltas=rope_deltas if getattr(self.model.config, "mrope_section", None) is not None else None,
                # per-slot adapter ids live in the donated slot_state bundle
                # (written once at admission), so a mixed-adapter window
                # reads them on device with zero extra H2D per dispatch
                lora=lora,
                lora_ids=st["lora"] if lora is not None else None,
            )
            raw_logits = logits
            if want_pen:
                logits = apply_penalties(logits, st["counts"], st["seen"], pres, freq, reps)
            if want_eos_mask:
                # min_tokens: ban the slot's EOS ids until its fed position
                # reaches eos_allowed_from
                rows = jnp.arange(logits.shape[0])[:, None]
                pen = jnp.where(positions >= eos_allowed_from, 0.0, -1e30)
                logits = logits.at[rows, eos_ids].add(pen[:, None], mode="drop")
            kwargs = dict(min_p=min_ps)
            if want_seed:
                kwargs.update(seeds=seeds, positions=positions)
            if want_lp:
                toks, chosen, tids, tvals = sample_tokens_with_logprobs(
                    logits, k, temps, top_ks, top_ps, raw_logits=raw_logits, **kwargs
                )
                ys = (toks, chosen, tids, tvals)
            else:
                # logprobs gated out of the trace: no full-vocab log_softmax or
                # top_k rides the hot path unless some request asked for them
                toks = sample_tokens(logits, k, temps, top_ks, top_ps, **kwargs)
                ys = (toks,)
            tokens = jnp.where(act, toks, st["tokens"])
            st = dict(st, tokens=tokens)
            if want_pen:
                rows = jnp.arange(tokens.shape[0])
                counts = st["counts"].at[rows, toks].add(act.astype(jnp.int32))
                # keep `seen` exact: only rows that actually emitted this step
                seen_tok = st["seen"].at[rows, toks].get() | act
                seen = st["seen"].at[rows, toks].set(seen_tok)
                st = dict(st, counts=counts, seen=seen)
            positions = positions + act.astype(positions.dtype)
            act = act & (positions <= limits)
            return (kv, st, positions, act), ys

        (kv, slot_state, _, _), ys = jax.lax.scan(
            body, (kv, slot_state, positions, active), keys
        )
        all_toks = ys[0]
        lp = (ys[1], ys[2], ys[3]) if want_lp else None
        # [num_steps, B] tokens (+ ([num_steps, B], [num_steps, B, K] x2) lp)
        return all_toks, lp, kv, slot_state

    def _verify_impl(self, params, kv, ints, flts, key, draft_probs=None, lora=None):
        """Speculative verify step: every slot feeds its anchor token plus up
        to K drafts at consecutive positions through the model's multi-query
        ``verify`` pass, then acceptance runs on device so only the tiny
        [B, K+1] token matrix and [B] emit counts cross back to the host.

        ``ints`` [6 + (K+1) + max_pages, B] = positions (anchor fed position),
        active, top_ks, seeds, n_drafts, lora slot ids, the K+1 fed-token
        rows, then the transposed page tables (K is derived from the array
        shape — one executable per configured k). ``flts`` [3, B] = temps,
        top_ps, min_ps.
        ``draft_probs`` ([B, K, V] device array from dispatch_draft, never
        staged through the host): the real draft distributions temperature>0
        acceptance divides by; None = one-hot (n-gram) proposals.
        ``lora``: a mixed-adapter verify round gathers each slot's adapter
        inside the one shared pass (the verify side must see the same
        adapter the sequence decodes with, or acceptance silently drops).
        Rows beyond a slot's n_drafts scatter their KV to the trash page, so a
        slot proposing fewer than K drafts never writes past its pages."""
        # K is config-static (one executable per configured k), so the page-
        # table width — which now varies with the ladder — falls out of the
        # array shape instead of being pinned to the dense max_pages_per_seq
        spec = self.config.spec
        K1 = (
            spec.k + 1
            if spec is not None
            else ints.shape[0] - 6 - self.config.max_pages_per_seq
        )
        positions = ints[0]
        active = ints[1].astype(bool)
        top_ks = ints[2]
        seeds = ints[3]
        n_drafts = ints[4]
        lora_ids = ints[5]
        fed = ints[6 : 6 + K1].T  # [B, K1]
        page_tables = ints[6 + K1 :].T  # [B, max_pages]
        temps, top_ps, min_ps = flts[0], flts[1], flts[2]
        t_idx = jnp.arange(K1, dtype=jnp.int32)
        pos_mat = positions[:, None] + t_idx[None, :]
        row_valid = active[:, None] & (t_idx[None, :] <= n_drafts[:, None])
        lkw = {} if lora is None else dict(lora=lora, lora_ids=lora_ids)
        logits, kv = self.model.verify(
            params, kv, fed, pos_mat, page_tables, row_valid, **lkw
        )
        out, n_emit = accept_speculative(
            logits, fed[:, 1:], n_drafts, key, temps, top_ks, top_ps,
            min_p=min_ps, seeds=seeds, positions=positions,
            draft_probs=draft_probs,
        )
        n_emit = jnp.where(active, n_emit, 0)
        return out, n_emit, kv

    # ---------------- host API (engine thread) ----------------

    def _next_key(self) -> jax.Array:
        self._key, sub = jax.random.split(self._key)
        return sub

    def prefill_chunk(
        self,
        tokens: np.ndarray,  # [n] real tokens for this chunk
        start_pos: int,
        page_table: np.ndarray,  # [max_pages_per_seq]
        sample: bool,
        temperature: float,
        top_k: int,
        top_p: float,
        slot: int = -1,  # decode slot to seed with the sampled token (device side)
        sync: bool = True,
        embeds: Optional[np.ndarray] = None,  # [n, D] mm overrides for this chunk
        embeds_mask: Optional[np.ndarray] = None,  # [n] bool
        rope_pos: Optional[np.ndarray] = None,  # [n, 3] M-RoPE positions
        want_logprobs: bool = False,  # sync=False only: also return lp arrays
        sampling=None,  # SamplingParams: penalties / min_p / seed (optional)
        eos_ids=None,  # request EOS ids (min_tokens device-side suppression)
        lora_slot: int = 0,  # adapter slot for this chunk (0 = base/zero)
    ):
        """Run one prefill chunk.

        When ``sample``: returns the sampled next token — as a host int when
        ``sync``, else as a device scalar (dispatch-ahead mode; an async
        device-to-host copy is already in flight). When ``slot >= 0`` the token
        is also written into ``slot_state["tokens"][slot]`` on device so decode windows
        can start without waiting for the host to see it."""
        n = len(tokens)
        bucket = self.config.bucket_for(n)
        # the caller's table is already sized to a ladder bucket (scheduler/
        # engine build them via table_bucket_for); its width picks the trace
        mp = len(page_table)
        V = self.model.config.vocab_size
        ints = np.full(bucket + mp + 6 + MAX_EOS_IDS, V, np.int32)  # tail = eos pad
        ints[:bucket] = 0
        ints[:n] = tokens
        ints[bucket : bucket + mp] = page_table[:mp]
        ints[bucket + mp] = start_pos
        ints[bucket + mp + 1] = n
        ints[bucket + mp + 2] = top_k
        # out-of-bounds slot => scatter mode="drop" skips the token write
        ints[bucket + mp + 3] = slot if (sample and slot >= 0) else self.config.max_seqs
        ints[bucket + mp + 4] = fold_seed(sampling.seed) if sampling is not None else 0
        ints[bucket + mp + 5] = lora_slot
        want_pen = sampling is not None and sampling.needs_penalties
        want_seed = sampling is not None and sampling.seed is not None
        # min_tokens >= 1: the first sampled token (generation #1) must not be
        # EOS -> suppress the request's EOS logits on device. Matches vLLM:
        # EOS is suppressed while generated < min_tokens, so min_tokens=1
        # guarantees one non-EOS token.
        want_eos = bool(
            sample
            and eos_ids is not None
            and len(eos_ids) > 0
            and sampling is not None
            and sampling.min_tokens >= 1
            and not sampling.ignore_eos
        )
        if want_eos:
            if len(eos_ids) > MAX_EOS_IDS:
                log.warning(
                    "min_tokens: %d EOS ids exceed the device limit %d; ids "
                    "beyond the limit are not suppressed",
                    len(eos_ids), MAX_EOS_IDS,
                )
            ids = np.asarray(eos_ids, np.int32)[:MAX_EOS_IDS]
            ints[bucket + mp + 6 : bucket + mp + 6 + len(ids)] = ids
        flts = np.array(
            [
                temperature,
                top_p,
                sampling.min_p if sampling is not None else 0.0,
                sampling.presence_penalty if sampling is not None else 0.0,
                sampling.frequency_penalty if sampling is not None else 0.0,
                sampling.repetition_penalty if sampling is not None else 1.0,
            ],
            np.float32,
        )
        mm_args = ()
        if embeds is not None or rope_pos is not None:
            # multimodal chunk: embeds/rope-override trace of _prefill (paged
            # path only; the sp/ring path is text-only for now)
            D = embeds.shape[1] if embeds is not None else 1
            emb = np.zeros((bucket, D), np.float32)
            msk = np.zeros(bucket, bool)
            if embeds is not None:
                emb[:n] = embeds
                msk[:n] = embeds_mask
            rp = None
            if rope_pos is not None:
                rp_pad = np.zeros((bucket, 3), np.int32)
                rp_pad[:n] = rope_pos
                rp = jnp.asarray(rp_pad)
            mm_args = (jnp.asarray(emb) if embeds is not None else None,
                       jnp.asarray(msk) if embeds is not None else None,
                       rp)
        # whole-prompt chunks go sequence-parallel when configured (ring
        # attention assumes the chunk starts at position 0)
        use_sp = (
            embeds is None
            and rope_pos is None
            and self.config.sp > 1
            and start_pos == 0
            and bucket % self.config.sp == 0
        )
        prefill_fn = self._prefill_sp if use_sp else self._prefill
        # same trace collapse as dispatch_decode_window: penalties/seeds/EOS
        # masking share one feature-bearing variant (neutral inputs are no-ops)
        want_extras = bool((want_pen and sample) or (want_seed and sample) or want_eos)
        if want_extras:
            self._ensure_penalty_state()
        tok, lp, self.kv_cache, self.slot_state = prefill_fn(
            self.params,
            self.kv_cache,
            self.slot_state,
            jnp.asarray(ints),
            jnp.asarray(flts),
            self._next_key(),
            *mm_args,
            lora=self.lora,
            # only the sampling (final) chunk's outputs are ever consumed
            want_lp=want_logprobs and sample,
            want_pen=want_extras,
            want_seed=want_extras,
            want_eos_mask=want_extras,
            mp=mp,
        )
        if not sample:
            return None
        if sync:
            return int(jax.device_get(tok))  # graftlint: sync-ok sync chunk path: caller asked for the token synchronously
        try:
            tok.copy_to_host_async()
            if lp is not None:
                for a in lp:
                    a.copy_to_host_async()
        except Exception:
            pass
        if want_logprobs:
            return tok, lp
        return tok

    VISION_BUCKETS = (64, 256, 1024, 4096, 16384)

    def encode_images(self, images: list) -> list[np.ndarray]:
        """Run the vision tower over a request's ImageInputs; returns per-image
        [num_tokens, D] float32 embeddings.

        All images pack into ONE bucket-padded call (attention is masked
        block-diagonal via segment ids), so a multi-image prompt costs a
        single dispatch — on tunneled PJRT platforms per-call latency
        dominates the tower itself. Falls back to per-image calls only when
        the combined patch count exceeds the largest bucket."""
        if not images:
            return []
        total = sum(im.patches.shape[0] for im in images)
        bucket = next((b for b in self.VISION_BUCKETS if b >= total), None)
        if bucket is None:
            if len(images) == 1:
                raise ValueError(f"image has {total} patches > max bucket")
            # too big combined: split the batch in half recursively
            mid = len(images) // 2
            return self.encode_images(images[:mid]) + self.encode_images(images[mid:])
        patch_dim = images[0].patches.shape[1]
        patches = np.zeros((bucket, patch_dim), np.float32)
        rows = np.zeros(bucket, np.int32)
        cols = np.zeros(bucket, np.int32)
        valid = np.zeros(bucket, bool)
        # single image: skip the pairwise segment mask entirely (it would be
        # an [N, N] f32 bias held across every tower layer)
        segments = None if len(images) == 1 else np.full(bucket, -1, np.int32)
        offset = 0
        spans = []
        for idx, im in enumerate(images):
            n = im.patches.shape[0]
            patches[offset : offset + n] = im.patches
            rows[offset : offset + n] = im.rows
            cols[offset : offset + n] = im.cols
            valid[offset : offset + n] = True
            if segments is not None:
                segments[offset : offset + n] = idx
            spans.append((offset, n))
            offset += n
        emb = np.asarray(  # graftlint: sync-ok vision embeds materialize once per request at admission
            jax.device_get(
                self._encode_images(
                    self.params,
                    jnp.asarray(patches),
                    jnp.asarray(rows),
                    jnp.asarray(cols),
                    jnp.asarray(valid),
                    jnp.asarray(segments) if segments is not None else None,
                )
            ),
            np.float32,
        )
        m2 = self.model.config.vision.spatial_merge_size ** 2
        return [
            emb[off // m2 : off // m2 + im.num_tokens]
            for (off, n), im in zip(spans, images)
        ]

    def write_token_slots(self, slots: np.ndarray, tokens: np.ndarray) -> None:
        """Host-known tokens (e.g. disagg adoption) -> slot token feedback."""
        self.slot_state = self._write_tokens(
            self.slot_state, jnp.asarray(slots, jnp.int32), jnp.asarray(tokens, jnp.int32)
        )

    def set_slot_lora(self, slot: int, lora_slot: int) -> None:
        """Pin a decode slot's adapter id in the device-resident slot_state
        (written once at admission; decode windows gather it per step).
        No-op on a LoRA-disabled engine."""
        if self.lora is None:
            return
        self.slot_state = self._set_lora(
            self.slot_state,
            jnp.asarray(slot, jnp.int32),
            jnp.asarray(lora_slot, jnp.int32),
        )

    def load_lora_slot(self, slot: int, host_tree: dict, scale: float) -> None:
        """Scatter one adapter's A/B planes into pool slot ``slot`` (donated
        in-place update; one executable total — every adapter arrives padded
        to the pool rank, so the shapes never vary)."""
        tree = {
            m: {"a": jnp.asarray(e["a"]), "b": jnp.asarray(e["b"])}
            for m, e in host_tree.items()
        }
        self.lora = self._lora_write(
            self.lora,
            jnp.asarray(slot, jnp.int32),
            tree,
            jnp.asarray(scale, jnp.float32),
        )

    def _ensure_penalty_state(self) -> None:
        if "counts" not in self.slot_state:
            V = self.model.config.vocab_size
            B = self.config.max_seqs
            self.slot_state = dict(
                self.slot_state,
                counts=jnp.zeros((B, V), jnp.int32),
                seen=jnp.zeros((B, V), bool),
            )

    def _pad_ids_bucket(self, ids: np.ndarray) -> np.ndarray:
        """Pad an id list to a prefill bucket with V (OOB -> scatter-dropped)
        so _seed_pen compiles once per bucket, not per prompt length."""
        V = self.model.config.vocab_size
        n = len(ids)
        size = next(
            (b for b in self.config.prefill_buckets if b >= n),
            max(self.config.max_model_len, n),
        )
        out = np.full(size, V, np.int32)
        out[:n] = ids
        return out

    def seed_penalty_slot(self, slot: int, token_ids, output_from: int | None = None) -> None:
        """Reset a slot's penalty state: mark all of ``token_ids`` seen; count
        the tail from ``output_from`` as output occurrences (a preempted
        request's prompt embeds its prior output — restoring the counts keeps
        presence/frequency penalties continuous across preemption)."""
        self._ensure_penalty_state()
        ids = np.asarray(token_ids, np.int32)
        out_ids = ids[output_from:] if output_from is not None else ids[:0]
        self.slot_state = self._seed_pen(
            self.slot_state,
            jnp.asarray(slot, jnp.int32),
            jnp.asarray(self._pad_ids_bucket(ids)),
            jnp.asarray(self._pad_ids_bucket(out_ids)),
        )

    def dispatch_decode_window(
        self,
        positions: np.ndarray,  # [B] fed-token position per slot
        page_tables: np.ndarray,  # [B, max_pages_per_seq]
        active: np.ndarray,  # [B] bool
        limits: np.ndarray,  # [B] max fed-token position per slot
        temps: np.ndarray,
        top_ks: np.ndarray,
        top_ps: np.ndarray,
        num_steps: int,
        want_logprobs: bool = False,
        rope_deltas: np.ndarray | None = None,  # [B] M-RoPE offsets
        min_ps: np.ndarray | None = None,  # [B]
        penalties: np.ndarray | None = None,  # [3, B] presence/frequency/repetition
        seeds: np.ndarray | None = None,  # [B] int32 (0 = unseeded)
        eos_allowed_from: np.ndarray | None = None,  # [B] fed pos (min_tokens)
        eos_ids: np.ndarray | None = None,  # [B, MAX_EOS_IDS] V-padded
    ):
        """Dispatch one fused decode window WITHOUT waiting for results.

        Returns the [num_steps, B] device token array with an async
        device-to-host copy already started; the caller materializes it later
        (np.asarray) while further windows run on device."""
        B = positions.shape[0]
        V = self.model.config.vocab_size
        ints = np.empty((7 + MAX_EOS_IDS + page_tables.shape[1], B), np.int32)
        ints[0] = positions
        ints[1] = limits
        ints[2] = active
        ints[3] = top_ks
        ints[4] = rope_deltas if rope_deltas is not None else 0
        ints[5] = seeds if seeds is not None else 0
        ints[6] = eos_allowed_from if eos_allowed_from is not None else 0
        ints[7 : 7 + MAX_EOS_IDS] = eos_ids.T if eos_ids is not None else V
        ints[7 + MAX_EOS_IDS :] = page_tables.T
        flts = np.empty((6, B), np.float32)
        flts[0] = temps
        flts[1] = top_ps
        flts[2] = min_ps if min_ps is not None else 0.0
        flts[3:6] = penalties if penalties is not None else np.array([[0.0], [0.0], [1.0]])
        # penalties / seeded streams / min_tokens EOS masking collapse into ONE
        # feature-bearing trace: all their neutral inputs are no-ops (penalty
        # (0,0,1), seed 0, V-padded EOS rows dropped by the OOB scatter), so
        # 2^3 flag combinations become 2 and a request introducing a new
        # combination mid-serving can't hit a multi-second cold XLA compile.
        want_extras = (
            penalties is not None
            or (seeds is not None and bool(np.any(seeds)))
            or eos_ids is not None
        )
        if want_extras:
            self._ensure_penalty_state()
        toks, lp, self.kv_cache, self.slot_state = self._decode_window(
            self.params,
            self.kv_cache,
            self.slot_state,
            jnp.asarray(ints),
            jnp.asarray(flts),
            self._next_key(),
            lora=self.lora,
            num_steps=num_steps,
            want_lp=want_logprobs,
            want_pen=want_extras,
            want_seed=want_extras,
            want_eos_mask=want_extras,
        )
        try:
            toks.copy_to_host_async()
            if want_logprobs:
                for a in lp:
                    a.copy_to_host_async()
        except Exception:
            pass
        return (toks, lp) if want_logprobs else toks

    def dispatch_verify(
        self,
        positions: np.ndarray,  # [B] anchor fed position per slot
        page_tables: np.ndarray,  # [B, max_pages_per_seq]
        active: np.ndarray,  # [B] bool
        fed_tokens: np.ndarray,  # [B, K+1] anchor + (padded) draft tokens
        n_drafts: np.ndarray,  # [B] real draft count per slot
        temps: np.ndarray,
        top_ks: np.ndarray,
        top_ps: np.ndarray,
        min_ps: np.ndarray | None = None,
        seeds: np.ndarray | None = None,  # [B] int32 (0 = unseeded)
        draft_probs=None,  # [B, K, V] device array from dispatch_draft
        lora_slots: np.ndarray | None = None,  # [B] adapter slot ids
    ):
        """Dispatch one speculative verify pass; returns the (tokens [B, K+1],
        n_emit [B]) device arrays with async host copies already started. The
        caller materializes both (the proposer needs the accepted tokens
        before it can draft the next round, so verify rounds are synchronous
        per slot — the win is k+1 tokens per weight pass, not dispatch-ahead).
        ``draft_probs`` rides through to the on-device acceptance untouched
        (draft-model rounds); None keeps the one-hot (n-gram) rule."""
        B = positions.shape[0]
        K1 = fed_tokens.shape[1]
        ints = np.empty((6 + K1 + page_tables.shape[1], B), np.int32)
        ints[0] = positions
        ints[1] = active
        ints[2] = top_ks
        ints[3] = seeds if seeds is not None else 0
        ints[4] = n_drafts
        ints[5] = lora_slots if lora_slots is not None else 0
        ints[6 : 6 + K1] = fed_tokens.T
        ints[6 + K1 :] = page_tables.T
        flts = np.empty((3, B), np.float32)
        flts[0] = temps
        flts[1] = top_ps
        flts[2] = min_ps if min_ps is not None else 0.0
        out, n_emit, self.kv_cache = self._verify(
            self.params,
            self.kv_cache,
            jnp.asarray(ints),
            jnp.asarray(flts),
            self._next_key(),
            draft_probs,
            lora=self.lora,
        )
        try:
            out.copy_to_host_async()
            n_emit.copy_to_host_async()
        except Exception:
            pass
        return out, n_emit

    def dispatch_draft(self, *args, **kwargs):
        """One batched draft round across every spec-mode lane (draft-model
        speculation only; see spec/draft.py DraftModelRunner.dispatch_draft).
        Returns (draft tokens [B, K] dev, draft probs [B, K, V] dev)."""
        if self.draft is None:
            raise RuntimeError("dispatch_draft requires speculative='draft:...'")
        return self.draft.dispatch_draft(*args, **kwargs)

    def warmup(self) -> None:
        """Pre-compile every trace variant synchronously (core + extras)."""
        import time as _time

        t0 = _time.monotonic()
        self.warmup_core()
        for thunk in self.warmup_extra_thunks():
            thunk()
        log.info("warmup: trace variants compiled in %.1fs", _time.monotonic() - t0)

    @property
    def packed_prefill_mode(self) -> bool:
        """True when the scheduler packs prefill chunks through the packed
        trace (the single definition of the gate; the scheduler adds a
        per-request `not req.images` condition on top)."""
        return (
            self.config.prefill_lanes > 1
            and self.config.pp == 1
            and self.config.sp == 1
            and hasattr(self.model, "prefill_packed")
        )

    def _warmup_shapes(self, table_width: Optional[int] = None):
        B = self.config.max_seqs
        # narrow (first-rung) tables are the hot path for a fresh engine —
        # deep sequences promote into the wider ladder variants, which
        # compile via warmup_extra_thunks
        mp = table_width or self.config.table_buckets[0]
        return {
            "zeros_i": np.zeros(B, np.int32),
            "pt": np.zeros((B, mp), np.int32),
            "inactive": np.zeros(B, bool),
            "temps": np.zeros(B, np.float32),
            "ones_f": np.ones(B, np.float32),
            "neutral_pen": np.tile(
                np.array([[0.0], [0.0], [1.0]], np.float32), (1, B)
            ),
        }

    def warmup_core(self) -> None:
        """Blocking pre-compile of the traces the FIRST requests need: the
        default decode window plus every prefill bucket's default trace (per-
        request and packed). All slots are inactive / writes target the
        reserved null page 0, so the calls execute harmlessly; what matters is
        that the XLA executables land in the jit cache before live traffic.

        Feature variants (logprobs/penalties) compile via
        ``warmup_extra_thunks`` — in the background on a serving engine
        (first deploy of a new geometry used to block ~100-174 s cold on the
        remote compiler for variants most traffic never touches)."""
        import time as _time

        t0 = _time.monotonic()
        # Allocate the penalty buffers FIRST: slot_state's pytree structure is
        # part of the jit cache key, so every variant must compile against the
        # final (counts-bearing) structure or live traffic re-traces them all.
        self._ensure_penalty_state()
        sh = self._warmup_shapes()
        K = self.config.decode_steps
        out = self.dispatch_decode_window(
            sh["zeros_i"], sh["pt"], sh["inactive"], sh["zeros_i"],
            sh["temps"], sh["zeros_i"], sh["ones_f"], K,
        )
        jax.block_until_ready(out)  # graftlint: sync-ok warmup: compile gate, not serving traffic
        spec = self.config.spec
        if spec is not None:
            # one verify executable per configured k (all slots inactive, KV
            # rows land on the trash page — harmless, compiles the trace);
            # draft mode compiles the draft-probs-bearing variant plus the
            # draft runner's own step/prefill executables
            B = self.config.max_seqs
            dp = None
            if self.draft is not None:
                self.draft.warmup()
                V = self.model.config.vocab_size
                dp = jnp.zeros((B, spec.k, V), jnp.float32)
            out = self.dispatch_verify(
                sh["zeros_i"], sh["pt"], sh["inactive"],
                np.zeros((B, spec.k + 1), np.int32), sh["zeros_i"],
                sh["temps"], sh["zeros_i"], sh["ones_f"], draft_probs=dp,
            )
            jax.block_until_ready(out)  # graftlint: sync-ok warmup: compile gate, not serving traffic
        for b in self.config.prefill_buckets:
            if not self.packed_prefill_mode:
                self.prefill_chunk(
                    np.zeros(b, np.int32), 0, sh["pt"][0], sample=True,
                    temperature=0.0, top_k=0, top_p=1.0, slot=-1, sync=True,
                )
                continue
            # the scheduler dispatches packed calls at power-of-two N up to
            # lanes_for(b); N=1 (lone chunks) and N=lanes_max are the hot
            # ones — intermediates, feature variants, and the per-request
            # trace (still reached by disagg remote prefill and image
            # requests) compile via the extras thunks
            lane = (
                np.zeros(b, np.int32), 0, sh["pt"][0], -1,
                SamplingParams(temperature=0.0), (), False,
            )
            for N in {1, self.config.lanes_for(b)}:
                out = self.prefill_chunk_batch([lane], N=N)
                jax.block_until_ready(out)  # graftlint: sync-ok warmup: compile gate, not serving traffic
        log.info("warmup(core): compiled in %.1fs", _time.monotonic() - t0)

    def warmup_extra_thunks(self) -> list:
        """Thunks compiling the feature-bearing trace variants — decode
        windows with penalties/logprobs, prefill extras/logprobs traces, and
        the packed equivalents. Each runs harmlessly against inactive slots;
        a serving engine executes them one by one between steps (via
        run_on_engine) so readiness never waits on them."""
        sh = self._warmup_shapes()
        K = self.config.decode_steps
        thunks = []

        def window(kwargs):
            def run():
                out = self.dispatch_decode_window(
                    sh["zeros_i"], sh["pt"], sh["inactive"], sh["zeros_i"],
                    sh["temps"], sh["zeros_i"], sh["ones_f"], K, **kwargs,
                )
                jax.block_until_ready(out)  # graftlint: sync-ok warmup: compile gate, not serving traffic
            return run

        for kwargs in (
            {"penalties": sh["neutral_pen"]},
            {"want_logprobs": True},
            {"want_logprobs": True, "penalties": sh["neutral_pen"]},
        ):
            thunks.append(window(kwargs))

        def chunk(bucket, sampling, want_lp):
            def run():
                out = self.prefill_chunk(
                    np.zeros(bucket, np.int32), 0, sh["pt"][0], sample=True,
                    temperature=0.0, top_k=0, top_p=1.0, slot=-1,
                    sync=not want_lp, want_logprobs=want_lp, sampling=sampling,
                    eos_ids=(0,) if sampling is not None else None,
                )
                if want_lp:
                    jax.block_until_ready(out)  # graftlint: sync-ok warmup: compile gate, not serving traffic
            return run

        def packed(bucket, N, sampling, want_lp):
            def run():
                lane = (
                    np.zeros(bucket, np.int32), 0, sh["pt"][0], -1,
                    sampling or SamplingParams(temperature=0.0),
                    (0,) if sampling is not None else (),
                    sampling is not None,
                )
                out = self.prefill_chunk_batch([lane], N=N, want_logprobs=want_lp)
                jax.block_until_ready(out)  # graftlint: sync-ok warmup: compile gate, not serving traffic
            return run

        bucket = self.config.prefill_buckets[0]
        for sampling, want_lp in (
            (None, True),
            (SamplingParams(presence_penalty=0.1, min_tokens=1), False),
            (SamplingParams(presence_penalty=0.1, min_tokens=1), True),
        ):
            thunks.append(chunk(bucket, sampling, want_lp))
        if self.packed_prefill_mode:
            # the per-request trace is NOT dead in packed mode: disagg remote
            # prefill (run_prefill_chunks) and image-bearing requests still
            # dispatch it — compile its default per-bucket traces here
            def per_request(b):
                def run():
                    self.prefill_chunk(
                        np.zeros(b, np.int32), 0, sh["pt"][0], sample=True,
                        temperature=0.0, top_k=0, top_p=1.0, slot=-1, sync=True,
                    )
                return run

            for b in self.config.prefill_buckets:
                thunks.append(per_request(b))
        # packed-prefill executables: each power-of-two N <= lanes_for(b) per
        # bucket (the scheduler rounds partial packs up to pow2), for the
        # neutral AND feature-bearing variants (want_* are static jit args —
        # every combo is a distinct executable). Without these the first
        # packed shape cold-compiles mid-traffic — on a tunneled PJRT
        # platform that stall exceeds HTTP client timeouts.
        for b in self.config.prefill_buckets:
            lanes_max = self.config.lanes_for(b)
            n = 1
            while n <= lanes_max:
                if n > 1 and n < lanes_max:
                    thunks.append(packed(b, n, None, False))
                for sampling, want_lp in (
                    (None, True),
                    (SamplingParams(presence_penalty=0.1, min_tokens=1), False),
                    (SamplingParams(presence_penalty=0.1, min_tokens=1), True),
                ):
                    thunks.append(packed(b, n, sampling, want_lp))
                n *= 2
        # page-table ladder: wider-table variants for the traces a DEEP
        # sequence promotes into mid-serving — the default decode window and
        # the prefill bucket the depth-aware planner runs at that depth
        # (chunk_len_for shrinks chunks as context grows, so the (chunk,
        # width) pairs compiled here are the ones live traffic reaches)
        def wide_window(width):
            shw = self._warmup_shapes(table_width=width)

            def run():
                out = self.dispatch_decode_window(
                    shw["zeros_i"], shw["pt"], shw["inactive"], shw["zeros_i"],
                    shw["temps"], shw["zeros_i"], shw["ones_f"], K,
                )
                jax.block_until_ready(out)  # graftlint: sync-ok warmup: compile gate, not serving traffic
            return run

        def wide_chunk(width, b):
            def run():
                pt = np.zeros(width, np.int32)
                if self.packed_prefill_mode:
                    lane = (
                        np.zeros(b, np.int32), 0, pt, -1,
                        SamplingParams(temperature=0.0), (), False,
                    )
                    out = self.prefill_chunk_batch([lane], N=1)
                    jax.block_until_ready(out)  # graftlint: sync-ok warmup: compile gate, not serving traffic
                else:
                    self.prefill_chunk(
                        np.zeros(b, np.int32), 0, pt, sample=True,
                        temperature=0.0, top_k=0, top_p=1.0, slot=-1, sync=True,
                    )
            return run

        for w in self.config.table_buckets[1:]:
            thunks.append(wide_window(w))
            depth = (w // 2) * self.config.page_size  # where this rung starts
            thunks.append(wide_chunk(w, self.config.chunk_len_for(depth)))
        return thunks

    def extract_pages_device(self, page_ids: np.ndarray) -> jax.Array:
        """Gather KV blocks into a device array [L, 2, n, page_size, Hkv, D]
        WITHOUT a host copy — the same-pod (ICI) transfer path: the consumer
        reshards it onto its own mesh with jax.device_put, so on multi-chip
        hardware the blocks ride the interconnect, never host DRAM."""
        return self._gather_pages(self.kv_cache, jnp.asarray(page_ids, jnp.int32))

    def extract_pages(self, page_ids: np.ndarray):
        """Pull KV blocks to host: [L, 2, n, page_size, Hkv, D] numpy — or,
        with an int8 cache, the {"q", "s"} wire dict (quant/kv.py): int8
        page data plus its per-row scale plane, half the host bytes.

        The device gather runs jitted; the host copy is the DCN-transfer
        staging step (same-pod ICI transfers use extract_pages_device).
        """
        return jax.tree.map(np.asarray, jax.device_get(self.extract_pages_device(page_ids)))  # graftlint: sync-ok DCN staging: deliberate D2H export priced by kv_stream metrics

    def extract_pages_async(self, page_ids: np.ndarray):
        """Chunk-streamed export: dispatch the device gather NOW (on the
        engine thread, so it enqueues right behind the prefill chunk that
        finalized these pages) and resolve the blocking device->host copy on
        a two-worker side pool. Returns a concurrent.futures.Future of the
        host numpy array (or {"q","s"} wire dict for int8 caches).
        Double-buffered by construction: the engine thread
        is free to dispatch chunk i+1's compute while chunk i's pages drain
        to host, and at most two pulls are ever in flight."""
        dev = self.extract_pages_device(page_ids)
        pool = getattr(self, "_d2h_pool", None)
        if pool is None:
            from concurrent.futures import ThreadPoolExecutor

            pool = self._d2h_pool = ThreadPoolExecutor(
                max_workers=2, thread_name_prefix="kv-d2h"
            )
        return pool.submit(lambda: jax.tree.map(np.asarray, jax.device_get(dev)))  # graftlint: sync-ok D2H resolved on the side pool, engine thread stays free

    def inject_pages_bucketed(self, page_ids: np.ndarray, data, axis=None) -> None:
        """Scatter a PARTIAL run of pages, padded to a power-of-two id count
        (the HostKvPool.load_many trick): pad ids are out of range so the
        donated scatter drops them. Streamed KV parts and prefix restores
        arrive in arbitrary sizes; without bucketing every distinct size
        would compile its own scatter executable."""
        from dynamo_tpu.quant.kv import wire_pad

        if axis is None:
            axis = getattr(self.model, "wire_n_axis", 2)
        ids = np.asarray(page_ids, np.int32)
        n = len(ids)
        if n == 0:
            return
        bucket = 1 << (n - 1).bit_length()
        if bucket > n:
            padded = np.full(bucket, np.iinfo(np.int32).max // 2, np.int32)
            padded[:n] = ids
            ids = padded
            data = wire_pad(data, axis, bucket - n)
        self.inject_pages(ids, data)

    def inject_pages(self, page_ids: np.ndarray, data) -> None:
        """Write KV blocks received from a peer into our pages (donated
        scatter). ``data`` may be host numpy (DCN path), a device array from
        a peer engine (ICI path) — device_put reshards it onto our mesh —
        or the int8 {"q","s"} wire dict (host or device leaves). Dtype
        conversion happens inside the model's scatter_pages_wire: a
        full-precision wire block quantizes into an int8 cache and an int8
        block dequantizes into a full-precision one, so mixed-dtype disagg
        pairs stay interoperable."""
        if isinstance(data, dict):
            leaves = list(data.values())
            if any(isinstance(x, jax.Array) for x in leaves):
                ws = self.model.wire_sharding(self.mesh)
                if not isinstance(ws, dict):
                    # int8 wire from a peer into a full-precision cache
                    ws = {"q": ws, "s": NamedSharding(self.mesh, P())}
                data = jax.device_put(data, ws)
            else:
                data = {k: jnp.asarray(v) for k, v in data.items()}
        elif isinstance(data, jax.Array):
            ws = self.model.wire_sharding(self.mesh)
            if isinstance(ws, dict):
                ws = ws["q"]  # plain-array wire into an int8 cache
            data = jax.device_put(data, ws)
        else:
            data = jnp.asarray(data)
        self.kv_cache = self._scatter_pages(
            self.kv_cache, jnp.asarray(page_ids, jnp.int32), data
        )

    def hbm_stats(self) -> dict:
        """Device memory gauges: live/peak bytes summed over local devices via
        ``jax.Device.memory_stats()`` (TPU/GPU); graceful zeros on CPU, where
        the runtime reports nothing."""
        live = peak = limit = 0
        devices = 0
        for d in jax.local_devices():
            try:
                stats = d.memory_stats()
            except Exception:
                stats = None
            if not stats:
                continue
            devices += 1
            live += int(stats.get("bytes_in_use", 0))
            peak += int(stats.get("peak_bytes_in_use", stats.get("bytes_in_use", 0)))
            limit += int(stats.get("bytes_limit", 0))
        return {
            "hbm_bytes_in_use": live,
            "hbm_peak_bytes_in_use": peak,
            "hbm_bytes_limit": limit,
            "hbm_reporting_devices": devices,
        }

    def decode_steps(
        self,
        tokens: np.ndarray,  # [B]
        positions: np.ndarray,  # [B]
        page_tables: np.ndarray,  # [B, max_pages_per_seq]
        active: np.ndarray,  # [B] bool
        limits: np.ndarray,  # [B] max fed-token position per slot
        temps: np.ndarray,
        top_ks: np.ndarray,
        top_ps: np.ndarray,
        num_steps: int,
    ) -> np.ndarray:
        """Synchronous fused multi-step decode with host-provided feed tokens:
        seeds the token feedback, runs one window, returns [num_steps, B] tokens.

        Accepts any B <= max_seqs; inputs are padded to the max_seqs batch the
        window executable is compiled for (extra slots inactive)."""
        B = tokens.shape[0]
        S = self.config.max_seqs
        if B > S:
            raise ValueError(f"batch {B} exceeds max_seqs {S}")
        if B < S:
            pad = S - B
            tokens = np.concatenate([tokens, np.zeros(pad, tokens.dtype)])
            positions = np.concatenate([positions, np.zeros(pad, positions.dtype)])
            page_tables = np.concatenate(
                [page_tables, np.zeros((pad, page_tables.shape[1]), page_tables.dtype)]
            )
            active = np.concatenate([active, np.zeros(pad, bool)])
            limits = np.concatenate([limits, np.zeros(pad, limits.dtype)])
            temps = np.concatenate([temps, np.zeros(pad, temps.dtype)])
            top_ks = np.concatenate([top_ks, np.zeros(pad, top_ks.dtype)])
            top_ps = np.concatenate([top_ps, np.ones(pad, top_ps.dtype)])
        self.write_token_slots(np.arange(S, dtype=np.int32), tokens)
        toks = self.dispatch_decode_window(
            positions, page_tables, active, limits, temps, top_ks, top_ps, num_steps
        )
        return np.asarray(jax.device_get(toks))[:, :B]  # graftlint: sync-ok sync decode helper for bench/tests, not the serving loop
