"""ModelRunner: owns the device mesh, sharded params, the donated paged KV
cache, the device-resident token-feedback buffer, and the jitted
prefill/decode+sample step functions.

TPU execution notes:
  - prefill chunks are padded to config.prefill_buckets so jit caches one
    executable per bucket (static shapes, no recompiles per request)
  - the KV cache is donated on every step — XLA aliases it in place
  - sampling is fused into the step so only the sampled token ids (a few bytes)
    cross back to host per step
  - the last sampled token per slot lives in a donated device buffer
    (``tokens_dev``): a sampling prefill writes its slot's first token there,
    and decode windows read/update it on device. The host therefore never has
    to sync on a window's results before dispatching the next one — the
    scheduler runs windows dispatch-ahead and reconciles token results as they
    arrive (hides dispatch/transfer latency entirely; on tunneled PJRT
    platforms that latency is ~100 ms per round trip)
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dynamo_tpu.engine.config import EngineConfig
from dynamo_tpu.engine.sampling import sample_tokens, sample_tokens_with_logprobs
from dynamo_tpu.utils import get_logger

log = get_logger("engine.runner")


class ModelRunner:
    def __init__(
        self,
        config: EngineConfig,
        model,
        params,
        mesh: Optional[Mesh] = None,
    ):
        self.config = config
        self.model = model
        if config.sp > 1 and config.tp > 1:
            raise ValueError("sp and tp cannot both exceed 1 yet")
        if config.pp > 1:
            if config.tp > 1 or config.sp > 1:
                raise ValueError("pp is exclusive with tp/sp for now")
            if model.config.num_layers % config.pp:
                raise ValueError(
                    f"num_layers={model.config.num_layers} not divisible by pp={config.pp}"
                )
            if len(jax.devices()) < config.pp:
                raise ValueError(
                    f"pp={config.pp} but only {len(jax.devices())} devices available"
                )
            if any(b % config.pp for b in config.prefill_buckets):
                raise ValueError(
                    f"every prefill bucket must divide into pp={config.pp} microbatches"
                )
            if config.max_seqs % config.pp:
                raise ValueError(f"max_seqs must be divisible by pp={config.pp}")
        if config.sp > 1:
            if not hasattr(model, "prefill_sp"):
                raise ValueError(
                    f"model {type(model).__name__} has no sequence-parallel prefill"
                )
            if len(jax.devices()) < config.sp:
                raise ValueError(
                    f"sp={config.sp} but only {len(jax.devices())} devices available"
                )
            if not any(b % config.sp == 0 for b in config.prefill_buckets):
                raise ValueError(
                    f"sp={config.sp} divides none of prefill_buckets="
                    f"{config.prefill_buckets}; SP prefill would never engage"
                )
        if mesh is None:
            if config.pp > 1:
                devices = jax.devices()[: config.pp]
                mesh = Mesh(np.array(devices).reshape(len(devices)), ("pp",))
            elif config.sp > 1:
                devices = jax.devices()[: config.sp]
                mesh = Mesh(np.array(devices).reshape(len(devices)), ("sp",))
            else:
                devices = jax.devices()[: config.tp]
                mesh = Mesh(np.array(devices).reshape(len(devices)), ("tp",))
        self.mesh = mesh
        if config.tp > 1:
            # the Pallas decode kernel runs under shard_map on this mesh
            # (attention is head-parallel; no collectives inside)
            model.attn_mesh = mesh
        if config.pp > 1:
            # stage sharding: layer stack + layer-major KV pool split over pp
            from dynamo_tpu.parallel.pipeline import (
                stage_kv_sharding,
                stage_param_shardings,
            )

            shardings = stage_param_shardings(model, mesh)
            kv_sharding = stage_kv_sharding(mesh)
            probe = jax.eval_shape(
                lambda: model.init_kv_cache(config.num_pages, config.page_size)
            )
            if set(probe) != {"k", "v"}:
                raise ValueError(
                    "pp currently supports the k/v page-pool model families"
                )
        else:
            shardings = model.param_shardings(mesh)
            kv_sharding = model.kv_cache_sharding(mesh)
        self.params = jax.device_put(params, shardings)
        self.kv_cache = jax.device_put(
            model.init_kv_cache(config.num_pages, config.page_size), kv_sharding
        )
        self._replicated = NamedSharding(mesh, P())
        self._key = jax.random.key(0)
        # device-resident last-token-per-slot feedback buffer
        self.tokens_dev = jnp.zeros(config.max_seqs, jnp.int32)

        self._prefill = jax.jit(
            self._prefill_impl, donate_argnums=(1, 2), static_argnames=("want_lp",)
        )
        # multimodal vision encode (compiled lazily; text-only models never
        # pay for it — the mm prefill variant is _prefill traced with embeds)
        self._encode_images = jax.jit(
            lambda params, patches, rows, cols, valid: self.model.encode_images(
                params, patches, rows, cols, valid
            )
        )
        if config.sp > 1:
            # sequence-parallel whole-prompt prefill (ring attention over sp)
            self._prefill_sp = jax.jit(
                self._prefill_sp_impl, donate_argnums=(1, 2), static_argnames=("want_lp",)
            )
        self._decode_window = jax.jit(
            self._decode_window_impl, donate_argnums=(1, 2), static_argnums=(6, 7)
        )
        self._write_tokens = jax.jit(
            lambda td, idx, vals: td.at[idx].set(vals, mode="drop"),
            donate_argnums=(0,),
        )
        # block-granularity KV IO for disaggregation / offload
        # (the NIXL-slot replacement, reference: patch nixl.py register_kv_caches).
        # The model defines its canonical wire layout (llama: [L,2,n,ps,Hkv,D];
        # MLA: [L,n,ps,latent_padded]); on device the pools are flat [L*P, ...].
        L = model.config.num_layers
        Pn = config.num_pages

        def _flat_ids(ids):  # [n] logical -> [L, n] flat
            return ids[None, :] + (jnp.arange(L, dtype=jnp.int32) * Pn)[:, None]

        self._gather_pages = jax.jit(
            lambda kv, ids: model.gather_pages_wire(kv, _flat_ids(ids))
        )
        self._scatter_pages = jax.jit(
            lambda kv, ids, data: model.scatter_pages_wire(kv, _flat_ids(ids), data),
            donate_argnums=(0,),
        )

    # ---------------- jitted bodies ----------------

    def _model_prefill(self, params, kv, tokens, positions, page_table, valid, last, embeds=None, emask=None, rope_pos=None):
        """model.prefill, or its GPipe-pipelined form when pp > 1."""
        if self.config.pp > 1:
            from dynamo_tpu.parallel.pipeline import prefill_pipelined

            return prefill_pipelined(
                self.model, params, kv, tokens, positions, page_table, valid, last,
                self.mesh, input_embeds=embeds, embeds_mask=emask,
                rope_positions=rope_pos,
            )
        return self.model.prefill(
            params, kv, tokens, positions, page_table, valid, last,
            input_embeds=embeds, embeds_mask=emask, rope_positions=rope_pos,
        )

    def _model_decode(self, params, kv, tokens, positions, page_tables, active, rope_deltas=None):
        if self.config.pp > 1:
            from dynamo_tpu.parallel.pipeline import decode_pipelined

            return decode_pipelined(
                self.model, params, kv, tokens, positions, page_tables, active,
                self.mesh, rope_deltas=rope_deltas,
            )
        return self.model.decode(
            params, kv, tokens, positions, page_tables, active, rope_deltas=rope_deltas
        )

    def _prefill_impl(self, params, kv, tokens_dev, ints, flts, key, embeds=None, emask=None, rope_pos=None, want_lp=False):
        """ints [bucket + max_pages + 4] = token buf, page table, then
        (start_pos, n_real, top_k, slot); flts [2] = (temperature, top_p).
        Positions and the valid mask derive on device — one packed H2D per
        chunk. The sampled token is written into ``tokens_dev[slot]`` (slot >=
        max_seqs drops the write) so a following decode window can consume it
        without any host round trip.

        Multimodal chunks pass ``embeds`` [bucket, D] + ``emask`` [bucket]
        (a second trace of this same jit): vision-tower outputs replace the
        masked tokens' embeddings."""
        mp = self.config.max_pages_per_seq
        bucket = ints.shape[0] - mp - 4
        tokens = ints[:bucket]
        page_table = ints[bucket : bucket + mp]
        start_pos = ints[bucket + mp]
        n = ints[bucket + mp + 1]
        top_k = ints[bucket + mp + 2]
        slot = ints[bucket + mp + 3]
        positions = start_pos + jnp.arange(bucket, dtype=jnp.int32)
        valid = jnp.arange(bucket) < n
        logits, kv = self._model_prefill(
            params, kv, tokens, positions, page_table, valid, n - 1,
            embeds=embeds, emask=emask, rope_pos=rope_pos,
        )
        if want_lp:
            toks, chosen, tids, tvals = sample_tokens_with_logprobs(
                logits[None, :], key, flts[:1], top_k[None], flts[1:]
            )
            lp = (chosen[0], tids[0], tvals[0])
        else:
            # same gating as the decode window: no full-vocab log_softmax or
            # top_k in the trace unless the request asked for logprobs
            toks = sample_tokens(logits[None, :], key, flts[:1], top_k[None], flts[1:])
            lp = None
        tok = toks[0]
        tokens_dev = tokens_dev.at[slot].set(tok, mode="drop")
        return tok, lp, kv, tokens_dev

    def _prefill_sp_impl(self, params, kv, tokens_dev, ints, flts, key, want_lp=False):
        """Same packed-ints contract as _prefill_impl, but the whole-prompt
        chunk runs sequence-parallel (model.prefill_sp: ring attention over
        the sp mesh axis). Only called with start_pos == 0."""
        mp = self.config.max_pages_per_seq
        bucket = ints.shape[0] - mp - 4
        tokens = ints[:bucket]
        page_table = ints[bucket : bucket + mp]
        n = ints[bucket + mp + 1]
        top_k = ints[bucket + mp + 2]
        slot = ints[bucket + mp + 3]
        positions = jnp.arange(bucket, dtype=jnp.int32)
        valid = positions < n
        logits, kv = self.model.prefill_sp(
            params, kv, tokens, positions, page_table, valid, n - 1, mesh=self.mesh
        )
        if want_lp:
            toks, chosen, tids, tvals = sample_tokens_with_logprobs(
                logits[None, :], key, flts[:1], top_k[None], flts[1:]
            )
            lp = (chosen[0], tids[0], tvals[0])
        else:
            toks = sample_tokens(logits[None, :], key, flts[:1], top_k[None], flts[1:])
            lp = None
        tok = toks[0]
        tokens_dev = tokens_dev.at[slot].set(tok, mode="drop")
        return tok, lp, kv, tokens_dev

    def _decode_window_impl(self, params, kv, tokens_dev, ints, flts, key, num_steps, want_lp=False):
        """num_steps fused decode steps; the sampled-token feedback loop starts
        from the device-resident ``tokens_dev`` buffer, so the host can
        dispatch windows back-to-back without reading any results in between.

        All small per-slot inputs ride in two packed arrays (one H2D transfer
        each — per-call transfer latency dominates on tunneled platforms):
        ``ints`` [5 + max_pages, B] = positions, limits, active, top_ks,
        rope_deltas, then the transposed page tables; ``flts`` [2, B] =
        temps, top_ps. Page
        tables are static across the window — the host pre-allocates pages to
        cover positions + num_steps - 1 before calling, and a sequence freezes
        once its fed position would pass ``limits`` (no writes past its
        capacity)."""
        positions, limits = ints[0], ints[1]
        active = ints[2].astype(bool)
        top_ks = ints[3]
        rope_deltas = ints[4]  # M-RoPE per-slot offsets (zeros for text models)
        page_tables = ints[5:].T  # [B, max_pages]
        temps, top_ps = flts[0], flts[1]
        keys = jax.random.split(key, num_steps)

        def body(carry, k):
            kv, tokens, positions, act = carry
            logits, kv = self._model_decode(
                params, kv, tokens, positions, page_tables, act,
                rope_deltas=rope_deltas if getattr(self.model.config, "mrope_section", None) is not None else None,
            )
            if want_lp:
                toks, chosen, tids, tvals = sample_tokens_with_logprobs(
                    logits, k, temps, top_ks, top_ps
                )
                ys = (toks, chosen, tids, tvals)
            else:
                # logprobs gated out of the trace: no full-vocab log_softmax or
                # top_k rides the hot path unless some request asked for them
                toks = sample_tokens(logits, k, temps, top_ks, top_ps)
                ys = (toks,)
            tokens = jnp.where(act, toks, tokens)
            positions = positions + act.astype(positions.dtype)
            act = act & (positions <= limits)
            return (kv, tokens, positions, act), ys

        (kv, tokens, _, _), ys = jax.lax.scan(
            body, (kv, tokens_dev, positions, active), keys
        )
        all_toks = ys[0]
        lp = (ys[1], ys[2], ys[3]) if want_lp else None
        # [num_steps, B] tokens (+ ([num_steps, B], [num_steps, B, K] x2) lp)
        return all_toks, lp, kv, tokens

    # ---------------- host API (engine thread) ----------------

    def _next_key(self) -> jax.Array:
        self._key, sub = jax.random.split(self._key)
        return sub

    def prefill_chunk(
        self,
        tokens: np.ndarray,  # [n] real tokens for this chunk
        start_pos: int,
        page_table: np.ndarray,  # [max_pages_per_seq]
        sample: bool,
        temperature: float,
        top_k: int,
        top_p: float,
        slot: int = -1,  # decode slot to seed with the sampled token (device side)
        sync: bool = True,
        embeds: Optional[np.ndarray] = None,  # [n, D] mm overrides for this chunk
        embeds_mask: Optional[np.ndarray] = None,  # [n] bool
        rope_pos: Optional[np.ndarray] = None,  # [n, 3] M-RoPE positions
        want_logprobs: bool = False,  # sync=False only: also return lp arrays
    ):
        """Run one prefill chunk.

        When ``sample``: returns the sampled next token — as a host int when
        ``sync``, else as a device scalar (dispatch-ahead mode; an async
        device-to-host copy is already in flight). When ``slot >= 0`` the token
        is also written into ``tokens_dev[slot]`` on device so decode windows
        can start without waiting for the host to see it."""
        n = len(tokens)
        bucket = self.config.bucket_for(n)
        mp = self.config.max_pages_per_seq
        ints = np.zeros(bucket + mp + 4, np.int32)
        ints[:n] = tokens
        ints[bucket : bucket + mp] = page_table[:mp]
        ints[bucket + mp] = start_pos
        ints[bucket + mp + 1] = n
        ints[bucket + mp + 2] = top_k
        # out-of-bounds slot => scatter mode="drop" skips the tokens_dev write
        ints[bucket + mp + 3] = slot if (sample and slot >= 0) else self.config.max_seqs
        flts = np.array([temperature, top_p], np.float32)
        mm_args = ()
        if embeds is not None or rope_pos is not None:
            # multimodal chunk: embeds/rope-override trace of _prefill (paged
            # path only; the sp/ring path is text-only for now)
            D = embeds.shape[1] if embeds is not None else 1
            emb = np.zeros((bucket, D), np.float32)
            msk = np.zeros(bucket, bool)
            if embeds is not None:
                emb[:n] = embeds
                msk[:n] = embeds_mask
            rp = None
            if rope_pos is not None:
                rp_pad = np.zeros((bucket, 3), np.int32)
                rp_pad[:n] = rope_pos
                rp = jnp.asarray(rp_pad)
            mm_args = (jnp.asarray(emb) if embeds is not None else None,
                       jnp.asarray(msk) if embeds is not None else None,
                       rp)
        # whole-prompt chunks go sequence-parallel when configured (ring
        # attention assumes the chunk starts at position 0)
        use_sp = (
            embeds is None
            and rope_pos is None
            and self.config.sp > 1
            and start_pos == 0
            and bucket % self.config.sp == 0
        )
        prefill_fn = self._prefill_sp if use_sp else self._prefill
        tok, lp, self.kv_cache, self.tokens_dev = prefill_fn(
            self.params,
            self.kv_cache,
            self.tokens_dev,
            jnp.asarray(ints),
            jnp.asarray(flts),
            self._next_key(),
            *mm_args,
            # only the sampling (final) chunk's logprobs are ever consumed
            want_lp=want_logprobs and sample,
        )
        if not sample:
            return None
        if sync:
            return int(jax.device_get(tok))
        try:
            tok.copy_to_host_async()
            if lp is not None:
                for a in lp:
                    a.copy_to_host_async()
        except Exception:
            pass
        if want_logprobs:
            return tok, lp
        return tok

    VISION_BUCKETS = (64, 256, 1024, 4096, 16384)

    def encode_images(self, images: list) -> list[np.ndarray]:
        """Run the vision tower over each ImageInput; returns per-image
        [num_tokens, D] float32 embeddings. Patch counts pad to static buckets
        (one executable per bucket; the validity mask hides padding)."""
        out = []
        for im in images:
            n = im.patches.shape[0]
            bucket = next((b for b in self.VISION_BUCKETS if b >= n), None)
            if bucket is None:
                raise ValueError(f"image has {n} patches > max bucket")
            patches = np.zeros((bucket, im.patches.shape[1]), np.float32)
            patches[:n] = im.patches
            rows = np.zeros(bucket, np.int32)
            cols = np.zeros(bucket, np.int32)
            rows[:n] = im.rows
            cols[:n] = im.cols
            valid = np.zeros(bucket, bool)
            valid[:n] = True
            emb = self._encode_images(
                self.params,
                jnp.asarray(patches),
                jnp.asarray(rows),
                jnp.asarray(cols),
                jnp.asarray(valid),
            )
            out.append(np.asarray(jax.device_get(emb), np.float32)[: im.num_tokens])
        return out

    def write_token_slots(self, slots: np.ndarray, tokens: np.ndarray) -> None:
        """Host-known tokens (e.g. disagg adoption) -> tokens_dev[slots]."""
        self.tokens_dev = self._write_tokens(
            self.tokens_dev, jnp.asarray(slots, jnp.int32), jnp.asarray(tokens, jnp.int32)
        )

    def dispatch_decode_window(
        self,
        positions: np.ndarray,  # [B] fed-token position per slot
        page_tables: np.ndarray,  # [B, max_pages_per_seq]
        active: np.ndarray,  # [B] bool
        limits: np.ndarray,  # [B] max fed-token position per slot
        temps: np.ndarray,
        top_ks: np.ndarray,
        top_ps: np.ndarray,
        num_steps: int,
        want_logprobs: bool = False,
        rope_deltas: np.ndarray | None = None,  # [B] M-RoPE offsets
    ):
        """Dispatch one fused decode window WITHOUT waiting for results.

        Returns the [num_steps, B] device token array with an async
        device-to-host copy already started; the caller materializes it later
        (np.asarray) while further windows run on device."""
        B = positions.shape[0]
        ints = np.empty((5 + page_tables.shape[1], B), np.int32)
        ints[0] = positions
        ints[1] = limits
        ints[2] = active
        ints[3] = top_ks
        ints[4] = rope_deltas if rope_deltas is not None else 0
        ints[5:] = page_tables.T
        flts = np.stack([temps, top_ps]).astype(np.float32)
        toks, lp, self.kv_cache, self.tokens_dev = self._decode_window(
            self.params,
            self.kv_cache,
            self.tokens_dev,
            jnp.asarray(ints),
            jnp.asarray(flts),
            self._next_key(),
            num_steps,
            want_logprobs,
        )
        try:
            toks.copy_to_host_async()
            if want_logprobs:
                for a in lp:
                    a.copy_to_host_async()
        except Exception:
            pass
        return (toks, lp) if want_logprobs else toks

    def extract_pages_device(self, page_ids: np.ndarray) -> jax.Array:
        """Gather KV blocks into a device array [L, 2, n, page_size, Hkv, D]
        WITHOUT a host copy — the same-pod (ICI) transfer path: the consumer
        reshards it onto its own mesh with jax.device_put, so on multi-chip
        hardware the blocks ride the interconnect, never host DRAM."""
        return self._gather_pages(self.kv_cache, jnp.asarray(page_ids, jnp.int32))

    def extract_pages(self, page_ids: np.ndarray) -> np.ndarray:
        """Pull KV blocks to host: [L, 2, n, page_size, Hkv, D] numpy.

        The device gather runs jitted; the host copy is the DCN-transfer
        staging step (same-pod ICI transfers use extract_pages_device).
        """
        return np.asarray(jax.device_get(self.extract_pages_device(page_ids)))

    def inject_pages(self, page_ids: np.ndarray, data) -> None:
        """Write KV blocks received from a peer into our pages (donated
        scatter). ``data`` may be host numpy (DCN path) or a device array from
        a peer engine (ICI path) — device_put reshards it onto our mesh."""
        dt = jax.tree.leaves(self.kv_cache)[0].dtype
        if isinstance(data, jax.Array):
            data = jax.device_put(data, self.model.wire_sharding(self.mesh))
            data = data.astype(dt)
        else:
            data = jnp.asarray(data, dt)
        self.kv_cache = self._scatter_pages(
            self.kv_cache, jnp.asarray(page_ids, jnp.int32), data
        )

    def decode_steps(
        self,
        tokens: np.ndarray,  # [B]
        positions: np.ndarray,  # [B]
        page_tables: np.ndarray,  # [B, max_pages_per_seq]
        active: np.ndarray,  # [B] bool
        limits: np.ndarray,  # [B] max fed-token position per slot
        temps: np.ndarray,
        top_ks: np.ndarray,
        top_ps: np.ndarray,
        num_steps: int,
    ) -> np.ndarray:
        """Synchronous fused multi-step decode with host-provided feed tokens:
        seeds tokens_dev, runs one window, returns [num_steps, B] tokens.

        Accepts any B <= max_seqs; inputs are padded to the max_seqs batch the
        window executable is compiled for (extra slots inactive)."""
        B = tokens.shape[0]
        S = self.config.max_seqs
        if B > S:
            raise ValueError(f"batch {B} exceeds max_seqs {S}")
        if B < S:
            pad = S - B
            tokens = np.concatenate([tokens, np.zeros(pad, tokens.dtype)])
            positions = np.concatenate([positions, np.zeros(pad, positions.dtype)])
            page_tables = np.concatenate(
                [page_tables, np.zeros((pad, page_tables.shape[1]), page_tables.dtype)]
            )
            active = np.concatenate([active, np.zeros(pad, bool)])
            limits = np.concatenate([limits, np.zeros(pad, limits.dtype)])
            temps = np.concatenate([temps, np.zeros(pad, temps.dtype)])
            top_ks = np.concatenate([top_ks, np.zeros(pad, top_ks.dtype)])
            top_ps = np.concatenate([top_ps, np.ones(pad, top_ps.dtype)])
        self.write_token_slots(np.arange(S, dtype=np.int32), tokens)
        toks = self.dispatch_decode_window(
            positions, page_tables, active, limits, temps, top_ks, top_ps, num_steps
        )
        return np.asarray(jax.device_get(toks))[:, :B]
