"""ModelRunner: owns the device mesh, sharded params, the donated paged KV
cache, and the jitted prefill/decode+sample step functions.

TPU execution notes:
  - prefill chunks are padded to config.prefill_buckets so jit caches one
    executable per bucket (static shapes, no recompiles per request)
  - the KV cache is donated on every step — XLA aliases it in place
  - sampling is fused into the step so only the sampled token ids (a few bytes)
    cross back to host per step
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dynamo_tpu.engine.config import EngineConfig
from dynamo_tpu.engine.sampling import sample_tokens
from dynamo_tpu.utils import get_logger

log = get_logger("engine.runner")


class ModelRunner:
    def __init__(
        self,
        config: EngineConfig,
        model,
        params,
        mesh: Optional[Mesh] = None,
    ):
        self.config = config
        self.model = model
        if config.tp > 1:
            # the Pallas decode kernel is not yet shard_map-wrapped for TP;
            # GSPMD cannot partition a pallas_call, so fall back to the XLA path
            import os

            os.environ.setdefault("DYNTPU_PALLAS", "0")
        if mesh is None:
            devices = jax.devices()[: config.tp]
            mesh = Mesh(np.array(devices).reshape(len(devices)), ("tp",))
        self.mesh = mesh
        shardings = model.param_shardings(mesh)
        self.params = jax.device_put(params, shardings)
        kv_sharding = model.kv_cache_sharding(mesh)
        self.kv_cache = jax.device_put(
            model.init_kv_cache(config.num_pages, config.page_size), kv_sharding
        )
        self._replicated = NamedSharding(mesh, P())
        self._key = jax.random.key(0)

        self._prefill = jax.jit(self._prefill_impl, donate_argnums=(1,))
        self._decode_multi = jax.jit(
            self._decode_multi_impl, donate_argnums=(1,), static_argnums=(5,)
        )
        # block-granularity KV IO for disaggregation / offload
        # (the NIXL-slot replacement, reference: patch nixl.py register_kv_caches)
        self._gather_pages = jax.jit(lambda kv, ids: kv[:, :, ids])
        self._scatter_pages = jax.jit(
            lambda kv, ids, data: kv.at[:, :, ids].set(data), donate_argnums=(0,)
        )

    # ---------------- jitted bodies ----------------

    def _prefill_impl(self, params, kv, ints, flts, key):
        """ints [bucket + max_pages + 3] = token buf, page table, then
        (start_pos, n_real, top_k); flts [2] = (temperature, top_p). Positions
        and the valid mask derive on device — one packed H2D per chunk."""
        mp = self.config.max_pages_per_seq
        bucket = ints.shape[0] - mp - 3
        tokens = ints[:bucket]
        page_table = ints[bucket : bucket + mp]
        start_pos = ints[bucket + mp]
        n = ints[bucket + mp + 1]
        top_k = ints[bucket + mp + 2]
        positions = start_pos + jnp.arange(bucket, dtype=jnp.int32)
        valid = jnp.arange(bucket) < n
        logits, kv = self.model.prefill(params, kv, tokens, positions, page_table, valid, n - 1)
        tok = sample_tokens(logits[None, :], key, flts[:1], top_k[None], flts[1:])[0]
        return tok, kv

    def _decode_multi_impl(self, params, kv, ints, flts, key, num_steps):
        """num_steps fused decode steps; the sampled-token feedback loop stays
        on device (one host round-trip per num_steps tokens).

        All small per-slot inputs ride in two packed arrays (one H2D transfer
        each — per-call transfer latency dominates on tunneled platforms):
        ``ints`` [5 + max_pages, B] = tokens, positions, limits, active,
        top_ks, then the transposed page tables; ``flts`` [2, B] = temps,
        top_ps. Page tables are static across the window — the host
        pre-allocates pages to cover positions + num_steps - 1 before calling,
        and a sequence freezes once its fed position would pass ``limits``
        (no writes past its capacity)."""
        tokens, positions, limits = ints[0], ints[1], ints[2]
        active = ints[3].astype(bool)
        top_ks = ints[4]
        page_tables = ints[5:].T  # [B, max_pages]
        temps, top_ps = flts[0], flts[1]
        keys = jax.random.split(key, num_steps)

        def body(carry, k):
            kv, tokens, positions, act = carry
            logits, kv = self.model.decode(params, kv, tokens, positions, page_tables, act)
            toks = sample_tokens(logits, k, temps, top_ks, top_ps)
            tokens = jnp.where(act, toks, tokens)
            positions = positions + act.astype(positions.dtype)
            act = act & (positions <= limits)
            return (kv, tokens, positions, act), toks

        (kv, _, _, _), all_toks = jax.lax.scan(body, (kv, tokens, positions, active), keys)
        return all_toks, kv  # [num_steps, B]

    # ---------------- host API (engine thread) ----------------

    def _next_key(self) -> jax.Array:
        self._key, sub = jax.random.split(self._key)
        return sub

    def prefill_chunk(
        self,
        tokens: np.ndarray,  # [n] real tokens for this chunk
        start_pos: int,
        page_table: np.ndarray,  # [max_pages_per_seq]
        sample: bool,
        temperature: float,
        top_k: int,
        top_p: float,
    ) -> Optional[int]:
        """Run one prefill chunk; returns the sampled next token when `sample`."""
        n = len(tokens)
        bucket = self.config.bucket_for(n)
        mp = self.config.max_pages_per_seq
        ints = np.zeros(bucket + mp + 3, np.int32)
        ints[:n] = tokens
        ints[bucket : bucket + mp] = page_table[:mp]
        ints[bucket + mp] = start_pos
        ints[bucket + mp + 1] = n
        ints[bucket + mp + 2] = top_k
        flts = np.array([temperature, top_p], np.float32)
        tok, self.kv_cache = self._prefill(
            self.params,
            self.kv_cache,
            jnp.asarray(ints),
            jnp.asarray(flts),
            self._next_key(),
        )
        if sample:
            return int(jax.device_get(tok))
        return None

    def extract_pages(self, page_ids: np.ndarray) -> np.ndarray:
        """Pull KV blocks to host: [L, 2, n, page_size, Hkv, D] numpy.

        The device gather runs jitted; the host copy is the DCN-transfer
        staging step (same-pod ICI transfers skip this path).
        """
        out = self._gather_pages(self.kv_cache, jnp.asarray(page_ids, jnp.int32))
        return np.asarray(jax.device_get(out))

    def inject_pages(self, page_ids: np.ndarray, data: np.ndarray) -> None:
        """Write KV blocks received from a peer into our pages (donated scatter)."""
        self.kv_cache = self._scatter_pages(
            self.kv_cache,
            jnp.asarray(page_ids, jnp.int32),
            jnp.asarray(data, self.kv_cache.dtype),
        )

    def decode_steps(
        self,
        tokens: np.ndarray,  # [B]
        positions: np.ndarray,  # [B]
        page_tables: np.ndarray,  # [B, max_pages_per_seq]
        active: np.ndarray,  # [B] bool
        limits: np.ndarray,  # [B] max fed-token position per slot
        temps: np.ndarray,
        top_ks: np.ndarray,
        top_ps: np.ndarray,
        num_steps: int,
    ) -> np.ndarray:
        """Fused multi-step decode: returns [num_steps, B] sampled tokens."""
        B = tokens.shape[0]
        ints = np.empty((5 + page_tables.shape[1], B), np.int32)
        ints[0] = tokens
        ints[1] = positions
        ints[2] = limits
        ints[3] = active
        ints[4] = top_ks
        ints[5:] = page_tables.T
        flts = np.stack([temps, top_ps]).astype(np.float32)
        toks, self.kv_cache = self._decode_multi(
            self.params,
            self.kv_cache,
            jnp.asarray(ints),
            jnp.asarray(flts),
            self._next_key(),
            num_steps,
        )
        return np.asarray(jax.device_get(toks))
