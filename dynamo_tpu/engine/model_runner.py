"""ModelRunner: owns the device mesh, sharded params, the donated paged KV
cache, and the jitted prefill/decode+sample step functions.

TPU execution notes:
  - prefill chunks are padded to config.prefill_buckets so jit caches one
    executable per bucket (static shapes, no recompiles per request)
  - the KV cache is donated on every step — XLA aliases it in place
  - sampling is fused into the step so only the sampled token ids (a few bytes)
    cross back to host per step
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dynamo_tpu.engine.config import EngineConfig
from dynamo_tpu.engine.sampling import sample_tokens
from dynamo_tpu.utils import get_logger

log = get_logger("engine.runner")


class ModelRunner:
    def __init__(
        self,
        config: EngineConfig,
        model,
        params,
        mesh: Optional[Mesh] = None,
    ):
        self.config = config
        self.model = model
        if config.tp > 1:
            # the Pallas decode kernel is not yet shard_map-wrapped for TP;
            # GSPMD cannot partition a pallas_call, so fall back to the XLA path
            import os

            os.environ.setdefault("DYNTPU_PALLAS", "0")
        if mesh is None:
            devices = jax.devices()[: config.tp]
            mesh = Mesh(np.array(devices).reshape(len(devices)), ("tp",))
        self.mesh = mesh
        shardings = model.param_shardings(mesh)
        self.params = jax.device_put(params, shardings)
        kv_sharding = model.kv_cache_sharding(mesh)
        self.kv_cache = jax.device_put(
            model.init_kv_cache(config.num_pages, config.page_size), kv_sharding
        )
        self._replicated = NamedSharding(mesh, P())
        self._key = jax.random.key(0)

        self._prefill = jax.jit(self._prefill_impl, donate_argnums=(1,))
        self._decode = jax.jit(self._decode_impl, donate_argnums=(1,))
        # block-granularity KV IO for disaggregation / offload
        # (the NIXL-slot replacement, reference: patch nixl.py register_kv_caches)
        self._gather_pages = jax.jit(lambda kv, ids: kv[:, :, ids])
        self._scatter_pages = jax.jit(
            lambda kv, ids, data: kv.at[:, :, ids].set(data), donate_argnums=(0,)
        )

    # ---------------- jitted bodies ----------------

    def _prefill_impl(self, params, kv, tokens, positions, page_table, valid, last_idx, key, temp, top_k, top_p):
        logits, kv = self.model.prefill(params, kv, tokens, positions, page_table, valid, last_idx)
        tok = sample_tokens(logits[None, :], key, temp[None], top_k[None], top_p[None])[0]
        return tok, kv

    def _decode_impl(self, params, kv, tokens, positions, page_tables, active, key, temps, top_ks, top_ps):
        logits, kv = self.model.decode(params, kv, tokens, positions, page_tables, active)
        toks = sample_tokens(logits, key, temps, top_ks, top_ps)
        return toks, kv

    # ---------------- host API (engine thread) ----------------

    def _next_key(self) -> jax.Array:
        self._key, sub = jax.random.split(self._key)
        return sub

    def prefill_chunk(
        self,
        tokens: np.ndarray,  # [n] real tokens for this chunk
        start_pos: int,
        page_table: np.ndarray,  # [max_pages_per_seq]
        sample: bool,
        temperature: float,
        top_k: int,
        top_p: float,
    ) -> Optional[int]:
        """Run one prefill chunk; returns the sampled next token when `sample`."""
        n = len(tokens)
        bucket = self.config.bucket_for(n)
        buf = np.zeros(bucket, np.int32)
        buf[:n] = tokens
        positions = start_pos + np.arange(bucket, dtype=np.int32)
        valid = np.arange(bucket) < n
        tok, self.kv_cache = self._prefill(
            self.params,
            self.kv_cache,
            jnp.asarray(buf),
            jnp.asarray(positions),
            jnp.asarray(page_table),
            jnp.asarray(valid),
            jnp.asarray(n - 1, jnp.int32),
            self._next_key(),
            jnp.asarray(temperature, jnp.float32),
            jnp.asarray(top_k, jnp.int32),
            jnp.asarray(top_p, jnp.float32),
        )
        if sample:
            return int(jax.device_get(tok))
        return None

    def extract_pages(self, page_ids: np.ndarray) -> np.ndarray:
        """Pull KV blocks to host: [L, 2, n, page_size, Hkv, D] numpy.

        The device gather runs jitted; the host copy is the DCN-transfer
        staging step (same-pod ICI transfers skip this path).
        """
        out = self._gather_pages(self.kv_cache, jnp.asarray(page_ids, jnp.int32))
        return np.asarray(jax.device_get(out))

    def inject_pages(self, page_ids: np.ndarray, data: np.ndarray) -> None:
        """Write KV blocks received from a peer into our pages (donated scatter)."""
        self.kv_cache = self._scatter_pages(
            self.kv_cache,
            jnp.asarray(page_ids, jnp.int32),
            jnp.asarray(data, self.kv_cache.dtype),
        )

    def decode_step(
        self,
        tokens: np.ndarray,  # [B]
        positions: np.ndarray,  # [B]
        page_tables: np.ndarray,  # [B, max_pages_per_seq]
        active: np.ndarray,  # [B] bool
        temps: np.ndarray,
        top_ks: np.ndarray,
        top_ps: np.ndarray,
    ) -> np.ndarray:
        toks, self.kv_cache = self._decode(
            self.params,
            self.kv_cache,
            jnp.asarray(tokens),
            jnp.asarray(positions),
            jnp.asarray(page_tables),
            jnp.asarray(active),
            self._next_key(),
            jnp.asarray(temps),
            jnp.asarray(top_ks),
            jnp.asarray(top_ps),
        )
        return np.asarray(jax.device_get(toks))
