"""Trace replay: drive a compiled trace against a real serving surface.

Two drivers share one measurement contract:

  - ``replay_engine``: submits EngineRequests straight into an in-process
    ``AsyncJaxEngine`` at the trace's timestamps (optionally time-scaled),
    measuring the client view — TTFT at first-token arrival, per-token
    inter-arrival gaps amortized over each decode window's tokens (exactly
    how the HTTP frontend prices ITL), finish reason, token counts.
  - ``replay_http``: POSTs the same trace as streaming OpenAI completions
    against a frontend URL (token-id prompts, ``ext.ignore_eos``), measuring
    SSE chunk arrivals.

Every request produces one ``RequestOutcome`` (utils/goodput.py) stamped
with the scenario's SLO budgets; the report is ``summarize_outcomes`` plus
replay-side counters (schedule lag — how late submissions ran vs the trace
schedule — is the replay harness's own health signal: a lagging generator
under-delivers the offered load and silently flatters the system).

``ReplayMetrics`` renders the ``dynamo_replay_*`` Prometheus families
(conformance-checked via utils/prometheus._sample_surfaces).
"""

from __future__ import annotations

import asyncio
import threading
import time
from typing import Callable, Optional

from dynamo_tpu.loadgen.scenarios import ScenarioSpec
from dynamo_tpu.utils.goodput import (
    GoodputTracker,
    RequestOutcome,
    summarize_outcomes,
)
from dynamo_tpu.utils.prometheus import Histogram, render_family

# how late a submission may run behind its trace timestamp before the replay
# flags itself as lagging in the report
_LAG_BUCKETS = (0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0)


class ReplayMetrics:
    """dynamo_replay_* exposition for a replay run (thread-safe)."""

    def __init__(self):
        self._lock = threading.Lock()
        # (scenario, result) -> count; result in ok|error
        self._requests: dict = {}
        self._tokens: dict = {}  # scenario -> output tokens
        self._inflight = 0
        self.schedule_lag = Histogram(
            "dynamo_replay_schedule_lag_seconds",
            "how late each replayed submission ran vs its trace timestamp "
            "(a lagging generator under-delivers the offered load)",
            _LAG_BUCKETS,
        )
        self.max_lag_s = 0.0

    def observe_lag(self, lag_s: float) -> None:
        lag_s = max(0.0, lag_s)
        self.schedule_lag.observe(lag_s)
        with self._lock:
            self.max_lag_s = max(self.max_lag_s, lag_s)

    def submitted(self) -> None:
        with self._lock:
            self._inflight += 1

    def finished(self, scenario: str, tokens: int, error: bool) -> None:
        with self._lock:
            self._inflight -= 1
            key = (scenario, "error" if error else "ok")
            self._requests[key] = self._requests.get(key, 0) + 1
            self._tokens[scenario] = self._tokens.get(scenario, 0) + tokens

    def render_metrics(self) -> str:
        with self._lock:
            requests = sorted(self._requests.items())
            tokens = sorted(self._tokens.items())
            inflight = self._inflight
        out = render_family(
            "dynamo_replay_requests_total", "counter",
            "replayed requests by scenario and result",
            [({"scenario": sc, "result": r}, n) for (sc, r), n in requests]
            or [({"scenario": "", "result": "ok"}, 0)],
        )
        out += render_family(
            "dynamo_replay_tokens_total", "counter",
            "output tokens received by the replay client, by scenario",
            [({"scenario": sc}, n) for sc, n in tokens]
            or [({"scenario": ""}, 0)],
        )
        out += render_family(
            "dynamo_replay_inflight_requests", "gauge",
            "replayed requests currently in flight", [({}, inflight)],
        )
        out += self.schedule_lag.render()
        return out


# ---------------- trace -> engine request ----------------


def _build_image_input(image: dict, model, offset: int):
    """Deterministic ImageInput from a trace image spec: pixels from the
    recorded seed, patchified at the model's vision geometry."""
    import numpy as np

    from dynamo_tpu.llm.multimodal import (
        ImageInput,
        image_content_hash,
        patchify,
        virtual_token_ids,
    )

    vision = model.config.vision
    rng = np.random.RandomState(image["seed"])
    pixels = rng.rand(image["h"], image["w"], 3).astype(np.float32)
    patches, rows, cols, grid = patchify(
        pixels, vision.patch_size, vision.spatial_merge_size
    )
    n_tok = patches.shape[0] // vision.spatial_merge_size ** 2
    chash = image_content_hash(pixels)
    toks = virtual_token_ids(chash, n_tok, model.config.vocab_size)
    im = ImageInput(
        offset=offset, patches=patches, rows=rows, cols=cols, grid=grid,
        num_tokens=n_tok, content_hash=chash,
    )
    return im, toks


def to_engine_request(tr, engine=None):
    """TraceRequest -> EngineRequest (lazy imports: trace/scenario modules
    stay jax-free). Image specs materialize against the engine's model."""
    from dynamo_tpu.engine.sampling import SamplingParams
    from dynamo_tpu.engine.scheduler import EngineRequest

    token_ids = list(tr.token_ids)
    images = []
    if tr.image is not None:
        if engine is None or getattr(engine.model.config, "vision", None) is None:
            raise ValueError(
                f"trace request {tr.request_id} carries an image but the "
                "engine's model has no vision tower"
            )
        im, vtoks = _build_image_input(tr.image, engine.model, len(token_ids))
        token_ids = token_ids + vtoks + [1]
        images = [im]
    return EngineRequest(
        request_id=tr.request_id,
        token_ids=token_ids,
        sampling=SamplingParams(
            temperature=tr.temperature, max_tokens=tr.max_tokens,
            ignore_eos=True,  # OSL is the workload's output budget, exactly
        ),
        images=images,
        tenant=tr.tenant,
        scenario=tr.scenario,
        lora_name=tr.adapter,
    )


# ---------------- engine replay ----------------


async def replay_engine(
    engine,
    trace: list,
    spec: Optional[ScenarioSpec] = None,
    speed: float = 1.0,
    goodput: Optional[GoodputTracker] = None,
    metrics: Optional[ReplayMetrics] = None,
    request_hook: Optional[Callable] = None,
) -> dict:
    """Replay a trace against an in-process engine at its recorded
    timestamps (``speed`` > 1 compresses the schedule). ``request_hook(req,
    tr)`` may mutate each EngineRequest before submission (e.g. attach a
    fleet prefix holder). Returns the replay report."""
    metrics = metrics or ReplayMetrics()
    budgets = {}
    if spec is not None:
        budgets = {
            "ttft_budget_s": (
                spec.slo_ttft_ms / 1e3 if spec.slo_ttft_ms is not None else None
            ),
            "itl_budget_s": (
                spec.slo_itl_ms / 1e3 if spec.slo_itl_ms is not None else None
            ),
        }
    outcomes: list[RequestOutcome] = []
    cost_base = _cost_base(engine)
    t0 = time.monotonic()

    async def one(tr) -> None:
        planned = tr.at_s / speed
        delay = planned - (time.monotonic() - t0)
        if delay > 0:
            await asyncio.sleep(delay)
        metrics.observe_lag(time.monotonic() - t0 - planned)
        req = to_engine_request(tr, engine)
        if request_hook is not None:
            request_hook(req, tr)
        metrics.submitted()
        sub = time.monotonic()
        t_first = t_prev = None
        gaps: list[float] = []
        toks = cached = 0
        error, reason = False, ""
        try:
            async for batch in engine.generate_batched(req):
                now = time.monotonic()
                ntok = sum(1 for o in batch if o.token is not None)
                if ntok:
                    if t_first is None:
                        t_first = now
                    else:
                        # amortize the window gap over its tokens — the same
                        # honest per-token number the HTTP frontend reports
                        gaps.extend([(now - t_prev) / ntok] * ntok)
                    t_prev = now
                    toks += ntok
                for o in batch:
                    cached = max(cached, o.cached_tokens)
                    if o.finished:
                        reason = o.finish_reason or "stop"
                        error = reason == "error"
        except Exception:
            error, reason = True, "error"
        outcome = RequestOutcome(
            request_id=tr.request_id,
            scenario=tr.scenario,
            tenant=tr.tenant,
            adapter=tr.adapter,
            ttft_s=(t_first - sub) if t_first is not None else None,
            itl_s=tuple(gaps),
            prompt_tokens=len(req.token_ids),
            output_tokens=toks,
            cached_tokens=cached,
            duration_s=time.monotonic() - sub,
            finish_reason=reason,
            error=error,
            **budgets,
        )
        outcomes.append(outcome)
        if goodput is not None:
            goodput.observe(outcome)
        metrics.finished(tr.scenario, toks, error)

    await asyncio.gather(*(one(tr) for tr in trace))
    wall = time.monotonic() - t0
    return _report(
        spec, trace, outcomes, wall, speed, metrics,
        costs=_cost_delta(engine, cost_base),
    )


# ---------------- http replay ----------------


async def replay_http(
    base_url,
    model: str,
    trace: list,
    spec: Optional[ScenarioSpec] = None,
    speed: float = 1.0,
    goodput: Optional[GoodputTracker] = None,
    metrics: Optional[ReplayMetrics] = None,
) -> dict:
    """Replay a trace as streaming OpenAI completions against an HTTP
    frontend: token-id prompts, ``ext.ignore_eos`` for exact OSL, tenant in
    the ``x-tenant`` header, ``<model>:<adapter>`` names for LoRA requests.
    ``base_url`` may be one URL or a sequence of frontend URLs — requests
    round-robin across them by trace position, which is how the fleet
    multi-frontend scenarios drive 2+ front doors with ONE merged trace and
    get ONE fleet-wide report back. Image traces are engine-replay only (the
    HTTP image path ships real payloads, not seeds)."""
    import aiohttp

    from dynamo_tpu.llm.protocols import sse

    metrics = metrics or ReplayMetrics()
    budgets = {}
    if spec is not None:
        budgets = {
            "ttft_budget_s": (
                spec.slo_ttft_ms / 1e3 if spec.slo_ttft_ms is not None else None
            ),
            "itl_budget_s": (
                spec.slo_itl_ms / 1e3 if spec.slo_itl_ms is not None else None
            ),
        }
    outcomes: list[RequestOutcome] = []
    t0 = time.monotonic()
    url_list = [base_url] if isinstance(base_url, str) else list(base_url)
    urls = [u.rstrip("/") + "/v1/completions" for u in url_list]

    async def one(session, index, tr) -> None:
        url = urls[index % len(urls)]
        planned = tr.at_s / speed
        delay = planned - (time.monotonic() - t0)
        if delay > 0:
            await asyncio.sleep(delay)
        metrics.observe_lag(time.monotonic() - t0 - planned)
        body = {
            "model": f"{model}:{tr.adapter}" if tr.adapter else model,
            "prompt": list(tr.token_ids),
            "stream": True,
            "max_tokens": tr.max_tokens,
            "temperature": tr.temperature,
            "ext": {"ignore_eos": True},
        }
        # tags ride headers -> PreprocessedRequest -> EngineRequest, so the
        # frontend AND engine goodput planes attribute the replayed request
        headers = {"x-scenario": tr.scenario}
        if tr.tenant:
            headers["x-tenant"] = tr.tenant
        metrics.submitted()
        sub = time.monotonic()
        t_first = t_prev = None
        gaps: list[float] = []
        toks = 0
        error, reason = False, ""
        try:
            async with session.post(url, json=body, headers=headers) as resp:
                if resp.status != 200:
                    error, reason = True, f"http_{resp.status}"
                    await resp.read()
                else:
                    async for msg in sse.decode_stream(resp.content.iter_any()):
                        if msg.is_done:
                            break
                        doc = msg.json()
                        if not isinstance(doc, dict):
                            continue
                        if "error" in doc:
                            error, reason = True, "error"
                            continue
                        choice = (doc.get("choices") or [{}])[0]
                        delta = choice.get("text") or (
                            choice.get("delta") or {}
                        ).get("content")
                        now = time.monotonic()
                        if delta:
                            if t_first is None:
                                t_first = now
                            else:
                                gaps.append(now - t_prev)
                            t_prev = now
                            toks += 1
                        usage = doc.get("usage")
                        if usage and usage.get("completion_tokens"):
                            toks = max(toks, usage["completion_tokens"])
                        if choice.get("finish_reason"):
                            reason = choice["finish_reason"]
        except Exception:
            error, reason = True, "error"
        outcome = RequestOutcome(
            request_id=tr.request_id,
            scenario=tr.scenario,
            tenant=tr.tenant,
            adapter=tr.adapter,
            ttft_s=(t_first - sub) if t_first is not None else None,
            itl_s=tuple(gaps),
            prompt_tokens=len(tr.token_ids),
            output_tokens=toks,
            duration_s=time.monotonic() - sub,
            finish_reason=reason,
            error=error,
            **budgets,
        )
        outcomes.append(outcome)
        if goodput is not None:
            goodput.observe(outcome)
        metrics.finished(tr.scenario, toks, error)

    async with aiohttp.ClientSession() as session:
        await asyncio.gather(*(one(session, i, tr) for i, tr in enumerate(trace)))
    wall = time.monotonic() - t0
    return _report(spec, trace, outcomes, wall, speed, metrics)


# ---------------- reporting ----------------


def _cost_base(engine) -> dict:
    """Per-tenant (device_s, kv_byte_s) baseline off the engine's
    MeterLedger (utils/metering.py) so the report charges only THIS
    run's burn; {} when the engine has no metering plane."""
    snap_fn = getattr(engine, "cost_snapshot", None)
    if snap_fn is None:
        return {}
    base = {}
    for tenant, row in ((snap_fn() or {}).get("tenants") or {}).items():
        base[tenant] = (
            row.get("device_s") or 0.0,
            sum((row.get("kv_byte_s") or {}).values()),
        )
    return base


def _cost_delta(engine, base: dict) -> Optional[dict]:
    snap_fn = getattr(engine, "cost_snapshot", None)
    if snap_fn is None:
        return None
    delta = {}
    for tenant, row in ((snap_fn() or {}).get("tenants") or {}).items():
        d0, k0 = base.get(tenant, (0.0, 0.0))
        delta[tenant] = {
            "device_s": max(0.0, (row.get("device_s") or 0.0) - d0),
            "kv_byte_s": max(
                0.0, sum((row.get("kv_byte_s") or {}).values()) - k0
            ),
        }
    return delta or None


def _tenant_rollup(outcomes, costs=None) -> dict:
    """Per-tenant accounting rows: request/token counts with offered-load
    shares, joined (when an engine-side meter was reachable) with the run's
    measured device-ms and KV byte-second shares — the e2e surface for
    checking that measured burn tracks token share."""
    rows: dict[str, dict] = {}
    for o in outcomes:
        row = rows.setdefault(o.tenant, {
            "requests": 0, "errors": 0,
            "prompt_tokens": 0, "output_tokens": 0,
        })
        row["requests"] += 1
        row["errors"] += 1 if o.error else 0
        row["prompt_tokens"] += o.prompt_tokens
        row["output_tokens"] += o.output_tokens
    tok_total = sum(
        r["prompt_tokens"] + r["output_tokens"] for r in rows.values()
    )
    for row in rows.values():
        toks = row["prompt_tokens"] + row["output_tokens"]
        row["token_share"] = round(toks / tok_total, 4) if tok_total else 0.0
    if costs:
        dev_total = sum(c.get("device_s") or 0.0 for c in costs.values())
        kv_total = sum(c.get("kv_byte_s") or 0.0 for c in costs.values())
        for tenant, c in costs.items():
            row = rows.setdefault(tenant, {
                "requests": 0, "errors": 0, "prompt_tokens": 0,
                "output_tokens": 0, "token_share": 0.0,
            })
            dev = c.get("device_s") or 0.0
            kvb = c.get("kv_byte_s") or 0.0
            row["device_ms"] = round(1e3 * dev, 3)
            row["device_share"] = round(dev / dev_total, 4) if dev_total else 0.0
            row["kv_byte_s"] = round(kvb, 3)
            row["kv_share"] = round(kvb / kv_total, 4) if kv_total else 0.0
    return rows


def _report(spec, trace, outcomes, wall_s, speed, metrics, costs=None) -> dict:
    budgets = {}
    if spec is not None:
        budgets = {
            "ttft_budget_s": (
                spec.slo_ttft_ms / 1e3 if spec.slo_ttft_ms is not None else None
            ),
            "itl_budget_s": (
                spec.slo_itl_ms / 1e3 if spec.slo_itl_ms is not None else None
            ),
        }
    summary = summarize_outcomes(outcomes, wall_s=wall_s, **budgets)
    return {
        "scenario": spec.name if spec is not None else "",
        "speed": speed,
        "wall_s": round(wall_s, 3),
        "schedule_lag_max_s": round(metrics.max_lag_s, 4),
        **summary,
        "tenants": _tenant_rollup(outcomes, costs),
        "outcomes": [o.to_wire() for o in outcomes],
    }
