"""Deterministic trace compilation: ScenarioSpec -> replayable request list.

The determinism contract (tested): the same spec + seed compiles to a
byte-identical JSONL trace and an identical per-request schedule, on any
platform. Everything derives from one ``random.Random(seed)`` stream in a
fixed draw order; timestamps round to microseconds before serialization so
float formatting can never wobble a byte.

A ``TraceRequest`` is engine-agnostic: token ids, arrival offset, output
budget, tenant/adapter/scenario tags, and (for multimodal scenarios) a
compact image spec (seed + shape — the replay side regenerates the pixels
deterministically instead of shipping them). The same trace drives the
in-process engine or the OpenAI HTTP frontend (loadgen/replay.py).
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import math
import random
from dataclasses import dataclass
from typing import Optional

from dynamo_tpu.loadgen.scenarios import ScenarioSpec


@dataclass
class TraceRequest:
    at_s: float  # arrival offset from trace start (seconds, µs-rounded)
    request_id: str
    scenario: str
    token_ids: list
    max_tokens: int
    tenant: str = ""
    adapter: str = ""
    temperature: float = 0.0
    session: str = ""  # session group id ("" = independent request)
    # multimodal: {"seed": int, "h": int, "w": int} — the replay runner
    # regenerates the image deterministically (llm/multimodal patchify)
    image: Optional[dict] = None

    def to_json(self) -> str:
        d = dataclasses.asdict(self)
        if d["image"] is None:
            del d["image"]
        for k in ("tenant", "adapter", "session"):
            if not d[k]:
                del d[k]
        return json.dumps(d, sort_keys=True, separators=(",", ":"))

    @classmethod
    def from_json(cls, line: str) -> "TraceRequest":
        return cls(**json.loads(line))


# ---------------- arrival processes ----------------


def _rate_at(spec: ScenarioSpec, t: float) -> float:
    """Instantaneous arrival rate at offset t (the thinning envelope)."""
    if spec.arrival == "bursty":
        on = (t % spec.burst_period_s) < spec.burst_duty * spec.burst_period_s
        # scale so the duty-weighted mean stays rate_rps
        off_rate = spec.rate_rps * (1.0 - spec.burst_duty * spec.burst_factor) / max(
            1e-9, 1.0 - spec.burst_duty
        )
        return spec.rate_rps * spec.burst_factor if on else max(0.0, off_rate)
    if spec.arrival == "diurnal":
        return spec.rate_rps * (
            1.0 + spec.diurnal_amplitude
            * math.sin(2.0 * math.pi * t / spec.diurnal_period_s)
        )
    return spec.rate_rps


def _arrivals(spec: ScenarioSpec, rng: random.Random) -> list:
    """num_requests arrival offsets via Lewis thinning against the rate
    envelope (uniform spreads evenly; poisson is the constant envelope)."""
    if spec.arrival == "uniform":
        gap = 1.0 / spec.rate_rps
        return [i * gap for i in range(spec.num_requests)]
    peak = spec.rate_rps * max(
        1.0,
        spec.burst_factor if spec.arrival == "bursty" else 1.0 + spec.diurnal_amplitude,
    )
    out, t = [], 0.0
    while len(out) < spec.num_requests:
        t += rng.expovariate(peak)
        if rng.random() * peak <= _rate_at(spec, t):
            out.append(t)
    return out


# ---------------- length distributions ----------------


def _length(dist: str, mean: int, sigma: float, lo: int, hi: int,
            alpha: float, rng: random.Random) -> int:
    if dist == "fixed":
        n = mean
    elif dist == "pareto":
        # Pareto with the body anchored near the configured median
        n = int(mean * rng.paretovariate(alpha) / (2 ** (1.0 / alpha)))
    else:  # lognormal: median = mean knob, sigma controls the tail
        n = int(round(rng.lognormvariate(math.log(max(1, mean)), sigma)))
    return max(lo, min(hi, n))


def _zipf_pick(items: tuple, alpha: float, rng: random.Random):
    weights = [1.0 / (k + 1) ** alpha for k in range(len(items))]
    return rng.choices(items, weights=weights, k=1)[0]


# ---------------- compilation ----------------


def compile_trace(spec: ScenarioSpec) -> list:
    """Pure function: ScenarioSpec -> [TraceRequest] sorted by arrival.

    Draw order is fixed (arrivals first, then per-request fields in field
    order) so any spec change perturbs exactly the draws after it — and the
    same spec can never produce two different traces."""
    rng = random.Random(spec.seed)
    arrivals = _arrivals(spec, rng)
    # session shared prefixes: one sub-generator per group, seeded from the
    # main stream so group contents are independent of group count order
    prefixes = []
    for g in range(spec.session_groups):
        grng = random.Random(rng.randrange(1 << 62))
        prefixes.append(
            [grng.randrange(1, spec.vocab) for _ in range(spec.shared_prefix_len)]
        )
    if spec.session_turns > 1:
        # parked sessions take a separate branch so single-turn scenarios
        # keep their exact historical draw sequence (digest stability)
        return _compile_parked(spec, rng, arrivals, prefixes)
    out = []
    for i, at in enumerate(arrivals):
        isl = _length(spec.isl_dist, spec.isl_mean, spec.isl_sigma,
                      spec.isl_min, spec.isl_max, spec.tail_alpha, rng)
        osl = _length(spec.osl_dist, spec.osl_mean, spec.osl_sigma,
                      spec.osl_min, spec.osl_max, spec.tail_alpha, rng)
        tenant = rng.choice(spec.tenants) if spec.tenants else ""
        adapter = ""
        if spec.adapters and rng.random() >= spec.base_model_share:
            adapter = _zipf_pick(spec.adapters, spec.zipf_alpha, rng)
        session = ""
        token_ids = []
        if prefixes:
            g = rng.randrange(len(prefixes))
            session = f"s{g}"
            token_ids = list(prefixes[g])
        token_ids += [rng.randrange(1, spec.vocab) for _ in range(isl)]
        image = None
        if spec.images:
            image = {
                "seed": rng.randrange(1 << 31),
                "h": spec.image_hw[0],
                "w": spec.image_hw[1],
            }
        out.append(TraceRequest(
            at_s=round(at, 6),
            request_id=f"{spec.name}-{spec.seed}-{i:05d}",
            scenario=spec.name,
            token_ids=token_ids,
            max_tokens=osl,
            tenant=tenant,
            adapter=adapter,
            temperature=spec.temperature,
            session=session,
            image=image,
        ))
    return out


def _compile_parked(spec: ScenarioSpec, rng: random.Random,
                    arrivals: list, prefixes: list) -> list:
    """Multi-turn conversations that go cold between turns (parked
    sessions): each arrival starts a conversation of spec.session_turns
    turns. Turn k's prompt is turn k-1's full prompt plus a fresh tail —
    the conversation-history shape that makes follow-up turns pure prefix
    hits — and consecutive turns are spaced park_s seconds apart, long
    enough for the session's KV blocks to demote down the tier ladder
    (HBM -> host -> disk) before the resume measures the restore path.

    Draw order is fixed per conversation (tenant, adapter, group pick,
    then per turn: isl, osl, tail tokens, image) so the determinism
    contract holds exactly as in the single-turn branch."""
    out = []
    for c, at in enumerate(arrivals):
        tenant = rng.choice(spec.tenants) if spec.tenants else ""
        adapter = ""
        if spec.adapters and rng.random() >= spec.base_model_share:
            adapter = _zipf_pick(spec.adapters, spec.zipf_alpha, rng)
        session = f"c{c}"
        history = []
        if prefixes:
            g = rng.randrange(len(prefixes))
            session = f"s{g}-c{c}"
            history = list(prefixes[g])
        for k in range(spec.session_turns):
            isl = _length(spec.isl_dist, spec.isl_mean, spec.isl_sigma,
                          spec.isl_min, spec.isl_max, spec.tail_alpha, rng)
            osl = _length(spec.osl_dist, spec.osl_mean, spec.osl_sigma,
                          spec.osl_min, spec.osl_max, spec.tail_alpha, rng)
            history = history + [
                rng.randrange(1, spec.vocab) for _ in range(isl)
            ]
            image = None
            if spec.images:
                image = {
                    "seed": rng.randrange(1 << 31),
                    "h": spec.image_hw[0],
                    "w": spec.image_hw[1],
                }
            out.append(TraceRequest(
                at_s=round(at + k * spec.park_s, 6),
                request_id=f"{spec.name}-{spec.seed}-{c:05d}-t{k}",
                scenario=spec.name,
                token_ids=list(history),
                max_tokens=osl,
                tenant=tenant,
                adapter=adapter,
                temperature=spec.temperature,
                session=session,
                image=image,
            ))
    out.sort(key=lambda t: (t.at_s, t.request_id))
    return out


# ---------------- serialization ----------------


def dumps_jsonl(trace: list) -> str:
    """Canonical JSONL (sorted keys, compact separators, µs timestamps):
    the byte-identity surface the determinism test hashes."""
    return "".join(t.to_json() + "\n" for t in trace)


def write_jsonl(trace: list, path) -> None:
    with open(path, "w") as f:
        f.write(dumps_jsonl(trace))


def read_jsonl(path) -> list:
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                out.append(TraceRequest.from_json(line))
    return out


def trace_digest(trace: list) -> str:
    return hashlib.sha256(dumps_jsonl(trace).encode()).hexdigest()


def trace_summary(spec: ScenarioSpec, trace: list) -> dict:
    """The --dry-run report: schedule span, length percentiles, tag
    histograms, and the determinism digest."""
    from dynamo_tpu.utils.goodput import percentile

    isls = [len(t.token_ids) for t in trace]
    osls = [t.max_tokens for t in trace]
    adapters: dict = {}
    tenants: dict = {}
    for t in trace:
        if t.adapter:
            adapters[t.adapter] = adapters.get(t.adapter, 0) + 1
        if t.tenant:
            tenants[t.tenant] = tenants.get(t.tenant, 0) + 1
    return {
        "scenario": spec.name,
        "seed": spec.seed,
        "requests": len(trace),
        "span_s": round(trace[-1].at_s, 3) if trace else 0.0,
        "arrival": spec.arrival,
        "rate_rps": spec.rate_rps,
        "isl_p50": percentile(isls, 50),
        "isl_p99": percentile(isls, 99),
        "osl_p50": percentile(osls, 50),
        "osl_p99": percentile(osls, 99),
        "prompt_tokens": sum(isls),
        "output_budget_tokens": sum(osls),
        "tenants": tenants,
        "adapters": adapters,
        "sessions": len({t.session for t in trace if t.session}),
        "images": sum(1 for t in trace if t.image),
        "slo": {"ttft_ms": spec.slo_ttft_ms, "itl_p99_ms": spec.slo_itl_ms},
        "digest": trace_digest(trace),
    }
