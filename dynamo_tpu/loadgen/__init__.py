"""Trace-replay load harness: seeded scenario specs -> deterministic traces
-> replay against the in-process engine or the HTTP frontend, with
per-request SLO outcomes flowing into the goodput plane (utils/goodput.py).

    python -m dynamo_tpu.loadgen --scenario bursty_chat --dry-run
    python -m dynamo_tpu.loadgen --scenario lora_churn --out trace.jsonl

Scenario/trace modules are pure stdlib (no jax) — compiling and inspecting
traces is sub-second; only replay imports the engine.
"""

from dynamo_tpu.loadgen.scenarios import (  # noqa: F401
    BUILTIN_SCENARIOS,
    ScenarioSpec,
    load_scenario,
    load_scenarios_yaml,
)
from dynamo_tpu.loadgen.trace import (  # noqa: F401
    TraceRequest,
    compile_trace,
    dumps_jsonl,
    read_jsonl,
    trace_digest,
    trace_summary,
    write_jsonl,
)
