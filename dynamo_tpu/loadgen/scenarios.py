"""Scenario specs: parametric descriptions of "millions of users" traffic.

A ``ScenarioSpec`` is everything needed to *deterministically* compile a
replayable trace (loadgen/trace.py): an arrival process (Poisson, bursty
on/off Poisson, or a diurnal sinusoid — a scaled day), heavy-tailed ISL/OSL
sampled from parametric distributions (lognormal body, optional Pareto tail),
multi-tenant adapter churn (zipf hot/cold LoRA adapters), long-context
sessions with shared prefixes, and multimodal image requests (Qwen2-VL).

Everything here is pure stdlib — no jax, no numpy — so scenario compilation
and the ``--dry-run`` CLI stay sub-second and importable anywhere (the
determinism contract rides ``random.Random(seed)``, whose generators are
stable across platforms).

Builtin scenarios (``BUILTIN_SCENARIOS``) are the bench spine's workload
shapes; YAML/dict overrides layer on top via ``load_scenario``.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass
from typing import Optional

ARRIVALS = ("poisson", "bursty", "diurnal", "uniform")
LENGTH_DISTS = ("lognormal", "pareto", "fixed")


@dataclass(frozen=True)
class ScenarioSpec:
    """One scenario's complete, seedable description. Frozen: a spec is a
    value — compile_trace(spec) is a pure function of it."""

    name: str
    seed: int = 0
    # ---------------- arrival process ----------------
    num_requests: int = 64
    arrival: str = "poisson"  # poisson | bursty | diurnal | uniform
    rate_rps: float = 8.0  # mean arrival rate over the trace
    # bursty: on/off modulated Poisson — rate multiplies by burst_factor for
    # burst_duty of every burst_period_s (thinning keeps the MEAN at rate_rps)
    burst_factor: float = 4.0
    burst_period_s: float = 4.0
    burst_duty: float = 0.25
    # diurnal: sinusoidal rate over diurnal_period_s (a scaled "day");
    # amplitude 1.0 swings between 0 and 2x the mean
    diurnal_period_s: float = 60.0
    diurnal_amplitude: float = 0.8
    # ---------------- prompt/output lengths (heavy-tailed) ----------------
    isl_dist: str = "lognormal"
    isl_mean: int = 64  # body median, tokens
    isl_sigma: float = 0.6  # lognormal sigma (spread)
    isl_min: int = 4
    isl_max: int = 512
    osl_dist: str = "lognormal"
    osl_mean: int = 16
    osl_sigma: float = 0.5
    osl_min: int = 2
    osl_max: int = 256
    # pareto tail exponent (isl/osl_dist == "pareto"); smaller = heavier
    tail_alpha: float = 2.5
    # ---------------- multi-tenant / adapters ----------------
    tenants: tuple = ()  # e.g. ("tenant-a", "tenant-b"); uniform draw
    adapters: tuple = ()  # LoRA adapter names; zipf hot/cold draw
    zipf_alpha: float = 1.2  # adapter popularity skew (1 = mild, 2 = extreme)
    base_model_share: float = 0.0  # fraction of requests on the base model
    # ---------------- sessions / shared prefixes ----------------
    # >0: requests belong to session groups; each group shares a common
    # prefix of shared_prefix_len tokens (system prompt / document context —
    # the prefix-cache + long-context shape)
    session_groups: int = 0
    shared_prefix_len: int = 0
    # ---------------- multi-turn parked sessions ----------------
    # session_turns > 1: each of num_requests arrivals starts a CONVERSATION
    # of that many turns; turn k's prompt extends turn k-1's prompt with a
    # fresh tail (the conversation history), and consecutive turns are
    # spaced park_s seconds apart — the session goes COLD between turns, so
    # its KV blocks demote down the tier ladder (HBM -> host -> disk,
    # engine/kv_store.py) and the next turn's TTFT measures the resume path
    session_turns: int = 1
    park_s: float = 0.0
    # ---------------- multimodal ----------------
    images: bool = False  # attach one deterministic random image per request
    image_hw: tuple = (32, 32)
    # ---------------- token space ----------------
    vocab: int = 512  # prompt token ids drawn from [1, vocab)
    temperature: float = 0.0
    # ---------------- SLO budgets (the goodput verdict) ----------------
    slo_ttft_ms: Optional[float] = 2000.0
    slo_itl_ms: Optional[float] = 200.0  # budget on each request's ITL p99

    def __post_init__(self):
        if self.arrival not in ARRIVALS:
            raise ValueError(f"arrival must be one of {ARRIVALS}; got {self.arrival!r}")
        for d in (self.isl_dist, self.osl_dist):
            if d not in LENGTH_DISTS:
                raise ValueError(f"length dist must be one of {LENGTH_DISTS}; got {d!r}")
        if self.num_requests < 1:
            raise ValueError("num_requests must be >= 1")
        if self.rate_rps <= 0:
            raise ValueError("rate_rps must be > 0")
        if self.session_groups and self.shared_prefix_len <= 0:
            raise ValueError("session_groups needs shared_prefix_len > 0")
        if self.session_turns < 1:
            raise ValueError("session_turns must be >= 1")
        if self.park_s < 0:
            raise ValueError("park_s must be >= 0")
        # yaml lists arrive as lists; freeze to tuples so the spec hashes
        object.__setattr__(self, "tenants", tuple(self.tenants))
        object.__setattr__(self, "adapters", tuple(self.adapters))
        object.__setattr__(self, "image_hw", tuple(self.image_hw))

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    def replace(self, **over) -> "ScenarioSpec":
        return dataclasses.replace(self, **over)


def _spec(**kw) -> ScenarioSpec:
    return ScenarioSpec(**kw)


#: The bench spine's scenario set. Names are stable artifact keys
#: (``replay.{name}.*``); geometry scales via replace() at the call site.
BUILTIN_SCENARIOS: dict = {
    # bursty chat: on/off Poisson bursts, heavy-tailed short prompts — the
    # shape that blows ITL p99 when admission serializes prefill ahead of
    # running decodes
    "bursty_chat": _spec(
        name="bursty_chat", arrival="bursty", rate_rps=16.0, burst_factor=4.0,
        num_requests=64, isl_mean=48, isl_max=256, osl_mean=16, osl_max=64,
    ),
    # diurnal: slow sinusoidal load swing (a scaled day) — the planner's
    # scale-up/down signal shape
    "diurnal_chat": _spec(
        name="diurnal_chat", arrival="diurnal", rate_rps=8.0,
        diurnal_period_s=30.0, num_requests=64,
        isl_mean=48, isl_max=256, osl_mean=16, osl_max=64,
    ),
    # multi-tenant LoRA churn: zipf hot/cold adapters over several tenants —
    # exercises slot LRU eviction/hot-swap and the per-tenant SLO series
    "lora_churn": _spec(
        name="lora_churn", arrival="poisson", rate_rps=12.0, num_requests=48,
        tenants=("tenant-a", "tenant-b", "tenant-c"),
        adapters=("a1", "a2", "a3", "a4", "a5", "a6"),
        zipf_alpha=1.3, base_model_share=0.2,
        isl_mean=32, isl_max=128, osl_mean=12, osl_max=48,
    ),
    # long-context sessions: groups sharing a long prefix (system prompt /
    # document) with individual tails — prefix cache, table ladder, offload
    "long_context_sessions": _spec(
        name="long_context_sessions", arrival="poisson", rate_rps=4.0,
        num_requests=24, session_groups=4, shared_prefix_len=192,
        isl_mean=64, isl_sigma=0.4, isl_min=16, isl_max=256,
        osl_mean=16, osl_max=48, slo_ttft_ms=5000.0,
    ),
    # the 128K deep end (standing PR 8/11 follow-up): few, enormous prompts
    # with a shared document prefix — the page-table ladder's widest rung,
    # depth-aware chunking, and pressure-driven host offload all under the
    # SAME goodput verdict as every other scenario. Sized for the serving
    # ladder's 131072 max_model_len (isl_max leaves OSL headroom); CPU smoke
    # replays it scaled down (tests/test_loadgen.py), the driver's TPU run
    # prices it at full depth.
    "long_context_128k": _spec(
        name="long_context_128k", arrival="poisson", rate_rps=0.5,
        num_requests=6, session_groups=2, shared_prefix_len=65536,
        # isl is the per-request TAIL past the shared 64K prefix: total
        # prompt tops out at 65536 + 65024 + OSL < 131072
        isl_dist="lognormal", isl_mean=32768, isl_sigma=0.3,
        isl_min=4096, isl_max=65024,
        osl_dist="fixed", osl_mean=32, osl_max=64,
        vocab=32000, slo_ttft_ms=120000.0, slo_itl_ms=2000.0,
    ),
    # parked sessions: multi-turn conversations that go cold between turns —
    # each arrival is a conversation whose turn k prompt is turn k-1's
    # prompt plus a fresh tail, with park_s of silence in between. While
    # parked, the session's KV blocks demote HBM -> host -> disk; the
    # follow-up turn's TTFT is the cold-resume headline (bench kv_tiers)
    "parked_sessions": _spec(
        name="parked_sessions", arrival="poisson", rate_rps=2.0,
        num_requests=8, session_turns=3, park_s=20.0,
        isl_mean=48, isl_sigma=0.4, isl_min=16, isl_max=128,
        osl_dist="fixed", osl_mean=8, osl_max=16, slo_ttft_ms=8000.0,
    ),
    # multimodal: Qwen2-VL image requests (deterministic random images) —
    # the capability that had zero perf numbers before this harness
    "mm_vl": _spec(
        name="mm_vl", arrival="poisson", rate_rps=4.0, num_requests=16,
        images=True, image_hw=(16, 16), isl_dist="fixed", isl_mean=12,
        isl_max=64, osl_dist="fixed", osl_mean=8, osl_max=16,
        slo_ttft_ms=5000.0,
    ),
}


def load_scenario(name_or_spec, **overrides) -> ScenarioSpec:
    """Resolve a scenario: a builtin name, a dict (e.g. one YAML stanza),
    or a ScenarioSpec — with keyword overrides layered on top."""
    if isinstance(name_or_spec, ScenarioSpec):
        spec = name_or_spec
    elif isinstance(name_or_spec, dict):
        spec = ScenarioSpec(**name_or_spec)
    elif name_or_spec in BUILTIN_SCENARIOS:
        spec = BUILTIN_SCENARIOS[name_or_spec]
    else:
        raise ValueError(
            f"unknown scenario {name_or_spec!r} "
            f"(builtins: {sorted(BUILTIN_SCENARIOS)})"
        )
    return spec.replace(**overrides) if overrides else spec


def load_scenarios_yaml(path) -> list[ScenarioSpec]:
    """Scenario list from a YAML file: either ``scenarios: [{...}, ...]``
    stanzas (each a ScenarioSpec dict, ``scenario:`` naming a builtin base)
    or a bare list."""
    import yaml

    with open(path) as f:
        doc = yaml.safe_load(f)
    stanzas = doc.get("scenarios", doc) if isinstance(doc, dict) else doc
    if not isinstance(stanzas, list):
        raise ValueError(f"{path}: expected a scenario list")
    specs = []
    for stanza in stanzas:
        if isinstance(stanza, str):
            specs.append(load_scenario(stanza))
            continue
        stanza = dict(stanza)
        base = stanza.pop("scenario", None)
        if base is not None:
            specs.append(load_scenario(base, **stanza))
        else:
            specs.append(ScenarioSpec(**stanza))
    return specs
