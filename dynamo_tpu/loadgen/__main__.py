"""Loadgen CLI.

    # compile + inspect (no engine, no jax — sub-second; the tier-1 smoke)
    python -m dynamo_tpu.loadgen --scenario bursty_chat --dry-run

    # write the replayable trace
    python -m dynamo_tpu.loadgen --scenario lora_churn --seed 7 --out t.jsonl

    # replay against a tiny in-process engine (CPU smoke) or a frontend
    python -m dynamo_tpu.loadgen --scenario bursty_chat --replay-engine tiny
    python -m dynamo_tpu.loadgen --trace t.jsonl --replay-url http://h:8080 \
        --model tiny

    # a YAML scenario set (examples/configs/replay_smoke.yaml)
    python -m dynamo_tpu.loadgen --config examples/configs/replay_smoke.yaml \
        --dry-run
"""

from __future__ import annotations

import argparse
import json
import sys


def _specs_from_args(args) -> list:
    from dynamo_tpu.loadgen.scenarios import (
        BUILTIN_SCENARIOS,
        load_scenario,
        load_scenarios_yaml,
    )

    over = {}
    if args.seed is not None:
        over["seed"] = args.seed
    if args.num_requests is not None:
        over["num_requests"] = args.num_requests
    if args.config:
        specs = load_scenarios_yaml(args.config)
        return [s.replace(**over) if over else s for s in specs]
    names = args.scenario or sorted(BUILTIN_SCENARIOS)
    return [load_scenario(n, **over) for n in names]


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m dynamo_tpu.loadgen", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    p.add_argument("--scenario", action="append",
                   help="builtin scenario name (repeatable; default: all)")
    p.add_argument("--config", help="YAML scenario set (scenarios: [...])")
    p.add_argument("--seed", type=int, default=None)
    p.add_argument("--num-requests", type=int, default=None)
    p.add_argument("--list", action="store_true", help="list builtin scenarios")
    p.add_argument("--dry-run", action="store_true",
                   help="compile traces and print summaries; no engine, no jax")
    p.add_argument("--out", help="write the compiled trace JSONL here "
                                 "(single scenario only)")
    p.add_argument("--trace", help="replay an existing trace JSONL instead "
                                   "of compiling one")
    p.add_argument("--replay-engine", metavar="MODEL",
                   help="replay against an in-process engine on this model id")
    p.add_argument("--replay-url", metavar="URL",
                   help="replay against an OpenAI HTTP frontend")
    p.add_argument("--model", default="tiny",
                   help="model name for --replay-url requests")
    p.add_argument("--speed", type=float, default=1.0,
                   help="schedule compression factor (2 = replay 2x faster)")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="print reports as JSON instead of the table")
    args = p.parse_args(argv)

    from dynamo_tpu.loadgen.scenarios import BUILTIN_SCENARIOS

    if args.list:
        for name, spec in sorted(BUILTIN_SCENARIOS.items()):
            print(f"{name:<24} arrival={spec.arrival:<8} "
                  f"n={spec.num_requests:<4} rate={spec.rate_rps}rps "
                  f"isl~{spec.isl_mean} osl~{spec.osl_mean}"
                  f"{' images' if spec.images else ''}"
                  f"{' adapters=%d' % len(spec.adapters) if spec.adapters else ''}")
        return 0

    from dynamo_tpu.loadgen.trace import (
        compile_trace,
        read_jsonl,
        trace_summary,
        write_jsonl,
    )

    specs = _specs_from_args(args)
    if args.trace:
        traces = [(None, read_jsonl(args.trace))]
    else:
        traces = [(spec, compile_trace(spec)) for spec in specs]

    if args.out:
        if len(traces) != 1:
            print("--out needs exactly one scenario", file=sys.stderr)
            return 2
        write_jsonl(traces[0][1], args.out)
        print(f"wrote {len(traces[0][1])} requests to {args.out}")

    if args.dry_run or not (args.replay_engine or args.replay_url):
        for spec, trace in traces:
            if spec is not None:
                print(json.dumps(trace_summary(spec, trace), indent=1))
            else:
                print(json.dumps({"requests": len(trace)}, indent=1))
        return 0

    # ---------------- replay (imports jax / aiohttp lazily) ----------------
    import asyncio

    from dynamo_tpu.loadgen.replay import ReplayMetrics, replay_engine, replay_http
    from dynamo_tpu.loadgen.report import render_report
    from dynamo_tpu.utils.goodput import GoodputTracker

    async def run() -> list:
        reports = []
        metrics = ReplayMetrics()
        goodput = GoodputTracker()
        if args.replay_engine:
            from dynamo_tpu.engine.config import EngineConfig
            from dynamo_tpu.engine.engine import AsyncJaxEngine

            eng = AsyncJaxEngine(EngineConfig(model_id=args.replay_engine))
            await eng.start()
            try:
                for spec, trace in traces:
                    reports.append(await replay_engine(
                        eng, trace, spec=spec, speed=args.speed,
                        goodput=goodput, metrics=metrics,
                    ))
            finally:
                await eng.shutdown()
        else:
            for spec, trace in traces:
                reports.append(await replay_http(
                    args.replay_url, args.model, trace, spec=spec,
                    speed=args.speed, goodput=goodput, metrics=metrics,
                ))
        return reports

    reports = asyncio.run(run())
    if args.as_json:
        for r in reports:
            r = dict(r)
            r.pop("outcomes", None)
            print(json.dumps(r, indent=1))
    else:
        print(render_report(reports))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
