"""replaytop-style text report over replay results.

Pure renderer (testable without an engine): one row per scenario with the
goodput verdict, latency percentiles against their budgets, throughput, and
the replay harness's own health (schedule lag, errors). The same dict shape
the bench artifact's ``replay.{scenario}.*`` keys compress from.
"""

from __future__ import annotations


def _ms(v) -> str:
    return f"{v:.1f}" if isinstance(v, (int, float)) else "-"


def _pct(v) -> str:
    return f"{100.0 * v:.1f}%" if isinstance(v, (int, float)) else "-"


def render_report(reports: list, title: str = "replay") -> str:
    """reports: list of replay report dicts (loadgen/replay.py _report)."""
    header = (
        f"{'SCENARIO':<24} {'REQS':>5} {'ERR':>4} {'GOODPUT':>8} "
        f"{'TTFT p50/p99':>14} {'ITL p50/p99':>13} {'TOK/S':>8} "
        f"{'LAG':>7}  BUDGET(ttft/itl ms)"
    )
    lines = [f"{title} — {len(reports)} scenario(s)", "", header, "-" * len(header)]
    for r in reports:
        budget = (
            f"{_ms(r.get('ttft_budget_ms'))}/{_ms(r.get('itl_budget_ms'))}"
        )
        lines.append(
            f"{r.get('scenario', '?'):<24} {r.get('requests', 0):>5} "
            f"{r.get('errors', 0):>4} {_pct(r.get('goodput')):>8} "
            f"{_ms(r.get('ttft_p50_ms')):>6}/{_ms(r.get('ttft_p99_ms')):<7} "
            f"{_ms(r.get('itl_p50_ms')):>5}/{_ms(r.get('itl_p99_ms')):<7} "
            f"{r.get('tok_s') if r.get('tok_s') is not None else '-':>8} "
            f"{_ms(1e3 * r.get('schedule_lag_max_s', 0.0)):>7}  {budget}"
        )
        # per-tenant cost rollup rows (loadgen/replay.py _tenant_rollup):
        # shown when the run was multi-tenant or an engine meter priced it
        tenants = r.get("tenants") or {}
        metered = any("device_ms" in t for t in tenants.values())
        if len(tenants) > 1 or metered:
            for name, t in sorted(tenants.items()):
                toks = t.get("prompt_tokens", 0) + t.get("output_tokens", 0)
                lines.append(
                    f"  tenant {name or '-':<16} req={t.get('requests', 0):>4} "
                    f"tok={toks:>7} ({_pct(t.get('token_share'))}) "
                    f"dev_ms={t.get('device_ms', '-')} "
                    f"({_pct(t.get('device_share'))}) "
                    f"kv_Bs={t.get('kv_byte_s', '-')} "
                    f"({_pct(t.get('kv_share'))})"
                )
    if not reports:
        lines.append("(no scenarios replayed)")
    return "\n".join(lines)
