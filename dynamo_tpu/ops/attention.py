"""Paged attention — pure-JAX reference implementations.

The KV cache is paged: K and V each live in one **flat page pool**
``[num_layers * num_pages, page_size, num_kv_heads, head_dim]`` where layer
*l*'s physical page *p* sits at flat index ``l * num_pages + p``. A sequence's
logical block *i* maps to physical page ``page_table[i]``; because gathering
``pages[layer_offset + page_table]`` restores logical order, the flattened
context index *j* IS the token position, which keeps all masks trivially
computable under jit (static shapes, no data-dependent control flow).

Why flat (TPU note): the forward pass scans over layers with the K/V pools as
**loop carries**, so XLA performs every per-token scatter in place on the
donated buffers. Threading a per-layer ``[L, ...]`` cache through scan xs/ys
(the naive translation of a list-of-layer-tensors cache) forces XLA to
re-materialize the whole cache every step — measured 3x slower at decode on
v5e. With the flat pool nothing but the touched rows is ever written.

Page 0 of each layer (flat index ``l * num_pages``) is reserved as the
null/trash page by the allocator (dynamo_tpu/engine/page_table.py): padded
page-table entries and masked-out scatter rows all target it, so no valid data
is ever clobbered and no masked-select of old values is needed in the scatter.

Int8 KV cache (EngineConfig.kv_cache_dtype="int8"): the pools arrive as
``QuantizedPages`` (quant/kv.py) — an int8 pool plus a per-(page, token-row)
f32 scale plane. ``scatter_kv`` quantizes fresh rows on the way in (one
absmax per row; fully incremental, decode appends never requantize a page)
and ``gather_pages`` dequantizes the gathered context on the way out, so
every reference path below works unchanged. The Pallas kernels instead apply
the scales to score/prob tiles in VMEM after the int8 DMA — same algebra,
half the HBM context traffic.

The Pallas TPU kernel with the same contract lives in
dynamo_tpu/ops/pallas/paged_attention.py; this module is the semantic
reference and the CPU/test path.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from dynamo_tpu.quant.kv import QuantizedPages, quantize_kv_rows

_NEG_INF = -1e30


def scatter_kv(
    k_pages,  # [LP, ps, Hkv, D] flat pool (plain or QuantizedPages)
    v_pages,  # [LP, ps, Hkv, D]
    k_new: jnp.ndarray,  # [T, Hkv, D]
    v_new: jnp.ndarray,  # [T, Hkv, D]
    phys_pages: jnp.ndarray,  # [T] int32 flat page per row (trash page for dropped rows)
    offsets: jnp.ndarray,  # [T] int32 offset within page
):
    """Scatter new K/V rows into their physical pages.

    Unconditional: the caller routes invalid rows to a trash page (see module
    docstring), so no old-value gather/select is needed — the scatter stays a
    pure in-place write on donated buffers. Int8 pools quantize each fresh
    row here (symmetric absmax over its head values) and scatter the int8
    row + its f32 scale together.
    """
    if k_pages.ndim == 3 and k_new.ndim == 3:
        # folded pool (see LlamaConfig.kv_folded): fold the NEW rows — tiny —
        # never the pool (reshaping a donated, scatter-updated pool copies it)
        k_new = k_new.reshape(k_new.shape[0], -1)
        v_new = v_new.reshape(v_new.shape[0], -1)
    if isinstance(k_pages, QuantizedPages):
        kq, ks = quantize_kv_rows(k_new)
        vq, vs = quantize_kv_rows(v_new)
        return (
            QuantizedPages(
                k_pages.q.at[phys_pages, offsets].set(kq),
                k_pages.s.at[phys_pages, offsets].set(ks),
            ),
            QuantizedPages(
                v_pages.q.at[phys_pages, offsets].set(vq),
                v_pages.s.at[phys_pages, offsets].set(vs),
            ),
        )
    k_pages = k_pages.at[phys_pages, offsets].set(k_new)
    v_pages = v_pages.at[phys_pages, offsets].set(v_new)
    return k_pages, v_pages


def write_kv_pages(
    k_pages: jnp.ndarray,
    v_pages: jnp.ndarray,
    k_new: jnp.ndarray,
    v_new: jnp.ndarray,
    positions: jnp.ndarray,  # [T] int32 absolute positions
    page_table: jnp.ndarray,  # [max_pages] int32 flat page ids (entry 0 = trash)
    valid: jnp.ndarray,  # [T] bool — False rows are routed to page_table[0]'s layer trash
    trash_page: jnp.ndarray | int = 0,  # flat index of this layer's trash page
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Position-addressed wrapper over scatter_kv for a single sequence."""
    page_size = k_pages.shape[1]
    phys = jnp.where(valid, page_table[positions // page_size], trash_page)
    offsets = jnp.where(valid, positions % page_size, 0)
    return scatter_kv(k_pages, v_pages, k_new, v_new, phys, offsets)


def gather_pages(pages, page_table: jnp.ndarray, head_dim: int | None = None) -> jnp.ndarray:
    """[P, ps, Hkv, D] gathered by [max_pages] -> [max_pages * ps, Hkv, D].

    Folded pools ([P, ps, Hkv*D], see LlamaConfig.kv_folded) unfold here —
    the GATHERED context is small, so the reshape is cheap, unlike reshaping
    the pool itself. Int8 pools dequantize the gathered context (tiny, like
    the unfold) with their per-row scales — the reference path's analogue of
    the kernels' in-VMEM dequant."""
    max_pages = page_table.shape[0]
    ps = pages.shape[1]
    if isinstance(pages, QuantizedPages):
        g = pages.q[page_table].astype(jnp.float32)  # [max_pages, ps, ...]
        s = pages.s[page_table]  # [max_pages, ps]
        g = g * s.reshape(s.shape + (1,) * (g.ndim - 2))
    else:
        g = pages[page_table]  # [max_pages, ps, ...]
    out = g.reshape(max_pages * ps, *g.shape[2:])
    if out.ndim == 2:  # folded: [S, Hkv*D] -> [S, Hkv, D]
        if head_dim is None:
            raise ValueError("folded pages need head_dim to unfold")
        return out.reshape(out.shape[0], -1, head_dim)
    return out


def _repeat_kv(x: jnp.ndarray, num_q_heads: int) -> jnp.ndarray:
    """GQA: [S, Hkv, D] -> [S, Hq, D] by repeating each kv head for its group."""
    num_kv = x.shape[1]
    if num_kv == num_q_heads:
        return x
    group = num_q_heads // num_kv
    return jnp.repeat(x, group, axis=1)


def attention_with_positions(
    q: jnp.ndarray,  # [T, Hq, D]
    k_ctx: jnp.ndarray,  # [S, Hkv, D] in logical order (index == position)
    v_ctx: jnp.ndarray,  # [S, Hkv, D]
    q_positions: jnp.ndarray,  # [T] int32
) -> jnp.ndarray:
    """Causal attention where context index j attends iff j <= q_position[t].

    Softmax in float32; output cast back to q.dtype.
    """
    head_dim = q.shape[-1]
    k = _repeat_kv(k_ctx, q.shape[1])
    v = _repeat_kv(v_ctx, q.shape[1])
    scale = 1.0 / jnp.sqrt(jnp.float32(head_dim))
    scores = jnp.einsum("thd,shd->hts", q.astype(jnp.float32), k.astype(jnp.float32)) * scale
    ctx_idx = jnp.arange(k.shape[0], dtype=jnp.int32)
    mask = ctx_idx[None, :] <= q_positions[:, None]  # [T, S]
    scores = jnp.where(mask[None, :, :], scores, _NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("hts,shd->thd", probs, v.astype(jnp.float32))
    return out.astype(q.dtype)


def paged_prefill_attention(
    q: jnp.ndarray,  # [T, Hq, D] (padded chunk)
    k_pages: jnp.ndarray,
    v_pages: jnp.ndarray,
    page_table: jnp.ndarray,  # [max_pages]
    q_positions: jnp.ndarray,  # [T] absolute positions (pad rows: anything)
) -> jnp.ndarray:
    """Chunk attention over all cached context + self (already written to pages)."""
    D = q.shape[-1]
    k_ctx = gather_pages(k_pages, page_table, head_dim=D)
    v_ctx = gather_pages(v_pages, page_table, head_dim=D)
    return attention_with_positions(q, k_ctx, v_ctx, q_positions)


def paged_decode_attention(
    q: jnp.ndarray,  # [B, Hq, D]
    k_pages: jnp.ndarray,
    v_pages: jnp.ndarray,
    page_tables: jnp.ndarray,  # [B, max_pages]
    positions: jnp.ndarray,  # [B] the query token's absolute position
) -> jnp.ndarray:
    """Single-token-per-sequence attention for the decode batch."""
    D = q.shape[-1]

    def one(q_b, pt_b, pos_b):
        out = attention_with_positions(
            q_b[None, :, :],
            gather_pages(k_pages, pt_b, head_dim=D),
            gather_pages(v_pages, pt_b, head_dim=D),
            pos_b[None],
        )
        return out[0]

    return jax.vmap(one)(q, page_tables, positions)


def _on_tpu() -> bool:
    """True when the default backend drives real TPU hardware. The backend
    *name* is not always "tpu" (tunneled PJRT plugins register under their own
    platform name), so check the device kind too."""
    if jax.default_backend() == "tpu":
        return True
    try:
        return "TPU" in jax.devices()[0].device_kind.upper()
    except Exception:
        return False


def pallas_flag():
    """DYNTPU_PALLAS override: True (forced on; interpret off-TPU), False
    (forced off), or None (kernel-specific default)."""
    import os

    flag = os.environ.get("DYNTPU_PALLAS")
    if flag == "0":
        return False
    if flag == "1":
        return True
    return None


def use_pallas_decode(head_dim: int, num_kv_heads: int) -> bool:
    """Trace-time choice of the Pallas decode kernel.

    DYNTPU_PALLAS=1 forces on (interpret on CPU), =0 forces off; default: on
    for real TPU backends when either the head_dim is lane-aligned (128) or
    the folded-heads variant applies (head_dim < 128 with Hkv*D
    lane-aligned — TinyLlama/Qwen2-small shapes)."""
    flag = pallas_flag()
    if flag is not None:
        return flag
    if not _on_tpu():
        return False
    return head_dim % 128 == 0 or (num_kv_heads * head_dim) % 128 == 0



def _tp_shard_map(fn, mesh, in_specs, out_specs):
    """shard_map wrapper for pallas dispatchers (kernel outputs carry no vma
    info, so the replication check is disabled; handles the pre-jax-0.8
    import path)."""
    import functools

    try:
        from jax import shard_map as _sm

        sm = functools.partial(_sm, check_vma=False)
    except ImportError:  # older jax
        from jax.experimental.shard_map import shard_map as _sm_old

        sm = functools.partial(_sm_old, check_rep=False)
    return sm(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs)


def dispatch_paged_decode_attention(q, k_pages, v_pages, page_tables, positions, mesh=None):
    """Pallas kernel on TPU, pure-JAX reference elsewhere (same contract).

    With a tensor-parallel mesh the kernel runs under shard_map: attention is
    head-parallel, so each device handles its Hq/Hkv shard with no
    communication (GSPMD cannot partition a pallas_call by itself)."""
    num_kv_heads = (
        k_pages.shape[2] // q.shape[-1] if k_pages.ndim == 3 else k_pages.shape[2]
    )
    if use_pallas_decode(q.shape[-1], num_kv_heads):
        import os

        from dynamo_tpu.ops.pallas.paged_attention import (
            paged_decode_attention_pallas,
            paged_decode_attention_pallas_chunked,
            paged_decode_attention_pallas_folded,
            paged_decode_attention_pallas_grouped,
            paged_decode_attention_pallas_lookahead,
        )

        # lookahead (default): perseq's per-sequence program + double
        # buffer, plus cross-program DMA prefetch (r5 A/B: at ideal KV-read
        # bandwidth). perseq: the classic in-program-only double buffer
        # (the r4 design point; the escape hatch). chunked/grouped: kept
        # selectable for future hardware — both lost on v5e (bs 8-128,
        # ps 16-128). folded: head_dim < 128 shapes (Mosaic can't DMA-slice
        # sub-128-lane pools; heads live folded into the lane dim).
        folded = k_pages.ndim == 3
        quantized = isinstance(k_pages, QuantizedPages)
        # lookahead (default since r5): perseq + cross-program DMA
        # prefetch — measured AT the ideal KV-read bandwidth (78.9 us/call
        # vs perseq's 141 at the headline shape); falls back to perseq
        # internally when the prefetch window would blow the VMEM budget
        kernel_choice = os.environ.get("DYNTPU_DECODE_KERNEL", "lookahead")
        if quantized and kernel_choice in ("chunked", "grouped"):
            # chunked/grouped never grew int8 support (both lost the bf16
            # A/B; carrying dead scale plumbing there buys nothing) — an
            # int8 cache rides the production lookahead/perseq family
            kernel_choice = "lookahead"
        if folded or q.shape[-1] % 128 != 0:
            paged_decode_attention_pallas = paged_decode_attention_pallas_folded
        elif kernel_choice == "lookahead":
            paged_decode_attention_pallas = paged_decode_attention_pallas_lookahead
        elif kernel_choice == "chunked":
            paged_decode_attention_pallas = paged_decode_attention_pallas_chunked
        elif kernel_choice == "grouped":
            paged_decode_attention_pallas = paged_decode_attention_pallas_grouped
        interpret = not _on_tpu()
        tp = 1 if mesh is None else mesh.shape.get("tp", 1)
        if tp > 1:
            import functools

            from jax.sharding import PartitionSpec as P

            shard_lanes_ok = (
                not folded
                or (num_kv_heads % tp == 0
                    and (num_kv_heads // tp) * q.shape[-1] % 128 == 0)
            )
            if q.shape[1] % tp or num_kv_heads % tp or not shard_lanes_ok:
                # per-shard folded lanes must stay 128-aligned or the shard
                # kernel would face the very sub-128 pool this path avoids
                return paged_decode_attention(q, k_pages, v_pages, page_tables, positions)
            pool_spec = P(None, None, "tp") if folded else P(None, None, "tp", None)
            if quantized:
                # int8 pool shards like the bf16 pool; the per-row scale
                # plane is head-independent, so it replicates over tp
                pool_spec = QuantizedPages(pool_spec, P(None, None))
            fn = functools.partial(paged_decode_attention_pallas, interpret=interpret)
            return _tp_shard_map(
                fn,
                mesh,
                in_specs=(
                    P(None, "tp", None),  # q: heads sharded
                    pool_spec,  # k pages: kv heads sharded
                    pool_spec,  # v pages
                    P(None, None),  # page tables replicated
                    P(None),  # positions replicated
                ),
                out_specs=P(None, "tp", None),
            )(q, k_pages, v_pages, page_tables, positions)
        return paged_decode_attention_pallas(
            q, k_pages, v_pages, page_tables, positions, interpret=interpret
        )
    return paged_decode_attention(q, k_pages, v_pages, page_tables, positions)


def use_pallas_prefill(head_dim: int, chunk_len: int, block_q: int = 128) -> bool:
    """Trace-time choice of the Pallas prefill kernel: DYNTPU_PALLAS override,
    else on for real TPU with lane-aligned head_dim and block-divisible
    chunks (buckets are multiples of 128 in practice)."""
    if chunk_len % block_q:
        return False
    flag = pallas_flag()
    if flag is not None:
        return flag
    return _on_tpu() and head_dim % 128 == 0


def prefill_kernel_lookahead() -> bool:
    """DYNTPU_PREFILL_KERNEL: "lookahead" (default — cross-program context-
    tile prefetch, the decode lookahead insight ported to the flash prefill
    grid) or "basic" (the in-program-only double buffer; escape hatch)."""
    import os

    return os.environ.get("DYNTPU_PREFILL_KERNEL", "lookahead") != "basic"


def dispatch_paged_prefill_attention(
    q, k_pages, v_pages, page_table, positions, mesh=None
):
    """Chunked-prefill attention: Pallas flash kernel on TPU (context pages
    streamed HBM->VMEM in double-buffered tiles — with the next query
    block's tiles prefetched ACROSS grid programs by default, see
    prefill_attention.py _kernel_lookahead — online softmax, causal work
    bound per query block), gather-based pure-JAX reference elsewhere. Int8
    pools (QuantizedPages) ride the same kernels with scale rows DMA'd next
    to the pages. Under tensor parallelism the kernel runs per-head-shard
    via shard_map like the decode kernel.

    Kernel precondition (stricter than the reference): ``positions`` must be
    UNIT-STRIDE within the chunk (positions[i] = positions[0] + i), which is
    exactly what the engine's bucket-padded chunks provide. The reference
    path only needs monotone positions."""
    import functools

    from jax.sharding import PartitionSpec as P

    quantized = isinstance(k_pages, QuantizedPages)
    if k_pages.ndim == 3:
        # folded pool (sub-128 head_dim): dedicated folded flash kernel when
        # shapes allow (R = block_q * Hq rows must stay VMEM-sane); the
        # gather reference (which unfolds the small gathered context) covers
        # the rest
        tp = 1 if mesh is None else mesh.shape.get("tp", 1)
        block_q = 64
        R = q.shape[1] * block_q  # folded row count per query block
        F = k_pages.shape[2]
        num_kv_heads = F // q.shape[-1]
        # the kernel's working set is several [R, F] f32 buffers; keep their
        # sum inside the ~16MB scoped-VMEM limit (R*F*4B*~5 buffers)
        shape_ok = (
            q.shape[0] % block_q == 0
            and F % 128 == 0
            and R * F * 4 * 5 <= 12 * 1024 * 1024
        )
        # tp>1: the folded kernel runs per head shard under shard_map (the
        # decode kernel's pattern — it used to silently fall back to the
        # gather reference here). The shard's folded lanes must stay
        # 128-aligned or the shard kernel would face the very sub-128 pool
        # this layout exists to avoid.
        shard_ok = tp == 1 or (
            q.shape[1] % tp == 0
            and num_kv_heads % tp == 0
            and (num_kv_heads // tp) * q.shape[-1] % 128 == 0
        )
        flag = pallas_flag()
        folded_ok = shard_ok and shape_ok and (
            flag is True or (_on_tpu() and flag is not False)
        )
        if folded_ok:
            from dynamo_tpu.ops.pallas.prefill_attention import (
                paged_prefill_attention_pallas_folded,
            )

            fn = functools.partial(
                paged_prefill_attention_pallas_folded, block_q=block_q,
                interpret=not _on_tpu(),
            )
            if tp > 1:
                pool_spec = P(None, None, "tp")
                if quantized:
                    pool_spec = QuantizedPages(pool_spec, P(None, None))
                return _tp_shard_map(
                    fn,
                    mesh,
                    in_specs=(
                        P(None, "tp", None),  # q: heads sharded
                        pool_spec,  # folded pools: lane (head-major) sharded
                        pool_spec,
                        P(None),  # page table replicated
                        P(None),  # positions replicated
                    ),
                    out_specs=P(None, "tp", None),
                )(q, k_pages, v_pages, page_table, positions)
            return fn(q, k_pages, v_pages, page_table, positions)
        return paged_prefill_attention(q, k_pages, v_pages, page_table, positions)
    if use_pallas_prefill(q.shape[-1], q.shape[0]):
        from dynamo_tpu.ops.pallas.prefill_attention import (
            paged_prefill_attention_pallas,
        )

        interpret = not _on_tpu()
        lookahead = prefill_kernel_lookahead()
        tp = 1 if mesh is None else mesh.shape.get("tp", 1)
        if tp > 1:
            if q.shape[1] % tp or k_pages.shape[2] % tp:
                return paged_prefill_attention(q, k_pages, v_pages, page_table, positions)
            pool_spec = P(None, None, "tp", None)
            if quantized:
                pool_spec = QuantizedPages(pool_spec, P(None, None))
            fn = functools.partial(
                paged_prefill_attention_pallas, interpret=interpret,
                lookahead=lookahead,
            )
            return _tp_shard_map(
                fn,
                mesh,
                in_specs=(
                    P(None, "tp", None),
                    pool_spec,
                    pool_spec,
                    P(None),
                    P(None),
                ),
                out_specs=P(None, "tp", None),
            )(q, k_pages, v_pages, page_table, positions)
        return paged_prefill_attention_pallas(
            q, k_pages, v_pages, page_table, positions, interpret=interpret,
            lookahead=lookahead,
        )
    return paged_prefill_attention(q, k_pages, v_pages, page_table, positions)
