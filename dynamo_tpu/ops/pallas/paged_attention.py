"""Pallas TPU kernel: paged decode attention.

One query token per sequence attends over its paged KV context. The page
table rides in as scalar-prefetch (available before the kernel body, so page
DMAs can be issued from dynamic indices), K/V page pools stay in HBM, and
pages stream through a double-buffered VMEM scratch overlapping DMA with
compute (pallas_guide.md: PrefetchScalarGridSpec + double buffering).

Contract matches the pure-JAX reference (dynamo_tpu/ops/attention.py
paged_decode_attention): q [B, Hq, D], pages [P, ps, Hkv, D],
page_tables [B, max_pages], positions [B] (query position; context length =
position + 1). GQA folded as [Hkv, G, D] per-kv-head batched matmuls.

Kernel A/B record (v5e-1, bench headline geometry B=64 Hq16/Hkv8 D128
ps=128 ctx=256, 24-layer chained-scan harness, best-of-4 wall time with the
tunnel RTT cancelled; round 4):

    perseq (r4's default)             4.32 ms/step   <- r4 production
    perseq at ps=256 (1 page/seq)     5.22 ms/step   (no DMA/compute overlap)
    grouped ps=128 / ps=256          12.06 / 11.35 ms/step
    chunked                          12.76 ms/step
    fused-KV row-flat "m1" proto     10.55 ms/step
    fused-KV row-flat grouped/chunk  11.1-12.0 ms/step
    fused-KV [P,2ps,...] proto       17.2-21.6 ms/step

The round-3 fused-pool prototypes (tools/proto_flatfused.py,
tools/proto_fused2.py — deleted in round 4) were 2.4-5x SLOWER than perseq
despite issuing half the DMAs: the [ps, Hkv, D] leading-index page DMA that
perseq issues is the layout Mosaic moves fastest, and the one-page-ahead
double buffer already hides the latency the fused variants try to batch
away.

Round 4 also falsified the "per-grid-program overhead" theory with two more
prototypes (deleted after measurement): a vectorized-group kernel (batched
dot_general over g sequences — Mosaic's tpu.matmul supports only ONE batch
dim, and the merged-dim shape casts that would collapse (g, Hkv) are
rejected by infer-vector-layout) and a concat-context kernel (g sequences'
pages in one row-contiguous scratch, one [Hkv, g*G, g*ps] matmul with
block-diagonal masks; fully Mosaic-legal). The concat variant measured
10.9 (g=2) and 9.8 (g=4) ms/step — still 2.3x worse than perseq. The
correct mental model: Mosaic pipelines ACROSS grid programs, so B
one-sequence programs overlap each other's DMAs and compute for free;
any within-program grouping trades that away for a serialized group body.

Round 5 re-measured with a corrected harness (tools/profile_attn.py now
DIFFERENCES two chained-scan lengths — a single wall/N division leaves the
~100 ms tunnel dispatch RTT in every number and had inflated the r4 record
by the RTT share) and settled the floor question with a null-hypothesis
kernel (same grid, same 2-page double-buffered DMA stream, NO attention
math):

    dmaonly (null)       100.6 us/call    2.41 ms/step   <- measured floor
    pure KV-read ideal    81.9 us/call    1.97 ms/step   (819 GB/s)
    perseq               149.6 us/call    3.59 ms/step   <- production
    perseq bf16-no-cast  402.9 us/call    9.67 ms/step   (2.7x SLOWER)
    chunked / grouped    477.7 / 459.3 us/call

Conclusions: (1) the DMA stream itself runs at 81% of ideal HBM bandwidth —
the floor claim is PROVEN by measurement, not prose; (2) perseq carries
~49 us/call of compute not hidden under DMA (1.49x the measured floor, not
the 2x the r4 wall/N numbers suggested); (3) dropping the f32 casts makes
the kernel 2.7x SLOWER — Mosaic relayouts ([ps,Hkv,D]->[Hkv,ps,D]) are far
cheaper in 32-bit than bf16, so the casts this kernel carries are
load-bearing, and the no-transpose dot_general variants (batch dim in K's
middle position) are Mosaic-illegal outright (tpu.matmul requires leading
batch dims).

The r5 finding that DID pay: the gap between perseq and the floor is the
per-program DMA-latency exposure at every grid-program boundary, and the
page table being scalar-prefetched means program b can issue program b+1's
DMAs — see _kernel_lookahead below:

    lookahead (r5 default)        78.9 us/call    1.89 ms/step

Measured numerically exact, BELOW the null kernel (the boundary latency it
removes also bounds dmaonly), and worth +14.7%% end-to-end on the serving
headline (6338 -> 7270 tok/s same session, engine bench).

Int8 KV (r6, quant/kv.py QuantizedPages): perseq, lookahead, and folded
accept int8 pools plus a per-row f32 scale plane ([P, 1, ps] as passed in).
Scale rows ride their own tiny DMAs beside the page DMAs (the HBM context
stream halves — that is the win) and dequantization is applied to the
score/prob tiles in VMEM: ``scores *= k_s`` / ``probs *= v_s`` is the exact
per-column algebra, and both are lane-axis broadcasts (Mosaic-legal; no
sub-128 minor-dim reshapes). chunked/grouped stay bf16-only — they already
lost the A/B and the dispatcher never routes int8 to them.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from dynamo_tpu.quant.kv import QuantizedPages

_NEG_INF = -1e30


def _decode_unpack_pools(k_pages, v_pages):
    """(k, v, k_scale [P,1,ps] | None, v_scale | None, quantized)."""
    if isinstance(k_pages, QuantizedPages):
        P, ps = k_pages.s.shape
        return (
            k_pages.q, v_pages.q,
            k_pages.s.reshape(P, 1, ps), v_pages.s.reshape(P, 1, ps),
            True,
        )
    return k_pages, v_pages, None, None, False


def _kernel(
    *refs,
    page_size: int,
    max_pages: int,
    quantized: bool = False,
):
    """perseq decode kernel (one sequence per grid program, in-program
    double buffer). refs: page_tables [B, max_pages] + lengths [B] (SMEM
    scalar prefetch) | q [1, Hq, D], k/v pools [P, ps, Hkv, D] HBM
    [, k/v scale planes [P, 1, ps]] | out [1, Hq, D] | k/v scratch
    [2, ps, Hkv, D] [, scale scratch [2, 1, ps]], sems [2, 2|4]."""
    if quantized:
        (page_tables_ref, lengths_ref, q_ref, k_hbm, v_hbm, ks_hbm, vs_hbm,
         out_ref, k_scratch, v_scratch, ks_scratch, vs_scratch, sems) = refs
        pools = [(k_hbm, k_scratch), (v_hbm, v_scratch),
                 (ks_hbm, ks_scratch), (vs_hbm, vs_scratch)]
    else:
        (page_tables_ref, lengths_ref, q_ref, k_hbm, v_hbm,
         out_ref, k_scratch, v_scratch, sems) = refs
        pools = [(k_hbm, k_scratch), (v_hbm, v_scratch)]

    b = pl.program_id(0)
    length = lengths_ref[b]
    n_pages = jnp.maximum(1, pl.cdiv(length, page_size))

    Hq, D = q_ref.shape[1], q_ref.shape[2]
    Hkv = k_hbm.shape[2]
    G = Hq // Hkv

    q = q_ref[0].astype(jnp.float32).reshape(Hkv, G, D)
    scale = 1.0 / jnp.sqrt(jnp.float32(D))

    def dma(slot, i, c):
        hbm, scratch = pools[c]
        return pltpu.make_async_copy(
            hbm.at[page_tables_ref[b, i]], scratch.at[slot], sems.at[slot, c]
        )

    # warm up buffer 0
    for c in range(len(pools)):
        dma(0, 0, c).start()

    def body(i, carry):
        m, l, acc = carry
        slot = jax.lax.rem(i, 2)
        next_slot = jax.lax.rem(i + 1, 2)

        @pl.when(i + 1 < n_pages)
        def _():
            for c in range(len(pools)):
                dma(next_slot, i + 1, c).start()

        for c in range(len(pools)):
            dma(slot, i, c).wait()

        k_page = k_scratch[slot].astype(jnp.float32)  # [ps, Hkv, D]
        v_page = v_scratch[slot].astype(jnp.float32)
        kt = jnp.transpose(k_page, (1, 0, 2))  # [Hkv, ps, D]
        vt = jnp.transpose(v_page, (1, 0, 2))

        # [Hkv, G, ps] = [Hkv, G, D] x [Hkv, ps, D]
        scores = jax.lax.dot_general(
            q, kt, (((2,), (2,)), ((0,), (0,))), preferred_element_type=jnp.float32
        ) * scale
        if quantized:
            # per-row K scales multiply score COLUMNS: [1, ps] -> [1, 1, ps]
            scores = scores * ks_scratch[slot][None]

        idx = i * page_size + jax.lax.broadcasted_iota(jnp.int32, (1, 1, page_size), 2)
        scores = jnp.where(idx < length, scores, _NEG_INF)

        chunk_max = jnp.max(scores, axis=-1)  # [Hkv, G]
        new_m = jnp.maximum(m, chunk_max)
        corr = jnp.exp(m - new_m)
        probs = jnp.exp(scores - new_m[..., None])  # [Hkv, G, ps]
        new_l = l * corr + jnp.sum(probs, axis=-1)
        if quantized:
            probs = probs * vs_scratch[slot][None]  # V scales fold into probs
        # [Hkv, G, D] = [Hkv, G, ps] x [Hkv, ps, D]
        chunk_out = jax.lax.dot_general(
            probs, vt, (((2,), (1,)), ((0,), (0,))), preferred_element_type=jnp.float32
        )
        new_acc = acc * corr[..., None] + chunk_out
        return new_m, new_l, new_acc

    m0 = jnp.full((Hkv, G), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((Hkv, G), jnp.float32)
    acc0 = jnp.zeros((Hkv, G, D), jnp.float32)
    m, l, acc = jax.lax.fori_loop(0, n_pages, body, (m0, l0, acc0))

    out = acc / jnp.maximum(l, 1e-20)[..., None]
    out_ref[0] = out.reshape(Hq, D).astype(out_ref.dtype)


def _kernel_grouped(
    # scalar prefetch
    page_tables_ref,  # [B, max_pages] SMEM
    lengths_ref,  # [B] SMEM
    # inputs
    q_ref,  # [Gq, Hq, D] VMEM (this group's queries)
    k_hbm,  # [P, ps, Hkv, D] HBM
    v_hbm,  # [P, ps, Hkv, D] HBM
    # output
    out_ref,  # [Gq, Hq, D] VMEM
    # scratch
    k_scratch,  # [2, Gq, ps, Hkv, D] VMEM
    v_scratch,  # [2, Gq, ps, Hkv, D] VMEM
    sems,  # DMA sems [2, Gq, 2]
    *,
    page_size: int,
    group: int,
):
    """Gq sequences per grid program: page index walks the whole group at
    once (2*Gq outstanding DMAs per iteration) and the per-program fixed cost
    amortizes across the group — the winning regime once pages are large
    (few pages/seq, per-PROGRAM overhead dominates the per-seq kernel)."""
    g0 = pl.program_id(0) * group
    Hq, D = q_ref.shape[1], q_ref.shape[2]
    Hkv = k_hbm.shape[2]
    G = Hq // Hkv

    lengths = [lengths_ref[g0 + j] for j in range(group)]
    n_pages = [jnp.maximum(1, pl.cdiv(lengths[j], page_size)) for j in range(group)]
    max_n = n_pages[0]
    for j in range(1, group):
        max_n = jnp.maximum(max_n, n_pages[j])

    qs = [q_ref[j].reshape(Hkv, G, D) for j in range(group)]
    scale = 1.0 / jnp.sqrt(jnp.float32(D))

    def dma(slot, j, i, which):
        hbm, scratch = (k_hbm, k_scratch) if which == 0 else (v_hbm, v_scratch)
        return pltpu.make_async_copy(
            hbm.at[page_tables_ref[g0 + j, i]],
            scratch.at[slot, j],
            sems.at[slot, j, which],
        )

    def start_all(slot, i):
        for j in range(group):  # static unroll
            @pl.when(i < n_pages[j])
            def _(j=j):
                dma(slot, j, i, 0).start()
                dma(slot, j, i, 1).start()

    def wait_all(slot, i):
        for j in range(group):
            @pl.when(i < n_pages[j])
            def _(j=j):
                dma(slot, j, i, 0).wait()
                dma(slot, j, i, 1).wait()

    start_all(0, 0)

    def body(i, carry):
        m, l, acc = carry  # [group, Hkv, G], [group, Hkv, G], [group, Hkv, G, D]
        slot = jax.lax.rem(i, 2)
        next_slot = jax.lax.rem(i + 1, 2)

        @pl.when(i + 1 < max_n)
        def _():
            start_all(next_slot, i + 1)

        wait_all(slot, i)

        idx = i * page_size + jax.lax.broadcasted_iota(jnp.int32, (1, 1, page_size), 2)
        vidx = i * page_size + jax.lax.broadcasted_iota(
            jnp.int32, (1, page_size, 1), 1
        )
        ms, ls, accs = [], [], []
        for j in range(group):
            kt = jnp.transpose(k_scratch[slot, j], (1, 0, 2))  # [Hkv, ps, D] bf16
            vt = jnp.transpose(v_scratch[slot, j], (1, 0, 2))
            scores = jax.lax.dot_general(
                qs[j], kt, (((2,), (2,)), ((0,), (0,))),
                preferred_element_type=jnp.float32,
            ) * scale
            # beyond-length/stale rows: mask K scores outright and zero V so
            # 0-weight garbage (or uninitialized first-call VMEM) can't
            # poison acc via 0 * NaN
            scores = jnp.where(idx < lengths[j], scores, _NEG_INF)
            vt = jnp.where(vidx < lengths[j], vt, 0)

            chunk_max = jnp.max(scores, axis=-1)
            new_m = jnp.maximum(m[j], chunk_max)
            corr = jnp.exp(m[j] - new_m)
            probs = jnp.exp(scores - new_m[..., None])
            new_l = l[j] * corr + jnp.sum(probs, axis=-1)
            chunk_out = jax.lax.dot_general(
                probs.astype(kt.dtype), vt, (((2,), (1,)), ((0,), (0,))),
                preferred_element_type=jnp.float32,
            )
            ms.append(new_m)
            ls.append(new_l)
            accs.append(acc[j] * corr[..., None] + chunk_out)
        return jnp.stack(ms), jnp.stack(ls), jnp.stack(accs)

    m0 = jnp.full((group, Hkv, G), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((group, Hkv, G), jnp.float32)
    acc0 = jnp.zeros((group, Hkv, G, D), jnp.float32)
    m, l, acc = jax.lax.fori_loop(0, max_n, body, (m0, l0, acc0))

    out = acc / jnp.maximum(l, 1e-20)[..., None]
    out_ref[...] = out.reshape(group, Hq, D).astype(out_ref.dtype)


@functools.partial(jax.jit, static_argnames=("interpret",))
def paged_decode_attention_pallas_grouped(
    q: jnp.ndarray,  # [B, Hq, D]
    k_pages: jnp.ndarray,  # [P, ps, Hkv, D]
    v_pages: jnp.ndarray,
    page_tables: jnp.ndarray,  # [B, max_pages] int32
    positions: jnp.ndarray,  # [B] int32 query positions
    interpret: bool = False,
) -> jnp.ndarray:
    B, Hq, D = q.shape
    P, ps, Hkv, _ = k_pages.shape
    lengths = positions.astype(jnp.int32) + 1
    # largest group that divides B AND keeps the double-buffered K+V scratch
    # within a conservative VMEM budget (v5e scoped limit is ~16MB)
    bytes_per_seq = 2 * 2 * ps * Hkv * D * k_pages.dtype.itemsize  # 2 slots x k+v
    group = 1
    for cand in (8, 4, 2):
        if B % cand == 0 and cand * bytes_per_seq <= 8 * 1024 * 1024:
            group = cand
            break

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B // group,),
        in_specs=[
            pl.BlockSpec((group, Hq, D), lambda b, *_: (b, 0, 0)),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=pl.BlockSpec((group, Hq, D), lambda b, *_: (b, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((2, group, ps, Hkv, D), k_pages.dtype),
            pltpu.VMEM((2, group, ps, Hkv, D), v_pages.dtype),
            pltpu.SemaphoreType.DMA((2, group, 2)),
        ],
    )
    kernel = pl.pallas_call(
        functools.partial(_kernel_grouped, page_size=ps, group=group),
        out_shape=jax.ShapeDtypeStruct((B, Hq, D), q.dtype),
        grid_spec=grid_spec,
        interpret=interpret,
    )
    return kernel(page_tables.astype(jnp.int32), lengths, q, k_pages, v_pages)


def _kernel_lookahead(
    *refs,
    page_size: int,
    lookahead: int,
    quantized: bool = False,
):
    """perseq with CROSS-PROGRAM DMA pipelining (r5 A/B: 78.9 us/call vs
    perseq's 141 at the headline shape — below even the dmaonly null kernel,
    i.e. at ideal KV-read bandwidth).

    Grid programs execute serially on the core, and scratch PERSISTS across
    them; the page table is scalar-prefetched, so program b can issue program
    b+1's first ``lookahead`` page DMAs into the opposite parity's slot pair
    while it computes on its own pages (prefetched by b-1). The per-program
    DMA-latency exposure at every program boundary — the entire gap between
    perseq and the measured DMA floor — collapses to one program's worth for
    the whole grid. Pages >= lookahead (long contexts) stream through the
    classic in-program double buffer.

    refs: page_tables + lengths (scalar prefetch) | q, k/v pools [, k/v
    scale planes [P, 1, ps]] | out | k_pre, v_pre [2, W, ps, Hkv, D]
    [, scale windows [2, W, 1, ps]], k_tail, v_tail [2, ps, Hkv, D]
    [, scale tails [2, 1, ps]], sems_pre [2, W, 2|4], sems_tail [2, 2|4]."""
    if quantized:
        (page_tables_ref, lengths_ref, q_ref, k_hbm, v_hbm, ks_hbm, vs_hbm,
         out_ref, k_pre, v_pre, ks_pre, vs_pre, k_tail, v_tail, ks_tail,
         vs_tail, sems_pre, sems_tail) = refs
        pre_pools = [(k_hbm, k_pre), (v_hbm, v_pre),
                     (ks_hbm, ks_pre), (vs_hbm, vs_pre)]
        tail_pools = [(k_hbm, k_tail), (v_hbm, v_tail),
                      (ks_hbm, ks_tail), (vs_hbm, vs_tail)]
    else:
        (page_tables_ref, lengths_ref, q_ref, k_hbm, v_hbm,
         out_ref, k_pre, v_pre, k_tail, v_tail, sems_pre, sems_tail) = refs
        pre_pools = [(k_hbm, k_pre), (v_hbm, v_pre)]
        tail_pools = [(k_hbm, k_tail), (v_hbm, v_tail)]

    b = pl.program_id(0)
    nb = pl.num_programs(0)
    par = jax.lax.rem(b, 2)
    W = lookahead
    length = lengths_ref[b]
    n_pages = jnp.maximum(1, pl.cdiv(length, page_size))

    Hq, D = q_ref.shape[1], q_ref.shape[2]
    Hkv = k_hbm.shape[2]
    G = Hq // Hkv
    q = q_ref[0].astype(jnp.float32).reshape(Hkv, G, D)
    scale = 1.0 / jnp.sqrt(jnp.float32(D))

    def pre_dma(parity, j, seq_idx, c):
        hbm, scratch = pre_pools[c]
        return pltpu.make_async_copy(
            hbm.at[page_tables_ref[seq_idx, j]],
            scratch.at[parity, j],
            sems_pre.at[parity, j, c],
        )

    def tail_dma(slot, i, c):
        hbm, scratch = tail_pools[c]
        return pltpu.make_async_copy(
            hbm.at[page_tables_ref[b, i]],
            scratch.at[slot],
            sems_tail.at[slot, c],
        )

    def issue_pre(seq_idx, parity):
        npg = jnp.maximum(1, pl.cdiv(lengths_ref[seq_idx], page_size))
        for j in range(W):  # static unroll: DMA issues only

            @pl.when(j < npg)
            def _(j=j):
                for c in range(len(pre_pools)):
                    pre_dma(parity, j, seq_idx, c).start()

    # program 0 has no predecessor: prefetch its own window
    @pl.when(b == 0)
    def _():
        issue_pre(0, 0)

    # prefetch the NEXT program's window while this one computes
    @pl.when(b + 1 < nb)
    def _():
        issue_pre(b + 1, 1 - par)

    # long-context tail: warm the in-program double buffer for page W
    @pl.when(W < n_pages)
    def _():
        for c in range(len(tail_pools)):
            tail_dma(W % 2, W, c).start()

    def merge(carry, k_page, v_page, j, k_s, v_s):
        m, l, acc = carry
        kt = jnp.transpose(k_page, (1, 0, 2))  # [Hkv, ps, D]
        vt = jnp.transpose(v_page, (1, 0, 2))
        scores = jax.lax.dot_general(
            q, kt, (((2,), (2,)), ((0,), (0,))), preferred_element_type=jnp.float32
        ) * scale
        if quantized:
            scores = scores * k_s[None]  # [1, 1, ps] per-row K scales
        idx = j * page_size + jax.lax.broadcasted_iota(jnp.int32, (1, 1, page_size), 2)
        scores = jnp.where(idx < length, scores, _NEG_INF)
        chunk_max = jnp.max(scores, axis=-1)
        new_m = jnp.maximum(m, chunk_max)
        corr = jnp.exp(m - new_m)
        probs = jnp.exp(scores - new_m[..., None])
        new_l = l * corr + jnp.sum(probs, axis=-1)
        if quantized:
            probs = probs * v_s[None]
        chunk_out = jax.lax.dot_general(
            probs, vt, (((2,), (1,)), ((0,), (0,))), preferred_element_type=jnp.float32
        )
        return new_m, new_l, acc * corr[..., None] + chunk_out

    def pre_body(j, carry):
        for c in range(len(pre_pools)):
            pre_dma(par, j, b, c).wait()
        return merge(
            carry,
            k_pre[par, j].astype(jnp.float32),
            v_pre[par, j].astype(jnp.float32),
            j,
            ks_pre[par, j] if quantized else None,
            vs_pre[par, j] if quantized else None,
        )

    def tail_body(j, carry):
        slot = jax.lax.rem(j, 2)
        next_slot = jax.lax.rem(j + 1, 2)

        @pl.when(j + 1 < n_pages)
        def _():
            for c in range(len(tail_pools)):
                tail_dma(next_slot, j + 1, c).start()

        for c in range(len(tail_pools)):
            tail_dma(slot, j, c).wait()
        return merge(
            carry,
            k_tail[slot].astype(jnp.float32),
            v_tail[slot].astype(jnp.float32),
            j,
            ks_tail[slot] if quantized else None,
            vs_tail[slot] if quantized else None,
        )

    m0 = jnp.full((Hkv, G), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((Hkv, G), jnp.float32)
    acc0 = jnp.zeros((Hkv, G, D), jnp.float32)
    carry = jax.lax.fori_loop(0, jnp.minimum(W, n_pages), pre_body, (m0, l0, acc0))
    m, l, acc = jax.lax.fori_loop(W, n_pages, tail_body, carry)

    out = acc / jnp.maximum(l, 1e-20)[..., None]
    out_ref[0] = out.reshape(Hq, D).astype(out_ref.dtype)


#: scratch budget for the lookahead window (VMEM is ~16 MB/core scoped)
_LOOKAHEAD_SCRATCH_BYTES = 6 * 1024 * 1024


def lookahead_window(page_size: int, num_kv_heads: int, head_dim: int,
                     itemsize: int = 2) -> int:
    """Prefetch window W that fits the scratch budget (0 = kernel not
    applicable). Scratch = 2 parities x W pages x (k+v) + the 2-slot tail."""
    page_bytes = page_size * num_kv_heads * head_dim * itemsize
    budget = _LOOKAHEAD_SCRATCH_BYTES - 2 * 2 * page_bytes  # tail buffers
    return max(0, min(4, budget // (2 * 2 * page_bytes)))


@functools.partial(jax.jit, static_argnames=("interpret",))
def paged_decode_attention_pallas_lookahead(
    q: jnp.ndarray,  # [B, Hq, D]
    k_pages,  # [P, ps, Hkv, D] plain or QuantizedPages
    v_pages,
    page_tables: jnp.ndarray,  # [B, max_pages] int32
    positions: jnp.ndarray,  # [B] int32 query positions
    interpret: bool = False,
) -> jnp.ndarray:
    B, Hq, D = q.shape
    kq, vq, ks, vs, quantized = _decode_unpack_pools(k_pages, v_pages)
    P, ps, Hkv, _ = kq.shape
    lengths = positions.astype(jnp.int32) + 1
    W = lookahead_window(ps, Hkv, D, kq.dtype.itemsize)
    if W < 1:
        return paged_decode_attention_pallas(
            q, k_pages, v_pages, page_tables, positions, interpret=interpret
        )

    scratch_shapes = [
        pltpu.VMEM((2, W, ps, Hkv, D), kq.dtype),
        pltpu.VMEM((2, W, ps, Hkv, D), vq.dtype),
    ]
    if quantized:
        scratch_shapes += [
            pltpu.VMEM((2, W, 1, ps), jnp.float32),
            pltpu.VMEM((2, W, 1, ps), jnp.float32),
        ]
    scratch_shapes += [
        pltpu.VMEM((2, ps, Hkv, D), kq.dtype),
        pltpu.VMEM((2, ps, Hkv, D), vq.dtype),
    ]
    if quantized:
        scratch_shapes += [
            pltpu.VMEM((2, 1, ps), jnp.float32),
            pltpu.VMEM((2, 1, ps), jnp.float32),
        ]
    C = 4 if quantized else 2
    scratch_shapes += [
        pltpu.SemaphoreType.DMA((2, W, C)),
        pltpu.SemaphoreType.DMA((2, C)),
    ]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B,),
        in_specs=[
            pl.BlockSpec((1, Hq, D), lambda b, *_: (b, 0, 0)),
            *[pl.BlockSpec(memory_space=pl.ANY) for _ in range(C)],
        ],
        out_specs=pl.BlockSpec((1, Hq, D), lambda b, *_: (b, 0, 0)),
        scratch_shapes=scratch_shapes,
    )
    kernel = pl.pallas_call(
        functools.partial(
            _kernel_lookahead, page_size=ps, lookahead=W, quantized=quantized
        ),
        out_shape=jax.ShapeDtypeStruct((B, Hq, D), q.dtype),
        grid_spec=grid_spec,
        # cross-program scratch persistence (program b prefetches b+1's pages
        # into the opposite parity's slots) requires the grid to run SERIALLY
        # — pin it rather than relying on the implicit default
        compiler_params=pltpu.TPUCompilerParams(dimension_semantics=("arbitrary",)),
        interpret=interpret,
    )
    args = (kq, vq, ks, vs) if quantized else (kq, vq)
    return kernel(page_tables.astype(jnp.int32), lengths, q, *args)


def _kernel_folded(
    *refs,
    page_size: int,
    num_kv_heads: int,
    head_dim: int,
    quantized: bool = False,
):
    """Decode attention for head_dim < 128 (e.g. TinyLlama/Qwen2-small: 64).

    Mosaic can't DMA-slice an HBM pool whose minor dim is under the 128-lane
    tile, so the pools arrive with kv heads FOLDED into the lane dim
    ([ps, Hkv*D] rows, >= 128 lanes). The per-head math never unfolds in bf16:

      - scores: Q is placed into a zero-padded folded layout (each q head
        occupies its kv head's D-slice, zeros elsewhere), so one
        [Hq, Hkv*D] x [ps, Hkv*D] matmul yields exact per-head scores —
        the zero slices kill every cross-head term.
      - output: probs @ V_folded gives [Hq, Hkv*D]; each head's true output
        sits in its kv head's slice, selected with a one-hot contraction in
        f32 (32-bit ops may reshape the minor dim; bf16 may not).

    refs: page_tables + lengths (scalar prefetch) | q [1, Hq, D], k/v pools
    [P, ps, Hkv*D] [, k/v scale planes [P, 1, ps]] | out | k/v scratch
    [2, ps, Hkv*D] [, scale scratch [2, 1, ps]], sems [2, 2|4]. The per-row
    int8 scale is head-independent, so the folded scores/probs scale with
    the same [1, ps] rows as the unfolded kernels.
    """
    if quantized:
        (page_tables_ref, lengths_ref, q_ref, k_hbm, v_hbm, ks_hbm, vs_hbm,
         out_ref, k_scratch, v_scratch, ks_scratch, vs_scratch, sems) = refs
        pools = [(k_hbm, k_scratch), (v_hbm, v_scratch),
                 (ks_hbm, ks_scratch), (vs_hbm, vs_scratch)]
    else:
        (page_tables_ref, lengths_ref, q_ref, k_hbm, v_hbm,
         out_ref, k_scratch, v_scratch, sems) = refs
        pools = [(k_hbm, k_scratch), (v_hbm, v_scratch)]

    b = pl.program_id(0)
    length = lengths_ref[b]
    n_pages = jnp.maximum(1, pl.cdiv(length, page_size))

    Hq, D = q_ref.shape[1], head_dim
    Hkv = num_kv_heads
    G = Hq // Hkv
    F = Hkv * D  # folded lane width

    q32 = q_ref[0].astype(jnp.float32)  # [Hq, D]
    # Everything stays 2D — Mosaic (this version) rejects minor-dim reshapes
    # outright. The folded-lane ownership mask [Hq, F]:
    #   mask[h, f] = (f // D == h // G)
    lane = jax.lax.broadcasted_iota(jnp.int32, (Hq, F), 1)
    head = jax.lax.broadcasted_iota(jnp.int32, (Hq, F), 0)
    mask = (lane // D == head // G).astype(jnp.float32)
    # folded q via lane-tiling: concat Hkv copies of q along lanes, zero all
    # slices a head doesn't own
    qtile = jnp.concatenate([q32] * Hkv, axis=1)  # [Hq, F]
    qf = (qtile * mask).astype(q_ref.dtype)
    scale = 1.0 / jnp.sqrt(jnp.float32(D))

    def dma(slot, i, c):
        hbm, scratch = pools[c]
        return pltpu.make_async_copy(
            hbm.at[page_tables_ref[b, i]], scratch.at[slot], sems.at[slot, c]
        )

    for c in range(len(pools)):
        dma(0, 0, c).start()

    def body(i, carry):
        m, l, acc = carry  # [Hq], [Hq], [Hq, F] f32
        slot = jax.lax.rem(i, 2)
        next_slot = jax.lax.rem(i + 1, 2)

        @pl.when(i + 1 < n_pages)
        def _():
            for c in range(len(pools)):
                dma(next_slot, i + 1, c).start()

        for c in range(len(pools)):
            dma(slot, i, c).wait()

        k_page = k_scratch[slot]  # [ps, F] bf16 (or int8)
        v_page = v_scratch[slot]
        idx = i * page_size + jax.lax.broadcasted_iota(jnp.int32, (1, page_size), 1)
        vidx = i * page_size + jax.lax.broadcasted_iota(
            jnp.int32, (page_size, 1), 0
        )

        # [Hq, ps] exact per-head scores via the folded contraction
        # (int8 pages upcast to f32 for the dot — operand dtypes must match)
        scores = jax.lax.dot_general(
            qf.astype(jnp.float32) if quantized else qf,
            k_page.astype(jnp.float32) if quantized else k_page,
            (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale
        if quantized:
            scores = scores * ks_scratch[slot]  # [1, ps] per-row K scales
        scores = jnp.where(idx < length, scores, _NEG_INF)
        v_page = jnp.where(vidx < length, v_page, 0)

        chunk_max = jnp.max(scores, axis=-1)  # [Hq]
        new_m = jnp.maximum(m, chunk_max)
        corr = jnp.exp(m - new_m)
        probs = jnp.exp(scores - new_m[:, None])  # [Hq, ps]
        new_l = l * corr + jnp.sum(probs, axis=-1)
        # [Hq, F] = [Hq, ps] x [ps, F]
        if quantized:
            probs = probs * vs_scratch[slot]  # V scales fold into probs
            chunk_out = jax.lax.dot_general(
                probs, v_page.astype(jnp.float32),
                (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
        else:
            chunk_out = jax.lax.dot_general(
                probs.astype(v_page.dtype), v_page,
                (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
        new_acc = acc * corr[:, None] + chunk_out
        return new_m, new_l, new_acc

    m0 = jnp.full((Hq,), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((Hq,), jnp.float32)
    acc0 = jnp.zeros((Hq, F), jnp.float32)
    m, l, acc = jax.lax.fori_loop(0, n_pages, body, (m0, l0, acc0))

    # select each head's slice: zero un-owned lanes, then fold the Hkv
    # D-wide lane slices together (only the owned one is nonzero)
    acc_m = acc * mask
    out = acc_m[:, 0:D]
    for j in range(1, Hkv):
        out = out + acc_m[:, j * D : (j + 1) * D]
    out = out / jnp.maximum(l, 1e-20)[:, None]
    out_ref[0] = out.astype(out_ref.dtype)


@functools.partial(jax.jit, static_argnames=("interpret",))
def paged_decode_attention_pallas_folded(
    q: jnp.ndarray,  # [B, Hq, D]
    k_pages,  # [P, ps, Hkv*D] folded (plain or QuantizedPages), or [P, ps, Hkv, D]
    v_pages,
    page_tables: jnp.ndarray,  # [B, max_pages] int32
    positions: jnp.ndarray,  # [B] int32 query positions
    interpret: bool = False,
) -> jnp.ndarray:
    B, Hq, D = q.shape
    lengths = positions.astype(jnp.int32) + 1
    if k_pages.ndim == 4:
        # direct-call convenience (tests): fold here. Serving passes pools
        # ALREADY folded (LlamaConfig.kv_folded) — reshaping a donated,
        # scatter-updated pool at attention time copies the whole pool.
        P, ps, Hkv, _ = k_pages.shape
        if isinstance(k_pages, QuantizedPages):
            k_pages = QuantizedPages(k_pages.q.reshape(P, ps, Hkv * D), k_pages.s)
            v_pages = QuantizedPages(v_pages.q.reshape(P, ps, Hkv * D), v_pages.s)
        else:
            k_pages = k_pages.reshape(P, ps, Hkv * D)
            v_pages = v_pages.reshape(P, ps, Hkv * D)
    kf, vf, ks, vs, quantized = _decode_unpack_pools(k_pages, v_pages)
    P, ps, F = kf.shape
    Hkv = F // D

    scratch_shapes = [
        pltpu.VMEM((2, ps, Hkv * D), kf.dtype),
        pltpu.VMEM((2, ps, Hkv * D), vf.dtype),
    ]
    if quantized:
        scratch_shapes += [
            pltpu.VMEM((2, 1, ps), jnp.float32),
            pltpu.VMEM((2, 1, ps), jnp.float32),
        ]
    C = 4 if quantized else 2
    scratch_shapes.append(pltpu.SemaphoreType.DMA((2, C)))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B,),
        in_specs=[
            pl.BlockSpec((1, Hq, D), lambda b, *_: (b, 0, 0)),
            *[pl.BlockSpec(memory_space=pl.ANY) for _ in range(C)],
        ],
        out_specs=pl.BlockSpec((1, Hq, D), lambda b, *_: (b, 0, 0)),
        scratch_shapes=scratch_shapes,
    )
    kernel = pl.pallas_call(
        functools.partial(
            _kernel_folded, page_size=ps, num_kv_heads=Hkv, head_dim=D,
            quantized=quantized,
        ),
        out_shape=jax.ShapeDtypeStruct((B, Hq, D), q.dtype),
        grid_spec=grid_spec,
        interpret=interpret,
    )
    args = (kf, vf, ks, vs) if quantized else (kf, vf)
    return kernel(page_tables.astype(jnp.int32), lengths, q, *args)


def _kernel_chunked(
    # scalar prefetch
    page_tables_ref,  # [B, max_pages] SMEM
    lengths_ref,  # [B] SMEM
    # inputs
    q_ref,  # [1, Hq, D] VMEM (this sequence's query)
    k_hbm,  # [P, ps, Hkv, D] HBM
    v_hbm,  # [P, ps, Hkv, D] HBM
    # output
    out_ref,  # [1, Hq, D] VMEM
    # scratch
    k_scratch,  # [2, C, ps, Hkv, D] VMEM
    v_scratch,  # [2, C, ps, Hkv, D] VMEM
    sems,  # DMA sems [2, C, 2]
    *,
    page_size: int,
    chunk: int,
):
    """Per-sequence grid, C pages per loop iteration: the C k/v DMAs of a
    chunk are all in flight together (hides HBM latency) and the softmax
    update contracts [Hkv, G, D] x [Hkv, C*ps, D] — C*ps context positions
    per MXU call instead of ps (the one-page version's 2x16 dots use a
    vanishing fraction of the 128x128 MXU tile and run overhead-bound)."""
    b = pl.program_id(0)
    length = lengths_ref[b]
    n_pages = jnp.maximum(1, pl.cdiv(length, page_size))
    C = chunk
    n_chunks = pl.cdiv(n_pages, C)

    Hq, D = q_ref.shape[1], q_ref.shape[2]
    Hkv = k_hbm.shape[2]
    G = Hq // Hkv
    q = q_ref[0].reshape(Hkv, G, D)  # native dtype: MXU takes bf16 directly
    scale = 1.0 / jnp.sqrt(jnp.float32(D))

    def dma(slot, j, page_idx, which):
        hbm, scratch = (k_hbm, k_scratch) if which == 0 else (v_hbm, v_scratch)
        return pltpu.make_async_copy(
            hbm.at[page_tables_ref[b, page_idx]],
            scratch.at[slot, j],
            sems.at[slot, j, which],
        )

    def start_chunk(slot, c):
        for j in range(C):  # static unroll
            @pl.when(c * C + j < n_pages)
            def _(j=j):
                dma(slot, j, c * C + j, 0).start()
                dma(slot, j, c * C + j, 1).start()

    def wait_chunk(slot, c):
        for j in range(C):
            @pl.when(c * C + j < n_pages)
            def _(j=j):
                dma(slot, j, c * C + j, 0).wait()
                dma(slot, j, c * C + j, 1).wait()

    start_chunk(0, 0)

    def body(c, carry):
        m, l, acc = carry
        slot = jax.lax.rem(c, 2)
        next_slot = jax.lax.rem(c + 1, 2)

        @pl.when(c + 1 < n_chunks)
        def _():
            start_chunk(next_slot, c + 1)

        wait_chunk(slot, c)

        N = C * page_size
        # [C, ps, Hkv, D] -> [N, Hkv, D] (leading-dim merge: layout-preserving)
        # -> [Hkv, N, D] (one bf16 relayout per chunk)
        kt = jnp.transpose(k_scratch[slot].reshape(N, Hkv, D), (1, 0, 2))
        vt = jnp.transpose(v_scratch[slot].reshape(N, Hkv, D), (1, 0, 2))
        idx = c * N + jax.lax.broadcasted_iota(jnp.int32, (1, 1, N), 2)
        vidx = c * N + jax.lax.broadcasted_iota(jnp.int32, (1, N, 1), 1)

        # [Hkv, G, N] = [Hkv, G, D] x [Hkv, N, D]
        scores = jax.lax.dot_general(
            q, kt, (((2,), (2,)), ((0,), (0,))), preferred_element_type=jnp.float32
        ) * scale
        # beyond-length/unfetched tail: mask K scores outright and zero V so
        # 0-weight garbage (or uninitialized first-call VMEM) can't poison
        # acc via 0 * NaN
        scores = jnp.where(idx < length, scores, _NEG_INF)
        vt = jnp.where(vidx < length, vt, 0)

        chunk_max = jnp.max(scores, axis=-1)  # [Hkv, G]
        new_m = jnp.maximum(m, chunk_max)
        corr = jnp.exp(m - new_m)
        probs = jnp.exp(scores - new_m[..., None])  # [Hkv, G, N]
        new_l = l * corr + jnp.sum(probs, axis=-1)
        # [Hkv, G, D] = [Hkv, G, N] x [Hkv, N, D]; probs in the pages' dtype
        chunk_out = jax.lax.dot_general(
            probs.astype(kt.dtype), vt, (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        )
        new_acc = acc * corr[..., None] + chunk_out
        return new_m, new_l, new_acc

    m0 = jnp.full((Hkv, G), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((Hkv, G), jnp.float32)
    acc0 = jnp.zeros((Hkv, G, D), jnp.float32)
    m, l, acc = jax.lax.fori_loop(0, n_chunks, body, (m0, l0, acc0))

    out = acc / jnp.maximum(l, 1e-20)[..., None]
    out_ref[0] = out.reshape(Hq, D).astype(out_ref.dtype)


@functools.partial(jax.jit, static_argnames=("interpret",))
def paged_decode_attention_pallas_chunked(
    q: jnp.ndarray,  # [B, Hq, D]
    k_pages: jnp.ndarray,  # [P, ps, Hkv, D]
    v_pages: jnp.ndarray,  # [P, ps, Hkv, D]
    page_tables: jnp.ndarray,  # [B, max_pages] int32
    positions: jnp.ndarray,  # [B] int32 query positions
    interpret: bool = False,
) -> jnp.ndarray:
    B, Hq, D = q.shape
    P, ps, Hkv, _ = k_pages.shape
    max_pages = page_tables.shape[1]
    lengths = positions.astype(jnp.int32) + 1
    # ~256 context positions per chunk: MXU-worthy contraction length while
    # 2 x 2 x C pages of scratch stay tiny vs VMEM
    chunk = max(1, min(max_pages, -(-256 // ps)))

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B,),
        in_specs=[
            pl.BlockSpec((1, Hq, D), lambda b, *_: (b, 0, 0)),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=pl.BlockSpec((1, Hq, D), lambda b, *_: (b, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((2, chunk, ps, Hkv, D), k_pages.dtype),
            pltpu.VMEM((2, chunk, ps, Hkv, D), v_pages.dtype),
            pltpu.SemaphoreType.DMA((2, chunk, 2)),
        ],
    )
    kernel = pl.pallas_call(
        functools.partial(_kernel_chunked, page_size=ps, chunk=chunk),
        out_shape=jax.ShapeDtypeStruct((B, Hq, D), q.dtype),
        grid_spec=grid_spec,
        interpret=interpret,
    )
    return kernel(page_tables.astype(jnp.int32), lengths, q, k_pages, v_pages)


@functools.partial(jax.jit, static_argnames=("interpret",))
def paged_decode_attention_pallas(
    q: jnp.ndarray,  # [B, Hq, D]
    k_pages,  # [P, ps, Hkv, D] plain or QuantizedPages
    v_pages,
    page_tables: jnp.ndarray,  # [B, max_pages] int32
    positions: jnp.ndarray,  # [B] int32 query positions
    interpret: bool = False,
) -> jnp.ndarray:
    B, Hq, D = q.shape
    kq, vq, ks, vs, quantized = _decode_unpack_pools(k_pages, v_pages)
    P, ps, Hkv, _ = kq.shape
    max_pages = page_tables.shape[1]
    lengths = positions.astype(jnp.int32) + 1

    scratch_shapes = [
        pltpu.VMEM((2, ps, Hkv, D), kq.dtype),
        pltpu.VMEM((2, ps, Hkv, D), vq.dtype),
    ]
    if quantized:
        scratch_shapes += [
            pltpu.VMEM((2, 1, ps), jnp.float32),
            pltpu.VMEM((2, 1, ps), jnp.float32),
        ]
    C = 4 if quantized else 2
    scratch_shapes.append(pltpu.SemaphoreType.DMA((2, C)))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B,),
        in_specs=[
            pl.BlockSpec((1, Hq, D), lambda b, *_: (b, 0, 0)),
            # k/v pages (and int8 scale planes) stay in HBM
            *[pl.BlockSpec(memory_space=pl.ANY) for _ in range(C)],
        ],
        out_specs=pl.BlockSpec((1, Hq, D), lambda b, *_: (b, 0, 0)),
        scratch_shapes=scratch_shapes,
    )
    kernel = pl.pallas_call(
        functools.partial(
            _kernel, page_size=ps, max_pages=max_pages, quantized=quantized
        ),
        out_shape=jax.ShapeDtypeStruct((B, Hq, D), q.dtype),
        grid_spec=grid_spec,
        interpret=interpret,
    )
    args = (kq, vq, ks, vs) if quantized else (kq, vq)
    return kernel(page_tables.astype(jnp.int32), lengths, q, *args)
