"""Pallas TPU kernels: chunked-prefill flash attention over the paged KV pool.

A prefill chunk's queries attend causally over the sequence's paged context
(which already contains the chunk's own rows — the model scatters before
attending). The XLA reference path (ops/attention.py paged_prefill_attention)
materializes the whole gathered context ``[max_pages * ps, Hkv, D]`` plus a
``[Hq, T, S]`` score tensor per layer; these kernels stream context pages
HBM -> VMEM in multi-page tiles with double buffering and keep the online
softmax in VMEM, so HBM traffic is one pass over the needed pages and no
score/gather materialization at all. Causality additionally bounds work per
query block: block b only loops over tiles up to its last query position.

Two scheduling variants share the math:

  - ``_kernel`` (basic): the r4 design point — per-program double buffer
    only. Every grid program (query block) pays the full first-tile DMA
    latency at its boundary before any compute can start.
  - ``_kernel_lookahead`` (default on TPU): the decode ``_kernel_lookahead``
    insight ported to prefill. Grid programs run serially on the core and
    scratch PERSISTS across them; the page table and positions are
    scalar-prefetched, so query block b issues block b+1's first
    ``lookahead`` context-tile DMAs into the opposite parity's window while
    it runs its own online softmax — the same cross-program pipelining that
    put the decode kernel AT ideal KV-read bandwidth (r5 A/B,
    paged_attention.py). Prefill re-reads the context from tile 0 for every
    query block, so the boundary exposure repeats T/block_q times per chunk
    per layer; hiding it matters most exactly on the prefill-bound
    ref-workload shape (3K ISL). Tiles >= lookahead stream through the
    classic in-program double buffer. DYNTPU_PREFILL_KERNEL=basic is the
    escape hatch.

Int8 KV (quant/kv.py QuantizedPages): the pools arrive as int8 plus a
per-row f32 scale plane reshaped to ``[P, 1, ps]``. Scale rows ride their
own tiny DMAs next to the page DMAs (HBM reads stay int8 — that is the
point: the context stream halves), and dequantization happens on the score/
prob TILES in VMEM: ``scores *= k_scale_row`` and ``probs *= v_scale_row``
are exact per-column algebra (see quant/kv.py) and touch only lane-axis
broadcasts/concats — the same Mosaic-legal idioms the folded kernels use.

Contract: q [T, Hq, D] (bucket-padded chunk), k/v pages [P, ps, Hkv, D],
page_table [max_pages] (this sequence's logical pages, trash page 0 padding),
positions [T] absolute and **unit-stride** (positions[i] = positions[0] + i —
the mask derives row positions from positions[block_start] + row offset;
engine chunks always satisfy this; the XLA reference only needs monotone).
GQA folds as [Hkv, G*Bq, D] batched matmuls.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from dynamo_tpu.quant.kv import QuantizedPages

_NEG_INF = -1e30


def _unpack_pools(k_pages, v_pages):
    """(k, v, k_scale [P,1,ps] | None, v_scale | None, quantized) from plain
    or QuantizedPages pools. The [P, ps] -> [P, 1, ps] scale reshape is a
    zero-cost leading-dim split; it gives the per-page DMA slice a 2D
    ([1, ps]) destination."""
    if isinstance(k_pages, QuantizedPages):
        P, ps = k_pages.s.shape
        return (
            k_pages.q, v_pages.q,
            k_pages.s.reshape(P, 1, ps), v_pages.s.reshape(P, 1, ps),
            True,
        )
    return k_pages, v_pages, None, None, False


def _scale_tile_row(scratch_tile):
    """[TP, 1, ps] VMEM scale tiles -> one [1, S] row via lane-axis concat
    (the folded kernels' q lane-tiling idiom; leading/lane ops only)."""
    TP = scratch_tile.shape[0]
    if TP == 1:
        return scratch_tile[0]
    return jnp.concatenate([scratch_tile[p] for p in range(TP)], axis=-1)


def _tile_dma_helpers(page_table_ref, hbm_scratch_pairs, sems,
                      tile_pages: int, max_pages: int):
    """Shared double-buffered context-tile DMA scaffolding for the prefill
    kernels: ``hbm_scratch_pairs`` is [(hbm_pool, scratch)] — k/v and, when
    quantized, their scale planes — each scratch indexed ``[buf, p]`` and
    ``sems`` channel c matching pair c (``[2, C, TP]``). Returns (start,
    wait), each taking (buf, tile). The final tile clamps page indices to
    max_pages - 1 (aliased content is masked by the callers' ctx-bound
    check)."""

    def tile_dma(buf, tile):
        copies = []
        for p in range(tile_pages):
            idx = jnp.minimum(tile * tile_pages + p, max_pages - 1)
            for c, (hbm, scratch) in enumerate(hbm_scratch_pairs):
                copies.append(
                    pltpu.make_async_copy(
                        hbm.at[page_table_ref[idx]], scratch.at[buf, p],
                        sems.at[buf, c, p],
                    )
                )
        return copies

    def start(buf, tile):
        for cp in tile_dma(buf, tile):
            cp.start()

    def wait(buf, tile):
        for cp in tile_dma(buf, tile):
            cp.wait()

    return start, wait


def _flash_merge(carry, q, kt, vt, scores_extra, mask, ks_row, vs_row):
    """One online-softmax merge step shared by every non-folded prefill
    kernel. kt/vt are [Hkv, S, D] f32 context tiles; ks_row/vs_row are
    [1, S] f32 scale rows (None on bf16 pools); mask [G*Bq, S]."""
    m, l, acc = carry
    scores = jax.lax.dot_general(
        q, kt, (((2,), (2,)), ((0,), (0,))), preferred_element_type=jnp.float32
    ) * scores_extra
    if ks_row is not None:
        scores = scores * ks_row[None]  # [1, 1, S] column scales (exact)
    scores = jnp.where(mask[None], scores, _NEG_INF)
    chunk_max = jnp.max(scores, axis=-1)
    new_m = jnp.maximum(m, chunk_max)
    corr = jnp.exp(m - new_m)
    probs = jnp.exp(scores - new_m[..., None])
    new_l = l * corr + jnp.sum(probs, axis=-1)
    if vs_row is not None:
        probs = probs * vs_row[None]  # scale probs, not V: stays one multiply
    chunk_out = jax.lax.dot_general(
        probs, vt, (((2,), (1,)), ((0,), (0,))), preferred_element_type=jnp.float32
    )
    return new_m, new_l, acc * corr[..., None] + chunk_out


def _kernel(
    *refs,
    page_size: int,
    max_pages: int,
    tile_pages: int,
    block_q: int,
    quantized: bool,
):
    """Basic (in-program double buffer) flash prefill; see module docstring.

    refs layout: page_table, positions (scalar prefetch) | q, k_hbm, v_hbm
    [, ks_hbm, vs_hbm] | out | k_scratch, v_scratch [, ks_scratch,
    vs_scratch], sems."""
    if quantized:
        (page_table_ref, positions_ref, q_ref, k_hbm, v_hbm, ks_hbm, vs_hbm,
         out_ref, k_scratch, v_scratch, ks_scratch, vs_scratch, sems) = refs
        pairs = [(k_hbm, k_scratch), (v_hbm, v_scratch),
                 (ks_hbm, ks_scratch), (vs_hbm, vs_scratch)]
    else:
        (page_table_ref, positions_ref, q_ref, k_hbm, v_hbm,
         out_ref, k_scratch, v_scratch, sems) = refs
        pairs = [(k_hbm, k_scratch), (v_hbm, v_scratch)]

    qb = pl.program_id(0)
    Bq, Hq, D = q_ref.shape
    Hkv = k_hbm.shape[2]
    G = Hq // Hkv
    TP = tile_pages
    S = TP * page_size  # context tile length

    # this block's query positions and causal context bound
    q_start = qb * block_q
    last_pos = positions_ref[q_start + Bq - 1]
    ctx_len = last_pos + 1
    n_tiles = jnp.minimum(
        pl.cdiv(ctx_len, S), pl.cdiv(jnp.int32(max_pages * page_size), S)
    )

    # [Hkv, G*Bq, D] query layout: head-major groups so each kv head's block
    # is one batched matmul operand
    q = (
        q_ref[...]
        .astype(jnp.float32)
        .reshape(Bq, Hkv, G, D)
        .transpose(1, 2, 0, 3)
        .reshape(Hkv, G * Bq, D)
    )
    scale = 1.0 / jnp.sqrt(jnp.float32(D))

    start, wait = _tile_dma_helpers(page_table_ref, pairs, sems, TP, max_pages)
    start(0, 0)

    # causal mask geometry, built directly in 2D [G*Bq, S] (Mosaic rejects 1D
    # vector reshapes): row i is block-row i % Bq; its query position is
    # positions[q_start] + (i % Bq)
    pos0 = positions_ref[q_start]
    iota_row = jax.lax.broadcasted_iota(jnp.int32, (G * Bq, S), 0)
    iota_col = jax.lax.broadcasted_iota(jnp.int32, (G * Bq, S), 1)
    q_pos_2d = pos0 + jax.lax.rem(iota_row, Bq)  # [G*Bq, S]

    def body(t, carry):
        buf = jax.lax.rem(t, 2)

        @pl.when(t + 1 < n_tiles)
        def _():
            start(jax.lax.rem(t + 1, 2), t + 1)

        wait(buf, t)

        kt = (
            k_scratch[buf]
            .astype(jnp.float32)
            .reshape(S, Hkv, D)
            .transpose(1, 0, 2)
        )  # [Hkv, S, D]
        vt = (
            v_scratch[buf]
            .astype(jnp.float32)
            .reshape(S, Hkv, D)
            .transpose(1, 0, 2)
        )
        ks_row = _scale_tile_row(ks_scratch[buf]) if quantized else None
        vs_row = _scale_tile_row(vs_scratch[buf]) if quantized else None

        ctx_idx = t * S + iota_col
        # causal, and never beyond the page table (the final tile clamps its
        # page indices to max_pages - 1, which would alias earlier content)
        mask = (ctx_idx <= q_pos_2d) & (ctx_idx < max_pages * page_size)
        return _flash_merge(carry, q, kt, vt, scale, mask, ks_row, vs_row)

    m0 = jnp.full((Hkv, G * Bq), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((Hkv, G * Bq), jnp.float32)
    acc0 = jnp.zeros((Hkv, G * Bq, D), jnp.float32)
    m, l, acc = jax.lax.fori_loop(0, n_tiles, body, (m0, l0, acc0))

    out = acc / jnp.maximum(l, 1e-20)[..., None]  # [Hkv, G*Bq, D]
    out_ref[...] = (
        out.reshape(Hkv, G, Bq, D).transpose(2, 0, 1, 3).reshape(Bq, Hq, D)
    ).astype(out_ref.dtype)


def _kernel_dmaonly(
    *refs,
    page_size: int,
    max_pages: int,
    tile_pages: int,
    block_q: int,
    quantized: bool,
):
    """Null-hypothesis prefill kernel: ``_kernel``'s exact grid, causal tile
    bound, and double-buffered context-tile DMA stream with NO attention
    math — the decode ``dmaonly`` methodology (tools/profile_attn.py, r5)
    ported to the prefill grid. Its wall time is the irreducible per-chunk
    HBM context traffic; the gap to the real kernel is compute not hidden
    under DMA. Computes garbage by design — timing only."""
    if quantized:
        (page_table_ref, positions_ref, q_ref, k_hbm, v_hbm, ks_hbm, vs_hbm,
         out_ref, k_scratch, v_scratch, ks_scratch, vs_scratch, sems) = refs
        pairs = [(k_hbm, k_scratch), (v_hbm, v_scratch),
                 (ks_hbm, ks_scratch), (vs_hbm, vs_scratch)]
    else:
        (page_table_ref, positions_ref, q_ref, k_hbm, v_hbm,
         out_ref, k_scratch, v_scratch, sems) = refs
        pairs = [(k_hbm, k_scratch), (v_hbm, v_scratch)]

    qb = pl.program_id(0)
    Bq = q_ref.shape[0]
    TP = tile_pages
    S = TP * page_size

    q_start = qb * block_q
    last_pos = positions_ref[q_start + Bq - 1]
    n_tiles = jnp.minimum(
        pl.cdiv(last_pos + 1, S), pl.cdiv(jnp.int32(max_pages * page_size), S)
    )

    start, wait = _tile_dma_helpers(page_table_ref, pairs, sems, TP, max_pages)
    start(0, 0)

    def body(t, acc):
        buf = jax.lax.rem(t, 2)

        @pl.when(t + 1 < n_tiles)
        def _():
            start(jax.lax.rem(t + 1, 2), t + 1)

        wait(buf, t)
        # consume one row per tile so the waits can't be elided; no matmuls,
        # no softmax, no casts, no relayouts
        return (
            acc
            + k_scratch[buf, 0, 0].astype(jnp.float32)
            + v_scratch[buf, 0, 0].astype(jnp.float32)
        )

    Hkv, D = k_scratch.shape[3], k_scratch.shape[4]
    acc = jax.lax.fori_loop(
        0, n_tiles, body, jnp.zeros((Hkv, D), jnp.float32)
    )
    out_ref[...] = jnp.broadcast_to(
        acc[:1] * 1e-6, out_ref.shape
    ).astype(out_ref.dtype)


def _kernel_lookahead(
    *refs,
    page_size: int,
    max_pages: int,
    tile_pages: int,
    block_q: int,
    lookahead: int,
    quantized: bool,
):
    """Flash prefill with CROSS-PROGRAM context-tile prefetch (the decode
    lookahead kernel's scheduling applied to the query-block grid; see the
    module docstring for why the boundary exposure matters more here).

    refs layout: page_table, positions | q, k_hbm, v_hbm [, ks_hbm, vs_hbm]
    | out | k_pre, v_pre [, ks_pre, vs_pre], k_tail, v_tail [, ks_tail,
    vs_tail], sems_pre, sems_tail."""
    if quantized:
        (page_table_ref, positions_ref, q_ref, k_hbm, v_hbm, ks_hbm, vs_hbm,
         out_ref, k_pre, v_pre, ks_pre, vs_pre, k_tail, v_tail, ks_tail,
         vs_tail, sems_pre, sems_tail) = refs
        pre_pools = [(k_hbm, k_pre), (v_hbm, v_pre),
                     (ks_hbm, ks_pre), (vs_hbm, vs_pre)]
        tail_pairs = [(k_hbm, k_tail), (v_hbm, v_tail),
                      (ks_hbm, ks_tail), (vs_hbm, vs_tail)]
    else:
        (page_table_ref, positions_ref, q_ref, k_hbm, v_hbm,
         out_ref, k_pre, v_pre, k_tail, v_tail, sems_pre, sems_tail) = refs
        pre_pools = [(k_hbm, k_pre), (v_hbm, v_pre)]
        tail_pairs = [(k_hbm, k_tail), (v_hbm, v_tail)]

    qb = pl.program_id(0)
    nb = pl.num_programs(0)
    par = jax.lax.rem(qb, 2)
    W = lookahead
    Bq, Hq, D = q_ref.shape
    Hkv = k_hbm.shape[2]
    G = Hq // Hkv
    TP = tile_pages
    S = TP * page_size
    ctx_cap = jnp.int32(max_pages * page_size)

    def block_tiles(block_idx):
        """Causal tile count for query block ``block_idx`` (its last row's
        position is scalar-prefetched, so any program can compute it)."""
        last_pos = positions_ref[block_idx * block_q + Bq - 1]
        return jnp.minimum(pl.cdiv(last_pos + 1, S), pl.cdiv(ctx_cap, S))

    n_tiles = block_tiles(qb)

    q = (
        q_ref[...]
        .astype(jnp.float32)
        .reshape(Bq, Hkv, G, D)
        .transpose(1, 2, 0, 3)
        .reshape(Hkv, G * Bq, D)
    )
    scale = 1.0 / jnp.sqrt(jnp.float32(D))

    def pre_dma(parity, j, p, c):
        hbm, scratch = pre_pools[c]
        idx = jnp.minimum(j * TP + p, max_pages - 1)
        return pltpu.make_async_copy(
            hbm.at[page_table_ref[idx]],
            scratch.at[parity, j, p],
            sems_pre.at[parity, j, c, p],
        )

    def tail_dma(slot, tile, p, c):
        hbm, scratch = tail_pairs[c]
        idx = jnp.minimum(tile * TP + p, max_pages - 1)
        return pltpu.make_async_copy(
            hbm.at[page_table_ref[idx]],
            scratch.at[slot, p],
            sems_tail.at[slot, c, p],
        )

    def issue_pre(block_idx, parity):
        # context pages are shared by every query block of the chunk, so the
        # NEXT block's first W tiles are known from the page table alone;
        # only how many it needs (its causal bound) depends on the block
        npg = block_tiles(block_idx)
        for j in range(W):  # static unroll: DMA issues only

            @pl.when(j < npg)
            def _(j=j):
                for p in range(TP):
                    for c in range(len(pre_pools)):
                        pre_dma(parity, j, p, c).start()

    # program 0 has no predecessor: prefetch its own window
    @pl.when(qb == 0)
    def _():
        issue_pre(0, 0)

    # prefetch the NEXT query block's window while this one computes
    @pl.when(qb + 1 < nb)
    def _():
        issue_pre(qb + 1, 1 - par)

    # long-context tail: warm the in-program double buffer for tile W
    @pl.when(W < n_tiles)
    def _():
        for p in range(TP):
            for c in range(len(tail_pairs)):
                tail_dma(W % 2, W, p, c).start()

    pos0 = positions_ref[qb * block_q]
    iota_row = jax.lax.broadcasted_iota(jnp.int32, (G * Bq, S), 0)
    iota_col = jax.lax.broadcasted_iota(jnp.int32, (G * Bq, S), 1)
    q_pos_2d = pos0 + jax.lax.rem(iota_row, Bq)

    def merge_tile(carry, t, k_tile, v_tile, ks_tile, vs_tile):
        kt = k_tile.astype(jnp.float32).reshape(S, Hkv, D).transpose(1, 0, 2)
        vt = v_tile.astype(jnp.float32).reshape(S, Hkv, D).transpose(1, 0, 2)
        ks_row = _scale_tile_row(ks_tile) if quantized else None
        vs_row = _scale_tile_row(vs_tile) if quantized else None
        ctx_idx = t * S + iota_col
        mask = (ctx_idx <= q_pos_2d) & (ctx_idx < ctx_cap)
        return _flash_merge(carry, q, kt, vt, scale, mask, ks_row, vs_row)

    def pre_body(j, carry):
        for p in range(TP):
            for c in range(len(pre_pools)):
                pre_dma(par, j, p, c).wait()
        return merge_tile(
            carry, j, k_pre[par, j], v_pre[par, j],
            ks_pre[par, j] if quantized else None,
            vs_pre[par, j] if quantized else None,
        )

    def tail_body(t, carry):
        slot = jax.lax.rem(t, 2)
        next_slot = jax.lax.rem(t + 1, 2)

        @pl.when(t + 1 < n_tiles)
        def _():
            for p in range(TP):
                for c in range(len(tail_pairs)):
                    tail_dma(next_slot, t + 1, p, c).start()

        for p in range(TP):
            for c in range(len(tail_pairs)):
                tail_dma(slot, t, p, c).wait()
        return merge_tile(
            carry, t, k_tail[slot], v_tail[slot],
            ks_tail[slot] if quantized else None,
            vs_tail[slot] if quantized else None,
        )

    m0 = jnp.full((Hkv, G * Bq), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((Hkv, G * Bq), jnp.float32)
    acc0 = jnp.zeros((Hkv, G * Bq, D), jnp.float32)
    carry = jax.lax.fori_loop(0, jnp.minimum(W, n_tiles), pre_body, (m0, l0, acc0))
    m, l, acc = jax.lax.fori_loop(W, n_tiles, tail_body, carry)

    out = acc / jnp.maximum(l, 1e-20)[..., None]
    out_ref[...] = (
        out.reshape(Hkv, G, Bq, D).transpose(2, 0, 1, 3).reshape(Bq, Hq, D)
    ).astype(out_ref.dtype)


def _kernel_folded(
    *refs,
    page_size: int,
    max_pages: int,
    tile_pages: int,
    block_q: int,
    num_kv_heads: int,
    head_dim: int,
    quantized: bool,
):
    """Folded-lane flash prefill for head_dim < 128 (see the decode
    _kernel_folded in paged_attention.py for the trick): every (query row,
    head) pair becomes one row of a zero-placed folded Q [Bq*Hq, Hkv*D], so a
    single [R, F] x [S, F] matmul yields exact per-head scores — the zero
    slices kill cross-head terms and cost only Hkv x extra MACs on an op
    that is a rounding error of prefill FLOPs. All shape changes are
    leading-dim merges/splits (minor dim untouched: Mosaic-legal). Int8
    pools: the per-row scale is head-INDEPENDENT, so one [1, S] scale row
    applies to the folded scores/probs exactly like the unfolded case."""
    if quantized:
        (page_table_ref, positions_ref, q_ref, k_hbm, v_hbm, ks_hbm, vs_hbm,
         out_ref, k_scratch, v_scratch, ks_scratch, vs_scratch, sems) = refs
        pairs = [(k_hbm, k_scratch), (v_hbm, v_scratch),
                 (ks_hbm, ks_scratch), (vs_hbm, vs_scratch)]
    else:
        (page_table_ref, positions_ref, q_ref, k_hbm, v_hbm,
         out_ref, k_scratch, v_scratch, sems) = refs
        pairs = [(k_hbm, k_scratch), (v_hbm, v_scratch)]

    qb = pl.program_id(0)
    Bq, Hq, D = q_ref.shape
    Hkv, F = num_kv_heads, num_kv_heads * head_dim
    G = Hq // Hkv
    TP = tile_pages
    S = TP * page_size
    R = Bq * Hq

    q_start = qb * block_q
    last_pos = positions_ref[q_start + Bq - 1]
    ctx_len = last_pos + 1
    n_tiles = jnp.minimum(
        pl.cdiv(ctx_len, S), pl.cdiv(jnp.int32(max_pages * page_size), S)
    )

    # folded queries [R, F]: row r = (t, h) with t = r // Hq, h = r % Hq;
    # q[t, h] occupies kv(h) = (h // G)'s D-slice, zeros elsewhere
    q2 = q_ref[...].reshape(R, D)  # leading merge only
    lane = jax.lax.broadcasted_iota(jnp.int32, (R, F), 1)
    row = jax.lax.broadcasted_iota(jnp.int32, (R, F), 0)
    own = (lane // D == jax.lax.rem(row, Hq) // G).astype(jnp.float32)
    qtile = jnp.concatenate([q2.astype(jnp.float32)] * Hkv, axis=1)  # [R, F]
    qf = (qtile * own).astype(q_ref.dtype)
    scale = 1.0 / jnp.sqrt(jnp.float32(D))

    start, wait = _tile_dma_helpers(page_table_ref, pairs, sems, TP, max_pages)
    start(0, 0)

    # causal geometry: row r's query position = positions[q_start] + r // Hq
    pos0 = positions_ref[q_start]
    iota_row = jax.lax.broadcasted_iota(jnp.int32, (R, S), 0)
    iota_col = jax.lax.broadcasted_iota(jnp.int32, (R, S), 1)
    q_pos_2d = pos0 + iota_row // Hq  # [R, S]

    def body(t, carry):
        m, l, acc = carry
        buf = jax.lax.rem(t, 2)

        @pl.when(t + 1 < n_tiles)
        def _():
            start(jax.lax.rem(t + 1, 2), t + 1)

        wait(buf, t)

        kf = k_scratch[buf].reshape(S, F)  # leading merge
        vf = v_scratch[buf].reshape(S, F)

        # [R, S] exact per-(row, head) scores via the folded contraction
        # (int8 pages upcast to f32 for the dot — operand dtypes must match)
        scores = jax.lax.dot_general(
            qf.astype(jnp.float32) if quantized else qf,
            kf.astype(jnp.float32) if quantized else kf,
            (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale
        if quantized:
            scores = scores * _scale_tile_row(ks_scratch[buf])  # [1, S]
        ctx_idx = t * S + iota_col
        mask = (ctx_idx <= q_pos_2d) & (ctx_idx < max_pages * page_size)
        scores = jnp.where(mask, scores, _NEG_INF)

        chunk_max = jnp.max(scores, axis=-1)  # [R]
        new_m = jnp.maximum(m, chunk_max)
        corr = jnp.exp(m - new_m)
        probs = jnp.exp(scores - new_m[:, None])
        new_l = l * corr + jnp.sum(probs, axis=-1)
        # [R, F] = [R, S] x [S, F]
        if quantized:
            probs = probs * _scale_tile_row(vs_scratch[buf])
            chunk_out = jax.lax.dot_general(
                probs, vf.astype(jnp.float32), (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
        else:
            chunk_out = jax.lax.dot_general(
                probs.astype(kf.dtype), vf, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
        new_acc = acc * corr[:, None] + chunk_out
        return new_m, new_l, new_acc

    m0 = jnp.full((R,), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((R,), jnp.float32)
    acc0 = jnp.zeros((R, F), jnp.float32)
    m, l, acc = jax.lax.fori_loop(0, n_tiles, body, (m0, l0, acc0))

    # keep each row's owned D-slice: zero the rest and fold the Hkv slices
    acc_m = acc * own
    out2 = acc_m[:, 0:D]
    for j in range(1, Hkv):
        out2 = out2 + acc_m[:, j * D : (j + 1) * D]
    out2 = out2 / jnp.maximum(l, 1e-20)[:, None]
    out_ref[...] = out2.reshape(Bq, Hq, D).astype(out_ref.dtype)  # leading split


def _pool_in_specs(quantized: bool):
    """in_specs for [q_block, k_pool, v_pool (, k_scale, v_scale)]."""
    pools = 4 if quantized else 2
    return [pl.BlockSpec(memory_space=pl.ANY) for _ in range(pools)]


#: scoped-VMEM budget for the lookahead prefill window (the decode kernel's
#: rationale, prefill tile sizes; ~16 MB/core scoped limit)
_PREFILL_LOOKAHEAD_SCRATCH_BYTES = 8 * 1024 * 1024


def prefill_lookahead_window(page_size: int, tile_pages: int,
                             num_kv_heads: int, head_dim: int,
                             itemsize: int = 2) -> int:
    """Prefetch window W in context TILES that fits the scratch budget
    (0 = lookahead not applicable at this geometry). Scratch = 2 parities x
    W tiles x (k+v) + the 2-slot tail; int8 scale tiles are noise."""
    tile_bytes = 2 * tile_pages * page_size * num_kv_heads * head_dim * itemsize
    budget = _PREFILL_LOOKAHEAD_SCRATCH_BYTES - 2 * tile_bytes  # tail buffers
    return max(0, min(4, budget // (2 * tile_bytes)))


@functools.partial(jax.jit, static_argnames=("interpret", "block_q"))
def paged_prefill_attention_pallas_folded(
    q: jnp.ndarray,  # [T, Hq, D] bucket-padded chunk
    k_pages,  # [P, ps, Hkv*D] folded (plain or QuantizedPages), or [P, ps, Hkv, D]
    v_pages,
    page_table: jnp.ndarray,  # [max_pages] int32
    positions: jnp.ndarray,  # [T] int32 absolute positions (unit-stride)
    block_q: int = 128,
    interpret: bool = False,
) -> jnp.ndarray:
    T, Hq, D = q.shape
    if k_pages.ndim == 4:  # direct-call convenience (tests)
        P, ps, Hkv, _ = k_pages.shape
        if isinstance(k_pages, QuantizedPages):
            k_pages = QuantizedPages(k_pages.q.reshape(P, ps, Hkv * D), k_pages.s)
            v_pages = QuantizedPages(v_pages.q.reshape(P, ps, Hkv * D), v_pages.s)
        else:
            k_pages = k_pages.reshape(P, ps, Hkv * D)
            v_pages = v_pages.reshape(P, ps, Hkv * D)
    kq, vq, ks, vs, quantized = _unpack_pools(k_pages, v_pages)
    P, ps, F = kq.shape
    Hkv = F // D
    max_pages = page_table.shape[0]
    assert T % block_q == 0, f"chunk {T} % block_q {block_q}"
    tile_pages = max(1, 128 // ps)

    scratch_shapes = [
        pltpu.VMEM((2, tile_pages, ps, F), kq.dtype),
        pltpu.VMEM((2, tile_pages, ps, F), vq.dtype),
    ]
    if quantized:
        scratch_shapes += [
            pltpu.VMEM((2, tile_pages, 1, ps), jnp.float32),
            pltpu.VMEM((2, tile_pages, 1, ps), jnp.float32),
        ]
    scratch_shapes.append(
        pltpu.SemaphoreType.DMA((2, 4 if quantized else 2, tile_pages))
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(T // block_q,),
        in_specs=[
            pl.BlockSpec((block_q, Hq, D), lambda qb, *_: (qb, 0, 0)),
            *_pool_in_specs(quantized),
        ],
        out_specs=pl.BlockSpec((block_q, Hq, D), lambda qb, *_: (qb, 0, 0)),
        scratch_shapes=scratch_shapes,
    )
    kernel = pl.pallas_call(
        functools.partial(
            _kernel_folded,
            page_size=ps,
            max_pages=max_pages,
            tile_pages=tile_pages,
            block_q=block_q,
            num_kv_heads=Hkv,
            head_dim=D,
            quantized=quantized,
        ),
        out_shape=jax.ShapeDtypeStruct((T, Hq, D), q.dtype),
        grid_spec=grid_spec,
        interpret=interpret,
    )
    args = (kq, vq, ks, vs) if quantized else (kq, vq)
    return kernel(
        page_table.astype(jnp.int32), positions.astype(jnp.int32), q, *args
    )


@functools.partial(
    jax.jit, static_argnames=("interpret", "block_q", "lookahead")
)
def paged_prefill_attention_pallas(
    q: jnp.ndarray,  # [T, Hq, D] bucket-padded chunk
    k_pages,  # [P, ps, Hkv, D] plain or QuantizedPages
    v_pages,
    page_table: jnp.ndarray,  # [max_pages] int32
    positions: jnp.ndarray,  # [T] int32 absolute positions (unit-stride)
    block_q: int = 128,
    interpret: bool = False,
    lookahead: bool = True,
) -> jnp.ndarray:
    """Flash prefill dispatcher: lookahead (cross-program tile prefetch)
    when the window fits VMEM, else the basic in-program double buffer."""
    T, Hq, D = q.shape
    kq, vq, ks, vs, quantized = _unpack_pools(k_pages, v_pages)
    P, ps, Hkv, _ = kq.shape
    max_pages = page_table.shape[0]
    assert T % block_q == 0, f"chunk {T} % block_q {block_q}"
    tile_pages = max(1, 128 // ps)
    W = (
        prefill_lookahead_window(ps, tile_pages, Hkv, D, kq.dtype.itemsize)
        if lookahead
        else 0
    )

    if W >= 1:
        scratch_shapes = [
            pltpu.VMEM((2, W, tile_pages, ps, Hkv, D), kq.dtype),
            pltpu.VMEM((2, W, tile_pages, ps, Hkv, D), vq.dtype),
        ]
        if quantized:
            scratch_shapes += [
                pltpu.VMEM((2, W, tile_pages, 1, ps), jnp.float32),
                pltpu.VMEM((2, W, tile_pages, 1, ps), jnp.float32),
            ]
        scratch_shapes += [
            pltpu.VMEM((2, tile_pages, ps, Hkv, D), kq.dtype),
            pltpu.VMEM((2, tile_pages, ps, Hkv, D), vq.dtype),
        ]
        if quantized:
            scratch_shapes += [
                pltpu.VMEM((2, tile_pages, 1, ps), jnp.float32),
                pltpu.VMEM((2, tile_pages, 1, ps), jnp.float32),
            ]
        C = 4 if quantized else 2
        scratch_shapes += [
            pltpu.SemaphoreType.DMA((2, W, C, tile_pages)),
            pltpu.SemaphoreType.DMA((2, C, tile_pages)),
        ]
        body = functools.partial(
            _kernel_lookahead,
            page_size=ps,
            max_pages=max_pages,
            tile_pages=tile_pages,
            block_q=block_q,
            lookahead=W,
            quantized=quantized,
        )
        # cross-program scratch persistence (query block b prefetches b+1's
        # context tiles into the opposite parity) requires the grid to run
        # SERIALLY — pin it, as the decode lookahead kernel does
        compiler_params = pltpu.TPUCompilerParams(dimension_semantics=("arbitrary",))
    else:
        scratch_shapes = [
            pltpu.VMEM((2, tile_pages, ps, Hkv, D), kq.dtype),
            pltpu.VMEM((2, tile_pages, ps, Hkv, D), vq.dtype),
        ]
        if quantized:
            scratch_shapes += [
                pltpu.VMEM((2, tile_pages, 1, ps), jnp.float32),
                pltpu.VMEM((2, tile_pages, 1, ps), jnp.float32),
            ]
        scratch_shapes.append(
            pltpu.SemaphoreType.DMA((2, 4 if quantized else 2, tile_pages))
        )
        body = functools.partial(
            _kernel,
            page_size=ps,
            max_pages=max_pages,
            tile_pages=tile_pages,
            block_q=block_q,
            quantized=quantized,
        )
        compiler_params = None

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(T // block_q,),
        in_specs=[
            pl.BlockSpec((block_q, Hq, D), lambda qb, *_: (qb, 0, 0)),
            *_pool_in_specs(quantized),
        ],
        out_specs=pl.BlockSpec((block_q, Hq, D), lambda qb, *_: (qb, 0, 0)),
        scratch_shapes=scratch_shapes,
    )
    kwargs = {}
    if compiler_params is not None:
        kwargs["compiler_params"] = compiler_params
    kernel = pl.pallas_call(
        body,
        out_shape=jax.ShapeDtypeStruct((T, Hq, D), q.dtype),
        grid_spec=grid_spec,
        interpret=interpret,
        **kwargs,
    )
    args = (kq, vq, ks, vs) if quantized else (kq, vq)
    return kernel(
        page_table.astype(jnp.int32), positions.astype(jnp.int32), q, *args
    )


@functools.partial(jax.jit, static_argnames=("interpret", "block_q"))
def paged_prefill_dmaonly(
    q: jnp.ndarray,
    k_pages,
    v_pages,
    page_table: jnp.ndarray,
    positions: jnp.ndarray,
    block_q: int = 128,
    interpret: bool = False,
) -> jnp.ndarray:
    """Null-hypothesis A/B partner of ``paged_prefill_attention_pallas``
    (basic variant): same grid geometry and DMA stream, no attention math.
    ``tools/profile_prefill.py`` differences this against the real kernel to
    split a prefill call's cost into DMA floor vs exposed compute. Output is
    garbage by design — never dispatch it for serving."""
    T, Hq, D = q.shape
    kq, vq, ks, vs, quantized = _unpack_pools(k_pages, v_pages)
    P, ps, Hkv, _ = kq.shape
    max_pages = page_table.shape[0]
    assert T % block_q == 0, f"chunk {T} % block_q {block_q}"
    tile_pages = max(1, 128 // ps)

    scratch_shapes = [
        pltpu.VMEM((2, tile_pages, ps, Hkv, D), kq.dtype),
        pltpu.VMEM((2, tile_pages, ps, Hkv, D), vq.dtype),
    ]
    if quantized:
        scratch_shapes += [
            pltpu.VMEM((2, tile_pages, 1, ps), jnp.float32),
            pltpu.VMEM((2, tile_pages, 1, ps), jnp.float32),
        ]
    scratch_shapes.append(
        pltpu.SemaphoreType.DMA((2, 4 if quantized else 2, tile_pages))
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(T // block_q,),
        in_specs=[
            pl.BlockSpec((block_q, Hq, D), lambda qb, *_: (qb, 0, 0)),
            *_pool_in_specs(quantized),
        ],
        out_specs=pl.BlockSpec((block_q, Hq, D), lambda qb, *_: (qb, 0, 0)),
        scratch_shapes=scratch_shapes,
    )
    kernel = pl.pallas_call(
        functools.partial(
            _kernel_dmaonly,
            page_size=ps,
            max_pages=max_pages,
            tile_pages=tile_pages,
            block_q=block_q,
            quantized=quantized,
        ),
        out_shape=jax.ShapeDtypeStruct((T, Hq, D), q.dtype),
        grid_spec=grid_spec,
        interpret=interpret,
    )
    args = (kq, vq, ks, vs) if quantized else (kq, vq)
    return kernel(
        page_table.astype(jnp.int32), positions.astype(jnp.int32), q, *args
    )
