"""Pallas TPU kernel: chunked-prefill flash attention over the paged KV pool.

A prefill chunk's queries attend causally over the sequence's paged context
(which already contains the chunk's own rows — the model scatters before
attending). The XLA reference path (ops/attention.py paged_prefill_attention)
materializes the whole gathered context ``[max_pages * ps, Hkv, D]`` plus a
``[Hq, T, S]`` score tensor per layer; this kernel streams context pages
HBM -> VMEM in multi-page tiles with double buffering and keeps the online
softmax in VMEM, so HBM traffic is one pass over the needed pages and no
score/gather materialization at all. Causality additionally bounds work per
query block: block b only loops over tiles up to its last query position.

Contract: q [T, Hq, D] (bucket-padded chunk), k/v pages [P, ps, Hkv, D],
page_table [max_pages] (this sequence's logical pages, trash page 0 padding),
positions [T] absolute and **unit-stride** (positions[i] = positions[0] + i —
the mask derives row positions from positions[block_start] + row offset;
engine chunks always satisfy this; the XLA reference only needs monotone).
GQA folds as [Hkv, G*Bq, D] batched matmuls.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30


def _tile_dma_helpers(page_table_ref, k_hbm, v_hbm, k_scratch, v_scratch, sems,
                      tile_pages: int, max_pages: int):
    """Shared double-buffered context-tile DMA scaffolding for the prefill
    kernels: returns (start, wait), each taking (buf, tile). The final tile
    clamps page indices to max_pages - 1 (aliased content is masked by the
    callers' ctx-bound check)."""

    def tile_dma(buf, tile):
        copies = []
        for p in range(tile_pages):
            idx = jnp.minimum(tile * tile_pages + p, max_pages - 1)
            copies.append(
                (
                    pltpu.make_async_copy(
                        k_hbm.at[page_table_ref[idx]], k_scratch.at[buf, p],
                        sems.at[buf, 0, p],
                    ),
                    pltpu.make_async_copy(
                        v_hbm.at[page_table_ref[idx]], v_scratch.at[buf, p],
                        sems.at[buf, 1, p],
                    ),
                )
            )
        return copies

    def start(buf, tile):
        for kc, vc in tile_dma(buf, tile):
            kc.start()
            vc.start()

    def wait(buf, tile):
        for kc, vc in tile_dma(buf, tile):
            kc.wait()
            vc.wait()

    return start, wait


def _kernel(
    # scalar prefetch
    page_table_ref,  # [max_pages] SMEM
    positions_ref,  # [T] SMEM
    # inputs
    q_ref,  # [Bq, Hq, D] VMEM (this query block)
    k_hbm,  # [P, ps, Hkv, D] HBM
    v_hbm,  # [P, ps, Hkv, D] HBM
    # output
    out_ref,  # [Bq, Hq, D] VMEM
    # scratch
    k_scratch,  # [2, TP, ps, Hkv, D] VMEM
    v_scratch,  # [2, TP, ps, Hkv, D] VMEM
    sems,  # DMA sems [2, 2, TP]
    *,
    page_size: int,
    max_pages: int,
    tile_pages: int,
    block_q: int,
):
    qb = pl.program_id(0)
    Bq, Hq, D = q_ref.shape
    Hkv = k_hbm.shape[2]
    G = Hq // Hkv
    TP = tile_pages
    S = TP * page_size  # context tile length

    # this block's query positions and causal context bound
    q_start = qb * block_q
    last_pos = positions_ref[q_start + Bq - 1]
    ctx_len = last_pos + 1
    n_tiles = jnp.minimum(
        pl.cdiv(ctx_len, S), pl.cdiv(jnp.int32(max_pages * page_size), S)
    )

    # [Hkv, G*Bq, D] query layout: head-major groups so each kv head's block
    # is one batched matmul operand
    q = (
        q_ref[...]
        .astype(jnp.float32)
        .reshape(Bq, Hkv, G, D)
        .transpose(1, 2, 0, 3)
        .reshape(Hkv, G * Bq, D)
    )
    scale = 1.0 / jnp.sqrt(jnp.float32(D))

    start, wait = _tile_dma_helpers(
        page_table_ref, k_hbm, v_hbm, k_scratch, v_scratch, sems, TP, max_pages
    )
    start(0, 0)

    # causal mask geometry, built directly in 2D [G*Bq, S] (Mosaic rejects 1D
    # vector reshapes): row i is block-row i % Bq; its query position is
    # positions[q_start] + (i % Bq)
    pos0 = positions_ref[q_start]
    iota_row = jax.lax.broadcasted_iota(jnp.int32, (G * Bq, S), 0)
    iota_col = jax.lax.broadcasted_iota(jnp.int32, (G * Bq, S), 1)
    q_pos_2d = pos0 + jax.lax.rem(iota_row, Bq)  # [G*Bq, S]

    def body(t, carry):
        m, l, acc = carry
        buf = jax.lax.rem(t, 2)

        @pl.when(t + 1 < n_tiles)
        def _():
            start(jax.lax.rem(t + 1, 2), t + 1)

        wait(buf, t)

        kt = (
            k_scratch[buf]
            .astype(jnp.float32)
            .reshape(S, Hkv, D)
            .transpose(1, 0, 2)
        )  # [Hkv, S, D]
        vt = (
            v_scratch[buf]
            .astype(jnp.float32)
            .reshape(S, Hkv, D)
            .transpose(1, 0, 2)
        )

        # [Hkv, G*Bq, S]
        scores = (
            jax.lax.dot_general(
                q, kt, (((2,), (2,)), ((0,), (0,))), preferred_element_type=jnp.float32
            )
            * scale
        )
        ctx_idx = t * S + iota_col
        # causal, and never beyond the page table (the final tile clamps its
        # page indices to max_pages - 1, which would alias earlier content)
        mask = (ctx_idx <= q_pos_2d) & (ctx_idx < max_pages * page_size)
        scores = jnp.where(mask[None], scores, _NEG_INF)

        chunk_max = jnp.max(scores, axis=-1)  # [Hkv, G*Bq]
        new_m = jnp.maximum(m, chunk_max)
        corr = jnp.exp(m - new_m)
        probs = jnp.exp(scores - new_m[..., None])
        new_l = l * corr + jnp.sum(probs, axis=-1)
        chunk_out = jax.lax.dot_general(
            probs, vt, (((2,), (1,)), ((0,), (0,))), preferred_element_type=jnp.float32
        )
        new_acc = acc * corr[..., None] + chunk_out
        return new_m, new_l, new_acc

    m0 = jnp.full((Hkv, G * Bq), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((Hkv, G * Bq), jnp.float32)
    acc0 = jnp.zeros((Hkv, G * Bq, D), jnp.float32)
    m, l, acc = jax.lax.fori_loop(0, n_tiles, body, (m0, l0, acc0))

    out = acc / jnp.maximum(l, 1e-20)[..., None]  # [Hkv, G*Bq, D]
    out_ref[...] = (
        out.reshape(Hkv, G, Bq, D).transpose(2, 0, 1, 3).reshape(Bq, Hq, D)
    ).astype(out_ref.dtype)


def _kernel_folded(
    # scalar prefetch
    page_table_ref,  # [max_pages] SMEM
    positions_ref,  # [T] SMEM
    # inputs
    q_ref,  # [Bq, Hq, D] VMEM (this query block)
    k_hbm,  # [P, ps, Hkv*D] HBM (heads folded into lanes)
    v_hbm,  # [P, ps, Hkv*D] HBM
    # output
    out_ref,  # [Bq, Hq, D] VMEM
    # scratch
    k_scratch,  # [2, TP, ps, Hkv*D] VMEM
    v_scratch,  # [2, TP, ps, Hkv*D] VMEM
    sems,  # DMA sems [2, 2, TP]
    *,
    page_size: int,
    max_pages: int,
    tile_pages: int,
    block_q: int,
    num_kv_heads: int,
    head_dim: int,
):
    """Folded-lane flash prefill for head_dim < 128 (see the decode
    _kernel_folded in paged_attention.py for the trick): every (query row,
    head) pair becomes one row of a zero-placed folded Q [Bq*Hq, Hkv*D], so a
    single [R, F] x [S, F] matmul yields exact per-head scores — the zero
    slices kill cross-head terms and cost only Hkv x extra MACs on an op
    that is a rounding error of prefill FLOPs. All shape changes are
    leading-dim merges/splits (minor dim untouched: Mosaic-legal)."""
    qb = pl.program_id(0)
    Bq, Hq, D = q_ref.shape
    Hkv, F = num_kv_heads, num_kv_heads * head_dim
    G = Hq // Hkv
    TP = tile_pages
    S = TP * page_size
    R = Bq * Hq

    q_start = qb * block_q
    last_pos = positions_ref[q_start + Bq - 1]
    ctx_len = last_pos + 1
    n_tiles = jnp.minimum(
        pl.cdiv(ctx_len, S), pl.cdiv(jnp.int32(max_pages * page_size), S)
    )

    # folded queries [R, F]: row r = (t, h) with t = r // Hq, h = r % Hq;
    # q[t, h] occupies kv(h) = (h // G)'s D-slice, zeros elsewhere
    q2 = q_ref[...].reshape(R, D)  # leading merge only
    lane = jax.lax.broadcasted_iota(jnp.int32, (R, F), 1)
    row = jax.lax.broadcasted_iota(jnp.int32, (R, F), 0)
    own = (lane // D == jax.lax.rem(row, Hq) // G).astype(jnp.float32)
    qtile = jnp.concatenate([q2.astype(jnp.float32)] * Hkv, axis=1)  # [R, F]
    qf = (qtile * own).astype(q_ref.dtype)
    scale = 1.0 / jnp.sqrt(jnp.float32(D))

    start, wait = _tile_dma_helpers(
        page_table_ref, k_hbm, v_hbm, k_scratch, v_scratch, sems, TP, max_pages
    )
    start(0, 0)

    # causal geometry: row r's query position = positions[q_start] + r // Hq
    pos0 = positions_ref[q_start]
    iota_row = jax.lax.broadcasted_iota(jnp.int32, (R, S), 0)
    iota_col = jax.lax.broadcasted_iota(jnp.int32, (R, S), 1)
    q_pos_2d = pos0 + iota_row // Hq  # [R, S]

    def body(t, carry):
        m, l, acc = carry
        buf = jax.lax.rem(t, 2)

        @pl.when(t + 1 < n_tiles)
        def _():
            start(jax.lax.rem(t + 1, 2), t + 1)

        wait(buf, t)

        kf = k_scratch[buf].reshape(S, F)  # leading merge, bf16
        vf = v_scratch[buf].reshape(S, F)

        # [R, S] exact per-(row, head) scores via the folded contraction
        scores = jax.lax.dot_general(
            qf, kf, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale
        ctx_idx = t * S + iota_col
        mask = (ctx_idx <= q_pos_2d) & (ctx_idx < max_pages * page_size)
        scores = jnp.where(mask, scores, _NEG_INF)

        chunk_max = jnp.max(scores, axis=-1)  # [R]
        new_m = jnp.maximum(m, chunk_max)
        corr = jnp.exp(m - new_m)
        probs = jnp.exp(scores - new_m[:, None])
        new_l = l * corr + jnp.sum(probs, axis=-1)
        # [R, F] = [R, S] x [S, F]
        chunk_out = jax.lax.dot_general(
            probs.astype(kf.dtype), vf, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        new_acc = acc * corr[:, None] + chunk_out
        return new_m, new_l, new_acc

    m0 = jnp.full((R,), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((R,), jnp.float32)
    acc0 = jnp.zeros((R, F), jnp.float32)
    m, l, acc = jax.lax.fori_loop(0, n_tiles, body, (m0, l0, acc0))

    # keep each row's owned D-slice: zero the rest and fold the Hkv slices
    acc_m = acc * own
    out2 = acc_m[:, 0:D]
    for j in range(1, Hkv):
        out2 = out2 + acc_m[:, j * D : (j + 1) * D]
    out2 = out2 / jnp.maximum(l, 1e-20)[:, None]
    out_ref[...] = out2.reshape(Bq, Hq, D).astype(out_ref.dtype)  # leading split


@functools.partial(jax.jit, static_argnames=("interpret", "block_q"))
def paged_prefill_attention_pallas_folded(
    q: jnp.ndarray,  # [T, Hq, D] bucket-padded chunk
    k_pages: jnp.ndarray,  # [P, ps, Hkv*D] folded, or [P, ps, Hkv, D]
    v_pages: jnp.ndarray,
    page_table: jnp.ndarray,  # [max_pages] int32
    positions: jnp.ndarray,  # [T] int32 absolute positions (unit-stride)
    block_q: int = 128,
    interpret: bool = False,
) -> jnp.ndarray:
    T, Hq, D = q.shape
    if k_pages.ndim == 4:  # direct-call convenience (tests)
        P, ps, Hkv, _ = k_pages.shape
        k_pages = k_pages.reshape(P, ps, Hkv * D)
        v_pages = v_pages.reshape(P, ps, Hkv * D)
    P, ps, F = k_pages.shape
    Hkv = F // D
    max_pages = page_table.shape[0]
    assert T % block_q == 0, f"chunk {T} % block_q {block_q}"
    tile_pages = max(1, 128 // ps)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(T // block_q,),
        in_specs=[
            pl.BlockSpec((block_q, Hq, D), lambda qb, *_: (qb, 0, 0)),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=pl.BlockSpec((block_q, Hq, D), lambda qb, *_: (qb, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((2, tile_pages, ps, F), k_pages.dtype),
            pltpu.VMEM((2, tile_pages, ps, F), v_pages.dtype),
            pltpu.SemaphoreType.DMA((2, 2, tile_pages)),
        ],
    )
    kernel = pl.pallas_call(
        functools.partial(
            _kernel_folded,
            page_size=ps,
            max_pages=max_pages,
            tile_pages=tile_pages,
            block_q=block_q,
            num_kv_heads=Hkv,
            head_dim=D,
        ),
        out_shape=jax.ShapeDtypeStruct((T, Hq, D), q.dtype),
        grid_spec=grid_spec,
        interpret=interpret,
    )
    return kernel(page_table.astype(jnp.int32), positions.astype(jnp.int32), q, k_pages, v_pages)


@functools.partial(jax.jit, static_argnames=("interpret", "block_q"))
def paged_prefill_attention_pallas(
    q: jnp.ndarray,  # [T, Hq, D] bucket-padded chunk
    k_pages: jnp.ndarray,  # [P, ps, Hkv, D]
    v_pages: jnp.ndarray,  # [P, ps, Hkv, D]
    page_table: jnp.ndarray,  # [max_pages] int32
    positions: jnp.ndarray,  # [T] int32 absolute positions (unit-stride)
    block_q: int = 128,
    interpret: bool = False,
) -> jnp.ndarray:
    T, Hq, D = q.shape
    P, ps, Hkv, _ = k_pages.shape
    max_pages = page_table.shape[0]
    assert T % block_q == 0, f"chunk {T} % block_q {block_q}"
    tile_pages = max(1, 128 // ps)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(T // block_q,),
        in_specs=[
            pl.BlockSpec((block_q, Hq, D), lambda qb, *_: (qb, 0, 0)),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=pl.BlockSpec((block_q, Hq, D), lambda qb, *_: (qb, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((2, tile_pages, ps, Hkv, D), k_pages.dtype),
            pltpu.VMEM((2, tile_pages, ps, Hkv, D), v_pages.dtype),
            pltpu.SemaphoreType.DMA((2, 2, tile_pages)),
        ],
    )
    kernel = pl.pallas_call(
        functools.partial(
            _kernel,
            page_size=ps,
            max_pages=max_pages,
            tile_pages=tile_pages,
            block_q=block_q,
        ),
        out_shape=jax.ShapeDtypeStruct((T, Hq, D), q.dtype),
        grid_spec=grid_spec,
        interpret=interpret,
    )
    return kernel(page_table.astype(jnp.int32), positions.astype(jnp.int32), q, k_pages, v_pages)
