"""Pallas TPU kernel: paged MLA (latent) decode attention.

The absorbed MLA formulation makes decode attention a pure latent-space
operation: with q_cat = [q_nope·W_kb ; q_rope] (computed outside, where the
MXU-shaped einsums belong) and each cached row = [norm(latent) ; rope(k_rope)],
the score is a single dot product over latent_dim = d_c + d_r, and the output
is the probability-weighted sum of the latent part only — the per-head v-up
projection also happens outside. So the kernel streams latent pages from HBM
(page-table scalar prefetch, double-buffered VMEM scratch) exactly like the
GQA kernel in paged_attention.py, but with one fused [H, latent] x [latent,
ps] matmul per page and an accumulator over rows' first d_c dims.

Contract (matches DeepseekModel._absorbed_attention's decode path):
  q_cat [B, H, d_c + d_r] — PRE-SCALED by 1/sqrt(d_n + d_r)
  pages [P, ps, d_c + d_r], page_tables [B, max_pages], positions [B]
  -> a_lat [B, H, d_c] (unprojected attention output in latent space)
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30


def _kernel(
    # scalar prefetch
    page_tables_ref,  # [B, max_pages] SMEM
    lengths_ref,  # [B] SMEM
    # inputs
    q_ref,  # [1, H, latent] VMEM (this sequence's pre-scaled folded query)
    pages_hbm,  # [P, ps, latent] HBM
    # output
    out_ref,  # [1, H, d_c] VMEM
    # scratch
    scratch,  # [2, ps, latent] VMEM
    sems,  # DMA sems [2]
    *,
    page_size: int,
    d_c: int,
):
    b = pl.program_id(0)
    length = lengths_ref[b]
    n_pages = jnp.maximum(1, pl.cdiv(length, page_size))

    q = q_ref[0].astype(jnp.float32)  # [H, latent]

    def dma(slot, i):
        return pltpu.make_async_copy(
            pages_hbm.at[page_tables_ref[b, i]], scratch.at[slot], sems.at[slot]
        )

    dma(0, 0).start()

    def body(i, carry):
        m, l, acc = carry
        slot = jax.lax.rem(i, 2)
        next_slot = jax.lax.rem(i + 1, 2)

        @pl.when(i + 1 < n_pages)
        def _():
            dma(next_slot, i + 1).start()

        dma(slot, i).wait()
        rows = scratch[slot].astype(jnp.float32)  # [ps, latent]

        # [H, ps] = [H, latent] x [latent, ps]
        scores = jax.lax.dot_general(
            q, rows, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        idx = i * page_size + jax.lax.broadcasted_iota(jnp.int32, (1, page_size), 1)
        scores = jnp.where(idx < length, scores, _NEG_INF)

        chunk_max = jnp.max(scores, axis=-1)  # [H]
        new_m = jnp.maximum(m, chunk_max)
        corr = jnp.exp(m - new_m)
        probs = jnp.exp(scores - new_m[:, None])  # [H, ps]
        new_l = l * corr + jnp.sum(probs, axis=-1)
        # accumulate over the latent part of the rows: [H, d_c]
        chunk_out = jax.lax.dot_general(
            probs, rows[:, :d_c], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        new_acc = acc * corr[:, None] + chunk_out
        return new_m, new_l, new_acc

    H = q_ref.shape[1]
    m0 = jnp.full((H,), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((H,), jnp.float32)
    acc0 = jnp.zeros((H, d_c), jnp.float32)
    m, l, acc = jax.lax.fori_loop(0, n_pages, body, (m0, l0, acc0))

    out_ref[0] = (acc / jnp.maximum(l, 1e-20)[:, None]).astype(out_ref.dtype)


def _kernel_lookahead(
    # scalar prefetch
    page_tables_ref,  # [B, max_pages] SMEM
    lengths_ref,  # [B] SMEM
    # inputs
    q_ref,  # [1, H, latent] VMEM
    pages_hbm,  # [P, ps, latent] HBM
    # output
    out_ref,  # [1, H, d_c] VMEM
    # scratch
    pre,  # [2, W, ps, latent] VMEM — per-parity prefetch window
    tail,  # [2, ps, latent] VMEM — double buffer for pages >= W
    sems_pre,  # [2, W]
    sems_tail,  # [2]
    *,
    page_size: int,
    d_c: int,
    lookahead: int,
):
    """Cross-program DMA pipelining (see paged_attention._kernel_lookahead):
    program b issues program b+1's first W latent-page DMAs into the opposite
    parity's slots while computing on its own (prefetched by b-1). Latent
    pages are small (~147 KB at ps=128/latent=576), so the per-program DMA
    LATENCY — not bandwidth — dominates the stream; hiding it across
    programs matters even more here than for the GQA kernel."""
    b = pl.program_id(0)
    nb = pl.num_programs(0)
    par = jax.lax.rem(b, 2)
    W = lookahead
    length = lengths_ref[b]
    n_pages = jnp.maximum(1, pl.cdiv(length, page_size))

    q = q_ref[0].astype(jnp.float32)  # [H, latent]

    def pre_dma(parity, j, seq_idx):
        return pltpu.make_async_copy(
            pages_hbm.at[page_tables_ref[seq_idx, j]],
            pre.at[parity, j],
            sems_pre.at[parity, j],
        )

    def tail_dma(slot, i):
        return pltpu.make_async_copy(
            pages_hbm.at[page_tables_ref[b, i]], tail.at[slot], sems_tail.at[slot]
        )

    def issue_pre(seq_idx, parity):
        npg = jnp.maximum(1, pl.cdiv(lengths_ref[seq_idx], page_size))
        for j in range(W):

            @pl.when(j < npg)
            def _(j=j):
                pre_dma(parity, j, seq_idx).start()

    @pl.when(b == 0)
    def _():
        issue_pre(0, 0)

    @pl.when(b + 1 < nb)
    def _():
        issue_pre(b + 1, 1 - par)

    @pl.when(W < n_pages)
    def _():
        tail_dma(W % 2, W).start()

    def merge(carry, rows, i):
        m, l, acc = carry
        scores = jax.lax.dot_general(
            q, rows, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        idx = i * page_size + jax.lax.broadcasted_iota(jnp.int32, (1, page_size), 1)
        scores = jnp.where(idx < length, scores, _NEG_INF)
        chunk_max = jnp.max(scores, axis=-1)
        new_m = jnp.maximum(m, chunk_max)
        corr = jnp.exp(m - new_m)
        probs = jnp.exp(scores - new_m[:, None])
        new_l = l * corr + jnp.sum(probs, axis=-1)
        chunk_out = jax.lax.dot_general(
            probs, rows[:, :d_c], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return new_m, new_l, acc * corr[:, None] + chunk_out

    def pre_body(j, carry):
        pre_dma(par, j, b).wait()
        return merge(carry, pre[par, j].astype(jnp.float32), j)

    def tail_body(j, carry):
        slot = jax.lax.rem(j, 2)
        next_slot = jax.lax.rem(j + 1, 2)

        @pl.when(j + 1 < n_pages)
        def _():
            tail_dma(next_slot, j + 1).start()

        tail_dma(slot, j).wait()
        return merge(carry, tail[slot].astype(jnp.float32), j)

    H = q_ref.shape[1]
    m0 = jnp.full((H,), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((H,), jnp.float32)
    acc0 = jnp.zeros((H, d_c), jnp.float32)
    carry = jax.lax.fori_loop(0, jnp.minimum(W, n_pages), pre_body, (m0, l0, acc0))
    m, l, acc = jax.lax.fori_loop(W, n_pages, tail_body, carry)

    out_ref[0] = (acc / jnp.maximum(l, 1e-20)[:, None]).astype(out_ref.dtype)


#: scratch budget mirrors paged_attention's (latent pages are much smaller)
_LOOKAHEAD_SCRATCH_BYTES = 6 * 1024 * 1024


def _mla_lookahead_window(page_size: int, latent: int, itemsize: int) -> int:
    page_bytes = page_size * latent * itemsize
    budget = _LOOKAHEAD_SCRATCH_BYTES - 2 * page_bytes
    return max(0, min(4, budget // (2 * page_bytes)))


@functools.partial(jax.jit, static_argnames=("d_c", "lookahead", "interpret"))
def paged_mla_decode_attention_pallas(
    q_cat: jnp.ndarray,  # [B, H, latent] pre-scaled
    pages: jnp.ndarray,  # [P, ps, latent]
    page_tables: jnp.ndarray,  # [B, max_pages] int32
    positions: jnp.ndarray,  # [B] int32 query positions
    d_c: int,
    lookahead: bool = False,
    interpret: bool = False,
) -> jnp.ndarray:
    B, H, latent = q_cat.shape
    P, ps, _ = pages.shape
    lengths = positions.astype(jnp.int32) + 1
    # r5 on-chip A/B (tiny-mla bs32, healthy tunnel, best of 3):
    #   classic (this default)  4671 tok/s      lookahead  4534 tok/s
    # — within round noise of each other, so the MLA stream keeps the simpler
    # classic double buffer (its one small latent DMA per page pipelines well
    # already); the GQA kernel's +14.7% from cross-program prefetch did NOT
    # transfer. DYNTPU_DECODE_KERNEL=lookahead opts in for future hardware —
    # resolved by the DISPATCHER (deepseek._mla_decode_pallas) and passed as
    # a static jit argument: an os.environ read here would freeze into the
    # first-traced executable per shape (ADVICE r5).
    W = _mla_lookahead_window(ps, latent, pages.dtype.itemsize) if lookahead else 0

    if W >= 1:
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(B,),
            in_specs=[
                pl.BlockSpec((1, H, latent), lambda b, *_: (b, 0, 0)),
                pl.BlockSpec(memory_space=pl.ANY),
            ],
            out_specs=pl.BlockSpec((1, H, d_c), lambda b, *_: (b, 0, 0)),
            scratch_shapes=[
                pltpu.VMEM((2, W, ps, latent), pages.dtype),
                pltpu.VMEM((2, ps, latent), pages.dtype),
                pltpu.SemaphoreType.DMA((2, W)),
                pltpu.SemaphoreType.DMA((2,)),
            ],
        )
        kernel = pl.pallas_call(
            functools.partial(_kernel_lookahead, page_size=ps, d_c=d_c, lookahead=W),
            out_shape=jax.ShapeDtypeStruct((B, H, d_c), q_cat.dtype),
            grid_spec=grid_spec,
            # cross-program scratch persistence (program b prefetches b+1's
            # pages into the opposite parity's slots) requires the grid to run
            # SERIALLY — pin it rather than relying on the implicit default
            compiler_params=pltpu.TPUCompilerParams(
                dimension_semantics=("arbitrary",)
            ),
            interpret=interpret,
        )
        return kernel(page_tables.astype(jnp.int32), lengths, q_cat, pages)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B,),
        in_specs=[
            pl.BlockSpec((1, H, latent), lambda b, *_: (b, 0, 0)),
            pl.BlockSpec(memory_space=pl.ANY),  # latent pages stay in HBM
        ],
        out_specs=pl.BlockSpec((1, H, d_c), lambda b, *_: (b, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((2, ps, latent), pages.dtype),
            pltpu.SemaphoreType.DMA((2,)),
        ],
    )
    kernel = pl.pallas_call(
        functools.partial(_kernel, page_size=ps, d_c=d_c),
        out_shape=jax.ShapeDtypeStruct((B, H, d_c), q_cat.dtype),
        grid_spec=grid_spec,
        interpret=interpret,
    )
    return kernel(page_tables.astype(jnp.int32), lengths, q_cat, pages)


def _prefill_kernel(
    # scalar prefetch
    page_table_ref,  # [max_pages] SMEM
    positions_ref,  # [T] SMEM
    # inputs
    q_ref,  # [Bq, H, latent] VMEM (pre-scaled folded queries)
    pages_hbm,  # [P, ps, latent] HBM
    # output
    out_ref,  # [Bq, H, d_c] VMEM
    # scratch
    scratch,  # [2, TP, ps, latent] VMEM
    sems,  # DMA sems [2, TP]
    *,
    page_size: int,
    max_pages: int,
    tile_pages: int,
    block_q: int,
    d_c: int,
):
    qb = pl.program_id(0)
    Bq, H, latent = q_ref.shape
    TP = tile_pages
    S = TP * page_size

    q_start = qb * block_q
    ctx_len = positions_ref[q_start + Bq - 1] + 1
    n_tiles = jnp.minimum(
        pl.cdiv(ctx_len, S), pl.cdiv(jnp.int32(max_pages * page_size), S)
    )

    q = q_ref[...].astype(jnp.float32).transpose(1, 0, 2)  # [H, Bq, latent]

    def tile_dma(buf, tile):
        copies = []
        for p in range(TP):
            idx = jnp.minimum(tile * TP + p, max_pages - 1)  # clamp; masked below
            copies.append(
                pltpu.make_async_copy(
                    pages_hbm.at[page_table_ref[idx]], scratch.at[buf, p], sems.at[buf, p]
                )
            )
        return copies

    def start(buf, tile):
        for c_ in tile_dma(buf, tile):
            c_.start()

    def wait(buf, tile):
        for c_ in tile_dma(buf, tile):
            c_.wait()

    start(0, 0)

    pos0 = positions_ref[q_start]
    iota_row = jax.lax.broadcasted_iota(jnp.int32, (Bq, S), 0)
    iota_col = jax.lax.broadcasted_iota(jnp.int32, (Bq, S), 1)
    q_pos_2d = pos0 + iota_row  # unit-stride positions within the block

    def body(t, carry):
        m, l, acc = carry
        buf = jax.lax.rem(t, 2)

        @pl.when(t + 1 < n_tiles)
        def _():
            start(jax.lax.rem(t + 1, 2), t + 1)

        wait(buf, t)
        rows = scratch[buf].astype(jnp.float32).reshape(S, latent)

        # [H, Bq, S] = [H, Bq, latent] x [S, latent]
        scores = jax.lax.dot_general(
            q, rows, (((2,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ctx_idx = t * S + iota_col
        mask = (ctx_idx <= q_pos_2d) & (ctx_idx < max_pages * page_size)
        scores = jnp.where(mask[None], scores, _NEG_INF)

        chunk_max = jnp.max(scores, axis=-1)  # [H, Bq]
        new_m = jnp.maximum(m, chunk_max)
        corr = jnp.exp(m - new_m)
        probs = jnp.exp(scores - new_m[..., None])  # [H, Bq, S]
        new_l = l * corr + jnp.sum(probs, axis=-1)
        # [H, Bq, d_c] accumulated over the latent part only
        chunk_out = jax.lax.dot_general(
            probs, rows[:, :d_c], (((2,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        new_acc = acc * corr[..., None] + chunk_out
        return new_m, new_l, new_acc

    m0 = jnp.full((H, Bq), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((H, Bq), jnp.float32)
    acc0 = jnp.zeros((H, Bq, d_c), jnp.float32)
    m, l, acc = jax.lax.fori_loop(0, n_tiles, body, (m0, l0, acc0))

    out = acc / jnp.maximum(l, 1e-20)[..., None]  # [H, Bq, d_c]
    out_ref[...] = out.transpose(1, 0, 2).astype(out_ref.dtype)


@functools.partial(jax.jit, static_argnames=("d_c", "block_q", "interpret"))
def paged_mla_prefill_attention_pallas(
    q_cat: jnp.ndarray,  # [T, H, latent] pre-scaled folded queries
    pages: jnp.ndarray,  # [P, ps, latent]
    page_table: jnp.ndarray,  # [max_pages] int32
    positions: jnp.ndarray,  # [T] int32, unit-stride within the chunk
    d_c: int,
    block_q: int | None = None,
    interpret: bool = False,
) -> jnp.ndarray:
    """Chunked-prefill MLA attention (the latent-space analogue of
    ops/pallas/prefill_attention.py): latent pages stream HBM -> VMEM in
    multi-page tiles, online softmax per query block, causal work bounded per
    block. Returns a_lat [T, H, d_c].

    block_q auto-sizes to the VMEM budget when None: MLA's wide rows (d_c up
    to 512) make the f32 query + accumulator the dominant VMEM tenants, so
    real-geometry models run 64- or 32-row blocks where GQA uses 128."""
    T, H, latent = q_cat.shape
    P, ps, _ = pages.shape
    max_pages = page_table.shape[0]
    if block_q is None:
        per_row = H * (latent + d_c) * 4  # f32 query + accumulator bytes/row
        block_q = 128
        while block_q > 32 and per_row * block_q > 6 * 1024 * 1024:
            block_q //= 2
    block_q = min(block_q, T)
    while T % block_q:
        block_q //= 2
    assert block_q >= 1
    tile_pages = max(1, 128 // ps)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(T // block_q,),
        in_specs=[
            pl.BlockSpec((block_q, H, latent), lambda qb, *_: (qb, 0, 0)),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=pl.BlockSpec((block_q, H, d_c), lambda qb, *_: (qb, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((2, tile_pages, ps, latent), pages.dtype),
            pltpu.SemaphoreType.DMA((2, tile_pages)),
        ],
    )
    kernel = pl.pallas_call(
        functools.partial(
            _prefill_kernel,
            page_size=ps,
            max_pages=max_pages,
            tile_pages=tile_pages,
            block_q=block_q,
            d_c=d_c,
        ),
        out_shape=jax.ShapeDtypeStruct((T, H, d_c), q_cat.dtype),
        grid_spec=grid_spec,
        interpret=interpret,
    )
    return kernel(page_table.astype(jnp.int32), positions.astype(jnp.int32), q_cat, pages)
