"""Sparse Mixture-of-Experts block (Mixtral-style top-k routing).

TPU-first formulation: GShard-style capacity-based dispatch — one-hot dispatch/
combine einsums turn token->expert routing into dense batched matmuls (MXU
friendly, static shapes), and the expert axis shards over the mesh's "ep"
axis so each chip holds E/ep experts (reference has no MoE of its own,
SURVEY.md §2.8 — only engine-delegated; this is the native design).

capacity = ceil(T * K / E * capacity_factor); tokens beyond an expert's
capacity are dropped (their weight is renormalized away). For exactness in
tests use capacity_factor large enough that nothing drops.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from dynamo_tpu.quant import qlinear_expert


def topk_routing(
    router_logits: jnp.ndarray,  # [T, E] float32
    k: int,
    renormalize: bool = True,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Returns (weights [T, K], indices [T, K]).

    renormalize=True (Mixtral, norm_topk_prob): softmax over the selected k.
    renormalize=False (DeepSeek default): softmax over ALL experts, top-k
    probabilities used as-is."""
    top_logits, top_idx = jax.lax.top_k(router_logits, k)
    if renormalize:
        weights = jax.nn.softmax(top_logits, axis=-1)
    else:
        probs = jax.nn.softmax(router_logits, axis=-1)
        weights = jnp.take_along_axis(probs, top_idx, axis=-1)
    return weights, top_idx


def moe_block(
    hidden: jnp.ndarray,  # [T, D]
    router_w: jnp.ndarray,  # [D, E]
    w_gate: jnp.ndarray,  # [E, D, F]
    w_up: jnp.ndarray,  # [E, D, F]
    w_down: jnp.ndarray,  # [E, F, D]
    num_experts_per_tok: int,
    capacity_factor: float = 2.0,
    renormalize: bool = True,
) -> jnp.ndarray:
    T, D = hidden.shape
    E = router_w.shape[1]
    K = num_experts_per_tok
    capacity = max(1, int(-(-T * K * capacity_factor // E)))

    logits = (hidden.astype(jnp.float32) @ router_w.astype(jnp.float32))  # [T, E]
    weights, idx = topk_routing(logits, K, renormalize=renormalize)  # [T, K]

    # one-hot over experts per routing slot: [T, K, E]
    onehot = jax.nn.one_hot(idx, E, dtype=jnp.float32)
    # position of each (token, slot) within its expert queue: [T, K, E]
    # flatten routing slots in (slot-major, token-minor) order for the cumsum
    flat = onehot.transpose(1, 0, 2).reshape(K * T, E)
    pos_flat = jnp.cumsum(flat, axis=0) - flat  # position within expert
    pos = pos_flat.reshape(K, T, E).transpose(1, 0, 2)  # [T, K, E]
    keep = (pos < capacity) * onehot  # drop overflow
    pos_clamped = jnp.minimum(pos, capacity - 1).astype(jnp.int32)

    # dispatch[t, e, c]: token t occupies slot c of expert e
    cap_onehot = jax.nn.one_hot(pos_clamped, capacity, dtype=jnp.float32)  # [T,K,E,C]
    dispatch = jnp.einsum("tke,tkec->tec", keep, cap_onehot)
    combine = jnp.einsum("tk,tke,tkec->tec", weights, keep, cap_onehot)

    expert_in = jnp.einsum("tec,td->ecd", dispatch, hidden.astype(jnp.float32))
    expert_in = expert_in.astype(hidden.dtype)
    # batched expert FFN: [E, C, D] x [E, D, F] (banks may be weight-only
    # int8 — qlinear_expert dequantizes into the einsum)
    gated = jax.nn.silu(qlinear_expert(expert_in, w_gate))
    up = qlinear_expert(expert_in, w_up)
    expert_out = qlinear_expert(gated * up, w_down)  # [E, C, D]

    out = jnp.einsum("tec,ecd->td", combine, expert_out.astype(jnp.float32))
    return out.astype(hidden.dtype)
