"""Rotary position embeddings (RoPE), NeoX/Llama convention."""

from __future__ import annotations

import jax.numpy as jnp


def rope_frequencies(head_dim: int, theta: float) -> jnp.ndarray:
    """Inverse frequencies [head_dim // 2], float32."""
    exponent = jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim
    return 1.0 / (theta**exponent)


def apply_rope(x: jnp.ndarray, positions: jnp.ndarray, theta: float) -> jnp.ndarray:
    """Rotate q or k by position.

    x: [T, num_heads, head_dim]; positions: [T] int32. Returns same shape/dtype.
    Uses the split-halves (rotate_half) convention matching HF Llama.
    """
    head_dim = x.shape[-1]
    inv_freq = rope_frequencies(head_dim, theta)  # [hd/2]
    angles = positions.astype(jnp.float32)[:, None] * inv_freq[None, :]  # [T, hd/2]
    cos = jnp.cos(angles)[:, None, :]  # [T, 1, hd/2]
    sin = jnp.sin(angles)[:, None, :]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    rotated = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    return rotated.astype(x.dtype)


def apply_mrope(
    x: jnp.ndarray,  # [T, num_heads, head_dim]
    positions3: jnp.ndarray,  # [T, 3] (temporal, row, col) position per token
    sections: tuple[int, int, int],  # frequency split, sums to head_dim // 2
    theta: float,
) -> jnp.ndarray:
    """Multimodal RoPE (Qwen2-VL): the inverse-frequency vector is split into
    (temporal, row, col) sections; frequency j takes its angle from the
    position component its section belongs to. Text tokens carry equal
    components, for which this reduces EXACTLY to apply_rope — so text-only
    prompts match the plain path bit-for-bit.

    x: [T, H, D]; positions3: [T, 3] int32. sections must sum to D // 2.
    """
    head_dim = x.shape[-1]
    inv_freq = rope_frequencies(head_dim, theta)  # [D/2]
    # component selector per frequency: 0 (temporal) | 1 (row) | 2 (col)
    comp = jnp.repeat(
        jnp.arange(3, dtype=jnp.int32), jnp.asarray(sections, jnp.int32),
        total_repeat_length=head_dim // 2,
    )
    pos = positions3.astype(jnp.float32)[:, comp]  # [T, D/2]
    angles = pos * inv_freq[None, :]
    cos = jnp.cos(angles)[:, None, :]
    sin = jnp.sin(angles)[:, None, :]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    rotated = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    return rotated.astype(x.dtype)
