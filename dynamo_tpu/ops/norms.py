"""Normalization layers (computed in float32, cast back)."""

from __future__ import annotations

import jax.numpy as jnp


def rms_norm(x: jnp.ndarray, weight: jnp.ndarray, eps: float) -> jnp.ndarray:
    """RMSNorm over the last axis; accumulates in f32 like the TPU-friendly norm."""
    xf = x.astype(jnp.float32)
    variance = jnp.mean(xf * xf, axis=-1, keepdims=True)
    normed = xf * jnp.reciprocal(jnp.sqrt(variance + eps))
    return (normed * weight.astype(jnp.float32)).astype(x.dtype)
