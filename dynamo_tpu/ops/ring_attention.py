"""Ring attention: causal attention over a sequence sharded across devices
(context/sequence parallelism for long prompts).

Absent from the reference (SURVEY.md §2.8 — no ring/Ulysses/CP anywhere);
designed fresh for TPU: the sequence axis is sharded over a mesh axis, K/V
chunks rotate around the ring via ``lax.ppermute`` (XLA collective-permute —
rides ICI neighbor links), and each hop merges with a flash-style online
softmax (running max / sum / unnormalized accumulator), so the full sequence
never materializes on any one chip.

Pure computation: O(T^2) work split over n devices with O(T/n) memory per chip.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

_NEG_INF = -1e30


def _repeat_kv(x: jnp.ndarray, num_q_heads: int) -> jnp.ndarray:
    if x.shape[1] == num_q_heads:
        return x
    return jnp.repeat(x, num_q_heads // x.shape[1], axis=1)


def _ring_attention_local(
    q: jnp.ndarray,  # [Tc, Hq, D] local query chunk
    k: jnp.ndarray,  # [Tc, Hkv, D] local key chunk
    v: jnp.ndarray,  # [Tc, Hkv, D]
    axis_name: str,
):
    n = jax.lax.psum(1, axis_name)
    my = jax.lax.axis_index(axis_name)
    Tc, Hq, D = q.shape
    scale = 1.0 / jnp.sqrt(jnp.float32(D))

    # K/V rotate around the ring in their compact GQA form ([Tc, Hkv, D]);
    # expansion to Hq happens per hop just before the matmul, so ring traffic
    # and resident K/V stay Hq/Hkv times smaller
    qf = q.astype(jnp.float32)

    q_pos = my * Tc + jnp.arange(Tc, dtype=jnp.int32)  # [Tc] global positions
    local_idx = jnp.arange(Tc, dtype=jnp.int32)

    perm = [(i, (i + 1) % n) for i in range(n)]

    def hop(carry, s):
        k_cur, v_cur, m, l, acc = carry
        src = (my - s) % n  # ring owner of the chunk currently held
        kv_pos = src * Tc + local_idx  # [Tc]

        scores = jnp.einsum(
            "thd,shd->hts", qf, _repeat_kv(k_cur, Hq).astype(jnp.float32)
        ) * scale  # [H, Tq, Tk]
        mask = kv_pos[None, :] <= q_pos[:, None]  # [Tq, Tk] causal on global pos
        scores = jnp.where(mask[None], scores, _NEG_INF)

        # online softmax merge
        chunk_max = jnp.max(scores, axis=-1)  # [H, Tq]
        new_m = jnp.maximum(m, chunk_max)
        correction = jnp.exp(m - new_m)  # [H, Tq]
        probs = jnp.exp(scores - new_m[..., None])  # [H, Tq, Tk]
        new_l = l * correction + jnp.sum(probs, axis=-1)
        chunk_out = jnp.einsum(
            "hts,shd->htd", probs, _repeat_kv(v_cur, Hq).astype(jnp.float32)
        )
        new_acc = acc * correction[..., None] + chunk_out

        # rotate kv to the next device (skipped compute on the last hop would
        # need a cond; one extra permute is cheap and keeps the loop uniform)
        k_next = jax.lax.ppermute(k_cur, axis_name, perm)
        v_next = jax.lax.ppermute(v_cur, axis_name, perm)
        return (k_next, v_next, new_m, new_l, new_acc), None

    m0 = jnp.full((Hq, Tc), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((Hq, Tc), jnp.float32)
    acc0 = jnp.zeros((Hq, Tc, D), jnp.float32)
    (k_f, v_f, m, l, acc), _ = jax.lax.scan(
        hop, (k, v, m0, l0, acc0), jnp.arange(n)
    )
    out = acc / jnp.maximum(l, 1e-20)[..., None]  # [H, Tq, D]
    return jnp.transpose(out, (1, 0, 2)).astype(q.dtype)  # [Tq, H, D]


def ring_attention(
    q: jnp.ndarray,  # [T, Hq, D] — T sharded over `axis` on the mesh
    k: jnp.ndarray,  # [T, Hkv, D]
    v: jnp.ndarray,  # [T, Hkv, D]
    mesh: Mesh,
    axis: str = "sp",
    head_axis: str | None = None,
) -> jnp.ndarray:
    """Causal self-attention with the sequence sharded over mesh axis `axis`.

    On a composed (sp, tp) mesh the head dim additionally shards over
    ``head_axis`` (auto-detected as "tp" when present): attention is
    head-local, so each tp shard runs its own independent sp ring — sequence
    and tensor parallelism compose with no extra collectives."""
    if head_axis is None and "tp" in mesh.axis_names:
        head_axis = "tp"
    spec = P(axis) if head_axis is None else P(axis, head_axis)
    # _tp_shard_map handles the jax.shard_map / jax.experimental.shard_map
    # API split (pre-0.8 jax has no top-level jax.shard_map)
    from dynamo_tpu.ops.attention import _tp_shard_map

    fn = _tp_shard_map(
        partial(_ring_attention_local, axis_name=axis),
        mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
    )
    return fn(q, k, v)
