"""Flight recorder: a bounded, causally-stamped request-lifecycle event journal.

The aggregate planes (SLO percentiles, goodput windows, stage seconds) can say
*that* a tenant's ITL-p99 blew its budget; nothing before this module could
say *why request X was slow* — queue wait, a QoS shed, a preemption, a dead
prefix-fetch holder, a migration pause, or a planner-triggered drain. Every
plane now appends one small :class:`Event` at each decision point:

  - scheduler: admission (accept/reject/defer), preempt + victim pick,
    speculative-decode degrade, prefix-fetch hit/fallback/timeout, offload
    drains/restores
  - frontend QoS: admit/throttle/shed verdicts
  - migration: freeze, handoff, adopt, and every failure-ladder arm
  - health: lifecycle transitions; planner: observe/decide/execute
  - chaos: `disagg/faults.py` injections, so seeded fault runs are
    self-documenting

Design constraints, in order:

  - **bounded**: one ring of ``capacity`` (default 4096) records; eviction is
    deque-append. A separate small *capture* map pins the full event chain of
    any request that finished over budget or errored, so forensics on the
    interesting requests survive ring eviction under load.
  - **lock-cheap**: ``emit()`` is one lock acquire around a deque append +
    two dict increments; no I/O, no serialization, no ambient-context lookup
    unless the caller omitted the ids. The decode hot loop emits a handful
    of events per *request*, not per token.
  - **causal**: every event carries a process-monotonic ``seq`` plus a wall
    clock (one monotonic->epoch anchor shared with utils/tracing.py), so
    per-worker order is exact and cross-worker merges sort on (wall, seq).
  - **conformant**: ``kind`` must be a member of :data:`DECLARED_EVENT_KINDS`
    — the same three-way pin as metric families: ``emit()`` raises on an
    unknown kind, graftlint's ``event-conformance`` detector statically
    checks every ``events.emit("<kind>", ...)`` literal against the tuple
    (and that every declared kind has an emitting site), and the
    ``dynamo_event_*`` exposition rides the prometheus ``--check`` surface.

The black box: :meth:`EventJournal.dump_post_mortem` writes the ring as JSONL
(one event per line, newest last) when the engine loop crashes or a worker
dies — the path comes from ``DYNTPU_POSTMORTEM_DIR`` (default: the system
temp dir). When tracing is enabled, every emit also records a zero-duration
span named ``event.<kind>`` carrying the event's seq — the event<->span
exemplar link, so journal entries line up on the Perfetto timeline keyed by
the same ``trace_id`` the event carries.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import OrderedDict, deque
from dataclasses import dataclass, field
from typing import Optional

from dynamo_tpu.utils import tracing

#: Every event kind this system may emit — the conformance surface.
#: graftlint's event-conformance detector pins emitting-site literals against
#: this tuple in both directions (mirror of DECLARED_METRIC_FAMILIES). Keep
#: one kind per line. Taxonomy: ``<plane>.<decision>``.
DECLARED_EVENT_KINDS: tuple = (
    "request.enqueued",
    "request.first_token",
    "request.finished",
    "request.failed",
    "sched.admitted",
    "sched.admission_rejected",
    "sched.admission_deferred",
    "sched.preempted",
    "sched.victim_picked",
    "sched.spec_degraded",
    "qos.admitted",
    "qos.throttled",
    "qos.shed",
    "migration.freeze",
    "migration.handoff",
    "migration.adopted",
    "migration.fallback",
    "prefix_fetch.hit",
    "prefix_fetch.fallback",
    "prefix_fetch.timeout",
    "offload.drain",
    "offload.restore",
    "offload.disk_spill",
    "offload.disk_restore",
    "offload.disk_drop",
    "health.transition",
    "planner.observe",
    "planner.decide",
    "planner.execute",
    "fault.injected",
    "engine.crash",
)

_KIND_SET = frozenset(DECLARED_EVENT_KINDS)

CAPACITY = 4096
CAPTURE_CAPACITY = 64
#: events kept per pinned capture (a pathological 30k-token stream must not
#: let one capture eat the whole budget)
CAPTURE_EVENTS = 256

POSTMORTEM_DIR_ENV = "DYNTPU_POSTMORTEM_DIR"

# monotonic->epoch anchor shared shape with utils/tracing.py: emit stamps
# monotonic (cheap, ordering-exact) and wire forms add the offset
_EPOCH_OFFSET = time.time() - time.monotonic()


def _ambient_ids() -> tuple[str, str]:
    """(request_id, trace_id) from the ambient RequestContext, ("", "")
    outside a request. Lazy import: utils loads during runtime bootstrap."""
    from dynamo_tpu.runtime.context import current_context

    ctx = current_context()
    if ctx is None:
        return "", ""
    rid = ctx.request_id or ""
    return rid, ctx.metadata.get("trace_id") or rid


@dataclass(frozen=True)
class Event:
    """One journal record. Frozen: snapshots hand out references, and a
    pinned capture must not see later mutation."""

    seq: int
    mono: float  # time.monotonic() at emit
    kind: str
    request_id: str = ""
    trace_id: str = ""
    tenant: str = ""
    priority: str = ""
    detail: dict = field(default_factory=dict)

    @property
    def wall(self) -> float:
        return self.mono + _EPOCH_OFFSET

    def to_wire(self) -> dict:
        out = {
            "seq": self.seq,
            "wall": round(self.wall, 6),
            "kind": self.kind,
        }
        for k in ("request_id", "trace_id", "tenant", "priority"):
            v = getattr(self, k)
            if v:
                out[k] = v
        if self.detail:
            out["detail"] = self.detail
        return out


class EventJournal:
    def __init__(
        self,
        capacity: int = CAPACITY,
        capture_capacity: int = CAPTURE_CAPACITY,
        clock=time.monotonic,
    ):
        self._clock = clock
        self._lock = threading.Lock()
        self._ring: deque[Event] = deque(maxlen=capacity)
        self._seq = 0
        self._counts: dict[str, int] = {}
        # request_id -> {"reason", "pinned_mono", "events": [Event, ...]}
        # (LRU-bounded: the newest interesting requests win)
        self._captures: OrderedDict[str, dict] = OrderedDict()
        self._capture_capacity = capture_capacity
        self.pinned_total = 0

    # ---------------- ingest ----------------

    def emit(
        self,
        kind: str,
        request_id: Optional[str] = None,
        trace_id: Optional[str] = None,
        tenant: str = "",
        priority: str = "",
        **detail,
    ) -> Event:
        """Append one event. ``kind`` must be declared; request/trace ids
        default to the ambient request context's (pass them explicitly on
        threads outside the context — the engine loop)."""
        if kind not in _KIND_SET:
            raise ValueError(
                f"undeclared event kind {kind!r} — add it to "
                "DECLARED_EVENT_KINDS (utils/events.py)"
            )
        if request_id is None and trace_id is None:
            request_id, trace_id = _ambient_ids()
        ev = Event(
            seq=0,  # replaced under the lock below
            mono=self._clock(),
            kind=kind,
            request_id=request_id or "",
            trace_id=trace_id or request_id or "",
            tenant=tenant,
            priority=priority,
            detail=detail,
        )
        with self._lock:
            object.__setattr__(ev, "seq", self._seq)
            self._seq += 1
            self._ring.append(ev)
            self._counts[kind] = self._counts.get(kind, 0) + 1
        # event<->span exemplar link: journal entries land on the trace
        # timeline as zero-duration spans keyed by the same trace_id
        if tracing.enabled():
            tracing.record_span(
                f"event.{kind}", ev.mono, duration=0.0,
                request_id=ev.request_id or None, trace_id=ev.trace_id or None,
                attrs={"event_seq": ev.seq, **{k: str(v) for k, v in detail.items()}},
            )
        return ev

    # ---------------- forensics ----------------

    def events_for(self, request_id: str) -> list[Event]:
        """Every journal event for one request: the pinned capture (if any)
        merged with whatever still lives in the ring, seq-ordered."""
        with self._lock:
            cap = self._captures.get(request_id)
            chain = {e.seq: e for e in (cap["events"] if cap else ())}
            for e in self._ring:
                if e.request_id == request_id:
                    chain.setdefault(e.seq, e)
        return [chain[s] for s in sorted(chain)]

    def pin(self, request_id: str, reason: str) -> bool:
        """Copy a request's current event chain into the capture map so it
        survives ring eviction. Called at finish time for any request that
        blew its TTFT/ITL budget or errored. Idempotent per request (the
        first reason wins); returns True when a new capture landed."""
        if not request_id:
            return False
        with self._lock:
            if request_id in self._captures:
                self._captures.move_to_end(request_id)
                return False
            events = [e for e in self._ring if e.request_id == request_id]
            self._captures[request_id] = {
                "reason": reason,
                "pinned_mono": self._clock(),
                "events": events[-CAPTURE_EVENTS:],
            }
            self.pinned_total += 1
            while len(self._captures) > self._capture_capacity:
                self._captures.popitem(last=False)
        return True

    def capture_reason(self, request_id: str) -> Optional[str]:
        with self._lock:
            cap = self._captures.get(request_id)
            return cap["reason"] if cap else None

    def captured_ids(self) -> list[str]:
        with self._lock:
            return list(self._captures)

    def timeline(self, request_id: str) -> dict:
        """The ``/debug/requests/{id}`` document: the request's events in
        causal order with inter-event durations, plus the pin verdict."""
        events = self.events_for(request_id)
        out_events = []
        prev: Optional[Event] = None
        for e in events:
            w = e.to_wire()
            w["dt_ms"] = round((e.mono - prev.mono) * 1e3, 3) if prev else 0.0
            out_events.append(w)
            prev = e
        return {
            "request_id": request_id,
            "found": bool(events),
            "events": out_events,
            "span_ms": (
                round((events[-1].mono - events[0].mono) * 1e3, 3)
                if len(events) > 1 else 0.0
            ),
            "pinned": self.capture_reason(request_id),
        }

    # ---------------- fleet / exposition ----------------

    def snapshot(self, limit: int = 32) -> dict:
        """Wire form for worker stats broadcasts: the newest ``limit``
        events + per-kind lifetime counts (fleet `/cluster/events` merges
        the recent lists; dynotop's EVT column reads the counts)."""
        with self._lock:
            recent = list(self._ring)[-limit:]
            counts = dict(self._counts)
            emitted = self._seq
            captures = len(self._captures)
        return {
            "emitted": emitted,
            "counts": counts,
            "captures": captures,
            "recent": [e.to_wire() for e in recent],
        }

    def render_metrics(self, prefix: str = "dynamo_event") -> str:
        from dynamo_tpu.utils.prometheus import render_family

        with self._lock:
            counts = sorted(self._counts.items())
            size = len(self._ring)
            pinned = self.pinned_total
        out = render_family(
            f"{prefix}_emitted_total", "counter",
            "lifecycle events appended to the flight-recorder journal, by kind",
            [({"kind": k}, n) for k, n in counts]
            or [({"kind": "request.enqueued"}, 0)],
        )
        out += render_family(
            f"{prefix}_journal_size", "gauge",
            "events currently resident in the bounded journal ring",
            [({}, size)],
        )
        out += render_family(
            f"{prefix}_captures_pinned_total", "counter",
            "slow/errored request event chains pinned to the capture ring "
            "(they survive journal eviction for /debug/requests forensics)",
            [({}, pinned)],
        )
        return out

    # ---------------- black box ----------------

    def dump_post_mortem(self, reason: str, path: Optional[str] = None) -> Optional[str]:
        """Write the ring (oldest first) as JSONL — the crash black box.
        Never raises: a failing dump must not mask the crash it documents.
        Returns the path written, or None."""
        import tempfile

        with self._lock:
            events = list(self._ring)
        if path is None:
            directory = os.environ.get(POSTMORTEM_DIR_ENV) or tempfile.gettempdir()
            path = os.path.join(
                directory,
                f"dyntpu-postmortem-{os.getpid()}-{int(time.time())}.jsonl",
            )
        try:
            with open(path, "w") as f:
                f.write(json.dumps({
                    "postmortem": reason,
                    "wall": time.time(),
                    "pid": os.getpid(),
                    "events": len(events),
                }) + "\n")
                for e in events:
                    f.write(json.dumps(e.to_wire(), default=str) + "\n")
        except OSError:
            return None
        return path


#: the per-process journal every plane emits into (tests construct their own)
JOURNAL = EventJournal()


def emit(kind: str, **kwargs) -> Event:
    return JOURNAL.emit(kind, **kwargs)


def merge_recent(worker_events: list[tuple[str, dict]], limit: int = 200) -> list[dict]:
    """Fleet timeline: merge per-worker ``snapshot()['recent']`` lists into
    one (wall, seq)-ordered view, each event labeled with its worker. Pure —
    `components/metrics` and tests call it off scraped stats."""
    merged: list[dict] = []
    for worker_id, snap in worker_events:
        for ev in (snap or {}).get("recent", ()):
            merged.append({**ev, "worker_id": worker_id})
    merged.sort(key=lambda e: (e.get("wall", 0.0), e.get("seq", 0)))
    return merged[-limit:]
