"""Logging init for the framework.

Mirrors the reference's logging surface (reference: lib/runtime/src/logging.rs:20-298):
env-filtered level via ``DYNTPU_LOG`` (analogue of DYN_LOG), JSON-lines structured
output via ``DYNTPU_LOG_JSONL`` (analogue of DYN_LOGGING_JSONL).
"""

from __future__ import annotations

import json
import logging
import os
import sys
import time

_INITIALIZED = False


class JsonlFormatter(logging.Formatter):
    """One JSON object per line, fields flattened (reference: logging.rs:172-244)."""

    def format(self, record: logging.LogRecord) -> str:
        entry = {
            "ts": time.strftime("%Y-%m-%dT%H:%M:%S", time.gmtime(record.created))
            + f".{int(record.msecs):03d}Z",
            "level": record.levelname,
            "target": record.name,
            "message": record.getMessage(),
        }
        extra = getattr(record, "fields", None)
        if isinstance(extra, dict):
            entry.update(extra)
        if record.exc_info:
            entry["exception"] = self.formatException(record.exc_info)
        return json.dumps(entry, default=str)


def init_logging(level: str | None = None) -> None:
    """Logging setup honouring DYNTPU_LOG / DYNTPU_LOG_JSONL.

    Handler installation is idempotent, but an explicit ``level`` always takes
    effect — modules calling get_logger() at import time must not pin the level.
    """
    global _INITIALIZED
    level_name = (level or os.environ.get("DYNTPU_LOG", "info")).upper()
    if _INITIALIZED:
        if level is not None:
            logging.getLogger("dynamo_tpu").setLevel(getattr(logging, level_name, logging.INFO))
        return
    _INITIALIZED = True
    handler = logging.StreamHandler(sys.stderr)
    if os.environ.get("DYNTPU_LOG_JSONL", "").lower() in ("1", "true", "yes"):
        handler.setFormatter(JsonlFormatter())
    else:
        handler.setFormatter(
            logging.Formatter(
                "%(asctime)s %(levelname).1s %(name)s: %(message)s",
                datefmt="%H:%M:%S",
            )
        )
    root = logging.getLogger("dynamo_tpu")
    root.setLevel(getattr(logging, level_name, logging.INFO))
    root.addHandler(handler)
    root.propagate = False


def get_logger(name: str) -> logging.Logger:
    init_logging()
    return logging.getLogger(f"dynamo_tpu.{name}")
