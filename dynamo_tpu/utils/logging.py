"""Logging init for the framework.

Mirrors the reference's logging surface (reference: lib/runtime/src/logging.rs:20-298):
env-filtered level via ``DYNTPU_LOG`` (analogue of DYN_LOG), JSON-lines structured
output via ``DYNTPU_LOG_JSONL`` (analogue of DYN_LOGGING_JSONL).

Both formatters auto-stamp the ambient request/trace id (from
``runtime.context.current_context``) into every record emitted while handling
a request, so worker logs are joinable against traces (``DYNTPU_TRACE``
captures) with no per-call-site plumbing.
"""

from __future__ import annotations

import json
import logging
import os
import sys
import time

_INITIALIZED = False
_current_context = None  # resolved lazily: runtime imports utils at startup


def _ambient_ids() -> tuple:
    """(request_id, trace_id) of the ambient request, or (None, None)."""
    global _current_context
    if _current_context is None:
        try:
            from dynamo_tpu.runtime.context import current_context
        except ImportError:  # mid-bootstrap: no request can be in flight yet
            return None, None
        _current_context = current_context
    ctx = _current_context()
    if ctx is None:
        return None, None
    return ctx.request_id, ctx.metadata.get("trace_id") or ctx.request_id


class JsonlFormatter(logging.Formatter):
    """One JSON object per line, fields flattened (reference: logging.rs:172-244)."""

    def format(self, record: logging.LogRecord) -> str:
        entry = {
            "ts": time.strftime("%Y-%m-%dT%H:%M:%S", time.gmtime(record.created))
            + f".{int(record.msecs):03d}Z",
            "level": record.levelname,
            "target": record.name,
            "message": record.getMessage(),
        }
        rid, tid = _ambient_ids()
        if rid is not None:
            entry["request_id"] = rid
            if tid != rid:
                entry["trace_id"] = tid
        extra = getattr(record, "fields", None)
        if isinstance(extra, dict):
            entry.update(extra)
        if record.exc_info:
            entry["exception"] = self.formatException(record.exc_info)
        return json.dumps(entry, default=str)


class PlainFormatter(logging.Formatter):
    """Human format with the ambient request id appended when present."""

    def format(self, record: logging.LogRecord) -> str:
        base = super().format(record)
        rid, _ = _ambient_ids()
        if rid is not None:
            return f"{base} [rid={rid}]"
        return base


def init_logging(level: str | None = None) -> None:
    """Logging setup honouring DYNTPU_LOG / DYNTPU_LOG_JSONL.

    Handler installation is idempotent, but an explicit ``level`` always takes
    effect — modules calling get_logger() at import time must not pin the level.
    """
    global _INITIALIZED
    level_name = (level or os.environ.get("DYNTPU_LOG", "info")).upper()
    if _INITIALIZED:
        if level is not None:
            logging.getLogger("dynamo_tpu").setLevel(getattr(logging, level_name, logging.INFO))
        return
    _INITIALIZED = True
    handler = logging.StreamHandler(sys.stderr)
    if os.environ.get("DYNTPU_LOG_JSONL", "").lower() in ("1", "true", "yes"):
        handler.setFormatter(JsonlFormatter())
    else:
        handler.setFormatter(
            PlainFormatter(
                "%(asctime)s %(levelname).1s %(name)s: %(message)s",
                datefmt="%H:%M:%S",
            )
        )
    root = logging.getLogger("dynamo_tpu")
    root.setLevel(getattr(logging, level_name, logging.INFO))
    root.addHandler(handler)
    root.propagate = False


def get_logger(name: str) -> logging.Logger:
    init_logging()
    return logging.getLogger(f"dynamo_tpu.{name}")
