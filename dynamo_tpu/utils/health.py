"""Per-process health monitor: lifecycle states, heartbeats, stuck watchdog.

The fleet-level signal the ROADMAP's "heavy traffic" target needs on every
serving process (engine, worker, prefill worker): the planner scales on
ForwardPassMetrics and the router targets workers, but neither can tell a
healthy quiet worker from a wedged one — stats broadcasts keep flowing from
the asyncio thread even when the engine loop is stuck on a dead device op.

``HealthMonitor`` closes that gap:

  - explicit lifecycle states: ``starting -> ready`` (engine initialized),
    ``degraded`` (watchdog alarm), ``draining`` (operator-initiated
    scale-down; routers skip it but in-flight work finishes),
    ``migrating`` (drain with live migration: in-flight sequences are being
    handed to peers — disagg/migrate.py — instead of finishing by
    attrition), ``dead`` (shutdown / loop exit)
  - monotonic heartbeats stamped by the engine loop (``beat()``); every stats
    broadcast carries ``heartbeat_age_s`` so aggregators can spot a process
    whose asyncio side answers scrapes while its engine thread is wedged
  - a stuck-request watchdog (``check()``): oldest-queued-age and no-progress
    alarms computed from scheduler signals. Alarms degrade the state and
    auto-clear — an operator-set ``draining`` is never overridden.

Thread-safety: ``beat()`` runs on the engine thread, ``snapshot()`` on the
asyncio thread; a single lock guards transitions, scalar stamps ride the GIL.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Optional

from dynamo_tpu.utils.logging import get_logger

log = get_logger("utils.health")

STATES = ("starting", "ready", "degraded", "draining", "migrating", "dead")

# states a router / planner must not hand new work to (a MIGRATING worker is
# mid-drain: its in-flight sequences are leaving, new ones must not arrive)
UNSERVABLE_STATES = ("draining", "migrating", "dead")


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except (TypeError, ValueError):
        return default


class HealthMonitor:
    def __init__(
        self,
        component: str = "engine",
        stuck_queue_s: Optional[float] = None,
        no_progress_s: Optional[float] = None,
        clock=time.monotonic,
    ):
        self.component = component
        # a request older than this in the waiting queue while the engine has
        # capacity signals admission livelock; a wedged device op shows up as
        # no-progress instead
        self.stuck_queue_s = (
            stuck_queue_s
            if stuck_queue_s is not None
            else _env_float("DYNTPU_STUCK_QUEUE_S", 120.0)
        )
        self.no_progress_s = (
            no_progress_s
            if no_progress_s is not None
            else _env_float("DYNTPU_NO_PROGRESS_S", 60.0)
        )
        self._clock = clock
        self._lock = threading.Lock()
        now = clock()
        self._state = "starting"
        self._since = now
        self._started = now
        self._reason = "initializing"
        self._beat_ts = now
        self._beats = 0
        self._transitions: list[dict] = []
        # watchdog bookkeeping
        self._alarm: Optional[str] = None
        self._progress_marker: Optional[int] = None
        self._progress_ts = now

    # ---------------- state ----------------

    @property
    def state(self) -> str:
        return self._state

    @property
    def alarm(self) -> Optional[str]:
        return self._alarm

    def is_servable(self) -> bool:
        """May new work be routed here? degraded still serves (best effort)."""
        return self._state not in UNSERVABLE_STATES

    def set_state(self, state: str, reason: str = "") -> None:
        if state not in STATES:
            raise ValueError(f"unknown health state {state!r}")
        with self._lock:
            if state == self._state:
                return
            if self._state == "dead":
                return  # dead is terminal
            now = self._clock()
            self._transitions.append(
                {"from": self._state, "to": state, "reason": reason,
                 "at_s": round(now - self._started, 3)}
            )
            del self._transitions[:-8]  # bounded history
            prev = self._transitions[-1]["from"]
            self._state = state
            self._since = now
            self._reason = reason
        log.info("%s health: %s (%s)", self.component, state, reason or "-")
        from dynamo_tpu.utils import events

        events.emit(
            "health.transition", request_id="",
            component=self.component, from_state=prev, to_state=state,
            reason=reason,
        )

    # ---------------- heartbeat ----------------

    def beat(self) -> None:
        """Stamp liveness from the serving loop. Cheap enough per step."""
        self._beat_ts = self._clock()
        self._beats += 1

    def heartbeat_age(self) -> float:
        return max(0.0, self._clock() - self._beat_ts)

    # ---------------- watchdog ----------------

    def check(
        self,
        oldest_waiting_age: float = 0.0,
        has_work: bool = False,
        progress_marker: int = 0,
    ) -> Optional[str]:
        """Evaluate the stuck-request alarms; returns the active alarm name.

        ``progress_marker`` is any monotonically increasing count of completed
        engine work (prefill calls + decode windows + finished requests): a
        marker frozen for ``no_progress_s`` while ``has_work`` means the loop
        is spinning without the device completing anything. Alarms flip a
        ready engine to degraded and auto-clear; explicit draining/dead
        states are never touched.
        """
        now = self._clock()
        if self._progress_marker != progress_marker or not has_work:
            self._progress_marker = progress_marker
            self._progress_ts = now

        alarm: Optional[str] = None
        if has_work and (now - self._progress_ts) > self.no_progress_s:
            alarm = "no-progress"
        elif oldest_waiting_age > self.stuck_queue_s:
            alarm = "stuck-queue"

        if alarm is not None:
            self._alarm = alarm
            if self._state == "ready":
                self.set_state("degraded", f"watchdog: {alarm}")
        elif self._alarm is not None:
            self._alarm = None
            if self._state == "degraded":
                self.set_state("ready", "watchdog alarm cleared")
        return self._alarm

    # ---------------- exposition ----------------

    def snapshot(self) -> dict:
        """Wire form for stats broadcasts / ``/cluster/status``."""
        now = self._clock()
        with self._lock:
            return {
                "component": self.component,
                "state": self._state,
                "reason": self._reason,
                "state_age_s": round(now - self._since, 3),
                "uptime_s": round(now - self._started, 3),
                "heartbeat_age_s": round(now - self._beat_ts, 3),
                "beats": self._beats,
                "alarm": self._alarm,
                "transitions": list(self._transitions),
            }

    def render_metrics(self, prefix: str = "dynamo_health") -> str:
        """Prometheus exposition: one-hot state gauge + heartbeat age."""
        from dynamo_tpu.utils.prometheus import render_family

        snap = self.snapshot()
        out = render_family(
            f"{prefix}_state", "gauge",
            "process lifecycle state (one-hot over the state label)",
            [({"component": self.component, "state": s}, 1 if s == snap["state"] else 0)
             for s in STATES],
        )
        out += render_family(
            f"{prefix}_heartbeat_age_seconds", "gauge",
            "seconds since the serving loop last stamped liveness",
            [({"component": self.component}, snap["heartbeat_age_s"])],
        )
        out += render_family(
            f"{prefix}_uptime_seconds", "gauge",
            "seconds since this monitor was created",
            [({"component": self.component}, snap["uptime_s"])],
        )
        return out


def is_snapshot_servable(health: Optional[dict]) -> bool:
    """Router/planner-side predicate over a scraped health snapshot dict.

    Workers that never report health (older builds, mock workers) stay
    servable — absence of the plane must not take traffic down.
    """
    if not health:
        return True
    return health.get("state") not in UNSERVABLE_STATES
