"""Goodput accounting: per-request SLO outcomes -> windowed goodput.

The reference frames planner and disagg verdicts in DistServe-style *goodput*
terms: the fraction of requests that met their latency budgets, not raw
throughput. This module is the accounting half of the trace-replay harness
(``dynamo_tpu/loadgen/``): every finished request produces ONE
``RequestOutcome`` — TTFT, the per-token inter-arrival series, queue wait,
token counts, and the tenant/adapter/scenario tags the request carried — and
a ``GoodputTracker`` folds outcomes into a rolling window of met/missed/error
verdicts per scenario and per tenant.

A request MEETS its SLO when it finished without error, its TTFT is within
the TTFT budget, and the p99 of its OWN inter-token-latency series is within
the ITL budget (per-request p99, the DistServe criterion — a single stalled
window blows the request, averaging cannot hide it). Budgets resolve
per-outcome first (a replay scenario stamps its own), then the tracker's
defaults; an unset budget never fails a request.

Exposed as the ``dynamo_goodput_*`` Prometheus families on the engine and
HTTP-frontend /metrics surfaces (conformance-checked), in worker stats
broadcasts (dynotop's GOODPUT column), and — via ``summarize_outcomes`` — as
the ``replay.{scenario}.*`` sections of the bench artifact.

Thread-safe: the engine loop and the HTTP asyncio thread both observe.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Iterable, Optional

# per-request ITL series cap: enough for a 128K-output request at one gap per
# token; beyond that the p99 is already stable and memory growth is the risk
MAX_ITL_SAMPLES = 8192


def percentile(vals, p: float) -> Optional[float]:
    """Nearest-rank percentile; None on an empty series (never 0.0 — a fake
    zero p99 reads as a *great* latency, the worst possible failure mode)."""
    vals = sorted(vals)
    if not vals:
        return None
    k = max(0, min(len(vals) - 1, int(round(p / 100.0 * (len(vals) - 1)))))
    return vals[k]


@dataclass
class RequestOutcome:
    """One finished request's SLO-relevant facts (the unit of goodput)."""

    request_id: str
    scenario: str = ""  # replay scenario tag ("" = organic traffic)
    tenant: str = ""
    adapter: str = ""  # LoRA adapter name ("" = base model)
    queue_wait_s: Optional[float] = None  # engine submission -> admission
    ttft_s: Optional[float] = None  # submission -> first token (None = no token)
    # per-token inter-arrival gaps AFTER the first token, client-shaped: a
    # decode window's tokens land together, so the series is bursty by
    # design and its p99 is the honest stall signal
    itl_s: tuple = ()
    prompt_tokens: int = 0
    output_tokens: int = 0
    cached_tokens: int = 0
    duration_s: float = 0.0  # submission -> finish
    finish_reason: str = ""  # stop | length | error | ...
    error: bool = False
    # per-request budget overrides (seconds; None = use the tracker default)
    ttft_budget_s: Optional[float] = None
    itl_budget_s: Optional[float] = None

    def itl_p99_s(self) -> Optional[float]:
        return percentile(self.itl_s, 99)

    def itl_p50_s(self) -> Optional[float]:
        return percentile(self.itl_s, 50)

    def to_wire(self) -> dict:
        """Compact wire/JSONL form: the ITL series collapses to its
        percentiles (a 8K-entry float list per request would dwarf the
        record it annotates)."""
        p50, p99 = self.itl_p50_s(), self.itl_p99_s()
        return {
            "request_id": self.request_id,
            "scenario": self.scenario,
            "tenant": self.tenant,
            "adapter": self.adapter,
            "queue_wait_ms": _ms(self.queue_wait_s),
            "ttft_ms": _ms(self.ttft_s),
            "itl_p50_ms": _ms(p50),
            "itl_p99_ms": _ms(p99),
            "itl_n": len(self.itl_s),
            "prompt_tokens": self.prompt_tokens,
            "output_tokens": self.output_tokens,
            "cached_tokens": self.cached_tokens,
            "duration_ms": _ms(self.duration_s),
            "finish_reason": self.finish_reason,
            "error": self.error,
        }


def _ms(s: Optional[float]) -> Optional[float]:
    return round(s * 1e3, 3) if s is not None else None


def outcome_meets(
    outcome: RequestOutcome,
    ttft_budget_s: Optional[float] = None,
    itl_budget_s: Optional[float] = None,
) -> bool:
    """The DistServe criterion for one request: finished cleanly, TTFT within
    budget, and the request's own ITL p99 within budget. Per-outcome budgets
    win over the passed defaults; an unset budget never fails."""
    if outcome.error:
        return False
    ttft_b = outcome.ttft_budget_s if outcome.ttft_budget_s is not None else ttft_budget_s
    itl_b = outcome.itl_budget_s if outcome.itl_budget_s is not None else itl_budget_s
    if ttft_b is not None:
        if outcome.ttft_s is None or outcome.ttft_s > ttft_b:
            return False
    if itl_b is not None:
        p99 = outcome.itl_p99_s()
        if p99 is not None and p99 > itl_b:
            return False
    return True


@dataclass
class _Sample:
    ts: float
    scenario: str
    tenant: str
    adapter: str
    met: bool
    error: bool
    ttft_s: Optional[float]
    itl_p99_s: Optional[float]
    output_tokens: int


class GoodputTracker:
    """Rolling-window goodput per scenario and per tenant.

    goodput(window) = met / (met + missed + errors) over the window's
    finished requests. Lifetime met/missed/error counters survive window
    pruning (the ``dynamo_goodput_requests_total`` counter family)."""

    def __init__(
        self,
        ttft_budget_s: Optional[float] = None,
        itl_budget_s: Optional[float] = None,
        window_s: float = 300.0,
        max_samples: int = 8192,
        clock=time.monotonic,
    ):
        self.ttft_budget_s = ttft_budget_s
        self.itl_budget_s = itl_budget_s
        self.window_s = window_s
        self._clock = clock
        self._lock = threading.Lock()
        self._window: deque[_Sample] = deque(maxlen=max_samples)
        # lifetime (scenario) -> [met, missed, errors]; "" = untagged traffic
        self._totals: dict[str, list] = {}
        self._tenant_totals: dict[str, list] = {}

    # ---------------- ingest ----------------

    def observe(self, outcome: RequestOutcome) -> bool:
        """Fold one finished request in; returns whether it met its SLO."""
        met = outcome_meets(outcome, self.ttft_budget_s, self.itl_budget_s)
        now = self._clock()
        with self._lock:
            self._window.append(_Sample(
                now, outcome.scenario, outcome.tenant, outcome.adapter,
                met, outcome.error,
                outcome.ttft_s, outcome.itl_p99_s(), outcome.output_tokens,
            ))
            for totals, key in (
                (self._totals, outcome.scenario),
                (self._tenant_totals, outcome.tenant),
            ):
                t = totals.setdefault(key, [0, 0, 0])
                if outcome.error:
                    t[2] += 1
                elif met:
                    t[0] += 1
                else:
                    t[1] += 1
        return met

    def _prune(self, now: float) -> None:
        cutoff = now - self.window_s
        while self._window and self._window[0].ts < cutoff:
            self._window.popleft()

    # ---------------- evaluation ----------------

    def snapshot(self) -> dict:
        """Wire form: overall + per-scenario + per-tenant windowed goodput
        (None with an empty window — never a fake 1.0 or 0.0) and lifetime
        counters."""
        now = self._clock()
        with self._lock:
            self._prune(now)
            window = list(self._window)
            totals = {k: list(v) for k, v in self._totals.items()}
            tenant_totals = {k: list(v) for k, v in self._tenant_totals.items()}

        def fold(samples: list) -> dict:
            n = len(samples)
            met = sum(1 for s in samples if s.met)
            ttfts = [s.ttft_s for s in samples if s.ttft_s is not None]
            itls = [s.itl_p99_s for s in samples if s.itl_p99_s is not None]
            return {
                "requests": n,
                "met": met,
                "errors": sum(1 for s in samples if s.error),
                "goodput": round(met / n, 5) if n else None,
                "ttft_p99_ms": _ms(percentile(ttfts, 99)),
                "itl_p99_ms": _ms(percentile(itls, 99)),
            }

        scenarios = sorted({s.scenario for s in window} | set(totals))
        tenants = sorted(
            ({s.tenant for s in window} | set(tenant_totals)) - {""}
        )
        snap = {
            "window_s": self.window_s,
            "ttft_budget_ms": _ms(self.ttft_budget_s),
            "itl_budget_ms": _ms(self.itl_budget_s),
            **fold(window),
            "scenarios": {
                sc: {
                    **fold([s for s in window if s.scenario == sc]),
                    "lifetime": dict(zip(
                        ("met", "missed", "errors"), totals.get(sc, [0, 0, 0])
                    )),
                }
                for sc in scenarios
            },
            "tenants": {
                t: fold([s for s in window if s.tenant == t]) for t in tenants
            },
        }
        # (tenant, adapter)-keyed windows, join key "tenant|adapter" — the
        # SAME key MeterLedger.snapshot()["adapters"] uses, so /cluster/status
        # readers join cost (device-seconds) against goodput per adapter
        # without re-parsing labels. Fully-untagged traffic ("|") is omitted;
        # base-model requests of a tagged tenant keep their "tenant|" row.
        pairs = sorted(
            {(s.tenant, s.adapter) for s in window} - {("", "")}
        )
        snap["adapters"] = {
            f"{t}|{a}": fold(
                [s for s in window if s.tenant == t and s.adapter == a]
            )
            for t, a in pairs
        }
        return snap

    def goodput(self, scenario: Optional[str] = None) -> Optional[float]:
        snap = self.snapshot()
        if scenario is None:
            return snap["goodput"]
        sc = snap["scenarios"].get(scenario)
        return sc["goodput"] if sc else None

    # ---------------- exposition ----------------

    def render_metrics(self, prefix: str = "dynamo_goodput") -> str:
        from dynamo_tpu.utils.prometheus import render_family

        snap = self.snapshot()
        ratio_samples = []
        if snap["goodput"] is not None:
            ratio_samples.append(({"scenario": ""}, snap["goodput"]))
        ttft_samples, itl_samples = [], []
        for sc, s in sorted(snap["scenarios"].items()):
            if s["goodput"] is not None:
                ratio_samples.append(({"scenario": sc}, s["goodput"]))
            if s["ttft_p99_ms"] is not None:
                ttft_samples.append(({"scenario": sc}, s["ttft_p99_ms"] / 1e3))
            if s["itl_p99_ms"] is not None:
                itl_samples.append(({"scenario": sc}, s["itl_p99_ms"] / 1e3))
        out = render_family(
            f"{prefix}_ratio", "gauge",
            "windowed fraction of finished requests meeting their TTFT/ITL-p99 "
            "budgets, by scenario (scenario=\"\" = all traffic; absent = empty "
            "window)",
            ratio_samples or [({"scenario": ""}, 1.0)],
        )
        totals = []
        with self._lock:
            for sc, t in sorted(self._totals.items()):
                for i, result in enumerate(("met", "missed", "error")):
                    totals.append(({"scenario": sc, "result": result}, t[i]))
        out += render_family(
            f"{prefix}_requests_total", "counter",
            "lifetime finished requests by scenario and SLO verdict",
            totals or [({"scenario": "", "result": "met"}, 0)],
        )
        if ttft_samples:
            out += render_family(
                f"{prefix}_ttft_p99_seconds", "gauge",
                "windowed p99 of per-request TTFT by scenario", ttft_samples,
            )
        if itl_samples:
            out += render_family(
                f"{prefix}_itl_p99_seconds", "gauge",
                "windowed p99 of per-request ITL-p99 by scenario", itl_samples,
            )
        tenant_samples = [
            ({"tenant": t}, s["goodput"])
            for t, s in sorted(snap["tenants"].items())
            if s["goodput"] is not None
        ]
        if tenant_samples:
            out += render_family(
                f"{prefix}_tenant_ratio", "gauge",
                "windowed goodput by tenant (multi-tenant QoS view)",
                tenant_samples,
            )
        return out


def summarize_outcomes(
    outcomes: Iterable[RequestOutcome],
    wall_s: Optional[float] = None,
    ttft_budget_s: Optional[float] = None,
    itl_budget_s: Optional[float] = None,
) -> dict:
    """Bench/replay report over a finished outcome set: goodput against the
    budgets, pooled TTFT/ITL percentiles (ms), and output tok/s over
    ``wall_s`` (the replay's wall clock). The ``replay.{scenario}.*`` keys in
    the bench artifact come from exactly this dict."""
    outcomes = list(outcomes)
    n = len(outcomes)
    met = sum(
        1 for o in outcomes if outcome_meets(o, ttft_budget_s, itl_budget_s)
    )
    ttfts = [o.ttft_s for o in outcomes if o.ttft_s is not None]
    gaps: list[float] = []
    for o in outcomes:
        gaps.extend(o.itl_s)
    queue_waits = [o.queue_wait_s for o in outcomes if o.queue_wait_s is not None]
    out_tokens = sum(o.output_tokens for o in outcomes)
    return {
        "requests": n,
        "errors": sum(1 for o in outcomes if o.error),
        "goodput": round(met / n, 4) if n else None,
        "ttft_p50_ms": _ms(percentile(ttfts, 50)),
        "ttft_p99_ms": _ms(percentile(ttfts, 99)),
        "itl_p50_ms": _ms(percentile(gaps, 50)),
        "itl_p99_ms": _ms(percentile(gaps, 99)),
        "queue_wait_p99_ms": _ms(percentile(queue_waits, 99)),
        "output_tokens": out_tokens,
        "cached_tokens": sum(o.cached_tokens for o in outcomes),
        "tok_s": (
            round(out_tokens / wall_s, 2) if wall_s and wall_s > 0 else None
        ),
        "ttft_budget_ms": _ms(ttft_budget_s),
        "itl_budget_ms": _ms(itl_budget_s),
    }
