"""Multi-tenant QoS: priority classes, token-rate admission budgets, and the
queue-drain Retry-After estimator.

One engine now serves M fine-tunes and many tenants (PR 10/11), which makes
noisy-neighbor isolation the production gap ROADMAP item 5 names: a tenant-A
burst must not blow tenant B's ITL-p99 budget. The QoS plane pushes back at
three points, all built from this module:

  - **priority classes** (``critical`` | ``standard`` | ``batch``): stamped
    from the ``x-priority`` header or per-tenant/adapter policy, riding
    ``PreprocessedRequest`` -> ``EngineRequest`` like tenant tags. The
    scheduler composes class *weights* with the existing prefill fairness
    cap, admits the highest class first, and preempts ``batch`` lanes before
    anything else (preferring live migration when a peer can adopt).
  - **admission control**: per-tenant windowed token buckets
    (``AdmissionController``) answer a structured retriable **429 +
    Retry-After** at the HTTP frontend BEFORE any SSE bytes when a tenant's
    token-rate budget is exhausted, and an engine-backpressure check (queue
    depth x measured drain rate vs the TTFT budget) sheds ``batch``-class
    load first.
  - **Retry-After from measurement**: ``DrainRateEstimator`` watches request
    completions and prices "how long until the queue drains" — shared by the
    new 429 path and the existing draining-503 path (which used to send a
    constant), clamped to [1, 30] s.

Everything here is pure stdlib + thread-safe (the engine loop, the HTTP
asyncio thread, and the bench all touch it). Exposed as the ``dynamo_qos_*``
Prometheus families (conformance-checked), ``resource_snapshot.qos``,
dynotop's QOS column, and the bench ``qos`` isolation section.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Optional

#: ordered most- to least-important; rank = index (lower = more important)
PRIORITY_CLASSES = ("critical", "standard", "batch")
DEFAULT_PRIORITY = "standard"

#: fairness-cap composition: one prefill start consumes 1/weight cap units,
#: so at the default per-step cap of 2 a critical burst can start 4 prefill
#: chains per step while batch work gets at most one — priority shapes the
#: exact serialization pressure the fairness cap exists to bound, instead of
#: adding a second competing throttle
PRIORITY_WEIGHTS = {"critical": 2.0, "standard": 1.0, "batch": 0.5}

_RANK = {c: i for i, c in enumerate(PRIORITY_CLASSES)}


def parse_priority(value: Optional[str]) -> str:
    """Strict parse of a client-supplied class name (the ``x-priority``
    header): unknown values raise so the frontend can answer a structured
    400 instead of silently serving at the wrong class."""
    if not value:
        return DEFAULT_PRIORITY
    v = str(value).strip().lower()
    if v not in _RANK:
        raise ValueError(
            f"unknown priority class {value!r} (expected one of {PRIORITY_CLASSES})"
        )
    return v


def priority_rank(cls: Optional[str]) -> int:
    """Scheduling rank (0 = most important). Unknown/empty values rank as
    ``standard`` — wire peers predating the QoS plane keep today's order."""
    return _RANK.get(cls or DEFAULT_PRIORITY, _RANK[DEFAULT_PRIORITY])


def priority_weight(cls: Optional[str]) -> float:
    return PRIORITY_WEIGHTS.get(cls or DEFAULT_PRIORITY, 1.0)


# ---------------- Retry-After from measured drain ----------------

RETRY_AFTER_MIN_S = 1.0
RETRY_AFTER_MAX_S = 30.0
#: fallback when nothing has finished yet (cold engine): the old constant
RETRY_AFTER_DEFAULT_S = 10.0


def retry_after_from_queue(
    queue_depth: int,
    drain_rps: Optional[float],
    default_s: float = RETRY_AFTER_DEFAULT_S,
) -> int:
    """Seconds a client should back off before retrying: the time the
    current queue takes to drain at the measured completion rate, clamped to
    [1, 30] s (sub-second advice churns reconnects; >30 s advice outlives
    any burst this plane is sized for). With no measured rate yet, the
    clamped default."""
    if drain_rps and drain_rps > 0:
        est = queue_depth / drain_rps if queue_depth > 0 else RETRY_AFTER_MIN_S
    else:
        est = default_s
    return int(round(min(RETRY_AFTER_MAX_S, max(RETRY_AFTER_MIN_S, est))))


class DrainRateEstimator:
    """Windowed request-completion rate (requests/s) off finish events.

    Fed by the engine's outcome sink (every natural finish, errors included
    — an erroring engine still drains its queue); read by the frontend's
    backpressure check and both retriable-status paths (429 and 503) so one
    measurement prices every Retry-After. Thread-safe."""

    def __init__(self, window_s: float = 60.0, max_samples: int = 2048,
                 clock=time.monotonic):
        self.window_s = window_s
        self._clock = clock
        self._lock = threading.Lock()
        from collections import deque

        self._finishes = deque(maxlen=max_samples)

    def note_finish(self, n: int = 1) -> None:
        now = self._clock()
        with self._lock:
            for _ in range(max(1, n)):
                self._finishes.append(now)

    def rate_rps(self) -> Optional[float]:
        """Completions per second over the window; None until anything
        finished (a cold engine must not fake an infinite drain rate)."""
        now = self._clock()
        cutoff = now - self.window_s
        with self._lock:
            while self._finishes and self._finishes[0] < cutoff:
                self._finishes.popleft()
            n = len(self._finishes)
            if n == 0:
                return None
            span = max(now - self._finishes[0], 1e-3)
        return n / span

    def retry_after_s(self, queue_depth: int) -> int:
        return retry_after_from_queue(queue_depth, self.rate_rps())


# ---------------- token buckets ----------------


class TokenBucket:
    """Windowed token-rate budget: ``rate`` tokens/s refill up to ``burst``
    capacity. NOT thread-safe on its own — the AdmissionController holds the
    lock (one lock for buckets + counters keeps admit() atomic)."""

    def __init__(self, rate: float, burst: Optional[float] = None,
                 clock=time.monotonic):
        if rate <= 0:
            raise ValueError(f"token bucket rate must be > 0; got {rate}")
        self.rate = float(rate)
        # default burst: 2 s of rate — enough that a single normal request
        # never throttles an idle tenant, small enough that a burst can't
        # pre-bank minutes of budget
        self.burst = float(burst) if burst is not None else 2.0 * self.rate
        self._clock = clock
        self._tokens = self.burst
        self._last = clock()

    def _refill(self, now: float) -> None:
        self._tokens = min(self.burst, self._tokens + (now - self._last) * self.rate)
        self._last = now

    def try_consume(self, n: float) -> bool:
        """Take ``n`` tokens if available. A request larger than the whole
        burst capacity is admitted when the bucket is FULL (draining it to
        zero) — a budget must throttle sustained overuse, not permanently
        deadlock one oversized-but-legitimate request."""
        now = self._clock()
        self._refill(now)
        need = min(float(n), self.burst)
        if self._tokens >= need - 1e-9:
            self._tokens -= need
            return True
        return False

    def fill_fraction(self) -> float:
        self._refill(self._clock())
        return self._tokens / self.burst if self.burst > 0 else 0.0

    def seconds_until(self, n: float) -> float:
        """Time until ``n`` tokens are available (0 if already)."""
        self._refill(self._clock())
        need = min(float(n), self.burst)
        deficit = need - self._tokens
        return max(0.0, deficit / self.rate)


# ---------------- policy ----------------


@dataclass
class QosPolicy:
    """Frontend QoS configuration: per-tenant token budgets + per-tenant/
    adapter default priority classes.

    Spec grammar (env ``DYNTPU_QOS_BUDGETS`` / ``DYNTPU_QOS_PRIORITIES`` or
    CLI/yaml passthrough):

        budgets:    "tenant-a=500,tenant-b=4000:8000,*=2000"
                    (``tenant=rate[:burst]`` tokens/s; ``*`` = default for
                    unlisted tenants; no ``*`` = unlisted tenants unlimited)
        priorities: "tenant-a=batch,tenant-b=critical,adapter:a1=batch"
                    (keys are tenant names or ``adapter:<name>``; the
                    x-priority header wins over policy)
    """

    # tenant -> (rate_tokens_per_s, burst_tokens or None)
    budgets: dict = field(default_factory=dict)
    default_budget: Optional[tuple] = None  # the "*" entry
    priorities: dict = field(default_factory=dict)  # tenant -> class
    adapter_priorities: dict = field(default_factory=dict)  # adapter -> class
    # backpressure shed: estimated queue wait beyond which batch-class load
    # sheds when no TTFT SLO target is configured to derive it from
    shed_wait_s: float = 10.0
    # fleet-shared admission: the number of frontend replicas the budget spec
    # is split across. Specs name the FLEET budget; each replica enforces
    # rate/N and burst/N deterministically, so N frontends together admit
    # exactly one shared budget's worth — no coordination traffic, no 2x
    # leakage from per-replica buckets (Mooncake's fleet-level admission
    # plane, done by arithmetic instead of consensus)
    fleet_replicas: int = 1

    @classmethod
    def from_specs(cls, budget_spec: str = "", priority_spec: str = "",
                   shed_wait_s: float = 10.0,
                   fleet_replicas: int = 1) -> "QosPolicy":
        budgets: dict = {}
        default_budget = None
        for rule in filter(None, (r.strip() for r in (budget_spec or "").split(","))):
            tenant, _, rhs = rule.partition("=")
            tenant = tenant.strip()
            if not rhs:
                raise ValueError(f"budget rule {rule!r} needs tenant=rate[:burst]")
            rate_s, _, burst_s = rhs.partition(":")
            rate = float(rate_s)
            burst = float(burst_s) if burst_s else None
            if tenant == "*":
                default_budget = (rate, burst)
            else:
                budgets[tenant] = (rate, burst)
        priorities: dict = {}
        adapter_priorities: dict = {}
        for rule in filter(None, (r.strip() for r in (priority_spec or "").split(","))):
            key, _, val = rule.partition("=")
            key = key.strip()
            if not val:
                raise ValueError(f"priority rule {rule!r} needs key=class")
            pcls = parse_priority(val)
            if key.startswith("adapter:"):
                adapter_priorities[key[len("adapter:"):]] = pcls
            else:
                priorities[key] = pcls
        if fleet_replicas < 1:
            raise ValueError(f"fleet_replicas must be >= 1; got {fleet_replicas}")
        return cls(budgets=budgets, default_budget=default_budget,
                   priorities=priorities, adapter_priorities=adapter_priorities,
                   shed_wait_s=shed_wait_s, fleet_replicas=fleet_replicas)

    @classmethod
    def from_env(cls, environ=None) -> Optional["QosPolicy"]:
        """Policy from DYNTPU_QOS_BUDGETS / DYNTPU_QOS_PRIORITIES (None when
        neither is set — the frontend runs without an admission plane)."""
        import os

        env = environ if environ is not None else os.environ
        budgets = env.get("DYNTPU_QOS_BUDGETS", "").strip()
        prios = env.get("DYNTPU_QOS_PRIORITIES", "").strip()
        if not budgets and not prios:
            return None
        shed = env.get("DYNTPU_QOS_SHED_WAIT_S", "").strip()
        replicas = env.get("DYNTPU_QOS_FLEET_REPLICAS", "").strip()
        return cls.from_specs(budgets, prios,
                              shed_wait_s=float(shed) if shed else 10.0,
                              fleet_replicas=int(replicas) if replicas else 1)

    def priority_for(self, tenant: str = "", adapter: str = "") -> str:
        """Policy default class for a request (header wins at the caller)."""
        if adapter and adapter in self.adapter_priorities:
            return self.adapter_priorities[adapter]
        return self.priorities.get(tenant, DEFAULT_PRIORITY)


# ---------------- admission controller ----------------


@dataclass
class AdmissionDecision:
    admitted: bool
    action: str  # admitted | throttled | shed
    retry_after_s: int = 0
    reason: str = ""


class AdmissionController:
    """The frontend admission plane: per-tenant token buckets + counters +
    the ``dynamo_qos_*`` exposition. One lock covers buckets and counters so
    an admit() is atomic under the asyncio + replay threads."""

    def __init__(self, policy: Optional[QosPolicy] = None, clock=time.monotonic):
        self.policy = policy or QosPolicy()
        self._clock = clock
        self._lock = threading.Lock()
        self._buckets: dict[str, TokenBucket] = {}
        # (class, tenant, action) -> count; action in admitted|throttled|shed
        self._counts: dict[tuple, int] = {}
        # tenant -> tokens actually admitted: the fleet-leakage audit trail
        # (summing this across replicas must stay inside ONE shared budget)
        self._admitted_tokens: dict[str, float] = {}

    def _bucket_for(self, tenant: str) -> Optional[TokenBucket]:
        b = self._buckets.get(tenant)
        if b is not None:
            return b
        spec = self.policy.budgets.get(tenant, self.policy.default_budget)
        if spec is None:
            return None  # unbudgeted tenant: never throttled here
        rate, burst = spec
        # fleet split: each of N replicas enforces 1/N of the fleet budget.
        # burst=None keeps the 2s-of-rate default, which divides with the
        # rate automatically
        n = max(1, int(self.policy.fleet_replicas))
        b = TokenBucket(rate / n, burst / n if burst is not None else None,
                        clock=self._clock)
        self._buckets[tenant] = b
        return b

    def _count(self, cls: str, tenant: str, action: str) -> None:
        key = (cls, tenant, action)
        self._counts[key] = self._counts.get(key, 0) + 1

    def admit(
        self, tenant: str, cls: str, tokens: int, request_id: str = ""
    ) -> AdmissionDecision:
        """Charge ``tokens`` (prompt + output budget) against the tenant's
        bucket. A throttle is a *retriable* verdict: Retry-After says when
        the bucket will hold this request's cost."""
        from dynamo_tpu.utils import events

        with self._lock:
            bucket = self._bucket_for(tenant)
            if bucket is None or bucket.try_consume(tokens):
                self._count(cls, tenant, "admitted")
                self._admitted_tokens[tenant] = (
                    self._admitted_tokens.get(tenant, 0.0) + float(tokens)
                )
                decision = AdmissionDecision(True, "admitted")
            else:
                wait = bucket.seconds_until(tokens)
                self._count(cls, tenant, "throttled")
                decision = AdmissionDecision(
                    False, "throttled",
                    retry_after_s=int(round(
                        min(RETRY_AFTER_MAX_S, max(RETRY_AFTER_MIN_S, wait))
                    )),
                    reason=f"tenant {tenant or 'default'!r} token budget exhausted",
                )
        # journal outside the lock (explicit id when the caller has one —
        # HTTP admission runs before the RequestContext is established —
        # else the ambient context's)
        if decision.admitted:
            events.emit(
                "qos.admitted", request_id=request_id or None,
                tenant=tenant, priority=cls, tokens=tokens,
            )
        else:
            events.emit(
                "qos.throttled", request_id=request_id or None,
                tenant=tenant, priority=cls, tokens=tokens,
                retry_after_s=decision.retry_after_s,
            )
        return decision

    def record_shed(self, tenant: str, cls: str, request_id: str = "") -> None:
        """One request shed by the engine-backpressure check (counted here so
        sheds and throttles read off one family)."""
        with self._lock:
            self._count(cls, tenant, "shed")
        from dynamo_tpu.utils import events

        events.emit(
            "qos.shed", request_id=request_id or None,
            tenant=tenant, priority=cls, site="frontend",
        )

    def snapshot(self) -> dict:
        with self._lock:
            counts = dict(self._counts)
            fills = {t: round(b.fill_fraction(), 4)
                     for t, b in self._buckets.items()}
            admitted = dict(self._admitted_tokens)
        out: dict = {"budget_fill": fills, "classes": {},
                     "admitted_tokens": admitted,
                     "fleet_replicas": max(1, int(self.policy.fleet_replicas))}
        for (cls, tenant, action), n in sorted(counts.items()):
            out["classes"].setdefault(cls, {}).setdefault(tenant, {})[action] = n
        return out

    def render_metrics(self) -> str:
        from dynamo_tpu.utils.prometheus import render_family

        with self._lock:
            counts = sorted(self._counts.items())
            fills = sorted(
                (t, b.fill_fraction()) for t, b in self._buckets.items()
            )
        out = render_family(
            "dynamo_qos_requests_total", "counter",
            "admission-plane verdicts by priority class, tenant, and action "
            "(admitted; throttled = tenant token budget exhausted, 429; "
            "shed = engine backpressure shed batch-class load, 429)",
            [({"class": cls, "tenant": tenant, "action": action}, n)
             for (cls, tenant, action), n in counts]
            or [({"class": DEFAULT_PRIORITY, "tenant": "", "action": "admitted"}, 0)],
        )
        out += render_family(
            "dynamo_qos_budget_fill", "gauge",
            "per-tenant token-budget fill fraction (1 = full burst headroom, "
            "0 = exhausted; only budgeted tenants appear)",
            [({"tenant": t}, round(f, 4)) for t, f in fills]
            or [({"tenant": ""}, 1.0)],
        )
        return out
