"""Step-anatomy profiler: per-dispatch host/device time attribution with a
live roofline accounting plane.

The r5 judge decomposition put decode at 7.24 ms/step — 69.8% of the 5.05 ms
weight+KV HBM floor — with ~30% of every step lost to host dispatch/reconcile
overhead, but that number came from a one-off ``tools/profile_decode.py`` run.
This module makes the split a *standing* measurement: every engine dispatch
(decode window, packed prefill, per-request chunk, spec draft, spec verify,
LoRA slot load, prefix-fetch scatter, offload drain) records one
:class:`StepRecord` into a bounded ring, decomposed into four phases:

  host_prep    host time building the dispatch (numpy control arrays,
               capacity passes, table refreshes) before the runner call
  dispatch     host time inside the runner call (trace lookup, H2D, XLA
               dispatch — device may already be busy underneath)
  device_wait  host time *blocked* on device results (the reconcile sync the
               dispatch-ahead pipeline exists to hide)
  reconcile    host time materializing results back into scheduler state
               (token emission, EOS/stop scanning, stream posting)

Since the engine loop is single-threaded, the sum of all phases over all
kinds is the engine thread's wall time; ``host_frac`` (everything except
device_wait, over the total) is the fraction of a serving step the host
spends NOT waiting on the chip — the overhead the planned multi-step fused
decode (ROADMAP item 3) must drive down, and this plane is its before/after
instrument.

The roofline estimator prices the bytes-moved floor of a decode step from
live state: every step re-reads the full parameter set plus each live
sequence's KV pages (``quant/kv.kv_page_bytes`` at the ACTUAL cache dtype,
so int8 KV lowers the floor exactly as it lowers HBM traffic). Dividing by
the device's HBM bandwidth (``DYNTPU_HBM_GBPS``, default v5e's 819) gives a
floor time; ``roofline_fraction`` = floor / measured decode seconds — the
69.8% number as a gauge (``dynamo_engine_roofline_fraction``). On CPU the
bandwidth constant is fiction, but the *bytes* are exact and the fraction
still moves with the same code changes, so CPU smoke runs record it labeled
with the platform.

Prefill gets the same treatment (PR 19): a prefill dispatch is
compute-bound once the chunk is wide enough, so its floor is
``max(FLOP bound, bytes bound)`` — ~2·param_count FLOPs per prompt row
against the MXU peak (``DYNTPU_MXU_TFLOPS``, default v5e's 197 bf16), vs
one weight read plus the KV the chunk writes against HBM bandwidth. Each
``prefill_packed``/``prefill_chunk`` record prices its floor at dispatch
(``note_prefill_floor``); ``prefill_roofline_fraction`` = summed floors /
measured prefill engine seconds (``dynamo_engine_prefill_roofline_fraction``)
and ``prefill_fixed_ms`` is the live per-dispatch host cost — the quantity
``tools/profile_prefill.py`` decomposes into host-prep / H2D / dispatch /
kernel on hardware.

Exposed everywhere the repo already has rails: ``render_metrics`` emits
``dynamo_step_seconds_total{phase,kind}`` / ``dynamo_step_dispatch_total
{kind}`` / ``dynamo_engine_roofline_fraction`` on the engine's conformance
surface, ``snapshot()`` rides ``resource_snapshot`` -> worker stats ->
dynotop STEP/ROOF/PREFILL columns, ``records()`` backs the ``/debug/steps``
JSON endpoint, and the bench ``step_anatomy``/``prefill_anatomy`` sections
price ``host_frac``/``roofline_frac``/``dispatch_gap_ms_p50`` and the
prefill dispatch economics per arm.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Optional

#: dispatch kinds (the label vocabulary of dynamo_step_seconds_total{kind=})
KINDS = (
    "decode_window",
    "prefill_packed",
    "prefill_chunk",
    "spec_draft",
    "spec_verify",
    "lora_slot_load",
    "prefix_fetch_scatter",
    "offload_drain",
)

PHASES = ("host_prep", "dispatch", "device_wait", "reconcile")

#: the prefill-regime dispatch kinds (the packed serving path and the
#: per-request chain) — the label set prefill_roofline_fraction and the
#: dynotop PREFILL column aggregate over
PREFILL_KINDS = ("prefill_packed", "prefill_chunk")

#: default ring capacity: at ms-scale steps this is a few seconds of recent
#: history — enough for dynotop/debug inspection without unbounded growth
DEFAULT_RING = 512

#: v5e HBM bandwidth; override with DYNTPU_HBM_GBPS for other parts
DEFAULT_HBM_GBPS = 819.0

#: v5e bf16 MXU peak; override with DYNTPU_MXU_TFLOPS for other parts (the
#: FLOP-bound side of the prefill floor — decode never touches it because a
#: single-token step is bytes-bound by orders of magnitude)
DEFAULT_MXU_TFLOPS = 197.0


def hbm_bandwidth_bytes_s() -> float:
    try:
        return float(os.environ.get("DYNTPU_HBM_GBPS", DEFAULT_HBM_GBPS)) * 1e9
    except ValueError:
        return DEFAULT_HBM_GBPS * 1e9


def mxu_flops_s() -> float:
    try:
        return float(
            os.environ.get("DYNTPU_MXU_TFLOPS", DEFAULT_MXU_TFLOPS)
        ) * 1e12
    except ValueError:
        return DEFAULT_MXU_TFLOPS * 1e12


@dataclass
class RooflineModel:
    """Bytes-moved floor arithmetic for one engine's decode step.

    param_bytes: every decode step reads the full parameter set once (the
    weight-bound term; int8 weights are 1 byte/element automatically because
    the bytes come from the actual leaves).
    page_bytes: HBM cost of ONE allocator page across all layers, K and V,
    at the ACTUAL kv_cache_dtype (``quant/kv.kv_page_bytes`` — int8 pages
    include their f32 scale planes).
    """

    param_bytes: int
    page_bytes: int
    page_size: int
    hbm_bw: float = field(default_factory=hbm_bandwidth_bytes_s)
    # parameter COUNT (not bytes): the FLOP side of the prefill floor is
    # ~2 FLOPs per parameter per row regardless of storage dtype
    param_count: int = 0
    mxu_flops: float = field(default_factory=mxu_flops_s)

    def step_floor_bytes(self, live_pages: int) -> int:
        """Bytes one decode step must move: weights + the live KV pages the
        batch's attention re-reads."""
        return self.param_bytes + live_pages * self.page_bytes

    def step_floor_seconds(self, live_pages: int) -> float:
        return self.step_floor_bytes(live_pages) / max(1.0, self.hbm_bw)

    def prefill_floor_bytes(self, rows: int) -> int:
        """Bytes one prefill dispatch must move: one weight read plus the KV
        pages the chunk's rows fill (attention re-reads of the context ride
        on-chip for the chunk widths the engine uses, so they are not priced
        — the floor stays a floor)."""
        pages = -(-max(0, rows) // max(1, self.page_size))
        return self.param_bytes + pages * self.page_bytes

    def prefill_floor_seconds(self, rows: int) -> float:
        """max(MXU-FLOP bound, bytes-moved bound) for a dispatch computing
        ``rows`` prompt rows: a dense forward pass is ~2·param_count FLOPs
        per row, so wide chunks are compute-bound and narrow ones fall back
        to the same weight-read floor decode pays."""
        bytes_s = self.prefill_floor_bytes(rows) / max(1.0, self.hbm_bw)
        flops_s = (
            2.0 * self.param_count * max(0, rows) / max(1.0, self.mxu_flops)
        )
        return max(bytes_s, flops_s)

    def to_dict(self) -> dict:
        return {
            "param_bytes": self.param_bytes,
            "page_bytes": self.page_bytes,
            "page_size": self.page_size,
            "hbm_bw_bytes_s": self.hbm_bw,
            "param_count": self.param_count,
            "mxu_flops_s": self.mxu_flops,
        }


def roofline_for_runner(runner, config) -> Optional[RooflineModel]:
    """Build the estimator from a live ModelRunner: parameter bytes from the
    actual leaves, page bytes from the model's own accounting (the same
    ``kv_page_bytes`` the resource gauges and dynotop render). None when the
    runner/model can't price pages (external engines, test fakes)."""
    model = getattr(runner, "model", None)
    params = getattr(runner, "params", None)
    if model is None or params is None or not hasattr(model, "kv_page_bytes"):
        return None
    try:
        import jax

        leaves = jax.tree_util.tree_leaves(params)
        param_bytes = int(sum(
            leaf.size * leaf.dtype.itemsize
            for leaf in leaves
            if hasattr(leaf, "size") and hasattr(leaf, "dtype")
        ))
        param_count = int(sum(
            leaf.size for leaf in leaves if hasattr(leaf, "size")
        ))
        page_bytes = int(model.kv_page_bytes(config.page_size))
    except Exception:
        return None
    if param_bytes <= 0 or page_bytes <= 0:
        return None
    return RooflineModel(
        param_bytes=param_bytes, page_bytes=page_bytes,
        page_size=config.page_size, param_count=param_count,
    )


@dataclass
class StepRecord:
    """One engine dispatch, decomposed. Mutated in place as phases land
    (device_wait/reconcile arrive at the pipelined reconcile, possibly
    several windows after the dispatch)."""

    seq: int  # monotonic record id (eviction-stable ordering)
    ts: float  # time.monotonic() at dispatch start
    kind: str
    host_prep_s: float = 0.0
    dispatch_s: float = 0.0
    device_wait_s: float = 0.0
    reconcile_s: float = 0.0
    steps: int = 0  # decode steps / verify rows this dispatch advances
    tokens: int = 0  # tokens scheduled (decode) or rows computed (prefill)
    participants: int = 0
    floor_bytes: int = 0  # bytes-moved floor estimate (decode kinds only)
    floor_s: float = 0.0  # max(FLOP, bytes) floor seconds (prefill kinds only)
    #: cost-attribution bill: (request_id, tenant, adapter, priority, weight)
    #: rows the meter splits this record's phases across (None = system work)
    bill: Optional[list] = None

    @property
    def total_s(self) -> float:
        return (self.host_prep_s + self.dispatch_s + self.device_wait_s
                + self.reconcile_s)

    @property
    def host_s(self) -> float:
        """Host time NOT blocked on the device."""
        return self.host_prep_s + self.dispatch_s + self.reconcile_s

    def to_dict(self) -> dict:
        return {
            "seq": self.seq,
            "ts": round(self.ts, 6),
            "kind": self.kind,
            "host_prep_ms": round(self.host_prep_s * 1e3, 4),
            "dispatch_ms": round(self.dispatch_s * 1e3, 4),
            "device_wait_ms": round(self.device_wait_s * 1e3, 4),
            "reconcile_ms": round(self.reconcile_s * 1e3, 4),
            "steps": self.steps,
            "tokens": self.tokens,
            "participants": self.participants,
            "floor_bytes": self.floor_bytes,
            "floor_ms": round(self.floor_s * 1e3, 4),
        }


class StepAnatomy:
    """Bounded ring of StepRecords + cumulative per-(phase, kind) counters.

    The engine thread is the only writer of records; ``snapshot``/
    ``render_metrics``/``records`` run on the asyncio/scrape threads, so the
    ring append and the counter updates take a lock (a handful of float adds
    per dispatch against ms-scale stages — same budget as StageStats).
    """

    def __init__(self, ring_size: int = DEFAULT_RING,
                 roofline: Optional[RooflineModel] = None):
        self._lock = threading.Lock()
        self.ring: deque[StepRecord] = deque(maxlen=ring_size)
        self._seq = 0
        # (phase, kind) -> cumulative seconds; kind -> dispatch count
        self.phase_seconds: dict[tuple[str, str], float] = {}
        self.dispatch_counts: dict[str, int] = {}
        self.steps_total: dict[str, int] = {}
        self.floor_bytes_total = 0  # cumulative priced floors
        self._floor_kinds: set[str] = set()  # kinds that recorded a floor
        # prefill plane: floors are SECONDS (max of FLOP and bytes bounds,
        # which don't share a unit) and accumulate separately so they can
        # never pollute the decode-regime roofline_fraction above
        self.prefill_floor_s_total = 0.0
        self._prefill_floor_kinds: set[str] = set()
        self.roofline = roofline
        #: optional utils/metering.MeterLedger — every clamped phase delta is
        #: forwarded to it with the record's bill, so the attributed cost
        #: plane shares this plane's samples (the conservation identity)
        self.meter = None

    # ---------------- recording (engine thread) ----------------

    def begin(self, kind: str, ts: Optional[float] = None,
              bill: Optional[list] = None) -> StepRecord:
        """Open one dispatch record and append it to the ring (it fills in
        place as phases complete). ``bill`` must be set before the first
        ``add_phase`` — phase deltas forward to the meter immediately."""
        with self._lock:
            self._seq += 1
            rec = StepRecord(seq=self._seq, ts=ts or time.monotonic(),
                             kind=kind, bill=bill)
            self.ring.append(rec)
            self.dispatch_counts[kind] = self.dispatch_counts.get(kind, 0) + 1
        return rec

    def add_phase(self, rec: Optional[StepRecord], phase: str, dt: float) -> None:
        """Attribute ``dt`` seconds of ``phase`` to a record (None-safe: a
        reconcile for an untracked dispatch still lands in the totals)."""
        if dt < 0:
            dt = 0.0
        kind = rec.kind if rec is not None else "decode_window"
        with self._lock:
            key = (phase, kind)
            self.phase_seconds[key] = self.phase_seconds.get(key, 0.0) + dt
            if rec is not None:
                setattr(rec, phase + "_s", getattr(rec, phase + "_s") + dt)
        if self.meter is not None and dt > 0:
            self.meter.on_phase(rec, phase, dt)

    def record(self, kind: str, dispatch_s: float, host_prep_s: float = 0.0,
               device_wait_s: float = 0.0, reconcile_s: float = 0.0,
               steps: int = 0, tokens: int = 0, participants: int = 0,
               floor_bytes: int = 0, ts: Optional[float] = None,
               bill: Optional[list] = None) -> StepRecord:
        """One-shot record for synchronous dispatch kinds (spec rounds, LoRA
        slot loads, scatters, drains): all phases known at the call site."""
        rec = self.begin(kind, ts=ts, bill=bill)
        for phase, dt in (("host_prep", host_prep_s), ("dispatch", dispatch_s),
                          ("device_wait", device_wait_s),
                          ("reconcile", reconcile_s)):
            if dt:
                self.add_phase(rec, phase, dt)
        self.note_steps(rec, steps=steps, tokens=tokens,
                        participants=participants, floor_bytes=floor_bytes)
        return rec

    def note_steps(self, rec: StepRecord, steps: int = 0, tokens: int = 0,
                   participants: int = 0, floor_bytes: int = 0) -> None:
        with self._lock:
            rec.steps += steps
            rec.tokens += tokens
            rec.participants = max(rec.participants, participants)
            rec.floor_bytes += floor_bytes
            if steps:
                self.steps_total[rec.kind] = (
                    self.steps_total.get(rec.kind, 0) + steps
                )
            if floor_bytes:
                self.floor_bytes_total += floor_bytes
                self._floor_kinds.add(rec.kind)

    def decode_floor_bytes(self, live_pages: int, steps: int) -> int:
        """Floor bytes for a K-step decode window at the current occupancy
        (0 when no roofline model is attached)."""
        if self.roofline is None:
            return 0
        return self.roofline.step_floor_bytes(live_pages) * max(1, steps)

    def note_prefill_floor(self, rec: Optional[StepRecord], rows: int) -> None:
        """Price one prefill dispatch's max(FLOP, bytes) floor live at the
        dispatch site (no-op without a roofline model or rows)."""
        if self.roofline is None or rec is None or rows <= 0:
            return
        floor_s = self.roofline.prefill_floor_seconds(rows)
        with self._lock:
            rec.floor_s += floor_s
            self.prefill_floor_s_total += floor_s
            self._prefill_floor_kinds.add(rec.kind)

    # ---------------- derived views (any thread) ----------------

    def _ring_snapshot(self) -> list[StepRecord]:
        with self._lock:
            return list(self.ring)

    def host_fraction(self, kinds: Optional[tuple] = None) -> Optional[float]:
        """Host-side share of engine time over the cumulative counters:
        (host_prep + dispatch + reconcile) / total. None before any data."""
        with self._lock:
            items = list(self.phase_seconds.items())
        host = wait = 0.0
        for (phase, kind), s in items:
            if kinds is not None and kind not in kinds:
                continue
            if phase == "device_wait":
                wait += s
            else:
                host += s
        total = host + wait
        if total <= 0:
            return None
        return host / total

    def roofline_fraction(self) -> Optional[float]:
        """floor / measured over the priced decode-regime kinds (decode
        windows; spec verify rounds on spec engines): the fraction of the
        decode regime's engine time the HBM floor accounts for. None until a
        priced dispatch completes."""
        if self.roofline is None:
            return None
        with self._lock:
            floor_bytes = self.floor_bytes_total
            measured = sum(
                s for (phase, kind), s in self.phase_seconds.items()
                if kind in self._floor_kinds
            )
        if floor_bytes <= 0 or measured <= 0:
            return None
        return (floor_bytes / self.roofline.hbm_bw) / measured

    def prefill_roofline_fraction(self) -> Optional[float]:
        """Summed per-dispatch prefill floors over measured prefill engine
        seconds — how close the prefill regime runs to max(MXU, HBM). The gap
        (1 - fraction) is per-dispatch fixed cost plus padding, the quantity
        the dispatch-ahead pipeline and bucket promotion attack. None until a
        priced prefill dispatch completes."""
        if self.roofline is None:
            return None
        with self._lock:
            floor_s = self.prefill_floor_s_total
            measured = sum(
                s for (phase, kind), s in self.phase_seconds.items()
                if kind in self._prefill_floor_kinds
            )
        if floor_s <= 0 or measured <= 0:
            return None
        return floor_s / measured

    def prefill_fixed_ms(self) -> Optional[float]:
        """Mean host-side (host_prep + dispatch) milliseconds per prefill
        dispatch — the live proxy for the per-call fixed cost
        ``tools/profile_prefill.py`` decomposes offline. None before any
        prefill dispatch."""
        with self._lock:
            host = sum(
                s for (phase, kind), s in self.phase_seconds.items()
                if kind in PREFILL_KINDS and phase in ("host_prep", "dispatch")
            )
            n = sum(self.dispatch_counts.get(k, 0) for k in PREFILL_KINDS)
        if n <= 0:
            return None
        return host / n * 1e3

    def dispatch_gap_ms(self, kind: str = "decode_window",
                        q: float = 0.5) -> Optional[float]:
        """Quantile of gaps between consecutive same-kind dispatch starts in
        the ring — the host-side cadence (a fused-decode win shows up here
        as the gap growing while tokens/gap grows faster)."""
        ts = [r.ts for r in self._ring_snapshot() if r.kind == kind]
        if len(ts) < 2:
            return None
        gaps = sorted(b - a for a, b in zip(ts, ts[1:]))
        idx = min(len(gaps) - 1, max(0, int(q * (len(gaps) - 1))))
        return gaps[idx] * 1e3

    def records(self, limit: int = 128, kind: Optional[str] = None) -> list[dict]:
        """Most-recent records (newest last) as JSON-safe dicts — the
        ``/debug/steps`` payload."""
        snap = self._ring_snapshot()
        if kind is not None:
            snap = [r for r in snap if r.kind == kind]
        return [r.to_dict() for r in snap[-max(0, limit):]]

    def snapshot(self) -> dict:
        """Wire-safe summary for resource_snapshot -> worker stats ->
        dynotop: per-kind second totals, the two headline fractions, and the
        decode dispatch cadence."""
        with self._lock:
            phase_seconds = {
                f"{phase}.{kind}": round(s, 6)
                for (phase, kind), s in sorted(self.phase_seconds.items())
            }
            counts = dict(self.dispatch_counts)
            steps = dict(self.steps_total)
            floor_bytes = self.floor_bytes_total
        gap = self.dispatch_gap_ms("decode_window")
        snap = {
            "phase_seconds": phase_seconds,
            "dispatches": counts,
            "steps": steps,
            "host_frac": _round_opt(self.host_fraction()),
            "decode_host_frac": _round_opt(
                self.host_fraction(kinds=("decode_window",))
            ),
            "roofline_frac": _round_opt(self.roofline_fraction()),
            "prefill_host_frac": _round_opt(
                self.host_fraction(kinds=PREFILL_KINDS)
            ),
            "prefill_roofline_frac": _round_opt(
                self.prefill_roofline_fraction()
            ),
            "prefill_fixed_ms": _round_opt(self.prefill_fixed_ms(), 3),
            "dispatch_gap_ms_p50": round(gap, 3) if gap is not None else None,
            "floor_bytes_total": floor_bytes,
            "records": len(self.ring),
        }
        if self.roofline is not None:
            snap["roofline"] = self.roofline.to_dict()
        return snap

    def render_metrics(self) -> str:
        """Prometheus families for the engine exposition surface."""
        from dynamo_tpu.utils.prometheus import render_family

        with self._lock:
            phase_items = sorted(self.phase_seconds.items())
            counts = sorted(self.dispatch_counts.items())
        parts = [
            render_family(
                "dynamo_step_seconds_total", "counter",
                "engine-thread seconds per step-anatomy phase and dispatch "
                "kind (host_prep/dispatch/reconcile = host overhead; "
                "device_wait = host blocked on the chip)",
                [({"kind": kind, "phase": phase}, round(s, 6))
                 for (phase, kind), s in phase_items]
                or [({"kind": "decode_window", "phase": "dispatch"}, 0)],
            ),
            render_family(
                "dynamo_step_dispatch_total", "counter",
                "engine dispatches by step-anatomy kind",
                [({"kind": k}, n) for k, n in counts]
                or [({"kind": "decode_window"}, 0)],
            ),
        ]
        frac = self.roofline_fraction()
        if frac is not None:
            parts.append(render_family(
                "dynamo_engine_roofline_fraction", "gauge",
                "HBM bytes-moved floor over measured decode-window engine "
                "seconds (1.0 = running at the roofline; the r5 69.8% "
                "decomposition as a standing gauge)",
                [({}, round(frac, 4))],
            ))
        pfrac = self.prefill_roofline_fraction()
        if pfrac is not None:
            parts.append(render_family(
                "dynamo_engine_prefill_roofline_fraction", "gauge",
                "summed max(MXU-FLOP, HBM-bytes) prefill dispatch floors "
                "over measured prefill engine seconds (1.0 = every dispatch "
                "at the hardware bound; the gap is fixed per-call cost)",
                [({}, round(pfrac, 4))],
            ))
        host = self.host_fraction()
        if host is not None:
            parts.append(render_family(
                "dynamo_step_host_fraction", "gauge",
                "host-side share of attributed engine time (1 - device_wait "
                "share): the per-token overhead multi-step fused decode "
                "exists to shrink",
                [({}, round(host, 4))],
            ))
        return "".join(parts)


def _round_opt(v: Optional[float], nd: int = 4) -> Optional[float]:
    return round(v, nd) if v is not None else None
