"""Minimal Prometheus text-exposition helpers (no client library).

Shared by the HTTP service metrics, the engine stage histograms, and the
standalone metrics component so every producer emits *conformant* exposition:
exactly one ``# HELP``/``# TYPE`` pair per metric family (emitted before the
family's first sample), canonically formatted ``le`` labels (never ``repr()``),
escaped label values, and cumulative histogram buckets ending at ``+Inf``.

``check_exposition`` is the promtool-style validator the test suite runs
against every ``/metrics`` surface; keeping it next to the formatters means a
new producer can't drift from what the checker enforces.
"""

from __future__ import annotations

import threading
from typing import Iterable, Optional, Sequence

_TYPES = ("counter", "gauge", "histogram", "summary", "untyped")


def fmt_value(v) -> str:
    """Canonical sample/bucket-bound formatting: shortest float that round-trips
    for exposition purposes ('0.005', '1', '60', '2.5e-05') — never repr().
    Pre-formatted strings pass through (callers pinning a decimal width)."""
    if isinstance(v, str):
        return v
    if v != v:  # NaN
        return "NaN"
    if v == float("inf"):
        return "+Inf"
    if v == float("-inf"):
        return "-Inf"
    s = f"{float(v):.12g}"
    return s


def escape_label_value(v) -> str:
    return str(v).replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def fmt_labels(labels: dict) -> str:
    if not labels:
        return ""
    inner = ",".join(
        f'{k}="{escape_label_value(v)}"' for k, v in sorted(labels.items())
    )
    return "{" + inner + "}"


class Histogram:
    """A labeled histogram family rendered in Prometheus text format.

    Buckets are cumulative (le-style); observe() walks a dozen floats so it is
    cheap enough for per-request hot paths. Thread-safe: the engine loop and
    the asyncio thread both observe.
    """

    def __init__(
        self,
        name: str,
        help: str,
        buckets: Sequence[float],
        label_names: Sequence[str] = (),
    ):
        self.name = name
        self.help = help
        self.buckets = tuple(buckets)
        self.label_names = tuple(label_names)
        self._lock = threading.Lock()
        # labelset tuple -> ([bucket counts], sum, count)
        self._series: dict[tuple, list] = {}

    def observe(self, value: float, labels: Sequence[str] = ()) -> None:
        key = tuple(labels)
        with self._lock:
            s = self._series.get(key)
            if s is None:
                s = [[0] * len(self.buckets), 0.0, 0]
                self._series[key] = s
            counts, _, _ = s
            for i, b in enumerate(self.buckets):
                if value <= b:
                    counts[i] += 1
            s[1] += value
            s[2] += 1

    @property
    def count(self) -> int:
        with self._lock:
            return sum(s[2] for s in self._series.values())

    @property
    def sum(self) -> float:
        with self._lock:
            return sum(s[1] for s in self._series.values())

    def render(self) -> str:
        lines = [
            f"# HELP {self.name} {self.help}",
            f"# TYPE {self.name} histogram",
        ]
        with self._lock:
            for key in sorted(self._series):
                counts, total, n = self._series[key]
                base = dict(zip(self.label_names, key))
                for b, c in zip(self.buckets, counts):
                    lines.append(
                        f"{self.name}_bucket{fmt_labels({**base, 'le': fmt_value(b)})} {c}"
                    )
                lines.append(f"{self.name}_bucket{fmt_labels({**base, 'le': '+Inf'})} {n}")
                lines.append(f"{self.name}_sum{fmt_labels(base)} {total:.6f}")
                lines.append(f"{self.name}_count{fmt_labels(base)} {n}")
        return "\n".join(lines) + "\n"


def render_family(
    name: str, mtype: str, help: str, samples: Iterable[tuple[dict, float]]
) -> str:
    """One complete family: HELP/TYPE then every (labels, value) sample."""
    lines = [f"# HELP {name} {help}", f"# TYPE {name} {mtype}"]
    for labels, value in samples:
        lines.append(f"{name}{fmt_labels(labels)} {fmt_value(value)}")
    return "\n".join(lines) + "\n"


def _family_of(sample_name: str, histogram_families: set[str]) -> str:
    for suffix in ("_bucket", "_sum", "_count"):
        if sample_name.endswith(suffix) and sample_name[: -len(suffix)] in histogram_families:
            return sample_name[: -len(suffix)]
    return sample_name


def check_exposition(text: str) -> list[str]:
    """Promtool-style lint of a text exposition. Returns a list of problems
    (empty = conformant). Enforced rules:

      - every sample belongs to a family with exactly one HELP and one TYPE
        line, both appearing before the family's first sample
      - TYPE values are legal; histogram families carry _bucket/_sum/_count
        samples and every ``le`` is a parseable float or ``+Inf``
      - sample values parse as floats; label strings are well-formed
    """
    problems: list[str] = []
    helps: dict[str, int] = {}
    types: dict[str, str] = {}
    first_sample_seen: set[str] = set()
    hist_families: set[str] = set()
    hist_has: dict[str, set] = {}

    for lineno, raw in enumerate(text.splitlines(), 1):
        line = raw.rstrip()
        if not line:
            continue
        if line.startswith("# HELP "):
            parts = line.split(None, 3)
            if len(parts) < 3:
                problems.append(f"line {lineno}: malformed HELP")
                continue
            fam = parts[2]
            helps[fam] = helps.get(fam, 0) + 1
            if helps[fam] > 1:
                problems.append(f"line {lineno}: duplicate HELP for {fam}")
            if fam in first_sample_seen:
                problems.append(f"line {lineno}: HELP for {fam} after its samples")
            continue
        if line.startswith("# TYPE "):
            parts = line.split()
            if len(parts) != 4:
                problems.append(f"line {lineno}: malformed TYPE")
                continue
            fam, mtype = parts[2], parts[3]
            if fam in types:
                problems.append(f"line {lineno}: duplicate TYPE for {fam}")
            if mtype not in _TYPES:
                problems.append(f"line {lineno}: illegal TYPE {mtype!r} for {fam}")
            if fam in first_sample_seen:
                problems.append(f"line {lineno}: TYPE for {fam} after its samples")
            types[fam] = mtype
            if mtype == "histogram":
                hist_families.add(fam)
                hist_has[fam] = set()
            continue
        if line.startswith("#"):
            continue  # free-text comment: legal, attaches to nothing
        # sample line: name{labels} value  |  name value
        name_part, _, value_part = line.rpartition(" ")
        if not name_part:
            problems.append(f"line {lineno}: malformed sample")
            continue
        try:
            float(value_part)
        except ValueError:
            problems.append(f"line {lineno}: non-numeric value {value_part!r}")
        labels: dict[str, str] = {}
        if "{" in name_part:
            name, _, rest = name_part.partition("{")
            if not rest.endswith("}"):
                problems.append(f"line {lineno}: unterminated label set")
                continue
            body = rest[:-1]
            # simple split: label values in this codebase never contain
            # escaped quotes followed by commas; good enough for linting
            for pair in filter(None, body.split(",")):
                if "=" not in pair:
                    problems.append(f"line {lineno}: malformed label {pair!r}")
                    continue
                k, _, v = pair.partition("=")
                if not (v.startswith('"') and v.endswith('"')):
                    problems.append(f"line {lineno}: unquoted label value in {pair!r}")
                    continue
                labels[k] = v[1:-1]
        else:
            name = name_part
        fam = _family_of(name, hist_families)
        first_sample_seen.add(fam)
        if fam not in types:
            problems.append(f"line {lineno}: sample {name} has no TYPE for family {fam}")
        if fam not in helps:
            problems.append(f"line {lineno}: sample {name} has no HELP for family {fam}")
        if fam in hist_families:
            for suffix in ("_bucket", "_sum", "_count"):
                if name == fam + suffix:
                    hist_has[fam].add(suffix)
            if name == fam + "_bucket":
                le = labels.get("le")
                if le is None:
                    problems.append(f"line {lineno}: histogram bucket without le")
                elif le != "+Inf":
                    try:
                        float(le)
                    except ValueError:
                        problems.append(f"line {lineno}: unparseable le {le!r}")

    for fam, seen in hist_has.items():
        missing = {"_bucket", "_sum", "_count"} - seen
        if fam in first_sample_seen and missing:
            problems.append(f"histogram {fam} missing {sorted(missing)} samples")
    return problems
