"""Minimal Prometheus text-exposition helpers (no client library).

Shared by the HTTP service metrics, the engine stage histograms, and the
standalone metrics component so every producer emits *conformant* exposition:
exactly one ``# HELP``/``# TYPE`` pair per metric family (emitted before the
family's first sample), canonically formatted ``le`` labels (never ``repr()``),
escaped label values, and cumulative histogram buckets ending at ``+Inf``.

``check_exposition`` is the promtool-style validator the test suite runs
against every ``/metrics`` surface; keeping it next to the formatters means a
new producer can't drift from what the checker enforces.
"""

from __future__ import annotations

import threading
from typing import Iterable, Sequence

_TYPES = ("counter", "gauge", "histogram", "summary", "untyped")

#: The conformance surface for every ``dynamo_*`` metric family this system
#: exposes. Three planes pin each other through this one tuple:
#:   - ``--check`` (the lint gate) asserts the families RENDERED by
#:     ``_sample_surfaces()`` equal this set exactly — a new emitter must
#:     declare itself here, a removed one must be deleted here;
#:   - ``tools/graftlint`` (metric-conformance detector) statically checks
#:     every ``dynamo_*`` string literal at an emitting site against this
#:     tuple, and that every name here is referenced by some emitter;
#:   - the exposition tests ride the same ``_sample_surfaces()`` list.
#: So a metric-name typo, a family renamed on one side only, or a dead
#: declaration all fail CI before any cluster exists. Keep one name per line
#: (graftlint suppressions are per-line).
DECLARED_METRIC_FAMILIES: tuple = (
    "dynamo_alert_state",
    "dynamo_cost_device_seconds_total",
    "dynamo_cost_kv_byte_seconds_total",
    "dynamo_cost_kv_resident_bytes",
    "dynamo_cost_queued_seconds_total",
    "dynamo_cost_tokens_total",
    "dynamo_engine_context_chunk_total",
    "dynamo_engine_context_table_dispatch_total",
    "dynamo_engine_context_table_promotions_total",
    "dynamo_engine_decode_window_dispatch_seconds",
    "dynamo_engine_disk_blocks",
    "dynamo_engine_disk_bytes",
    "dynamo_engine_disk_restore_seconds",
    "dynamo_engine_disk_restores_total",
    "dynamo_engine_disk_spills_total",
    "dynamo_engine_goodput_itl_p99_seconds",
    "dynamo_engine_goodput_ratio",
    "dynamo_engine_goodput_requests_total",
    "dynamo_engine_goodput_ttft_p99_seconds",
    "dynamo_engine_hbm_bytes",
    "dynamo_engine_kv_cache_bytes",
    "dynamo_engine_kv_cache_page_bytes",
    "dynamo_engine_kv_pages",
    "dynamo_engine_offload_blocks_total",
    "dynamo_engine_offload_bytes_resident",
    "dynamo_engine_offload_pressure_blocks_total",
    "dynamo_engine_preemptions_total",
    "dynamo_engine_prefill_roofline_fraction",
    "dynamo_engine_prefill_seconds",
    "dynamo_engine_prefix_cache_blocks_total",
    "dynamo_engine_pressure_drains_total",
    "dynamo_engine_queue_wait_seconds",
    "dynamo_engine_reconcile_wait_seconds",
    "dynamo_engine_roofline_fraction",
    "dynamo_engine_slo_latency_seconds",
    "dynamo_engine_slo_violations_total",
    "dynamo_engine_stage_seconds_total",
    "dynamo_engine_ttft_seconds",
    "dynamo_engine_xla_compile_seconds_total",
    "dynamo_engine_xla_compiles_total",
    "dynamo_event_captures_pinned_total",
    "dynamo_event_emitted_total",
    "dynamo_event_journal_size",
    "dynamo_goodput_itl_p99_seconds",
    "dynamo_goodput_ratio",
    "dynamo_goodput_requests_total",
    "dynamo_goodput_tenant_ratio",
    "dynamo_goodput_ttft_p99_seconds",
    "dynamo_health_heartbeat_age_seconds",
    "dynamo_health_state",
    "dynamo_health_uptime_seconds",
    "dynamo_kv_stream_bytes_received_total",
    "dynamo_kv_stream_bytes_sent_total",
    "dynamo_kv_stream_checksum_failures_total",
    "dynamo_kv_stream_dropped_total",
    "dynamo_kv_stream_lanes",
    "dynamo_kv_stream_overlap_seconds_total",
    "dynamo_kv_stream_part_bytes",
    "dynamo_kv_stream_parts_received_total",
    "dynamo_kv_stream_parts_sent_total",
    "dynamo_kv_stream_reconnects_total",
    "dynamo_kv_stream_rejected_total",
    "dynamo_kv_stream_requests_total",
    "dynamo_kv_stream_send_seconds_total",
    "dynamo_kv_stream_transfers_received_total",
    "dynamo_lora_evictions_total",
    "dynamo_lora_load_seconds_total",
    "dynamo_lora_loads_total",
    "dynamo_lora_requests_total",
    "dynamo_lora_slots",
    "dynamo_migration_pause_seconds",
    "dynamo_migration_requests_total",
    "dynamo_migration_tokens_salvaged_total",
    "dynamo_planner_rebalance_executed_total",
    "dynamo_prefix_fetch_blocks_total",
    "dynamo_prefix_fetch_bytes_total",
    "dynamo_prefix_fetch_client_blocks_total",
    "dynamo_prefix_fetch_client_bytes_total",
    "dynamo_prefix_fetch_client_requests_total",
    "dynamo_prefix_fetch_client_seconds",
    "dynamo_prefix_fetch_requests_total",
    "dynamo_prefix_fetch_seconds",
    "dynamo_prefix_fetch_served_blocks_total",
    "dynamo_prefix_fetch_served_bytes_total",
    "dynamo_prefix_fetch_served_total",
    "dynamo_prefix_fetch_tokens_total",
    "dynamo_qos_budget_fill",
    "dynamo_qos_preemptions_total",
    "dynamo_qos_requests_total",
    "dynamo_replay_inflight_requests",
    "dynamo_replay_requests_total",
    "dynamo_replay_schedule_lag_seconds",
    "dynamo_replay_tokens_total",
    "dynamo_router_radix_bytes",
    "dynamo_router_radix_evictions_total",
    "dynamo_router_radix_hits_total",
    "dynamo_router_radix_nodes",
    "dynamo_slo_burn_rate",
    "dynamo_slo_compliance_ratio",
    "dynamo_slo_error_budget_remaining",
    "dynamo_slo_latency_seconds",
    "dynamo_slo_target_seconds",
    "dynamo_slo_violations_total",
    "dynamo_spec_acceptance_ratio",
    "dynamo_spec_accepted_per_round",
    "dynamo_spec_accepted_total",
    "dynamo_spec_draft_dispatch_total",
    "dynamo_spec_draft_pages",
    "dynamo_spec_draft_prefill_total",
    "dynamo_spec_draft_seconds_total",
    "dynamo_spec_proposed_total",
    "dynamo_step_dispatch_total",
    "dynamo_step_host_fraction",
    "dynamo_step_seconds_total",
)


def fmt_value(v) -> str:
    """Canonical sample/bucket-bound formatting: shortest float that round-trips
    for exposition purposes ('0.005', '1', '60', '2.5e-05') — never repr().
    Pre-formatted strings pass through (callers pinning a decimal width)."""
    if isinstance(v, str):
        return v
    if v != v:  # NaN
        return "NaN"
    if v == float("inf"):
        return "+Inf"
    if v == float("-inf"):
        return "-Inf"
    s = f"{float(v):.12g}"
    return s


def escape_label_value(v) -> str:
    return str(v).replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def fmt_labels(labels: dict) -> str:
    if not labels:
        return ""
    inner = ",".join(
        f'{k}="{escape_label_value(v)}"' for k, v in sorted(labels.items())
    )
    return "{" + inner + "}"


class Histogram:
    """A labeled histogram family rendered in Prometheus text format.

    Buckets are cumulative (le-style); observe() walks a dozen floats so it is
    cheap enough for per-request hot paths. Thread-safe: the engine loop and
    the asyncio thread both observe.
    """

    def __init__(
        self,
        name: str,
        help: str,
        buckets: Sequence[float],
        label_names: Sequence[str] = (),
    ):
        self.name = name
        self.help = help
        self.buckets = tuple(buckets)
        self.label_names = tuple(label_names)
        self._lock = threading.Lock()
        # labelset tuple -> ([bucket counts], sum, count)
        self._series: dict[tuple, list] = {}

    def observe(self, value: float, labels: Sequence[str] = ()) -> None:
        key = tuple(labels)
        with self._lock:
            s = self._series.get(key)
            if s is None:
                s = [[0] * len(self.buckets), 0.0, 0]
                self._series[key] = s
            counts, _, _ = s
            for i, b in enumerate(self.buckets):
                if value <= b:
                    counts[i] += 1
            s[1] += value
            s[2] += 1

    @property
    def count(self) -> int:
        with self._lock:
            return sum(s[2] for s in self._series.values())

    @property
    def sum(self) -> float:
        with self._lock:
            return sum(s[1] for s in self._series.values())

    def render(self) -> str:
        lines = [
            f"# HELP {self.name} {self.help}",
            f"# TYPE {self.name} histogram",
        ]
        with self._lock:
            for key in sorted(self._series):
                counts, total, n = self._series[key]
                base = dict(zip(self.label_names, key))
                for b, c in zip(self.buckets, counts):
                    lines.append(
                        f"{self.name}_bucket{fmt_labels({**base, 'le': fmt_value(b)})} {c}"
                    )
                lines.append(f"{self.name}_bucket{fmt_labels({**base, 'le': '+Inf'})} {n}")
                lines.append(f"{self.name}_sum{fmt_labels(base)} {total:.6f}")
                lines.append(f"{self.name}_count{fmt_labels(base)} {n}")
        return "\n".join(lines) + "\n"


def render_family(
    name: str, mtype: str, help: str, samples: Iterable[tuple[dict, float]]
) -> str:
    """One complete family: HELP/TYPE then every (labels, value) sample."""
    lines = [f"# HELP {name} {help}", f"# TYPE {name} {mtype}"]
    for labels, value in samples:
        lines.append(f"{name}{fmt_labels(labels)} {fmt_value(value)}")
    return "\n".join(lines) + "\n"


def _family_of(sample_name: str, histogram_families: set[str]) -> str:
    for suffix in ("_bucket", "_sum", "_count"):
        if sample_name.endswith(suffix) and sample_name[: -len(suffix)] in histogram_families:
            return sample_name[: -len(suffix)]
    return sample_name


def check_exposition(text: str) -> list[str]:
    """Promtool-style lint of a text exposition. Returns a list of problems
    (empty = conformant). Enforced rules:

      - every sample belongs to a family with exactly one HELP and one TYPE
        line, both appearing before the family's first sample
      - TYPE values are legal; histogram families carry _bucket/_sum/_count
        samples and every ``le`` is a parseable float or ``+Inf``
      - sample values parse as floats; label strings are well-formed
    """
    problems: list[str] = []
    helps: dict[str, int] = {}
    types: dict[str, str] = {}
    first_sample_seen: set[str] = set()
    hist_families: set[str] = set()
    hist_has: dict[str, set] = {}

    for lineno, raw in enumerate(text.splitlines(), 1):
        line = raw.rstrip()
        if not line:
            continue
        if line.startswith("# HELP "):
            parts = line.split(None, 3)
            if len(parts) < 3:
                problems.append(f"line {lineno}: malformed HELP")
                continue
            fam = parts[2]
            helps[fam] = helps.get(fam, 0) + 1
            if helps[fam] > 1:
                problems.append(f"line {lineno}: duplicate HELP for {fam}")
            if fam in first_sample_seen:
                problems.append(f"line {lineno}: HELP for {fam} after its samples")
            continue
        if line.startswith("# TYPE "):
            parts = line.split()
            if len(parts) != 4:
                problems.append(f"line {lineno}: malformed TYPE")
                continue
            fam, mtype = parts[2], parts[3]
            if fam in types:
                problems.append(f"line {lineno}: duplicate TYPE for {fam}")
            if mtype not in _TYPES:
                problems.append(f"line {lineno}: illegal TYPE {mtype!r} for {fam}")
            if fam in first_sample_seen:
                problems.append(f"line {lineno}: TYPE for {fam} after its samples")
            types[fam] = mtype
            if mtype == "histogram":
                hist_families.add(fam)
                hist_has[fam] = set()
            continue
        if line.startswith("#"):
            continue  # free-text comment: legal, attaches to nothing
        # sample line: name{labels} value  |  name value
        name_part, _, value_part = line.rpartition(" ")
        if not name_part:
            problems.append(f"line {lineno}: malformed sample")
            continue
        try:
            float(value_part)
        except ValueError:
            problems.append(f"line {lineno}: non-numeric value {value_part!r}")
        labels: dict[str, str] = {}
        if "{" in name_part:
            name, _, rest = name_part.partition("{")
            if not rest.endswith("}"):
                problems.append(f"line {lineno}: unterminated label set")
                continue
            body = rest[:-1]
            # simple split: label values in this codebase never contain
            # escaped quotes followed by commas; good enough for linting
            for pair in filter(None, body.split(",")):
                if "=" not in pair:
                    problems.append(f"line {lineno}: malformed label {pair!r}")
                    continue
                k, _, v = pair.partition("=")
                if not (v.startswith('"') and v.endswith('"')):
                    problems.append(f"line {lineno}: unquoted label value in {pair!r}")
                    continue
                labels[k] = v[1:-1]
        else:
            name = name_part
        fam = _family_of(name, hist_families)
        first_sample_seen.add(fam)
        if fam not in types:
            problems.append(f"line {lineno}: sample {name} has no TYPE for family {fam}")
        if fam not in helps:
            problems.append(f"line {lineno}: sample {name} has no HELP for family {fam}")
        if fam in hist_families:
            for suffix in ("_bucket", "_sum", "_count"):
                if name == fam + suffix:
                    hist_has[fam].add(suffix)
            if name == fam + "_bucket":
                le = labels.get("le")
                if le is None:
                    problems.append(f"line {lineno}: histogram bucket without le")
                elif le != "+Inf":
                    try:
                        float(le)
                    except ValueError:
                        problems.append(f"line {lineno}: unparseable le {le!r}")

    for fam, seen in hist_has.items():
        missing = {"_bucket", "_sum", "_count"} - seen
        if fam in first_sample_seen and missing:
            problems.append(f"histogram {fam} missing {sorted(missing)} samples")
    return problems


# ---------------- self-check (python -m dynamo_tpu.utils.prometheus --check) ----


def _sample_surfaces() -> list[tuple[str, str]]:
    """Build every exposition surface with representative samples, WITHOUT a
    cluster: (name, rendered text) pairs. The CI lint gate and the
    conformance test both run check_exposition over these, so a new metric
    family can't regress HELP/TYPE/label format unnoticed."""
    import time as _time

    surfaces: list[tuple[str, str]] = []

    # HTTP service metrics (request counters + latency histograms)
    from dynamo_tpu.llm.http.metrics import Metrics

    m = Metrics()
    m.inc_request("tiny", "chat_completions", "stream", "200")
    m.inflight("tiny", 1)
    m.observe_duration("tiny", "chat_completions", 0.25)
    m.observe_ttft("tiny", 0.05)
    m.observe_itl("tiny", 0.004)
    surfaces.append(("llm.http.metrics", m.render()))

    # SLO tracker + health monitor (fleet health plane)
    from dynamo_tpu.utils.health import HealthMonitor
    from dynamo_tpu.utils.slo import SloTracker

    slo = SloTracker({"ttft": 0.5, "itl": 0.05})
    for v in (0.1, 0.2, 0.7):
        slo.observe("ttft", v)
        slo.observe("itl", v / 20)
    # tenant- and priority-class-labeled series must render conformantly
    # alongside the aggregate
    slo.observe("ttft", 0.15, tenant="tenant-a")
    slo.observe("ttft", 0.12, priority="critical")
    surfaces.append(("utils.slo", slo.render_metrics()))
    # burn-rate alerting surface (dynamo_slo_burn_rate + dynamo_alert_state):
    # a separate render method because the engine re-renders the same tracker
    # under its dynamo_engine_slo prefix — burn/alert families appear exactly
    # once, on the frontend /metrics
    surfaces.append(("utils.slo.burn", slo.render_burn_metrics()))

    # flight-recorder journal exposition (utils/events.py)
    from dynamo_tpu.utils.events import EventJournal

    ej = EventJournal()
    ej.emit("request.enqueued", request_id="r-check", prompt_tokens=16)
    ej.emit("request.finished", request_id="r-check", output_tokens=4)
    ej.pin("r-check", "ttft_over_budget")
    surfaces.append(("utils.events", ej.render_metrics()))
    hm = HealthMonitor("selfcheck")
    hm.set_state("ready", "self-check")
    hm.beat()
    surfaces.append(("utils.health", hm.render_metrics()))

    # goodput plane: per-request SLO outcomes -> windowed goodput families
    # (dynamo_goodput_*), incl. a missed request and a tenant breakdown
    from dynamo_tpu.utils.goodput import GoodputTracker, RequestOutcome

    gp = GoodputTracker(ttft_budget_s=0.5, itl_budget_s=0.05)
    gp.observe(RequestOutcome(
        "r1", scenario="bursty_chat", tenant="tenant-a", ttft_s=0.1,
        itl_s=(0.004, 0.006), output_tokens=16,
    ))
    gp.observe(RequestOutcome(
        "r2", scenario="bursty_chat", ttft_s=0.9, output_tokens=4,
    ))
    gp.observe(RequestOutcome("r3", scenario="lora_churn", error=True))
    surfaces.append(("utils.goodput", gp.render_metrics()))

    # multi-tenant QoS admission plane (utils/qos.py): budgets + classes ->
    # the dynamo_qos_requests_total / dynamo_qos_budget_fill families
    from dynamo_tpu.utils.qos import AdmissionController, QosPolicy

    qos = AdmissionController(QosPolicy.from_specs(
        "tenant-a=500,tenant-b=4000", "tenant-a=batch,tenant-b=critical",
    ))
    qos.admit("tenant-a", "batch", 120)
    qos.admit("tenant-b", "critical", 64)
    for _ in range(8):  # exhaust tenant-a's burst so a throttle renders
        qos.admit("tenant-a", "batch", 400)
    qos.record_shed("tenant-a", "batch")
    surfaces.append(("utils.qos", qos.render_metrics()))

    # planner rebalance executor (components/planner.py)
    from dynamo_tpu.components.planner import PlannerService

    class _PlannerDrt:
        cplane = None

    psvc = PlannerService(_PlannerDrt(), "ns")
    psvc.rebalance_executed = 2
    psvc.rebalance_execute_failures = 1
    surfaces.append(("components.planner", psvc.render_metrics()))

    # trace-replay harness: the dynamo_replay_* client-side families
    from dynamo_tpu.loadgen.replay import ReplayMetrics

    rm = ReplayMetrics()
    rm.submitted()
    rm.observe_lag(0.002)
    rm.finished("bursty_chat", 16, error=False)
    surfaces.append(("loadgen.replay", rm.render_metrics()))

    # engine stage histograms + resource gauges (scheduler built directly on
    # a real allocator; no model/runner/device needed)
    from dynamo_tpu.engine.config import EngineConfig
    from dynamo_tpu.engine.engine import AsyncJaxEngine
    from dynamo_tpu.engine.page_table import PageAllocator
    from dynamo_tpu.engine.scheduler import Scheduler

    # speculative = draft so the dynamo_spec_* and dynamo_spec_draft_*
    # families render (the draft runner itself is faked below — building a
    # real one would load a model, which the cluster-free gate must not do)
    cfg = EngineConfig(model_id="tiny", page_size=4, num_pages=8, max_seqs=2,
                       prefill_buckets=(16,), speculative="draft:tiny:2")
    eng = AsyncJaxEngine(cfg)
    eng.allocator = PageAllocator(cfg.num_pages, cfg.page_size)
    eng.scheduler = Scheduler(cfg, None, eng.allocator)
    for name in ("queue_wait", "ttft", "prefill", "decode_window", "reconcile"):
        eng.scheduler.stage_hist[name].observe(0.01)
    eng.scheduler.stage.prefill_s = 0.5
    eng.scheduler.stage.spec_proposed = 8
    eng.scheduler.stage.spec_accepted = 6
    eng.scheduler.stage.spec_draft_calls = 2
    eng.scheduler.stage.spec_draft_s = 0.01
    # live migration: both roles' counters + a sample pause so the
    # dynamo_migration_* families render on the conformance surface
    eng.scheduler.migration_out = 2
    eng.scheduler.migration_in = 1
    eng.scheduler.migration_in_pulled = 1
    eng.scheduler.migration_tokens_salvaged = 24
    eng.migration_pause_hist.observe(0.04)
    # multi-tenant QoS: per-class victims so dynamo_qos_preemptions_total
    # renders class-labeled samples on the engine surface
    eng.scheduler.qos_preempted = {"batch": 3, "standard": 1}
    eng.scheduler.qos_sheds = 2
    eng.scheduler.qos_shed_migrations = 1

    class _DraftPool:
        pages_total, pages_used = 7, 3

    class _LoraStore:  # shape resource_snapshot actually reads
        def metrics_snapshot(self):
            return {
                "resident": 2, "capacity": 4, "evictions": 1, "loads": 3,
                "load_seconds": 0.42, "requests": {"a1": 5, "a2": 2},
                "hot": "a1",
            }

    class _CompileMonitor:  # shape resource_snapshot actually reads
        def snapshot(self):
            return {"compiles": 3, "compile_s": 0.82}

    class _SpecRunner:  # shape resource_snapshot actually reads
        draft = _DraftPool()
        lora_store = _LoraStore()
        model = None
        compile_monitor = _CompileMonitor()

        def hbm_stats(self):
            return {}

    eng.runner = _SpecRunner()

    class _Disk:  # shape resource_snapshot actually reads: puts the
        # dynamo_engine_disk_* families on the conformance surface
        spills, restores, drops, io_errors = 5, 3, 1, 1
        bytes_resident, budget_bytes = 16384, 65536
        restore_s = 0.012

        def __len__(self):
            return 4

    class _Offload:  # shape resource_snapshot actually reads: puts the
        # dynamo_engine_offload_* families on the conformance surface
        saves, loads, drops = 4, 2, 1
        capacity_blocks, block_bytes, bytes_resident = 64, 4096, 8192
        transfer_s = 0.003
        disk = _Disk()

        def __len__(self):
            return 2

    eng.offload = _Offload()
    # step-anatomy families (dynamo_step_* + dynamo_engine_roofline_fraction):
    # seed one priced decode window + a LoRA slot load so every family —
    # including the roofline gauge, which only renders once a floor-priced
    # dispatch completed — is on the conformance surface
    from dynamo_tpu.utils.step_anatomy import RooflineModel

    anat = eng.scheduler.anatomy
    anat.roofline = RooflineModel(
        param_bytes=2_600_000_000, page_bytes=4096, page_size=4,
        param_count=1_300_000_000,
    )
    rec = anat.begin("decode_window")
    anat.add_phase(rec, "host_prep", 0.0004)
    anat.add_phase(rec, "dispatch", 0.0021)
    anat.add_phase(rec, "device_wait", 0.0049)
    anat.add_phase(rec, "reconcile", 0.0003)
    anat.note_steps(rec, steps=4, tokens=8, participants=2,
                    floor_bytes=anat.decode_floor_bytes(64, 4))
    anat.record("lora_slot_load", dispatch_s=0.0031)
    # one priced prefill dispatch: dynamo_engine_prefill_roofline_fraction
    # renders only once note_prefill_floor has priced a packed call
    prec = anat.begin("prefill_packed")
    anat.add_phase(prec, "host_prep", 0.0006)
    anat.add_phase(prec, "dispatch", 0.0102)
    anat.note_steps(prec, tokens=256, participants=2)
    anat.note_prefill_floor(prec, 256)
    # cost-attribution families (dynamo_cost_* via utils/metering.py): the
    # engine's MeterLedger is their single emitting site, reached through
    # render_stage_metrics. Wire the anatomy's meter tap and drive one billed
    # dispatch plus each charge edge (KV residency, queue wait, token
    # charges) so every family renders labeled samples cluster-free
    anat.meter = eng.meter
    crec = anat.begin("decode_window", bill=[
        ("r-cost", "tenant-a", "a1", "critical", 4.0),
    ])
    anat.add_phase(crec, "dispatch", 0.002)
    anat.add_phase(crec, "device_wait", 0.005)
    eng.meter.kv_acquire("hbm", ("blk", 1), 4096, owner=("tenant-a", "r-cost"))
    eng.meter.kv_acquire("host", ("blk", 2), 4096, owner=("tenant-a", "r-cost"))
    eng.meter.queued("tenant-a", 0.01)
    eng.meter.charge_tokens("tenant-a", "admitted", 24)
    eng.meter.charge_tokens("tenant-a", "prompt", 16)
    eng.meter.charge_tokens("tenant-a", "output", 8)
    # the engine-scoped goodput families (dynamo_engine_goodput_*) need a
    # sample outcome to render their gauges
    eng.goodput.observe(RequestOutcome(
        "e1", scenario="bursty_chat", ttft_s=0.05, itl_s=(0.004,),
        output_tokens=8,
    ))
    surfaces.append(("engine.render_stage_metrics", eng.render_stage_metrics()))

    # disagg KV data-plane server/client + prefill worker send side
    from dynamo_tpu.disagg.dataplane import KvDataPlaneClient, KvDataPlaneServer
    from dynamo_tpu.disagg.prefill_worker import PrefillWorker

    surfaces.append(("disagg.dataplane.server", KvDataPlaneServer().render_metrics()))
    surfaces.append(("disagg.dataplane.client", KvDataPlaneClient(lanes=2).render_metrics()))

    # fleet prefix cache: pull server (export side) + fetch client (requester
    # wire side); the engine-side dynamo_prefix_fetch_* counters/histogram
    # ride the engine.render_stage_metrics surface above
    from dynamo_tpu.disagg.prefix_fetch import KvPullServer, PrefixFetchClient

    pull = KvPullServer(None)
    pull.served = 2
    pull.served_blocks["hbm"] = 8
    surfaces.append(("disagg.prefix_fetch.server", pull.render_metrics()))
    pf = PrefixFetchClient(None)
    pf.results["hit"] = 1
    pf.fetch_seconds.observe(0.02)
    surfaces.append(("disagg.prefix_fetch.client", pf.render_metrics()))

    class _Eng:
        config = None

    surfaces.append(("disagg.prefill_worker", PrefillWorker(_Eng(), None, "ns", "m").render_metrics()))

    # standalone metrics component: pool aggregates + federated per-worker
    # health/resource families, off an injected fleet view
    from dynamo_tpu.components.metrics import MetricsService
    from dynamo_tpu.llm.kv_router.metrics_aggregator import WorkerView
    from dynamo_tpu.llm.kv_router.scheduler import WorkerLoad

    class _Drt:
        cplane = None

    svc = MetricsService(_Drt(), "ns", "backend")
    kv = {
        "request_active_slots": 1, "request_total_slots": 8,
        "kv_active_blocks": 5, "kv_total_blocks": 100,
        "num_requests_waiting": 0, "gpu_cache_usage_perc": 0.05,
        "gpu_prefix_cache_hit_rate": 0.5,
    }
    svc.aggregator._workers[0xAB] = WorkerView(
        0xAB,
        data={
            "kv_metrics": kv,
            "health": {"state": "ready", "heartbeat_age_s": 0.01},
            "resources": {"kv_pages_used": 5, "kv_pages_total": 100,
                          "xla_compiles": 3, "hbm_bytes_in_use": 0},
            "stage_seconds": {"prefill_s": 1.0, "queue_wait_n": 2},
            # fleet per-class SLO aggregation source (one worker's
            # SloTracker.snapshot()["priorities"] shape)
            "slo": {"priorities": {"critical": {"itl": {
                "count": 4, "compliance": 0.75, "violations_total": 1,
            }}}},
        },
        load=WorkerLoad.from_wire(0xAB, kv),
        last_seen=_time.monotonic(),
    )
    svc._isl_blocks, svc._overlap_blocks = 10, 4
    # router radix-index health as relayed on the hit-rate subject: a tiny
    # bounded indexer driven past its cap so evictions/hits are nonzero
    from dynamo_tpu.llm.kv_events import KvCacheEvent, StoredBlock
    from dynamo_tpu.llm.kv_router.indexer import KvIndexer, RouterEvent

    idx = KvIndexer(kv_block_size=4, use_native=False, max_nodes=4, num_shards=2)
    for i in range(8):
        idx.apply_event(RouterEvent(
            worker_id=0xAB,
            event=KvCacheEvent.stored(None, [StoredBlock(1000 + i, 2000 + i)]),
        ))
    idx.find_matches([2007])
    idx.find_matches([1])  # a miss, so both result labels sample
    svc._router_radix = idx.radix_stats()
    surfaces.append(("components.metrics", svc.render()))
    return surfaces


def _declaration_problems(surfaces: list[tuple[str, str]]) -> list[str]:
    """Cross-validate DECLARED_METRIC_FAMILIES against the families actually
    RENDERED by the sample surfaces: exact set equality, both directions.
    This is the runtime half of the metric-conformance contract; the static
    half (literals at emitting sites vs the same tuple) is graftlint's
    metric-conformance detector."""
    rendered: set[str] = set()
    for _, text in surfaces:
        for line in text.splitlines():
            if line.startswith("# TYPE dynamo_"):
                rendered.add(line.split()[2])
    declared = set(DECLARED_METRIC_FAMILIES)
    problems = []
    for fam in sorted(rendered - declared):
        problems.append(
            f"rendered family {fam} is not in DECLARED_METRIC_FAMILIES"
        )
    for fam in sorted(declared - rendered):
        problems.append(
            f"declared family {fam} is rendered by no sample surface — "
            "seed it in _sample_surfaces or delete the declaration"
        )
    return problems


def self_check() -> list[str]:
    """check_exposition over every cluster-free sample surface, plus the
    declared-vs-rendered family cross-validation; returns the flattened
    problem list (empty = all conformant)."""
    problems: list[str] = []
    surfaces = _sample_surfaces()
    for name, text in surfaces:
        problems.extend(f"{name}: {p}" for p in check_exposition(text))
        if not text.strip():
            problems.append(f"{name}: rendered empty exposition")
    problems.extend(_declaration_problems(surfaces))
    return problems


def _main(argv=None) -> int:
    import argparse

    p = argparse.ArgumentParser(
        description="Prometheus exposition helpers; --check validates every "
                    "metrics surface without a cluster (the CI lint step)."
    )
    p.add_argument("--check", action="store_true")
    args = p.parse_args(argv)
    if not args.check:
        p.print_help()
        return 2
    problems = self_check()
    for prob in problems:
        print(f"FAIL {prob}")
    if problems:
        return 1
    print(
        f"ok: exposition surfaces conformant; "
        f"{len(DECLARED_METRIC_FAMILIES)} declared dynamo_* families match "
        "the rendered set"
    )
    return 0


if __name__ == "__main__":
    raise SystemExit(_main())
