"""SLO tracking: rolling-window latency percentiles against configured targets.

Serving SLOs for an LLM fleet are latency-shaped — TTFT (time to first
token), ITL (inter-token latency), and engine queue wait — and operators
reason about them as *objectives* ("p99 TTFT under 500 ms, 99% of the time"),
not raw histograms. ``SloTracker`` keeps a bounded rolling window of raw
observations per metric, computes percentiles on demand, and derives an
error-budget gauge: the fraction of the allowed violation quota still
unspent inside the window. Budget 1.0 = no violations; 0.0 = the objective
is exactly burned; negative = actively out of SLO.

Targets come from CLI flags (``--slo-ttft-ms`` / ``--slo-itl-ms``) or the
``DYNTPU_SLO_TTFT_MS`` / ``DYNTPU_SLO_ITL_MS`` / ``DYNTPU_SLO_QUEUE_WAIT_MS``
environment knobs; a metric without a target still tracks percentiles but
never violates.

Empty-window semantics: percentiles are ``None`` when the window holds no
samples (never a fake 0.0 p99 — that reads as a *great* latency), and the
rendered exposition simply omits the quantile samples (NaN-free).

Per-tenant and per-class breakdown: ``observe(metric, seconds, tenant="a",
priority="critical")`` feeds the aggregate series (every existing consumer
sees every sample) AND a tenant-keyed AND a priority-class-keyed series,
rendered with ``tenant=`` / ``priority=`` labels on the same families — the
views multi-tenant QoS scheduling and per-class dashboards consume.

Burn-rate alerting (the SRE multi-window rule): the *burn rate* of a window
is the fraction of its violation quota spent per unit of quota — observed
violation ratio divided by the allowed ratio ``1 - objective``. Burn 1.0
spends the budget exactly at the sustainable pace; burn 10 exhausts a
300-second budget in 30 seconds. An alert fires only when BOTH a short
window (fast detection) and the long window (burst de-noising) burn above
the threshold, and clears when either drops back — the standard two-window
trade of detection latency vs. flappiness. Rendered as
``dynamo_slo_burn_rate{metric,window}`` and ``dynamo_alert_state{alert}``;
PlannerService reads the same verdict (read-only) off worker stats.

Thread-safe: the HTTP asyncio thread and the engine loop both observe.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Optional

# canonical metric names (any name is accepted; these get env-knob defaults)
TTFT = "ttft"
ITL = "itl"
QUEUE_WAIT = "queue_wait"

_ENV_KNOBS = {
    TTFT: "DYNTPU_SLO_TTFT_MS",
    ITL: "DYNTPU_SLO_ITL_MS",
    QUEUE_WAIT: "DYNTPU_SLO_QUEUE_WAIT_MS",
}

PERCENTILES = (50, 90, 99)

#: two-window burn-rate rule: the long window is the tracker's full window,
#: the short window is this fraction of it (300 s -> 60 s)
BURN_SHORT_FRACTION = 0.2
#: both windows must burn above this to fire (DYNTPU_SLO_BURN_THRESHOLD
#: overrides; 2.0 = spending budget at twice the sustainable pace)
BURN_THRESHOLD = 2.0

BURN_THRESHOLD_ENV = "DYNTPU_SLO_BURN_THRESHOLD"


def targets_from_env(overrides: Optional[dict] = None) -> dict:
    """Metric -> target seconds, from env knobs overlaid with explicit
    ms-valued overrides (CLI flags; None values are ignored)."""
    targets: dict[str, float] = {}
    for metric, env in _ENV_KNOBS.items():
        raw = os.environ.get(env)
        if raw:
            try:
                targets[metric] = float(raw) / 1e3
            except ValueError:
                pass
    for metric, ms in (overrides or {}).items():
        if ms is not None:
            targets[metric] = float(ms) / 1e3
    return targets


def _percentile(sorted_vals: list, p: float) -> Optional[float]:
    """Nearest-rank percentile; None on an empty window (a single sample IS
    every percentile — no interpolation against a phantom neighbor)."""
    if not sorted_vals:
        return None
    k = max(0, min(len(sorted_vals) - 1, int(round(p / 100.0 * (len(sorted_vals) - 1)))))
    return sorted_vals[k]


class SloTracker:
    def __init__(
        self,
        targets: Optional[dict] = None,
        window_s: float = 300.0,
        objective: float = 0.99,
        max_samples: int = 4096,
        clock=time.monotonic,
        burn_threshold: Optional[float] = None,
    ):
        self.targets = dict(targets or {})  # metric -> target SECONDS
        self.window_s = window_s
        self.objective = objective
        self.max_samples = max_samples
        self._clock = clock
        self._lock = threading.Lock()
        # (metric, tenant, priority) -> deque[(ts, seconds)]. Tenant and
        # priority are breakdown DIMENSIONS, not a cross product: every
        # observation lands in the aggregate ("", "") series, plus at most
        # one tenant series and one priority-class series.
        self._samples: dict[tuple, deque] = {}
        # lifetime counters (survive window pruning), keyed like _samples
        self._observed: dict[tuple, int] = {}
        self._violated: dict[tuple, int] = {}
        if burn_threshold is None:
            raw = os.environ.get(BURN_THRESHOLD_ENV)
            try:
                burn_threshold = float(raw) if raw else BURN_THRESHOLD
            except ValueError:
                burn_threshold = BURN_THRESHOLD
        self.burn_threshold = burn_threshold

    # ---------------- ingest ----------------

    def observe(
        self, metric: str, seconds: float, tenant: str = "", priority: str = ""
    ) -> None:
        now = self._clock()
        keys = [(metric, "", "")]
        if tenant:
            keys.append((metric, tenant, ""))
        if priority:
            keys.append((metric, "", priority))
        with self._lock:
            target = self.targets.get(metric)
            for key in keys:
                q = self._samples.get(key)
                if q is None:
                    q = self._samples[key] = deque(maxlen=self.max_samples)
                q.append((now, seconds))
                self._observed[key] = self._observed.get(key, 0) + 1
                if target is not None and seconds > target:
                    self._violated[key] = self._violated.get(key, 0) + 1

    def _window(self, key: tuple, now: float) -> list:
        q = self._samples.get(key)
        if not q:
            return []
        cutoff = now - self.window_s
        while q and q[0][0] < cutoff:
            q.popleft()
        return [v for _, v in q]

    # ---------------- evaluation ----------------

    def metric_state(self, metric: str, tenant: str = "", priority: str = "") -> dict:
        """Window percentiles + target compliance + error budget for one
        metric (optionally one tenant's or one priority class's series). An
        empty window reports ``None`` percentiles — never a misleading 0.0 —
        and spends no budget."""
        now = self._clock()
        key = (metric, tenant, priority)
        with self._lock:
            vals = sorted(self._window(key, now))
            target = self.targets.get(metric)
            n = len(vals)
            state = {
                "count": n,
                "target_ms": round(target * 1e3, 3) if target is not None else None,
                "observed_total": self._observed.get(key, 0),
                "violations_total": self._violated.get(key, 0),
            }
            for p in PERCENTILES:
                v = _percentile(vals, p)
                state[f"p{p}_ms"] = round(v * 1e3, 3) if v is not None else None
            if target is None or n == 0:
                state["violations"] = 0
                state["compliance"] = 1.0
                state["error_budget"] = 1.0
                state["ok"] = True
                return state
            violations = sum(1 for v in vals if v > target)
            compliance = 1.0 - violations / n
            allowed = (1.0 - self.objective) * n
            # budget remaining: 1 with zero violations, 0 when the quota is
            # exactly spent, negative when the objective is blown
            budget = 1.0 - (violations / allowed if allowed > 0 else float(violations))
            state["violations"] = violations
            state["compliance"] = round(compliance, 5)
            state["error_budget"] = round(budget, 5)
            state["ok"] = budget > 0.0
            return state

    # ---------------- burn-rate alerting ----------------

    def _burn(self, key: tuple, target: float, horizon_s: float, now: float) -> float:
        """Burn rate over the trailing ``horizon_s``: violation ratio divided
        by the allowed ratio (1 - objective). Empty horizon burns nothing.
        Caller holds the lock."""
        q = self._samples.get(key)
        if not q:
            return 0.0
        cutoff = now - horizon_s
        vals = [v for ts, v in q if ts >= cutoff]
        if not vals:
            return 0.0
        ratio = sum(1 for v in vals if v > target) / len(vals)
        allowed = 1.0 - self.objective
        return ratio / allowed if allowed > 0 else float(ratio > 0)

    def burn_snapshot(self) -> dict:
        """Two-window burn per targeted metric plus the alert verdicts —
        the wire form worker stats broadcast and the planner reads."""
        now = self._clock()
        short_s = max(1.0, self.window_s * BURN_SHORT_FRACTION)
        with self._lock:
            metrics = {}
            for metric, target in sorted(self.targets.items()):
                key = (metric, "", "")
                self._window(key, now)  # prune so the long horizon is exact
                short = self._burn(key, target, short_s, now)
                long = self._burn(key, target, self.window_s, now)
                metrics[metric] = {
                    "short": round(short, 4),
                    "long": round(long, 4),
                    # two-window rule: short gives detection speed, long
                    # keeps a lone burst from paging
                    "alert": short >= self.burn_threshold
                    and long >= self.burn_threshold,
                }
        return {
            "threshold": self.burn_threshold,
            "short_window_s": short_s,
            "long_window_s": self.window_s,
            "metrics": metrics,
            "alerting": sorted(m for m, s in metrics.items() if s["alert"]),
        }

    def snapshot(self) -> dict:
        """Wire form: per-metric aggregate state + per-tenant and per-class
        breakdowns + burn verdicts + the overall ok (aggregate series only —
        one noisy tenant blows its own view, the fleet verdict stays the
        pooled objective)."""
        with self._lock:
            metrics = sorted(
                {m for m, t, p in self._samples if not t and not p}
                | set(self.targets)
            )
            tenant_keys = sorted((t, m) for m, t, p in self._samples if t)
            priority_keys = sorted((p, m) for m, t, p in self._samples if p)
        per = {m: self.metric_state(m) for m in metrics}
        out = {
            "objective": self.objective,
            "window_s": self.window_s,
            "metrics": per,
            "ok": all(s["ok"] for s in per.values()) if per else True,
        }
        if tenant_keys:
            tenants: dict[str, dict] = {}
            for tenant, metric in tenant_keys:
                tenants.setdefault(tenant, {})[metric] = self.metric_state(
                    metric, tenant
                )
            out["tenants"] = tenants
        if priority_keys:
            priorities: dict[str, dict] = {}
            for priority, metric in priority_keys:
                priorities.setdefault(priority, {})[metric] = self.metric_state(
                    metric, priority=priority
                )
            out["priorities"] = priorities
        if self.targets:
            out["burn"] = self.burn_snapshot()
        return out

    def ok(self) -> bool:
        return self.snapshot()["ok"]

    # ---------------- exposition ----------------

    def render_metrics(self, prefix: str = "dynamo_slo") -> str:
        from dynamo_tpu.utils.prometheus import render_family

        snap = self.snapshot()
        quantile_samples, target_samples, budget_samples, compliance_samples = [], [], [], []
        violation_samples = []
        series = [({}, m, s) for m, s in sorted(snap["metrics"].items())]
        for tenant, metrics in sorted(snap.get("tenants", {}).items()):
            series.extend(
                ({"tenant": tenant}, m, s) for m, s in sorted(metrics.items())
            )
        for priority, metrics in sorted(snap.get("priorities", {}).items()):
            series.extend(
                ({"priority": priority}, m, s) for m, s in sorted(metrics.items())
            )
        for base, metric, s in series:
            for p in PERCENTILES:
                # empty windows render NO quantile sample (None must never
                # reach the exposition as NaN or a fake 0.0)
                if s[f"p{p}_ms"] is not None:
                    quantile_samples.append((
                        {**base, "metric": metric, "quantile": f"0.{p}"},
                        s[f"p{p}_ms"] / 1e3,
                    ))
            if s["target_ms"] is not None:
                if not base:
                    target_samples.append(({"metric": metric}, s["target_ms"] / 1e3))
                budget_samples.append(({**base, "metric": metric}, s["error_budget"]))
                compliance_samples.append(({**base, "metric": metric}, s["compliance"]))
            violation_samples.append(({**base, "metric": metric}, s["violations_total"]))
        out = render_family(
            f"{prefix}_latency_seconds", "gauge",
            "rolling-window latency percentile per SLO metric (tenant-/"
            "priority-labeled series = one tenant's or class's breakdown)",
            quantile_samples,
        )
        if target_samples:
            out += render_family(
                f"{prefix}_target_seconds", "gauge",
                "configured SLO target per metric", target_samples,
            )
            out += render_family(
                f"{prefix}_error_budget_remaining", "gauge",
                "fraction of the allowed violation quota unspent in the window "
                "(negative = out of SLO)", budget_samples,
            )
            out += render_family(
                f"{prefix}_compliance_ratio", "gauge",
                "fraction of window samples meeting the target", compliance_samples,
            )
        out += render_family(
            f"{prefix}_violations_total", "counter",
            "lifetime observations exceeding their SLO target", violation_samples,
        )
        return out

    def render_burn_metrics(self, prefix: str = "dynamo_slo") -> str:
        """Burn-rate + alert-state exposition. A SEPARATE method from
        render_metrics on purpose: the engine re-renders the SLO families
        under its ``dynamo_engine_slo`` prefix (the colocated frontend owns
        the bare names), but burn alerts are a fleet-level verdict rendered
        exactly once — by the frontend /metrics and the conformance
        surface."""
        from dynamo_tpu.utils.prometheus import render_family

        burn = self.burn_snapshot()
        burn_samples, alert_samples = [], []
        for metric, s in sorted(burn["metrics"].items()):
            for window in ("short", "long"):
                burn_samples.append(({"metric": metric, "window": window}, s[window]))
            alert_samples.append(
                ({"alert": f"slo_burn_{metric}"}, 1 if s["alert"] else 0)
            )
        out = render_family(
            f"{prefix}_burn_rate", "gauge",
            "error-budget burn rate per SLO metric and window (1.0 = spending "
            "the violation quota exactly at the sustainable pace)",
            burn_samples or [({"metric": "ttft", "window": "short"}, 0.0)],
        )
        out += render_family(
            "dynamo_alert_state", "gauge",
            "multi-window burn-rate alert verdict (1 = firing: both windows "
            "burn above threshold; 0 = ok)",
            alert_samples or [({"alert": "slo_burn_ttft"}, 0)],
        )
        return out
