"""SLO tracking: rolling-window latency percentiles against configured targets.

Serving SLOs for an LLM fleet are latency-shaped — TTFT (time to first
token), ITL (inter-token latency), and engine queue wait — and operators
reason about them as *objectives* ("p99 TTFT under 500 ms, 99% of the time"),
not raw histograms. ``SloTracker`` keeps a bounded rolling window of raw
observations per metric, computes percentiles on demand, and derives an
error-budget gauge: the fraction of the allowed violation quota still
unspent inside the window. Budget 1.0 = no violations; 0.0 = the objective
is exactly burned; negative = actively out of SLO.

Targets come from CLI flags (``--slo-ttft-ms`` / ``--slo-itl-ms``) or the
``DYNTPU_SLO_TTFT_MS`` / ``DYNTPU_SLO_ITL_MS`` / ``DYNTPU_SLO_QUEUE_WAIT_MS``
environment knobs; a metric without a target still tracks percentiles but
never violates.

Empty-window semantics: percentiles are ``None`` when the window holds no
samples (never a fake 0.0 p99 — that reads as a *great* latency), and the
rendered exposition simply omits the quantile samples (NaN-free).

Per-tenant breakdown: ``observe(metric, seconds, tenant="a")`` feeds BOTH the
aggregate series (every existing consumer sees every sample) and a
tenant-keyed series rendered with a ``tenant=`` label on the same families —
the view multi-tenant QoS scheduling consumes.

Thread-safe: the HTTP asyncio thread and the engine loop both observe.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Optional

# canonical metric names (any name is accepted; these get env-knob defaults)
TTFT = "ttft"
ITL = "itl"
QUEUE_WAIT = "queue_wait"

_ENV_KNOBS = {
    TTFT: "DYNTPU_SLO_TTFT_MS",
    ITL: "DYNTPU_SLO_ITL_MS",
    QUEUE_WAIT: "DYNTPU_SLO_QUEUE_WAIT_MS",
}

PERCENTILES = (50, 90, 99)


def targets_from_env(overrides: Optional[dict] = None) -> dict:
    """Metric -> target seconds, from env knobs overlaid with explicit
    ms-valued overrides (CLI flags; None values are ignored)."""
    targets: dict[str, float] = {}
    for metric, env in _ENV_KNOBS.items():
        raw = os.environ.get(env)
        if raw:
            try:
                targets[metric] = float(raw) / 1e3
            except ValueError:
                pass
    for metric, ms in (overrides or {}).items():
        if ms is not None:
            targets[metric] = float(ms) / 1e3
    return targets


def _percentile(sorted_vals: list, p: float) -> Optional[float]:
    """Nearest-rank percentile; None on an empty window (a single sample IS
    every percentile — no interpolation against a phantom neighbor)."""
    if not sorted_vals:
        return None
    k = max(0, min(len(sorted_vals) - 1, int(round(p / 100.0 * (len(sorted_vals) - 1)))))
    return sorted_vals[k]


class SloTracker:
    def __init__(
        self,
        targets: Optional[dict] = None,
        window_s: float = 300.0,
        objective: float = 0.99,
        max_samples: int = 4096,
        clock=time.monotonic,
    ):
        self.targets = dict(targets or {})  # metric -> target SECONDS
        self.window_s = window_s
        self.objective = objective
        self.max_samples = max_samples
        self._clock = clock
        self._lock = threading.Lock()
        # (metric, tenant) -> deque[(ts, seconds)]; tenant "" = the aggregate
        # series every tenant observation ALSO lands in
        self._samples: dict[tuple, deque] = {}
        # lifetime counters (survive window pruning), keyed like _samples
        self._observed: dict[tuple, int] = {}
        self._violated: dict[tuple, int] = {}

    # ---------------- ingest ----------------

    def observe(self, metric: str, seconds: float, tenant: str = "") -> None:
        now = self._clock()
        keys = [(metric, "")]
        if tenant:
            keys.append((metric, tenant))
        with self._lock:
            target = self.targets.get(metric)
            for key in keys:
                q = self._samples.get(key)
                if q is None:
                    q = self._samples[key] = deque(maxlen=self.max_samples)
                q.append((now, seconds))
                self._observed[key] = self._observed.get(key, 0) + 1
                if target is not None and seconds > target:
                    self._violated[key] = self._violated.get(key, 0) + 1

    def _window(self, key: tuple, now: float) -> list:
        q = self._samples.get(key)
        if not q:
            return []
        cutoff = now - self.window_s
        while q and q[0][0] < cutoff:
            q.popleft()
        return [v for _, v in q]

    # ---------------- evaluation ----------------

    def metric_state(self, metric: str, tenant: str = "") -> dict:
        """Window percentiles + target compliance + error budget for one
        metric (optionally one tenant's series). An empty window reports
        ``None`` percentiles — never a misleading 0.0 — and spends no
        budget."""
        now = self._clock()
        key = (metric, tenant)
        with self._lock:
            vals = sorted(self._window(key, now))
            target = self.targets.get(metric)
            n = len(vals)
            state = {
                "count": n,
                "target_ms": round(target * 1e3, 3) if target is not None else None,
                "observed_total": self._observed.get(key, 0),
                "violations_total": self._violated.get(key, 0),
            }
            for p in PERCENTILES:
                v = _percentile(vals, p)
                state[f"p{p}_ms"] = round(v * 1e3, 3) if v is not None else None
            if target is None or n == 0:
                state["violations"] = 0
                state["compliance"] = 1.0
                state["error_budget"] = 1.0
                state["ok"] = True
                return state
            violations = sum(1 for v in vals if v > target)
            compliance = 1.0 - violations / n
            allowed = (1.0 - self.objective) * n
            # budget remaining: 1 with zero violations, 0 when the quota is
            # exactly spent, negative when the objective is blown
            budget = 1.0 - (violations / allowed if allowed > 0 else float(violations))
            state["violations"] = violations
            state["compliance"] = round(compliance, 5)
            state["error_budget"] = round(budget, 5)
            state["ok"] = budget > 0.0
            return state

    def snapshot(self) -> dict:
        """Wire form: per-metric aggregate state + per-tenant breakdown +
        the overall verdict (aggregate series only — one noisy tenant blows
        its own view, the fleet verdict stays the pooled objective)."""
        with self._lock:
            metrics = sorted(
                {m for m, t in self._samples if not t} | set(self.targets)
            )
            tenant_keys = sorted((t, m) for m, t in self._samples if t)
        per = {m: self.metric_state(m) for m in metrics}
        out = {
            "objective": self.objective,
            "window_s": self.window_s,
            "metrics": per,
            "ok": all(s["ok"] for s in per.values()) if per else True,
        }
        if tenant_keys:
            tenants: dict[str, dict] = {}
            for tenant, metric in tenant_keys:
                tenants.setdefault(tenant, {})[metric] = self.metric_state(
                    metric, tenant
                )
            out["tenants"] = tenants
        return out

    def ok(self) -> bool:
        return self.snapshot()["ok"]

    # ---------------- exposition ----------------

    def render_metrics(self, prefix: str = "dynamo_slo") -> str:
        from dynamo_tpu.utils.prometheus import render_family

        snap = self.snapshot()
        quantile_samples, target_samples, budget_samples, compliance_samples = [], [], [], []
        violation_samples = []
        series = [({}, m, s) for m, s in sorted(snap["metrics"].items())]
        for tenant, metrics in sorted(snap.get("tenants", {}).items()):
            series.extend(
                ({"tenant": tenant}, m, s) for m, s in sorted(metrics.items())
            )
        for base, metric, s in series:
            for p in PERCENTILES:
                # empty windows render NO quantile sample (None must never
                # reach the exposition as NaN or a fake 0.0)
                if s[f"p{p}_ms"] is not None:
                    quantile_samples.append((
                        {**base, "metric": metric, "quantile": f"0.{p}"},
                        s[f"p{p}_ms"] / 1e3,
                    ))
            if s["target_ms"] is not None:
                if not base:
                    target_samples.append(({"metric": metric}, s["target_ms"] / 1e3))
                budget_samples.append(({**base, "metric": metric}, s["error_budget"]))
                compliance_samples.append(({**base, "metric": metric}, s["compliance"]))
            violation_samples.append(({**base, "metric": metric}, s["violations_total"]))
        out = render_family(
            f"{prefix}_latency_seconds", "gauge",
            "rolling-window latency percentile per SLO metric "
            "(tenant-labeled series = one tenant's breakdown)",
            quantile_samples,
        )
        if target_samples:
            out += render_family(
                f"{prefix}_target_seconds", "gauge",
                "configured SLO target per metric", target_samples,
            )
            out += render_family(
                f"{prefix}_error_budget_remaining", "gauge",
                "fraction of the allowed violation quota unspent in the window "
                "(negative = out of SLO)", budget_samples,
            )
            out += render_family(
                f"{prefix}_compliance_ratio", "gauge",
                "fraction of window samples meeting the target", compliance_samples,
            )
        out += render_family(
            f"{prefix}_violations_total", "counter",
            "lifetime observations exceeding their SLO target", violation_samples,
        )
        return out
