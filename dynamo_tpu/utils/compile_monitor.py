"""Monitored jit: count XLA compiles and cumulative compile seconds.

Recompile storms are the top TPU serving hazard: a stray dynamic shape (an
unbucketed prompt length, a new sampling-feature combination mid-traffic)
silently turns ms-scale steps into multi-second XLA compiles, and nothing in
the serving metrics distinguishes that from device slowness. ``monitored_jit``
wraps a ``jax.jit``-ed callable and charges any call that grew the function's
executable cache to a shared ``CompileMonitor`` — count, cumulative seconds,
and the last compile's label/age land in the engine's resource gauges, so a
storm shows up as a climbing ``dynamo_engine_xla_compiles_total`` instead of
an unexplained latency cliff.

Detection uses the jitted function's ``_cache_size()`` (present on every jax
version this repo supports): a call that returns with a bigger cache compiled.
The attributed seconds include trace time — exactly the stall a request
experienced. Wrappers are transparent for plain calls; attribute access
forwards to the wrapped function.
"""

from __future__ import annotations

import threading
import time
from typing import Optional

from dynamo_tpu.utils.logging import get_logger

log = get_logger("utils.compile_monitor")


class CompileMonitor:
    """Shared compile telemetry for one process's jitted functions."""

    def __init__(self, clock=time.monotonic):
        self._clock = clock
        self._lock = threading.Lock()
        self.compiles = 0
        self.compile_s = 0.0
        self.last_label: Optional[str] = None
        self.last_ts: Optional[float] = None
        self.per_label: dict[str, int] = {}

    def record(self, label: str, seconds: float, count: int = 1) -> None:
        with self._lock:
            self.compiles += count
            self.compile_s += seconds
            self.last_label = label
            self.last_ts = self._clock()
            self.per_label[label] = self.per_label.get(label, 0) + count
        if seconds > 1.0:
            # a slow compile mid-serving is worth a log line even without
            # Prometheus scraping: it is the stall the caller just felt
            log.info("xla compile: %s took %.2fs (%d total)", label, seconds, self.compiles)

    def snapshot(self) -> dict:
        with self._lock:
            age = (
                round(self._clock() - self.last_ts, 3)
                if self.last_ts is not None
                else None
            )
            return {
                "compiles": self.compiles,
                "compile_s": round(self.compile_s, 4),
                "last_label": self.last_label,
                "last_compile_age_s": age,
                "per_label": dict(self.per_label),
            }


class _MonitoredJit:
    """Callable proxy over a jitted function; detects cache growth per call."""

    __slots__ = ("_fn", "_label", "_monitor", "_clock")

    def __init__(self, fn, label: str, monitor: CompileMonitor, clock=time.monotonic):
        self._fn = fn
        self._label = label
        self._monitor = monitor
        self._clock = clock

    def _cache_size(self) -> Optional[int]:
        probe = getattr(self._fn, "_cache_size", None)
        if probe is None:
            return None
        try:
            return probe()
        except Exception:
            return None

    def __call__(self, *args, **kwargs):
        before = self._cache_size()
        t0 = self._clock()
        result = self._fn(*args, **kwargs)
        if before is not None:
            after = self._cache_size()
            if after is not None and after > before:
                self._monitor.record(self._label, self._clock() - t0, after - before)
        return result

    def __getattr__(self, name):
        return getattr(self._fn, name)


def monitored_jit(fn, label: str, monitor: Optional[CompileMonitor]):
    """Wrap an already-jitted callable; ``monitor=None`` is a passthrough."""
    if monitor is None:
        return fn
    return _MonitoredJit(fn, label, monitor)
