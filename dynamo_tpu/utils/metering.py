"""Cost attribution plane: per-tenant device-time and KV-residency metering.

The repo measures what tenants *experience* (goodput/SLO windows, PR 11) and
what they're *allowed* (QoS token buckets, PR 15), but until this module
nothing measured what they actually *consume*: device-seconds and KV
byte-seconds were only accounted globally (step anatomy; the
``dynamo_engine_kv_pages`` gauge). The :class:`MeterLedger` closes that gap
with two attributed planes that are **conservation-checked** against the
global instruments they shadow — in the step-anatomy tradition, the planes
can never disagree:

**Device-time plane.** Every engine dispatch already lands its four phases
(host_prep/dispatch/device_wait/reconcile) on a ``StepRecord`` through
``StepAnatomy.add_phase``. Each record now carries a *bill*: the list of
``(request_id, tenant, adapter, priority, weight)`` rows participating in the
dispatch, weighted by the token rows each contributes (decode steps per lane,
prompt rows per packed-prefill chunk, draft+1 rows per spec-verify lane).
``add_phase`` forwards every clamped phase delta here and the ledger splits
it across the bill proportionally, so by construction

    sum over (tenant, adapter, priority, kind) of device_seconds
      == sum over (phase, kind) of StepAnatomy.phase_seconds

to float round-off. Dispatches no request caused (offload drains, LoRA slot
loads) bill the empty *system* key — attributed time is partitioned, never
invented or dropped.

**KV-residency plane.** Byte-seconds of residency per tier (hbm/host/disk),
integrated lazily on the exact allocate/free/demote/restore edges the
``PageAllocator`` / ``HostKvPool`` / ``DiskKvStore`` ladder already executes.
Ownership model: a resident block is owned by the ``(tenant, request_id)``
that first made its bytes resident. Prefix-cache hits (refcount bumps,
host/disk membership hits) never re-own; a freed-but-cached reusable page
keeps charging its creator — residency *is* the benefit the prefix cache
sells, so its cost stays attributed. Demotions (hbm -> host -> disk) carry
the owner down the ladder; promotions re-own to the restoring request (its
prompt is why the bytes came back up). A global per-tier occupancy integral
is maintained on the *same* edges with the *same* timestamps, so

    sum over tenants of kv_byte_seconds[tier] == occupancy integral[tier]

exactly (shared piecewise-constant integration grid; tests and the bench
``metering`` section assert both identities).

**Queue/token plane.** Queued-seconds per tenant at admission, plus
admitted-vs-consumed token counters against the QoS bucket charge
(``admitted`` = the prompt+budget tokens the bucket was debited;
``prompt``/``output`` = what the engine actually computed), so the
admission-estimate-vs-realized-cost gap (the VTC fairness critique) is a
standing measurement instead of a hope.

Surfaces: ``render_metrics`` emits the five ``dynamo_cost_*`` families on
the engine's conformance surface; ``snapshot()`` rides resource_snapshot ->
worker stats -> ``/cluster/costs`` and the dynotop COST column;
``request_cost()`` backs the cost footer on ``/debug/requests/{id}`` from a
bounded LRU of per-request footers. ``PlannerService`` consumes the merged
per-tenant burn as the ROADMAP-item-1 demand signal.

Zero-cost when off: ``EngineConfig.metering=False`` wires no ledger anywhere
and every hook site is a ``meter is not None`` check.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from typing import Optional

#: KV residency tiers, top to bottom of the offload ladder
TIERS = ("hbm", "host", "disk")

#: charge kinds on dynamo_cost_tokens_total{kind=}: admitted = the QoS bucket
#: debit at admission; prompt/output = tokens the engine actually computed
TOKEN_KINDS = ("admitted", "prompt", "output")

#: (tenant, adapter, priority) for engine work no request caused — offload
#: drains, LoRA slot loads, untracked reconciles. Empty labels render as
#: tenant="" and keep the device-time partition exhaustive.
SYSTEM_KEY = ("", "", "")

#: per-request cost footers retained for /debug/requests/{id} (LRU bound —
#: footers are forensics, not accounting; the ledger totals never evict)
DEFAULT_FOOTERS = 256


class MeterLedger:
    """Per-(tenant, adapter, priority-class) cost accumulators.

    Thread-safe: the engine thread writes on every dispatch phase and KV
    edge; snapshot/render/request_cost run on the asyncio and scrape
    threads. The write path is a handful of dict float-adds under one lock —
    the bench ``metering`` section prices it at <1% of a decode step wall.
    The clock is injectable so conservation tests can drive a fake timeline.
    """

    def __init__(self, clock=None, footer_capacity: int = DEFAULT_FOOTERS):
        self._lock = threading.Lock()
        self._clock = clock or time.monotonic
        # ---- device-time plane: (tenant, adapter, priority, kind) -> s
        self.device_seconds: dict[tuple, float] = {}
        # ---- KV plane, per tier:
        #   _kv_blocks: key -> (nbytes, owner)   owner = (tenant, request_id)
        #   _kv_tenant: tenant -> [resident_bytes, last_ts, byte_seconds]
        #   _kv_global: [resident_bytes, last_ts, byte_seconds]
        self._kv_blocks: dict[str, dict] = {t: {} for t in TIERS}
        self._kv_tenant: dict[str, dict] = {t: {} for t in TIERS}
        self._kv_global: dict[str, list] = {t: [0, None, 0.0] for t in TIERS}
        # ---- queue/token plane
        self.queued_seconds: dict[str, float] = {}
        self.token_counts: dict[tuple, int] = {}  # (tenant, kind) -> tokens
        # ---- per-request footers (LRU): rid -> footer dict
        self._footers: OrderedDict[str, dict] = OrderedDict()
        self._footer_cap = footer_capacity

    # ---------------- device-time plane (engine thread) ----------------

    def on_phase(self, rec, phase: str, dt: float) -> None:
        """Attribute one phase delta across the record's bill. Called by
        ``StepAnatomy.add_phase`` with the same clamped ``dt`` it adds to its
        own (phase, kind) counters — the two planes share every sample, which
        is what makes the conservation identity exact."""
        if dt <= 0:
            return
        kind = rec.kind if rec is not None else "decode_window"
        bill = getattr(rec, "bill", None) if rec is not None else None
        device = self.device_seconds
        with self._lock:
            if not bill:
                key = SYSTEM_KEY + (kind,)
                device[key] = device.get(key, 0.0) + dt
                return
            total_w = 0.0
            for row in bill:
                total_w += row[4]
            if total_w <= 0:
                total_w = float(len(bill))
            scale = dt / total_w
            footers = self._footers
            for rid, tenant, adapter, priority, weight in bill:
                share = scale * (weight if weight > 0 else 1.0)
                key = (tenant or "", adapter or "", priority or "", kind)
                device[key] = device.get(key, 0.0) + share
                if rid:
                    # hot path: no LRU bump per phase — footer recency rides
                    # creation and the (rarer) KV edges; the bench prices
                    # this loop against the decode step wall (<1% contract)
                    ent = footers.get(rid)
                    if ent is None:
                        ent = self._footer(rid, tenant, adapter, priority)
                    elif adapter and not ent["adapter"]:
                        ent["adapter"] = str(adapter)
                        if priority and not ent["priority"]:
                            ent["priority"] = str(priority)
                    d = ent["device_s"]
                    d[kind] = d.get(kind, 0.0) + share

    # ---------------- KV-residency plane (engine thread) ----------------

    def _settle(self, entry: list, now: float) -> None:
        """Lazy piecewise-constant integration step: fold the time since the
        last edge at the current resident level, then advance the mark."""
        if entry[1] is not None and now > entry[1]:
            entry[2] += entry[0] * (now - entry[1])
        entry[1] = now

    def kv_acquire(self, tier: str, key, nbytes: int, owner) -> None:
        """Bytes became resident in ``tier`` under ``owner`` = (tenant,
        request_id). Idempotent: re-acquiring a resident key is a no-op (the
        original owner keeps paying — cache hits never re-own)."""
        if nbytes <= 0:
            return
        if owner:
            tenant = str(owner[0] or "")
            rid = str(owner[1] or "")
        else:
            tenant = rid = ""
        with self._lock:
            blocks = self._kv_blocks[tier]
            if key in blocks:
                return
            now = self._clock()
            blocks[key] = (int(nbytes), (tenant, rid))
            g = self._kv_global[tier]
            self._settle(g, now)
            g[0] += nbytes
            t = self._kv_tenant[tier].get(tenant)
            if t is None:
                t = self._kv_tenant[tier][tenant] = [0, now, 0.0]
            self._settle(t, now)
            t[0] += nbytes
            if rid:
                # hot path: no LRU bump per page — footer recency rides
                # creation; this edge is priced by the bench <1% contract
                ent = self._footers.get(rid)
                if ent is None:
                    ent = self._footer(rid, tenant, None, None)
                res = ent["kv_resident"].get(tier, 0) + nbytes
                ent["kv_resident"][tier] = res
                if res > ent["kv_peak"].get(tier, 0):
                    ent["kv_peak"][tier] = res

    def kv_release(self, tier: str, key):
        """Bytes left ``tier``. Returns the owner tuple so demotion sites can
        carry it down the ladder; safe no-op (returns None) for keys this
        ledger never saw (metering attached mid-flight)."""
        with self._lock:
            rec = self._kv_blocks[tier].pop(key, None)
            if rec is None:
                return None
            nbytes, owner = rec
            now = self._clock()
            g = self._kv_global[tier]
            self._settle(g, now)
            g[0] -= nbytes
            t = self._kv_tenant[tier].get(owner[0])
            if t is not None:
                self._settle(t, now)
                t[0] = max(0, t[0] - nbytes)
            ent = self._footers.get(owner[1])
            if ent is not None:
                ent["kv_resident"][tier] = max(
                    0, ent["kv_resident"].get(tier, 0) - nbytes
                )
            return owner

    def kv_resident_bytes(self, tier: str) -> int:
        """Current global resident bytes the ledger believes ``tier`` holds —
        tests pin this against the pool's own occupancy truth."""
        with self._lock:
            return self._kv_global[tier][0]

    # ---------------- queue/token plane (engine thread) ----------------

    def queued(self, tenant, seconds: float) -> None:
        if seconds <= 0:
            return
        key = str(tenant or "")
        with self._lock:
            self.queued_seconds[key] = (
                self.queued_seconds.get(key, 0.0) + seconds
            )

    def charge_tokens(self, tenant, kind: str, n: int) -> None:
        if n <= 0:
            return
        key = (str(tenant or ""), kind)
        with self._lock:
            self.token_counts[key] = self.token_counts.get(key, 0) + int(n)

    # ---------------- per-request footers ----------------

    def _footer(self, rid: str, tenant, adapter, priority) -> dict:
        """Get-or-create the LRU footer for ``rid`` (lock held by caller)."""
        ent = self._footers.get(rid)
        if ent is None:
            ent = {
                "tenant": str(tenant or ""),
                "adapter": str(adapter or ""),
                "priority": str(priority or ""),
                "device_s": {},
                "kv_resident": {},
                "kv_peak": {},
            }
            self._footers[rid] = ent
            while len(self._footers) > self._footer_cap:
                self._footers.popitem(last=False)
        else:
            self._footers.move_to_end(rid)
            if adapter and not ent["adapter"]:
                ent["adapter"] = str(adapter)
            if priority and not ent["priority"]:
                ent["priority"] = str(priority)
        return ent

    def request_cost(self, rid: str) -> Optional[dict]:
        """JSON-safe cost footer for one request — the /debug/requests/{id}
        payload. None once the LRU evicted it (footers are forensics)."""
        with self._lock:
            ent = self._footers.get(rid)
            if ent is None:
                return None
            device_ms = {
                k: round(s * 1e3, 4) for k, s in sorted(ent["device_s"].items())
            }
            return {
                "request_id": rid,
                "tenant": ent["tenant"],
                "adapter": ent["adapter"],
                "priority": ent["priority"],
                "device_ms": device_ms,
                "device_ms_total": round(
                    sum(ent["device_s"].values()) * 1e3, 4
                ),
                "kv_peak_bytes": {
                    t: int(v) for t, v in sorted(ent["kv_peak"].items()) if v
                },
            }

    # ---------------- conservation (tests + bench) ----------------

    def device_seconds_total(self) -> float:
        with self._lock:
            return sum(self.device_seconds.values())

    def kv_byte_seconds(self, tier: str, now: Optional[float] = None) -> dict:
        """Settle ``tier`` to ``now`` and return both sides of the identity:
        per-tenant byte-seconds and the global occupancy integral."""
        with self._lock:
            if now is None:
                now = self._clock()
            g = self._kv_global[tier]
            self._settle(g, now)
            tenants = {}
            for tenant, t in self._kv_tenant[tier].items():
                self._settle(t, now)
                tenants[tenant] = t[2]
            return {
                "tenants": tenants,
                "global": g[2],
                "resident_bytes": g[0],
            }

    def conservation(self, anatomy=None, now: Optional[float] = None) -> dict:
        """Both identities in one report (the bench ``metering`` section's
        payload): attributed device-seconds vs the step-anatomy wall totals,
        and per-tier summed byte-seconds vs the occupancy integrals."""
        out: dict = {}
        if anatomy is not None:
            with anatomy._lock:
                wall = sum(anatomy.phase_seconds.values())
            mine = self.device_seconds_total()
            out["device"] = {
                "meter_s": mine,
                "anatomy_s": wall,
                "abs_err_s": abs(mine - wall),
                "rel_err": abs(mine - wall) / wall if wall > 0 else 0.0,
            }
        kv = {}
        for tier in TIERS:
            side = self.kv_byte_seconds(tier, now=now)
            total = sum(side["tenants"].values())
            glob = side["global"]
            kv[tier] = {
                "tenant_sum_byte_s": total,
                "global_byte_s": glob,
                "abs_err_byte_s": abs(total - glob),
                "rel_err": abs(total - glob) / glob if glob > 0 else 0.0,
            }
        out["kv"] = kv
        return out

    # ---------------- derived views (any thread) ----------------

    def snapshot(self) -> dict:
        """Wire-safe rollup for resource_snapshot -> worker stats ->
        /cluster/costs and the dynotop COST column: per-tenant device-seconds
        by kind, per-tier byte-seconds and residency, queue and token
        charges, plus a (tenant|adapter) join table for the goodput plane."""
        now = self._clock()
        with self._lock:
            device = dict(self.device_seconds)
            queued = dict(self.queued_seconds)
            tokens = dict(self.token_counts)
            kv_t: dict[str, dict] = {}
            kv_g: dict[str, dict] = {}
            for tier in TIERS:
                g = self._kv_global[tier]
                self._settle(g, now)
                kv_g[tier] = {
                    "resident_bytes": g[0],
                    "byte_s": round(g[2], 6),
                }
                for tenant, t in self._kv_tenant[tier].items():
                    self._settle(t, now)
                    row = kv_t.setdefault(
                        tenant, {"byte_s": {}, "resident_bytes": {}}
                    )
                    row["byte_s"][tier] = round(t[2], 6)
                    row["resident_bytes"][tier] = t[0]
        tenants: dict[str, dict] = {}

        def _trow(tenant: str) -> dict:
            return tenants.setdefault(tenant, {
                "device_s": 0.0, "by_kind": {}, "kv_byte_s": {},
                "kv_resident_bytes": {}, "queued_s": 0.0, "tokens": {},
            })

        adapters: dict[str, float] = {}
        for (tenant, adapter, _priority, kind), s in device.items():
            row = _trow(tenant)
            row["device_s"] = round(row["device_s"] + s, 6)
            row["by_kind"][kind] = round(row["by_kind"].get(kind, 0.0) + s, 6)
            jk = f"{tenant}|{adapter}"
            adapters[jk] = round(adapters.get(jk, 0.0) + s, 6)
        for tenant, kv_row in kv_t.items():
            row = _trow(tenant)
            row["kv_byte_s"] = kv_row["byte_s"]
            row["kv_resident_bytes"] = kv_row["resident_bytes"]
        for tenant, s in queued.items():
            _trow(tenant)["queued_s"] = round(s, 6)
        for (tenant, kind), n in tokens.items():
            _trow(tenant)["tokens"][kind] = n
        total = sum(v for v in (r["device_s"] for r in tenants.values()))
        top = ""
        top_s = -1.0
        for tenant, row in tenants.items():
            if tenant and row["device_s"] > top_s:
                top, top_s = tenant, row["device_s"]
        return {
            "tenants": tenants,
            "adapters": adapters,
            "tiers": kv_g,
            "device_s_total": round(total, 6),
            "top_tenant": top,
            "footers": len(self._footers),
        }

    def render_metrics(self) -> str:
        """The five dynamo_cost_* families for the engine's conformance
        exposition surface (the single emitting site graftlint pins)."""
        from dynamo_tpu.utils.prometheus import render_family

        now = self._clock()
        with self._lock:
            device = sorted(self.device_seconds.items())
            queued = sorted(self.queued_seconds.items())
            tokens = sorted(self.token_counts.items())
            byte_s: list = []
            resident: list = []
            for tier in TIERS:
                for tenant in sorted(self._kv_tenant[tier]):
                    t = self._kv_tenant[tier][tenant]
                    self._settle(t, now)
                    byte_s.append(
                        ({"tenant": tenant, "tier": tier}, round(t[2], 6))
                    )
                    resident.append(
                        ({"tenant": tenant, "tier": tier}, t[0])
                    )
        parts = [
            render_family(
                "dynamo_cost_device_seconds_total", "counter",
                "attributed engine device-time per tenant/adapter/priority "
                "and dispatch kind (sums to the step-anatomy wall totals by "
                "construction; empty tenant = unattributed system work)",
                [({"tenant": t, "adapter": a, "priority": p, "kind": k},
                  round(s, 6))
                 for (t, a, p, k), s in device]
                or [({"tenant": "", "adapter": "", "priority": "",
                      "kind": "decode_window"}, 0)],
            ),
            render_family(
                "dynamo_cost_kv_byte_seconds_total", "counter",
                "KV residency integral per tenant and tier (byte-seconds; "
                "sums to the tier occupancy integral by construction)",
                byte_s or [({"tenant": "", "tier": "hbm"}, 0)],
            ),
            render_family(
                "dynamo_cost_kv_resident_bytes", "gauge",
                "KV bytes currently resident per owning tenant and tier",
                resident or [({"tenant": "", "tier": "hbm"}, 0)],
            ),
            render_family(
                "dynamo_cost_queued_seconds_total", "counter",
                "seconds requests spent queued before admission, per tenant",
                [({"tenant": t}, round(s, 6)) for t, s in queued]
                or [({"tenant": ""}, 0)],
            ),
            render_family(
                "dynamo_cost_tokens_total", "counter",
                "token charges per tenant: admitted = the QoS bucket debit "
                "at admission; prompt/output = tokens the engine computed "
                "(the admitted-vs-consumed gap is the fairness residual)",
                [({"tenant": t, "kind": k}, n) for (t, k), n in tokens]
                or [({"tenant": "", "kind": "admitted"}, 0)],
            ),
        ]
        return "".join(parts)
