"""Persistent XLA compilation cache bootstrap for engine processes.

An engine restart otherwise re-pays every executable's compile (~25 s per
executable on remote-compile platforms); with the cache, executables
deserialize from disk. One shared helper so every long-lived engine
entrypoint (run CLI, worker, prefill worker) behaves the same.
"""

from __future__ import annotations

import os

from dynamo_tpu.utils.logging import get_logger

log = get_logger("utils.xla_cache")


def enable_compilation_cache() -> None:
    """Point JAX at a persistent compilation cache directory.

    ``JAX_COMPILATION_CACHE_DIR`` overrides the default (set it empty to
    disable). The default is per-user: a fixed path in shared /tmp would be
    unwritable for the second user on a host — and poisonable by the first.
    """
    default = os.path.join(
        os.environ.get("XDG_CACHE_HOME", os.path.expanduser("~/.cache")),
        "dynamo_tpu", "xla_cache",
    )
    path = os.environ.get("JAX_COMPILATION_CACHE_DIR", default)
    if not path:
        return
    try:
        import jax

        jax.config.update("jax_compilation_cache_dir", path)
    except Exception:
        log.warning(
            "persistent compilation cache unavailable (path %s); engine "
            "restarts will recompile every executable", path, exc_info=True,
        )
