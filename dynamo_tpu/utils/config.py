"""Layered configuration: dataclass defaults <- file <- env vars.

Mirrors the reference's figment-layered config (reference: lib/runtime/src/config.rs:26-170):
defaults are overridden by an optional TOML/YAML/JSON file, which is overridden by
``DYNTPU_<SECTION>_<KEY>`` environment variables.
"""

from __future__ import annotations

import dataclasses
import json
import os
from pathlib import Path
from typing import Any, Type, TypeVar

T = TypeVar("T")


def _load_file(path: str | Path) -> dict[str, Any]:
    path = Path(path)
    text = path.read_text()
    if path.suffix in (".yaml", ".yml"):
        import yaml

        return yaml.safe_load(text) or {}
    if path.suffix == ".toml":
        import tomllib

        return tomllib.loads(text)
    return json.loads(text)


_BUILTIN_TYPES = {"bool": bool, "int": int, "float": float, "str": str, "list": list, "dict": dict}


def _resolve_type(annotation: Any) -> Any:
    """Map a dataclass field annotation (possibly a string under PEP 563, possibly
    Optional[...]/list[...]) to the concrete type env values should coerce to."""
    if isinstance(annotation, type):
        return annotation
    name = str(annotation)
    # Strip Optional wrappers: "int | None", "Optional[int]", "typing.Optional[int]"
    name = name.replace("typing.", "").replace("Optional[", "").rstrip("]")
    name = name.replace("| None", "").replace("None |", "").strip()
    base = name.split("[", 1)[0].strip()
    return _BUILTIN_TYPES.get(base, str)


def _coerce(value: str, typ: Any) -> Any:
    if typ is bool:
        return value.lower() in ("1", "true", "yes", "on")
    if typ is int:
        return int(value)
    if typ is float:
        return float(value)
    if typ in (list, dict) or str(typ).startswith(("list", "dict", "typing.")):
        return json.loads(value)
    return value


def from_settings(
    cls: Type[T],
    *,
    env_prefix: str,
    config_path: str | Path | None = None,
    overrides: dict[str, Any] | None = None,
) -> T:
    """Build a dataclass config with file + env layering.

    Env var name for field ``foo_bar`` with prefix ``DYNTPU_RUNTIME`` is
    ``DYNTPU_RUNTIME_FOO_BAR``.
    """
    assert dataclasses.is_dataclass(cls)
    values: dict[str, Any] = {}
    file_path = config_path or os.environ.get(f"{env_prefix}_CONFIG")
    if file_path and Path(file_path).exists():
        values.update(_load_file(file_path))
    if overrides:
        values.update(overrides)

    fields = {f.name: f for f in dataclasses.fields(cls)}
    kwargs: dict[str, Any] = {}
    for name, field in fields.items():
        env_key = f"{env_prefix}_{name.upper()}"
        if env_key in os.environ:
            kwargs[name] = _coerce(os.environ[env_key], _resolve_type(field.type))
        elif name in values:
            kwargs[name] = values[name]
    return cls(**kwargs)
