"""Async object pool with return-handles.

Semantics mirror the reference's pool utility (reference: lib/runtime/src/utils/pool.rs:28-427),
the basis of KV block reuse: acquiring yields a ``PoolItem`` guard; dropping/releasing the
guard returns the value to the pool rather than destroying it.
"""

from __future__ import annotations

import asyncio
from collections import deque
from typing import Callable, Generic, Optional, TypeVar

V = TypeVar("V")


class PoolItem(Generic[V]):
    """Guard over a pooled value; release() (or async context exit) returns it."""

    def __init__(self, pool: "Pool[V]", value: V):
        self._pool: Optional[Pool[V]] = pool
        self.value = value

    def release(self) -> None:
        if self._pool is not None:
            pool, self._pool = self._pool, None
            pool._return(self.value)

    def take(self) -> V:
        """Detach the value from the pool permanently."""
        self._pool = None
        return self.value

    async def __aenter__(self) -> V:
        return self.value

    async def __aexit__(self, *exc) -> None:
        self.release()

    def __del__(self):  # safety net mirroring Drop-returns semantics
        if self._pool is not None:
            try:
                self.release()
            except Exception:
                pass


class Pool(Generic[V]):
    """FIFO pool of reusable values with an optional factory for lazy growth."""

    def __init__(
        self,
        initial: list[V] | None = None,
        *,
        factory: Callable[[], V] | None = None,
        capacity: int | None = None,
    ):
        self._items: deque[V] = deque(initial or [])
        self._factory = factory
        self._created = len(self._items)
        self._capacity = capacity
        self._waiters: deque[asyncio.Future] = deque()

    def __len__(self) -> int:
        return len(self._items)

    def try_acquire(self) -> Optional[PoolItem[V]]:
        if self._items:
            return PoolItem(self, self._items.popleft())
        if self._factory and (self._capacity is None or self._created < self._capacity):
            self._created += 1
            return PoolItem(self, self._factory())
        return None

    async def acquire(self) -> PoolItem[V]:
        item = self.try_acquire()
        if item is not None:
            return item
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._waiters.append(fut)
        try:
            value = await fut
        except asyncio.CancelledError:
            if fut.done() and not fut.cancelled():
                # _return already handed us the value; put it back for others
                self._return(fut.result())
            else:
                try:
                    self._waiters.remove(fut)
                except ValueError:
                    pass
            raise
        return PoolItem(self, value)

    def _return(self, value: V) -> None:
        while self._waiters:
            fut = self._waiters.popleft()
            if not fut.done():
                fut.set_result(value)
                return
        self._items.append(value)
