"""End-to-end request tracing: a lightweight span recorder with
Chrome-trace/Perfetto export.

Spans ride the ambient :class:`~dynamo_tpu.runtime.context.RequestContext`
(the id + metadata bag that already crosses every network hop): the trace id
stamped at the edge lands in the context's metadata, every hop's handler
re-enters the context, and every span recorded anywhere in the stack carries
that trace id — so one request's spans from the HTTP frontend, the
processor/router, the prefill worker, and the decode worker stitch into a
single timeline keyed by ``trace_id``.

Off by default: ``span()`` costs one attribute read when disabled, so the hot
paths (scheduler windows, reconcile) pay nothing. Enable with
``DYNTPU_TRACE=<path>`` (spans append to the file as JSONL, one Chrome trace
event per line) or programmatically via :func:`enable` (in-memory ring only
when no path is given). ``tools/trace_view.py`` summarizes a capture;
the HTTP service's ``/trace`` endpoint serves the in-memory ring as a
Perfetto-loadable ``{"traceEvents": [...]}`` document.

Event shape (Chrome trace event format, complete-event ``ph: "X"``)::

    {"name": "engine.prefill", "ph": "X", "cat": "dyntpu",
     "ts": <epoch µs>, "dur": <µs>, "pid": <os pid>, "tid": <thread id>,
     "args": {"trace_id": ..., "request_id": ..., "thread": ..., ...}}

``ts`` is epoch-anchored (one monotonic->epoch offset captured at import), so
events from different processes line up on a shared timeline.
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time
from collections import deque
from typing import Iterator, Optional


def _ambient_context():
    # lazy: the runtime package imports utils during its own bootstrap
    from dynamo_tpu.runtime.context import current_context

    return current_context()


TRACE_ENV = "DYNTPU_TRACE"
MAX_EVENTS = 65536

# monotonic->epoch anchor: span timers use monotonic, exported ts is epoch µs
_EPOCH_OFFSET = time.time() - time.monotonic()

_lock = threading.Lock()
_events: deque = deque(maxlen=MAX_EVENTS)
_file = None
_path: Optional[str] = None
_enabled = bool(os.environ.get(TRACE_ENV))
if _enabled:
    _path = os.environ[TRACE_ENV]


def enabled() -> bool:
    return _enabled


def enable(path: Optional[str] = None) -> None:
    """Turn the recorder on; ``path`` (or $DYNTPU_TRACE) gets JSONL appends."""
    global _enabled, _path
    with _lock:
        _enabled = True
        if path is not None:
            _path = path


def disable() -> None:
    global _enabled, _file, _path
    with _lock:
        _enabled = False
        if _file is not None:
            try:
                _file.close()
            except OSError:
                pass
            _file = None
        # a later bare enable() starts fresh (env-configured path or memory
        # only) instead of appending to whatever path the last enable() used
        _path = os.environ.get(TRACE_ENV) or None


def clear() -> None:
    with _lock:
        _events.clear()


def current_trace_id() -> Optional[str]:
    """Trace id of the ambient request context (metadata-stamped id, falling
    back to the request id), or None outside a request."""
    ctx = _ambient_context()
    if ctx is None:
        return None
    return ctx.metadata.get("trace_id") or ctx.request_id


def _write_line(ev: dict) -> None:
    global _file
    if _path is None:
        return
    try:
        if _file is None:
            _file = open(_path, "a", buffering=1)
        _file.write(json.dumps(ev, default=str) + "\n")
    except OSError:
        pass  # tracing must never take the serving path down


def record_span(
    name: str,
    start: float,
    end: Optional[float] = None,
    duration: Optional[float] = None,
    request_id: Optional[str] = None,
    trace_id: Optional[str] = None,
    attrs: Optional[dict] = None,
) -> None:
    """Record one complete span. ``start``/``end`` are time.monotonic() values;
    pass ``duration`` instead of ``end`` when more convenient. request/trace
    ids default to the ambient context's — pass them explicitly on threads
    that run outside the request context (the engine loop)."""
    if not _enabled:
        return
    if duration is None:
        duration = (end if end is not None else time.monotonic()) - start
    if request_id is None or trace_id is None:
        ctx = _ambient_context()
        if ctx is not None:
            if request_id is None:
                request_id = ctx.request_id
            if trace_id is None:
                trace_id = ctx.metadata.get("trace_id") or ctx.request_id
    if trace_id is None:
        trace_id = request_id
    thread = threading.current_thread()
    args = {"trace_id": trace_id, "request_id": request_id, "thread": thread.name}
    if attrs:
        args.update(attrs)
    ev = {
        "name": name,
        "ph": "X",
        "cat": "dyntpu",
        "ts": int((start + _EPOCH_OFFSET) * 1e6),
        "dur": max(0, int(duration * 1e6)),
        "pid": os.getpid(),
        "tid": thread.ident or 0,
        "args": args,
    }
    with _lock:
        _events.append(ev)
        _write_line(ev)


@contextlib.contextmanager
def span(
    name: str,
    request_id: Optional[str] = None,
    trace_id: Optional[str] = None,
    **attrs,
) -> Iterator[None]:
    """Time a block as one span. No-op (one bool read) when tracing is off.
    Works across awaits: it measures wall time of the enclosed block."""
    if not _enabled:
        yield
        return
    t0 = time.monotonic()
    try:
        yield
    finally:
        record_span(
            name, t0, end=time.monotonic(),
            request_id=request_id, trace_id=trace_id, attrs=attrs or None,
        )


def events(
    trace_id: Optional[str] = None, request_id: Optional[str] = None
) -> list[dict]:
    """Snapshot of the in-memory ring, optionally filtered."""
    with _lock:
        snap = list(_events)
    if trace_id is not None:
        snap = [e for e in snap if e["args"].get("trace_id") == trace_id]
    if request_id is not None:
        snap = [e for e in snap if e["args"].get("request_id") == request_id]
    return snap


def trace_ids() -> list[str]:
    """Distinct trace ids currently in the ring (insertion order)."""
    seen: dict[str, None] = {}
    with _lock:
        for e in _events:
            tid = e["args"].get("trace_id")
            if tid:
                seen.setdefault(tid, None)
    return list(seen)


def export(trace_id: Optional[str] = None) -> dict:
    """Perfetto/chrome://tracing-loadable document."""
    return {
        "displayTimeUnit": "ms",
        "traceEvents": events(trace_id=trace_id),
        "otherData": {"source": "dynamo_tpu", "enabled": _enabled},
    }
