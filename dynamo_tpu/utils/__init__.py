from dynamo_tpu.utils.logging import init_logging, get_logger
from dynamo_tpu.utils import tracing
