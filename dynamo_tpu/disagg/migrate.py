"""Live sequence migration: hand an in-flight sequence to a peer mid-decode.

The composition ROADMAP item 4 names: chained block identity any worker can
recompute (llm/tokens.py), the peer-to-peer KV pull protocol with its
timeout->recompute fallback (disagg/prefix_fetch.py, extended with a
``seq_handoff`` kind that exports *per-sequence* page runs), and the
remote-adopt scheduler rebuild from authoritative token history. A draining
or hot worker snapshots a sequence's authoritative state into the small
msgpack ``SequenceManifest`` below, ships it to the destination, and the
destination re-enters the sequence through its normal admission path:

  - the manifest's token history (prompt + every generated token) IS the
    sequence — sampling state is positional (fold_seed keys draws by
    (seed, position)), penalties restore from ``penalty_output_from``, the
    draft-model cache and LoRA slot pins rebuild at admission exactly like a
    preemption resume, so the continuation is token-identical for greedy and
    seeded lanes;
  - committed KV pages ship over the pull dataplane (``kv_handoff_seq``
    drives the scheduler's FETCHING_KV state at the destination with the
    ``seq_handoff`` fetch kind); a timeout, a dead source, or corrupt parts
    degrade to chunked recompute from the same history — migration is
    *never worse* than today's preempt+recompute;
  - the source relays the destination's continuation tokens into the
    original output stream (AsyncJaxEngine.migrate_out), so the client sees
    ONE uninterrupted stream, re-pinned to the new worker.

The manifest is deliberately tiny (tokens + sampling scalars, no KV): a
128-token conversation manifests in ~1 KB of msgpack; the KV rides the
existing bulk dataplane where it belongs.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field
from typing import Optional

import msgpack

from dynamo_tpu.engine.sampling import SamplingParams
from dynamo_tpu.engine.scheduler import EngineRequest
from dynamo_tpu.utils import get_logger

log = get_logger("disagg.migrate")


@dataclass
class SequenceManifest:
    """Authoritative snapshot of one in-flight sequence, small enough to
    ship in a control-plane message. Everything the destination needs to
    continue the stream token-identically — and nothing it can recompute
    from the history itself (block hashes, for instance, derive from
    tokens + salt on either side)."""

    request_id: str
    prompt_tokens: list = field(default_factory=list)
    generated: list = field(default_factory=list)  # tokens already emitted
    sampling: dict = field(default_factory=dict)  # asdict(SamplingParams)
    eos_token_ids: list = field(default_factory=list)
    lora_name: str = ""
    logprobs: Optional[int] = None
    # prior-output split for presence/frequency penalties (the ORIGINAL
    # prompt end; earlier preemptions/migrations carry their split forward)
    penalty_output_from: Optional[int] = None
    trace_id: Optional[str] = None
    tenant: str = ""
    scenario: str = ""
    # QoS priority class ("" = standard): the destination serves the
    # migrated sequence at the same class it held on the source
    priority: str = ""
    # KV handoff: the source worker's pull-server address and how many full
    # committed blocks of the history it can export via ``seq_handoff``
    source_addr: str = ""
    kv_blocks: int = 0
    # request age at snapshot time (seconds): the destination back-dates
    # enqueue_ts so goodput/duration accounting spans the whole request
    age_s: float = 0.0

    # ---------------- wire ----------------

    def to_wire(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_wire(cls, data: dict) -> "SequenceManifest":
        known = {f.name for f in dataclasses.fields(cls)}
        m = cls(**{k: v for k, v in data.items() if k in known})
        if "stop" in m.sampling:
            # msgpack flattens tuples to lists; SamplingParams.stop is a
            # tuple — normalize so roundtrips are byte-stable
            m.sampling = {**m.sampling, "stop": tuple(m.sampling["stop"])}
        return m

    def pack(self) -> bytes:
        """Compact msgpack form (the cross-worker wire payload)."""
        return msgpack.packb(self.to_wire())

    @classmethod
    def unpack(cls, raw: bytes) -> "SequenceManifest":
        return cls.from_wire(msgpack.unpackb(raw))

    # ---------------- reconstruction ----------------

    @property
    def history(self) -> list:
        return list(self.prompt_tokens) + list(self.generated)

    def to_engine_request(self, now: Optional[float] = None) -> EngineRequest:
        """The destination's admission request: the preemption-resume shape
        (history as prompt, budgets reduced by what already streamed) plus
        the seq_handoff pull hints so admission fetches the committed KV
        instead of recomputing it."""
        s = SamplingParams(**self.sampling)
        done = len(self.generated)
        sampling = dataclasses.replace(
            s,
            max_tokens=max(1, s.max_tokens - done),
            min_tokens=max(0, s.min_tokens - done),
        )
        return EngineRequest(
            request_id=self.request_id,
            token_ids=self.history,
            sampling=sampling,
            eos_token_ids=tuple(self.eos_token_ids),
            logprobs=self.logprobs,
            penalty_output_from=(
                self.penalty_output_from
                if self.penalty_output_from is not None
                else len(self.prompt_tokens)
            ),
            enqueue_ts=max(0.0, now - self.age_s) if now else 0.0,
            trace_id=self.trace_id,
            tenant=self.tenant,
            scenario=self.scenario,
            priority=self.priority,
            lora_name=self.lora_name,
            kv_holder_addr=self.source_addr,
            kv_holder_blocks=self.kv_blocks,
            kv_handoff_seq=self.request_id,
        )

    def to_resume_request(self, relayed: list, now: float) -> EngineRequest:
        """The source's local-resume request after a FAILED handoff that
        already relayed ``relayed`` destination tokens into the client
        stream: history + relayed tokens become the prompt (their KV
        recomputes; the prefix cache usually still holds the committed
        blocks), budgets shrink by everything already delivered. Exactly the
        preemption-resume contract — the failure arm of the ladder is
        literally today's preempt+recompute."""
        s = SamplingParams(**self.sampling)
        done = len(self.generated) + len(relayed)
        sampling = dataclasses.replace(
            s,
            max_tokens=max(1, s.max_tokens - done),
            min_tokens=max(0, s.min_tokens - done),
        )
        return EngineRequest(
            request_id=self.request_id,
            token_ids=self.history + list(relayed),
            sampling=sampling,
            eos_token_ids=tuple(self.eos_token_ids),
            logprobs=self.logprobs,
            penalty_output_from=(
                self.penalty_output_from
                if self.penalty_output_from is not None
                else len(self.prompt_tokens)
            ),
            # same back-dating as to_engine_request: the resumed request
            # bills queue wait / TTFT / duration from the ORIGINAL
            # submission, not from the moment the handoff failed
            enqueue_ts=max(0.0, now - self.age_s) if now else 0.0,
            trace_id=self.trace_id,
            tenant=self.tenant,
            scenario=self.scenario,
            priority=self.priority,
            lora_name=self.lora_name,
        )
