"""Fleet-wide prefix cache: the cross-worker KV **pull** protocol.

The push dataplane (disagg/dataplane.py) moves KV the prefill worker just
computed to the decode worker that asked for it. This module is the other
direction: when the KV router places a request on a worker that does NOT
hold its prefix, that worker pulls the matching pages from the peer the
radix indexer says has them — instead of recomputing the whole prefix.
This extends the reference's single-node KVBM reuse across the fleet
(mooncake-style disaggregated KV pooling; SURVEY capability 5).

Two halves:

  - ``KvPullServer`` — worker-side export service. A peer connects and
    sends a fetch frame naming the *chained sequence hashes* (llm/tokens.py
    — the engine block identity carried in KV events) of the prefix blocks
    it wants. The server walks the contiguous leading run of those hashes
    down the tier ladder — HBM pages first (``ModelRunner.
    extract_pages_async``, dispatched on the engine thread), then
    ``HostKvPool`` blocks — and streams the data back as checksummed parts
    on the same connection. A leading miss returns a clean ``gone`` frame,
    never a timeout: the requester must fall back to recompute immediately,
    not stall admission behind a dead wait.

  - ``PrefixFetchClient`` — requester side, driven by the engine scheduler
    (a thread without an event loop): ``fetch()`` schedules the coroutine
    onto the serving loop via ``run_coroutine_threadsafe`` and hands back a
    concurrent Future the scheduler polls each step while the sequence
    waits in its FETCHING_KV state. Every failure mode (timeout, refused
    connection, holder death mid-stream, checksum mismatch, ``gone``)
    resolves the future with a non-hit result — the scheduler then
    recomputes; a fetch can never error a request.

Wire format (shared framing with the push plane: ``u32 len | msgpack
header [| payload]``):

    request:  {kind: "prefix_fetch", hashes: [u64, ...]}
              | {kind: "seq_handoff", seq_id, hashes: [u64, ...]}
    response: 1..N part frames, each
              {status: "ok", part_seq, part_total, block_from, block_to,
               tier: "hbm"|"host", shape, dtype, xxh3, cat_axis
               [, scales, scales_shape, scales_dtype]} | payload
              — or a single payload-less {status: "gone"} / {status:
              "error", error} frame.

``block_from``/``block_to`` index into the REQUESTED hash list, so the
requester maps parts onto its own freshly-allocated pages. Int8 KV caches
ship int8 page data (half the wire bytes) with the per-row scale plane
riding the part header, exactly like the push protocol — and because the
parts land in ``ModelRunner.inject_pages_bucketed``, mixed-dtype peers
interoperate (scatter_pages_wire re/de-quantizes).

``seq_handoff`` (live migration, disagg/migrate.py) is the same response
wire driven by a different export: instead of walking the *shared prefix
cache*, the server exports the named live sequence's OWN page run — full
committed blocks of a mid-decode sequence, including decode-written blocks
whose cache registration deduped onto another page — so a migrating
sequence's KV follows it to the destination worker.

Both serve paths honor the seeded chaos knobs in ``disagg/faults.py``
(DYNTPU_FAULT_DATAPLANE: drop-part / delay-ms / corrupt-checksum), so the
failure ladder tests drive real timeout/corruption arms deterministically
instead of standing up socket blackholes.
"""

from __future__ import annotations

import asyncio
import time
from dataclasses import dataclass, field
from typing import Optional

import msgpack
import numpy as np
import xxhash

from dynamo_tpu.disagg.dataplane import _LEN, MAX_HEADER
from dynamo_tpu.utils import get_logger
from dynamo_tpu.utils.prometheus import Histogram, render_family

log = get_logger("disagg.prefix_fetch")

# whole-fetch latency: localhost pulls are ms-scale, a cross-host pull of a
# long prefix reaches seconds
_FETCH_SECONDS_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                          0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0)


def _np_dtype(name: str) -> np.dtype:
    from dynamo_tpu.llm.remote_prefill import _np_dtype as _impl

    return _impl(name)


def _pack_part(
    seq: int, total: int, block_from: int, block_to: int, tier: str,
    data, axis: int,
) -> tuple[bytes, memoryview]:
    """One response part -> (header bytes, payload memoryview). ``data`` may
    be the int8 {"q","s"} wire dict — the scale plane rides the header."""
    scales = None
    if isinstance(data, dict):
        scales = data["s"]
        data = data["q"]
    arr = np.ascontiguousarray(data)
    payload = memoryview(arr.view(np.uint8).reshape(-1))
    fields = {
        "status": "ok",
        "part_seq": seq,
        "part_total": total,
        "block_from": block_from,
        "block_to": block_to,
        "tier": tier,
        "shape": list(arr.shape),
        "dtype": str(arr.dtype),
        "xxh3": xxhash.xxh3_64_intdigest(payload),
        "cat_axis": axis,
    }
    if scales is not None:
        s = np.ascontiguousarray(scales)
        fields["scales"] = s.tobytes()
        fields["scales_shape"] = list(s.shape)
        fields["scales_dtype"] = str(s.dtype)
    return msgpack.packb(fields), payload


@dataclass
class FetchedPart:
    """One pulled prefix range, ready for inject_pages_bucketed."""

    block_from: int  # indices into the requested hash list
    block_to: int
    data: object  # np array, or the int8 {"q","s"} wire dict
    cat_axis: int
    tier: str = ""


@dataclass
class PrefixFetchResult:
    """Terminal state of one fetch; every failure mode is a status, never an
    exception — the scheduler's fallback ladder keys off it."""

    status: str  # "hit" | "gone" | "timeout" | "error"
    blocks: int = 0  # contiguous leading blocks received
    bytes: int = 0  # payload bytes received
    parts: list = field(default_factory=list)  # [FetchedPart], block order
    error: str = ""


class KvPullServer:
    """Worker-side KV export service: serves prefix pulls from this engine's
    HBM pages and host-pool blocks."""

    def __init__(self, engine, host: str = "0.0.0.0", advertise_host: Optional[str] = None):
        self.engine = engine
        self.host = host
        self.advertise_host = advertise_host
        self.port: Optional[int] = None
        self._server: Optional[asyncio.AbstractServer] = None
        self._writers: set[asyncio.StreamWriter] = set()
        self.served = 0  # fetches answered with >= 1 block
        self.gone = 0  # clean leading-miss responses
        self.errors = 0
        self.served_blocks = {"hbm": 0, "host": 0}
        self.bytes_sent = 0
        self.handoffs_served = 0  # seq_handoff exports answered with blocks

    @property
    def address(self) -> str:
        host = self.advertise_host
        if host is None:
            if self.host in ("0.0.0.0", "::"):
                import socket

                host = socket.gethostname()
            else:
                host = self.host
        return f"{host}:{self.port}"

    async def start(self, port: int = 0) -> "KvPullServer":
        self._server = await asyncio.start_server(self._on_conn, self.host, port)
        self.port = self._server.sockets[0].getsockname()[1]
        log.info("kv pull server listening on %s", self.address)
        return self

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            for w in list(self._writers):
                w.close()
            await self._server.wait_closed()

    # ---------------- wire ----------------

    async def _on_conn(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter):
        peer = writer.get_extra_info("peername")
        self._writers.add(writer)
        try:
            while True:
                raw = await reader.readexactly(_LEN.size)
                (hlen,) = _LEN.unpack(raw)
                if hlen > MAX_HEADER:
                    raise ValueError(f"prefix fetch header too large: {hlen}")
                header = msgpack.unpackb(await reader.readexactly(hlen))
                kind = header.get("kind")
                if kind not in ("prefix_fetch", "seq_handoff"):
                    raise ValueError(f"unexpected frame kind {kind!r}")
                await self._serve_fetch(
                    writer, list(header.get("hashes", ())), kind=kind,
                    seq_id=str(header.get("seq_id", "") or ""),
                )
        except (asyncio.IncompleteReadError, ConnectionResetError):
            pass
        except Exception:
            log.exception("kv pull connection from %s failed", peer)
        finally:
            self._writers.discard(writer)
            writer.close()

    async def _write_status(self, writer, status: str, error: str = "") -> None:
        fields = {"status": status, "part_total": 0}
        if error:
            fields["error"] = error
        header = msgpack.packb(fields)
        writer.write(_LEN.pack(len(header)))
        writer.write(header)
        await writer.drain()

    async def _serve_fetch(
        self, writer, hashes: list[int], kind: str = "prefix_fetch",
        seq_id: str = "",
    ) -> None:
        from dynamo_tpu.disagg.faults import active_plan

        engine = self.engine
        plan = active_plan()
        if plan is not None:
            delay = plan.delay_s(kind)
            if delay > 0:
                await asyncio.sleep(delay)
        export = None
        if hashes and engine is not None:
            try:
                if kind == "seq_handoff":
                    # live migration: export the named sequence's OWN page
                    # run (committed blocks of a mid-decode sequence),
                    # falling back to the shared prefix cache when the
                    # sequence has already been released
                    export = await engine.run_on_engine(
                        lambda: engine.sync_export_sequence(seq_id, hashes)
                    )
                else:
                    export = await engine.run_on_engine(
                        lambda: engine.sync_export_prefix(hashes)
                    )
            except Exception:
                log.exception("prefix export failed")
                self.errors += 1
                await self._write_status(writer, "error", "export failed")
                return
        if export is None:
            # leading block in no tier: a clean miss the requester can act on
            # immediately (it recomputes), never a timeout
            self.gone += 1
            await self._write_status(writer, "gone")
            return
        n_dev, dev_future, host_blocks, axis = export
        try:
            parts = []
            if n_dev:
                # resolve the D2H staging off-loop; the gather itself was
                # dispatched on the engine thread inside sync_export_prefix
                data = await asyncio.wrap_future(dev_future)
                parts.append((0, n_dev, "hbm", data))
            if host_blocks:
                from dynamo_tpu.quant.kv import wire_concat

                hdata = (
                    wire_concat(host_blocks, axis=axis)
                    if len(host_blocks) > 1
                    else host_blocks[0]
                )
                parts.append((n_dev, n_dev + len(host_blocks), "host", hdata))
        except Exception:
            log.exception("prefix export staging failed")
            self.errors += 1
            await self._write_status(writer, "error", "staging failed")
            return
        total = len(parts)
        for seq, (b0, b1, tier, data) in enumerate(parts):
            if plan is not None and plan.should_drop(kind):
                # injected blackhole: the frame is never written, so the
                # requester's own timeout must unwedge it (the exact failure
                # a dead socket produces, without a real dead socket)
                log.warning("fault: dropping %s part %d for test", kind, seq)
                continue
            header, payload = _pack_part(seq, total, b0, b1, tier, data, axis)
            if plan is not None and plan.should_corrupt(kind):
                fields = msgpack.unpackb(header)
                fields["xxh3"] = (fields["xxh3"] ^ 1) & 0xFFFFFFFFFFFFFFFF
                header = msgpack.packb(fields)
            writer.write(_LEN.pack(len(header)))
            writer.write(header)
            writer.write(payload)
            await writer.drain()
            self.served_blocks[tier] = self.served_blocks.get(tier, 0) + (b1 - b0)
            self.bytes_sent += payload.nbytes
        self.served += 1
        if kind == "seq_handoff":
            self.handoffs_served += 1

    # ---------------- metrics ----------------

    def render_metrics(self) -> str:
        return "".join([
            render_family(
                "dynamo_prefix_fetch_served_total", "counter",
                "prefix pulls answered, by result",
                [({"result": "hit"}, self.served),
                 ({"result": "gone"}, self.gone),
                 ({"result": "error"}, self.errors)],
            ),
            render_family(
                "dynamo_prefix_fetch_served_blocks_total", "counter",
                "KV blocks exported to pulling peers, by tier",
                [({"tier": t}, n) for t, n in sorted(self.served_blocks.items())],
            ),
            render_family(
                "dynamo_prefix_fetch_served_bytes_total", "counter",
                "KV payload bytes exported to pulling peers",
                [({}, self.bytes_sent)],
            ),
        ])


class PrefixFetchClient:
    """Requester side: pulls a prefix's blocks from a peer's KvPullServer.

    ``fetch()`` is thread-safe (the engine scheduler calls it from the
    engine thread); the returned concurrent Future ALWAYS resolves to a
    PrefixFetchResult — timeouts, dead peers, and protocol errors become
    statuses, so a fetch can never wedge or error admission."""

    def __init__(self, loop: Optional[asyncio.AbstractEventLoop], timeout_s: float = 5.0):
        self._loop = loop
        self.timeout_s = timeout_s
        self.requests = 0
        self.results: dict[str, int] = {}
        self.blocks_received = 0
        self.bytes_received = 0
        self.fetch_seconds = Histogram(
            "dynamo_prefix_fetch_client_seconds",
            "wall time of one prefix pull, connection to last part",
            _FETCH_SECONDS_BUCKETS,
        )

    def fetch(
        self, addr: str, hashes: list[int], timeout_s: Optional[float] = None,
        kind: str = "prefix_fetch", seq_id: str = "",
    ):
        """Start a pull; returns a concurrent.futures.Future[PrefixFetchResult].
        ``kind="seq_handoff"`` + ``seq_id`` pulls a live sequence's own page
        run off a migrating source instead of the shared prefix cache."""
        if self._loop is None or self._loop.is_closed():
            raise RuntimeError("prefix fetch client has no running event loop")
        return asyncio.run_coroutine_threadsafe(
            self._fetch(addr, list(hashes), timeout_s or self.timeout_s,
                        kind=kind, seq_id=seq_id),
            self._loop,
        )

    async def _fetch(
        self, addr: str, hashes: list[int], timeout_s: float,
        kind: str = "prefix_fetch", seq_id: str = "",
    ) -> PrefixFetchResult:
        self.requests += 1
        t0 = time.monotonic()
        try:
            res = await asyncio.wait_for(
                self._fetch_inner(addr, hashes, kind=kind, seq_id=seq_id),
                timeout_s,
            )
        except asyncio.TimeoutError:
            res = PrefixFetchResult(status="timeout")
        except asyncio.CancelledError:
            raise
        except Exception as e:
            res = PrefixFetchResult(status="error", error=f"{type(e).__name__}: {e}")
        self.results[res.status] = self.results.get(res.status, 0) + 1
        self.blocks_received += res.blocks
        self.bytes_received += res.bytes
        self.fetch_seconds.observe(time.monotonic() - t0)
        if res.status != "hit":
            log.debug("prefix fetch from %s: %s %s", addr, res.status, res.error)
        return res

    async def _fetch_inner(
        self, addr: str, hashes: list[int], kind: str = "prefix_fetch",
        seq_id: str = "",
    ) -> PrefixFetchResult:
        host, _, port = addr.rpartition(":")
        reader, writer = await asyncio.open_connection(host, int(port))
        try:
            fields = {"kind": kind, "hashes": hashes}
            if seq_id:
                fields["seq_id"] = seq_id
            req = msgpack.packb(fields)
            writer.write(_LEN.pack(len(req)))
            writer.write(req)
            await writer.drain()
            parts: list[FetchedPart] = []
            total: Optional[int] = None
            nbytes_total = 0
            while total is None or len(parts) < total:
                raw = await reader.readexactly(_LEN.size)
                (hlen,) = _LEN.unpack(raw)
                if hlen > MAX_HEADER:
                    raise ValueError(f"prefix fetch header too large: {hlen}")
                header = msgpack.unpackb(await reader.readexactly(hlen))
                status = header.get("status")
                if status == "gone":
                    return PrefixFetchResult(status="gone")
                if status != "ok":
                    return PrefixFetchResult(
                        status="error", error=str(header.get("error", "bad status"))
                    )
                dtype = _np_dtype(header["dtype"])
                shape = tuple(header["shape"])
                nbytes = dtype.itemsize * int(np.prod(shape))
                payload = await reader.readexactly(nbytes)
                if xxhash.xxh3_64_intdigest(payload) != header["xxh3"]:
                    return PrefixFetchResult(status="error", error="checksum mismatch")
                data: object = np.frombuffer(payload, dtype).reshape(shape)
                if header.get("scales") is not None:
                    scales = np.frombuffer(
                        header["scales"], _np_dtype(header["scales_dtype"])
                    ).reshape(tuple(header["scales_shape"]))
                    data = {"q": data, "s": scales}
                parts.append(FetchedPart(
                    block_from=int(header["block_from"]),
                    block_to=int(header["block_to"]),
                    data=data,
                    cat_axis=int(header.get("cat_axis", 2)),
                    tier=str(header.get("tier", "")),
                ))
                total = max(1, int(header["part_total"]))
                nbytes_total += nbytes
            parts.sort(key=lambda p: p.block_from)
            # only the contiguous leading run is usable as cached prefix
            blocks = 0
            usable = []
            for p in parts:
                if p.block_from != blocks:
                    break
                usable.append(p)
                blocks = p.block_to
            if blocks == 0:
                return PrefixFetchResult(status="gone")
            return PrefixFetchResult(
                status="hit", blocks=blocks, bytes=nbytes_total, parts=usable
            )
        finally:
            writer.close()

    # ---------------- metrics ----------------

    def render_metrics(self) -> str:
        results = {s: self.results.get(s, 0) for s in ("hit", "gone", "timeout", "error")}
        return "".join([
            render_family(
                "dynamo_prefix_fetch_client_requests_total", "counter",
                "prefix pulls issued to peers, by terminal result",
                [({"result": s}, n) for s, n in sorted(results.items())],
            ),
            render_family(
                "dynamo_prefix_fetch_client_blocks_total", "counter",
                "KV blocks pulled off peers (contiguous usable runs)",
                [({}, self.blocks_received)],
            ),
            render_family(
                "dynamo_prefix_fetch_client_bytes_total", "counter",
                "KV payload bytes pulled off peers (at the wire KV dtype)",
                [({}, self.bytes_received)],
            ),
            self.fetch_seconds.render(),
        ])
