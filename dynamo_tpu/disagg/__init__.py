"""Disaggregated prefill/decode serving (reference: docs/disagg_serving.md,
examples/llm/components/{worker,prefill_worker}.py, the NIXL patch)."""

from dynamo_tpu.disagg.decode_worker import DisaggDecodeEngine
from dynamo_tpu.disagg.prefill_worker import PrefillWorker
