"""Deterministic fault injection for the disagg dataplane wires.

The migration failure ladder and the prefix-fetch fallback tests used to
need real socket blackholes (an accepting server that never answers) to
exercise the timeout arms. Those are slow (the test eats the whole timeout),
racy across platforms, and can't target one wire *kind* at a time. This
module replaces them with seeded, per-kind chaos knobs every dataplane
producer honors:

    DYNTPU_FAULT_DATAPLANE="seq_handoff=drop-part,push=delay-ms:50"
    DYNTPU_FAULT_SEED=7

Grammar: comma-separated ``<kind>=<fault>[:<arg>]`` rules.

  kinds:   ``push``         — the KV stream client (dataplane.send_part)
           ``prefix_fetch`` — the pull server's shared-prefix export
           ``seq_handoff``  — the pull server's per-sequence migration export
           ``*``            — every kind
  faults:  ``drop-part[:p]``        — silently skip sending a part (the
                                      receiver's own timeout must fire; the
                                      frame is never written, exactly what a
                                      blackholed socket looks like)
           ``delay-ms:<ms>``        — sleep before each frame (latency
                                      injection; async sites await it)
           ``corrupt-checksum[:p]`` — send a wrong xxh3 so the receiver's
                                      per-part integrity check must reject

``p`` is a probability in [0, 1] (default 1.0 = every part): probabilistic
faults draw from a per-(kind, fault) ``random.Random`` seeded from
DYNTPU_FAULT_SEED, so a given seed produces the same drop pattern on every
run — chaos tests are replayable, not flaky.

The plan is re-resolved from the environment on each lookup (cached by spec
string), so tests can monkeypatch the env per-arm without reimporting
producers. An empty/unset env means zero overhead: one dict.get and out.
"""

from __future__ import annotations

import os
import random
from typing import Optional

FAULT_KINDS = ("push", "prefix_fetch", "seq_handoff")
FAULTS = ("drop-part", "delay-ms", "corrupt-checksum")

ENV_SPEC = "DYNTPU_FAULT_DATAPLANE"
ENV_SEED = "DYNTPU_FAULT_SEED"


def _journal(kind: str, fault: str) -> None:
    """Flight-recorder breadcrumb for every fault that actually fires:
    seeded chaos runs become self-documenting (`/debug/requests/{id}` shows
    the injection next to the fallback arm it triggered). Never raises —
    fault bookkeeping must not change the failure being injected."""
    try:
        from dynamo_tpu.utils import events

        # "plane" not "kind": the journal's own kind parameter owns that name
        events.emit("fault.injected", plane=kind, fault=fault)
    except Exception:
        pass


class FaultPlan:
    """Parsed fault rules: per-kind drop/delay/corrupt decisions."""

    def __init__(self, spec: str, seed: int = 0):
        self.spec = spec
        self.seed = seed
        # (kind, fault) -> arg (probability or milliseconds)
        self._rules: dict[tuple[str, str], float] = {}
        self._rngs: dict[tuple[str, str], random.Random] = {}
        for rule in filter(None, (r.strip() for r in spec.split(","))):
            kind, _, fault_spec = rule.partition("=")
            kind = kind.strip()
            if kind != "*" and kind not in FAULT_KINDS:
                raise ValueError(
                    f"unknown dataplane fault kind {kind!r} "
                    f"(expected one of {FAULT_KINDS} or '*')"
                )
            fault, _, arg = fault_spec.partition(":")
            fault = fault.strip()
            if fault not in FAULTS:
                raise ValueError(
                    f"unknown dataplane fault {fault!r} (expected one of {FAULTS})"
                )
            if arg:
                value = float(arg)
            else:
                if fault == "delay-ms":
                    raise ValueError("delay-ms requires a milliseconds arg")
                value = 1.0
            kinds = FAULT_KINDS if kind == "*" else (kind,)
            for k in kinds:
                self._rules[(k, fault)] = value

    def _hit(self, kind: str, fault: str) -> bool:
        p = self._rules.get((kind, fault))
        if p is None:
            return False
        if p >= 1.0:
            return True
        key = (kind, fault)
        rng = self._rngs.get(key)
        if rng is None:
            # per-(kind, fault) stream off the plan seed: deterministic per
            # process for a given seed, independent across rules
            rng = self._rngs[key] = random.Random(
                (self.seed << 8) ^ hash(key) & 0x7FFFFFFF
            )
        return rng.random() < p

    def should_drop(self, kind: str) -> bool:
        hit = self._hit(kind, "drop-part")
        if hit:
            _journal(kind, "drop-part")
        return hit

    def should_corrupt(self, kind: str) -> bool:
        hit = self._hit(kind, "corrupt-checksum")
        if hit:
            _journal(kind, "corrupt-checksum")
        return hit

    def delay_s(self, kind: str) -> float:
        ms = self._rules.get((kind, "delay-ms"), 0.0)
        return ms / 1000.0


class AdmissionFaultPlan:
    """Seeded chaos knobs for the ADMISSION plane (the QoS 429/shed path),
    mirroring the dataplane grammar so client retry/backoff behavior and the
    shed path are testable deterministically:

        DYNTPU_FAULT_ADMISSION="reject-rate:0.3,delay-ms:20"
        DYNTPU_FAULT_SEED=7

      ``reject-rate:<p>`` — answer a structured retriable 429 for a seeded
                            fraction p of requests BEFORE any SSE bytes
                            (exactly the budget-exhausted wire behavior)
      ``delay-ms:<ms>``   — sleep before the admission verdict (latency
                            injection; the async handler awaits it)
    """

    FAULTS = ("reject-rate", "delay-ms")

    def __init__(self, spec: str, seed: int = 0):
        self.spec = spec
        self.seed = seed
        self._rules: dict[str, float] = {}
        for rule in filter(None, (r.strip() for r in spec.split(","))):
            fault, _, arg = rule.partition(":")
            fault = fault.strip()
            if fault not in self.FAULTS:
                raise ValueError(
                    f"unknown admission fault {fault!r} "
                    f"(expected one of {self.FAULTS})"
                )
            if not arg:
                raise ValueError(f"admission fault {fault} requires an arg")
            self._rules[fault] = float(arg)
        # one stream per plan: a given seed produces the same reject pattern
        # on every run (replayable chaos, not flakiness)
        self._rng = random.Random((seed << 8) ^ 0x0AD)

    def should_reject(self) -> bool:
        p = self._rules.get("reject-rate", 0.0)
        if p <= 0.0:
            return False
        hit = True if p >= 1.0 else self._rng.random() < p
        if hit:
            _journal("admission", "reject")
        return hit

    def delay_s(self) -> float:
        return self._rules.get("delay-ms", 0.0) / 1000.0


_CACHE: dict[tuple[str, int], FaultPlan] = {}
_ADMISSION_CACHE: dict[tuple[str, int], AdmissionFaultPlan] = {}

ENV_ADMISSION = "DYNTPU_FAULT_ADMISSION"


def admission_plan() -> Optional[AdmissionFaultPlan]:
    """The admission-plane fault plan the environment asks for (None = no
    faults). Cached by (spec, seed) like the dataplane plan; note the RNG
    lives on the cached plan, so one process's reject sequence is one
    deterministic stream per (spec, seed)."""
    spec = os.environ.get(ENV_ADMISSION, "").strip()
    if not spec:
        return None
    try:
        seed = int(os.environ.get(ENV_SEED, "0") or 0)
    except ValueError:
        seed = 0
    key = (spec, seed)
    plan = _ADMISSION_CACHE.get(key)
    if plan is None:
        plan = _ADMISSION_CACHE[key] = AdmissionFaultPlan(spec, seed)
    return plan


def active_plan() -> Optional[FaultPlan]:
    """The fault plan the environment currently asks for (None = no faults).

    Parsed plans cache by (spec, seed) so the per-part cost of a configured
    plan is one dict lookup; a malformed spec raises at the first part — a
    chaos knob typo must fail the test loudly, not silently inject nothing.
    """
    spec = os.environ.get(ENV_SPEC, "").strip()
    if not spec:
        return None
    try:
        seed = int(os.environ.get(ENV_SEED, "0") or 0)
    except ValueError:
        seed = 0
    key = (spec, seed)
    plan = _CACHE.get(key)
    if plan is None:
        plan = _CACHE[key] = FaultPlan(spec, seed)
    return plan
