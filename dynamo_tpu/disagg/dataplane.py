"""Dedicated KV-block data plane for cross-process disaggregation.

The reference moves KV blocks between engine processes with NIXL RDMA WRITEs
plus completion notifications, off the control plane (reference: container/
deps/vllm/vllm_v0.7.2-dynamo-kv-disagg-patch.patch ``nixl.py`` —
``read_blocks``/``get_notifs``; docs/disagg_serving.md:83 non-blocking
property). The TPU-native analogue: bulk KV bytes ride dedicated TCP
sockets between the prefill and decode processes — never inside the
control-plane response message — and land in a per-request mailbox whose
future IS the completion notification. On-pod (same-process) transfers keep
using the device-array hub (dynamo_tpu/disagg/ici.py); this module is the
cross-process / cross-host path.

Wire format (v2, streamed): one request's KV travels as 1..N *parts*, each a
self-contained frame

    u32 header_len | msgpack header | payload bytes

    header = {request_id, shape, dtype, xxh3, token,
              part_seq, part_total, page_from, page_to, cat_axis
              [, scales, scales_shape, scales_dtype]}

``xxh3`` covers the payload of THIS part only, so a corrupt frame kills one
transfer, not the shared connection. ``page_from``/``page_to`` are logical
page indices within the sequence (the decode side maps them onto its own
page ids and scatters each part as it lands); ``cat_axis`` is the page axis
of the wire layout (models differ: llama [L,2,n,ps,H,D] -> 2, MLA latent
[L,n,ps,latent] -> 1) so a consumer-less receiver can reassemble. A v1
monolithic send is exactly a v2 transfer with ``part_total == 1``.

Int8 KV caches (quant/kv.py) ship the int8 page data as the payload — half
the bytes of the bf16 wire — with the per-page scale plane riding IN the
part header (``scales`` raw bytes + shape/dtype: ~ps f32 per page, a
rounding error of the payload). The receiver hands the consumer a
``KvPart`` whose ``scales`` field carries the decoded plane; reassembly
without a consumer yields the {"q","s"} wire dict.

The client keeps N parallel *lanes* (connections) per destination and
stripes parts across them, so one long prompt's multi-MB parts never
head-of-line-block every other request behind a single per-destination lock.

The server never blocks the sender on the consumer: payloads for requests
nobody expects (cancelled, duplicate) are received and dropped.
"""

from __future__ import annotations

import asyncio
import random
import secrets
import struct
from dataclasses import dataclass, field
from typing import Callable, Optional

import msgpack
import numpy as np
import xxhash

from dynamo_tpu.utils import get_logger
from dynamo_tpu.utils.prometheus import Histogram, render_family

log = get_logger("disagg.dataplane")

_LEN = struct.Struct("<I")
# frame-sanity bound, not a budget: int8 transfers carry their scale plane
# in the header (L * 2 * n * ps * 4 bytes — a monolithic small-page-size
# send can reach a few MB), so the cap sits well above any legitimate
# header while still rejecting a corrupt length prefix
MAX_HEADER = 8 << 20

# part payload sizes: a tiny-model part is KBs, a serving-geometry chunk part
# is tens of MB
_PART_BYTES_BUCKETS = (
    4096.0, 65536.0, 262144.0, 1048576.0, 4194304.0,
    16777216.0, 67108864.0, 268435456.0,
)


def stream_part_plan(
    start_page: int, cached_len: int, prompt_len: int, page_size: int,
    max_chunk: int,
) -> list[tuple[int, int]]:
    """Page ranges ``[(page_from, page_to), ...]`` a chunk-streamed prefill
    emits, in order. Deterministic from the chunk ladder, so both the part
    count (``part_total`` in every header) and each part's range are known
    before the first chunk runs:

      - pages already valid from the prefill-side prefix cache are final
        immediately (one leading part)
      - after chunk ``[start, end)`` completes, pages fully covered by
        ``end`` tokens are final; the possibly-partial tail page ships with
        the last chunk

    Pages below ``start_page`` (the decode side's shared prefix) are never
    sent at all."""
    n_pages = -(-prompt_len // page_size)
    parts: list[tuple[int, int]] = []
    sent = start_page
    cached_pages = min(cached_len // page_size, n_pages)
    if cached_pages > sent:
        parts.append((sent, cached_pages))
        sent = cached_pages
    start = cached_len
    while start < prompt_len:
        end = min(start + max_chunk, prompt_len)
        final = n_pages if end == prompt_len else end // page_size
        if final > sent:
            parts.append((sent, final))
            sent = final
        start = end
    return parts


@dataclass
class KvPart:
    """One received KV part, handed to the incremental consumer (or parked
    for reassembly) as it arrives."""

    seq: int
    total: int
    page_from: int  # logical page index within the sequence; -1 = unknown (v1)
    page_to: int
    cat_axis: int
    data: np.ndarray
    # int8 transfers: the per-page f32 scale plane decoded from the part
    # header (None on full-precision wire). data is then the int8 page data.
    scales: Optional[np.ndarray] = None

    def wire_data(self):
        """What inject_pages_bucketed consumes: the plain array, or the
        {"q","s"} dict when this part carries an int8 block + scales."""
        if self.scales is None:
            return self.data
        return {"q": self.data, "s": self.scales}


@dataclass
class _Pending:
    fut: asyncio.Future
    token: str
    total: int = 1
    received: set = field(default_factory=set)
    parts: dict = field(default_factory=dict)  # seq -> KvPart (no consumer)
    consumer: Optional[Callable[[KvPart], None]] = None


class KvDataPlaneServer:
    """Decode-side listener: framed KV parts -> incremental consumers (or a
    per-request reassembly future)."""

    def __init__(self, host: str = "0.0.0.0", advertise_host: Optional[str] = None):
        self.host = host
        self.advertise_host = advertise_host
        self.port: Optional[int] = None
        self._server: Optional[asyncio.AbstractServer] = None
        # per-request nonces live on the pending entry: a payload must carry
        # the token expect() minted (travels to the prefill side inside
        # RemotePrefillRequest), so a peer that guesses an in-flight
        # request_id can't poison the cache
        self._expected: dict[str, _Pending] = {}
        self._writers: set[asyncio.StreamWriter] = set()
        self.received = 0  # completed transfers (all parts)
        self.parts_received = 0
        self.bytes_received = 0
        self.dropped = 0  # unexpected / duplicate frames
        self.rejected = 0  # bad/missing token
        self.checksum_failures = 0
        self.part_bytes_hist = Histogram(
            "dynamo_kv_stream_part_bytes",
            "received KV part payload size in bytes",
            _PART_BYTES_BUCKETS,
        )

    @property
    def address(self) -> str:
        host = self.advertise_host
        if host is None:
            if self.host in ("0.0.0.0", "::"):
                # wildcard bind: advertise a cross-host-reachable name (same
                # policy as the response plane, runtime/tcp.py)
                import socket

                host = socket.gethostname()
            else:
                host = self.host
        return f"{host}:{self.port}"

    async def start(self, port: int = 0) -> "KvDataPlaneServer":
        self._server = await asyncio.start_server(self._on_conn, self.host, port)
        self.port = self._server.sockets[0].getsockname()[1]
        log.info("kv data plane listening on %s", self.address)
        return self

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            # close accepted connections BEFORE wait_closed(): on 3.12+ it
            # blocks until every connection handler returns, and prefill-side
            # pooled senders hold their sockets open indefinitely
            for w in list(self._writers):
                w.close()
            await self._server.wait_closed()
        for pend in self._expected.values():
            if pend.fut.done():
                if not pend.fut.cancelled():
                    pend.fut.exception()  # mark retrieved
            else:
                pend.fut.cancel()
        self._expected.clear()

    # ---------------- consumer API ----------------

    def expect(self, request_id: str) -> str:
        """Register interest BEFORE the remote prefill is requested, so an
        early-arriving payload parks instead of being dropped. Returns the
        per-request nonce the sender must echo in its part headers."""
        pend = self._expected.get(request_id)
        if pend is None:
            pend = _Pending(
                fut=asyncio.get_running_loop().create_future(),
                token=secrets.token_hex(16),
            )
            self._expected[request_id] = pend
        return pend.token

    def set_consumer(self, request_id: str, consumer: Callable[[KvPart], None]) -> None:
        """Attach an incremental per-part consumer (called on the server's
        event loop as each part lands); parts that arrived before attachment
        are flushed to it immediately, in seq order. With a consumer the
        ``receive()`` future resolves to None — the parts were already handed
        over, the future is purely the all-parts-arrived completion gate. A
        transfer that completed before attachment keeps its assembled-array
        result (``receive()`` returns it; the consumer is never called)."""
        pend = self._expected.get(request_id)
        if pend is None:
            raise RuntimeError(f"set_consumer() without expect() for {request_id}")
        if pend.fut.done():
            return
        pend.consumer = consumer
        for seq in sorted(pend.parts):
            if not self._feed(request_id, pend, pend.parts[seq]):
                break
        pend.parts.clear()

    async def receive(self, request_id: str, timeout: float = 120.0):
        """Await transfer completion. Returns the (re)assembled host array,
        or None when an incremental consumer already took the parts."""
        pend = self._expected.get(request_id)
        if pend is None:
            raise RuntimeError(f"receive() without expect() for {request_id}")
        try:
            return await asyncio.wait_for(pend.fut, timeout)
        finally:
            self._expected.pop(request_id, None)

    def abandon(self, request_id: str) -> None:
        """Cancellation: stop waiting; a late payload is received and dropped."""
        pend = self._expected.pop(request_id, None)
        if pend is None:
            return
        if pend.fut.done():
            if not pend.fut.cancelled():
                pend.fut.exception()  # mark retrieved (checksum-failed transfers)
        else:
            pend.fut.cancel()

    # ---------------- wire ----------------

    def _fail(self, pend: _Pending, exc: Exception) -> None:
        pend.parts.clear()
        if not pend.fut.done():
            pend.fut.set_exception(exc)

    def _feed(self, rid: str, pend: _Pending, part: KvPart) -> bool:
        try:
            pend.consumer(part)
            return True
        except Exception as e:
            log.exception("kv part consumer failed for %s", rid)
            self._fail(pend, e)
            return False

    def _assemble(self, pend: _Pending):
        parts = [pend.parts[seq] for seq in sorted(pend.parts)]
        if len(parts) == 1:
            return parts[0].wire_data()
        axis = parts[0].cat_axis
        if parts[0].scales is not None:
            return {
                "q": np.concatenate([p.data for p in parts], axis=axis),
                "s": np.concatenate([p.scales for p in parts], axis=axis),
            }
        return np.concatenate([p.data for p in parts], axis=axis)

    async def _on_conn(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter):
        peer = writer.get_extra_info("peername")
        self._writers.add(writer)
        try:
            while True:
                raw = await reader.readexactly(_LEN.size)
                (hlen,) = _LEN.unpack(raw)
                if hlen > MAX_HEADER:
                    raise ValueError(f"kv header too large: {hlen}")
                header = msgpack.unpackb(await reader.readexactly(hlen))
                from dynamo_tpu.llm.remote_prefill import _np_dtype

                dtype = _np_dtype(header["dtype"])  # handles bfloat16 et al
                shape = tuple(header["shape"])
                nbytes = dtype.itemsize * int(np.prod(shape))
                payload = await reader.readexactly(nbytes)
                rid = header["request_id"]
                seq = int(header.get("part_seq", 0))
                pend = self._expected.get(rid)
                if xxhash.xxh3_64_intdigest(payload) != header["xxh3"]:
                    # the length prefix still framed this payload correctly,
                    # so only the offending transfer dies — unrelated
                    # transfers sharing the connection keep flowing
                    self.checksum_failures += 1
                    log.warning("kv payload checksum mismatch for %s part %d", rid, seq)
                    if pend is not None:
                        self._fail(pend, RuntimeError(
                            f"kv payload checksum mismatch for {rid}"
                        ))
                    continue
                if pend is None or pend.fut.done():
                    self.dropped += 1
                    log.debug("dropping unexpected kv payload for %s", rid)
                    continue
                if header.get("token") != pend.token:
                    # wrong/missing nonce: never fulfil the future from an
                    # unauthenticated peer (checksum is sender-supplied).
                    # Enforcement is unconditional: tokenless senders must run
                    # the same protocol version (no mixed-version rollout)
                    self.rejected += 1
                    log.warning("rejecting kv payload with bad token for %s", rid)
                    continue
                if seq in pend.received:
                    self.dropped += 1
                    log.debug("dropping duplicate kv part %d for %s", seq, rid)
                    continue
                scales = None
                if header.get("scales") is not None:
                    scales = np.frombuffer(
                        header["scales"], _np_dtype(header["scales_dtype"])
                    ).reshape(tuple(header["scales_shape"]))
                part = KvPart(
                    seq=seq,
                    total=max(1, int(header.get("part_total", 1))),
                    page_from=int(header.get("page_from", -1)),
                    page_to=int(header.get("page_to", -1)),
                    cat_axis=int(header.get("cat_axis", 2)),
                    data=np.frombuffer(payload, dtype).reshape(shape),
                    scales=scales,
                )
                pend.received.add(seq)
                pend.total = max(pend.total, part.total)
                self.parts_received += 1
                self.bytes_received += nbytes
                self.part_bytes_hist.observe(float(nbytes))
                if pend.consumer is not None:
                    if not self._feed(rid, pend, part):
                        continue
                else:
                    pend.parts[seq] = part
                if len(pend.received) >= pend.total and not pend.fut.done():
                    pend.fut.set_result(
                        None if pend.consumer is not None else self._assemble(pend)
                    )
                    pend.parts.clear()
                    self.received += 1
        except (asyncio.IncompleteReadError, ConnectionResetError):
            pass
        except Exception:
            log.exception("kv data plane connection from %s failed", peer)
        finally:
            self._writers.discard(writer)
            writer.close()

    # ---------------- metrics ----------------

    def render_metrics(self) -> str:
        """Prometheus exposition for the receive side of the stream."""
        out = [
            render_family(
                "dynamo_kv_stream_transfers_received_total", "counter",
                "completed KV transfers (all parts arrived)",
                [({}, self.received)],
            ),
            render_family(
                "dynamo_kv_stream_parts_received_total", "counter",
                "KV parts accepted off the data plane",
                [({}, self.parts_received)],
            ),
            render_family(
                "dynamo_kv_stream_bytes_received_total", "counter",
                "KV payload bytes accepted off the data plane",
                [({}, self.bytes_received)],
            ),
            render_family(
                "dynamo_kv_stream_rejected_total", "counter",
                "KV payloads rejected for a bad/missing nonce",
                [({}, self.rejected)],
            ),
            render_family(
                "dynamo_kv_stream_dropped_total", "counter",
                "unexpected or duplicate KV payloads received and dropped",
                [({}, self.dropped)],
            ),
            render_family(
                "dynamo_kv_stream_checksum_failures_total", "counter",
                "KV payloads failing the per-part xxh3 check",
                [({}, self.checksum_failures)],
            ),
            self.part_bytes_hist.render(),
        ]
        return "".join(out)


class KvDataPlaneClient:
    """Prefill-side sender: N parallel lanes per destination, parts striped
    round-robin across them.

    Reconnects use bounded exponential backoff with jitter: a restarting
    receiver briefly refuses connections, and the old immediate-retry
    behavior either lost the frame (second attempt also refused) or — at
    fleet scale — hammered the recovering peer with synchronized retries.
    Each reconnect counts into ``dynamo_kv_stream_reconnects_total``."""

    #: reconnect backoff envelope: base * 2^attempt, jittered to [0.5, 1.0]x,
    #: capped — worst case ~0.35 s of extra latency across all retries
    BACKOFF_BASE_S = 0.05
    BACKOFF_MAX_S = 1.0
    MAX_ATTEMPTS = 3

    def __init__(self, lanes: int = 1):
        self.lanes = max(1, int(lanes))
        self._conns: dict[tuple, tuple[asyncio.StreamReader, asyncio.StreamWriter]] = {}
        self._locks: dict[tuple, asyncio.Lock] = {}
        self._rr: dict[str, int] = {}
        self.sent = 0  # payload frames written (every part counts)
        self.bytes_sent = 0
        self.reconnects = 0  # lane re-opens after a stale/refused socket

    async def send(
        self, address: str, request_id: str, array, token: str = "",
        page_from: int = -1, page_to: int = -1, cat_axis: int = 2,
    ) -> None:
        """Monolithic (single-part) transfer — a v2 frame with part_total=1.
        ``array`` may be the int8 {"q","s"} wire dict (quant/kv.py)."""
        await self.send_part(
            address, request_id, array, token=token,
            part_seq=0, part_total=1,
            page_from=page_from, page_to=page_to, cat_axis=cat_axis,
        )

    async def send_part(
        self, address: str, request_id: str, array, token: str = "",
        part_seq: int = 0, part_total: int = 1,
        page_from: int = -1, page_to: int = -1, cat_axis: int = 2,
        scales: np.ndarray | None = None,
    ) -> None:
        from dynamo_tpu.disagg.faults import active_plan

        plan = active_plan()
        if plan is not None:
            delay = plan.delay_s("push")
            if delay > 0:
                await asyncio.sleep(delay)
            if plan.should_drop("push"):
                # injected part loss: the receiver's transfer stays
                # incomplete and ITS timeout/fallback path must fire
                log.warning("fault: dropping push part %d for %s", part_seq, request_id)
                return
        if isinstance(array, dict):  # int8 wire dict: q = payload, s = header
            scales = array["s"] if scales is None else scales
            array = array["q"]
        # zero-copy payload: write a memoryview of the contiguous array
        # (KV parts are tens of MB; bytes-concatenation would copy them
        # again and stall the event loop)
        arr = np.ascontiguousarray(array)
        payload = memoryview(arr.view(np.uint8).reshape(-1))
        # hash BEFORE taking the lane lock: xxh3 over a multi-MB part blocks
        # the event loop either way, but must never extend the window in
        # which every other sender to this lane is stalled behind us —
        # per-part hashing also bounds each stall to one part, not one prompt
        digest = xxhash.xxh3_64_intdigest(payload)
        if plan is not None and plan.should_corrupt("push"):
            digest = (digest ^ 1) & 0xFFFFFFFFFFFFFFFF
        fields = {
            "request_id": request_id,
            "shape": list(array.shape),
            "dtype": str(array.dtype),
            "xxh3": digest,
            "token": token,
            "part_seq": part_seq,
            "part_total": part_total,
            "page_from": page_from,
            "page_to": page_to,
            "cat_axis": cat_axis,
        }
        if scales is not None:
            # int8 transfers: the per-page scale plane rides in the header
            # (~page_size f32 per page — noise next to the int8 payload,
            # which itself is HALF the bf16 wire bytes)
            s = np.ascontiguousarray(scales)
            fields["scales"] = s.tobytes()
            fields["scales_shape"] = list(s.shape)
            fields["scales_dtype"] = str(s.dtype)
        header = msgpack.packb(fields)
        lane = self._rr.get(address, 0) % self.lanes
        self._rr[address] = lane + 1
        key = (address, lane)
        lock = self._locks.setdefault(key, asyncio.Lock())
        async with lock:  # one in-flight frame per lane
            for attempt in range(self.MAX_ATTEMPTS):
                try:
                    conn = self._conns.get(key)
                    if conn is not None and (conn[0].at_eof() or conn[1].is_closing()):
                        # peer already hung up (server restart): a write here
                        # would be silently buffered into a dead socket —
                        # detect it up front instead of losing the frame
                        conn[1].close()
                        self._conns.pop(key, None)
                        self.reconnects += 1
                        conn = None
                    if conn is None:
                        host, _, port = address.rpartition(":")
                        conn = await asyncio.open_connection(host, int(port))
                        self._conns[key] = conn
                    _, writer = conn
                    writer.write(_LEN.pack(len(header)))
                    writer.write(header)
                    writer.write(payload)
                    await writer.drain()
                    self.sent += 1
                    self.bytes_sent += payload.nbytes
                    return
                except (ConnectionError, OSError):
                    stale = self._conns.pop(key, None)
                    if stale is not None:
                        # close the dead transport before retrying — popping
                        # alone leaks the socket fd until GC
                        stale[1].close()
                    if attempt == self.MAX_ATTEMPTS - 1:
                        raise
                    # bounded exponential backoff with jitter before the
                    # reconnect: a recovering receiver must not eat a
                    # synchronized immediate-retry stampede, and the jitter
                    # ([0.5, 1.0]x) decorrelates lanes that failed together
                    delay = min(self.BACKOFF_MAX_S,
                                self.BACKOFF_BASE_S * (1 << attempt))
                    delay *= 0.5 + 0.5 * random.random()
                    self.reconnects += 1
                    await asyncio.sleep(delay)

    async def close(self) -> None:
        for _, writer in self._conns.values():
            writer.close()
        self._conns.clear()

    def render_metrics(self) -> str:
        return "".join([
            render_family(
                "dynamo_kv_stream_parts_sent_total", "counter",
                "KV payload frames written to the data plane",
                [({}, self.sent)],
            ),
            render_family(
                "dynamo_kv_stream_bytes_sent_total", "counter",
                "KV payload bytes written to the data plane",
                [({}, self.bytes_sent)],
            ),
            render_family(
                "dynamo_kv_stream_lanes", "gauge",
                "parallel data-plane connections per destination",
                [({}, self.lanes)],
            ),
            render_family(
                "dynamo_kv_stream_reconnects_total", "counter",
                "data-plane lane re-opens after a stale or refused socket "
                "(each retried with bounded exponential backoff + jitter)",
                [({}, self.reconnects)],
            ),
        ])
