"""Dedicated KV-block data plane for cross-process disaggregation.

The reference moves KV blocks between engine processes with NIXL RDMA WRITEs
plus completion notifications, off the control plane (reference: container/
deps/vllm/vllm_v0.7.2-dynamo-kv-disagg-patch.patch ``nixl.py`` —
``read_blocks``/``get_notifs``; docs/disagg_serving.md:83 non-blocking
property). The TPU-native analogue: bulk KV bytes ride a dedicated TCP
socket between the prefill and decode processes — never inside the
control-plane response message — and land in a per-request mailbox whose
future IS the completion notification. On-pod (same-process) transfers keep
using the device-array hub (dynamo_tpu/disagg/ici.py); this module is the
cross-process / cross-host path.

Wire format per transfer (one stream, sequential transfers per connection):

    u32 header_len | msgpack header | payload bytes

    header = {request_id, shape, dtype, xxh3}  (xxh3 of the payload)

The server never blocks the sender on the consumer: payloads for requests
nobody expects (cancelled, duplicate) are received and dropped.
"""

from __future__ import annotations

import asyncio
import struct
from typing import Optional

import msgpack
import numpy as np
import xxhash

from dynamo_tpu.utils import get_logger

log = get_logger("disagg.dataplane")

_LEN = struct.Struct("<I")
MAX_HEADER = 1 << 20


class KvDataPlaneServer:
    """Decode-side listener: framed KV payloads -> per-request futures."""

    def __init__(self, host: str = "0.0.0.0", advertise_host: Optional[str] = None):
        self.host = host
        self.advertise_host = advertise_host
        self.port: Optional[int] = None
        self._server: Optional[asyncio.AbstractServer] = None
        self._expected: dict[str, asyncio.Future] = {}
        # per-request nonces: a payload must carry the token expect() minted
        # (travels to the prefill side inside RemotePrefillRequest), so a
        # peer that guesses an in-flight request_id can't poison the cache
        self._tokens: dict[str, str] = {}
        self._writers: set[asyncio.StreamWriter] = set()
        self.received = 0
        self.dropped = 0
        self.rejected = 0  # bad/missing token

    @property
    def address(self) -> str:
        host = self.advertise_host
        if host is None:
            if self.host in ("0.0.0.0", "::"):
                # wildcard bind: advertise a cross-host-reachable name (same
                # policy as the response plane, runtime/tcp.py)
                import socket

                host = socket.gethostname()
            else:
                host = self.host
        return f"{host}:{self.port}"

    async def start(self, port: int = 0) -> "KvDataPlaneServer":
        self._server = await asyncio.start_server(self._on_conn, self.host, port)
        self.port = self._server.sockets[0].getsockname()[1]
        log.info("kv data plane listening on %s", self.address)
        return self

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            # close accepted connections BEFORE wait_closed(): on 3.12+ it
            # blocks until every connection handler returns, and prefill-side
            # pooled senders hold their sockets open indefinitely
            for w in list(self._writers):
                w.close()
            await self._server.wait_closed()
        for fut in self._expected.values():
            if not fut.done():
                fut.cancel()
        self._expected.clear()

    # ---------------- consumer API ----------------

    def expect(self, request_id: str) -> str:
        """Register interest BEFORE the remote prefill is requested, so an
        early-arriving payload parks instead of being dropped. Returns the
        per-request nonce the sender must echo in its payload header."""
        if request_id not in self._expected:
            import secrets

            self._expected[request_id] = asyncio.get_running_loop().create_future()
            self._tokens[request_id] = secrets.token_hex(16)
        return self._tokens[request_id]

    async def receive(self, request_id: str, timeout: float = 120.0) -> np.ndarray:
        fut = self._expected.get(request_id)
        if fut is None:
            raise RuntimeError(f"receive() without expect() for {request_id}")
        try:
            return await asyncio.wait_for(fut, timeout)
        finally:
            self._expected.pop(request_id, None)
            self._tokens.pop(request_id, None)

    def abandon(self, request_id: str) -> None:
        """Cancellation: stop waiting; a late payload is received and dropped."""
        fut = self._expected.pop(request_id, None)
        self._tokens.pop(request_id, None)
        if fut is not None and not fut.done():
            fut.cancel()

    # ---------------- wire ----------------

    async def _on_conn(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter):
        peer = writer.get_extra_info("peername")
        self._writers.add(writer)
        try:
            while True:
                raw = await reader.readexactly(_LEN.size)
                (hlen,) = _LEN.unpack(raw)
                if hlen > MAX_HEADER:
                    raise ValueError(f"kv header too large: {hlen}")
                header = msgpack.unpackb(await reader.readexactly(hlen))
                from dynamo_tpu.llm.remote_prefill import _np_dtype

                dtype = _np_dtype(header["dtype"])  # handles bfloat16 et al
                shape = tuple(header["shape"])
                nbytes = dtype.itemsize * int(np.prod(shape))
                payload = await reader.readexactly(nbytes)
                if xxhash.xxh3_64_intdigest(payload) != header["xxh3"]:
                    raise ValueError("kv payload checksum mismatch")
                rid = header["request_id"]
                fut = self._expected.get(rid)
                want = self._tokens.get(rid)
                if fut is not None and want is not None and header.get("token") != want:
                    # wrong/missing nonce: never fulfil the future from an
                    # unauthenticated peer (checksum is sender-supplied).
                    # Enforcement is unconditional: tokenless senders (pre-nonce
                    # peers) are rejected — both sides of a disagg pair must run
                    # the same protocol version (no mixed-version rollout)
                    self.rejected += 1
                    log.warning("rejecting kv payload with bad token for %s", rid)
                elif fut is not None and not fut.done():
                    fut.set_result(np.frombuffer(payload, dtype).reshape(shape))
                    self.received += 1
                else:
                    self.dropped += 1
                    log.debug("dropping unexpected kv payload for %s", rid)
        except (asyncio.IncompleteReadError, ConnectionResetError):
            pass
        except Exception:
            log.exception("kv data plane connection from %s failed", peer)
        finally:
            self._writers.discard(writer)
            writer.close()


class KvDataPlaneClient:
    """Prefill-side sender with pooled connections per destination."""

    def __init__(self):
        self._conns: dict[str, tuple[asyncio.StreamReader, asyncio.StreamWriter]] = {}
        self._locks: dict[str, asyncio.Lock] = {}
        self.sent = 0

    async def send(
        self, address: str, request_id: str, array: np.ndarray, token: str = ""
    ) -> None:
        lock = self._locks.setdefault(address, asyncio.Lock())
        async with lock:  # one in-flight transfer per destination connection
            # zero-copy payload: write a memoryview of the contiguous array
            # (KV payloads are tens of MB; bytes-concatenation would copy them
            # again and stall the event loop)
            arr = np.ascontiguousarray(array)
            payload = memoryview(arr.view(np.uint8).reshape(-1))
            header = msgpack.packb(
                {
                    "request_id": request_id,
                    "shape": list(array.shape),
                    "dtype": str(array.dtype),
                    "xxh3": xxhash.xxh3_64_intdigest(payload),
                    "token": token,
                }
            )
            for attempt in (0, 1):  # one reconnect on a stale pooled socket
                try:
                    conn = self._conns.get(address)
                    if conn is None:
                        host, _, port = address.rpartition(":")
                        conn = await asyncio.open_connection(host, int(port))
                        self._conns[address] = conn
                    _, writer = conn
                    writer.write(_LEN.pack(len(header)))
                    writer.write(header)
                    writer.write(payload)
                    await writer.drain()
                    self.sent += 1
                    return
                except (ConnectionError, OSError):
                    self._conns.pop(address, None)
                    if attempt:
                        raise

    async def close(self) -> None:
        for _, writer in self._conns.values():
            writer.close()
        self._conns.clear()
