"""Prefill worker: consumes the remote-prefill work queue, runs prefill on its
engine, and pushes KV + first token to the decode worker.

Mirrors the reference prefill worker loop (reference: examples/llm/components/
prefill_worker.py:84-137 prefill_queue_handler).
"""

from __future__ import annotations

import asyncio
from typing import Optional

from dynamo_tpu.engine.engine import AsyncJaxEngine
from dynamo_tpu.llm.remote_prefill import RemotePrefillRequest, prefill_queue_name
from dynamo_tpu.runtime.context import RequestContext, use_context
from dynamo_tpu.utils import get_logger, tracing

log = get_logger("disagg.prefill")


class PrefillWorker:
    def __init__(
        self,
        engine: AsyncJaxEngine,
        drt,
        namespace: str,
        model: str,
    ):
        self.engine = engine
        self.drt = drt
        self.namespace = namespace
        self.model = model
        self.queue_name = prefill_queue_name(namespace, model)
        self._task: Optional[asyncio.Task] = None
        self._clients: dict[str, object] = {}
        self.completed = 0
        from dynamo_tpu.disagg.dataplane import KvDataPlaneClient

        self.kv_client = KvDataPlaneClient()

    async def start(self) -> "PrefillWorker":
        self._task = asyncio.create_task(self._loop())
        return self

    async def stop(self) -> None:
        if self._task:
            self._task.cancel()
        await self.kv_client.close()

    async def _client_for(self, endpoint: str):
        client = self._clients.get(endpoint)
        if client is None:
            client = await self.drt.endpoint_client(endpoint)
            await client.wait_for_instances(timeout=10)
            self._clients[endpoint] = client
        return client

    async def _loop(self) -> None:
        log.info("prefill worker consuming %s", self.queue_name)
        try:
            while True:
                try:
                    msg = await self.drt.cplane.queue_pull(self.queue_name)
                except ConnectionError:
                    if getattr(self.drt.cplane, "_dead", False):
                        # reconnect window exhausted: the broker is gone for
                        # good — die loudly, don't impersonate a live consumer
                        log.error(
                            "control plane is dead; prefill consumer for %s exiting",
                            self.queue_name,
                        )
                        return
                    # broker blip: the parked pull died with the connection;
                    # the cplane client heals in the background — keep
                    # re-arming the pull instead of letting the consumer die
                    # (the queue is durable, work survives the restart)
                    log.warning("queue pull lost connection; re-arming %s", self.queue_name)
                    await asyncio.sleep(0.5)
                    continue
                try:
                    await self._handle(RemotePrefillRequest.from_wire(msg.payload))
                    await self.drt.cplane.queue_ack(self.queue_name, msg.msg_id)
                    self.completed += 1
                except Exception:
                    log.exception("remote prefill failed; nacking")
                    try:
                        await self.drt.cplane.queue_nack(self.queue_name, msg.msg_id)
                    except Exception:
                        pass
        except asyncio.CancelledError:
            pass

    async def _handle(self, rp: RemotePrefillRequest) -> None:
        # the work queue bypasses the RPC envelope's context propagation, so
        # re-enter the request context from the message itself: logs stamp the
        # originating request id and spans land on the edge-stamped trace
        ctx = RequestContext(
            request_id=rp.request_id,
            metadata={"trace_id": rp.trace_id} if rp.trace_id else {},
        )
        with use_context(ctx):
            with tracing.span(
                "disagg.prefill", prompt_len=len(rp.token_ids),
                decode_worker=f"{rp.decode_worker_id:x}",
            ):
                await self._handle_traced(rp)

    async def _handle_traced(self, rp: RemotePrefillRequest) -> None:
        from dynamo_tpu.disagg import ici

        # same-process decode worker? hand the KV off as a device array (ICI
        # path: blocks reshard onto the decode mesh without touching host
        # memory). Cross-process with a kv_addr: bulk bytes ride the dedicated
        # data-plane socket and the control message is the completion
        # notification. Neither: legacy inline bytes in the result.
        device = ici.is_local(rp.decode_worker_id)
        mode = "ici" if device else ("socket" if rp.kv_addr else "inline")
        tkey = ici.transfer_key(rp.decode_worker_id, rp.request_id) if device else ""
        if tkey:
            # a redelivered message must not be swallowed by a tombstone a
            # cancelled earlier attempt (possibly a colocated sibling worker)
            # left behind
            ici.clear_tombstone(tkey)
        result = None
        delivered = False
        try:
            result, host_data = await self.engine.run_on_engine(
                lambda: self.engine.sync_remote_prefill(rp, mode=mode)
            )
            client = await self._client_for(rp.decode_endpoint)

            async def deliver():
                # deliver directly to the requesting decode worker (the
                # RDMA-WRITE + notify analogue)
                stream = await client.direct(result.to_wire(), rp.decode_worker_id)
                async for ack in stream:
                    if not ack.get("ok"):
                        # permanent rejection (request cancelled/unknown on
                        # the decode side): drop the work — nacking would
                        # redeliver a poisoned message forever
                        log.warning(
                            "decode worker rejected prefill result for %s: %s",
                            rp.request_id, ack,
                        )
                        return False
                return True

            if host_data is not None:
                # payload BEFORE notification: a delivered result then implies
                # the payload is on the wire, so a socket failure surfaces
                # here (-> nack + redelivery) instead of stranding the decode
                # side in a full receive() timeout after a notification whose
                # payload will never arrive
                with tracing.span(
                    "disagg.kv_send", bytes=int(host_data.nbytes), mode="socket"
                ):
                    await self.kv_client.send(
                        rp.kv_addr, rp.request_id, host_data, token=rp.kv_token
                    )
            ok = await deliver()
            if not ok:
                return
            delivered = True
        except BaseException:
            if tkey and result is None:
                # cancelled (or failed) while the engine thread may still be
                # producing: the park could land after us, so tombstone it.
                # An ordinary exception from sync_remote_prefill means nothing
                # was parked and the tombstone is TTL-pruned harmlessly.
                ici.discard_transfer(tkey)
            raise
        finally:
            if not delivered and result is not None and result.kv_transfer_id:
                # park happened but delivery/ack failed: drop the real array
                ici.pop_transfer(result.kv_transfer_id)
