"""Prefill worker: consumes the remote-prefill work queue, runs prefill on its
engine, and pushes KV + first token to the decode worker.

Mirrors the reference prefill worker loop (reference: examples/llm/components/
prefill_worker.py:84-137 prefill_queue_handler). Cross-process KV rides the
dedicated data plane; with streaming enabled (EngineConfig.kv_stream, the
default) each prefill chunk's finalized pages are staged to host and put on
the wire while the next chunk computes — so by the time the completion
notification lands on the decode worker most KV bytes are already there.
"""

from __future__ import annotations

import asyncio
import time
from typing import Optional

from dynamo_tpu.engine.engine import AsyncJaxEngine
from dynamo_tpu.llm.remote_prefill import RemotePrefillRequest, prefill_queue_name
from dynamo_tpu.runtime.context import RequestContext, use_context
from dynamo_tpu.utils import get_logger, tracing
from dynamo_tpu.utils.prometheus import render_family

log = get_logger("disagg.prefill")


class PrefillWorker:
    def __init__(
        self,
        engine: AsyncJaxEngine,
        drt,
        namespace: str,
        model: str,
        kv_stream: Optional[bool] = None,
        kv_stream_lanes: Optional[int] = None,
    ):
        self.engine = engine
        self.drt = drt
        self.namespace = namespace
        self.model = model
        self.queue_name = prefill_queue_name(namespace, model)
        self._task: Optional[asyncio.Task] = None
        self._clients: dict[str, object] = {}
        self.completed = 0
        cfg = getattr(engine, "config", None)
        if kv_stream is None:
            kv_stream = getattr(cfg, "kv_stream", True)
        if kv_stream_lanes is None:
            kv_stream_lanes = getattr(cfg, "kv_stream_lanes", 2)
        self.kv_stream = bool(kv_stream)
        from dynamo_tpu.disagg.dataplane import KvDataPlaneClient

        self.kv_client = KvDataPlaneClient(lanes=max(1, int(kv_stream_lanes or 1)))
        # streamed-transfer observability: wall seconds a part spent on the
        # wire (D2H complete -> drain), and the portion of that which
        # overlapped the request's remaining prefill compute — the pipelining
        # win the streamed protocol exists for
        self.stream_requests = 0
        self.stream_parts = 0
        self.stream_bytes = 0
        self.stream_send_s = 0.0
        self.stream_overlap_s = 0.0

    async def start(self) -> "PrefillWorker":
        self._task = asyncio.create_task(self._loop())
        return self

    async def stop(self) -> None:
        if self._task:
            self._task.cancel()
        await self.kv_client.close()

    async def _client_for(self, endpoint: str):
        client = self._clients.get(endpoint)
        if client is None:
            client = await self.drt.endpoint_client(endpoint)
            await client.wait_for_instances(timeout=10)
            self._clients[endpoint] = client
        return client

    async def _loop(self) -> None:
        log.info("prefill worker consuming %s", self.queue_name)
        try:
            while True:
                try:
                    msg = await self.drt.cplane.queue_pull(self.queue_name)
                except ConnectionError:
                    if getattr(self.drt.cplane, "_dead", False):
                        # reconnect window exhausted: the broker is gone for
                        # good — die loudly, don't impersonate a live consumer
                        log.error(
                            "control plane is dead; prefill consumer for %s exiting",
                            self.queue_name,
                        )
                        return
                    # broker blip: the parked pull died with the connection;
                    # the cplane client heals in the background — keep
                    # re-arming the pull instead of letting the consumer die
                    # (the queue is durable, work survives the restart)
                    log.warning("queue pull lost connection; re-arming %s", self.queue_name)
                    await asyncio.sleep(0.5)
                    continue
                try:
                    await self._handle(RemotePrefillRequest.from_wire(msg.payload))
                    await self.drt.cplane.queue_ack(self.queue_name, msg.msg_id)
                    self.completed += 1
                except Exception:
                    log.exception("remote prefill failed; nacking")
                    try:
                        await self.drt.cplane.queue_nack(self.queue_name, msg.msg_id)
                    except Exception:
                        pass
        except asyncio.CancelledError:
            pass

    async def _handle(self, rp: RemotePrefillRequest) -> None:
        # the work queue bypasses the RPC envelope's context propagation, so
        # re-enter the request context from the message itself: logs stamp the
        # originating request id and spans land on the edge-stamped trace
        ctx = RequestContext(
            request_id=rp.request_id,
            metadata={"trace_id": rp.trace_id} if rp.trace_id else {},
        )
        with use_context(ctx):
            with tracing.span(
                "disagg.prefill", prompt_len=len(rp.token_ids),
                decode_worker=f"{rp.decode_worker_id:x}",
            ):
                await self._handle_traced(rp)

    async def _handle_traced(self, rp: RemotePrefillRequest) -> None:
        from dynamo_tpu.disagg import ici

        # same-process decode worker? hand the KV off as a device array (ICI
        # path: blocks reshard onto the decode mesh without touching host
        # memory). Cross-process with a kv_addr: bulk bytes ride the dedicated
        # data-plane socket and the control message is the completion
        # notification. Neither: legacy inline bytes in the result.
        device = ici.is_local(rp.decode_worker_id)
        mode = "ici" if device else ("socket" if rp.kv_addr else "inline")
        stream = mode == "socket" and self.kv_stream
        tkey = ici.transfer_key(rp.decode_worker_id, rp.request_id) if device else ""
        if tkey:
            # a redelivered message must not be swallowed by a tombstone a
            # cancelled earlier attempt (possibly a colocated sibling worker)
            # left behind
            ici.clear_tombstone(tkey)
        result = None
        delivered = False
        send_tasks: list[asyncio.Task] = []
        loop = asyncio.get_running_loop()
        cat_axis = getattr(self.engine.runner.model, "wire_n_axis", 2)

        async def _ship(seq: int, total: int, pf: int, pt: int, d2h_fut):
            from dynamo_tpu.quant.kv import wire_nbytes

            arr = await asyncio.wrap_future(d2h_fut)  # D2H staged off-thread
            t0 = time.monotonic()
            # int8 caches stage the {"q","s"} wire dict: the int8 payload is
            # half the bf16 bytes and the scale plane rides the part header
            await self.kv_client.send_part(
                rp.kv_addr, rp.request_id, arr, token=rp.kv_token,
                part_seq=seq, part_total=total,
                page_from=pf, page_to=pt, cat_axis=cat_axis,
            )
            return t0, time.monotonic(), wire_nbytes(arr)

        def on_part(seq, total, pf, pt, d2h_fut):
            # engine thread -> event loop; tasks created in emission order so
            # the send_tasks list is complete before run_on_engine resolves
            # (both ride call_soon_threadsafe on the same loop, FIFO)
            loop.call_soon_threadsafe(
                lambda: send_tasks.append(
                    asyncio.create_task(_ship(seq, total, pf, pt, d2h_fut))
                )
            )

        try:
            result, host_data = await self.engine.run_on_engine(
                lambda: self.engine.sync_remote_prefill(
                    rp, mode=mode, on_part=on_part if stream else None
                )
            )
            t_compute_end = time.monotonic()
            client = await self._client_for(rp.decode_endpoint)

            async def deliver():
                # deliver directly to the requesting decode worker (the
                # RDMA-WRITE + notify analogue)
                stream_out = await client.direct(result.to_wire(), rp.decode_worker_id)
                async for ack in stream_out:
                    if not ack.get("ok"):
                        # permanent rejection (request cancelled/unknown on
                        # the decode side): drop the work — nacking would
                        # redeliver a poisoned message forever
                        log.warning(
                            "decode worker rejected prefill result for %s: %s",
                            rp.request_id, ack,
                        )
                        return False
                return True

            # every payload part BEFORE the notification: a delivered result
            # then implies the payload is on the wire, so a socket failure
            # surfaces here (-> nack + redelivery) instead of stranding the
            # decode side in a full receive() timeout after a notification
            # whose payload will never arrive
            if send_tasks:
                with tracing.span(
                    "disagg.kv_stream", parts=len(send_tasks), mode="socket"
                ):
                    spans = await asyncio.gather(*send_tasks)
                send_s = sum(t1 - t0 for t0, t1, _ in spans)
                overlap = sum(
                    max(0.0, min(t1, t_compute_end) - t0) for t0, t1, _ in spans
                )
                self.stream_requests += 1
                self.stream_parts += len(spans)
                self.stream_bytes += sum(b for _, _, b in spans)
                self.stream_send_s += send_s
                self.stream_overlap_s += overlap
            if host_data is not None:
                from dynamo_tpu.quant.kv import wire_nbytes

                ps = self.engine.config.page_size
                with tracing.span(
                    "disagg.kv_send", bytes=wire_nbytes(host_data), mode="socket"
                ):
                    await self.kv_client.send(
                        rp.kv_addr, rp.request_id, host_data, token=rp.kv_token,
                        page_from=result.skip_leading_tokens // ps,
                        page_to=-(-result.prompt_len // ps),
                        cat_axis=cat_axis,
                    )
            ok = await deliver()
            if not ok:
                return
            delivered = True
        except BaseException:
            if tkey and result is None:
                # cancelled (or failed) while the engine thread may still be
                # producing: the park could land after us, so tombstone it.
                # An ordinary exception from sync_remote_prefill means nothing
                # was parked and the tombstone is TTL-pruned harmlessly.
                ici.discard_transfer(tkey)
            raise
        finally:
            if send_tasks and not delivered:
                # a failed/cancelled request must not leave part sends (or
                # their D2H waits) dangling into the next queue item
                for t in send_tasks:
                    t.cancel()
                await asyncio.gather(*send_tasks, return_exceptions=True)
            if not delivered and result is not None and result.kv_transfer_id:
                # park happened but delivery/ack failed: drop the real array
                ici.pop_transfer(result.kv_transfer_id)

    def render_metrics(self) -> str:
        """Prometheus exposition for the send side of the KV stream: the
        client frame/byte/lane counters plus the measured compute/transfer
        overlap the chunk pipelining buys."""
        return self.kv_client.render_metrics() + "".join([
            render_family(
                "dynamo_kv_stream_requests_total", "counter",
                "remote prefills whose KV was chunk-streamed",
                [({}, self.stream_requests)],
            ),
            render_family(
                "dynamo_kv_stream_send_seconds_total", "counter",
                "wall seconds KV parts spent on the wire (D2H done -> drained)",
                [({}, round(self.stream_send_s, 6))],
            ),
            render_family(
                "dynamo_kv_stream_overlap_seconds_total", "counter",
                "portion of part send seconds overlapped with prefill compute",
                [({}, round(self.stream_overlap_s, 6))],
            ),
        ])
