"""Same-pod (ICI) KV transfer for disaggregated prefill/decode.

When the prefill and decode engines share a process (one TPU pod host serving
both roles on different mesh slices, or colocated workers), KV blocks never
need to touch host memory or the network data plane: the prefill side gathers
the blocks into a device array (ModelRunner.extract_pages_device) and the
decode side reshards it onto its own mesh with jax.device_put — on multi-chip
hardware that transfer rides the inter-chip interconnect (ICI), the analogue
of the reference's NIXL RDMA WRITE between GPUs (reference: patch
vllm/distributed/device_communicators/nixl.py). The control message
(PrefillResult) still travels the normal response plane; only the bulk KV
payload is handed off in-process.

The hub is a process-local registry: decode engines register under their
worker id; a prefill worker that finds its target here uses the device path
and parks the gathered array under the request id until the decode side
adopts it.
"""

from __future__ import annotations

import threading

import time

_lock = threading.Lock()
_local_workers: set[int] = set()  # decode worker ids served in this process
_transfers: dict[str, object] = {}  # transfer key -> device array
# abandoned keys whose park may still be in flight -> tombstone timestamp.
# TTL'd: a park that hasn't landed within the TTL never will (it's queued on
# an engine thread in this process), so stale entries are pruned instead of
# ever clearing the whole set (which could drop live tombstones and leak).
_tombstones: dict[str, float] = {}
_TOMBSTONE_TTL_S = 600.0
_total = 0  # device transfers ever started (observability/tests)


def _prune_tombstones_locked(now: float) -> None:
    if len(_tombstones) > 1024:
        dead = [k for k, t in _tombstones.items() if now - t > _TOMBSTONE_TTL_S]
        for k in dead:
            del _tombstones[k]


def register_worker(worker_id: int) -> None:
    with _lock:
        _local_workers.add(worker_id)


def unregister_worker(worker_id: int) -> None:
    with _lock:
        _local_workers.discard(worker_id)


def is_local(worker_id: int) -> bool:
    with _lock:
        return worker_id in _local_workers


def transfer_key(decode_worker_id: int, request_id: str) -> str:
    """Request ids are only unique per decode worker; the key namespaces them
    so colocated decode workers can never collide."""
    return f"{decode_worker_id}/{request_id}"


def put_transfer(transfer_id: str, data) -> bool:
    """Park a gathered device array. Returns False (and drops the data) when
    the consumer already abandoned the request — its discard_transfer left a
    tombstone because cancellation can land while the prefill engine thread is
    still producing, i.e. before there is anything to pop."""
    global _total
    with _lock:
        if transfer_id in _tombstones:
            del _tombstones[transfer_id]
            return False
        _transfers[transfer_id] = data
        _total += 1
        return True


def pop_transfer(transfer_id: str):
    with _lock:
        return _transfers.pop(transfer_id, None)


def discard_transfer(transfer_id: str) -> None:
    """Consumer-side abandon: drop the parked array now, or leave a tombstone
    so a park that is still in flight on the producer side gets dropped on
    arrival instead of leaking device memory."""
    now = time.monotonic()
    with _lock:
        if _transfers.pop(transfer_id, None) is None:
            _prune_tombstones_locked(now)
            _tombstones[transfer_id] = now


def clear_tombstone(transfer_id: str) -> None:
    """Called when a request id is (re)used for a fresh remote prefill so a
    stale tombstone from an earlier cancelled attempt can't swallow its KV."""
    with _lock:
        _tombstones.pop(transfer_id, None)


def transfer_count() -> int:
    """Parked (not yet adopted) transfers."""
    with _lock:
        return len(_transfers)


def drain_all() -> int:
    """Drop every parked transfer. Teardown belt for harnesses that cycle
    disagg worker fleets in one process: an abandoned transfer holds a
    DEVICE array (hundreds of MB at serving geometry), and anything a
    cancelled consumer raced past must not pin HBM into the next fleet.
    Tombstones are kept — a park still in flight on a producer thread must
    find its tombstone and drop, or it would re-pin HBM right after the
    drain. Returns the number of parked arrays dropped."""
    with _lock:
        n = len(_transfers)
        _transfers.clear()
        return n


def total_transfers() -> int:
    """Device transfers ever started."""
    with _lock:
        return _total
