"""Decode-side disaggregation: the DisaggDecodeEngine wraps a local
AsyncJaxEngine and conditionally offloads prefill to remote prefill workers.

Flow (mirrors reference: examples/llm/components/worker.py:148-189):
  1. estimate prefix-cache hit; ask the DisaggregatedRouter local-vs-remote
  2. remote: allocate decode-side pages, push a RemotePrefillRequest onto the
     broker work queue, await the PrefillResult on our ``prefill_result``
     endpoint (KV rides the TCP call-home data plane — the NIXL WRITE +
     notification analogue), inject + adopt
  3. local: plain engine.generate
"""

from __future__ import annotations

import asyncio
import time
from typing import AsyncIterator, Optional

import numpy as np

from dynamo_tpu.engine.engine import AsyncJaxEngine
from dynamo_tpu.engine.scheduler import EngineRequest, StepOutput
from dynamo_tpu.llm.disagg_router import DisaggregatedRouter
from dynamo_tpu.llm.remote_prefill import (
    PrefillResult,
    RemotePrefillRequest,
    prefill_queue_name,
)
from dynamo_tpu.utils import get_logger, tracing

log = get_logger("disagg.decode")

PREFILL_RESULT_ENDPOINT = "prefill_result"


class DisaggDecodeEngine:
    """Same generate() contract as AsyncJaxEngine; routes prefill conditionally."""

    def __init__(
        self,
        engine: AsyncJaxEngine,
        drt,
        namespace: str,
        component: str,
        model: str,
        disagg_router: Optional[DisaggregatedRouter] = None,
        remote_prefill_timeout: float = 120.0,
    ):
        self.engine = engine
        self.drt = drt
        self.namespace = namespace
        self.component = component
        self.model = model
        self.router = disagg_router or DisaggregatedRouter(model, cplane=drt.cplane)
        self.queue_name = prefill_queue_name(namespace, model)
        self.remote_prefill_timeout = remote_prefill_timeout
        self._pending: dict[str, asyncio.Future] = {}
        self._served = None
        self.kv_server = None  # KvDataPlaneServer, started in start()
        # disagg stats
        self.remote_prefills = 0
        self.local_prefills = 0
        self.remote_prefill_wait_s = 0.0  # queue push -> KV adopted (transfer leg)
        self.parts_scattered = 0  # streamed KV parts injected before adoption

    # ---------------- lifecycle ----------------

    async def start(self) -> "DisaggDecodeEngine":
        """Serve the prefill_result endpoint prefill workers call home to."""
        from dynamo_tpu.disagg import ici
        from dynamo_tpu.disagg.dataplane import KvDataPlaneServer

        ep = (
            self.drt.namespace(self.namespace)
            .component(self.component)
            .endpoint(PREFILL_RESULT_ENDPOINT)
        )
        self._served = await ep.serve_endpoint(self._on_prefill_result)
        await self.router.start_watching()
        # dedicated bulk-KV listener: cross-process prefill workers stream
        # block payloads here, off the control plane (disagg/dataplane.py)
        self.kv_server = await KvDataPlaneServer().start()
        # same-pod prefill workers discover us here and use the device-to-device
        # (ICI) KV handoff instead of host-staged bytes
        ici.register_worker(self.worker_id)
        return self

    async def shutdown(self) -> None:
        from dynamo_tpu.disagg import ici

        ici.unregister_worker(self.worker_id)
        if self._served is not None:
            await self._served.stop()
        if self.kv_server is not None:
            await self.kv_server.stop()
        await self.router.stop()
        await self.engine.shutdown()

    @property
    def worker_id(self) -> int:
        return self.drt.primary_lease.lease_id

    def metrics(self):
        return self.engine.metrics()

    def stage_snapshot(self) -> dict:
        snap = getattr(self.engine, "stage_snapshot", dict)()
        return snap

    def render_stage_metrics(self) -> str:
        """Inner engine stage histograms + the KV data-plane stream counters
        (parts/bytes/checksums on the receive side) — one exposition blob for
        whichever /metrics surface hosts this engine."""
        parts = []
        inner = getattr(self.engine, "render_stage_metrics", None)
        if inner is not None:
            parts.append(inner())
        if self.kv_server is not None:
            parts.append(self.kv_server.render_metrics())
        return "".join(parts)

    # ---------------- prefill result ingestion ----------------

    async def _on_prefill_result(self, request: dict):
        result = PrefillResult.from_wire(request)
        fut = self._pending.pop(result.request_id, None)
        if fut is None:
            log.warning("prefill result for unknown request %s", result.request_id)
            yield {"ok": False, "error": "unknown request"}
            return
        fut.set_result(result)
        yield {"ok": True}

    # ---------------- generate ----------------

    async def generate(self, request: EngineRequest) -> AsyncIterator[StepOutput]:
        async for batch in self.generate_batched(request):
            for item in batch:
                yield item

    async def generate_batched(self, request: EngineRequest) -> AsyncIterator[list[StepOutput]]:
        """Window-batched variant (see AsyncJaxEngine.generate_batched): the
        serving Backend consumes this to collapse per-token overhead."""
        # submission/trace stamps happen HERE (not only in the inner engine):
        # the remote path adopts via _register_stream and never goes through
        # engine.generate_batched, yet its queue-wait/TTFT/spans must exist
        AsyncJaxEngine._stamp_submission(request)
        prompt = list(request.token_ids)
        salt = 0
        if getattr(request, "lora_name", ""):
            from dynamo_tpu.lora.adapter import lora_uid

            salt = lora_uid(request.lora_name)
        prefix_hit = await self.engine.run_on_engine(
            lambda: self.engine.sync_lookup_prefix(prompt, salt=salt)
        )
        try:
            queue_depth = await self.drt.cplane.queue_depth(self.queue_name)
        except Exception:
            queue_depth = 0

        # multimodal, logprobs, penalty, and seeded prompts prefill locally:
        # the remote-prefill wire protocol carries token ids only (no pixel
        # data, no first-token logprobs) and the remote engine has no access
        # to this worker's per-slot penalty state or seed stream
        if (
            request.images
            or request.logprobs is not None
            or request.sampling.needs_penalties
            or request.sampling.seed is not None
            or request.sampling.min_p > 0  # remote wire carries no min_p
            # ...nor EOS suppression state for min_tokens' first token
            or (
                request.sampling.min_tokens > 1
                and not request.sampling.ignore_eos
                and bool(request.eos_token_ids)
            )
            # LoRA requests prefill locally: the remote engine would need the
            # same adapter pinned and the salted block identity carried over
            # the wire — the local scheduler already has both
            or bool(getattr(request, "lora_name", ""))
            or not self.router.prefill_remote(len(prompt), prefix_hit, queue_depth)
        ):
            self.local_prefills += 1
            async for batch in self.engine.generate_batched(request):
                yield batch
            return

        self.remote_prefills += 1
        log.debug(
            "remote prefill for %s (len=%d hit=%d depth=%d)",
            request.request_id, len(prompt), prefix_hit, queue_depth,
        )
        from dynamo_tpu.disagg import ici

        rid = request.request_id
        tkey = ici.transfer_key(self.worker_id, rid)
        # a retry reusing this request id must not be swallowed by a tombstone
        # left behind by an earlier cancelled attempt
        ici.clear_tombstone(tkey)
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._pending[rid] = fut
        # register interest on the data plane BEFORE the work is queued, so a
        # fast prefill worker's payload parks instead of being dropped
        kv_token = self.kv_server.expect(rid)
        self.engine._register_stream(rid)
        adopted = False
        pool_full = False
        # streamed (v2) transfers: every part that lands on the data plane is
        # scattered into this sequence's pages while later parts (and the
        # prefill itself) are still in flight — the final adopt only waits on
        # the tail part. scatter_tasks orders those engine-thread writes
        # before adoption/abort.
        scatter_tasks: list[asyncio.Task] = []
        injected_pages = [0]
        try:
            # inside the protected region: the engine thread allocates pages
            # even if this coroutine is cancelled mid-await, and the abort in
            # the finally is queued behind it (FIFO), so it always cleans up
            try:
                cached_len, shared_pages, page_ids = await self.engine.run_on_engine(
                    lambda: self.engine.sync_allocate_remote(rid, prompt)
                )
            except MemoryError:
                # remote-prefill allocation has no admission control (the
                # pages must exist before the prefill worker writes into
                # them); under page pressure fall back to the LOCAL path,
                # whose scheduler queues the request until pages free up
                # instead of failing it
                pool_full = True
            if not pool_full:
                ps = self.engine.config.page_size
                n_pages = -(-len(prompt) // ps)
                start_page = shared_pages

                def on_kv_part(part):
                    # runs on the event loop as each part lands; sentinel
                    # ranges (v1 monolithic frames) cover everything pending
                    pf = part.page_from if part.page_from >= 0 else start_page
                    pt = part.page_to if part.page_to >= 0 else n_pages
                    ids = np.asarray(page_ids[pf:pt], np.int32)
                    if len(ids) == 0:
                        return
                    # int8 parts carry their scale plane; wire_data() is the
                    # {"q","s"} dict inject_pages_bucketed scatters directly
                    data, axis = part.wire_data(), part.cat_axis
                    self.parts_scattered += 1
                    scatter_tasks.append(asyncio.create_task(
                        self.engine.run_on_engine(
                            lambda: self.engine.runner.inject_pages_bucketed(
                                ids, data, axis=axis
                            )
                        )
                    ))
                    injected_pages[0] += len(ids)

                self.kv_server.set_consumer(rid, on_kv_part)
                rp = RemotePrefillRequest(
                    request_id=rid,
                    token_ids=prompt,
                    temperature=request.sampling.temperature,
                    top_k=request.sampling.top_k,
                    top_p=request.sampling.top_p,
                    decode_worker_id=self.worker_id,
                    decode_endpoint=f"dyn://{self.namespace}.{self.component}.{PREFILL_RESULT_ENDPOINT}",
                    skip_leading_tokens=shared_pages * self.engine.config.page_size,
                    kv_addr=self.kv_server.address,
                    kv_token=kv_token,
                    trace_id=request.trace_id or "",
                    # the router's holder hint rides along: the prefill
                    # worker pulls the prefix from the holder before
                    # recomputing (its own min-advantage gate applies)
                    kv_holder_addr=getattr(request, "kv_holder_addr", ""),
                    kv_holder_blocks=getattr(request, "kv_holder_blocks", 0),
                )
                t_hop = time.monotonic()
                await self.drt.cplane.queue_push(self.queue_name, rp.to_wire())
                # one deadline covers BOTH waits (result notification + socket
                # payload): charging each a full timeout would double the
                # worst-case stall when the payload connection dies right
                # after the notification was delivered
                deadline = asyncio.get_running_loop().time() + self.remote_prefill_timeout
                result: PrefillResult = await asyncio.wait_for(fut, self.remote_prefill_timeout)
                kv_data = None
                if result.kv_mode == "socket" and (result.kv_shape or result.kv_parts):
                    # the result message is the notification; the payload
                    # rides the dedicated socket and may land just after it.
                    # Streamed transfers resolve to None here (the parts were
                    # consumed on arrival) — this await is the tail-part gate.
                    remaining = max(0.05, deadline - asyncio.get_running_loop().time())
                    with tracing.span(
                        "disagg.kv_receive", request_id=rid,
                        trace_id=request.trace_id, mode="socket",
                        parts=result.kv_parts,
                    ):
                        kv_data = await self.kv_server.receive(rid, timeout=remaining)
                if scatter_tasks:
                    # every incremental scatter must be on the page table
                    # before adoption enters the sequence into decode
                    await asyncio.gather(*scatter_tasks)
                await self.engine.run_on_engine(
                    lambda: self.engine.sync_adopt_prefilled(
                        request, result, cached_len, kv_data=kv_data,
                        injected_pages=injected_pages[0],
                    )
                )
                adopted = True
                dt = time.monotonic() - t_hop
                self.remote_prefill_wait_s += dt
                tracing.record_span(
                    "disagg.remote_prefill", t_hop, duration=dt,
                    request_id=rid, trace_id=request.trace_id,
                    attrs={"prompt_len": len(prompt), "mode": result.kv_mode},
                )
        finally:
            # finally (not except Exception): client cancellation raises
            # CancelledError, which must run the same cleanup — dropping any
            # parked (or still in-flight) ICI transfer and aborting through
            # the scheduler, since adoption may have completed on the engine
            # thread even though our await was cancelled
            self.kv_server.abandon(rid)
            if scatter_tasks and not adopted:
                # flush in-flight part scatters BEFORE freeing the pages: a
                # scatter landing after the abort would write into pages the
                # allocator may already have handed to another sequence
                await asyncio.gather(*scatter_tasks, return_exceptions=True)
            if not adopted:
                self._pending.pop(rid, None)
                ici.discard_transfer(tkey)
                await self.engine.run_on_engine(lambda: self.engine.sync_abort_remote(rid))
                self.engine._outputs.pop(rid, None)

        if pool_full:
            self.remote_prefills -= 1
            self.local_prefills += 1
            log.warning(
                "decode pool full; remote prefill for %s falls back to local", rid
            )
            async for batch in self.engine.generate_batched(request):
                yield batch
            return

        async for batch in self.engine._drain_stream_batched(rid):
            yield batch
