"""Build the local serving pipeline (preprocessor -> backend -> engine) from a
model deployment card, mirroring the reference's pipeline link chain for core
engines (reference: launch/dynamo-run/src/input/http.rs:95-101)."""

from __future__ import annotations

from dynamo_tpu.llm.backend import Backend
from dynamo_tpu.llm.http.service import ModelPipeline
from dynamo_tpu.llm.model_card import ModelDeploymentCard
from dynamo_tpu.llm.preprocessor import OpenAIPreprocessor
from dynamo_tpu.llm.tokenizer import get_tokenizer


def build_pipeline(engine, card: ModelDeploymentCard) -> ModelPipeline:
    tokenizer = get_tokenizer(card.tokenizer)
    preprocessor = OpenAIPreprocessor(
        tokenizer,
        model_name=card.display_name,
        max_model_len=card.context_length,
        mm=card.mm,
    )
    from dynamo_tpu.launch._remote import RemoteEngineProxy, RemoteTextBackend

    if isinstance(engine, RemoteEngineProxy):
        backend = RemoteTextBackend(engine)  # remote worker already detokenizes
    else:
        backend = Backend(engine, tokenizer)
    return ModelPipeline(card.display_name, preprocessor, backend, model_type="both")


class LoraPreprocessor:
    """Preprocessor wrapper that pins one adapter name onto every request it
    produces — the colocated-serving half of ``base:adapter`` model-name
    resolution (the distributed worker resolves the suffix itself in
    WorkerService._handle)."""

    def __init__(self, inner, adapter: str):
        self._inner = inner
        self.adapter = adapter

    @property
    def tokenizer(self):
        return self._inner.tokenizer

    def preprocess_chat(self, req):
        pre, annotations = self._inner.preprocess_chat(req)
        pre.lora_name = self.adapter
        return pre, annotations

    def preprocess_completion(self, req):
        pre, annotations = self._inner.preprocess_completion(req)
        pre.lora_name = self.adapter
        return pre, annotations


def lora_pipelines(base: ModelPipeline, adapter_specs) -> list[ModelPipeline]:
    """One servable ModelPipeline per configured adapter, named
    ``<base>:<adapter>`` — shares the base pipeline's backend/tokenizer; only
    the preprocessor differs (it stamps lora_name). Unknown adapter names
    then 404 (model_not_found) at the HTTP edge like any unknown model."""
    from dynamo_tpu.lora.adapter import parse_adapter_specs

    return [
        ModelPipeline(
            f"{base.name}:{name}",
            LoraPreprocessor(base.preprocessor, name),
            base.backend,
            base.model_type,
        )
        for name in parse_adapter_specs(adapter_specs)
    ]


def card_for_model(model_id: str | None, max_model_len: int | None = None) -> ModelDeploymentCard:
    from dynamo_tpu.models.registry import is_tiny_family

    if is_tiny_family(model_id):
        card = ModelDeploymentCard.for_tiny(model_id or "tiny")
        card.model_path = model_id or "tiny"
    else:
        card = ModelDeploymentCard.from_local_path(model_id)
    if max_model_len:
        card.context_length = max_model_len
    return card
