"""in=text: interactive chat REPL against the local pipeline."""

from __future__ import annotations

import asyncio
import sys

from dynamo_tpu.frontends.pipeline import build_pipeline, card_for_model
from dynamo_tpu.llm.protocols.openai import ChatCompletionRequest


async def run_text(engine, args) -> None:
    card = card_for_model(args.model, getattr(args, "max_model_len", None))
    pipeline = build_pipeline(engine, card)
    print(f"model: {card.display_name} — type a prompt, Ctrl-D to exit", flush=True)
    loop = asyncio.get_running_loop()
    while True:
        print("> ", end="", flush=True)
        line = await loop.run_in_executor(None, sys.stdin.readline)
        if not line:
            break
        line = line.strip()
        if not line:
            continue
        req = ChatCompletionRequest.from_dict(
            {"messages": [{"role": "user", "content": line}], "stream": True}
        )
        pre, _ = pipeline.preprocessor.preprocess_chat(req)
        async for out in pipeline.backend.generate(pre):
            print(out.text, end="", flush=True)
        print()
