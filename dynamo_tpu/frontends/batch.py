"""in=batch:<file.jsonl>: run prompts concurrently, write outputs, print a perf
summary (mirrors the reference batch mode, reference: launch/dynamo-run/src/
input/batch.rs:1-288)."""

from __future__ import annotations

import asyncio
import json
import time
from pathlib import Path

import numpy as np

from dynamo_tpu.frontends.pipeline import build_pipeline, card_for_model
from dynamo_tpu.llm.protocols.openai import ChatCompletionRequest


async def run_batch(engine, args, input_path: str) -> None:
    card = card_for_model(args.model, getattr(args, "max_model_len", None))
    pipeline = build_pipeline(engine, card)
    prompts = []
    # file I/O off-loop: a colocated engine shares this event loop, and a
    # multi-MB batch file read would stall its dispatch cadence
    text = await asyncio.to_thread(Path(input_path).read_text)
    for line in text.splitlines():
        line = line.strip()
        if line:
            prompts.append(json.loads(line))

    results = [None] * len(prompts)
    t_start = time.monotonic()

    async def one(i: int, entry: dict):
        text = entry.get("text") or entry.get("prompt") or ""
        req = ChatCompletionRequest.from_dict(
            {
                "messages": [{"role": "user", "content": text}],
                "max_tokens": entry.get("max_tokens", args and getattr(args, "max_tokens", None) or 128),
            }
        )
        pre, _ = pipeline.preprocessor.preprocess_chat(req)
        t0 = time.monotonic()
        ttft = None
        chunks = []
        n_tokens = 0
        async for out in pipeline.backend.generate(pre):
            if ttft is None and (out.text or out.token_ids):
                ttft = time.monotonic() - t0
            chunks.append(out.text)
            n_tokens = out.cumulative_tokens
        results[i] = {
            "prompt": text,
            "output": "".join(chunks),
            "tokens_in": len(pre.token_ids),
            "tokens_out": n_tokens,
            "ttft_s": ttft or 0.0,
            "latency_s": time.monotonic() - t0,
        }

    await asyncio.gather(*[one(i, e) for i, e in enumerate(prompts)])
    elapsed = time.monotonic() - t_start

    out_path = Path(input_path).with_suffix(".out.jsonl")
    payload = "".join(json.dumps(r) + "\n" for r in results)
    await asyncio.to_thread(out_path.write_text, payload)

    total_out = sum(r["tokens_out"] for r in results)
    lat = np.array([r["latency_s"] for r in results])
    ttfts = np.array([r["ttft_s"] for r in results])
    summary = {
        "requests": len(results),
        "elapsed_s": round(elapsed, 3),
        "output_tokens": total_out,
        "output_tok_per_s": round(total_out / elapsed, 2) if elapsed else 0,
        "ttft_p50_ms": round(float(np.percentile(ttfts, 50)) * 1e3, 1),
        "ttft_p99_ms": round(float(np.percentile(ttfts, 99)) * 1e3, 1),
        "latency_p50_s": round(float(np.percentile(lat, 50)), 3),
        "latency_p99_s": round(float(np.percentile(lat, 99)), 3),
        "output_file": str(out_path),
    }
    print(json.dumps(summary))
