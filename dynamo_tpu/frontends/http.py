"""in=http: serve the local pipeline over the OpenAI HTTP frontend."""

from __future__ import annotations

import asyncio

from dynamo_tpu.frontends.pipeline import build_pipeline, card_for_model
from dynamo_tpu.llm.http.service import HttpService
from dynamo_tpu.utils import get_logger
from dynamo_tpu.utils.prometheus import render_family

log = get_logger("frontends.http")


def engine_metrics_text(engine) -> str:
    """Prometheus exposition for a colocated engine: ForwardPassMetrics
    gauges (one conformant family per field) + the per-stage latency
    histograms (queue wait, TTFT, prefill, decode window, reconcile)."""
    parts = []
    m = getattr(engine, "metrics", None)
    if m is not None:
        fm = m()
        for k, v in fm.to_wire().items():
            parts.append(render_family(
                f"llm_worker_{k}", "gauge", f"worker {k}", [({}, v)]
            ))
    stage = getattr(engine, "render_stage_metrics", None)
    if stage is not None:
        parts.append(stage())
    return "".join(parts)


async def run_http(engine, args) -> None:
    card = card_for_model(args.model, getattr(args, "max_model_len", None))
    pipeline = build_pipeline(engine, card)

    service = HttpService(
        port=args.http_port, extra_metrics=lambda: engine_metrics_text(engine)
    )
    service.manager.add(pipeline)
    await service.run_forever()
