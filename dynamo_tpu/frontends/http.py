"""in=http: serve the local pipeline over the OpenAI HTTP frontend."""

from __future__ import annotations

import asyncio

from dynamo_tpu.frontends.pipeline import build_pipeline, card_for_model
from dynamo_tpu.llm.http.service import HttpService
from dynamo_tpu.utils import get_logger

log = get_logger("frontends.http")


async def run_http(engine, args) -> None:
    card = card_for_model(args.model, getattr(args, "max_model_len", None))
    pipeline = build_pipeline(engine, card)

    def extra_metrics() -> str:
        m = getattr(engine, "metrics", None)
        if m is None:
            return ""
        fm = m()
        lines = []
        for k, v in fm.to_wire().items():
            lines.append(f"llm_worker_{k} {v}")
        return "\n".join(lines) + "\n"

    service = HttpService(port=args.http_port, extra_metrics=extra_metrics)
    service.manager.add(pipeline)
    await service.run_forever()
