"""in=http: serve the local pipeline over the OpenAI HTTP frontend."""

from __future__ import annotations


from dynamo_tpu.frontends.pipeline import build_pipeline, card_for_model
from dynamo_tpu.llm.http.service import HttpService
from dynamo_tpu.utils import get_logger
from dynamo_tpu.utils.prometheus import render_family

log = get_logger("frontends.http")


def engine_metrics_text(engine) -> str:
    """Prometheus exposition for a colocated engine: ForwardPassMetrics
    gauges (one conformant family per field) + the per-stage latency
    histograms (queue wait, TTFT, prefill, decode window, reconcile)."""
    parts = []
    m = getattr(engine, "metrics", None)
    if m is not None:
        fm = m()
        for k, v in fm.to_wire().items():
            parts.append(render_family(
                f"llm_worker_{k}", "gauge", f"worker {k}", [({}, v)]
            ))
    stage = getattr(engine, "render_stage_metrics", None)
    if stage is not None:
        parts.append(stage())
    return "".join(parts)


def engine_readiness(engine):
    """/ready provider for a colocated engine: reflects the engine's
    HealthMonitor state (serving requires ready/degraded, not
    starting/draining/dead). Engines without the health plane (external
    token engines) stay ready."""

    def provider() -> tuple:
        health = getattr(engine, "health", None)
        if health is None:
            return True, {}
        snap = health.snapshot()
        ok = snap["state"] in ("ready", "degraded")
        return ok, {"engine": snap}

    return provider


async def run_http(engine, args) -> None:
    from dynamo_tpu.utils.slo import SloTracker, targets_from_env

    card = card_for_model(args.model, getattr(args, "max_model_len", None))
    pipeline = build_pipeline(engine, card)

    slo = SloTracker(targets_from_env({
        "ttft": getattr(args, "slo_ttft_ms", None),
        "itl": getattr(args, "slo_itl_ms", None),
    }))
    service = HttpService(
        port=args.http_port,
        extra_metrics=lambda: engine_metrics_text(engine),
        slo=slo,
        readiness=engine_readiness(engine),
        # step-anatomy debug plane (/debug/steps): recent per-dispatch
        # host/device phase records off the colocated engine's ring
        step_source=getattr(engine, "debug_steps", None),
        # cost footer on /debug/requests/{id}: the colocated engine's
        # MeterLedger per-request footer (utils/metering.py)
        cost_source=getattr(engine, "request_cost", None),
    )
    service.manager.add(pipeline)
    # multi-LoRA: each configured adapter serves as its own OpenAI model name
    # (<base>:<adapter>) through a lora_name-stamping preprocessor wrapper;
    # everything downstream (backend, engine) is shared
    adapters = getattr(getattr(engine, "config", None), "lora_adapters", ())
    if adapters:
        from dynamo_tpu.frontends.pipeline import lora_pipelines

        for lp in lora_pipelines(pipeline, adapters):
            service.manager.add(lp)
    await service.run_forever()
