"""llmctl: register/list/remove model -> endpoint mappings in the control plane
that HTTP frontends watch.

Mirrors the reference llmctl (reference: launch/llmctl/src/main.rs:115-442).

    python -m dynamo_tpu.launch.llmctl http add chat-model tiny dyn://ns.backend.generate
    python -m dynamo_tpu.launch.llmctl http list
    python -m dynamo_tpu.launch.llmctl http remove chat-model tiny
"""

from __future__ import annotations

import argparse
import asyncio
import json

from dynamo_tpu.cplane.client import CplaneClient
from dynamo_tpu.frontends.pipeline import card_for_model
from dynamo_tpu.llm.model_registry import (
    ModelEntry,
    list_models,
    register_model,
    unregister_model,
)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="llmctl", description=__doc__)
    p.add_argument("--cplane", default=None, help="broker address host:port")
    sub = p.add_subparsers(dest="plane", required=True)
    http = sub.add_parser("http", help="manage http-served models")
    hsub = http.add_subparsers(dest="action", required=True)

    add = hsub.add_parser("add")
    add.add_argument("kind", choices=["chat-model", "completion-model"])
    add.add_argument("name")
    add.add_argument("endpoint", help="dyn://ns.comp.endpoint")
    add.add_argument("--model-path", default=None, help="local path for card/tokenizer")

    rm = hsub.add_parser("remove")
    rm.add_argument("kind", choices=["chat-model", "completion-model"])
    rm.add_argument("name")

    hsub.add_parser("list")
    return p


def _model_type(kind: str) -> str:
    return "chat" if kind == "chat-model" else "completion"


async def _run(args) -> int:
    import os

    address = args.cplane or os.environ.get("DYNTPU_CPLANE", "127.0.0.1:4222")
    client = CplaneClient(address)
    await client.connect()
    try:
        if args.action == "add":
            card = card_for_model(args.model_path or args.name)
            card.display_name = args.name
            entry = ModelEntry(
                name=args.name,
                endpoint=args.endpoint,
                model_type=_model_type(args.kind),
                card=card,
            )
            await register_model(client, entry)
            print(f"registered {args.name} -> {args.endpoint}")
        elif args.action == "remove":
            ok = await unregister_model(client, _model_type(args.kind), args.name)
            print("removed" if ok else "not found")
        elif args.action == "list":
            for entry in await list_models(client):
                print(json.dumps({"name": entry.name, "endpoint": entry.endpoint, "type": entry.model_type}))
        return 0
    finally:
        await client.close()


def main(argv=None) -> int:
    return asyncio.run(_run(build_parser().parse_args(argv)))


if __name__ == "__main__":
    raise SystemExit(main())
