"""`dynamo-tpu run` universal CLI (analogue of the reference's dynamo-run,
reference: launch/dynamo-run/src/lib.rs:75-433).

in={http,text,batch,dyn://...} x out={echo,jax,dyn://...}. Engine wiring lands
with the JAX engine; this module owns arg parsing and dispatch.
"""

from __future__ import annotations

import argparse
import sys


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="dynamo-tpu", description=__doc__)
    sub = p.add_subparsers(dest="command", required=True)
    run = sub.add_parser("run", help="run a serving pipeline")
    run.add_argument("model", nargs="?", help="model path or registry name")
    run.add_argument("--in", dest="input", default="text", help="http|text|batch:<file.jsonl>|dyn://<endpoint>")
    run.add_argument("--out", dest="output", default="echo", help="echo|jax|dyn://<endpoint>")
    run.add_argument("--http-port", type=int, default=8080)
    run.add_argument("--max-model-len", type=int, default=None)
    run.add_argument("--num-pages", type=int, default=None, help="KV cache pages")
    run.add_argument("--max-seqs", type=int, default=None, help="decode batch slots")
    run.add_argument("--tp", type=int, default=None, help="tensor-parallel degree")
    run.add_argument("--pp", type=int, default=None, help="pipeline-parallel stages")
    run.add_argument("--max-tokens", type=int, default=None, help="batch mode default max_tokens")
    return p


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    if args.command == "run":
        from dynamo_tpu.launch._run_impl import run_command

        return run_command(args)
    return 1


if __name__ == "__main__":
    sys.exit(main())
