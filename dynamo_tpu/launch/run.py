"""`dynamo-tpu run` universal CLI (analogue of the reference's dynamo-run,
reference: launch/dynamo-run/src/lib.rs:75-433).

in={http,text,batch,dyn://...} x out={echo,jax,dyn://...}. Engine wiring lands
with the JAX engine; this module owns arg parsing and dispatch.
"""

from __future__ import annotations

import argparse
import sys


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="dynamo-tpu", description=__doc__)
    sub = p.add_subparsers(dest="command", required=True)
    run = sub.add_parser("run", help="run a serving pipeline")
    run.add_argument("model", nargs="?", help="model path or registry name")
    run.add_argument("--in", dest="input", default="text", help="http|text|batch:<file.jsonl>|dyn://<endpoint>")
    run.add_argument("--out", dest="output", default="echo",
                     help="echo|jax|pytok:<module>:<fn>|dyn://<endpoint>")
    run.add_argument("--http-port", type=int, default=8080)
    run.add_argument("--max-model-len", type=int, default=None)
    run.add_argument("--num-pages", type=int, default=None, help="KV cache pages")
    run.add_argument("--max-seqs", type=int, default=None, help="decode batch slots")
    run.add_argument("--tp", type=int, default=None, help="tensor-parallel degree")
    run.add_argument("--pp", type=int, default=None, help="pipeline-parallel stages")
    run.add_argument(
        "--quantize", choices=["int8_wo"], default=None,
        help="weight-only quantization applied at load time (int8 weights + "
             "per-channel scales; embeddings/norms stay bf16)",
    )
    run.add_argument(
        "--kv-cache-dtype", choices=["bf16", "int8"], default=None,
        help="KV cache storage dtype: int8 stores pages as int8 + per-page "
             "scales — half the attention HBM stream, ~2x page capacity at "
             "the same budget (composes with --quantize)",
    )
    run.add_argument(
        "--speculative", default=None, metavar="KIND:...",
        help="speculative decoding: ngram:<k> proposes from the sequence's "
             "own history (prompt-lookup); draft:<model>:<k> loads a second, "
             "smaller registry model that drafts k tokens per round in one "
             "batched on-device dispatch (composes with --quantize / "
             "--kv-cache-dtype); both verify in one batched forward pass",
    )
    run.add_argument(
        "--lora-adapters", default=None, metavar="SPECS",
        help="comma-separated LoRA adapter specs served as <model>:<name> "
             "(name | name=<dir> | name=random:<seed>): adapters load into "
             "device-resident stacked pools and a mixed-adapter batch "
             "decodes in ONE gathered dispatch (dynamo_tpu/lora/)",
    )
    run.add_argument(
        "--max-loras", type=int, default=None,
        help="device LoRA slots; more adapters than slots multiplex via LRU "
             "eviction/hot-swap (in-flight sequences pin their slot)",
    )
    run.add_argument(
        "--lora-rank", type=int, default=None,
        help="LoRA pool rank (adapters with smaller r zero-pad exactly)",
    )
    run.add_argument("--max-tokens", type=int, default=None, help="batch mode default max_tokens")
    run.add_argument(
        "--slo-ttft-ms", type=float, default=None,
        help="TTFT SLO target in ms: the engine and HTTP frontend track "
             "rolling-window percentiles + an error-budget gauge against it "
             "(/metrics, /ready; env DYNTPU_SLO_TTFT_MS)",
    )
    run.add_argument(
        "--slo-itl-ms", type=float, default=None,
        help="inter-token-latency SLO target in ms (env DYNTPU_SLO_ITL_MS)",
    )
    # serve/build/deploy are dispatched on argv[0] in main() (their argv is
    # forwarded verbatim — argparse REMAINDER can't capture leading options);
    # registered here so they show in --help
    for name, help_ in (
        ("serve", "launch a service graph (process-per-service supervisor)"),
        ("build", "package a service graph into a deployable artifact"),
        ("deploy", "manage deployments on the deploy API server"),
    ):
        sub.add_parser(name, help=help_, add_help=False)
    return p


def main(argv: list[str] | None = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    # forward delegated subcommands verbatim (options and all)
    if argv and argv[0] == "serve":
        from dynamo_tpu.sdk.serve import main as serve_main

        return serve_main(argv[1:])
    if argv and argv[0] == "build":
        from dynamo_tpu.sdk.build import main as build_main

        return build_main(argv[1:])
    if argv and argv[0] == "deploy":
        from dynamo_tpu.sdk.deploy import main as deploy_main

        return deploy_main(argv[1:])
    args = build_parser().parse_args(argv)
    if args.command == "run":
        from dynamo_tpu.launch._run_impl import run_command

        return run_command(args)
    return 1


if __name__ == "__main__":
    sys.exit(main())
