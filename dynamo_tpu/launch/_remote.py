"""dyn:// endpoint support for the run CLI.

out=dyn://ns.comp.ep — RemoteEngineProxy: a local engine facade that forwards
tokens-in/tokens-out requests to a distributed worker endpoint (the frontends
keep using the same Backend/engine contract).

in=dyn://ns.comp.ep — serve_engine_endpoint: expose the local engine (jax or
echo) as a worker endpoint speaking the same wire protocol as
components/worker.py, so a remote frontend can drive it.

Mirrors the reference launcher's dyn:// in/out modes
(reference: launch/dynamo-run/src/{input,output} dyn endpoints).
"""

from __future__ import annotations

from typing import AsyncIterator

from dynamo_tpu.engine.scheduler import EngineRequest, StepOutput
from dynamo_tpu.runtime.distributed import DistributedRuntime
from dynamo_tpu.utils import get_logger

log = get_logger("launch.remote")


class RemoteEngineProxy:
    """Engine facade forwarding to a remote worker endpoint.

    The remote worker detokenizes (worker wire protocol), so this proxy
    surfaces text via StepOutput extension — the local Backend sees token ids
    and passes text through untouched when present.
    """

    def __init__(self, endpoint: str):
        self.endpoint = endpoint
        self.drt: DistributedRuntime | None = None
        self._client = None

    async def start(self) -> None:
        self.drt = DistributedRuntime()
        await self.drt.connect()
        self._client = await self.drt.endpoint_client(self.endpoint)
        await self._client.wait_for_instances(timeout=60)

    async def shutdown(self) -> None:
        if self.drt is not None:
            await self.drt._shutdown_hook()

    async def generate(self, request: EngineRequest) -> AsyncIterator[StepOutput]:
        s = request.sampling
        wire = {
            "request_id": request.request_id,
            "token_ids": list(request.token_ids),
            "sampling": {
                "temperature": s.temperature,
                "top_k": s.top_k,
                "top_p": s.top_p,
                "min_p": s.min_p,
                "max_tokens": s.max_tokens,
                "min_tokens": s.min_tokens,
                "ignore_eos": s.ignore_eos,
                "seed": s.seed,
                "presence_penalty": s.presence_penalty,
                "frequency_penalty": s.frequency_penalty,
                "repetition_penalty": s.repetition_penalty,
            },
            "eos_token_ids": list(request.eos_token_ids),
            "logprobs": request.logprobs,
        }
        if request.images:
            wire["images"] = [im.to_wire() for im in request.images]
        stream = await self._client.random(wire)
        async for item in stream:
            ids = [int(t) for t in (item.get("token_ids") or [])]
            out = StepOutput(
                request_id=request.request_id,
                # wire items may carry a WINDOW of tokens (the worker-side
                # Backend batches per decode window); surface the last for
                # StepOutput consumers, the full list for RemoteTextBackend
                token=ids[-1] if ids else None,
                finished=item.get("finish_reason") is not None,
                finish_reason=item.get("finish_reason"),
                cached_tokens=item.get("cached_tokens", 0),
            )
            out.all_token_ids = ids
            out.cumulative = item.get("cumulative_tokens")
            out.text = item.get("text", "")  # pass-through for RemoteTextBackend
            out.lp_entries = item.get("logprobs")  # already OpenAI-shaped
            yield out


class RemoteTextBackend:
    """Backend facade over RemoteEngineProxy: the remote worker already
    detokenized, so text passes straight through (no local DecodeStream)."""

    def __init__(self, proxy: RemoteEngineProxy):
        self.proxy = proxy

    async def generate(self, request):
        from dynamo_tpu.llm.protocols.common import BackendOutput

        engine_req = EngineRequest(
            request_id=request.request_id,
            token_ids=list(request.token_ids),
            sampling=request.sampling,
            eos_token_ids=tuple(request.eos_token_ids),
            images=list(getattr(request, "images", ()) or ()),
            logprobs=getattr(request, "logprobs", None),
        )
        count = 0
        async for out in self.proxy.generate(engine_req):
            ids = getattr(out, "all_token_ids", None)
            if ids is None:
                ids = [out.token] if out.token is not None else []
            count = getattr(out, "cumulative", None) or (count + len(ids))
            yield BackendOutput(
                request_id=request.request_id,
                text=getattr(out, "text", ""),
                token_ids=ids,
                finish_reason=out.finish_reason,
                cumulative_tokens=count,
                cached_tokens=out.cached_tokens,
                logprobs=getattr(out, "lp_entries", None),
            )
            if out.finished:
                return


async def serve_engine_endpoint(engine, args) -> None:
    """Expose the local engine at dyn://ns.comp.ep (tokens in/out)."""
    from dynamo_tpu.frontends.pipeline import card_for_model
    from dynamo_tpu.llm.backend import Backend
    from dynamo_tpu.llm.model_registry import ModelEntry, register_model
    from dynamo_tpu.llm.protocols.common import PreprocessedRequest
    from dynamo_tpu.llm.tokenizer import get_tokenizer

    address = args.input[len("dyn://") :]
    ns, comp, ep_name = address.split(".")
    card = card_for_model(args.model, getattr(args, "max_model_len", None))
    tokenizer = get_tokenizer(card.tokenizer)
    backend = Backend(engine, tokenizer)

    drt = DistributedRuntime()
    await drt.connect()

    async def handle(request: dict):
        pre = PreprocessedRequest.from_wire(request)
        async for out in backend.generate(pre):
            yield {
                "request_id": out.request_id,
                "text": out.text,
                "token_ids": out.token_ids,
                "finish_reason": out.finish_reason,
                "cumulative_tokens": out.cumulative_tokens,
                "cached_tokens": out.cached_tokens,
                "logprobs": out.logprobs,
            }

    def stats():
        m = getattr(engine, "metrics", None)
        return {"kv_metrics": m().to_wire()} if m else {}

    served = await drt.namespace(ns).component(comp).endpoint(ep_name).serve_endpoint(
        handle, metrics=stats
    )
    entry = ModelEntry(
        name=card.display_name, endpoint=args.input, model_type="chat", card=card
    )
    await register_model(drt.cplane, entry, lease_id=drt.primary_lease.lease_id)
    log.info("engine served at %s (model %s)", args.input, card.display_name)
    try:
        await drt.runtime.cancellation.cancelled()
    finally:
        await served.stop()
        await drt._shutdown_hook()
