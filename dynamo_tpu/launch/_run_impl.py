"""Dispatch for `dynamo-tpu run`: wires input frontends to output engines.

Engine matrix mirrors the reference launcher (reference: launch/dynamo-run/src/opt.rs):
inputs http/text/batch/dyn endpoints; outputs echo (test engine,
reference: launch/dynamo-run/src/output/echo_core.rs) and the native JAX engine.
"""

from __future__ import annotations

import asyncio

from dynamo_tpu.utils import get_logger

log = get_logger("launch")


def run_command(args) -> int:
    asyncio.run(_run(args))
    return 0


async def _build_engine(args):
    if args.output == "echo":
        from dynamo_tpu.llm.echo import EchoEngine

        return EchoEngine()
    if args.output == "jax":
        from dynamo_tpu.engine import build_async_engine

        return await build_async_engine(args.model, max_model_len=args.max_model_len)
    raise ValueError(f"unsupported out={args.output}")


async def _run(args) -> None:
    engine = await _build_engine(args)
    try:
        if args.input == "text":
            from dynamo_tpu.frontends.text import run_text

            await run_text(engine, args)
        elif args.input == "http":
            from dynamo_tpu.frontends.http import run_http

            await run_http(engine, args)
        elif args.input.startswith("batch:"):
            from dynamo_tpu.frontends.batch import run_batch

            await run_batch(engine, args, args.input.split(":", 1)[1])
        else:
            raise ValueError(f"unsupported in={args.input}")
    finally:
        shutdown = getattr(engine, "shutdown", None)
        if shutdown is not None:
            result = shutdown()
            if asyncio.iscoroutine(result):
                await result
