"""Dispatch for `dynamo-tpu run`: wires input frontends to output engines.

Engine matrix mirrors the reference launcher (reference: launch/dynamo-run/src/opt.rs):
inputs http/text/batch/dyn endpoints; outputs echo (test engine,
reference: launch/dynamo-run/src/output/echo_core.rs) and the native JAX engine.
"""

from __future__ import annotations

import asyncio

from dynamo_tpu.utils import get_logger

log = get_logger("launch")


def run_command(args) -> int:
    from dynamo_tpu.utils.xla_cache import enable_compilation_cache

    enable_compilation_cache()
    asyncio.run(_run(args))
    return 0


def engine_config_for(args):
    import json

    from dynamo_tpu.engine.config import EngineConfig
    from dynamo_tpu.frontends.pipeline import card_for_model

    card = card_for_model(args.model, getattr(args, "max_model_len", None))
    is_tiny = card.model_path.startswith("tiny")
    model_path = card.model_path
    speculative = getattr(args, "speculative", None)
    if is_tiny and ":" in model_path:
        # engine-level keys may ride the tiny-override JSON (so a single
        # model string configures a test engine end to end); pop them out
        # before the registry parses the rest as MODEL config
        fam, js = model_path.split(":", 1)
        try:
            overrides = json.loads(js)
        except ValueError:
            overrides = None
        if isinstance(overrides, dict) and "speculative" in overrides:
            speculative = speculative or overrides.pop("speculative")
            model_path = fam + (":" + json.dumps(overrides) if overrides else "")
    # disagg data-plane knobs (graph yaml / CLI): default chunk-streamed
    ks = getattr(args, "kv_stream", None)
    kv_stream = True if ks is None else bool(ks)
    kv_stream_lanes = getattr(args, "kv_stream_lanes", None) or 2
    # long-context knobs (graph yaml / CLI): prefill buckets arrive as a
    # comma string (CLI) or a list (yaml)
    pb = getattr(args, "prefill_buckets", None)
    if isinstance(pb, str):
        pb = tuple(int(x) for x in pb.split(",") if x)
    elif pb:
        pb = tuple(int(x) for x in pb)
    long_ctx = dict(
        prefill_flat_depth=getattr(args, "prefill_flat_depth", None) or 8192,
        host_cache_blocks=getattr(args, "host_cache_blocks", None) or 0,
        host_cache_bytes=getattr(args, "host_cache_bytes", None) or 0,
        disk_cache_bytes=getattr(args, "disk_cache_bytes", None) or 0,
        disk_cache_dir=getattr(args, "disk_cache_dir", None) or "",
        offload_watermark=getattr(args, "offload_watermark", None) or 0.90,
        # multi-tenant QoS knobs (graph yaml / CLI)
        qos=not getattr(args, "no_qos", False),
        qos_preempt_wait_ms=getattr(args, "qos_preempt_wait_ms", None) or 250.0,
        metering=not getattr(args, "no_metering", False),
    )
    if pb:
        long_ctx["prefill_buckets"] = pb
    # multi-LoRA knobs (graph yaml / CLI): adapters arrive as a comma string
    # (CLI) or a list (yaml); EngineConfig normalizes either to a tuple
    la = getattr(args, "lora_adapters", None)
    if la:
        long_ctx["lora_adapters"] = (
            la if isinstance(la, str) else tuple(str(x) for x in la)
        )
        long_ctx["max_loras"] = getattr(args, "max_loras", None) or 4
        long_ctx["lora_rank"] = getattr(args, "lora_rank", None) or 8
    if is_tiny:
        tiny_ctx = dict(long_ctx)
        tiny_ctx.setdefault("prefill_buckets", (16, 32))
        return EngineConfig(
            model_id=model_path,
            page_size=card.kv_block_size,
            num_pages=getattr(args, "num_pages", None) or 128,
            max_seqs=getattr(args, "max_seqs", None) or 4,
            max_model_len=card.context_length,
            tp=getattr(args, "tp", None) or 1,
            pp=getattr(args, "pp", None) or 1,
            quantize=getattr(args, "quantize", None),
            kv_cache_dtype=getattr(args, "kv_cache_dtype", None),
            speculative=speculative,
            kv_stream=kv_stream,
            kv_stream_lanes=kv_stream_lanes,
            slo_ttft_ms=getattr(args, "slo_ttft_ms", None),
            slo_itl_ms=getattr(args, "slo_itl_ms", None),
            **tiny_ctx,
        )
    return EngineConfig(
        model_id=model_path,
        page_size=card.kv_block_size,
        num_pages=getattr(args, "num_pages", None) or 2048,
        max_seqs=getattr(args, "max_seqs", None) or 16,
        max_model_len=card.context_length,
        tp=getattr(args, "tp", None) or 1,
        pp=getattr(args, "pp", None) or 1,
        quantize=getattr(args, "quantize", None),
        kv_cache_dtype=getattr(args, "kv_cache_dtype", None),
        speculative=speculative,
        kv_stream=kv_stream,
        kv_stream_lanes=kv_stream_lanes,
        slo_ttft_ms=getattr(args, "slo_ttft_ms", None),
        slo_itl_ms=getattr(args, "slo_itl_ms", None),
        # serve as soon as the core traces compile; feature variants land in
        # the background (halves cold first-deploy readiness time)
        warmup="background",
        **long_ctx,
    )


async def _build_engine(args):
    if args.output == "echo":
        from dynamo_tpu.llm.echo import EchoEngine

        return EchoEngine()
    if args.output == "jax":
        from dynamo_tpu.engine.engine import AsyncJaxEngine
        from dynamo_tpu.parallel.mesh import init_multihost

        # multi-host pod slice (helm worker.yaml sets DYNTPU_COORDINATOR /
        # NUM_PROCESSES / PROCESS_ID): join the SPMD program before any
        # backend use; no-op on a single host
        init_multihost()
        engine = AsyncJaxEngine(engine_config_for(args))
        await engine.start()
        return engine
    if args.output.startswith("pytok:"):
        # user-supplied tokens-in/tokens-out async engine hosted behind the
        # full stack (reference: dynamo-run out=pytok:file.py, the generic
        # Python engine at lib/llm/src/engines/python.rs:105-146)
        from dynamo_tpu.llm.external import ExternalTokenEngine

        return ExternalTokenEngine(args.output[len("pytok:"):])
    if args.output.startswith("dyn://"):
        # remote engine: forward EngineRequests to a distributed endpoint that
        # speaks the worker wire protocol (reference: dynamo-run out=dyn://)
        from dynamo_tpu.launch._remote import RemoteEngineProxy

        proxy = RemoteEngineProxy(args.output)
        await proxy.start()
        return proxy
    raise ValueError(f"unsupported out={args.output}")


async def _run(args) -> None:
    engine = await _build_engine(args)
    try:
        if args.input == "text":
            from dynamo_tpu.frontends.text import run_text

            await run_text(engine, args)
        elif args.input == "http":
            from dynamo_tpu.frontends.http import run_http

            await run_http(engine, args)
        elif args.input.startswith("batch:"):
            from dynamo_tpu.frontends.batch import run_batch

            await run_batch(engine, args, args.input.split(":", 1)[1])
        elif args.input.startswith("dyn://"):
            # expose this engine as a distributed endpoint (worker mode)
            from dynamo_tpu.launch._remote import serve_engine_endpoint

            await serve_engine_endpoint(engine, args)
        else:
            raise ValueError(f"unsupported in={args.input}")
    finally:
        shutdown = getattr(engine, "shutdown", None)
        if shutdown is not None:
            result = shutdown()
            if asyncio.iscoroutine(result):
                await result
