"""Reconciler: DeploymentSpec -> Kubernetes manifests + desired/live diff.

The reference's operator reconciles DynamoNimDeployment CRs into
Deployments/Services/HPAs (reference:
deploy/dynamo/operator/internal/controller/dynamonimdeployment_controller.go).
Here reconciliation is a pure function: `render_manifests` produces the
desired objects, `reconcile` diffs them against a live snapshot into
create/update/delete actions — the same semantics, testable with no cluster
(mirrors the operator's resource unit tests, reference:
deploy/dynamo/operator/internal/controller_common/resource_test.go).
"""

from __future__ import annotations

import json
from typing import Any

from dynamo_tpu.deploy.crd import DeploymentSpec, ServiceSpec

CPLANE_PORT = 4222
MANAGED_BY = "dynamo-tpu-deploy"


def _meta(spec: DeploymentSpec, name: str, component: str) -> dict:
    return {
        "name": name,
        "namespace": spec.namespace,
        "labels": {
            "app.kubernetes.io/name": name,
            "app.kubernetes.io/part-of": spec.name,
            "app.kubernetes.io/managed-by": MANAGED_BY,
            "dynamo-tpu/component": component,
        },
    }


def _cplane_address(spec: DeploymentSpec) -> str:
    if spec.cplane == "managed":
        return f"{spec.name}-cplane:{CPLANE_PORT}"
    return spec.cplane


def _cplane_manifests(spec: DeploymentSpec) -> list[dict]:
    name = f"{spec.name}-cplane"
    meta = _meta(spec, name, "cplane")
    selector = {"app.kubernetes.io/name": name}
    return [
        {
            "apiVersion": "apps/v1",
            "kind": "Deployment",
            "metadata": meta,
            "spec": {
                "replicas": 1,
                "selector": {"matchLabels": selector},
                "template": {
                    "metadata": {"labels": dict(meta["labels"])},
                    "spec": {
                        "containers": [
                            {
                                "name": "cplane",
                                "image": spec.image,
                                "command": [
                                    "python", "-m", "dynamo_tpu.cplane.broker",
                                    "--port", str(CPLANE_PORT),
                                ],
                                "ports": [{"containerPort": CPLANE_PORT}],
                            }
                        ]
                    },
                },
            },
        },
        {
            "apiVersion": "v1",
            "kind": "Service",
            "metadata": meta,
            "spec": {
                "selector": selector,
                "ports": [{"port": CPLANE_PORT, "targetPort": CPLANE_PORT}],
            },
        },
    ]


def _service_manifests(spec: DeploymentSpec, svc: ServiceSpec) -> list[dict]:
    name = f"{spec.name}-{svc.name}"
    meta = _meta(spec, name, svc.name)
    selector = {"app.kubernetes.io/name": name}
    env = [{"name": "DYNTPU_CPLANE", "value": _cplane_address(spec)}]
    if svc.config:
        env.append(
            {"name": "DYNTPU_SERVICE_CONFIG", "value": json.dumps({svc.name: svc.config})}
        )
    env.extend({"name": k, "value": v} for k, v in sorted(svc.env.items()))

    container: dict[str, Any] = {
        "name": svc.name,
        "image": spec.image,
        "command": list(svc.command),
        "env": env,
    }
    if svc.port is not None:
        container["ports"] = [{"containerPort": svc.port}]
    if svc.tpu_chips > 0:
        container["resources"] = {"limits": {"google.com/tpu": str(svc.tpu_chips)}}

    objs: list[dict] = []
    if svc.hosts_per_slice > 1:
        # (autoscaling + multihost is rejected by ServiceSpec.validate)
        # Multihost slices: one StatefulSet PER slice replica. Within a
        # StatefulSet the pod ordinal IS the host index (DYNTPU_PROCESS_ID in
        # [0, hosts_per_slice)), and each slice gets its own coordinator
        # (its pod-0) — see dynamo_tpu/parallel/mesh.py. A single StatefulSet
        # of hosts*replicas pods would hand out ordinals >= hosts_per_slice
        # and share one coordinator across slices, which can never form a mesh.
        for r in range(max(1, svc.replicas)):
            rname = f"{name}-s{r}"
            rmeta = _meta(spec, rname, svc.name)
            rselector = {"app.kubernetes.io/name": rname}
            rcontainer = dict(container)
            rcontainer["env"] = env + [
                {"name": "DYNTPU_NUM_PROCESSES", "value": str(svc.hosts_per_slice)},
                {
                    "name": "DYNTPU_COORDINATOR",
                    "value": f"{rname}-0.{rname}.{spec.namespace}.svc:8476",
                },
                {
                    "name": "DYNTPU_PROCESS_ID",
                    "valueFrom": {
                        "fieldRef": {
                            "fieldPath": "metadata.labels['apps.kubernetes.io/pod-index']"
                        }
                    },
                },
            ]
            objs.append(
                {
                    "apiVersion": "apps/v1",
                    "kind": "StatefulSet",
                    "metadata": rmeta,
                    "spec": {
                        "replicas": svc.hosts_per_slice,
                        "serviceName": rname,
                        "selector": {"matchLabels": rselector},
                        "template": {
                            "metadata": {"labels": dict(rmeta["labels"])},
                            "spec": {"containers": [rcontainer]},
                        },
                    },
                }
            )
            # per-slice headless service: gives pods stable DNS + the
            # coordinator address
            objs.append(
                {
                    "apiVersion": "v1",
                    "kind": "Service",
                    "metadata": rmeta,
                    "spec": {
                        "clusterIP": "None",
                        "selector": rselector,
                        "ports": [{"port": 8476}],
                    },
                }
            )
        if svc.port is not None:
            # cross-slice ClusterIP service exposing the serving port (selects
            # every slice's pods via the shared component label)
            objs.append(
                {
                    "apiVersion": "v1",
                    "kind": "Service",
                    "metadata": meta,
                    "spec": {
                        "selector": {
                            "app.kubernetes.io/part-of": spec.name,
                            "dynamo-tpu/component": svc.name,
                        },
                        "ports": [{"port": svc.port, "targetPort": svc.port}],
                    },
                }
            )
        return objs

    has_hpa = (
        svc.autoscaling is not None
        and svc.autoscaling.max_replicas > svc.autoscaling.min_replicas
    )
    deployment_spec: dict[str, Any] = {
        "selector": {"matchLabels": selector},
        "template": {
            "metadata": {"labels": dict(meta["labels"])},
            "spec": {"containers": [container]},
        },
    }
    # when an HPA owns the scale, pinning spec.replicas would reset the
    # autoscaler's decision on every apply — omit the field
    if not has_hpa:
        deployment_spec["replicas"] = svc.replicas
    objs.append(
        {
            "apiVersion": "apps/v1",
            "kind": "Deployment",
            "metadata": meta,
            "spec": deployment_spec,
        }
    )
    if svc.port is not None:
        objs.append(
            {
                "apiVersion": "v1",
                "kind": "Service",
                "metadata": meta,
                "spec": {
                    "selector": selector,
                    "ports": [{"port": svc.port, "targetPort": svc.port}],
                },
            }
        )
    if has_hpa:
        a = svc.autoscaling
        if a.metric == "cpu":
            metrics = [
                {
                    "type": "Resource",
                    "resource": {
                        "name": "cpu",
                        "target": {"type": "Utilization", "averageUtilization": a.target},
                    },
                }
            ]
        else:
            metrics = [
                {
                    "type": "Pods",
                    "pods": {
                        "metric": {"name": "llm_http_service_inflight_requests"},
                        "target": {"type": "AverageValue", "averageValue": str(a.target)},
                    },
                }
            ]
        objs.append(
            {
                "apiVersion": "autoscaling/v2",
                "kind": "HorizontalPodAutoscaler",
                "metadata": meta,
                "spec": {
                    "scaleTargetRef": {"apiVersion": "apps/v1", "kind": "Deployment", "name": name},
                    "minReplicas": a.min_replicas,
                    "maxReplicas": a.max_replicas,
                    "metrics": metrics,
                },
            }
        )
    return objs


def render_manifests(spec: DeploymentSpec) -> list[dict]:
    """Desired Kubernetes objects for a deployment spec (deterministic order:
    cplane infra first, then services in spec order)."""
    spec.validate()
    objs: list[dict] = []
    if spec.cplane == "managed":
        objs.extend(_cplane_manifests(spec))
    for svc in spec.services:
        objs.extend(_service_manifests(spec, svc))
    return objs


def manifests_yaml(spec: DeploymentSpec) -> str:
    import yaml

    return "\n---\n".join(yaml.safe_dump(o, sort_keys=False) for o in render_manifests(spec))


def render_build_job(
    name: str,
    image: str,
    context: str,
    namespace: str = "default",
    builder_image: str = "gcr.io/kaniko-project/executor:latest",
) -> dict:
    """In-cluster image-build Job for a packaged artifact (the reference's
    DynamoNimRequest image-build slot: its operator renders kaniko/buildkit
    Jobs from packaged artifacts — reference: deploy/dynamo/operator/internal/
    controller/dynamonimrequest_controller.go). ``context`` is the artifact
    location (a registry-hosted tar, git URL, or PVC-mounted path) holding
    the Containerfile `dynamo-tpu build` emitted; ``image`` is the
    destination tag the deployment's services will run."""
    job_name = f"{name}-image-build"
    return {
        "apiVersion": "batch/v1",
        "kind": "Job",
        "metadata": {
            "name": job_name,
            "namespace": namespace,
            "labels": {
                "app.kubernetes.io/name": job_name,
                "app.kubernetes.io/part-of": name,
                "app.kubernetes.io/managed-by": MANAGED_BY,
                "dynamo-tpu/component": "image-build",
            },
        },
        "spec": {
            "backoffLimit": 2,
            "ttlSecondsAfterFinished": 3600,
            "template": {
                "metadata": {"labels": {"app.kubernetes.io/part-of": name}},
                "spec": {
                    "restartPolicy": "Never",
                    "containers": [
                        {
                            "name": "build",
                            "image": builder_image,
                            "args": [
                                f"--context={context}",
                                "--dockerfile=Containerfile",
                                f"--destination={image}",
                            ],
                        }
                    ],
                },
            },
        },
    }


def _key(obj: dict) -> tuple:
    return (obj["kind"], obj["metadata"]["namespace"], obj["metadata"]["name"])


def _subset(desired, live) -> bool:
    """True when every field the reconciler renders matches the live object.

    A real API server decorates objects with uid/creationTimestamp/status and
    defaulted spec fields; whole-dict equality would flag every object as
    drifted forever. Dicts compare desired-keys-only, lists compare
    element-wise (length must match — k8s list fields are replaced, not
    merged, by server-side apply with our field manager)."""
    if isinstance(desired, dict):
        if not isinstance(live, dict):
            return False
        return all(k in live and _subset(v, live[k]) for k, v in desired.items())
    if isinstance(desired, list):
        if not isinstance(live, list) or len(desired) != len(live):
            return False
        return all(_subset(d, l) for d, l in zip(desired, live))
    return desired == live


def reconcile(spec: DeploymentSpec, live: list[dict]) -> dict[str, list[dict]]:
    """Diff desired state against a live snapshot.

    Returns {"create": [...], "update": [...], "delete": [...], "unchanged":
    [...]}: update = same kind/name but different content; delete = live
    objects managed by this deployment that the spec no longer wants."""
    desired = {_key(o): o for o in render_manifests(spec)}

    def _ours(o: dict) -> bool:
        labels = o.get("metadata", {}).get("labels", {})
        # ownership requires BOTH labels: part-of is a shared convention other
        # tools also set, managed-by marks objects this reconciler created
        return (
            labels.get("app.kubernetes.io/part-of") == spec.name
            and labels.get("app.kubernetes.io/managed-by") == MANAGED_BY
        )

    live_by_key = {_key(o): o for o in live if _ours(o)}
    actions: dict[str, list[dict]] = {"create": [], "update": [], "delete": [], "unchanged": []}
    for key, obj in desired.items():
        if key not in live_by_key:
            actions["create"].append(obj)
        elif not _subset(obj, live_by_key[key]):
            actions["update"].append(obj)
        else:
            actions["unchanged"].append(obj)
    for key, obj in live_by_key.items():
        if key not in desired:
            actions["delete"].append(obj)
    return actions
