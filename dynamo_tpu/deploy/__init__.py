"""Deploy plane: typed deployment specs, reconciler-style manifest
generation, and a REST deployment API server.

Fills the reference's §2.7 slot (K8s operator CRDs + reconcilers in Go,
reference: deploy/dynamo/operator/api/v1alpha1/, API server at
deploy/dynamo/api-server/api/) with a Python-native equivalent: the CRD
types are dataclasses, the reconciler is a pure spec -> manifests function
(testable without a cluster, like the operator's resource unit tests), and
the API server stores deployments + revision history behind a pluggable
store.
"""

from dynamo_tpu.deploy.crd import DeploymentSpec, ServiceSpec, Autoscaling
from dynamo_tpu.deploy.reconciler import render_manifests, reconcile

__all__ = [
    "DeploymentSpec",
    "ServiceSpec",
    "Autoscaling",
    "render_manifests",
    "reconcile",
]
