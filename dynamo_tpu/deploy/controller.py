"""Deploy controller: the live reconcile loop over desired state.

The reference's operator runs a controller-runtime loop — watch
DynamoNimDeployment CRs, render owned resources, converge the cluster,
write status back (reference: deploy/dynamo/operator/internal/controller/
dynamonimdeployment_controller.go Reconcile/ownership semantics). This is
that loop for the TPU stack: desired state comes from the DeploymentStore
(the API server's revision history), the cluster side is a pluggable
``ClusterApi`` (an in-memory fake for tests, a kubectl shim for real
clusters), and each pass repairs drift — objects deleted or mutated out from
under the controller converge back to the rendered manifests on the next
tick. Deleted deployments are garbage-collected by ownership labels.
"""

from __future__ import annotations

import asyncio
import time
from typing import Optional, Protocol

from dynamo_tpu.deploy.crd import DeploymentSpec
from dynamo_tpu.deploy.reconciler import MANAGED_BY, reconcile
from dynamo_tpu.utils import get_logger

log = get_logger("deploy.controller")


class ClusterApi(Protocol):
    """The minimal cluster surface the controller converges against."""

    async def list_objects(self, namespace: str) -> list[dict]: ...

    async def list_managed_namespaces(self) -> set[str]: ...

    async def apply(self, obj: dict) -> None: ...

    async def delete(self, kind: str, namespace: str, name: str) -> None: ...


class FakeCluster:
    """In-memory ClusterApi: unit-testable stand-in for a k8s API server.

    Tests inject drift by mutating/deleting entries in ``objects`` directly
    (the out-of-band actor) and can consult ``applied``/``deleted`` action
    logs to assert what the controller did."""

    def __init__(self):
        self.objects: dict[tuple, dict] = {}  # (kind, ns, name) -> object
        self.applied: list[tuple] = []
        self.deleted: list[tuple] = []

    @staticmethod
    def _key(obj: dict) -> tuple:
        return (obj["kind"], obj["metadata"]["namespace"], obj["metadata"]["name"])

    async def list_objects(self, namespace: str) -> list[dict]:
        import copy

        return [
            copy.deepcopy(o)
            for (kind, ns, _), o in self.objects.items()
            if ns == namespace
        ]

    async def list_managed_namespaces(self) -> set[str]:
        return {
            ns
            for (_, ns, _), o in self.objects.items()
            if o.get("metadata", {}).get("labels", {}).get(
                "app.kubernetes.io/managed-by"
            ) == MANAGED_BY
        }

    async def apply(self, obj: dict) -> None:
        import copy

        key = self._key(obj)
        obj = copy.deepcopy(obj)
        if obj["kind"] == "Job" and "status" not in obj:
            # the fake has no job controller: simulate instant success so the
            # build reconciler exercises the same condition-reading path a
            # real cluster drives
            obj["status"] = {
                "succeeded": 1,
                "conditions": [{"type": "Complete", "status": "True"}],
            }
        self.objects[key] = obj
        self.applied.append(key)

    async def delete(self, kind: str, namespace: str, name: str) -> None:
        self.objects.pop((kind, namespace, name), None)
        self.deleted.append((kind, namespace, name))


class KubectlCluster:
    """ClusterApi over kubectl (server-side apply); the real-cluster shim."""

    def __init__(self, kubectl: str = "kubectl"):
        self.kubectl = kubectl

    async def _run(self, *args: str, stdin: Optional[bytes] = None) -> bytes:
        proc = await asyncio.create_subprocess_exec(
            self.kubectl, *args,
            stdin=asyncio.subprocess.PIPE if stdin is not None else None,
            stdout=asyncio.subprocess.PIPE,
            stderr=asyncio.subprocess.PIPE,
        )
        out, err = await proc.communicate(stdin)
        if proc.returncode != 0:
            raise RuntimeError(f"kubectl {' '.join(args)}: {err.decode()[-500:]}")
        return out

    async def list_objects(self, namespace: str) -> list[dict]:
        import json

        out = await self._run(
            "get", "deployments,statefulsets,services,horizontalpodautoscalers,jobs",
            "-n", namespace, "-l", f"app.kubernetes.io/managed-by={MANAGED_BY}",
            "-o", "json",
        )
        return json.loads(out).get("items", [])

    async def list_managed_namespaces(self) -> set[str]:
        import json

        out = await self._run(
            "get", "deployments,statefulsets,services,horizontalpodautoscalers,jobs",
            "--all-namespaces", "-l", f"app.kubernetes.io/managed-by={MANAGED_BY}",
            "-o", "json",
        )
        return {
            i.get("metadata", {}).get("namespace", "default")
            for i in json.loads(out).get("items", [])
        }

    async def apply(self, obj: dict) -> None:
        import json

        await self._run(
            "apply", "-f", "-", "--server-side", "--field-manager", MANAGED_BY,
            stdin=json.dumps(obj).encode(),
        )

    async def delete(self, kind: str, namespace: str, name: str) -> None:
        await self._run("delete", kind.lower(), name, "-n", namespace, "--ignore-not-found")


class DeployController:
    """Poll the store's head revisions, converge the cluster, write status."""

    def __init__(self, store, cluster: ClusterApi, interval: float = 2.0,
                 build_job_grace_s: float = 60.0, build_job_max_reapplies: int = 3):
        self.store = store
        self.cluster = cluster
        self.interval = interval
        # a 'building' record whose Job object vanished (TTL GC while the
        # controller was down, out-of-band kubectl delete) must not wedge
        # forever: after the grace period re-apply the Job, and after
        # max_reapplies give up and mark the build failed
        self.build_job_grace_s = build_job_grace_s
        self.build_job_max_reapplies = build_job_max_reapplies
        self._task: Optional[asyncio.Task] = None
        self._kick = asyncio.Event()
        # deployments this controller has managed: name -> namespace; needed
        # to garbage-collect objects after a deployment disappears from the
        # store (the operator's finalizer/ownership slot)
        self._managed: dict[str, str] = {}
        self.passes = 0

    # ---------------- lifecycle ----------------

    async def start(self) -> "DeployController":
        self._task = asyncio.create_task(self._loop())
        return self

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass

    def kick(self) -> None:
        """Wake the loop immediately (API server calls this on spec changes)."""
        self._kick.set()

    async def _loop(self) -> None:
        while True:
            try:
                await self.converge_once()
            except Exception:
                log.exception("converge pass failed")
            try:
                await asyncio.wait_for(self._kick.wait(), self.interval)
            except asyncio.TimeoutError:
                pass
            self._kick.clear()

    # ---------------- one reconcile pass ----------------

    async def _converge_builds(self) -> None:
        """Apply pending image-build Jobs and track their completion (the
        DynamoNimRequest reconcile slot). The Job object's cluster state is
        the source of truth: once its status reports success the build is
        complete and the recorded image tag is usable by deployments."""
        for name in self.store.list_builds():
            rec = self.store.get_build(name)
            if rec is None:
                continue
            if rec["phase"] == "pending":
                try:
                    # a replacement build reuses the Job name and k8s Jobs have
                    # an immutable spec.template: clear any prior Job first
                    # (delete is ignore-not-found, so fresh builds are a no-op)
                    meta = rec["job"]["metadata"]
                    await self.cluster.delete("Job", meta["namespace"], meta["name"])
                    await self.cluster.apply(rec["job"])
                except Exception:
                    log.exception("build job apply failed for %s", name)
                    continue
                rec = {**rec, "phase": "building", "job_applied_at": time.time()}
                self.store.put_build(name, rec)
            if rec["phase"] == "building":
                job_name = rec["job"]["metadata"]["name"]
                ns = rec["job"]["metadata"]["namespace"]
                found = False
                for obj in await self.cluster.list_objects(ns):
                    if (
                        obj.get("kind") == "Job"
                        and obj["metadata"]["name"] == job_name
                    ):
                        found = True
                        # the Job's terminal CONDITIONS are the signal — pod
                        # counts lie (a retry that succeeds leaves failed > 0,
                        # and status is empty before the job controller runs)
                        conds = {
                            c.get("type"): c.get("status")
                            for c in obj.get("status", {}).get("conditions", [])
                        }
                        if conds.get("Complete") == "True":
                            self.store.put_build(
                                name,
                                {**rec, "phase": "complete", "completed_at": time.time()},
                            )
                        elif conds.get("Failed") == "True":
                            self.store.put_build(name, {**rec, "phase": "failed"})
                        break
                if not found:
                    # Job vanished before a terminal condition was observed
                    # (ttlSecondsAfterFinished GC while the controller was
                    # down, or out-of-band deletion): after the grace period
                    # re-apply it; after max_reapplies the build fails rather
                    # than wedging in 'building' permanently
                    age = time.time() - rec.get("job_applied_at", 0)
                    if age > self.build_job_grace_s:
                        reapplies = rec.get("job_reapplies", 0)
                        if reapplies >= self.build_job_max_reapplies:
                            log.warning(
                                "build %s: job %s/%s missing after %d re-applies; failing",
                                name, ns, job_name, reapplies,
                            )
                            self.store.put_build(
                                name, {**rec, "phase": "failed",
                                       "failure": "build Job disappeared before completion"},
                            )
                        else:
                            log.warning(
                                "build %s: job %s/%s missing %.0fs after apply; re-applying",
                                name, ns, job_name, age,
                            )
                            # count the ATTEMPT before applying: a permanently
                            # failing apply (namespace gone) must still burn
                            # through max_reapplies and reach 'failed' rather
                            # than retrying forever
                            rec = {**rec, "job_applied_at": time.time(),
                                   "job_reapplies": reapplies + 1}
                            self.store.put_build(name, rec)
                            try:
                                await self.cluster.apply(rec["job"])
                            except Exception:
                                log.exception("build job re-apply failed for %s", name)

    async def converge_once(self) -> dict[str, dict]:
        """Converge every deployment in the store; returns per-name action
        counts (for tests/observability)."""
        self.passes += 1
        await self._converge_builds()
        summary: dict[str, dict] = {}
        names = set(self.store.list())
        for name in sorted(names):
            head = self.store.head(name)
            if head is None:
                continue
            spec = DeploymentSpec.from_dict(head["spec"])
            live = await self.cluster.list_objects(spec.namespace)
            actions = reconcile(spec, live)
            for obj in actions["create"] + actions["update"]:
                await self.cluster.apply(obj)
            for obj in actions["delete"]:
                meta = obj["metadata"]
                await self.cluster.delete(obj["kind"], meta["namespace"], meta["name"])
            self._managed[name] = spec.namespace
            status = {
                "observed_revision": head["revision"],
                "created": len(actions["create"]),
                "updated": len(actions["update"]),
                "deleted": len(actions["delete"]),
                "unchanged": len(actions["unchanged"]),
                "converged": not (actions["create"] or actions["update"] or actions["delete"]),
                "last_reconcile": time.time(),
            }
            self.store.set_status(name, status)
            summary[name] = status
        # garbage-collect by OWNERSHIP LABELS, not in-process memory: any
        # managed object whose part-of deployment is absent from the store is
        # an orphan — this also catches deployments deleted while the
        # controller was down (a restarted controller's _managed starts
        # empty), in ANY namespace: the cluster-wide label-selector listing
        # finds managed namespaces no store head or in-process state names.
        sweep_namespaces = set(self._managed.values()) | {"default"}
        # minimal ClusterApi impls may not expose a cluster-wide listing:
        # detect absence with getattr so an AttributeError raised INSIDE a
        # real implementation (bad kubectl JSON, etc.) isn't silently eaten
        list_managed = getattr(self.cluster, "list_managed_namespaces", None)
        if list_managed is not None:
            try:
                sweep_namespaces |= await list_managed()
            except NotImplementedError:
                pass  # explicit opt-out: store/in-process sweep only
            except Exception:
                log.exception("list_managed_namespaces failed; skipping cluster-wide orphan sweep")
        for name in list(self._managed):
            if name not in names:
                del self._managed[name]
        for head_name in names:
            head = self.store.head(head_name)
            if head is not None:
                sweep_namespaces.add(head["spec"].get("namespace", "default"))
        # image-build Jobs are owned by BUILD records, not deployment heads:
        # their part-of must not read as an orphaned deployment
        build_owners = set(self.store.list_builds())
        for ns in sorted(sweep_namespaces):
            for obj in await self.cluster.list_objects(ns):
                labels = obj.get("metadata", {}).get("labels", {})
                owner = labels.get("app.kubernetes.io/part-of")
                if (
                    labels.get("app.kubernetes.io/managed-by") == MANAGED_BY
                    and owner is not None
                    and owner not in names
                    and not (
                        owner in build_owners
                        and labels.get("dynamo-tpu/component") == "image-build"
                    )
                ):
                    meta = obj["metadata"]
                    await self.cluster.delete(obj["kind"], meta["namespace"], meta["name"])
                    summary[owner] = {"garbage_collected": True}
        return summary


def main(argv: Optional[list] = None) -> int:
    """Run the controller as a daemon: API server + converge loop in one
    process (the operator deployment slot). --kubectl targets a real cluster;
    default is a FakeCluster (dry-run mode that logs actions)."""
    import argparse

    ap = argparse.ArgumentParser("dynamo-tpu-deploy-controller")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=8090)
    ap.add_argument("--interval", type=float, default=5.0)
    ap.add_argument("--store", default=None, help="JSON file store path")
    ap.add_argument("--kubectl", action="store_true", help="apply to the real cluster via kubectl")
    args = ap.parse_args(argv)

    async def run():
        from dynamo_tpu.deploy.api_server import (
            DeployApiServer,
            DeploymentStore,
            FileDeploymentStore,
        )

        store = FileDeploymentStore(args.store) if args.store else DeploymentStore()
        cluster = KubectlCluster() if args.kubectl else FakeCluster()
        ctrl = await DeployController(store, cluster, interval=args.interval).start()
        server = DeployApiServer(store, controller=ctrl)
        port = await server.start(args.host, args.port)
        log.info("deploy controller up: api=%s:%d cluster=%s", args.host, port,
                 "kubectl" if args.kubectl else "fake")
        try:
            await asyncio.Event().wait()
        finally:
            await server.stop()
            await ctrl.stop()

    try:
        asyncio.run(run())
    except KeyboardInterrupt:
        pass
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
