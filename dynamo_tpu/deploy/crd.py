"""Deployment CRD types (the DynamoDeployment / DynamoNimDeployment analogue).

reference: deploy/dynamo/operator/api/v1alpha1/ defines DynamoDeployment (a
graph of services) and DynamoNimDeployment (one component: replicas,
resources, autoscaling, ingress). Here both collapse into one typed spec: a
`DeploymentSpec` carries the graph, each `ServiceSpec` a component. TPU
resources replace GPU counts (`tpu_chips` -> `google.com/tpu` limits).
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field, asdict
from typing import Any, Optional

_NAME_RE = re.compile(r"^[a-z0-9]([a-z0-9-]{0,61}[a-z0-9])?$")  # dns-1123 label


class SpecError(ValueError):
    pass


@dataclass
class Autoscaling:
    """HPA-shaped autoscaling block (reference: DynamoNimDeployment
    spec.autoscaling)."""

    min_replicas: int = 1
    max_replicas: int = 1
    # scale on the frontend's inflight-requests gauge (custom metric) or cpu
    metric: str = "cpu"
    target: int = 80

    def validate(self) -> None:
        if self.min_replicas < 0 or self.max_replicas < self.min_replicas:
            raise SpecError("autoscaling: need 0 <= min_replicas <= max_replicas")
        if self.metric not in ("cpu", "inflight_requests"):
            raise SpecError(f"autoscaling: unknown metric {self.metric!r}")


@dataclass
class ServiceSpec:
    """One component of the serving graph (frontend/processor/worker/...)."""

    name: str
    command: list[str] = field(default_factory=list)  # container args
    replicas: int = 1
    tpu_chips: int = 0  # google.com/tpu resource limit per pod
    port: Optional[int] = None  # exposes a Service when set
    env: dict[str, str] = field(default_factory=dict)
    config: dict[str, Any] = field(default_factory=dict)  # DYNTPU_SERVICE_CONFIG section
    autoscaling: Optional[Autoscaling] = None
    # multihost TPU slice: pods-per-slice; >1 renders a headless service +
    # per-pod DYNTPU_PROCESS_ID wiring (dynamo_tpu/parallel/mesh.py)
    hosts_per_slice: int = 1

    def validate(self) -> None:
        if not _NAME_RE.match(self.name):
            raise SpecError(f"service name {self.name!r} is not a dns-1123 label")
        if self.replicas < 0:
            raise SpecError(f"{self.name}: replicas < 0")
        if self.tpu_chips < 0:
            raise SpecError(f"{self.name}: tpu_chips < 0")
        if self.hosts_per_slice < 1:
            raise SpecError(f"{self.name}: hosts_per_slice < 1")
        if self.port is not None and not (0 < self.port < 65536):
            raise SpecError(f"{self.name}: bad port {self.port}")
        if self.autoscaling is not None:
            self.autoscaling.validate()
            if self.hosts_per_slice > 1:
                raise SpecError(
                    f"{self.name}: autoscaling is not supported for multihost "
                    "slices (hosts_per_slice > 1); scale with `replicas` instead"
                )


@dataclass
class DeploymentSpec:
    """The full graph deployment (DynamoDeployment analogue)."""

    name: str
    image: str = "dynamo-tpu:latest"
    namespace: str = "default"
    services: list[ServiceSpec] = field(default_factory=list)
    # control-plane broker address injected into every service; "managed"
    # renders the built-in cplane Deployment too
    cplane: str = "managed"

    def validate(self) -> None:
        if not _NAME_RE.match(self.name):
            raise SpecError(f"deployment name {self.name!r} is not a dns-1123 label")
        if not self.services:
            raise SpecError("deployment has no services")
        seen = set()
        for svc in self.services:
            svc.validate()
            if svc.name in seen:
                raise SpecError(f"duplicate service {svc.name!r}")
            seen.add(svc.name)

    # ---------------- (de)serialization ----------------

    def to_dict(self) -> dict:
        return asdict(self)

    @classmethod
    def from_dict(cls, d: dict) -> "DeploymentSpec":
        try:
            services = []
            for s in d.get("services", []):
                s = dict(s)
                auto = s.pop("autoscaling", None)
                svc = ServiceSpec(**s)
                if auto:
                    svc.autoscaling = Autoscaling(**auto)
                services.append(svc)
            spec = cls(
                name=d["name"],
                image=d.get("image", "dynamo-tpu:latest"),
                namespace=d.get("namespace", "default"),
                services=services,
                cplane=d.get("cplane", "managed"),
            )
        except (KeyError, TypeError) as e:
            raise SpecError(f"bad deployment spec: {e}") from e
        spec.validate()
        return spec

    @classmethod
    def from_yaml(cls, path_or_text: str) -> "DeploymentSpec":
        import yaml
        from pathlib import Path

        text = path_or_text
        if "\n" not in path_or_text and Path(path_or_text).exists():
            text = Path(path_or_text).read_text()
        return cls.from_dict(yaml.safe_load(text))
