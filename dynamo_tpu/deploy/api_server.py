"""Deployment API server: REST CRUD over deployments with revision history.

The reference ships a Go API server (clusters/deployments/revisions CRUD,
Postgres-backed, deploys via the operator — reference:
deploy/dynamo/api-server/api/{routes,controllers,services}/). This is the
Python-native slot: aiohttp routes over a pluggable store (in-memory or
file-backed JSON — the fixture-backend pattern of the reference's
integration suite, reference: api-server/tests/integration/fixtures/
backendStorage.go), and "deploy" renders the reconciler's manifests instead
of calling a live cluster.

Routes (all JSON):
  GET    /healthz
  GET    /api/v1/clusters                      implicit local + registered
  GET    /api/v1/deployments                   list
  POST   /api/v1/deployments                   create (spec in body)
  GET    /api/v1/deployments/{name}            current spec + revision meta
  PUT    /api/v1/deployments/{name}            update -> new revision
  DELETE /api/v1/deployments/{name}
  GET    /api/v1/deployments/{name}/revisions  history (newest first)
  POST   /api/v1/deployments/{name}/rollback/{rev}
  GET    /api/v1/deployments/{name}/manifests  rendered k8s objects
  GET    /api/v1/deployments/{name}/status     controller status writeback
  POST   /api/v1/builds                        register an image build (Job)
  GET    /api/v1/builds[/{name}]               build records / phase
  GET|POST /api/v1/clusters, GET|DELETE /api/v1/clusters/{name}
  GET|POST /api/v1/deployment-targets[/{name}], DELETE .../{name}
  GET|POST /api/v1/components, GET|DELETE /api/v1/components/{name}
"""

from __future__ import annotations

import json
import re
import time
from pathlib import Path
from typing import Optional

from aiohttp import web

from dynamo_tpu.deploy.crd import DeploymentSpec, SpecError
from dynamo_tpu.deploy.reconciler import render_manifests
from dynamo_tpu.utils import get_logger

log = get_logger("deploy.api")

#: kubernetes object-name shape; used for every name that can reach kubectl
DNS1123 = r"[a-z0-9]([a-z0-9-]{0,61}[a-z0-9])?"


def _version_key(v: str):
    """Natural ordering: '1.10' > '1.9', non-numeric parts compare as text."""
    return [int(t) if t.isdigit() else t for t in re.split(r"(\d+)", v)]


class DeploymentStore:
    """In-memory store: name -> list of revision records (oldest first)."""

    def __init__(self):
        self._data: dict[str, list[dict]] = {}
        self._status: dict[str, dict] = {}  # controller-written status
        self._builds: dict[str, dict] = {}  # image-build records
        # registry collections (the reference API server's clusters /
        # deployment-target / component routes, reference:
        # deploy/dynamo/api-server/api/routes/{cluster,deployment_target,
        # dynamo_component}.go): kind -> name -> record
        self._registry: dict[str, dict[str, dict]] = {
            "clusters": {}, "deployment_targets": {}, "components": {},
        }

    # ---- registry collections ----

    def put_item(self, kind: str, name: str, record: dict) -> None:
        self._registry[kind][name] = record
        self._flush_registry(kind, name)

    def get_item(self, kind: str, name: str) -> Optional[dict]:
        return self._registry[kind].get(name)

    def list_items(self, kind: str) -> list[str]:
        return sorted(self._registry[kind])

    def delete_item(self, kind: str, name: str) -> bool:
        existed = name in self._registry[kind]
        self._registry[kind].pop(name, None)
        self._flush_registry(kind, name)
        return existed

    def _flush_registry(self, kind: str, name: str) -> None:
        pass

    def put_build(self, name: str, record: dict) -> None:
        self._builds[name] = record
        self._flush_build(name)

    def get_build(self, name: str) -> Optional[dict]:
        return self._builds.get(name)

    def list_builds(self) -> list[str]:
        return sorted(self._builds)

    def _flush_build(self, name: str) -> None:
        pass

    def list(self) -> list[str]:
        return sorted(self._data)

    def set_status(self, name: str, status: dict) -> None:
        """Controller status writeback (the CR .status slot)."""
        self._status[name] = status

    def get_status(self, name: str) -> Optional[dict]:
        return self._status.get(name)

    def revisions(self, name: str) -> list[dict]:
        return list(self._data.get(name, []))

    def head(self, name: str) -> Optional[dict]:
        revs = self._data.get(name)
        return revs[-1] if revs else None

    def put(self, name: str, spec: dict) -> dict:
        revs = self._data.setdefault(name, [])
        record = {
            "revision": (revs[-1]["revision"] + 1) if revs else 1,
            "created_at": time.time(),
            "spec": spec,
        }
        revs.append(record)
        self._flush()
        return record

    def delete(self, name: str) -> bool:
        existed = name in self._data
        self._data.pop(name, None)
        self._status.pop(name, None)
        self._flush()
        return existed

    def _flush(self) -> None:
        pass


class FileDeploymentStore(DeploymentStore):
    """JSON-file-backed store (kept for fixture-style tests; rewrites the
    whole file per mutation — use SqliteDeploymentStore for durability)."""

    def __init__(self, path: str | Path):
        super().__init__()
        self._path = Path(path)
        if self._path.exists():
            loaded = json.loads(self._path.read_text())
            # new format always writes BOTH keys with revisions a dict; a
            # legacy file could legitimately hold a deployment named
            # "revisions" (valid DNS-1123), so require the full shape
            if (
                isinstance(loaded, dict)
                and isinstance(loaded.get("revisions"), dict)
                and "builds" in loaded
            ):
                self._data = loaded["revisions"]
                self._builds = loaded.get("builds", {})
                for kind, items in loaded.get("registry", {}).items():
                    self._registry.setdefault(kind, {}).update(items)
            else:
                # pre-builds format: the whole file is the revisions map
                self._data = loaded

    def _flush(self) -> None:
        self._path.write_text(
            json.dumps({"revisions": self._data, "builds": self._builds,
                        "registry": self._registry})
        )

    def _flush_build(self, name: str) -> None:
        # builds must survive restarts too (they used to silently vanish:
        # only revisions were written to the JSON file)
        self._flush()

    def _flush_registry(self, kind: str, name: str) -> None:
        self._flush()


class SqliteDeploymentStore(DeploymentStore):
    """sqlite-backed store — the durable-DB slot (the reference's API server
    is Postgres-backed, reference: deploy/dynamo/api-server/api/database/
    database.go). Every mutation is one transactional INSERT/UPDATE/DELETE
    (WAL mode), not a whole-file rewrite; state survives process restarts."""

    def __init__(self, path: str | Path):
        import sqlite3

        super().__init__()
        self._db = sqlite3.connect(str(path))
        with self._db:
            self._db.execute("PRAGMA journal_mode=WAL")
            self._db.execute(
                "CREATE TABLE IF NOT EXISTS revisions ("
                " name TEXT NOT NULL, revision INTEGER NOT NULL,"
                " created_at REAL NOT NULL, spec TEXT NOT NULL,"
                " PRIMARY KEY (name, revision))"
            )
            self._db.execute(
                "CREATE TABLE IF NOT EXISTS status ("
                " name TEXT PRIMARY KEY, status TEXT NOT NULL)"
            )
            self._db.execute(
                "CREATE TABLE IF NOT EXISTS builds ("
                " name TEXT PRIMARY KEY, record TEXT NOT NULL)"
            )
            self._db.execute(
                "CREATE TABLE IF NOT EXISTS registry ("
                " kind TEXT NOT NULL, name TEXT NOT NULL, record TEXT NOT NULL,"
                " PRIMARY KEY (kind, name))"
            )
        for name, record in self._db.execute("SELECT name, record FROM builds"):
            self._builds[name] = json.loads(record)
        for kind, name, record in self._db.execute(
            "SELECT kind, name, record FROM registry"
        ):
            self._registry.setdefault(kind, {})[name] = json.loads(record)
        for name, revision, created_at, spec in self._db.execute(
            "SELECT name, revision, created_at, spec FROM revisions"
            " ORDER BY name, revision"
        ):
            self._data.setdefault(name, []).append(
                {"revision": revision, "created_at": created_at, "spec": json.loads(spec)}
            )
        for name, status in self._db.execute("SELECT name, status FROM status"):
            self._status[name] = json.loads(status)

    def put(self, name: str, spec: dict) -> dict:
        revs = self._data.setdefault(name, [])
        record = {
            "revision": (revs[-1]["revision"] + 1) if revs else 1,
            "created_at": time.time(),
            "spec": spec,
        }
        with self._db:
            self._db.execute(
                "INSERT INTO revisions (name, revision, created_at, spec)"
                " VALUES (?, ?, ?, ?)",
                (name, record["revision"], record["created_at"], json.dumps(spec)),
            )
        revs.append(record)
        return record

    def delete(self, name: str) -> bool:
        existed = name in self._data
        with self._db:
            self._db.execute("DELETE FROM revisions WHERE name = ?", (name,))
            self._db.execute("DELETE FROM status WHERE name = ?", (name,))
        self._data.pop(name, None)
        self._status.pop(name, None)
        return existed

    def set_status(self, name: str, status: dict) -> None:
        self._status[name] = status
        with self._db:
            self._db.execute(
                "INSERT INTO status (name, status) VALUES (?, ?)"
                " ON CONFLICT(name) DO UPDATE SET status = excluded.status",
                (name, json.dumps(status)),
            )

    def _flush_build(self, name: str) -> None:
        with self._db:
            self._db.execute(
                "INSERT INTO builds (name, record) VALUES (?, ?)"
                " ON CONFLICT(name) DO UPDATE SET record = excluded.record",
                (name, json.dumps(self._builds[name])),
            )

    def _flush_registry(self, kind: str, name: str) -> None:
        with self._db:
            record = self._registry[kind].get(name)
            if record is None:
                self._db.execute(
                    "DELETE FROM registry WHERE kind = ? AND name = ?", (kind, name)
                )
            else:
                self._db.execute(
                    "INSERT INTO registry (kind, name, record) VALUES (?, ?, ?)"
                    " ON CONFLICT(kind, name) DO UPDATE SET record = excluded.record",
                    (kind, name, json.dumps(record)),
                )

    def close(self) -> None:
        self._db.close()


class DeployApiServer:
    def __init__(self, store: Optional[DeploymentStore] = None, controller=None):
        self.store = store or DeploymentStore()
        # optional live DeployController: spec mutations kick an immediate
        # converge instead of waiting for the next periodic pass
        self.controller = controller
        self.app = web.Application()
        self.app.add_routes(
            [
                web.get("/healthz", self._health),
                web.get("/api/v1/clusters", self._clusters),
                web.get("/api/v1/deployments", self._list),
                web.post("/api/v1/deployments", self._create),
                web.get("/api/v1/deployments/{name}", self._get),
                web.put("/api/v1/deployments/{name}", self._update),
                web.delete("/api/v1/deployments/{name}", self._delete),
                web.get("/api/v1/deployments/{name}/revisions", self._revisions),
                web.post("/api/v1/deployments/{name}/rollback/{rev}", self._rollback),
                web.get("/api/v1/deployments/{name}/manifests", self._manifests),
                web.get("/api/v1/deployments/{name}/status", self._status),
                web.post("/api/v1/builds", self._create_build),
                web.get("/api/v1/builds", self._list_builds),
                web.get("/api/v1/builds/{name}", self._get_build),
                # registry collections (reference: api-server routes/
                # {cluster,deployment_target,dynamo_component}.go)
                web.post("/api/v1/clusters", self._registry_create("clusters")),
                web.get("/api/v1/clusters/{name}", self._registry_get("clusters")),
                web.delete("/api/v1/clusters/{name}", self._registry_delete("clusters")),
                web.get("/api/v1/deployment-targets", self._registry_list("deployment_targets")),
                web.post("/api/v1/deployment-targets", self._registry_create("deployment_targets")),
                web.get("/api/v1/deployment-targets/{name}", self._registry_get("deployment_targets")),
                web.delete("/api/v1/deployment-targets/{name}", self._registry_delete("deployment_targets")),
                web.get("/api/v1/components", self._list_components),
                web.post("/api/v1/components", self._register_component),
                web.get("/api/v1/components/{name}", self._get_component),
                web.delete("/api/v1/components/{name}", self._registry_delete("components")),
            ]
        )
        self._runner: Optional[web.AppRunner] = None
        self.port: Optional[int] = None

    def _kick(self) -> None:
        if self.controller is not None:
            self.controller.kick()

    # ---------------- lifecycle ----------------

    async def start(self, host: str = "127.0.0.1", port: int = 0) -> int:
        self._runner = web.AppRunner(self.app)
        await self._runner.setup()
        site = web.TCPSite(self._runner, host, port)
        await site.start()
        self.port = site._server.sockets[0].getsockname()[1]
        log.info("deploy api listening on %s:%d", host, self.port)
        return self.port

    async def stop(self) -> None:
        if self._runner is not None:
            await self._runner.cleanup()
            self._runner = None

    # ---------------- handlers ----------------

    async def _health(self, request: web.Request) -> web.Response:
        return web.json_response({"status": "ok"})

    async def _clusters(self, request: web.Request) -> web.Response:
        """The implicit local cluster plus every registered one (reference:
        routes/cluster.go list)."""
        items = [{"name": "default", "accelerator": "tpu",
                  "deployments": len(self.store.list())}]
        for name in self.store.list_items("clusters"):
            rec = self.store.get_item("clusters", name)
            items.append({"name": name, **{k: v for k, v in rec.items() if k != "name"}})
        return web.json_response({"clusters": items})

    # ---- registry collections (clusters / deployment-targets / components) ----

    def _registry_create(self, kind: str):
        async def handler(request: web.Request) -> web.Response:
            try:
                body = await request.json()
            except json.JSONDecodeError as e:
                return web.json_response({"error": f"bad json: {e}"}, status=400)
            if not isinstance(body, dict) or not body.get("name"):
                return web.json_response({"error": "name is required"}, status=422)
            name = str(body["name"])
            if not re.fullmatch(DNS1123, name):
                return web.json_response(
                    {"error": f"name {name!r} must be DNS-1123"}, status=422
                )
            if kind == "clusters" and name == "default":
                return web.json_response(
                    {"error": "cluster 'default' is implicit"}, status=409
                )
            if self.store.get_item(kind, name) is not None:
                return web.json_response(
                    {"error": f"{kind[:-1]} {name} exists"}, status=409
                )
            record = {**body, "created_at": time.time()}
            self.store.put_item(kind, name, record)
            return web.json_response({"name": name}, status=201)

        return handler

    def _registry_list(self, kind: str):
        async def handler(request: web.Request) -> web.Response:
            items = [
                self.store.get_item(kind, name)
                for name in self.store.list_items(kind)
            ]
            return web.json_response({kind.replace("_", "-"): items})

        return handler

    def _registry_get(self, kind: str):
        async def handler(request: web.Request) -> web.Response:
            name = request.match_info["name"]
            if kind == "clusters" and name == "default":
                # the implicit local cluster the list endpoint advertises
                return web.json_response({
                    "name": "default", "accelerator": "tpu",
                    "deployments": len(self.store.list()),
                })
            rec = self.store.get_item(kind, name)
            if rec is None:
                return web.json_response({"error": "not found"}, status=404)
            return web.json_response(rec)

        return handler

    def _registry_delete(self, kind: str):
        async def handler(request: web.Request) -> web.Response:
            name = request.match_info["name"]
            if kind == "clusters" and name == "default":
                return web.json_response(
                    {"error": "cluster 'default' is implicit"}, status=409
                )
            if not self.store.delete_item(kind, name):
                return web.json_response({"error": "not found"}, status=404)
            return web.json_response({"deleted": name})

        return handler

    async def _register_component(self, request: web.Request) -> web.Response:
        """Component registry: versioned artifacts a deployment references
        (reference: routes/dynamo_component.go — NIM component versions)."""
        try:
            body = await request.json()
        except json.JSONDecodeError as e:
            return web.json_response({"error": f"bad json: {e}"}, status=400)
        if not isinstance(body, dict):
            return web.json_response({"error": "body must be a JSON object"}, status=400)
        name, version = body.get("name"), body.get("version")
        if not (name and version):
            return web.json_response(
                {"error": "name and version are required"}, status=422
            )
        name, version = str(name), str(version)
        if not re.fullmatch(DNS1123, name):
            return web.json_response(
                {"error": f"name {name!r} must be DNS-1123"}, status=422
            )
        rec = self.store.get_item("components", name) or {"name": name, "versions": {}}
        if version in rec["versions"]:
            return web.json_response(
                {"error": f"component {name}:{version} exists"}, status=409
            )
        rec["versions"][version] = {
            **{k: v for k, v in body.items() if k not in ("name", "version")},
            "created_at": time.time(),
        }
        # highest by natural order, not most-recently-registered: backfilling
        # an old version must not downgrade latest
        rec["latest"] = max(rec["versions"], key=_version_key)
        self.store.put_item("components", name, rec)
        return web.json_response({"name": name, "version": str(version)}, status=201)

    async def _list_components(self, request: web.Request) -> web.Response:
        items = []
        for name in self.store.list_items("components"):
            rec = self.store.get_item("components", name)
            items.append({"name": name, "latest": rec.get("latest"),
                          "versions": sorted(rec["versions"], key=_version_key)})
        return web.json_response({"components": items})

    async def _get_component(self, request: web.Request) -> web.Response:
        rec = self.store.get_item("components", request.match_info["name"])
        if rec is None:
            return web.json_response({"error": "not found"}, status=404)
        return web.json_response(rec)

    async def _list(self, request: web.Request) -> web.Response:
        items = []
        for name in self.store.list():
            head = self.store.head(name)
            items.append({"name": name, "revision": head["revision"], "created_at": head["created_at"]})
        return web.json_response({"deployments": items})

    async def _parse_spec(self, request: web.Request) -> DeploymentSpec:
        try:
            body = await request.json()
        except json.JSONDecodeError as e:
            raise web.HTTPBadRequest(text=json.dumps({"error": f"bad json: {e}"}), content_type="application/json")
        try:
            return DeploymentSpec.from_dict(body)
        except SpecError as e:
            raise web.HTTPUnprocessableEntity(text=json.dumps({"error": str(e)}), content_type="application/json")

    async def _create(self, request: web.Request) -> web.Response:
        spec = await self._parse_spec(request)
        if self.store.head(spec.name) is not None:
            return web.json_response({"error": f"deployment {spec.name} exists"}, status=409)
        record = self.store.put(spec.name, spec.to_dict())
        self._kick()
        return web.json_response({"name": spec.name, "revision": record["revision"]}, status=201)

    def _head_or_404(self, request: web.Request) -> tuple[str, dict]:
        name = request.match_info["name"]
        head = self.store.head(name)
        if head is None:
            raise web.HTTPNotFound(text=json.dumps({"error": f"deployment {name} not found"}), content_type="application/json")
        return name, head

    async def _get(self, request: web.Request) -> web.Response:
        name, head = self._head_or_404(request)
        return web.json_response({"name": name, "revision": head["revision"], "spec": head["spec"]})

    async def _update(self, request: web.Request) -> web.Response:
        name, _ = self._head_or_404(request)
        spec = await self._parse_spec(request)
        if spec.name != name:
            return web.json_response({"error": "spec name must match path"}, status=422)
        record = self.store.put(name, spec.to_dict())
        self._kick()
        return web.json_response({"name": name, "revision": record["revision"]})

    async def _delete(self, request: web.Request) -> web.Response:
        name = request.match_info["name"]
        if not self.store.delete(name):
            raise web.HTTPNotFound(text=json.dumps({"error": f"deployment {name} not found"}), content_type="application/json")
        self._kick()
        return web.json_response({"deleted": name})

    async def _revisions(self, request: web.Request) -> web.Response:
        name, _ = self._head_or_404(request)
        revs = [
            {"revision": r["revision"], "created_at": r["created_at"]}
            for r in reversed(self.store.revisions(name))
        ]
        return web.json_response({"name": name, "revisions": revs})

    async def _rollback(self, request: web.Request) -> web.Response:
        name, _ = self._head_or_404(request)
        try:
            rev = int(request.match_info["rev"])
        except ValueError:
            return web.json_response({"error": "revision must be an integer"}, status=422)
        target = next((r for r in self.store.revisions(name) if r["revision"] == rev), None)
        if target is None:
            return web.json_response({"error": f"revision {rev} not found"}, status=404)
        record = self.store.put(name, target["spec"])
        self._kick()
        return web.json_response({"name": name, "revision": record["revision"], "rolled_back_to": rev})

    async def _status(self, request: web.Request) -> web.Response:
        name, head = self._head_or_404(request)
        return web.json_response({
            "name": name,
            "revision": head["revision"],
            "status": self.store.get_status(name) or {"observed_revision": None},
        })

    async def _manifests(self, request: web.Request) -> web.Response:
        name, head = self._head_or_404(request)
        spec = DeploymentSpec.from_dict(head["spec"])
        return web.json_response({"name": name, "manifests": render_manifests(spec)})

    # ---------------- image builds (the DynamoNimRequest slot) ----------------

    async def _create_build(self, request: web.Request) -> web.Response:
        """Register an image build for a packaged artifact: records the
        request and renders the in-cluster build Job (reference:
        dynamonimrequest_controller.go builds images from packaged
        artifacts). The controller applies the Job on its next pass."""
        from dynamo_tpu.deploy.reconciler import render_build_job

        try:
            body = await request.json()
        except json.JSONDecodeError as e:
            return web.json_response({"error": f"bad json: {e}"}, status=400)
        if not isinstance(body, dict):
            return web.json_response({"error": "body must be a JSON object"}, status=400)
        name = body.get("name")
        image = body.get("image")
        context = body.get("context")
        if not (name and image and context):
            return web.json_response(
                {"error": "name, image, and context are required"}, status=422
            )
        # 51-char cap: the rendered Job is named f"{name}-image-build"
        # (+12 chars) and must stay within Kubernetes' 63-char name/label limit
        if not re.fullmatch(r"[a-z0-9]([a-z0-9-]{0,49}[a-z0-9])?", str(name)):
            # the name becomes a Kubernetes Job name: enforce DNS-1123 here,
            # or the controller would log an apply error every pass forever
            return web.json_response(
                {"error": f"name {name!r} must be DNS-1123 (lowercase alnum + '-', "
                          "<= 51 chars)"},
                status=422,
            )
        namespace = body.get("namespace", "default")
        if not re.fullmatch(DNS1123, str(namespace)):
            # same failure mode as a bad name: the Job's namespace rides
            # straight into kubectl apply on every controller pass
            return web.json_response(
                {"error": f"namespace {namespace!r} must be DNS-1123 (lowercase alnum + '-')"},
                status=422,
            )
        existing = self.store.get_build(name)
        if existing is not None and existing.get("phase") in ("pending", "building"):
            # re-POSTing over an in-flight build would reset it to 'pending'
            # and make the controller re-apply the Job on top of the running
            # one; terminal builds (failed OR complete) may be replaced — a
            # rebuild with a fixed Containerfile is a normal workflow
            return web.json_response(
                {"error": f"build {name} already exists (phase={existing.get('phase')})"},
                status=409,
            )
        job = render_build_job(
            name, image, context,
            namespace=namespace,
            builder_image=body.get(
                "builder_image", "gcr.io/kaniko-project/executor:latest"
            ),
        )
        record = {
            "name": name,
            "image": image,
            "context": context,
            "namespace": namespace,
            "created_at": time.time(),
            "phase": "pending",
            "job": job,
        }
        self.store.put_build(name, record)
        self._kick()
        return web.json_response({"name": name, "phase": "pending"}, status=201)

    async def _list_builds(self, request: web.Request) -> web.Response:
        items = []
        for name in self.store.list_builds():
            rec = self.store.get_build(name)
            items.append({"name": name, "image": rec["image"], "phase": rec["phase"]})
        return web.json_response({"builds": items})

    async def _get_build(self, request: web.Request) -> web.Response:
        name = request.match_info["name"]
        rec = self.store.get_build(name)
        if rec is None:
            raise web.HTTPNotFound(
                text=json.dumps({"error": f"build {name} not found"}),
                content_type="application/json",
            )
        return web.json_response(rec)


def main(argv: Optional[list[str]] = None) -> int:
    import argparse
    import asyncio

    ap = argparse.ArgumentParser("dynamo-tpu-deploy-api")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=8090)
    ap.add_argument(
        "--store", default=None,
        help="store path: *.json = JSON file store, anything else = sqlite "
             "(default: in-memory)",
    )
    args = ap.parse_args(argv)

    def open_store(path):
        if path is None:
            return DeploymentStore()
        p = Path(path)
        if str(p).endswith(".json"):
            return FileDeploymentStore(p)
        if p.exists():
            # pre-sqlite deployments may hold a JSON store at any path: keep
            # reading it as one rather than crashing sqlite on JSON text
            head = p.read_bytes()[:16]
            if not head.startswith(b"SQLite format 3") and head[:1] in (b"{", b"["):
                log.warning("store %s holds JSON; using the file store (rename to migrate to sqlite)", p)
                return FileDeploymentStore(p)
        return SqliteDeploymentStore(p)

    async def run():
        store = open_store(args.store)
        server = DeployApiServer(store)
        port = await server.start(args.host, args.port)
        print(json.dumps({"listening": f"{args.host}:{port}"}), flush=True)
        try:
            await asyncio.Event().wait()
        finally:
            await server.stop()

    try:
        asyncio.run(run())
    except KeyboardInterrupt:
        pass
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
