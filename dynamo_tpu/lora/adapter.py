"""LoRA adapter sources: spec parsing, host-weight loading, identity salts.

An adapter is a per-target-module pair ``A [L, in, r]`` / ``B [L, r, out]``
(layer-stacked, matching the scan-stacked base weights) plus a scalar
``scale = lora_alpha / r``. Two source kinds:

  - ``random:<seed>`` — synthetic adapter at the engine's pool rank
    (deterministic given the model geometry; tests/bench build merged-weight
    references from the same seed)
  - a directory with ``adapter_config.json``
    (``{"r", "lora_alpha", "target_modules"}``) and ``adapter_model.npz``
    holding ``{module}.a`` / ``{module}.b`` arrays — the repo's canonical
    serving format (layer-stacked; a PEFT checkpoint converts to it with one
    np.stack per module)

Adapters with r below the engine pool rank zero-pad (A gains zero columns, B
zero rows — the product is exact); r above the pool rank is a config error.
"""

from __future__ import annotations

import json
from pathlib import Path

import numpy as np
import xxhash

from dynamo_tpu.llm.tokens import XXH3_SEED

#: target modules of the llama-family layer (q/k/v/o + the gated MLP); an
#: adapter may cover any subset — missing modules stay zero in the pool
LORA_MODULES = ("wq", "wk", "wv", "wo", "gate", "up", "down")


def lora_uid(name: str) -> int:
    """Stable nonzero identity salt for an adapter NAME (not its slot: slots
    are per-worker, but the salt must agree across the fleet so a peer
    holding the same adapter's prefix serves the same chained hashes)."""
    return xxhash.xxh3_64_intdigest(("lora:" + name).encode(), seed=XXH3_SEED) | 1


def module_dims(model_config) -> dict[str, tuple[int, int]]:
    """(in, out) of each target module's base matmul."""
    c = model_config
    D, F = c.hidden_size, c.intermediate_size
    qkv_out = c.num_heads * c.head_dim
    kv_out = c.num_kv_heads * c.head_dim
    return {
        "wq": (D, qkv_out),
        "wk": (D, kv_out),
        "wv": (D, kv_out),
        "wo": (qkv_out, D),
        "gate": (D, F),
        "up": (D, F),
        "down": (F, D),
    }


def parse_adapter_specs(specs) -> dict[str, str]:
    """``("a1", "a2=/path", "a3=random:7")`` -> {name: source} (order kept).

    A bare name defaults to a deterministic synthetic adapter (seeded from
    the name) — the test/bench shorthand. Names must be filesystem/URL-safe
    (they become OpenAI model suffixes ``base:adapter``)."""
    out: dict[str, str] = {}
    for spec in specs or ():
        spec = str(spec).strip()
        if not spec:
            continue
        name, _, source = spec.partition("=")
        name = name.strip()
        if not name or not all(ch.isalnum() or ch in "._-" for ch in name):
            raise ValueError(f"invalid LoRA adapter name {name!r}")
        if name in out:
            raise ValueError(f"duplicate LoRA adapter name {name!r}")
        out[name] = source.strip() or f"random:{lora_uid(name) % 100000}"
    return out


def synth_adapter(
    model_config, rank: int, seed: int, modules=LORA_MODULES
) -> tuple[dict, float]:
    """Deterministic random adapter at the pool rank. B is non-zero (a
    trained adapter's shape, not an init) but scaled small so the delta
    perturbs rather than swamps the base logits."""
    rng = np.random.default_rng(int(seed))
    L = model_config.num_layers
    dims = module_dims(model_config)
    tree = {}
    for m in LORA_MODULES:
        din, dout = dims[m]
        if m in modules:
            a = (rng.standard_normal((L, din, rank)) / np.sqrt(din)).astype(np.float32)
            b = (rng.standard_normal((L, rank, dout)) * 0.05).astype(np.float32)
        else:
            a = np.zeros((L, din, rank), np.float32)
            b = np.zeros((L, rank, dout), np.float32)
        tree[m] = {"a": a, "b": b}
    return tree, 1.0


def load_adapter(source: str, model_config, rank: int) -> tuple[dict, float]:
    """Resolve a source spec to (host tree at the POOL rank, scale)."""
    if source.startswith("random:"):
        return synth_adapter(model_config, rank, int(source.split(":", 1)[1]))
    return _load_adapter_dir(Path(source), model_config, rank)


def _pad_rank(a: np.ndarray, b: np.ndarray, rank: int) -> tuple[np.ndarray, np.ndarray]:
    r = a.shape[-1]
    if r > rank:
        raise ValueError(f"adapter rank {r} exceeds pool lora_rank {rank}")
    if r < rank:
        a = np.concatenate(
            [a, np.zeros(a.shape[:-1] + (rank - r,), a.dtype)], axis=-1
        )
        b = np.concatenate(
            [b, np.zeros((b.shape[0], rank - r, b.shape[2]), b.dtype)], axis=1
        )
    return a, b


def _load_adapter_dir(path: Path, model_config, rank: int) -> tuple[dict, float]:
    cfg = json.loads((path / "adapter_config.json").read_text())
    r = int(cfg.get("r", rank))
    alpha = float(cfg.get("lora_alpha", r))
    targets = set(cfg.get("target_modules") or LORA_MODULES)
    data = np.load(path / "adapter_model.npz")
    dims = module_dims(model_config)
    L = model_config.num_layers
    tree = {}
    for m in LORA_MODULES:
        din, dout = dims[m]
        if m in targets and f"{m}.a" in data:
            a = np.asarray(data[f"{m}.a"], np.float32)
            b = np.asarray(data[f"{m}.b"], np.float32)
            if a.shape != (L, din, r) or b.shape != (L, r, dout):
                raise ValueError(
                    f"adapter {path} module {m}: got A{a.shape} B{b.shape}, "
                    f"want A{(L, din, r)} B{(L, r, dout)}"
                )
            a, b = _pad_rank(a, b, rank)
        else:
            a = np.zeros((L, din, rank), np.float32)
            b = np.zeros((L, rank, dout), np.float32)
        tree[m] = {"a": a, "b": b}
    return tree, alpha / max(1, r)


def merge_adapter_into_params(model, params: dict, tree: dict, scale: float) -> dict:
    """Reference merge ``W' = W + scale * A @ B`` on a FULL-PRECISION host
    params tree (test/bench helper: the merged-weight arm the gathered
    kernel must match token-for-token). Quantized trees can't merge exactly
    — quantize(W + sAB) != quantize(W) + sAB — so int8 parity is asserted
    mixed-vs-alone instead."""
    import jax

    params = jax.tree.map(np.asarray, jax.device_get(params))  # graftlint: sync-ok test/bench reference merge on host, not the serving loop
    layers = dict(params["layers"])
    for m, entry in tree.items():
        w = np.asarray(layers[m], np.float32)
        delta = scale * np.einsum("lir,lro->lio", entry["a"], entry["b"])
        layers[m] = (w + delta).astype(np.asarray(params["layers"][m]).dtype)
    out = dict(params)
    out["layers"] = layers
    return out
