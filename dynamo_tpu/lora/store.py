"""Device-resident adapter slot pool with LRU eviction and async host loads.

The pool is one pytree of stacked per-module A/B planes,
``[L, max_loras + 1, in, r]`` / ``[L, max_loras + 1, r, out]`` (f32, so the
delta algebra is exact against a merged-weight f32 reference), plus a
``scales [S]`` vector. Slot 0 is the reserved ZERO adapter: base-only lanes
gather it like any other id — one gather, no branch in the trace.

The store (engine-thread owner) maps adapter names to slots:

  - ``acquire`` on a resident adapter pins its slot (refcounted; a slot is
    never swapped under an in-flight sequence)
  - a non-resident adapter kicks an ASYNC host load (side thread) and
    returns None — the scheduler keeps the request waiting and keeps
    serving everyone else; once host weights are ready, the next acquire
    scatters them into a free (or LRU-evicted refcount-0) slot in one
    donated device call
  - eviction only drops the DEVICE slot; host weights stay cached, so a
    hot-swap back in costs one scatter, not a reload (the S-LoRA
    host-spill behavior — here the host tier is the load cache itself)
"""

from __future__ import annotations

import time
from collections import OrderedDict
from concurrent.futures import ThreadPoolExecutor
from typing import Optional

import numpy as np

from dynamo_tpu.lora.adapter import (
    LORA_MODULES,
    load_adapter,
    module_dims,
    parse_adapter_specs,
)
from dynamo_tpu.utils import get_logger, tracing

log = get_logger("lora.store")


def init_lora_pool(model, max_loras: int, rank: int) -> dict:
    """Host zeros for the stacked pool: {"scales": [S], "mods": {m: {"a":
    [L, S, in, r], "b": [L, S, r, out]}}} with S = max_loras + 1 (slot 0 =
    base/zero)."""
    c = model.config
    S = max_loras + 1
    dims = module_dims(c)
    mods = {}
    for m in LORA_MODULES:
        din, dout = dims[m]
        mods[m] = {
            "a": np.zeros((c.num_layers, S, din, rank), np.float32),
            "b": np.zeros((c.num_layers, S, rank, dout), np.float32),
        }
    return {"scales": np.zeros(S, np.float32), "mods": mods}


class LoraStore:
    """Adapter name -> device slot bookkeeping (engine thread only, except
    the host-load worker which touches nothing but ``_host``/futures)."""

    def __init__(self, config, model, scatter_fn):
        self.max_loras = config.max_loras
        self.rank = config.lora_rank
        self.sources = parse_adapter_specs(config.lora_adapters)
        self.model_config = model.config
        self._scatter = scatter_fn  # (slot, host_tree, scale) -> device write
        self.slot_of: dict[str, int] = {}
        self._slot_name: dict[int, str] = {}
        self._free_slots = list(range(config.max_loras, 0, -1))  # 1..max
        self.refs: dict[str, int] = {}
        self._lru: OrderedDict[str, None] = OrderedDict()  # ref-0 residents
        self._host: dict[str, tuple[dict, float]] = {}
        self._loading: dict[str, object] = {}
        self._failed: dict[str, str] = {}
        self._pool = ThreadPoolExecutor(max_workers=1, thread_name_prefix="lora-load")
        # step-anatomy sink (utils/step_anatomy.StepAnatomy), attached by the
        # scheduler: device slot scatters record as lora_slot_load dispatches
        self.anatomy = None
        # metrics
        self.evictions = 0
        self.loads = 0
        self.load_seconds = 0.0
        self.requests: dict[str, int] = {name: 0 for name in self.sources}

    # ---------------- queries ----------------

    def known(self, name: str) -> bool:
        return name in self.sources

    @property
    def resident_count(self) -> int:
        return len(self.slot_of)

    def hot_adapter(self) -> str:
        if not any(self.requests.values()):
            return ""
        return max(self.requests, key=lambda n: self.requests[n])

    # ---------------- host load ----------------

    def _load_host(self, name: str) -> tuple[dict, float]:
        t0 = time.monotonic()
        with tracing.span("lora.host_load", adapter=name):
            tree, scale = load_adapter(self.sources[name], self.model_config, self.rank)
        self.load_seconds += time.monotonic() - t0
        self.loads += 1
        return tree, scale

    def _poll_host(self, name: str) -> Optional[tuple[dict, float]]:
        """Host weights if ready; kicks/polls the async load otherwise."""
        got = self._host.get(name)
        if got is not None:
            return got
        if name in self._failed:
            raise RuntimeError(f"LoRA adapter {name!r} failed to load: {self._failed[name]}")
        fut = self._loading.get(name)
        if fut is None:
            self._loading[name] = self._pool.submit(self._load_host, name)
            return None
        if not fut.done():
            return None
        del self._loading[name]
        try:
            got = fut.result()
        except Exception as e:
            log.exception("LoRA adapter %s load failed", name)
            self._failed[name] = str(e)
            raise RuntimeError(f"LoRA adapter {name!r} failed to load: {e}") from e
        self._host[name] = got
        return got

    # ---------------- slot lifecycle ----------------

    def acquire(self, name: str) -> Optional[int]:
        """Pin ``name``'s slot for one sequence. Returns the slot id, or
        None while the adapter is still loading / all slots are pinned (the
        caller keeps the request waiting — never an error). Raises KeyError
        for an unknown adapter and RuntimeError for a broken source."""
        if name not in self.sources:
            raise KeyError(f"unknown LoRA adapter {name!r}")
        slot = self.slot_of.get(name)
        if slot is not None:
            self.refs[name] = self.refs.get(name, 0) + 1
            self._lru.pop(name, None)
            self.requests[name] = self.requests.get(name, 0) + 1
            return slot
        host = self._poll_host(name)
        if host is None:
            return None
        slot = self._take_slot()
        if slot is None:
            return None  # every slot pinned by in-flight sequences
        tree, scale = host
        t0 = time.monotonic()
        self._scatter(slot, tree, scale)
        dt = time.monotonic() - t0
        # the device-slot load was invisible to the tracing/anatomy planes
        # before this span: a cold adapter's one-scatter hot-swap now shows
        # up per request timeline AND in dynamo_step_seconds_total
        tracing.record_span(
            "lora.slot_load", t0, duration=dt,
            attrs={"adapter": name, "slot": slot},
        )
        if self.anatomy is not None:
            self.anatomy.record("lora_slot_load", dispatch_s=dt,
                                participants=1, ts=t0)
        self.slot_of[name] = slot
        self._slot_name[slot] = name
        self.refs[name] = 1
        self.requests[name] = self.requests.get(name, 0) + 1
        return slot

    def acquire_blocking(self, name: str, timeout_s: float = 30.0) -> Optional[int]:
        """Synchronous acquire for paths with no retry loop (remote
        prefill): waits for the host load, then takes a slot. None only when
        every slot stays pinned for the whole timeout."""
        deadline = time.monotonic() + timeout_s
        while True:
            slot = self.acquire(name)
            if slot is not None or time.monotonic() >= deadline:
                return slot
            time.sleep(0.01)

    def release(self, name: str) -> None:
        """Unpin one sequence's hold; a refcount-0 slot stays resident (LRU
        tail) until a new adapter needs it."""
        rc = self.refs.get(name, 0) - 1
        if rc > 0:
            self.refs[name] = rc
            return
        self.refs.pop(name, None)
        if name in self.slot_of:
            self._lru[name] = None
            self._lru.move_to_end(name)

    def _take_slot(self) -> Optional[int]:
        if self._free_slots:
            return self._free_slots.pop()
        if not self._lru:
            return None
        victim, _ = self._lru.popitem(last=False)
        slot = self.slot_of.pop(victim)
        self._slot_name.pop(slot, None)
        self.evictions += 1
        log.info("evicting LoRA adapter %s from slot %d (host copy kept)", victim, slot)
        # the slot's pool plane is overwritten by the incoming scatter; no
        # zeroing write needed (nothing dispatches slot ids without a live
        # slot_of entry)
        return slot

    # ---------------- telemetry ----------------

    def metrics_snapshot(self) -> dict:
        return {
            "resident": self.resident_count,
            "capacity": self.max_loras,
            "evictions": self.evictions,
            "loads": self.loads,
            "load_seconds": round(self.load_seconds, 4),
            "requests": dict(self.requests),
            "hot": self.hot_adapter(),
        }
