"""Multi-LoRA multiplexing: serve M fine-tunes of one base model from one
engine.

Punica (Chen et al., 2023) / S-LoRA (Sheng et al., 2023) style serving: LoRA
A/B pairs for every target module live in device-resident stacked pools
``[L, max_loras+1, ...]`` (slot 0 = the zero adapter, so base-only lanes ride
the same gathered dispatch), and a mixed-adapter batch applies
``y += scale * (x @ A[ids]) @ B[ids]`` per module in ONE dispatch — no
per-adapter matmuls, no trace branches. Adapter-specific KV identity comes
from salting the chained block hash with the adapter's stable uid
(llm/tokens.py), so prefixes never cross-hit between adapters locally, in the
router's radix view, or over the fleet pull path.
"""

from dynamo_tpu.lora.adapter import (
    LORA_MODULES,
    load_adapter,
    lora_uid,
    merge_adapter_into_params,
    module_dims,
    parse_adapter_specs,
    synth_adapter,
)
from dynamo_tpu.lora.store import LoraStore, init_lora_pool

__all__ = [
    "LORA_MODULES",
    "LoraStore",
    "init_lora_pool",
    "load_adapter",
    "lora_uid",
    "merge_adapter_into_params",
    "module_dims",
    "parse_adapter_specs",
    "synth_adapter",
]
