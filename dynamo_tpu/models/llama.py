"""Llama-family model (Llama 2/3, DeepSeek-R1-Distill-Llama) in pure JAX with a
paged KV cache.

Design notes (TPU-first):
  - Layers are scan-stacked: every weight carries a leading ``[L]`` axis and the
    forward pass is one ``lax.scan`` over layers — a single compiled layer body
    and fast compiles.
  - The KV cache is a **flat page pool** ``{"k","v"}`` of shape
    ``[num_layers * num_pages, page_size, Hkv, D]`` each (layer l's page p at
    flat index ``l * num_pages + p``), carried through the layer scan and
    donated to the step functions so XLA scatters new tokens in place. See
    dynamo_tpu/ops/attention.py for why flat beats a per-layer [L, ...] cache
    threaded through scan xs/ys (3x decode step time on v5e).
  - Tensor parallelism is expressed purely as NamedSharding on params/cache
    (head-sharded) + GSPMD propagation; no explicit collectives in model code.
  - Weight layout is ``[in, out]`` so the hot path is plain ``h @ w`` (MXU).

This is the serving engine slot that the reference fills with external GPU
engines (reference: lib/llm/src/engines/vllm/worker.rs, SURVEY.md §7 step 3) —
here it is native.
"""

from __future__ import annotations

from dataclasses import dataclass, replace
from typing import Any

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dynamo_tpu.ops.attention import (
    dispatch_paged_decode_attention,
    dispatch_paged_prefill_attention,
    scatter_kv,
)
from dynamo_tpu.ops.norms import rms_norm
from dynamo_tpu.ops.rotary import apply_mrope, apply_rope
from dynamo_tpu.quant import (
    QUANT_MODES,
    QuantizedPages,
    init_quantized_pages,
    qlinear,
    quantize_shardings_int8,
    quantize_tree_int8,
)
from dynamo_tpu.quant.kv import kv_page_bytes as _kv_page_bytes, quantize_kv_rows


def _resolve_tp_axis(mesh: Mesh, tp_axis: str):
    """tp axis name if present; None for sp-only meshes (params replicated by
    design there); otherwise keep the name so NamedSharding raises loudly."""
    if tp_axis in mesh.axis_names:
        return tp_axis
    if "sp" in mesh.axis_names or "pp" in mesh.axis_names:
        return None  # sp/pp-only meshes replicate the tp dims by design
    return tp_axis  # unknown axis -> NamedSharding raises


def lora_delta(x: jnp.ndarray, entry: dict, ids, scales: jnp.ndarray) -> jnp.ndarray:
    """Gathered per-slot LoRA pass: ``scale[ids] * (x @ A[ids]) @ B[ids]``.

    ``entry`` is one module's slot-stacked planes {"a": [S, in, r], "b":
    [S, r, out]} (slot 0 = the zero adapter, so base-only lanes ride the same
    gather instead of a trace branch). ``ids`` is a per-token [T] vector (a
    mixed-adapter batch) or a scalar (a whole single-sequence chunk shares
    one adapter — the gather degenerates to a slice and the two einsums to
    plain matmuls). The f32 pool keeps the delta algebra exact against a
    merged-weight f32 reference; the result casts back to x's dtype."""
    xf = x.astype(jnp.float32)
    a = entry["a"][ids]
    b = entry["b"][ids]
    if jnp.ndim(ids) == 0:
        d = ((xf @ a) @ b) * scales[ids]
    else:
        xr = jnp.einsum("ti,tir->tr", xf, a)
        d = jnp.einsum("tr,tro->to", xr, b) * scales[ids][:, None]
    return d.astype(x.dtype)


def parse_dtype(value) -> Any:
    """Accept a jnp dtype or its string alias in tiny:{...} config overrides."""
    if isinstance(value, str):
        return {
            "bf16": jnp.bfloat16,
            "bfloat16": jnp.bfloat16,
            "f32": jnp.float32,
            "float32": jnp.float32,
        }[value]
    return value


@dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32000
    hidden_size: int = 4096
    intermediate_size: int = 11008
    num_layers: int = 32
    num_heads: int = 32
    num_kv_heads: int = 32
    head_dim: int = 128
    rope_theta: float = 10000.0
    rms_norm_eps: float = 1e-5
    tie_word_embeddings: bool = False
    attention_bias: bool = False  # Qwen2-style qkv biases
    # M-RoPE (Qwen2-VL): (temporal, row, col) frequency sections summing to
    # head_dim // 2. None = plain 1D RoPE. With equal position components
    # (all text) M-RoPE reduces exactly to 1D RoPE (ops/rotary.py).
    mrope_section: Any = None
    # weight-only quantization mode: None (full precision) or "int8_wo" —
    # the big linear weights become int8 + per-output-channel f32 scales at
    # load time; embeddings/lm_head/norms/biases stay at `dtype`
    # (dynamo_tpu/quant/int8.py)
    quantize: Any = None
    # KV cache storage dtype: None / "bf16" (the model dtype) or "int8" —
    # pages stored int8 with one f32 scale per (page, token row)
    # (dynamo_tpu/quant/kv.py QuantizedPages). Halves attention HBM traffic
    # and doubles page capacity at the same HBM budget; composes with
    # `quantize` (weights and cache quantize independently).
    kv_cache_dtype: Any = None
    dtype: Any = jnp.bfloat16

    @property
    def kv_quantized(self) -> bool:
        return self.kv_cache_dtype == "int8"

    @property
    def kv_folded(self) -> bool:
        """KV page rows store heads FOLDED into the lane dim ([ps, Hkv*D]
        instead of [ps, Hkv, D]) when head_dim isn't 128-lane aligned:
        Mosaic cannot DMA-slice an HBM pool whose minor dim is under the
        128-lane tile, and reshaping the (donated, scatter-updated) pool at
        attention time materializes a full-pool copy per layer per step.
        TinyLlama / Qwen2-small shapes (D=64) hit this; D=128 models don't."""
        return self.head_dim % 128 != 0

    @classmethod
    def from_hf_config(cls, d: dict) -> "LlamaConfig":
        """Build from a HuggingFace config.json dict (Llama / Qwen2 families)."""
        num_heads = d["num_attention_heads"]
        is_qwen = "qwen" in str(d.get("model_type", "")).lower()
        return cls(
            vocab_size=d["vocab_size"],
            hidden_size=d["hidden_size"],
            intermediate_size=d["intermediate_size"],
            num_layers=d["num_hidden_layers"],
            num_heads=num_heads,
            num_kv_heads=d.get("num_key_value_heads", num_heads),
            head_dim=d.get("head_dim", d["hidden_size"] // num_heads),
            rope_theta=d.get("rope_theta", 10000.0),
            rms_norm_eps=d.get("rms_norm_eps", 1e-5),
            tie_word_embeddings=d.get("tie_word_embeddings", False),
            attention_bias=d.get("attention_bias", is_qwen),
        )

    @classmethod
    def tiny(cls, **overrides) -> "LlamaConfig":
        """Small config for tests (runs on the virtual CPU mesh in seconds)."""
        if "dtype" in overrides:
            overrides["dtype"] = parse_dtype(overrides["dtype"])
        base = cls(
            vocab_size=256,
            hidden_size=64,
            intermediate_size=128,
            num_layers=2,
            num_heads=4,
            num_kv_heads=2,
            head_dim=16,
            dtype=jnp.float32,
        )
        return replace(base, **overrides)


class LlamaModel:
    """Stateless forward functions over a params pytree."""

    #: per-layer weights eligible for weight-only quantization — the decode
    #: hot path's big matmuls; norms/biases (and embed/lm_head outside the
    #: layer stack) stay at config.dtype
    QUANT_WEIGHT_NAMES = frozenset({"wq", "wk", "wv", "wo", "gate", "up", "down"})

    #: llama-family layers take the gathered LoRA pass (dynamo_tpu/lora/):
    #: q/k/v/o + gated-MLP deltas ride slot-stacked pools through every
    #: forward. Subclasses with their own _layer (mixtral's MoE block,
    #: deepseek's absorbed attention) opt out until they thread it.
    SUPPORTS_LORA = True

    def __init__(self, config: LlamaConfig):
        self.config = config
        # set by ModelRunner for tp>1 so the Pallas decode kernel can run
        # under shard_map (GSPMD cannot partition a pallas_call)
        self.attn_mesh = None

    # ---------------- params ----------------

    def quantize_params(self, params: dict) -> dict:
        """Apply config.quantize to a full-precision params tree (no-op when
        unset). Loaders call this after filling checkpoint weights; the
        subclass's QUANT_WEIGHT_NAMES picks the leaves."""
        mode = self.config.quantize
        if not mode:
            return params
        if mode not in QUANT_MODES:
            raise ValueError(f"unknown quantize mode {mode!r} (supported: {QUANT_MODES})")
        params = dict(params)
        params["layers"] = quantize_tree_int8(params["layers"], self.QUANT_WEIGHT_NAMES)
        return params

    def _quantize_shardings(self, shardings: dict) -> dict:
        """Mirror quantize_params onto the sharding tree: int8 weights keep
        the bf16 leaf's sharding, scales drop its contracted-axis entry (so
        they follow the weight's output-channel sharding and replicate over
        a row-parallel split)."""
        if not self.config.quantize:
            return shardings
        shardings = dict(shardings)
        shardings["layers"] = quantize_shardings_int8(
            shardings["layers"], self.QUANT_WEIGHT_NAMES
        )
        return shardings

    def init_params(self, rng: jax.Array, quantize: bool = True) -> dict:
        """quantize=False yields the raw full-precision tree even when the
        config requests quantization — the loader's allocation template
        (models/loader.py fills f32 arrays, then quantizes once at the end)."""
        params = self._init_raw_params(rng)
        return self.quantize_params(params) if quantize else params

    def _init_raw_params(self, rng: jax.Array) -> dict:
        c = self.config
        keys = iter(jax.random.split(rng, 16))

        def dense(key, shape, scale_axis):
            scale = 1.0 / jnp.sqrt(jnp.float32(shape[scale_axis]))
            return (jax.random.normal(key, shape, jnp.float32) * scale).astype(c.dtype)

        L, D, H, Hkv, Dh, F, V = (
            c.num_layers,
            c.hidden_size,
            c.num_heads,
            c.num_kv_heads,
            c.head_dim,
            c.intermediate_size,
            c.vocab_size,
        )
        params = {
            "embed": dense(next(keys), (V, D), 1),
            "layers": {
                "input_norm": jnp.ones((L, D), c.dtype),
                "wq": dense(next(keys), (L, D, H * Dh), 1),
                "wk": dense(next(keys), (L, D, Hkv * Dh), 1),
                "wv": dense(next(keys), (L, D, Hkv * Dh), 1),
                "wo": dense(next(keys), (L, H * Dh, D), 1),
                "post_norm": jnp.ones((L, D), c.dtype),
                "gate": dense(next(keys), (L, D, F), 1),
                "up": dense(next(keys), (L, D, F), 1),
                "down": dense(next(keys), (L, F, D), 1),
            },
            "final_norm": jnp.ones((D,), c.dtype),
        }
        if c.attention_bias:
            params["layers"]["bq"] = dense(next(keys), (L, H * Dh), 0)
            params["layers"]["bk"] = dense(next(keys), (L, Hkv * Dh), 0)
            params["layers"]["bv"] = dense(next(keys), (L, Hkv * Dh), 0)
        if not c.tie_word_embeddings:
            params["lm_head"] = dense(next(keys), (V, D), 1)
        return params

    def param_shardings(self, mesh: Mesh, tp_axis: str = "tp") -> dict:
        """NamedSharding pytree: attention heads and MLP hidden sharded on tp
        (replicated when the mesh is sp-only; any other missing axis raises
        so a misnamed tp mesh can't silently replicate a real model)."""
        tp_axis = _resolve_tp_axis(mesh, tp_axis)

        def ns(*spec):
            return NamedSharding(mesh, P(*spec))

        shardings = {
            "embed": ns(None, None),
            "layers": {
                "input_norm": ns(None, None),
                "wq": ns(None, None, tp_axis),
                "wk": ns(None, None, tp_axis),
                "wv": ns(None, None, tp_axis),
                "wo": ns(None, tp_axis, None),
                "post_norm": ns(None, None),
                "gate": ns(None, None, tp_axis),
                "up": ns(None, None, tp_axis),
                "down": ns(None, tp_axis, None),
            },
            "final_norm": ns(None),
        }
        if self.config.attention_bias:
            shardings["layers"]["bq"] = ns(None, tp_axis)
            shardings["layers"]["bk"] = ns(None, tp_axis)
            shardings["layers"]["bv"] = ns(None, tp_axis)
        if not self.config.tie_word_embeddings:
            shardings["lm_head"] = ns(tp_axis, None)
        return self._quantize_shardings(shardings)

    def kv_cache_shape(self, num_pages: int, page_size: int) -> tuple[int, ...]:
        """Shape of each of the two flat page pools (the "k" and "v" leaves).
        See LlamaConfig.kv_folded for the folded (sub-128 head_dim) layout."""
        c = self.config
        if c.kv_folded:
            return (c.num_layers * num_pages, page_size, c.num_kv_heads * c.head_dim)
        return (c.num_layers * num_pages, page_size, c.num_kv_heads, c.head_dim)

    #: llama-family pools support the int8 KV cache (deepseek's latent cache
    #: does not — its compression IS its cache optimization)
    SUPPORTS_KV_INT8 = True

    def init_kv_cache(self, num_pages: int, page_size: int) -> dict:
        shape = self.kv_cache_shape(num_pages, page_size)
        if self.config.kv_quantized:
            # int8 pools + per-(page, token-row) f32 scale planes; the dict
            # keeps its {"k","v"} structure — QuantizedPages is a pytree
            # node, so the scan carry / donation / device_put paths are
            # unchanged (quant/kv.py)
            return {
                "k": init_quantized_pages(shape),
                "v": init_quantized_pages(shape),
            }
        return {
            "k": jnp.zeros(shape, self.config.dtype),
            "v": jnp.zeros(shape, self.config.dtype),
        }

    def kv_page_bytes(self, page_size: int) -> int:
        """HBM bytes one allocator page costs across all layers (K + V and,
        for int8, the scale planes) — the capacity/telemetry number."""
        c = self.config
        return _kv_page_bytes(
            page_size, c.num_kv_heads, c.head_dim, c.num_layers,
            "int8" if c.kv_quantized else None,
            itemsize=jnp.dtype(c.dtype).itemsize,
        )

    def kv_cache_sharding(self, mesh: Mesh, tp_axis: str = "tp") -> dict:
        tp_axis = _resolve_tp_axis(mesh, tp_axis)
        if self.config.kv_folded:
            # folded lane dim is head-major, so a tp split that divides Hkv
            # stays head-aligned
            ns = NamedSharding(mesh, P(None, None, tp_axis))
        else:
            ns = NamedSharding(mesh, P(None, None, tp_axis, None))
        if self.config.kv_quantized:
            # per-row scales are head-independent: replicated over tp
            ns = QuantizedPages(ns, NamedSharding(mesh, P(None, None)))
        return {"k": ns, "v": ns}

    def _layer_offsets(self, num_pages: int) -> jnp.ndarray:
        """[L] flat-pool offset of each layer's page 0 (its trash page)."""
        return jnp.arange(self.config.num_layers, dtype=jnp.int32) * num_pages

    # ---------------- disagg / offload wire format ----------------
    # The wire layout is the model's canonical block serialization for DCN
    # transfer and host offload; flat_ids is [L, n] (per-layer flat page ids).

    # axis of the per-page (n) dimension in the wire arrays below — batched
    # host-tier restores concatenate single-page blocks along it
    wire_n_axis = 2

    def gather_pages_wire(self, kv: dict, flat_ids: jnp.ndarray):
        """-> [L, 2, n, page_size, Hkv, D] ([..., Hkv*D] when kv_folded —
        both disagg sides share the model config, so the layouts agree).

        Int8 caches return ``{"q": int8 [L, 2, n, ps, ...], "s": f32
        [L, 2, n, ps]}`` — the scale plane travels WITH the pages (half the
        wire/host bytes; scales ride disagg part headers and host-pool
        entries, see quant/kv.py wire helpers)."""
        if isinstance(kv["k"], QuantizedPages):
            return {
                "q": jnp.stack([kv["k"].q[flat_ids], kv["v"].q[flat_ids]], axis=1),
                "s": jnp.stack([kv["k"].s[flat_ids], kv["v"].s[flat_ids]], axis=1),
            }
        return jnp.stack([kv["k"][flat_ids], kv["v"][flat_ids]], axis=1)

    def scatter_pages_wire(self, kv: dict, flat_ids: jnp.ndarray, data) -> dict:
        if isinstance(kv["k"], QuantizedPages):
            if isinstance(data, dict):
                q = data["q"].astype(jnp.int8)
                s = data["s"].astype(jnp.float32)
            else:
                # full-precision wire into an int8 cache (a bf16 peer, the
                # legacy inline path): quantize per token row on the way in
                rows = data.reshape(-1, data.shape[-1] if data.ndim == 5 else
                                    data.shape[-2] * data.shape[-1])
                qr, sr = quantize_kv_rows(rows)
                q = qr.reshape(data.shape).astype(jnp.int8)
                s = sr.reshape(data.shape[:4])
            return {
                "k": QuantizedPages(
                    kv["k"].q.at[flat_ids].set(q[:, 0]),
                    kv["k"].s.at[flat_ids].set(s[:, 0]),
                ),
                "v": QuantizedPages(
                    kv["v"].q.at[flat_ids].set(q[:, 1]),
                    kv["v"].s.at[flat_ids].set(s[:, 1]),
                ),
            }
        dt = kv["k"].dtype
        if isinstance(data, dict):
            # int8 wire into a full-precision cache: dequantize the rows
            s = data["s"].astype(jnp.float32)
            data = data["q"].astype(jnp.float32) * s.reshape(
                s.shape + (1,) * (data["q"].ndim - s.ndim)
            )
        return {
            "k": kv["k"].at[flat_ids].set(data[:, 0].astype(dt)),
            "v": kv["v"].at[flat_ids].set(data[:, 1].astype(dt)),
        }

    def wire_sharding(self, mesh: Mesh, tp_axis: str = "tp"):
        tp_axis = _resolve_tp_axis(mesh, tp_axis)
        if self.config.kv_folded:
            ns = NamedSharding(mesh, P(None, None, None, None, tp_axis))
        else:
            ns = NamedSharding(mesh, P(None, None, None, None, tp_axis, None))
        if self.config.kv_quantized:
            # dict wire: int8 data shards like the pool; scales replicate
            return {"q": ns, "s": NamedSharding(mesh, P())}
        return ns

    # ---------------- forward ----------------

    def _unembed(self, params: dict, hidden: jnp.ndarray) -> jnp.ndarray:
        c = self.config
        h = rms_norm(hidden, params["final_norm"], c.rms_norm_eps)
        head = params["embed"] if c.tie_word_embeddings else params["lm_head"]
        # bf16 MXU matmul with f32 accumulation — no materialized f32 cast of
        # the [V, D] head (bf16 products are exact in the f32 accumulator)
        return jax.lax.dot_general(
            h, head, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )

    def _layer(
        self,
        lp: dict,
        hidden: jnp.ndarray,  # [T, D]
        k_pool: jnp.ndarray,  # [LP, ps, Hkv, D] full flat pool (carried)
        v_pool: jnp.ndarray,  # [LP, ps, Hkv, D]
        positions: jnp.ndarray,  # [T] sequential positions (KV addressing)
        flat_phys: jnp.ndarray,  # [T] flat page per token (layer trash for invalid)
        offsets: jnp.ndarray,  # [T]
        attn_fn,
        rope_positions: jnp.ndarray | None = None,  # [T, 3] M-RoPE components
        tp_axis: str | None = None,  # set inside an explicit (pp, tp) shard_map
        sp_axis: str | None = None,  # set inside a composed (pp, sp[, tp]) shard_map
        lora_mods: dict | None = None,  # this layer's slot-stacked LoRA planes
        lora_ids=None,  # [T] per-token adapter slot ids (or scalar)
        lora_scales: jnp.ndarray | None = None,  # [S] per-slot alpha/r
    ) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
        """One transformer layer. Under GSPMD (pp == 1) the tp sharding is
        handled by the compiler; inside an explicit shard_map over a composed
        (pp, tp) mesh this runs on the LOCAL head shard (wq/wk/wv column
        shards, wo/down row shards) and ``tp_axis`` names the axis for the
        two Megatron-style psums that complete each residual branch.

        ``sp_axis`` (composed pp x sp ring prefill): the token dim is sharded
        over sp, so before the pool scatter the fresh K/V rows (+ their page
        addresses) all-gather over sp — every sp peer writes ALL the chunk's
        rows and the stage's pool replicas stay bit-identical, which the
        decode path (replicated over sp) depends on. This mirrors the
        all-gather GSPMD inserts on the pure-sp path for the same scatter."""
        c = self.config
        T = hidden.shape[0]
        h = rms_norm(hidden, lp["input_norm"], c.rms_norm_eps)
        # qlinear == `h @ w` for full-precision weights; int8 weight-only
        # leaves dequantize inside the fused dot (dynamo_tpu/quant/int8.py)
        q_flat = qlinear(h, lp["wq"])
        k_flat = qlinear(h, lp["wk"])
        v_flat = qlinear(h, lp["wv"])
        if lora_mods is not None:
            # the adapter delta rides ON TOP of qlinear unchanged (int8 base
            # weights compose: dequant-in-matmul below, f32 delta here); k/v
            # deltas land BEFORE rope + the pool scatter, so cached pages are
            # adapter-specific — which the lora-salted block identity encodes
            q_flat = q_flat + lora_delta(h, lora_mods["wq"], lora_ids, lora_scales)
            k_flat = k_flat + lora_delta(h, lora_mods["wk"], lora_ids, lora_scales)
            v_flat = v_flat + lora_delta(h, lora_mods["wv"], lora_ids, lora_scales)
        if c.attention_bias:
            q_flat = q_flat + lp["bq"]
            k_flat = k_flat + lp["bk"]
            v_flat = v_flat + lp["bv"]
        # head counts from the weight shard, not the config: inside a tp
        # shard_map each device sees num_heads / tp of them
        q = q_flat.reshape(T, -1, c.head_dim)
        k = k_flat.reshape(T, -1, c.head_dim)
        v = v_flat.reshape(T, -1, c.head_dim)
        if c.mrope_section is not None:
            pos3 = (
                rope_positions
                if rope_positions is not None
                else jnp.stack([positions] * 3, axis=-1)
            )
            q = apply_mrope(q, pos3, tuple(c.mrope_section), c.rope_theta)
            k = apply_mrope(k, pos3, tuple(c.mrope_section), c.rope_theta)
        else:
            q = apply_rope(q, positions, c.rope_theta)
            k = apply_rope(k, positions, c.rope_theta)
        # scatter_kv folds the new rows itself when the pool is lane-folded
        if sp_axis is not None:
            k_all = jax.lax.all_gather(k, sp_axis, axis=0, tiled=True)
            v_all = jax.lax.all_gather(v, sp_axis, axis=0, tiled=True)
            phys_all = jax.lax.all_gather(flat_phys, sp_axis, axis=0, tiled=True)
            off_all = jax.lax.all_gather(offsets, sp_axis, axis=0, tiled=True)
            k_pool, v_pool = scatter_kv(k_pool, v_pool, k_all, v_all, phys_all, off_all)
        else:
            k_pool, v_pool = scatter_kv(k_pool, v_pool, k, v, flat_phys, offsets)
        # attn_fn sees both the updated pools (paged paths) and the chunk's
        # fresh rows (ring/SP path, which never reads the pool)
        attn = attn_fn(q, k, v, k_pool, v_pool)
        attn_flat = attn.reshape(T, -1)
        attn_out = qlinear(attn_flat, lp["wo"])
        if lora_mods is not None:
            attn_out = attn_out + lora_delta(
                attn_flat, lora_mods["wo"], lora_ids, lora_scales
            )
        if tp_axis is not None:
            attn_out = jax.lax.psum(attn_out, tp_axis)
        hidden = hidden + attn_out
        h = rms_norm(hidden, lp["post_norm"], c.rms_norm_eps)
        g = qlinear(h, lp["gate"])
        u = qlinear(h, lp["up"])
        if lora_mods is not None:
            g = g + lora_delta(h, lora_mods["gate"], lora_ids, lora_scales)
            u = u + lora_delta(h, lora_mods["up"], lora_ids, lora_scales)
        prod = jax.nn.silu(g) * u
        mlp = qlinear(prod, lp["down"])
        if lora_mods is not None:
            mlp = mlp + lora_delta(prod, lora_mods["down"], lora_ids, lora_scales)
        if tp_axis is not None:
            mlp = jax.lax.psum(mlp, tp_axis)
        hidden = hidden + mlp
        return hidden, k_pool, v_pool

    def _prefill_common(
        self, params, kv_cache, tokens, positions, page_table, valid, last_idx, make_attn_fn,
        input_embeds=None, embeds_mask=None, rope_positions=None,
        lora=None, lora_id=None,
    ) -> tuple[jnp.ndarray, dict]:
        """Shared prefill machinery; make_attn_fn(off) -> attn_fn for a layer
        (off = the layer's flat-pool offset). input_embeds [T, D] + embeds_mask
        [T] override the token embeddings where the mask is set (multimodal:
        vision-tower outputs replace image-slot virtual tokens). ``lora``
        (the slot-stacked adapter pool) + scalar ``lora_id`` apply one
        adapter's delta to the whole chunk (a chunk belongs to one sequence;
        id 0 gathers the zero adapter)."""
        c = self.config
        k_pool, v_pool = kv_cache["k"], kv_cache["v"]
        page_size = k_pool.shape[1]
        num_pages = k_pool.shape[0] // c.num_layers
        phys = jnp.where(valid, page_table[positions // page_size], 0)
        offsets = jnp.where(valid, positions % page_size, 0)

        hidden = params["embed"][tokens].astype(c.dtype)
        if input_embeds is not None:
            hidden = jnp.where(embeds_mask[:, None], input_embeds.astype(c.dtype), hidden)

        def body(carry, xs):
            h, kp, vp = carry
            lp, off = xs[0], xs[1]
            lkw = {}
            if lora is not None:
                lkw = dict(
                    lora_mods=xs[2], lora_ids=lora_id, lora_scales=lora["scales"]
                )
            h, kp, vp = self._layer(
                lp, h, kp, vp, positions, off + phys, offsets, make_attn_fn(off),
                rope_positions=rope_positions, **lkw,
            )
            return (h, kp, vp), None

        xs_all = (params["layers"], self._layer_offsets(num_pages))
        if lora is not None:
            xs_all = xs_all + (lora["mods"],)
        (hidden, k_pool, v_pool), _ = jax.lax.scan(
            body, (hidden, k_pool, v_pool), xs_all
        )
        logits = self._unembed(params, hidden[last_idx][None, :])[0]
        return logits, {"k": k_pool, "v": v_pool}

    def prefill(
        self,
        params: dict,
        kv_cache: dict,  # {"k","v"} flat pools (donated)
        tokens: jnp.ndarray,  # [T] padded chunk
        positions: jnp.ndarray,  # [T] absolute positions
        page_table: jnp.ndarray,  # [max_pages] logical (per-layer) page ids
        valid: jnp.ndarray,  # [T] bool
        last_idx: jnp.ndarray,  # scalar: index of the final real token in chunk
        input_embeds: jnp.ndarray | None = None,  # [T, D] mm embedding overrides
        embeds_mask: jnp.ndarray | None = None,  # [T] bool
        rope_positions: jnp.ndarray | None = None,  # [T, 3] M-RoPE components
        lora: dict | None = None,  # slot-stacked adapter pool (lora/store.py)
        lora_id=None,  # scalar adapter slot for this chunk (0 = base)
    ) -> tuple[jnp.ndarray, dict]:
        """One (possibly chunked) prefill pass for a single sequence.

        Returns (logits[V] at last_idx, updated kv_cache).
        """

        def make_attn_fn(off):
            def attn_fn(q, k_new, v_new, kp_, vp_):
                return dispatch_paged_prefill_attention(
                    q, kp_, vp_, off + page_table, positions, mesh=self.attn_mesh
                )

            return attn_fn

        return self._prefill_common(
            params, kv_cache, tokens, positions, page_table, valid, last_idx, make_attn_fn,
            input_embeds=input_embeds, embeds_mask=embeds_mask,
            rope_positions=rope_positions, lora=lora, lora_id=lora_id,
        )

    def prefill_packed(
        self,
        params: dict,
        kv_cache: dict,  # {"k","v"} flat pools (donated)
        tokens: jnp.ndarray,  # [N, T] bucket-padded chunks, one per lane
        positions: jnp.ndarray,  # [N, T] absolute positions per lane
        page_tables: jnp.ndarray,  # [N, max_pages] logical page ids per lane
        valid: jnp.ndarray,  # [N, T] bool
        last_idx: jnp.ndarray,  # [N] index of each lane's final real token
        lora: dict | None = None,  # slot-stacked adapter pool
        lora_ids: jnp.ndarray | None = None,  # [N] per-lane adapter slots
    ) -> tuple[jnp.ndarray, dict]:
        """Cross-request packed prefill: N lanes (chunks of N DIFFERENT
        sequences) flattened into one [N*T] token stream so the layer matmuls
        read the weights ONCE per call instead of once per request — the
        per-call overhead and weight traffic of N short prefills for the
        price of one (the reference's engines batch prefills the same way;
        vLLM scheduler: SURVEY.md §2.4). Lanes must belong to distinct
        sequences (chunk i+1 of one sequence reads pages chunk i wrote, so
        same-sequence chunks go in consecutive calls, never one call).

        Returns (logits [N, V] at each lane's last_idx, updated kv_cache)."""
        N, T = tokens.shape
        hidden, kv_cache = self._packed_forward(
            params, kv_cache, tokens, positions, page_tables, valid,
            lora=lora, lora_ids=lora_ids,
        )
        rows = hidden[jnp.arange(N) * T + last_idx]  # [N, D]
        logits = self._unembed(params, rows)  # [N, V]
        return logits, kv_cache

    def _packed_forward(
        self,
        params: dict,
        kv_cache: dict,
        tokens: jnp.ndarray,  # [N, T]
        positions: jnp.ndarray,  # [N, T]
        page_tables: jnp.ndarray,  # [N, max_pages]
        valid: jnp.ndarray,  # [N, T]
        lora: dict | None = None,
        lora_ids: jnp.ndarray | None = None,  # [N] per-lane adapter slots
    ) -> tuple[jnp.ndarray, dict]:
        """Shared N-lane layer stack for prefill_packed and verify: one weight
        pass over the flattened [N*T] token stream, per-lane paged attention.
        A mixed-adapter pack broadcasts each lane's slot id over its tokens —
        one gathered dispatch, not N per-adapter calls.
        Returns (hidden [N*T, D], updated kv_cache)."""
        c = self.config
        k_pool, v_pool = kv_cache["k"], kv_cache["v"]
        page_size = k_pool.shape[1]
        N, T = tokens.shape
        lane = jnp.arange(N)
        phys = jnp.where(valid, page_tables[lane[:, None], positions // page_size], 0)
        offsets = jnp.where(valid, positions % page_size, 0)
        pos_flat = positions.reshape(N * T)

        def make_attn_fn(off):
            def attn_fn(q, k_new, v_new, kp_, vp_):
                qs = q.reshape(N, T, *q.shape[1:])
                outs = [
                    dispatch_paged_prefill_attention(
                        qs[j], kp_, vp_, off + page_tables[j], positions[j],
                        mesh=self.attn_mesh,
                    )
                    for j in range(N)
                ]
                return jnp.concatenate(outs, axis=0)

            return attn_fn

        num_pages = k_pool.shape[0] // c.num_layers
        hidden = params["embed"][tokens.reshape(N * T)].astype(c.dtype)
        ids_flat = None
        if lora is not None:
            ids_flat = jnp.repeat(
                lora_ids.astype(jnp.int32)
                if lora_ids is not None
                else jnp.zeros(N, jnp.int32),
                T,
            )

        def body(carry, xs):
            h, kp, vp = carry
            lp, off = xs[0], xs[1]
            lkw = {}
            if lora is not None:
                lkw = dict(
                    lora_mods=xs[2], lora_ids=ids_flat, lora_scales=lora["scales"]
                )
            h, kp, vp = self._layer(
                lp, h, kp, vp, pos_flat,
                off + phys.reshape(N * T), offsets.reshape(N * T),
                make_attn_fn(off), **lkw,
            )
            return (h, kp, vp), None

        xs_all = (params["layers"], self._layer_offsets(num_pages))
        if lora is not None:
            xs_all = xs_all + (lora["mods"],)
        (hidden, k_pool, v_pool), _ = jax.lax.scan(
            body, (hidden, k_pool, v_pool), xs_all
        )
        return hidden, {"k": k_pool, "v": v_pool}

    def verify(
        self,
        params: dict,
        kv_cache: dict,  # {"k","v"} flat pools (donated)
        tokens: jnp.ndarray,  # [B, T] anchor + draft tokens per slot
        positions: jnp.ndarray,  # [B, T] consecutive fed positions per slot
        page_tables: jnp.ndarray,  # [B, max_pages] logical page ids per slot
        valid: jnp.ndarray,  # [B, T] bool (invalid rows -> trash page)
        lora: dict | None = None,  # slot-stacked adapter pool
        lora_ids: jnp.ndarray | None = None,  # [B] per-slot adapter slots
    ) -> tuple[jnp.ndarray, dict]:
        """Speculative verification: every slot feeds T = k+1 tokens at
        consecutive positions through the paged context in ONE weight pass
        (the multi-query-position generalization of decode — structurally the
        packed-prefill path with tiny chunks, so causal masking against the
        page table comes for free) and unembeds ALL rows.

        Returns (logits [B, T, V], updated kv_cache): logits[:, i] is the
        next-token distribution after the token fed at positions[:, i]. KV
        rows for invalid/rejected positions land on the trash page or are
        overwritten by the next pass at the advanced anchor."""
        B, T = tokens.shape
        hidden, kv_cache = self._packed_forward(
            params, kv_cache, tokens, positions, page_tables, valid,
            lora=lora, lora_ids=lora_ids,
        )
        logits = self._unembed(params, hidden)  # [B*T, V]
        return logits.reshape(B, T, -1), kv_cache

    def prefill_sp(
        self,
        params: dict,
        kv_cache: dict,  # {"k","v"} flat pools (donated)
        tokens: jnp.ndarray,  # [T] padded FULL prompt, T % sp == 0, start at pos 0
        positions: jnp.ndarray,  # [T] == arange(T)
        page_table: jnp.ndarray,  # [max_pages]
        valid: jnp.ndarray,  # [T] bool
        last_idx: jnp.ndarray,
        mesh: Mesh,
        sp_axis: str = "sp",
        lora: dict | None = None,
        lora_id=None,  # scalar adapter slot for this whole-prompt chunk
    ) -> tuple[jnp.ndarray, dict]:
        """Sequence-parallel prefill: the chunk's attention runs as ring
        attention over the ``sp`` mesh axis (K/V shards rotate via ppermute on
        ICI; no chip ever holds the full sequence's working set — the
        long-context path the reference lacks, SURVEY.md §2.8). The per-token
        projections stay GSPMD-sharded on the token axis; the paged-pool
        scatter reshards rows automatically. Only whole-prompt chunks
        (cached_len 0) qualify — ring attention derives global positions from
        ring offsets, so the chunk must start at position 0.

        Returns (logits[V] at last_idx, updated kv_cache)."""
        from dynamo_tpu.ops.ring_attention import ring_attention

        def make_attn_fn(off):
            def attn_fn(q, k_new, v_new, kp_, vp_):
                # ring attention consumes the chunk's own fresh K/V rows
                # directly; the pool is write-only on this path
                return ring_attention(q, k_new, v_new, mesh, axis=sp_axis)

            return attn_fn

        return self._prefill_common(
            params, kv_cache, tokens, positions, page_table, valid, last_idx,
            make_attn_fn, lora=lora, lora_id=lora_id,
        )

    def decode(
        self,
        params: dict,
        kv_cache: dict,  # {"k","v"} flat pools (donated)
        tokens: jnp.ndarray,  # [B] current token per slot
        positions: jnp.ndarray,  # [B] its absolute position
        page_tables: jnp.ndarray,  # [B, max_pages] logical (per-layer) page ids
        active: jnp.ndarray,  # [B] bool
        rope_deltas: jnp.ndarray | None = None,  # [B] M-RoPE position offsets
        lora: dict | None = None,  # slot-stacked adapter pool
        lora_ids: jnp.ndarray | None = None,  # [B] per-slot adapter ids
    ) -> tuple[jnp.ndarray, dict]:
        """One decode step for the whole batch. Returns (logits[B, V], kv_cache).

        rope_deltas (M-RoPE models): the decode rope position is
        ``positions + rope_deltas`` on every component — the per-sequence
        offset between sequential KV positions and the 3D rope timeline that
        image grids introduced during prefill."""
        c = self.config
        k_pool, v_pool = kv_cache["k"], kv_cache["v"]
        page_size = k_pool.shape[1]
        num_pages = k_pool.shape[0] // c.num_layers
        B = tokens.shape[0]
        logical = positions // page_size
        phys = jnp.where(active, page_tables[jnp.arange(B), logical], 0)
        offsets = jnp.where(active, positions % page_size, 0)

        hidden = params["embed"][tokens].astype(c.dtype)
        rope_pos3 = None
        if c.mrope_section is not None and rope_deltas is not None:
            rp = positions + rope_deltas
            rope_pos3 = jnp.stack([rp] * 3, axis=-1)

        def body(carry, xs):
            h, kp, vp = carry
            lp, off = xs[0], xs[1]

            def attn_fn(q, k_new, v_new, kp_, vp_):
                return dispatch_paged_decode_attention(
                    q, kp_, vp_, off + page_tables, positions, mesh=self.attn_mesh
                )

            lkw = {}
            if lora is not None:
                lkw = dict(
                    lora_mods=xs[2],
                    lora_ids=lora_ids
                    if lora_ids is not None
                    else jnp.zeros(B, jnp.int32),
                    lora_scales=lora["scales"],
                )
            h, kp, vp = self._layer(
                lp, h, kp, vp, positions, off + phys, offsets, attn_fn,
                rope_positions=rope_pos3, **lkw,
            )
            return (h, kp, vp), None

        xs_all = (params["layers"], self._layer_offsets(num_pages))
        if lora is not None:
            xs_all = xs_all + (lora["mods"],)
        (hidden, k_pool, v_pool), _ = jax.lax.scan(
            body, (hidden, k_pool, v_pool), xs_all
        )
        logits = self._unembed(params, hidden)
        return logits, {"k": k_pool, "v": v_pool}
