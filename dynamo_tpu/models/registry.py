"""Model registry: model_id -> (model, params).

Supported ids:
  - ``tiny`` / ``tiny:<json-overrides>``: random-weight test model
  - a local HuggingFace checkpoint directory (config.json [+ safetensors])

The reference resolves models from HF repos via its model-deployment-card
machinery (reference: lib/llm/src/model_card/create.rs, launch/dynamo-run/src/hub.rs);
here local directories fill that role (zero-egress environment).
"""

from __future__ import annotations

import json
from pathlib import Path

import jax

from dynamo_tpu.models.llama import LlamaConfig, LlamaModel
from dynamo_tpu.utils import get_logger

log = get_logger("models.registry")

# single-entry params cache for the synthetic "tiny*" families: colocated
# engines serving the SAME model (disagg prefill+decode pairs, router
# replicas, the bench's engine fleets) share one set of immutable weight
# buffers instead of materializing a copy each — params are never donated
# (only kv/slot_state are), and ModelRunner's device_put is a no-op when the
# sharding already matches, so sharing is safe. One entry only (loading a
# different model evicts the previous), and checkpoint DIRECTORIES are never
# cached: their content can change under the same path, and pinning a real
# model's host tree for process lifetime is not worth it. Written as one
# atomic (key, value) tuple: load_model runs on executor threads.
_cache: tuple | None = None  # ((model_id, seed), (model_cls, config, params))


def is_tiny_family(model_id) -> bool:
    """Exactly the synthetic tiny-family forms this registry special-cases —
    NOT any path that merely starts with "tiny": a checkpoint directory named
    tinyllama-1.1b/ is a real model and must be treated as one (not cached
    here, not given the byte-tokenizer tiny card by callers)."""
    if model_id is None:
        return True
    s = str(model_id)
    for fam in ("tiny", "tiny-moe", "tiny-mla", "tiny-vl"):
        if s == fam or s.startswith(fam + ":"):
            return True
    return False


_cacheable = is_tiny_family


def load_model(model_id: str, seed: int = 0, quantize: str | None = None,
               kv_cache_dtype: str | None = None):
    """Returns (model, params); for tiny-family models params may be shared
    with other engines in this process — treat as immutable.

    ``quantize`` ("int8_wo") applies weight-only quantization at load time —
    tiny families quantize their random init, checkpoint models quantize in
    the loader's _finish step. ``kv_cache_dtype`` ("int8") sets the KV cache
    storage dtype on the model config (pages are int8 + per-row scales,
    quant/kv.py) — llama-family pools only; the MLA latent cache raises. A
    mode embedded in a tiny:{...} override JSON works too; the explicit
    argument wins when both are set."""
    global _cache
    if kv_cache_dtype == "bf16":
        kv_cache_dtype = None  # the default storage dtype, spelled out
    key = (model_id, seed, quantize, kv_cache_dtype)
    entry = _cache
    if entry is not None and entry[0] == key:
        model_cls, cfg, params = entry[1]
        return model_cls(cfg), params  # fresh model object: attn_mesh is per-engine
    model, params = _load_model_uncached(model_id, seed, quantize, kv_cache_dtype)
    if kv_cache_dtype and not getattr(model, "SUPPORTS_KV_INT8", False):
        raise ValueError(
            f"kv_cache_dtype={kv_cache_dtype!r} is not supported by "
            f"{type(model).__name__} (the MLA latent cache is its own "
            "compression; int8 KV covers the k/v page-pool families)"
        )
    if _cacheable(model_id):
        _cache = (key, (type(model), model.config, params))
    return model, params


def _load_model_uncached(model_id: str, seed: int = 0, quantize: str | None = None,
                         kv_cache_dtype: str | None = None):
    """Returns (model, params) on host (unsharded); caller places onto mesh."""
    import dataclasses

    def with_quant(cfg):
        replace = {}
        if quantize:
            replace["quantize"] = quantize
        if kv_cache_dtype and "kv_cache_dtype" in getattr(
            cfg, "__dataclass_fields__", {}
        ):
            replace["kv_cache_dtype"] = kv_cache_dtype
        elif kv_cache_dtype:
            raise ValueError(
                f"kv_cache_dtype={kv_cache_dtype!r} is not supported by "
                f"{type(cfg).__name__}"
            )
        return dataclasses.replace(cfg, **replace) if replace else cfg

    if model_id is not None and (model_id == "tiny-moe" or model_id.startswith("tiny-moe:")):
        from dynamo_tpu.models.mixtral import MixtralConfig, MixtralModel

        overrides = json.loads(model_id.split(":", 1)[1]) if ":" in model_id else {}
        cfg = with_quant(MixtralConfig.tiny_moe(**overrides))
        model = MixtralModel(cfg)
        params = jax.jit(lambda key: model.init_params(key))(jax.random.key(seed))
        jax.block_until_ready(params)
        return model, params

    if model_id is not None and (model_id == "tiny-mla" or model_id.startswith("tiny-mla:")):
        from dynamo_tpu.models.deepseek import DeepseekConfig, DeepseekModel

        overrides = json.loads(model_id.split(":", 1)[1]) if ":" in model_id else {}
        cfg = with_quant(DeepseekConfig.tiny_mla(**overrides))
        model = DeepseekModel(cfg)
        params = jax.jit(lambda key: model.init_params(key))(jax.random.key(seed))
        jax.block_until_ready(params)
        return model, params

    if model_id is not None and (model_id == "tiny-vl" or model_id.startswith("tiny-vl:")):
        from dynamo_tpu.models.qwen2_vl import Qwen2VLConfig, Qwen2VLModel

        overrides = json.loads(model_id.split(":", 1)[1]) if ":" in model_id else {}
        cfg = with_quant(Qwen2VLConfig.tiny_vl(**overrides))
        model = Qwen2VLModel(cfg)
        params = jax.jit(lambda key: model.init_params(key))(jax.random.key(seed))
        jax.block_until_ready(params)
        return model, params

    if model_id is None or model_id == "tiny" or model_id.startswith("tiny:"):
        overrides = {}
        if model_id and ":" in model_id:
            overrides = json.loads(model_id.split(":", 1)[1])
        cfg = with_quant(LlamaConfig.tiny(**overrides))
        model = LlamaModel(cfg)
        # single jitted init: one compile for the whole tree (matters on TPU
        # backends where every compile round-trips a remote-compile service)
        params = jax.jit(lambda key: model.init_params(key))(jax.random.key(seed))
        jax.block_until_ready(params)
        return model, params

    path = Path(model_id)
    if path.is_dir() and (path / "config.json").exists():
        hf_cfg = json.loads((path / "config.json").read_text())
        arch = (hf_cfg.get("architectures") or ["LlamaForCausalLM"])[0]
        if "Mixtral" in arch:
            from dynamo_tpu.models.loader import load_mixtral_weights
            from dynamo_tpu.models.mixtral import MixtralConfig, MixtralModel

            cfg = with_quant(MixtralConfig.from_hf_config(hf_cfg))
            model = MixtralModel(cfg)
            return model, load_mixtral_weights(model, path)
        if "Deepseek" in arch:
            from dynamo_tpu.models.deepseek import DeepseekConfig, DeepseekModel
            from dynamo_tpu.models.loader import load_deepseek_weights

            cfg = with_quant(DeepseekConfig.from_hf_config(hf_cfg))
            model = DeepseekModel(cfg)
            return model, load_deepseek_weights(model, path)
        if "Qwen2VL" in arch or hf_cfg.get("model_type") == "qwen2_vl":
            from dynamo_tpu.models.loader import load_qwen2_vl_weights
            from dynamo_tpu.models.qwen2_vl import Qwen2VLConfig, Qwen2VLModel

            cfg = with_quant(Qwen2VLConfig.from_hf_config(hf_cfg))
            model = Qwen2VLModel(cfg)
            return model, load_qwen2_vl_weights(model, path)
        if "Llama" not in arch and "Qwen" not in arch:
            raise ValueError(f"unsupported architecture {arch}")
        cfg = with_quant(LlamaConfig.from_hf_config(hf_cfg))
        model = LlamaModel(cfg)
        from dynamo_tpu.models.loader import load_llama_weights

        params = load_llama_weights(model, path)
        return model, params

    raise ValueError(f"unknown model id {model_id!r} (not 'tiny' and not a local checkpoint dir)")
