"""Model registry: model_id -> (model, params).

Supported ids:
  - ``tiny`` / ``tiny:<json-overrides>``: random-weight test model
  - a local HuggingFace checkpoint directory (config.json [+ safetensors])

The reference resolves models from HF repos via its model-deployment-card
machinery (reference: lib/llm/src/model_card/create.rs, launch/dynamo-run/src/hub.rs);
here local directories fill that role (zero-egress environment).
"""

from __future__ import annotations

import json
from pathlib import Path

import jax

from dynamo_tpu.models.llama import LlamaConfig, LlamaModel
from dynamo_tpu.utils import get_logger

log = get_logger("models.registry")


def load_model(model_id: str, seed: int = 0):
    """Returns (model, params) on host (unsharded); caller places onto mesh."""
    if model_id is not None and (model_id == "tiny-moe" or model_id.startswith("tiny-moe:")):
        from dynamo_tpu.models.mixtral import MixtralConfig, MixtralModel

        overrides = json.loads(model_id.split(":", 1)[1]) if ":" in model_id else {}
        cfg = MixtralConfig.tiny_moe(**overrides)
        model = MixtralModel(cfg)
        params = jax.jit(lambda key: model.init_params(key))(jax.random.key(seed))
        jax.block_until_ready(params)
        return model, params

    if model_id is not None and (model_id == "tiny-mla" or model_id.startswith("tiny-mla:")):
        from dynamo_tpu.models.deepseek import DeepseekConfig, DeepseekModel

        overrides = json.loads(model_id.split(":", 1)[1]) if ":" in model_id else {}
        cfg = DeepseekConfig.tiny_mla(**overrides)
        model = DeepseekModel(cfg)
        params = jax.jit(lambda key: model.init_params(key))(jax.random.key(seed))
        jax.block_until_ready(params)
        return model, params

    if model_id is not None and (model_id == "tiny-vl" or model_id.startswith("tiny-vl:")):
        from dynamo_tpu.models.qwen2_vl import Qwen2VLConfig, Qwen2VLModel

        overrides = json.loads(model_id.split(":", 1)[1]) if ":" in model_id else {}
        cfg = Qwen2VLConfig.tiny_vl(**overrides)
        model = Qwen2VLModel(cfg)
        params = jax.jit(lambda key: model.init_params(key))(jax.random.key(seed))
        jax.block_until_ready(params)
        return model, params

    if model_id is None or model_id == "tiny" or model_id.startswith("tiny:"):
        overrides = {}
        if model_id and ":" in model_id:
            overrides = json.loads(model_id.split(":", 1)[1])
        cfg = LlamaConfig.tiny(**overrides)
        model = LlamaModel(cfg)
        # single jitted init: one compile for the whole tree (matters on TPU
        # backends where every compile round-trips a remote-compile service)
        params = jax.jit(lambda key: model.init_params(key))(jax.random.key(seed))
        jax.block_until_ready(params)
        return model, params

    path = Path(model_id)
    if path.is_dir() and (path / "config.json").exists():
        hf_cfg = json.loads((path / "config.json").read_text())
        arch = (hf_cfg.get("architectures") or ["LlamaForCausalLM"])[0]
        if "Mixtral" in arch:
            from dynamo_tpu.models.loader import load_mixtral_weights
            from dynamo_tpu.models.mixtral import MixtralConfig, MixtralModel

            cfg = MixtralConfig.from_hf_config(hf_cfg)
            model = MixtralModel(cfg)
            return model, load_mixtral_weights(model, path)
        if "Deepseek" in arch:
            from dynamo_tpu.models.deepseek import DeepseekConfig, DeepseekModel
            from dynamo_tpu.models.loader import load_deepseek_weights

            cfg = DeepseekConfig.from_hf_config(hf_cfg)
            model = DeepseekModel(cfg)
            return model, load_deepseek_weights(model, path)
        if "Qwen2VL" in arch or hf_cfg.get("model_type") == "qwen2_vl":
            from dynamo_tpu.models.loader import load_qwen2_vl_weights
            from dynamo_tpu.models.qwen2_vl import Qwen2VLConfig, Qwen2VLModel

            cfg = Qwen2VLConfig.from_hf_config(hf_cfg)
            model = Qwen2VLModel(cfg)
            return model, load_qwen2_vl_weights(model, path)
        if "Llama" not in arch and "Qwen" not in arch:
            raise ValueError(f"unsupported architecture {arch}")
        cfg = LlamaConfig.from_hf_config(hf_cfg)
        model = LlamaModel(cfg)
        from dynamo_tpu.models.loader import load_llama_weights

        params = load_llama_weights(model, path)
        return model, params

    raise ValueError(f"unknown model id {model_id!r} (not 'tiny' and not a local checkpoint dir)")
