"""Qwen2-VL-style multimodal model: ViT vision tower + Qwen2 language model.

The reference serves this family through its engine adapters (the vLLM patch's
model zoo); here it is native. The language half IS LlamaModel (Qwen2 = llama
geometry + qkv biases), so the paged KV cache, Pallas decode kernel, TP
shardings, disagg block extraction, and prefix caching all apply unchanged.
The vision half runs as a separate jitted encode (models/vision.py) whose
outputs override the embedding rows of the image-slot virtual tokens during
prefill (llm/multimodal.py explains the virtual-token scheme).

Decode is pure text — images only affect prefill — so the decode hot path is
byte-identical to the text family's.
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace

import jax
from jax.sharding import Mesh

from dynamo_tpu.models.llama import LlamaConfig, LlamaModel, parse_dtype
from dynamo_tpu.models.vision import VisionConfig, VisionModel


@dataclass(frozen=True)
class Qwen2VLConfig(LlamaConfig):
    vision: VisionConfig = field(default_factory=VisionConfig)

    @classmethod
    def from_hf_config(cls, d: dict) -> "Qwen2VLConfig":
        vision = VisionConfig.from_hf_config(
            d.get("vision_config", {}), out_hidden_size=d["hidden_size"]
        )
        base = LlamaConfig.from_hf_config(d)
        fields = {f: getattr(base, f) for f in base.__dataclass_fields__}
        rope_scaling = d.get("rope_scaling") or {}
        if rope_scaling.get("type", rope_scaling.get("rope_type")) == "mrope":
            section = tuple(rope_scaling["mrope_section"])
            if sum(section) != base.head_dim // 2 or len(section) != 3:
                raise ValueError(
                    f"mrope_section {section} must be 3 values summing to "
                    f"head_dim//2 = {base.head_dim // 2}"
                )
            fields["mrope_section"] = section
        return cls(**fields, vision=vision)

    @classmethod
    def tiny_vl(cls, **overrides) -> "Qwen2VLConfig":
        if "dtype" in overrides:
            overrides["dtype"] = parse_dtype(overrides["dtype"])
        # mrope on by default: the real qwen2_vl parameterization (head_dim 16
        # -> sections (2, 3, 3) summing to 8)
        text = LlamaConfig.tiny(attention_bias=True, mrope_section=(2, 3, 3))
        base = cls(
            **{f: getattr(text, f) for f in text.__dataclass_fields__},
            vision=VisionConfig.tiny(out_hidden_size=text.hidden_size),
        )
        return replace(base, **overrides)


class Qwen2VLModel(LlamaModel):
    """LlamaModel + a vision tower under params["vision"]."""

    def __init__(self, config: Qwen2VLConfig):
        super().__init__(config)
        self.vision = VisionModel(config.vision)

    @property
    def is_multimodal(self) -> bool:
        return True

    def init_params(self, rng: jax.Array, quantize: bool = True) -> dict:
        # only the text layers quantize (LlamaModel.QUANT_WEIGHT_NAMES); the
        # vision tower is prefill-only and stays full precision
        k_text, k_vis = jax.random.split(rng)
        params = super().init_params(k_text, quantize=quantize)
        params["vision"] = self.vision.init_params(k_vis)
        return params

    def param_shardings(self, mesh: Mesh, tp_axis: str = "tp") -> dict:
        shardings = super().param_shardings(mesh, tp_axis)
        shardings["vision"] = self.vision.param_shardings(mesh, tp_axis)
        return shardings

    def encode_images(self, params, patches, rows, cols, valid, segments=None):
        """[N, patch_dim] padded patches -> [N/merge^2, hidden] embeddings."""
        return self.vision.encode(
            params["vision"], patches, rows, cols, valid, segments=segments
        )
