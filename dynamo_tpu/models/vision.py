"""Vision tower: ViT encoder over image patches, Qwen2-VL style.

Fills the vision half of the multimodal model family slot (the reference
delegates multimodal serving to its engines — e.g. Qwen2-VL via vLLM; here the
tower is native JAX). TPU-first design:

  - the encoder consumes **pre-patchified** pixels ``[N, C*ps*ps]`` padded to a
    static patch bucket (one executable per bucket, no per-image recompiles);
    a validity mask handles padding
  - 2D rotary positions in the exact HF qwen2_vl layout (row/col angle halves
    with rotate_half pairing across the full head dim) so checkpoints load 1:1
  - layers are scan-stacked like the LLM (single compiled layer body)
  - a 2x2 spatial merger concatenates neighbouring patch features and projects
    into the LLM's hidden size, so each merged patch becomes ONE token in the
    language sequence (``tokens_per_image = (h/m) * (w/m)`` for an h x w patch
    grid with merge m)
  - everything is bf16 matmuls on the MXU; attention over patches is
    bidirectional (no causal mask)
"""

from __future__ import annotations

from dataclasses import dataclass, replace
from typing import Any

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dynamo_tpu.models.llama import parse_dtype


def layer_norm(x: jnp.ndarray, weight: jnp.ndarray, bias: jnp.ndarray, eps: float) -> jnp.ndarray:
    xf = x.astype(jnp.float32)
    mu = xf.mean(-1, keepdims=True)
    var = ((xf - mu) ** 2).mean(-1, keepdims=True)
    return ((xf - mu) * jax.lax.rsqrt(var + eps) * weight + bias).astype(x.dtype)


def quick_gelu(x: jnp.ndarray) -> jnp.ndarray:
    return x * jax.nn.sigmoid(1.702 * x)


_ACTS = {
    "quick_gelu": quick_gelu,
    "gelu": lambda x: jax.nn.gelu(x, approximate=False),
    "gelu_new": jax.nn.gelu,
    "silu": jax.nn.silu,
}


@dataclass(frozen=True)
class VisionConfig:
    patch_size: int = 14
    in_channels: int = 3
    spatial_merge_size: int = 2
    hidden_size: int = 1280
    intermediate_size: int = 3420
    num_layers: int = 32
    num_heads: int = 16
    out_hidden_size: int = 3584  # LLM hidden size
    hidden_act: str = "quick_gelu"  # HF qwen2_vl vision default
    layer_norm_eps: float = 1e-6
    rope_theta: float = 10000.0
    dtype: Any = jnp.bfloat16

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads

    @property
    def patch_dim(self) -> int:
        return self.in_channels * self.patch_size * self.patch_size

    @classmethod
    def from_hf_config(cls, d: dict, out_hidden_size: int) -> "VisionConfig":
        """From a HF qwen2_vl ``vision_config`` dict."""
        hidden = d.get("embed_dim", d.get("hidden_size", 1280))
        depth = d.get("depth", d.get("num_hidden_layers", 32))
        return cls(
            patch_size=d.get("patch_size", 14),
            in_channels=d.get("in_channels", d.get("in_chans", 3)),
            spatial_merge_size=d.get("spatial_merge_size", 2),
            hidden_size=hidden,
            intermediate_size=d.get(
                "intermediate_size", int(hidden * d.get("mlp_ratio", 4.0))
            ),
            num_layers=depth,
            num_heads=d.get("num_heads", d.get("num_attention_heads", 16)),
            out_hidden_size=out_hidden_size,
            hidden_act=d.get("hidden_act", "quick_gelu"),
        )

    @classmethod
    def tiny(cls, out_hidden_size: int = 64, **overrides) -> "VisionConfig":
        if "dtype" in overrides:
            overrides["dtype"] = parse_dtype(overrides["dtype"])
        base = cls(
            patch_size=4,
            in_channels=3,
            spatial_merge_size=2,
            hidden_size=32,
            intermediate_size=64,
            num_layers=2,
            num_heads=2,
            out_hidden_size=out_hidden_size,
            dtype=jnp.float32,
        )
        return replace(base, **overrides)


def rope_2d(x: jnp.ndarray, rows: jnp.ndarray, cols: jnp.ndarray, theta: float) -> jnp.ndarray:
    """2D rotary embedding, HF qwen2_vl layout: the angle vector over the
    first half of the head dim is ``[row_angles (D/4) | col_angles (D/4)]``
    (each with inv_freq ``theta^(-j/(D/4))``), duplicated to the second half,
    and dim i pairs with dim i + D/2 (rotate_half over the full head dim) — so
    loaded checkpoints see exactly the rotation they were trained with.

    x: [N, H, D] (D divisible by 4), rows/cols: [N] int32.
    """
    D = x.shape[-1]
    quarter = D // 4
    inv_freq = theta ** (-jnp.arange(quarter, dtype=jnp.float32) / quarter)
    ang = jnp.concatenate(
        [rows[:, None].astype(jnp.float32) * inv_freq,
         cols[:, None].astype(jnp.float32) * inv_freq],
        axis=-1,
    )  # [N, D/2]
    cos = jnp.cos(ang)[:, None, :].astype(x.dtype)
    sin = jnp.sin(ang)[:, None, :].astype(x.dtype)
    x1, x2 = x[..., : D // 2], x[..., D // 2 :]
    return jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)


class VisionModel:
    """Stateless ViT forward over a params pytree (one image per call)."""

    def __init__(self, config: VisionConfig):
        self.config = config

    def init_params(self, rng: jax.Array) -> dict:
        c = self.config
        keys = iter(jax.random.split(rng, 12))

        def dense(key, shape):
            scale = 1.0 / jnp.sqrt(jnp.float32(shape[0]))
            return (jax.random.normal(key, shape, jnp.float32) * scale).astype(c.dtype)

        L, D, F = c.num_layers, c.hidden_size, c.intermediate_size
        m2 = c.spatial_merge_size * c.spatial_merge_size
        # LayerNorm (weight+bias) and biased projections: the exact HF
        # qwen2_vl vision-tower parameterization, loadable 1:1
        return {
            "patch_embed": dense(next(keys), (c.patch_dim, D)),
            "layers": {
                "norm1": jnp.ones((L, D), c.dtype),
                "norm1_b": jnp.zeros((L, D), c.dtype),
                "wqkv": dense(next(keys), (L, D, 3 * D)),
                "bqkv": jnp.zeros((L, 3 * D), c.dtype),
                "wo": dense(next(keys), (L, D, D)),
                "bo": jnp.zeros((L, D), c.dtype),
                "norm2": jnp.ones((L, D), c.dtype),
                "norm2_b": jnp.zeros((L, D), c.dtype),
                "fc1": dense(next(keys), (L, D, F)),
                "bfc1": jnp.zeros((L, F), c.dtype),
                "fc2": dense(next(keys), (L, F, D)),
                "bfc2": jnp.zeros((L, D), c.dtype),
            },
            "merger_norm": jnp.ones((D,), c.dtype),
            "merger_norm_b": jnp.zeros((D,), c.dtype),
            "merger_fc1": dense(next(keys), (m2 * D, m2 * D)),
            "merger_bfc1": jnp.zeros((m2 * D,), c.dtype),
            "merger_fc2": dense(next(keys), (m2 * D, c.out_hidden_size)),
            "merger_bfc2": jnp.zeros((c.out_hidden_size,), c.dtype),
        }

    def param_shardings(self, mesh: Mesh, tp_axis: str = "tp") -> dict:
        """Vision tower is small next to the LLM: MLP/attention projections are
        tp-sharded on the output axis, everything else replicated."""
        tp = tp_axis if tp_axis in mesh.axis_names else None

        def ns(*spec):
            return NamedSharding(mesh, P(*spec))

        return {
            "patch_embed": ns(None, None),
            "layers": {
                "norm1": ns(None, None),
                "norm1_b": ns(None, None),
                "wqkv": ns(None, None, None),
                "bqkv": ns(None, None),
                "wo": ns(None, None, None),
                "bo": ns(None, None),
                "norm2": ns(None, None),
                "norm2_b": ns(None, None),
                "fc1": ns(None, None, tp),
                "bfc1": ns(None, tp),
                "fc2": ns(None, tp, None),
                "bfc2": ns(None, None),
            },
            "merger_norm": ns(None),
            "merger_norm_b": ns(None),
            "merger_fc1": ns(None, None),
            "merger_bfc1": ns(None),
            "merger_fc2": ns(None, None),
            "merger_bfc2": ns(None),
        }

    def encode(
        self,
        params: dict,
        patches: jnp.ndarray,  # [N, patch_dim] pre-patchified pixels (padded)
        rows: jnp.ndarray,  # [N] patch row index (0 for padding)
        cols: jnp.ndarray,  # [N] patch col index
        valid: jnp.ndarray,  # [N] bool
        segments: jnp.ndarray | None = None,  # [N] image id per patch
    ) -> jnp.ndarray:
        """-> [N // merge^2, out_hidden_size] merged patch embeddings.

        Patches must be laid out in merge-group order (all merge^2 members of a
        merged token contiguous) — llm/multimodal.py's patchify produces this.
        ``segments`` batches several images through one call: attention is
        masked block-diagonal so patches never attend across images.
        """
        c = self.config
        N = patches.shape[0]
        act = _ACTS[c.hidden_act]
        h = (patches.astype(c.dtype) @ params["patch_embed"])  # [N, D]

        neg = jnp.asarray(jnp.finfo(jnp.float32).min, jnp.float32)
        attn_bias = jnp.where(valid[None, :], 0.0, neg)  # [1, N]
        if segments is not None:
            attn_bias = attn_bias + jnp.where(
                segments[:, None] == segments[None, :], 0.0, neg
            )

        def body(hidden, lp):
            x = layer_norm(hidden, lp["norm1"], lp["norm1_b"], c.layer_norm_eps)
            qkv = x @ lp["wqkv"] + lp["bqkv"]
            q, k, v = jnp.split(qkv, 3, axis=-1)
            q = q.reshape(N, c.num_heads, c.head_dim)
            k = k.reshape(N, c.num_heads, c.head_dim)
            v = v.reshape(N, c.num_heads, c.head_dim)
            q = rope_2d(q, rows, cols, c.rope_theta)
            k = rope_2d(k, rows, cols, c.rope_theta)
            scores = jnp.einsum("qhd,khd->hqk", q, k, preferred_element_type=jnp.float32)
            scores = scores / jnp.sqrt(jnp.float32(c.head_dim)) + attn_bias[None]
            probs = jax.nn.softmax(scores, axis=-1).astype(c.dtype)
            attn = jnp.einsum("hqk,khd->qhd", probs, v)
            hidden = hidden + attn.reshape(N, -1) @ lp["wo"] + lp["bo"]
            x = layer_norm(hidden, lp["norm2"], lp["norm2_b"], c.layer_norm_eps)
            hidden = hidden + (act(x @ lp["fc1"] + lp["bfc1"]) @ lp["fc2"] + lp["bfc2"])
            return hidden, None

        h, _ = jax.lax.scan(body, h, params["layers"])

        # 2x2 spatial merge: groups are contiguous rows -> concat features
        m2 = c.spatial_merge_size * c.spatial_merge_size
        h = layer_norm(h, params["merger_norm"], params["merger_norm_b"], c.layer_norm_eps)
        h = h.reshape(N // m2, m2 * c.hidden_size)
        h = (
            jax.nn.gelu(
                h.astype(c.dtype) @ params["merger_fc1"] + params["merger_bfc1"],
                approximate=False,
            )
            @ params["merger_fc2"]
            + params["merger_bfc2"]
        )
        return h
