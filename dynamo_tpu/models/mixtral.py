"""Mixtral-family MoE model: Llama attention + sparse top-k expert MLP.

Reuses the paged-attention layer machinery from LlamaModel; replaces the dense
MLP with the GShard-style MoE block (dynamo_tpu/ops/moe.py). Expert weights
carry a leading [E] axis sharded over the mesh's "ep" axis; everything else
follows the Llama TP rules. Covers the reference's DeepSeek-V3/Mixtral MoE
target (BASELINE.md config 4; the reference itself delegates MoE to engines,
SURVEY.md §2.8).
"""

from __future__ import annotations

from dataclasses import dataclass, replace

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dynamo_tpu.models.llama import LlamaConfig, LlamaModel
from dynamo_tpu.ops.moe import moe_block
from dynamo_tpu.ops.norms import rms_norm
from dynamo_tpu.quant import qlinear, quantize_shardings_int8


@dataclass(frozen=True)
class MixtralConfig(LlamaConfig):
    num_experts: int = 8
    num_experts_per_tok: int = 2
    moe_capacity_factor: float = 2.0

    @classmethod
    def from_hf_config(cls, d: dict) -> "MixtralConfig":
        base = LlamaConfig.from_hf_config(d)
        return cls(
            **{f: getattr(base, f) for f in base.__dataclass_fields__},
            num_experts=d.get("num_local_experts", 8),
            num_experts_per_tok=d.get("num_experts_per_tok", 2),
        )

    @classmethod
    def tiny_moe(cls, **overrides) -> "MixtralConfig":
        from dynamo_tpu.models.llama import parse_dtype

        if "dtype" in overrides:
            overrides["dtype"] = parse_dtype(overrides["dtype"])
        tiny = LlamaConfig.tiny()
        base = cls(
            **{f: getattr(tiny, f) for f in tiny.__dataclass_fields__},
            num_experts=4,
            num_experts_per_tok=2,
            moe_capacity_factor=8.0,  # exact (no drops) at test scale
        )
        return replace(base, **overrides)


class MixtralModel(LlamaModel):
    #: the MoE _layer override predates the gathered LoRA pass; expert-bank
    #: adapter deltas need their own routing-aware treatment
    SUPPORTS_LORA = False

    #: attention matmuls + the per-expert FFN banks quantize; the router
    #: stays f32 (routing decisions are precision-sensitive and tiny)
    QUANT_WEIGHT_NAMES = frozenset(
        {"wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down"}
    )

    def __init__(self, config: MixtralConfig):
        super().__init__(config)

    def _init_raw_params(self, rng: jax.Array) -> dict:
        c = self.config
        params = super()._init_raw_params(rng)
        keys = iter(jax.random.split(jax.random.fold_in(rng, 1), 8))

        def dense(key, shape, scale_axis):
            scale = 1.0 / jnp.sqrt(jnp.float32(shape[scale_axis]))
            return (jax.random.normal(key, shape, jnp.float32) * scale).astype(c.dtype)

        L, D, F, E = c.num_layers, c.hidden_size, c.intermediate_size, c.num_experts
        layers = params["layers"]
        # replace the dense MLP with router + expert banks
        for k in ("gate", "up", "down"):
            del layers[k]
        layers["router"] = dense(next(keys), (L, D, E), 0).astype(jnp.float32)
        layers["w_gate"] = dense(next(keys), (L, E, D, F), 2)
        layers["w_up"] = dense(next(keys), (L, E, D, F), 2)
        layers["w_down"] = dense(next(keys), (L, E, F, D), 2)
        return params

    def param_shardings(self, mesh: Mesh, tp_axis: str = "tp", ep_axis: str = "ep") -> dict:
        shardings = super().param_shardings(mesh, tp_axis)
        layers = shardings["layers"]
        for k in ("gate", "up", "down"):
            del layers[k]
        ep = ep_axis if ep_axis in mesh.axis_names else None

        def ns(*spec):
            return NamedSharding(mesh, P(*spec))

        layers["router"] = ns(None, None, None)
        layers["w_gate"] = ns(None, ep, None, None)
        layers["w_up"] = ns(None, ep, None, None)
        layers["w_down"] = ns(None, ep, None, None)
        # second pass for the expert banks super() hadn't seen yet
        # (idempotent: the already-wrapped attention leaves skip)
        if self.config.quantize:
            shardings["layers"] = quantize_shardings_int8(
                shardings["layers"], self.QUANT_WEIGHT_NAMES
            )
        return shardings

    def _layer(self, lp, hidden, k_pool, v_pool, positions, flat_phys, offsets, attn_fn,
               rope_positions=None):
        # rope_positions (M-RoPE) accepted for base-class contract parity;
        # Mixtral is text-only so plain 1D RoPE always applies
        c = self.config
        T = hidden.shape[0]
        # attention sublayer identical to Llama
        from dynamo_tpu.ops.rotary import apply_rope
        from dynamo_tpu.ops.attention import scatter_kv

        h = rms_norm(hidden, lp["input_norm"], c.rms_norm_eps)
        q = apply_rope(qlinear(h, lp["wq"]).reshape(T, c.num_heads, c.head_dim), positions, c.rope_theta)
        k = apply_rope(qlinear(h, lp["wk"]).reshape(T, c.num_kv_heads, c.head_dim), positions, c.rope_theta)
        v = qlinear(h, lp["wv"]).reshape(T, c.num_kv_heads, c.head_dim)
        k_pool, v_pool = scatter_kv(k_pool, v_pool, k, v, flat_phys, offsets)
        attn = attn_fn(q, k, v, k_pool, v_pool)
        hidden = hidden + qlinear(attn.reshape(T, -1), lp["wo"])

        # sparse MoE sublayer
        h = rms_norm(hidden, lp["post_norm"], c.rms_norm_eps)
        moe_out = moe_block(
            h,
            lp["router"],
            lp["w_gate"],
            lp["w_up"],
            lp["w_down"],
            num_experts_per_tok=c.num_experts_per_tok,
            capacity_factor=c.moe_capacity_factor,
        )
        hidden = hidden + moe_out
        return hidden, k_pool, v_pool
