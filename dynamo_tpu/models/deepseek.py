"""DeepSeek-V2/V3-family model: Multi-head Latent Attention (MLA) + MoE with
shared experts, in pure JAX with a paged *latent* KV cache.

Why this family matters here: the reference's disaggregation patch explicitly
extends vLLM's deepseek_v2 model for MLA + disagg (reference: patch
`+++ b/vllm/model_executor/models/deepseek_v2.py`, SURVEY.md §2.4/§2.8), and
MLA is the strongest long-context lever available: the cache stores one
``kv_lora_rank + qk_rope_head_dim`` latent vector per token instead of
``2 * Hkv * head_dim`` — ~10-25x less HBM per token, which multiplies the
usable context length / batch on a TPU chip.

TPU-first design:
  - **Absorbed (weight-folded) attention everywhere**: scores are computed
    directly against the cached latents (q folded through the k-up projection,
    outputs folded through the v-up projection), so decode is two dense
    einsums over ``[S, d_c + d_r]`` — MXU-shaped, no per-head KV expansion and
    no gather of materialized K/V.
  - The latent cache is a flat page pool ``{"ckv": [L*P, ps, d_c + d_r]}``
    carried through the layer scans and donated (same in-place scatter
    property as the Llama pool; see dynamo_tpu/ops/attention.py).
  - Layers are scan-stacked in two homogeneous groups (DeepSeek interleaves
    dense and MoE layers: the first ``first_k_dense_replace`` are dense MLP,
    the rest are shared-expert + routed-expert MoE), one compiled body each.
  - Tensor parallelism: per-head projections (q up, k-up, v-up, o) shard on
    the ``tp`` axis; the latent path (down-projections, cache) is replicated —
    it is head-independent by construction. Routed experts shard on ``ep``.

Cache-content convention: the pool row for a token stores
``[rms_norm(c_latent), rope(k_rope)]`` — the normalized latent and the
position-rotated shared rope key, i.e. exactly what the absorbed score needs.
"""

from __future__ import annotations

from dataclasses import dataclass, replace
from typing import Any, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dynamo_tpu.ops.moe import moe_block
from dynamo_tpu.ops.norms import rms_norm
from dynamo_tpu.ops.rotary import apply_rope
from dynamo_tpu.quant import (
    QUANT_MODES,
    qlinear,
    quantize_shardings_int8,
    quantize_tree_int8,
)

_NEG_INF = -1e30


def _use_pallas_mla() -> bool:
    """Trace-time choice of the Pallas latent-page decode kernel: same
    DYNTPU_PALLAS override semantics as the GQA kernel (shared pallas_flag);
    default on for real TPU backends."""
    from dynamo_tpu.ops.attention import _on_tpu, pallas_flag

    flag = pallas_flag()
    if flag is not None:
        return flag
    return _on_tpu()


@dataclass(frozen=True)
class DeepseekConfig:
    vocab_size: int = 102400
    hidden_size: int = 5120
    intermediate_size: int = 12288  # dense layers' MLP width
    num_layers: int = 60
    num_heads: int = 128
    # MLA geometry
    q_lora_rank: Optional[int] = 1536  # None => plain q projection
    kv_lora_rank: int = 512  # d_c
    qk_nope_head_dim: int = 128  # d_n
    qk_rope_head_dim: int = 64  # d_r
    v_head_dim: int = 128  # d_v
    # MoE geometry
    n_routed_experts: int = 160
    num_experts_per_tok: int = 6
    n_shared_experts: int = 2
    moe_intermediate_size: int = 1536
    first_k_dense_replace: int = 1
    moe_capacity_factor: float = 2.0
    # routed-expert output scale (DeepSeek-V2 uses 16.0; V2-Lite 1.0)
    routed_scaling_factor: float = 1.0
    # False (DeepSeek default): top-k probs taken from the full softmax,
    # not renormalized over the selected k
    norm_topk_prob: bool = False
    rope_theta: float = 10000.0
    rms_norm_eps: float = 1e-6
    # weight-only quantization mode (None or "int8_wo"); see LlamaConfig
    quantize: Any = None
    dtype: Any = jnp.bfloat16

    @property
    def latent_dim(self) -> int:
        """Logical cache row width: latent + shared rope key."""
        return self.kv_lora_rank + self.qk_rope_head_dim

    @property
    def latent_dim_padded(self) -> int:
        """Physical row width, padded to the TPU lane tiling (128): Mosaic
        requires 128-aligned minor dims, and DeepSeek's 512+64=576 is not.
        ~11%% extra on a cache that is already ~20x smaller than full KV."""
        return -(-self.latent_dim // 128) * 128

    @classmethod
    def from_hf_config(cls, d: dict) -> "DeepseekConfig":
        """Build from a HuggingFace deepseek_v2/v3 config.json dict.

        Raises for checkpoint features this implementation does not model yet
        (wrong numerics would otherwise be silent): sigmoid routing with
        correction bias (V3), group-limited top-k, and yarn rope scaling."""
        unsupported = []
        if d.get("scoring_func", "softmax") != "softmax":
            unsupported.append(f"scoring_func={d['scoring_func']!r} (V3 sigmoid routing)")
        if d.get("topk_method", "greedy") not in ("greedy", None):
            unsupported.append(f"topk_method={d['topk_method']!r} (group-limited top-k)")
        if d.get("rope_scaling"):
            unsupported.append("rope_scaling (yarn + mscale)")
        if unsupported:
            raise ValueError(
                "deepseek checkpoint needs unsupported features: "
                + ", ".join(unsupported)
            )
        return cls(
            vocab_size=d["vocab_size"],
            hidden_size=d["hidden_size"],
            intermediate_size=d["intermediate_size"],
            num_layers=d["num_hidden_layers"],
            num_heads=d["num_attention_heads"],
            q_lora_rank=d.get("q_lora_rank"),
            kv_lora_rank=d.get("kv_lora_rank", 512),
            qk_nope_head_dim=d.get("qk_nope_head_dim", 128),
            qk_rope_head_dim=d.get("qk_rope_head_dim", 64),
            v_head_dim=d.get("v_head_dim", 128),
            n_routed_experts=d.get("n_routed_experts", 64),
            num_experts_per_tok=d.get("num_experts_per_tok", 6),
            n_shared_experts=d.get("n_shared_experts", 2),
            moe_intermediate_size=d.get("moe_intermediate_size", 1408),
            first_k_dense_replace=d.get("first_k_dense_replace", 1),
            routed_scaling_factor=d.get("routed_scaling_factor", 1.0),
            norm_topk_prob=d.get("norm_topk_prob", False),
            rope_theta=d.get("rope_theta", 10000.0),
            rms_norm_eps=d.get("rms_norm_eps", 1e-6),
        )

    @classmethod
    def tiny_mla(cls, **overrides) -> "DeepseekConfig":
        """Small config for tests (1 dense + 1 MoE layer)."""
        from dynamo_tpu.models.llama import parse_dtype

        if "dtype" in overrides:
            overrides["dtype"] = parse_dtype(overrides["dtype"])
        base = cls(
            vocab_size=256,
            hidden_size=64,
            intermediate_size=128,
            num_layers=2,
            num_heads=4,
            q_lora_rank=None,
            kv_lora_rank=32,
            qk_nope_head_dim=16,
            qk_rope_head_dim=8,
            v_head_dim=16,
            n_routed_experts=4,
            num_experts_per_tok=2,
            n_shared_experts=1,
            moe_intermediate_size=32,
            first_k_dense_replace=1,
            moe_capacity_factor=8.0,  # exact (no drops) at test scale
            dtype=jnp.float32,
        )
        return replace(base, **overrides)


class DeepseekModel:
    """Stateless forward functions over a params pytree (MLA + MoE)."""

    #: quantizable per-layer weights in both layer groups (applied
    #: by-presence). Deliberately excluded: the k-up/v-up banks w_kb/w_vb
    #: (3-D per-head einsum operands, ~1% of bytes), norms, and the f32
    #: router.
    QUANT_WEIGHT_NAMES = frozenset({
        "w_q", "w_dq", "w_uq", "w_dkv", "wo",
        "gate", "up", "down",
        "w_gate", "w_up", "w_down",
        "shared_gate", "shared_up", "shared_down",
    })

    def __init__(self, config: DeepseekConfig):
        self.config = config
        # set by ModelRunner for tp>1: the Pallas MLA kernel runs under
        # shard_map on this mesh (heads sharded; latent pool replicated)
        self.attn_mesh = None

    # ---------------- params ----------------

    def _attn_params(self, keys, L: int) -> dict:
        c = self.config

        def dense(key, shape, scale_axis):
            scale = 1.0 / jnp.sqrt(jnp.float32(shape[scale_axis]))
            return (jax.random.normal(key, shape, jnp.float32) * scale).astype(c.dtype)

        D, H = c.hidden_size, c.num_heads
        dn, dr, dv, dc = (
            c.qk_nope_head_dim,
            c.qk_rope_head_dim,
            c.v_head_dim,
            c.kv_lora_rank,
        )
        p = {
            "input_norm": jnp.ones((L, D), c.dtype),
            "w_dkv": dense(next(keys), (L, D, dc + dr), 1),
            "kv_norm": jnp.ones((L, dc), c.dtype),
            # k-up and v-up projections from the latent, per head
            "w_kb": dense(next(keys), (L, dc, H, dn), 1),
            "w_vb": dense(next(keys), (L, dc, H, dv), 1),
            "wo": dense(next(keys), (L, H * dv, D), 1),
            "post_norm": jnp.ones((L, D), c.dtype),
        }
        if c.q_lora_rank:
            p["w_dq"] = dense(next(keys), (L, D, c.q_lora_rank), 1)
            p["q_norm"] = jnp.ones((L, c.q_lora_rank), c.dtype)
            p["w_uq"] = dense(next(keys), (L, c.q_lora_rank, H * (dn + dr)), 1)
        else:
            p["w_q"] = dense(next(keys), (L, D, H * (dn + dr)), 1)
        return p

    def quantize_params(self, params: dict) -> dict:
        """Apply config.quantize to both layer groups (no-op when unset)."""
        mode = self.config.quantize
        if not mode:
            return params
        if mode not in QUANT_MODES:
            raise ValueError(f"unknown quantize mode {mode!r} (supported: {QUANT_MODES})")
        params = dict(params)
        for group in ("dense_layers", "moe_layers"):
            params[group] = quantize_tree_int8(params[group], self.QUANT_WEIGHT_NAMES)
        return params

    def init_params(self, rng: jax.Array, quantize: bool = True) -> dict:
        params = self._init_raw_params(rng)
        return self.quantize_params(params) if quantize else params

    def _init_raw_params(self, rng: jax.Array) -> dict:
        c = self.config
        keys = iter(jax.random.split(rng, 48))

        def dense(key, shape, scale_axis):
            scale = 1.0 / jnp.sqrt(jnp.float32(shape[scale_axis]))
            return (jax.random.normal(key, shape, jnp.float32) * scale).astype(c.dtype)

        D, F, V, E = (
            c.hidden_size,
            c.intermediate_size,
            c.vocab_size,
            c.n_routed_experts,
        )
        Fm, Fs = c.moe_intermediate_size, c.n_shared_experts * c.moe_intermediate_size
        Ld, Lm = c.first_k_dense_replace, c.num_layers - c.first_k_dense_replace

        dense_layers = self._attn_params(keys, Ld)
        dense_layers.update(
            {
                "gate": dense(next(keys), (Ld, D, F), 1),
                "up": dense(next(keys), (Ld, D, F), 1),
                "down": dense(next(keys), (Ld, F, D), 1),
            }
        )
        moe_layers = self._attn_params(keys, Lm)
        moe_layers.update(
            {
                "router": dense(next(keys), (Lm, D, E), 1).astype(jnp.float32),
                "w_gate": dense(next(keys), (Lm, E, D, Fm), 2),
                "w_up": dense(next(keys), (Lm, E, D, Fm), 2),
                "w_down": dense(next(keys), (Lm, E, Fm, D), 2),
                "shared_gate": dense(next(keys), (Lm, D, Fs), 1),
                "shared_up": dense(next(keys), (Lm, D, Fs), 1),
                "shared_down": dense(next(keys), (Lm, Fs, D), 1),
            }
        )
        return {
            "embed": dense(next(keys), (V, D), 1),
            "dense_layers": dense_layers,
            "moe_layers": moe_layers,
            "final_norm": jnp.ones((D,), c.dtype),
            "lm_head": dense(next(keys), (V, D), 1),
        }

    def param_shardings(self, mesh: Mesh, tp_axis: str = "tp", ep_axis: str = "ep") -> dict:
        c = self.config
        tp = tp_axis if tp_axis in mesh.axis_names else None
        ep = ep_axis if ep_axis in mesh.axis_names else None

        def ns(*spec):
            return NamedSharding(mesh, P(*spec))

        def attn():
            p = {
                "input_norm": ns(None, None),
                "w_dkv": ns(None, None, None),
                "kv_norm": ns(None, None),
                "w_kb": ns(None, None, tp, None),
                "w_vb": ns(None, None, tp, None),
                "wo": ns(None, tp, None),
                "post_norm": ns(None, None),
            }
            if c.q_lora_rank:
                p["w_dq"] = ns(None, None, None)
                p["q_norm"] = ns(None, None)
                p["w_uq"] = ns(None, None, tp)
            else:
                p["w_q"] = ns(None, None, tp)
            return p

        dense_layers = attn()
        dense_layers.update(
            {"gate": ns(None, None, tp), "up": ns(None, None, tp), "down": ns(None, tp, None)}
        )
        moe_layers = attn()
        moe_layers.update(
            {
                "router": ns(None, None, None),
                "w_gate": ns(None, ep, None, None),
                "w_up": ns(None, ep, None, None),
                "w_down": ns(None, ep, None, None),
                "shared_gate": ns(None, None, tp),
                "shared_up": ns(None, None, tp),
                "shared_down": ns(None, tp, None),
            }
        )
        if c.quantize:
            dense_layers = quantize_shardings_int8(dense_layers, self.QUANT_WEIGHT_NAMES)
            moe_layers = quantize_shardings_int8(moe_layers, self.QUANT_WEIGHT_NAMES)
        return {
            "embed": ns(None, None),
            "dense_layers": dense_layers,
            "moe_layers": moe_layers,
            "final_norm": ns(None),
            "lm_head": ns(tp, None),
        }

    # ---------------- KV cache (paged latents) ----------------

    def kv_cache_shape(self, num_pages: int, page_size: int) -> tuple[int, ...]:
        c = self.config
        return (c.num_layers * num_pages, page_size, c.latent_dim_padded)

    def init_kv_cache(self, num_pages: int, page_size: int) -> dict:
        return {"ckv": jnp.zeros(self.kv_cache_shape(num_pages, page_size), self.config.dtype)}

    def kv_cache_sharding(self, mesh: Mesh, tp_axis: str = "tp") -> dict:
        # the latent cache is head-independent: replicated across tp
        return {"ckv": NamedSharding(mesh, P(None, None, None))}

    def _layer_offsets(self, num_pages: int, start_layer: int, n_layers: int) -> jnp.ndarray:
        return (start_layer + jnp.arange(n_layers, dtype=jnp.int32)) * num_pages

    # ---------------- disagg / offload wire format ----------------

    wire_n_axis = 1  # see LlamaModel.wire_n_axis

    def gather_pages_wire(self, kv: dict, flat_ids: jnp.ndarray) -> jnp.ndarray:
        """[L, n] flat page ids -> wire array [L, n, ps, latent_dim_padded]
        (the physical 128-aligned row width; receivers must size buffers from
        kv_cache_shape, not latent_dim)."""
        return kv["ckv"][flat_ids]

    def scatter_pages_wire(self, kv: dict, flat_ids: jnp.ndarray, data: jnp.ndarray) -> dict:
        return {"ckv": kv["ckv"].at[flat_ids].set(data.astype(kv["ckv"].dtype))}

    def wire_sharding(self, mesh: Mesh) -> NamedSharding:
        return NamedSharding(mesh, P(None, None, None, None))

    # ---------------- attention core ----------------

    def _queries(self, lp: dict, h: jnp.ndarray, positions: jnp.ndarray):
        """h [T, D] -> (q_nope [T, H, dn], q_rope [T, H, dr] roped)."""
        c = self.config
        T = h.shape[0]
        H, dn, dr = c.num_heads, c.qk_nope_head_dim, c.qk_rope_head_dim
        if c.q_lora_rank:
            ql = rms_norm(qlinear(h, lp["w_dq"]), lp["q_norm"], c.rms_norm_eps)
            q = qlinear(ql, lp["w_uq"]).reshape(T, H, dn + dr)
        else:
            q = qlinear(h, lp["w_q"]).reshape(T, H, dn + dr)
        q_nope, q_rope = q[..., :dn], q[..., dn:]
        q_rope = apply_rope(q_rope, positions, c.rope_theta)
        return q_nope, q_rope

    def _cache_rows(self, lp: dict, h: jnp.ndarray, positions: jnp.ndarray) -> jnp.ndarray:
        """h [T, D] -> cache rows [T, latent_dim] = [norm(latent), roped k_rope]."""
        c = self.config
        dc = c.kv_lora_rank
        ckv = qlinear(h, lp["w_dkv"])  # [T, dc + dr]
        latent = rms_norm(ckv[:, :dc], lp["kv_norm"], c.rms_norm_eps)
        k_rope = apply_rope(ckv[:, None, dc:], positions, c.rope_theta)[:, 0]
        row = jnp.concatenate([latent, k_rope], axis=-1).astype(c.dtype)
        pad = c.latent_dim_padded - c.latent_dim
        if pad:
            row = jnp.pad(row, ((0, 0), (0, pad)))
        return row

    def _absorbed_attention(
        self,
        lp: dict,
        q_nope: jnp.ndarray,  # [T, H, dn]
        q_rope: jnp.ndarray,  # [T, H, dr] (roped)
        ctx: jnp.ndarray,  # [S, latent_dim] gathered cache rows (logical order)
        q_positions: jnp.ndarray,  # [T]
    ) -> jnp.ndarray:
        """Causal attention against cached latents; returns [T, H*dv]."""
        c = self.config
        dc = c.kv_lora_rank
        scale = 1.0 / jnp.sqrt(jnp.float32(c.qk_nope_head_dim + c.qk_rope_head_dim))
        latents = ctx[:, :dc].astype(jnp.float32)  # [S, dc]
        k_rope = ctx[:, dc : dc + c.qk_rope_head_dim].astype(jnp.float32)  # [S, dr]

        # fold q through the k-up projection: [T, H, dc]
        q_eff = jnp.einsum(
            "thn,chn->thc", q_nope.astype(jnp.float32), lp["w_kb"].astype(jnp.float32)
        )
        scores = (
            jnp.einsum("thc,sc->hts", q_eff, latents)
            + jnp.einsum("thr,sr->hts", q_rope.astype(jnp.float32), k_rope)
        ) * scale
        ctx_idx = jnp.arange(ctx.shape[0], dtype=jnp.int32)
        mask = ctx_idx[None, :] <= q_positions[:, None]  # [T, S]
        scores = jnp.where(mask[None, :, :], scores, _NEG_INF)
        probs = jax.nn.softmax(scores, axis=-1)  # [H, T, S]
        # attend in latent space, then fold through the v-up projection
        a_lat = jnp.einsum("hts,sc->thc", probs, latents)  # [T, H, dc]
        out = jnp.einsum(
            "thc,chv->thv", a_lat, lp["w_vb"].astype(jnp.float32)
        )  # [T, H, dv]
        return out.astype(self.config.dtype).reshape(out.shape[0], -1)

    def _mla_decode_pallas(
        self, lp, q_nope, q_rope, pool, page_tables, positions
    ) -> jnp.ndarray:
        """Decode-batch attention via the Pallas latent-page kernel: the q
        fold (MXU matmul) and the v-up projection stay outside; the kernel
        streams latent pages and returns the latent-space attention output."""
        from dynamo_tpu.ops.attention import _on_tpu
        from dynamo_tpu.ops.pallas.mla_attention import paged_mla_decode_attention_pallas

        c = self.config
        dc = c.kv_lora_rank
        q_cat = self._fold_q(lp, q_nope, q_rope)
        import functools
        import os

        # kernel choice resolved HERE (dispatch level, like ops/attention.py's
        # GQA dispatcher) and passed as a static argument — not read inside
        # the jitted kernel where it would freeze at first trace per shape
        kernel = functools.partial(
            paged_mla_decode_attention_pallas, d_c=dc,
            lookahead=os.environ.get("DYNTPU_DECODE_KERNEL") == "lookahead",
            interpret=not _on_tpu(),
        )
        mesh = self.attn_mesh
        tp = 1 if mesh is None else mesh.shape.get("tp", 1)
        if tp > 1 and q_cat.shape[1] % tp == 0:
            # GSPMD cannot partition a pallas_call: run per-head-shard under
            # shard_map (attention is head-parallel; the latent pool and page
            # tables are replicated)
            from dynamo_tpu.ops.attention import _tp_shard_map

            a_lat = _tp_shard_map(
                kernel,
                mesh,
                in_specs=(P(None, "tp", None), P(None, None, None), P(None, None), P(None)),
                out_specs=P(None, "tp", None),
            )(q_cat, pool, page_tables, positions)
        else:
            a_lat = kernel(q_cat, pool, page_tables, positions)
        out = jnp.einsum(
            "bhc,chv->bhv", a_lat.astype(jnp.float32), lp["w_vb"].astype(jnp.float32)
        )
        return out.astype(c.dtype).reshape(out.shape[0], -1)

    def _fold_q(self, lp, q_nope, q_rope):
        """(q_nope, q_rope) -> pre-scaled q_cat [.., H, latent_padded] for the
        latent-space kernels (the MXU-shaped fold through w_kb stays outside
        the pallas_call)."""
        c = self.config
        scale = 1.0 / jnp.sqrt(jnp.float32(c.qk_nope_head_dim + c.qk_rope_head_dim))
        q_eff = jnp.einsum(
            "...hn,chn->...hc", q_nope.astype(jnp.float32), lp["w_kb"].astype(jnp.float32)
        )
        q_cat = jnp.concatenate([q_eff, q_rope.astype(jnp.float32)], axis=-1) * scale
        pad = c.latent_dim_padded - c.latent_dim
        if pad:
            widths = [(0, 0)] * (q_cat.ndim - 1) + [(0, pad)]
            q_cat = jnp.pad(q_cat, widths)
        return q_cat

    def _mla_prefill_pallas(
        self, lp, q_nope, q_rope, pool, page_table, positions
    ) -> jnp.ndarray:
        """Chunked-prefill attention via the latent flash kernel; the v-up
        fold happens outside. Returns [T, H*dv]."""
        from dynamo_tpu.ops.attention import _on_tpu
        from dynamo_tpu.ops.pallas.mla_attention import (
            paged_mla_prefill_attention_pallas,
        )

        c = self.config
        q_cat = self._fold_q(lp, q_nope, q_rope)
        import functools

        kernel = functools.partial(
            paged_mla_prefill_attention_pallas,
            d_c=c.kv_lora_rank,
            interpret=not _on_tpu(),
        )
        mesh = self.attn_mesh
        tp = 1 if mesh is None else mesh.shape.get("tp", 1)
        if tp > 1 and q_cat.shape[1] % tp == 0:
            from dynamo_tpu.ops.attention import _tp_shard_map

            a_lat = _tp_shard_map(
                kernel,
                mesh,
                in_specs=(P(None, "tp", None), P(None, None, None), P(None), P(None)),
                out_specs=P(None, "tp", None),
            )(q_cat, pool, page_table, positions)
        else:
            a_lat = kernel(q_cat, pool, page_table, positions)
        out = jnp.einsum(
            "thc,chv->thv", a_lat.astype(jnp.float32), lp["w_vb"].astype(jnp.float32)
        )
        return out.astype(c.dtype).reshape(out.shape[0], -1)

    def _layer(
        self,
        lp: dict,
        hidden: jnp.ndarray,  # [T, D]
        pool: jnp.ndarray,  # [LP, ps, latent_dim] (carried)
        positions: jnp.ndarray,
        flat_phys: jnp.ndarray,
        offsets: jnp.ndarray,
        gather_tables: jnp.ndarray,  # [max_pages] or [B, max_pages] flat ids
        moe: bool,
        verify_T: int = 0,  # >0: B-lane speculative verify, T queries per lane
    ):
        c = self.config
        T = hidden.shape[0]
        h = rms_norm(hidden, lp["input_norm"], c.rms_norm_eps)
        q_nope, q_rope = self._queries(lp, h, positions)
        rows = self._cache_rows(lp, h, positions)
        pool = pool.at[flat_phys, offsets].set(rows)

        if verify_T:
            # speculative verify: each lane attends its own paged context with
            # verify_T query positions (absorbed-attention reference path; the
            # chunk is a handful of rows, so the per-lane gather is cheap)
            Bv = gather_tables.shape[0]
            ps = pool.shape[1]
            qn = q_nope.reshape(Bv, verify_T, *q_nope.shape[1:])
            qr = q_rope.reshape(Bv, verify_T, *q_rope.shape[1:])
            pos2 = positions.reshape(Bv, verify_T)
            outs = [
                self._absorbed_attention(
                    lp, qn[j], qr[j],
                    pool[gather_tables[j]].reshape(
                        gather_tables.shape[1] * ps, c.latent_dim_padded
                    ),
                    pos2[j],
                )
                for j in range(Bv)
            ]
            attn = jnp.concatenate(outs, axis=0)
        elif gather_tables.ndim == 1:
            if _use_pallas_mla() and T % 128 == 0:
                attn = self._mla_prefill_pallas(
                    lp, q_nope, q_rope, pool, gather_tables, positions
                )
            else:
                ps = pool.shape[1]
                ctx = pool[gather_tables].reshape(
                    gather_tables.shape[0] * ps, c.latent_dim_padded
                )
                attn = self._absorbed_attention(lp, q_nope, q_rope, ctx, positions)
        elif _use_pallas_mla():
            attn = self._mla_decode_pallas(lp, q_nope, q_rope, pool, gather_tables, positions)
        else:
            ps = pool.shape[1]

            def one(qn_b, qr_b, pt_b, pos_b):
                ctx = pool[pt_b].reshape(pt_b.shape[0] * ps, c.latent_dim_padded)
                return self._absorbed_attention(
                    lp, qn_b[None], qr_b[None], ctx, pos_b[None]
                )[0]

            attn = jax.vmap(one)(q_nope, q_rope, gather_tables, positions)

        hidden = hidden + qlinear(attn, lp["wo"])
        h = rms_norm(hidden, lp["post_norm"], c.rms_norm_eps)
        if moe:
            shared = qlinear(
                jax.nn.silu(qlinear(h, lp["shared_gate"])) * qlinear(h, lp["shared_up"]),
                lp["shared_down"],
            )
            routed = moe_block(
                h,
                lp["router"],
                lp["w_gate"],
                lp["w_up"],
                lp["w_down"],
                num_experts_per_tok=c.num_experts_per_tok,
                capacity_factor=c.moe_capacity_factor,
                renormalize=c.norm_topk_prob,
            )
            hidden = hidden + shared + c.routed_scaling_factor * routed
        else:
            mlp = qlinear(jax.nn.silu(qlinear(h, lp["gate"])) * qlinear(h, lp["up"]), lp["down"])
            hidden = hidden + mlp
        return hidden, pool

    def _unembed(self, params: dict, hidden: jnp.ndarray) -> jnp.ndarray:
        h = rms_norm(hidden, params["final_norm"], self.config.rms_norm_eps)
        return jax.lax.dot_general(
            h, params["lm_head"], (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )

    def _forward(
        self,
        params: dict,
        pool: jnp.ndarray,
        hidden: jnp.ndarray,
        positions: jnp.ndarray,
        phys: jnp.ndarray,  # logical phys page per token (trash=0)
        offsets: jnp.ndarray,
        tables: jnp.ndarray,  # [max_pages] or [B, max_pages] logical ids
        num_pages: int,
        verify_T: int = 0,
    ):
        c = self.config
        Ld = c.first_k_dense_replace

        def group(hidden, pool, lp_group, start, n, moe):
            offs = self._layer_offsets(num_pages, start, n)

            def body(carry, xs):
                h, pl = carry
                lp, off = xs
                h, pl = self._layer(
                    lp, h, pl, positions, off + phys, offsets, off + tables, moe,
                    verify_T=verify_T,
                )
                return (h, pl), None

            (hidden, pool), _ = jax.lax.scan(body, (hidden, pool), (lp_group, offs))
            return hidden, pool

        if Ld > 0:
            hidden, pool = group(hidden, pool, params["dense_layers"], 0, Ld, False)
        if c.num_layers - Ld > 0:
            hidden, pool = group(
                hidden, pool, params["moe_layers"], Ld, c.num_layers - Ld, True
            )
        return hidden, pool

    # ---------------- public forward API (ModelRunner contract) ----------------

    def prefill(self, params, kv_cache, tokens, positions, page_table, valid, last_idx,
                input_embeds=None, embeds_mask=None, rope_positions=None):
        # rope_positions (M-RoPE) is accepted for runner-contract parity but
        # unused: no multimodal MLA family exists
        c = self.config
        pool = kv_cache["ckv"]
        page_size = pool.shape[1]
        num_pages = pool.shape[0] // c.num_layers
        phys = jnp.where(valid, page_table[positions // page_size], 0)
        offsets = jnp.where(valid, positions % page_size, 0)
        hidden = params["embed"][tokens].astype(c.dtype)
        if input_embeds is not None:  # multimodal embedding overrides
            hidden = jnp.where(embeds_mask[:, None], input_embeds.astype(c.dtype), hidden)
        hidden, pool = self._forward(
            params, pool, hidden, positions, phys, offsets, page_table, num_pages
        )
        logits = self._unembed(params, hidden[last_idx][None, :])[0]
        return logits, {"ckv": pool}

    def decode(self, params, kv_cache, tokens, positions, page_tables, active, rope_deltas=None):
        c = self.config
        pool = kv_cache["ckv"]
        page_size = pool.shape[1]
        num_pages = pool.shape[0] // c.num_layers
        B = tokens.shape[0]
        logical = positions // page_size
        phys = jnp.where(active, page_tables[jnp.arange(B), logical], 0)
        offsets = jnp.where(active, positions % page_size, 0)
        hidden = params["embed"][tokens].astype(c.dtype)
        hidden, pool = self._forward(
            params, pool, hidden, positions, phys, offsets, page_tables, num_pages
        )
        logits = self._unembed(params, hidden)
        return logits, {"ckv": pool}

    def verify(self, params, kv_cache, tokens, positions, page_tables, valid):
        """Speculative verification (ModelRunner contract, see
        LlamaModel.verify): [B, T] anchor+draft tokens at consecutive
        positions, one weight pass, logits at ALL rows. Latent rows for
        invalid positions scatter to the trash page; each lane's attention
        runs the absorbed-MLA reference path against its own page table.

        Returns (logits [B, T, V], updated kv_cache)."""
        c = self.config
        pool = kv_cache["ckv"]
        page_size = pool.shape[1]
        num_pages = pool.shape[0] // c.num_layers
        B, T = tokens.shape
        lane = jnp.arange(B)
        phys = jnp.where(valid, page_tables[lane[:, None], positions // page_size], 0)
        offsets = jnp.where(valid, positions % page_size, 0)
        hidden = params["embed"][tokens.reshape(B * T)].astype(c.dtype)
        hidden, pool = self._forward(
            params, pool, hidden, positions.reshape(B * T),
            phys.reshape(B * T), offsets.reshape(B * T), page_tables, num_pages,
            verify_T=T,
        )
        logits = self._unembed(params, hidden)  # [B*T, V]
        return logits.reshape(B, T, -1), {"ckv": pool}
