"""HF checkpoint -> scan-stacked JAX param loading (safetensors / torch .bin).

Weight name mapping follows the HF conventions per family; our layout is
[in, out] (HF nn.Linear stores [out, in]) with all layers stacked on a leading
axis. Allocation comes from ``jax.eval_shape(model.init_params, ...)`` so the
loader can never drift from the model's param tree: shapes, dtypes, and
presence of optional leaves (biases, tied lm_head) are all derived from the
single source of truth.
"""

from __future__ import annotations

from pathlib import Path

import numpy as np
import jax
import jax.numpy as jnp

from dynamo_tpu.models.llama import LlamaModel
from dynamo_tpu.utils import get_logger

log = get_logger("models.loader")


def _iter_checkpoint_tensors(path: Path):
    """Yield (name, np.ndarray) from safetensors shards or torch .bin files."""
    st_files = sorted(path.glob("*.safetensors"))
    if st_files:
        from safetensors import safe_open

        for f in st_files:
            with safe_open(str(f), framework="np") as sf:
                for name in sf.keys():
                    yield name, sf.get_tensor(name)
        return
    bin_files = sorted(path.glob("pytorch_model*.bin"))
    if bin_files:
        import torch

        for f in bin_files:
            state = torch.load(str(f), map_location="cpu", weights_only=True)
            for name, t in state.items():
                yield name, t.float().numpy()
        return
    raise FileNotFoundError(f"no safetensors or pytorch_model*.bin under {path}")


def _alloc_like(model):
    """(numpy f32 arrays, ShapeDtypeStruct tree) matching the model's RAW
    (pre-quantization) param tree — checkpoint tensors fill full-precision
    buffers; _finish applies the config's quantize mode once at the end."""
    shapes = jax.eval_shape(
        lambda key: model.init_params(key, quantize=False), jax.random.key(0)
    )
    arrays = jax.tree.map(lambda s: np.zeros(s.shape, np.float32), shapes)
    return arrays, shapes


def _finish(arrays, shapes, model=None):
    """Cast the filled numpy arrays to the model's exact leaf dtypes, then
    quantize (quantize="int8_wo" checkpoints: weight-only int8 conversion
    happens HERE, at load time — the serving stack never sees bf16 copies of
    the quantized weights)."""
    params = jax.tree.map(lambda a, s: jnp.asarray(a, s.dtype), arrays, shapes)
    if model is not None:
        params = model.quantize_params(params)
    return params


def _set_layer(group: dict, key: str, layer: int, tensor: np.ndarray, transpose: bool):
    t = tensor.T if transpose else tensor
    group[key][layer] = t.astype(np.float32)


def load_llama_weights(model: LlamaModel, path: Path) -> dict:
    c = model.config
    arrays, shapes = _alloc_like(model)
    layers = arrays["layers"]

    per_layer = {
        "input_layernorm.weight": ("input_norm", False),
        "self_attn.q_proj.weight": ("wq", True),
        "self_attn.k_proj.weight": ("wk", True),
        "self_attn.v_proj.weight": ("wv", True),
        "self_attn.o_proj.weight": ("wo", True),
        "self_attn.q_proj.bias": ("bq", False),
        "self_attn.k_proj.bias": ("bk", False),
        "self_attn.v_proj.bias": ("bv", False),
        "post_attention_layernorm.weight": ("post_norm", False),
        "mlp.gate_proj.weight": ("gate", True),
        "mlp.up_proj.weight": ("up", True),
        "mlp.down_proj.weight": ("down", True),
    }

    seen_embed = seen_head = False
    for name, tensor in _iter_checkpoint_tensors(path):
        if name == "model.embed_tokens.weight":
            arrays["embed"][:] = tensor.astype(np.float32)
            seen_embed = True
        elif name == "model.norm.weight":
            arrays["final_norm"][:] = tensor.astype(np.float32)
        elif name == "lm_head.weight" and "lm_head" in arrays:
            arrays["lm_head"][:] = tensor.astype(np.float32)
            seen_head = True
        elif name.startswith("model.layers."):
            rest = name[len("model.layers.") :]
            layer_str, sub = rest.split(".", 1)
            l = int(layer_str)
            mapping = per_layer.get(sub)
            if mapping is None or mapping[0] not in layers or l >= c.num_layers:
                log.debug("skipping unmapped weight %s", name)
                continue
            _set_layer(layers, mapping[0], l, tensor, mapping[1])
        else:
            log.debug("skipping unmapped weight %s", name)

    if not seen_embed:
        raise ValueError("checkpoint missing model.embed_tokens.weight")
    if "lm_head" in arrays and not seen_head:
        arrays["lm_head"][:] = arrays["embed"]
    return _finish(arrays, shapes, model)


def load_mixtral_weights(model, path: Path) -> dict:
    """HF Mixtral convention: attention matches Llama; the sparse MLP stores
    block_sparse_moe.gate (router) + per-expert w1 (gate), w2 (down), w3 (up)."""
    c = model.config
    arrays, shapes = _alloc_like(model)
    layers = arrays["layers"]

    per_layer = {
        "input_layernorm.weight": ("input_norm", False),
        "self_attn.q_proj.weight": ("wq", True),
        "self_attn.k_proj.weight": ("wk", True),
        "self_attn.v_proj.weight": ("wv", True),
        "self_attn.o_proj.weight": ("wo", True),
        "post_attention_layernorm.weight": ("post_norm", False),
        "block_sparse_moe.gate.weight": ("router", True),
    }
    expert_map = {"w1": "w_gate", "w3": "w_up", "w2": "w_down"}

    seen_embed = seen_head = False
    for name, tensor in _iter_checkpoint_tensors(path):
        if name == "model.embed_tokens.weight":
            arrays["embed"][:] = tensor.astype(np.float32)
            seen_embed = True
        elif name == "model.norm.weight":
            arrays["final_norm"][:] = tensor.astype(np.float32)
        elif name == "lm_head.weight" and "lm_head" in arrays:
            arrays["lm_head"][:] = tensor.astype(np.float32)
            seen_head = True
        elif name.startswith("model.layers."):
            rest = name[len("model.layers.") :]
            layer_str, sub = rest.split(".", 1)
            l = int(layer_str)
            if l >= c.num_layers:
                log.debug("skipping out-of-range layer weight %s", name)
                continue
            if sub.startswith("block_sparse_moe.experts."):
                _, _, e_str, w_name, _ = sub.split(".")
                layers[expert_map[w_name]][l, int(e_str)] = tensor.T.astype(np.float32)
                continue
            mapping = per_layer.get(sub)
            if mapping is None:
                log.debug("skipping unmapped weight %s", name)
                continue
            _set_layer(layers, mapping[0], l, tensor, mapping[1])
        else:
            log.debug("skipping unmapped weight %s", name)

    if not seen_embed:
        raise ValueError("checkpoint missing model.embed_tokens.weight")
    if "lm_head" in arrays and not seen_head:
        arrays["lm_head"][:] = arrays["embed"]
    return _finish(arrays, shapes, model)


def load_deepseek_weights(model, path: Path) -> dict:
    """HF deepseek_v2/v3 convention -> the MLA param layout of
    dynamo_tpu/models/deepseek.py. kv_b_proj [H*(dn+dv), dc] splits into the
    k-up (w_kb) and v-up (w_vb) banks; layers partition into the leading dense
    group and the MoE group (first_k_dense_replace boundary). Names with a
    layer index >= num_layers (e.g. DeepSeek-V3's multi-token-prediction
    layer) are skipped, as are auxiliary tensors this serving stack doesn't
    model."""
    c = model.config
    arrays, shapes = _alloc_like(model)
    dn, dv, dc = c.qk_nope_head_dim, c.v_head_dim, c.kv_lora_rank
    H = c.num_heads
    Ld = c.first_k_dense_replace

    attn_map = {
        "input_layernorm.weight": ("input_norm", False),
        "self_attn.q_proj.weight": ("w_q", True),
        "self_attn.q_a_proj.weight": ("w_dq", True),
        "self_attn.q_a_layernorm.weight": ("q_norm", False),
        "self_attn.q_b_proj.weight": ("w_uq", True),
        "self_attn.kv_a_proj_with_mqa.weight": ("w_dkv", True),
        "self_attn.kv_a_layernorm.weight": ("kv_norm", False),
        "self_attn.o_proj.weight": ("wo", True),
        "post_attention_layernorm.weight": ("post_norm", False),
        "mlp.gate_proj.weight": ("gate", True),
        "mlp.up_proj.weight": ("up", True),
        "mlp.down_proj.weight": ("down", True),
        "mlp.gate.weight": ("router", True),
        "mlp.shared_experts.gate_proj.weight": ("shared_gate", True),
        "mlp.shared_experts.up_proj.weight": ("shared_up", True),
        "mlp.shared_experts.down_proj.weight": ("shared_down", True),
    }
    expert_map = {"gate_proj": "w_gate", "up_proj": "w_up", "down_proj": "w_down"}

    seen_embed = seen_head = False
    for name, tensor in _iter_checkpoint_tensors(path):
        if name == "model.embed_tokens.weight":
            arrays["embed"][:] = tensor.astype(np.float32)
            seen_embed = True
        elif name == "model.norm.weight":
            arrays["final_norm"][:] = tensor.astype(np.float32)
        elif name == "lm_head.weight":
            arrays["lm_head"][:] = tensor.astype(np.float32)
            seen_head = True
        elif name.startswith("model.layers."):
            rest = name[len("model.layers.") :]
            layer_str, sub = rest.split(".", 1)
            l = int(layer_str)
            if l >= c.num_layers:
                log.debug("skipping out-of-range layer weight %s", name)
                continue
            group, gl = (
                (arrays["dense_layers"], l) if l < Ld else (arrays["moe_layers"], l - Ld)
            )
            if sub == "self_attn.kv_b_proj.weight":
                # [H*(dn+dv), dc] -> [dc, H, dn+dv] -> split k-up / v-up
                t = tensor.T.reshape(dc, H, dn + dv).astype(np.float32)
                group["w_kb"][gl] = t[..., :dn]
                group["w_vb"][gl] = t[..., dn:]
                continue
            if sub.startswith("mlp.experts."):
                _, _, e_str, w_name, _ = sub.split(".")
                group[expert_map[w_name]][gl, int(e_str)] = tensor.T.astype(np.float32)
                continue
            mapping = attn_map.get(sub)
            if mapping is None or mapping[0] not in group:
                log.debug("skipping unmapped weight %s", name)
                continue
            _set_layer(group, mapping[0], gl, tensor, mapping[1])
        else:
            log.debug("skipping unmapped weight %s", name)

    if not seen_embed:
        raise ValueError("checkpoint missing model.embed_tokens.weight")
    if not seen_head:
        arrays["lm_head"][:] = arrays["embed"]
    return _finish(arrays, shapes, model)


def load_qwen2_vl_weights(model, path: Path) -> dict:
    """HF qwen2_vl convention: text half matches Qwen2 (llama layout + qkv
    biases under ``model.``); the vision tower lives under ``visual.``:
    conv patch embed (conv3d over 2 duplicated temporal frames — folded into a
    single linear by summing the temporal taps, exact for static images),
    fused ``attn.qkv``, LayerNorm ``norm1``/``norm2``, ``mlp.fc1/fc2``, and
    the ``merger`` (ln_q + 2-layer MLP into the LLM hidden size)."""
    c = model.config
    arrays, shapes = _alloc_like(model)
    vis = arrays["vision"]
    vlayers = vis["layers"]
    vc = c.vision

    text_arrays = {k: v for k, v in arrays.items() if k != "vision"}

    per_layer = {
        "input_layernorm.weight": ("input_norm", False),
        "self_attn.q_proj.weight": ("wq", True),
        "self_attn.k_proj.weight": ("wk", True),
        "self_attn.v_proj.weight": ("wv", True),
        "self_attn.o_proj.weight": ("wo", True),
        "self_attn.q_proj.bias": ("bq", False),
        "self_attn.k_proj.bias": ("bk", False),
        "self_attn.v_proj.bias": ("bv", False),
        "post_attention_layernorm.weight": ("post_norm", False),
        "mlp.gate_proj.weight": ("gate", True),
        "mlp.up_proj.weight": ("up", True),
        "mlp.down_proj.weight": ("down", True),
    }
    vis_per_layer = {
        "norm1.weight": ("norm1", False),
        "norm1.bias": ("norm1_b", False),
        "attn.qkv.weight": ("wqkv", True),
        "attn.qkv.bias": ("bqkv", False),
        "attn.proj.weight": ("wo", True),
        "attn.proj.bias": ("bo", False),
        "norm2.weight": ("norm2", False),
        "norm2.bias": ("norm2_b", False),
        "mlp.fc1.weight": ("fc1", True),
        "mlp.fc1.bias": ("bfc1", False),
        "mlp.fc2.weight": ("fc2", True),
        "mlp.fc2.bias": ("bfc2", False),
    }
    merger_map = {
        "merger.ln_q.weight": "merger_norm",
        "merger.ln_q.bias": "merger_norm_b",
        "merger.mlp.0.bias": "merger_bfc1",
        "merger.mlp.2.bias": "merger_bfc2",
    }

    seen_embed = seen_head = False
    for name, tensor in _iter_checkpoint_tensors(path):
        if name == "model.embed_tokens.weight":
            text_arrays["embed"][:] = tensor.astype(np.float32)
            seen_embed = True
        elif name == "model.norm.weight":
            text_arrays["final_norm"][:] = tensor.astype(np.float32)
        elif name == "lm_head.weight" and "lm_head" in text_arrays:
            text_arrays["lm_head"][:] = tensor.astype(np.float32)
            seen_head = True
        elif name.startswith("model.layers."):
            rest = name[len("model.layers.") :]
            layer_str, sub = rest.split(".", 1)
            l = int(layer_str)
            mapping = per_layer.get(sub)
            if mapping is None or mapping[0] not in text_arrays["layers"] or l >= c.num_layers:
                log.debug("skipping unmapped weight %s", name)
                continue
            _set_layer(text_arrays["layers"], mapping[0], l, tensor, mapping[1])
        elif name == "visual.patch_embed.proj.weight":
            t = tensor.astype(np.float32)
            if t.ndim == 5:  # conv3d [D, C, T, ps, ps]: sum temporal taps
                t = t.sum(axis=2)
            # conv2d [D, C, ps, ps] -> linear [C*ps*ps, D] matching patchify's
            # pixel order (ps, ps, C) per patch
            t = t.transpose(2, 3, 1, 0).reshape(-1, t.shape[0])
            if t.shape != vis["patch_embed"].shape:
                raise ValueError(
                    f"patch_embed shape {t.shape} != {vis['patch_embed'].shape}"
                )
            vis["patch_embed"][:] = t
        elif name.startswith("visual.blocks."):
            rest = name[len("visual.blocks.") :]
            layer_str, sub = rest.split(".", 1)
            l = int(layer_str)
            mapping = vis_per_layer.get(sub)
            if mapping is None or l >= vc.num_layers:
                log.debug("skipping unmapped weight %s", name)
                continue
            _set_layer(vlayers, mapping[0], l, tensor, mapping[1])
        elif name == "visual.merger.mlp.0.weight":
            vis["merger_fc1"][:] = tensor.T.astype(np.float32)
        elif name == "visual.merger.mlp.2.weight":
            vis["merger_fc2"][:] = tensor.T.astype(np.float32)
        elif name[len("visual.") :] in merger_map and name.startswith("visual."):
            vis[merger_map[name[len("visual.") :]]][:] = tensor.astype(np.float32)
        else:
            log.debug("skipping unmapped weight %s", name)

    if not seen_embed:
        raise ValueError("checkpoint missing model.embed_tokens.weight")
    if "lm_head" in text_arrays and not seen_head:
        text_arrays["lm_head"][:] = text_arrays["embed"]
    return _finish(arrays, shapes, model)
