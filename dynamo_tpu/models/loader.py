"""HF checkpoint -> scan-stacked JAX param loading (safetensors / torch .bin).

Weight name mapping follows the HF Llama convention; our layout is [in, out]
(HF nn.Linear stores [out, in]) with all layers stacked on a leading axis.
"""

from __future__ import annotations

import json
from pathlib import Path

import numpy as np
import jax.numpy as jnp

from dynamo_tpu.models.llama import LlamaModel
from dynamo_tpu.utils import get_logger

log = get_logger("models.loader")


def _iter_checkpoint_tensors(path: Path):
    """Yield (name, np.ndarray) from safetensors shards or torch .bin files."""
    st_files = sorted(path.glob("*.safetensors"))
    if st_files:
        from safetensors import safe_open

        for f in st_files:
            with safe_open(str(f), framework="np") as sf:
                for name in sf.keys():
                    yield name, sf.get_tensor(name)
        return
    bin_files = sorted(path.glob("pytorch_model*.bin"))
    if bin_files:
        import torch

        for f in bin_files:
            state = torch.load(str(f), map_location="cpu", weights_only=True)
            for name, t in state.items():
                yield name, t.float().numpy()
        return
    raise FileNotFoundError(f"no safetensors or pytorch_model*.bin under {path}")


def load_llama_weights(model: LlamaModel, path: Path) -> dict:
    c = model.config
    dt = c.dtype
    L = c.num_layers

    def alloc(shape):
        return np.zeros(shape, dtype=np.float32)

    H, Hkv, Dh, D, F, V = (
        c.num_heads,
        c.num_kv_heads,
        c.head_dim,
        c.hidden_size,
        c.intermediate_size,
        c.vocab_size,
    )
    layers = {
        "input_norm": alloc((L, D)),
        "wq": alloc((L, D, H * Dh)),
        "wk": alloc((L, D, Hkv * Dh)),
        "wv": alloc((L, D, Hkv * Dh)),
        "wo": alloc((L, H * Dh, D)),
        "post_norm": alloc((L, D)),
        "gate": alloc((L, D, F)),
        "up": alloc((L, D, F)),
        "down": alloc((L, F, D)),
    }
    if c.attention_bias:
        layers["bq"] = alloc((L, H * Dh))
        layers["bk"] = alloc((L, Hkv * Dh))
        layers["bv"] = alloc((L, Hkv * Dh))
    params = {"embed": None, "final_norm": None}

    per_layer = {
        "input_layernorm.weight": ("input_norm", False),
        "self_attn.q_proj.weight": ("wq", True),
        "self_attn.k_proj.weight": ("wk", True),
        "self_attn.v_proj.weight": ("wv", True),
        "self_attn.o_proj.weight": ("wo", True),
        "self_attn.q_proj.bias": ("bq", False),
        "self_attn.k_proj.bias": ("bk", False),
        "self_attn.v_proj.bias": ("bv", False),
        "post_attention_layernorm.weight": ("post_norm", False),
        "mlp.gate_proj.weight": ("gate", True),
        "mlp.up_proj.weight": ("up", True),
        "mlp.down_proj.weight": ("down", True),
    }

    for name, tensor in _iter_checkpoint_tensors(path):
        if name == "model.embed_tokens.weight":
            params["embed"] = tensor
        elif name == "model.norm.weight":
            params["final_norm"] = tensor
        elif name == "lm_head.weight":
            params["lm_head"] = tensor
        elif name.startswith("model.layers."):
            rest = name[len("model.layers.") :]
            layer_str, sub = rest.split(".", 1)
            mapping = per_layer.get(sub)
            if mapping is None:
                log.debug("skipping unmapped weight %s", name)
                continue
            key, transpose = mapping
            t = tensor.T if transpose else tensor
            layers[key][int(layer_str)] = t.astype(np.float32)
        else:
            log.debug("skipping unmapped weight %s", name)

    if params["embed"] is None:
        raise ValueError("checkpoint missing model.embed_tokens.weight")
    out = {
        "embed": jnp.asarray(params["embed"], dt),
        "layers": {k: jnp.asarray(v, dt) for k, v in layers.items()},
        "final_norm": jnp.asarray(params["final_norm"], dt),
    }
    if not c.tie_word_embeddings:
        head = params.get("lm_head")
        if head is None:
            head = params["embed"]
        out["lm_head"] = jnp.asarray(head, dt)
    return out
