"""HF checkpoint -> scan-stacked JAX param loading (safetensors / torch .bin).

Weight name mapping follows the HF conventions per family; our layout is
[in, out] (HF nn.Linear stores [out, in]) with all layers stacked on a leading
axis. Allocation comes from ``jax.eval_shape(model.init_params, ...)`` so the
loader can never drift from the model's param tree: shapes, dtypes, and
presence of optional leaves (biases, tied lm_head) are all derived from the
single source of truth.
"""

from __future__ import annotations

import json
from pathlib import Path

import numpy as np
import jax
import jax.numpy as jnp

from dynamo_tpu.models.llama import LlamaModel
from dynamo_tpu.utils import get_logger

log = get_logger("models.loader")


def _iter_checkpoint_tensors(path: Path):
    """Yield (name, np.ndarray) from safetensors shards or torch .bin files."""
    st_files = sorted(path.glob("*.safetensors"))
    if st_files:
        from safetensors import safe_open

        for f in st_files:
            with safe_open(str(f), framework="np") as sf:
                for name in sf.keys():
                    yield name, sf.get_tensor(name)
        return
    bin_files = sorted(path.glob("pytorch_model*.bin"))
    if bin_files:
        import torch

        for f in bin_files:
            state = torch.load(str(f), map_location="cpu", weights_only=True)
            for name, t in state.items():
                yield name, t.float().numpy()
        return
    raise FileNotFoundError(f"no safetensors or pytorch_model*.bin under {path}")


def _alloc_like(model):
    """(numpy f32 arrays, ShapeDtypeStruct tree) matching model.init_params."""
    shapes = jax.eval_shape(model.init_params, jax.random.key(0))
    arrays = jax.tree.map(lambda s: np.zeros(s.shape, np.float32), shapes)
    return arrays, shapes


def _finish(arrays, shapes):
    """Cast the filled numpy arrays to the model's exact leaf dtypes."""
    return jax.tree.map(lambda a, s: jnp.asarray(a, s.dtype), arrays, shapes)


def _set_layer(group: dict, key: str, layer: int, tensor: np.ndarray, transpose: bool):
    t = tensor.T if transpose else tensor
    group[key][layer] = t.astype(np.float32)


def load_llama_weights(model: LlamaModel, path: Path) -> dict:
    c = model.config
    arrays, shapes = _alloc_like(model)
    layers = arrays["layers"]

    per_layer = {
        "input_layernorm.weight": ("input_norm", False),
        "self_attn.q_proj.weight": ("wq", True),
        "self_attn.k_proj.weight": ("wk", True),
        "self_attn.v_proj.weight": ("wv", True),
        "self_attn.o_proj.weight": ("wo", True),
        "self_attn.q_proj.bias": ("bq", False),
        "self_attn.k_proj.bias": ("bk", False),
        "self_attn.v_proj.bias": ("bv", False),
        "post_attention_layernorm.weight": ("post_norm", False),
        "mlp.gate_proj.weight": ("gate", True),
        "mlp.up_proj.weight": ("up", True),
        "mlp.down_proj.weight": ("down", True),
    }

    seen_embed = seen_head = False
    for name, tensor in _iter_checkpoint_tensors(path):
        if name == "model.embed_tokens.weight":
            arrays["embed"][:] = tensor.astype(np.float32)
            seen_embed = True
        elif name == "model.norm.weight":
            arrays["final_norm"][:] = tensor.astype(np.float32)
        elif name == "lm_head.weight" and "lm_head" in arrays:
            arrays["lm_head"][:] = tensor.astype(np.float32)
            seen_head = True
        elif name.startswith("model.layers."):
            rest = name[len("model.layers.") :]
            layer_str, sub = rest.split(".", 1)
            l = int(layer_str)
            mapping = per_layer.get(sub)
            if mapping is None or mapping[0] not in layers or l >= c.num_layers:
                log.debug("skipping unmapped weight %s", name)
                continue
            _set_layer(layers, mapping[0], l, tensor, mapping[1])
        else:
            log.debug("skipping unmapped weight %s", name)

    if not seen_embed:
        raise ValueError("checkpoint missing model.embed_tokens.weight")
    if "lm_head" in arrays and not seen_head:
        arrays["lm_head"][:] = arrays["embed"]
    return _finish(arrays, shapes)


def load_mixtral_weights(model, path: Path) -> dict:
    """HF Mixtral convention: attention matches Llama; the sparse MLP stores
    block_sparse_moe.gate (router) + per-expert w1 (gate), w2 (down), w3 (up)."""
    c = model.config
    arrays, shapes = _alloc_like(model)
    layers = arrays["layers"]

    per_layer = {
        "input_layernorm.weight": ("input_norm", False),
        "self_attn.q_proj.weight": ("wq", True),
        "self_attn.k_proj.weight": ("wk", True),
        "self_attn.v_proj.weight": ("wv", True),
        "self_attn.o_proj.weight": ("wo", True),
        "post_attention_layernorm.weight": ("post_norm", False),
        "block_sparse_moe.gate.weight": ("router", True),
    }
    expert_map = {"w1": "w_gate", "w3": "w_up", "w2": "w_down"}

    seen_embed = seen_head = False
    for name, tensor in _iter_checkpoint_tensors(path):
        if name == "model.embed_tokens.weight":
            arrays["embed"][:] = tensor.astype(np.float32)
            seen_embed = True
        elif name == "model.norm.weight":
            arrays["final_norm"][:] = tensor.astype(np.float32)
        elif name == "lm_head.weight" and "lm_head" in arrays:
            arrays["lm_head"][:] = tensor.astype(np.float32)
            seen_head = True
        elif name.startswith("model.layers."):
            rest = name[len("model.layers.") :]
            layer_str, sub = rest.split(".", 1)
            l = int(layer_str)
            if l >= c.num_layers:
                log.debug("skipping out-of-range layer weight %s", name)
                continue
            if sub.startswith("block_sparse_moe.experts."):
                _, _, e_str, w_name, _ = sub.split(".")
                layers[expert_map[w_name]][l, int(e_str)] = tensor.T.astype(np.float32)
                continue
            mapping = per_layer.get(sub)
            if mapping is None:
                log.debug("skipping unmapped weight %s", name)
                continue
            _set_layer(layers, mapping[0], l, tensor, mapping[1])
        else:
            log.debug("skipping unmapped weight %s", name)

    if not seen_embed:
        raise ValueError("checkpoint missing model.embed_tokens.weight")
    if "lm_head" in arrays and not seen_head:
        arrays["lm_head"][:] = arrays["embed"]
    return _finish(arrays, shapes)


def load_deepseek_weights(model, path: Path) -> dict:
    """HF deepseek_v2/v3 convention -> the MLA param layout of
    dynamo_tpu/models/deepseek.py. kv_b_proj [H*(dn+dv), dc] splits into the
    k-up (w_kb) and v-up (w_vb) banks; layers partition into the leading dense
    group and the MoE group (first_k_dense_replace boundary). Names with a
    layer index >= num_layers (e.g. DeepSeek-V3's multi-token-prediction
    layer) are skipped, as are auxiliary tensors this serving stack doesn't
    model."""
    c = model.config
    arrays, shapes = _alloc_like(model)
    dn, dv, dc = c.qk_nope_head_dim, c.v_head_dim, c.kv_lora_rank
    H = c.num_heads
    Ld = c.first_k_dense_replace

    attn_map = {
        "input_layernorm.weight": ("input_norm", False),
        "self_attn.q_proj.weight": ("w_q", True),
        "self_attn.q_a_proj.weight": ("w_dq", True),
        "self_attn.q_a_layernorm.weight": ("q_norm", False),
        "self_attn.q_b_proj.weight": ("w_uq", True),
        "self_attn.kv_a_proj_with_mqa.weight": ("w_dkv", True),
        "self_attn.kv_a_layernorm.weight": ("kv_norm", False),
        "self_attn.o_proj.weight": ("wo", True),
        "post_attention_layernorm.weight": ("post_norm", False),
        "mlp.gate_proj.weight": ("gate", True),
        "mlp.up_proj.weight": ("up", True),
        "mlp.down_proj.weight": ("down", True),
        "mlp.gate.weight": ("router", True),
        "mlp.shared_experts.gate_proj.weight": ("shared_gate", True),
        "mlp.shared_experts.up_proj.weight": ("shared_up", True),
        "mlp.shared_experts.down_proj.weight": ("shared_down", True),
    }
    expert_map = {"gate_proj": "w_gate", "up_proj": "w_up", "down_proj": "w_down"}

    seen_embed = seen_head = False
    for name, tensor in _iter_checkpoint_tensors(path):
        if name == "model.embed_tokens.weight":
            arrays["embed"][:] = tensor.astype(np.float32)
            seen_embed = True
        elif name == "model.norm.weight":
            arrays["final_norm"][:] = tensor.astype(np.float32)
        elif name == "lm_head.weight":
            arrays["lm_head"][:] = tensor.astype(np.float32)
            seen_head = True
        elif name.startswith("model.layers."):
            rest = name[len("model.layers.") :]
            layer_str, sub = rest.split(".", 1)
            l = int(layer_str)
            if l >= c.num_layers:
                log.debug("skipping out-of-range layer weight %s", name)
                continue
            group, gl = (
                (arrays["dense_layers"], l) if l < Ld else (arrays["moe_layers"], l - Ld)
            )
            if sub == "self_attn.kv_b_proj.weight":
                # [H*(dn+dv), dc] -> [dc, H, dn+dv] -> split k-up / v-up
                t = tensor.T.reshape(dc, H, dn + dv).astype(np.float32)
                group["w_kb"][gl] = t[..., :dn]
                group["w_vb"][gl] = t[..., dn:]
                continue
            if sub.startswith("mlp.experts."):
                _, _, e_str, w_name, _ = sub.split(".")
                group[expert_map[w_name]][gl, int(e_str)] = tensor.T.astype(np.float32)
                continue
            mapping = attn_map.get(sub)
            if mapping is None or mapping[0] not in group:
                log.debug("skipping unmapped weight %s", name)
                continue
            _set_layer(group, mapping[0], gl, tensor, mapping[1])
        else:
            log.debug("skipping unmapped weight %s", name)

    if not seen_embed:
        raise ValueError("checkpoint missing model.embed_tokens.weight")
    if not seen_head:
        arrays["lm_head"][:] = arrays["embed"]
    return _finish(arrays, shapes)
