"""Runtime core: cancellation tokens, signal handling, graceful shutdown.

Mirrors the reference Runtime/Worker (reference: lib/runtime/src/runtime.rs:38-118,
worker.rs:16-45): a root CancellationToken with child tokens, SIGINT/SIGTERM
graceful shutdown with a timeout (DYNTPU_GRACEFUL_SHUTDOWN_TIMEOUT) and exit
code 911 if the timeout is exceeded.
"""

from __future__ import annotations

import asyncio
import os
import signal
import sys
from typing import Awaitable, Callable, Optional

from dynamo_tpu.utils import get_logger

log = get_logger("runtime")

EXIT_TIMEOUT = 911


class CancellationToken:
    """Hierarchical cancellation: cancelling a parent cancels all children."""

    def __init__(self, parent: Optional["CancellationToken"] = None):
        self._event = asyncio.Event()
        self._children: list[CancellationToken] = []
        self._callbacks: list[Callable[[], None]] = []
        if parent is not None:
            parent._children.append(self)
            if parent.is_cancelled():
                self.cancel()

    def child_token(self) -> "CancellationToken":
        return CancellationToken(parent=self)

    def cancel(self) -> None:
        if self._event.is_set():
            return
        self._event.set()
        for cb in self._callbacks:
            try:
                cb()
            except Exception:
                log.exception("cancellation callback failed")
        for child in self._children:
            child.cancel()

    def is_cancelled(self) -> bool:
        return self._event.is_set()

    async def cancelled(self) -> None:
        await self._event.wait()

    def on_cancel(self, cb: Callable[[], None]) -> None:
        if self.is_cancelled():
            cb()
        else:
            self._callbacks.append(cb)


class Runtime:
    """Owns the loop's root cancellation token and shutdown sequencing."""

    def __init__(self):
        self.cancellation = CancellationToken()
        self._shutdown_hooks: list[Callable[[], Awaitable[None]]] = []

    def child_token(self) -> CancellationToken:
        return self.cancellation.child_token()

    def on_shutdown(self, hook: Callable[[], Awaitable[None]]) -> None:
        self._shutdown_hooks.append(hook)

    def shutdown(self) -> None:
        self.cancellation.cancel()

    async def run_shutdown_hooks(self) -> None:
        for hook in reversed(self._shutdown_hooks):
            try:
                await hook()
            except Exception:
                log.exception("shutdown hook failed")


class Worker:
    """Entrypoint wrapper: installs signal handlers, runs the app coroutine,
    enforces the graceful-shutdown timeout."""

    @staticmethod
    def execute(app: Callable[[Runtime], Awaitable[None]]) -> None:
        timeout = float(os.environ.get("DYNTPU_GRACEFUL_SHUTDOWN_TIMEOUT", "30"))

        async def main() -> None:
            runtime = Runtime()
            loop = asyncio.get_running_loop()

            def on_signal(signame: str) -> None:
                log.info("received %s; shutting down", signame)
                runtime.shutdown()

            for sig in (signal.SIGINT, signal.SIGTERM):
                try:
                    loop.add_signal_handler(sig, on_signal, sig.name)
                except NotImplementedError:
                    pass

            app_task = asyncio.create_task(app(runtime))
            cancel_task = asyncio.create_task(runtime.cancellation.cancelled())
            done, _ = await asyncio.wait(
                [app_task, cancel_task], return_when=asyncio.FIRST_COMPLETED
            )
            runtime.shutdown()
            try:
                await asyncio.wait_for(runtime.run_shutdown_hooks(), timeout)
                if app_task not in done:
                    app_task.cancel()
                    try:
                        await asyncio.wait_for(app_task, timeout)
                    except (asyncio.CancelledError, asyncio.TimeoutError):
                        pass
                if app_task in done and app_task.exception() is not None:
                    raise app_task.exception()
            except asyncio.TimeoutError:
                log.error("graceful shutdown timed out; exiting 911")
                sys.exit(EXIT_TIMEOUT)

        asyncio.run(main())
