"""Client: endpoint discovery + routed streaming RPC.

Mirrors the reference Client + AddressedPushRouter (reference: lib/runtime/src/
component/client.rs:52-256, pipeline/network/egress/push.rs:62-181): watches
the instance prefix, routes random/round-robin/direct, pushes the request over
the control plane with the caller's ConnectionInfo, and returns the call-home
response stream.
"""

from __future__ import annotations

import asyncio
import random as _random
from typing import Any, AsyncIterator, Optional

import msgpack

from dynamo_tpu.runtime.component import EndpointInfo, INSTANCE_PREFIX
from dynamo_tpu.runtime.context import RequestContext, current_context
from dynamo_tpu.utils import get_logger, tracing

log = get_logger("runtime.client")


class NoInstancesError(ConnectionError):
    pass


class Client:
    def __init__(self, drt, namespace: str, component: str, endpoint: str):
        self._drt = drt
        self.namespace = namespace
        self.component = component
        self.endpoint = endpoint
        self._instances: dict[int, EndpointInfo] = {}
        self._rr_index = 0
        self._watcher = None
        self._watch_task: Optional[asyncio.Task] = None
        self._instances_changed = asyncio.Event()

    @property
    def prefix(self) -> str:
        return f"{INSTANCE_PREFIX}/{self.namespace}/components/{self.component}/{self.endpoint}:"

    # ---------------- discovery ----------------

    async def start(self) -> "Client":
        self._watcher = await self._drt.cplane.kv_get_and_watch_prefix(self.prefix)
        for item in self._watcher.initial:
            info = EndpointInfo.from_wire(msgpack.unpackb(item.value, raw=False))
            self._instances[info.instance_id] = info
        self._watch_task = asyncio.create_task(self._watch_loop())
        return self

    async def stop(self) -> None:
        if self._watch_task:
            self._watch_task.cancel()
        if self._watcher:
            try:
                await self._watcher.stop()
            except Exception:
                pass

    async def _watch_loop(self) -> None:
        try:
            async for ev in self._watcher.events():
                if ev.kind == "put":
                    info = EndpointInfo.from_wire(msgpack.unpackb(ev.value, raw=False))
                    self._instances[info.instance_id] = info
                elif ev.kind == "delete":
                    # key suffix after ':' is the lease hex
                    instance_id = int(ev.key.rsplit(":", 1)[1], 16)
                    self._instances.pop(instance_id, None)
                self._instances_changed.set()
                self._instances_changed = asyncio.Event()
        except asyncio.CancelledError:
            pass

    def instance_ids(self) -> list[int]:
        return sorted(self._instances)

    async def wait_for_instances(self, timeout: float = 30.0) -> list[int]:
        deadline = asyncio.get_running_loop().time() + timeout
        while not self._instances:
            remaining = deadline - asyncio.get_running_loop().time()
            if remaining <= 0:
                raise NoInstancesError(f"no instances for {self.prefix}")
            changed = self._instances_changed
            try:
                await asyncio.wait_for(changed.wait(), remaining)
            except asyncio.TimeoutError:
                pass
        return self.instance_ids()

    # ---------------- routing ----------------

    def _pick_random(self) -> EndpointInfo:
        if not self._instances:
            raise NoInstancesError(f"no instances for {self.prefix}")
        return self._instances[_random.choice(list(self._instances))]

    def _pick_round_robin(self) -> EndpointInfo:
        if not self._instances:
            raise NoInstancesError(f"no instances for {self.prefix}")
        ids = sorted(self._instances)
        info = self._instances[ids[self._rr_index % len(ids)]]
        self._rr_index += 1
        return info

    def _pick_direct(self, instance_id: int) -> EndpointInfo:
        info = self._instances.get(instance_id)
        if info is None:
            raise NoInstancesError(f"instance {instance_id:x} not found for {self.prefix}")
        return info

    # ---------------- RPC ----------------

    async def generate(
        self,
        request: Any,
        instance_id: Optional[int] = None,
        routing: str = "random",
        context: Optional[RequestContext] = None,
    ) -> AsyncIterator[Any]:
        """Routed streaming call; yields deserialized response items.

        ``context`` (or, when absent, the ambient request context) rides the
        request envelope so its metadata reaches the remote handler."""
        if instance_id is not None:
            info = self._pick_direct(instance_id)
        elif routing == "round_robin":
            info = self._pick_round_robin()
        else:
            info = self._pick_random()
        return await self._generate_to(info, request, context)

    async def random(self, request: Any) -> AsyncIterator[Any]:
        return await self.generate(request, routing="random")

    async def round_robin(self, request: Any) -> AsyncIterator[Any]:
        return await self.generate(request, routing="round_robin")

    async def direct(self, request: Any, instance_id: int) -> AsyncIterator[Any]:
        return await self.generate(request, instance_id=instance_id)

    async def _generate_to(
        self, info: EndpointInfo, request: Any, context: Optional[RequestContext] = None
    ) -> AsyncIterator[Any]:
        drt = self._drt
        await drt.ensure_tcp_server()
        conn_info, receiver = drt.tcp_server.register()
        ctx = context if context is not None else current_context()
        payload = {
            "conn_info": conn_info.to_wire(),
            "request": msgpack.packb(request, use_bin_type=True),
        }
        if ctx is not None:
            # the trace id must be IN the metadata bag before serialization so
            # the remote hop's spans stitch to the same timeline
            ctx.ensure_trace_id()
            payload["context"] = ctx.to_wire()
        try:
            # hop-overhead span: request push + remote handler setup, up to the
            # prologue (first-frame ok) — the wire cost a trace attributes to
            # this hop rather than to compute
            with tracing.span(
                f"rpc.push.{self.component}.{self.endpoint}",
                instance=f"{info.instance_id:x}",
            ):
                delivered = await drt.cplane.publish(info.subject, payload)
                if delivered == 0:
                    raise NoInstancesError(f"instance {info.instance_id:x} is gone")
                await asyncio.wait_for(receiver.prologue_ok, timeout=30.0)
        except Exception:
            drt.tcp_server.unregister(conn_info.context_id)
            raise

        async def stream() -> AsyncIterator[Any]:
            async for raw in receiver:
                yield msgpack.unpackb(raw, raw=False)

        return stream()
